"""Headline benchmark: ResNet-18 training throughput per chip.

Mirrors the reference's GPU image-training benchmark
(``doc/source/ray-air/benchmarks.rst:163-174``: torchvision ResNet-18,
746.29 images/sec across 16 T4 workers = 46.64 images/sec/chip) on one TPU
chip. Synthetic 224x224 data (the reference benchmark is also
data-loader-free compute measurement at this granularity), bfloat16, full
fwd+bwd+SGD step, steps chained inside one jit scan so dispatch overhead is
amortized (required under the axon relay).

Prints one JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

import json
import time

import jax
import jax.numpy as jnp
import optax

from ray_tpu.models import resnet

BASELINE_IMAGES_PER_SEC_PER_CHIP = 746.29 / 16  # T4, benchmarks.rst:171-174

BATCH = 256
IMAGE = 224
MEASURE_STEPS = 20


def main():
    cfg = resnet.resnet18(num_classes=1000)
    params = resnet.init_params(jax.random.PRNGKey(0), cfg)
    opt = optax.sgd(0.1, momentum=0.9)
    opt_state = opt.init(params)

    key = jax.random.PRNGKey(1)
    images = jax.random.normal(key, (BATCH, IMAGE, IMAGE, 3),
                               dtype=jnp.bfloat16)
    labels = jax.random.randint(jax.random.PRNGKey(2), (BATCH,), 0, 1000)

    def one_step(state, _):
        params, opt_state = state
        loss, grads = jax.value_and_grad(resnet.loss_fn)(
            params, images, labels, cfg)
        updates, opt_state = opt.update(grads, opt_state)
        params = optax.apply_updates(params, updates)
        return (params, opt_state), loss

    @jax.jit
    def run_steps(state, n_steps_arr):
        return jax.lax.scan(one_step, state, n_steps_arr)

    state = (params, opt_state)
    # Warmup with the SAME step count so the measured call hits the compile
    # cache (a different scan length is a different program).
    state, losses = run_steps(state, jnp.arange(MEASURE_STEPS))
    jax.block_until_ready(losses)

    t0 = time.perf_counter()
    state, losses = run_steps(state, jnp.arange(MEASURE_STEPS))
    jax.block_until_ready(losses)
    elapsed = time.perf_counter() - t0

    images_per_sec = BATCH * MEASURE_STEPS / elapsed
    print(json.dumps({
        "metric": "resnet18_train_images_per_sec_per_chip",
        "value": round(images_per_sec, 2),
        "unit": "images/sec",
        "vs_baseline": round(images_per_sec / BASELINE_IMAGES_PER_SEC_PER_CHIP,
                             2),
    }))


if __name__ == "__main__":
    main()
