"""Headline benchmark: ResNet-50 training throughput per chip, with MFU.

North-star image benchmark against the reference's GPU image-training
numbers (``doc/source/ray-air/benchmarks.rst:163-174``: torchvision
ResNet-18, 746.29 images/sec across 16 T4 workers = 46.64 images/sec/chip).
We run the *bigger* ResNet-50 (~2.4x the FLOPs of ResNet-18) and still
compare per-chip against that number, so ``vs_baseline`` is conservative.

Model FLOP utilization (``mfu_pct``) is computed from analytic FLOP
counts over the detected chip's peak bf16 throughput — the "is it
actually fast" number the reference never reports. (XLA's
``cost_analysis`` is NOT used: it counts a ``lax.scan`` body once
rather than per step, undercounting by the scan length.)

Extras carried in the same JSON line:
- ``transformer_tokens_per_sec`` (+ its MFU): decoder LM train step on the
  flagship transformer (the ``__graft_entry__`` model family).
- ``resnet18_images_per_sec``: continuity with rounds 1-3.

Synthetic data (the reference benchmark is also data-loader-free at this
granularity), bfloat16 compute, full fwd+bwd+optimizer step, steps chained
inside one jit scan so dispatch overhead is amortized (required under the
axon relay).

Outage-proofing (round-5 hardening): every section runs under its own
try/except and its result is emitted as a JSON progress line the moment it
is measured, so a tunnel outage or crash mid-run loses only the sections
not yet reached. The FINAL stdout line is always the combined headline
JSON (the one the driver parses), carrying whatever was captured plus a
``backend_available`` marker and its machine-parsed negation
``probe_failed: true`` when the TPU backend was lost — and the process
exits 0 regardless.
CPU-pinned sections (PPO) run BEFORE the backend probe so a dead tunnel
never starves them. The probe window is wall-clock bounded:
``BENCH_PROBE_DEADLINE_S`` (default 300) with ``BENCH_PROBE_DELAY_S``
(default 15) between attempts.
"""

import json
import os
import sys
import time

import jax
import jax.numpy as jnp
import optax

BASELINE_IMAGES_PER_SEC_PER_CHIP = 746.29 / 16  # T4, benchmarks.rst:171-174

MEASURE_STEPS = 20

# Peak dense bf16 FLOP/s per chip by device kind (public specs; the
# jax-ml scaling-book hardware table).
_PEAK_BF16 = (
    ("v6", 918e12),
    ("v5p", 459e12),
    ("v5 lite", 197e12),
    ("v5e", 197e12),
    ("v5", 459e12),
    ("v4", 275e12),
    ("v3", 123e12),
    ("v2", 45e12),
)


def _chip_peak_flops():
    dev = jax.devices()[0]
    kind = getattr(dev, "device_kind", "") or ""
    low = kind.lower()
    if dev.platform == "tpu":
        for tag, peak in _PEAK_BF16:
            if tag in low:
                return kind, peak
    return kind, None


def _timed_scan(step_fn, state, n_steps, min_measure_s: float = 0.5):
    """jit a lax.scan of ``n_steps`` steps; returns (state, elapsed_s).

    ``elapsed_s`` is the median per-invocation wall time over enough
    repetitions to accumulate ``min_measure_s`` of measured runtime —
    single-shot timing over the axon relay is noisy enough to produce
    physically impossible numbers. FLOP accounting is the CALLER's
    analytic formula: XLA's ``cost_analysis`` counts a ``scan`` body
    once, not ``n_steps`` times, so it undercounts by the step count.
    """
    @jax.jit
    def run(state, xs):
        return jax.lax.scan(step_fn, state, xs)

    xs = jnp.arange(n_steps)
    state, out = run(state, xs)   # compile + warmup
    jax.block_until_ready(out)
    times = []
    total = 0.0
    while total < min_measure_s or len(times) < 2:
        t0 = time.perf_counter()
        state, out = run(state, xs)
        jax.block_until_ready(out)
        dt = time.perf_counter() - t0
        times.append(dt)
        total += dt
        if len(times) >= 20:
            break
    times.sort()
    return state, times[len(times) // 2]


def bench_resnet(cfg_name: str, batch: int):
    from ray_tpu.models import resnet
    cfg = getattr(resnet, cfg_name)(num_classes=1000)
    params = resnet.init_params(jax.random.PRNGKey(0), cfg)
    opt = optax.sgd(0.1, momentum=0.9)
    opt_state = opt.init(params)
    images = jax.random.normal(jax.random.PRNGKey(1), (batch, 224, 224, 3),
                               dtype=jnp.bfloat16)
    labels = jax.random.randint(jax.random.PRNGKey(2), (batch,), 0, 1000)

    def one_step(state, _):
        params, opt_state = state
        loss, grads = jax.value_and_grad(resnet.loss_fn)(
            params, images, labels, cfg)
        updates, opt_state = opt.update(grads, opt_state)
        params = optax.apply_updates(params, updates)
        return (params, opt_state), loss

    _, elapsed = _timed_scan(one_step, (params, opt_state), MEASURE_STEPS)
    images_per_sec = batch * MEASURE_STEPS / elapsed
    # Analytic: ResNet-50 fwd ~= 4.09 GFLOP / image @224, ResNet-18
    # ~= 1.82; fwd+bwd ~= 3x fwd.
    per_image = {"resnet50": 4.09e9, "resnet18": 1.82e9}[cfg_name] * 3
    achieved = per_image * batch * MEASURE_STEPS / elapsed
    return images_per_sec, achieved


def bench_transformer():
    """Decoder-LM train step on the flagship transformer: tokens/sec."""
    from ray_tpu.models import transformer
    from ray_tpu.models.transformer import TransformerConfig

    batch, seq = 8, 1024
    cfg = TransformerConfig(
        vocab_size=32000, d_model=1024, n_layers=12, n_heads=16,
        max_seq_len=seq, dtype=jnp.bfloat16,
        use_flash=jax.default_backend() == "tpu")
    params = transformer.init_params(jax.random.PRNGKey(0), cfg)
    n_params = transformer.num_params(params)
    opt = optax.adamw(1e-3)
    opt_state = opt.init(params)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (batch, seq + 1), 0,
                                cfg.vocab_size)

    def one_step(state, _):
        params, opt_state = state
        loss, grads = jax.value_and_grad(
            lambda p: transformer.loss_fn(p, tokens, cfg))(params)
        updates, opt_state = opt.update(grads, opt_state, params=params)
        params = optax.apply_updates(params, updates)
        return (params, opt_state), loss

    steps = 10
    _, elapsed = _timed_scan(one_step, (params, opt_state), steps)
    tokens_per_sec = batch * seq * steps / elapsed
    flops = 6.0 * n_params * batch * seq * steps  # 2 fwd + 4 bwd
    achieved = flops / elapsed
    return tokens_per_sec, achieved, n_params


def bench_ppo():
    """End-to-end PPO throughput (sample + compiled learn), env-steps/sec.

    The RL analogue of the reference's tuned-example throughput tracking
    (``rllib/tuned_examples/ppo/``): in-repo CartPole over 8 vector envs,
    whole sgd schedule compiled as one XLA program (``rl/ppo.py``).

    Runs in a CPU-pinned SUBPROCESS: the RL design is CPU rollout actors
    feeding a compiled learner, and per-env-step policy dispatch through
    the axon TPU relay would measure tunnel latency, not the framework
    (~25 ms/step observed).
    """
    import subprocess
    import sys
    code = r"""
import time
import jax
jax.config.update("jax_platforms", "cpu")
from ray_tpu.rl import PPO
algo = (PPO.get_default_config()
        .environment("CartPole-v1")
        .rollouts(num_rollout_workers=0, num_envs_per_worker=8,
                  rollout_fragment_length=100)
        .training(train_batch_size=800, sgd_minibatch_size=256,
                  num_sgd_iter=8, lr=3e-4)
        .debugging(seed=0)
        .build())
algo.step()  # warmup: compiles the train program
t0 = time.perf_counter()
steps = 0
for _ in range(3):
    r = algo.step()
    steps += r.get("timesteps_this_iter", 0)
print("PPO_SPS", steps / (time.perf_counter() - t0))
algo.stop()
"""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run([sys.executable, "-c", code], env=env,
                          capture_output=True, text=True, timeout=600)
    for line in proc.stdout.splitlines():
        if line.startswith("PPO_SPS"):
            return float(line.split()[1])
    raise RuntimeError(f"ppo bench failed: {proc.stderr[-300:]}")


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, default))
    except ValueError:
        return default


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return default


def _wait_for_backend() -> bool:
    """The axon TPU tunnel is transiently unavailable at times; retry
    backend init rather than failing the whole bench run. The probe runs
    on a daemon thread with a timeout: a dead tunnel makes jax.devices()
    BLOCK (not raise), and a hung probe must count as a failed attempt.

    The window is bounded BOTH by a wall-clock deadline
    (``BENCH_PROBE_DEADLINE_S``, default 300 s) and an attempt cap
    (``BENCH_PROBE_MAX_ATTEMPTS``, default 3): a tunnel that fails fast
    can burn many attempts without touching the deadline (BENCH_r05: 17
    consecutive failures ate the whole run until ``timeout -k`` killed it
    with rc=124), and a down tunnel virtually never recovers within a
    probe window anyway. Returns True when the backend answered, False
    when either bound is hit — the caller degrades instead of raising.
    """
    import threading

    deadline_s = _env_float("BENCH_PROBE_DEADLINE_S", 300.0)
    delay_s = _env_float("BENCH_PROBE_DELAY_S", 15.0)
    max_attempts = _env_int("BENCH_PROBE_MAX_ATTEMPTS", 3)
    t_start = time.monotonic()

    def probe() -> bool:
        out = [False]

        def run():
            try:
                out[0] = len(jax.devices()) > 0
            except Exception:  # raylint: allow(swallow) probe failure IS the signal
                out[0] = False

        t = threading.Thread(target=run, daemon=True)
        t.start()
        # never let a single hung probe eat the whole window
        t.join(timeout=min(45.0, max(1.0, deadline_s / 2)))
        return out[0] and not t.is_alive()

    attempt = 0
    while True:
        attempt += 1
        if probe():
            return True
        _emit({"metric": "backend_probe_failed", "value": attempt,
               "unit": "attempts"})
        if attempt >= max_attempts:
            _emit({"metric": "backend_probe_gave_up", "value": attempt,
                   "unit": "attempts"})
            return False
        remaining = deadline_s - (time.monotonic() - t_start)
        if remaining <= 0:
            return False
        time.sleep(min(delay_s, max(0.0, remaining)))
        if time.monotonic() - t_start >= deadline_s:
            return False


def _emit(obj):
    """Progress line: flushed immediately so a crash later loses nothing."""
    print(json.dumps(obj), flush=True)


def _section(name, fn, results, timeout_s=900.0):
    """Run one bench section; record its result or its failure.

    Each section runs on a daemon thread with a wall-clock budget: a
    tunnel that dies MID-SECTION makes device ops block forever, and a
    hung section must not stop the remaining ones (or the final emit)
    from happening.
    """
    import threading

    box = {}

    def run():
        try:
            box["value"] = fn()
        except Exception as exc:  # noqa: BLE001 - partial-success by design
            box["error"] = f"{type(exc).__name__}: {exc}"

    t = threading.Thread(target=run, daemon=True)
    t0 = time.perf_counter()
    t.start()
    t.join(timeout=timeout_s)
    if t.is_alive():
        box["error"] = f"timeout after {timeout_s:.0f}s"
        box["timed_out"] = True
    results[name] = box
    _emit({"metric": f"section_{name}", "unit": "progress",
           "value": None if "error" in box else "ok",
           "error": box.get("error"),
           "elapsed_s": round(time.perf_counter() - t0, 1)})
    return box.get("value")


def main():
    results = {}
    # PPO runs CPU-pinned in a subprocess: independent of the TPU tunnel.
    # It goes FIRST so a dead tunnel (and the probe window that confirms
    # it) can never starve the sections that need no backend at all.
    ppo_sps = _section("ppo", bench_ppo, results, timeout_s=700.0)
    try:
        backend_ok = _wait_for_backend()
    except Exception as exc:  # noqa: BLE001 - even the probe must not kill us
        _emit({"metric": "backend_probe_error", "value": str(exc),
               "unit": "error"})
        backend_ok = False
    kind, peak = ("", None)
    if backend_ok:
        try:
            kind, peak = _chip_peak_flops()
        except Exception as exc:  # noqa: BLE001
            _emit({"metric": "chip_detect_error", "value": str(exc),
                   "unit": "error"})
        r50 = lm = r18 = None
        # A TIMEOUT (vs an exception) means the tunnel hung mid-section;
        # later device sections would each eat their full budget too, so
        # stop submitting device work after the first hang.
        for name, fn, slot in (
                ("resnet50", lambda: bench_resnet("resnet50", 128), "r50"),
                ("transformer", bench_transformer, "lm"),
                ("resnet18", lambda: bench_resnet("resnet18", 256), "r18")):
            val = _section(name, fn, results)
            if slot == "r50":
                r50 = val
            elif slot == "lm":
                lm = val
            else:
                r18 = val
            if results[name].get("timed_out"):
                _emit({"metric": "device_sections_aborted", "value": name,
                       "unit": "hung_section"})
                break
    else:
        r50 = lm = r18 = None

    def mfu(achieved):
        if peak is None or achieved is None:
            return None
        return round(100.0 * achieved / peak, 2)

    r50_ips, r50_flops = r50 if r50 else (None, None)
    lm_tps, lm_flops, lm_params = lm if lm else (None, None, None)
    r18_ips = r18[0] if r18 else None
    _emit({
        "metric": "resnet50_train_images_per_sec_per_chip",
        "value": None if r50_ips is None else round(r50_ips, 2),
        "unit": "images/sec",
        "vs_baseline": (None if r50_ips is None else
                        round(r50_ips / BASELINE_IMAGES_PER_SEC_PER_CHIP, 2)),
        "mfu_pct": mfu(r50_flops),
        "device_kind": kind,
        "peak_bf16_tflops": None if peak is None else round(peak / 1e12, 1),
        "backend_available": backend_ok,
        "probe_failed": not backend_ok,
        "errors": {k: v["error"] for k, v in results.items()
                   if "error" in v} or None,
        "extras": {
            "resnet18_images_per_sec": (None if r18_ips is None else
                                        round(r18_ips, 2)),
            "transformer_tokens_per_sec": (None if lm_tps is None else
                                           round(lm_tps, 2)),
            "transformer_mfu_pct": mfu(lm_flops),
            "transformer_params_m": (None if lm_params is None else
                                     round(lm_params / 1e6, 1)),
            "ppo_env_steps_per_sec": (None if ppo_sps is None
                                      else round(ppo_sps, 1)),
        },
    })


if __name__ == "__main__":
    try:
        main()
    except Exception as exc:  # noqa: BLE001 - the driver parses the last line
        _emit({"metric": "resnet50_train_images_per_sec_per_chip",
               "value": None, "unit": "images/sec", "vs_baseline": None,
               "mfu_pct": None, "backend_available": False,
               "probe_failed": True,
               "errors": {"harness": f"{type(exc).__name__}: {exc}"},
               "extras": {}})
    sys.exit(0)
