"""Data-plane tests: framed out-of-band serialization, the RPC bulk lane,
stream-pool striping with recv-into-destination landing, mid-transfer
failover, and control-plane batching ordering.

The striping/failover tests run two ``DistributedRuntime`` instances in one
process against a fake in-memory state client — the transfer plane under
test (FETCH_OBJECT over real sockets, data-stream pools, store recv
buffers) is exactly the production path; only the directory service is
stubbed (the C++ state service needs protoc, which CI images may lack).
"""

import pickle
import threading
import time

import numpy as np
import pytest

from ray_tpu import chaos
from ray_tpu._private.config import _config
from ray_tpu._private.framing import (FramedPayload, dumps_framed,
                                      loads_framed)
from ray_tpu._private.ids import ObjectID
from ray_tpu._private.rpc import (RpcClient, RpcConnectionError, RpcServer)
from ray_tpu.protocol import pb


def _pytree():
    rng = np.random.RandomState(7)
    return {
        "weights": rng.rand(257, 33),                  # odd, non-64-aligned
        "tokens": rng.randint(0, 1 << 30, size=1001, dtype=np.int64),
        "nested": [rng.rand(5).astype(np.float32), "label", 42,
                   {"mask": rng.rand(9, 9) > 0.5}],
        "scalar": 3.25,
    }


def _assert_tree_equal(a, b):
    assert np.array_equal(a["weights"], b["weights"])
    assert np.array_equal(a["tokens"], b["tokens"])
    assert np.array_equal(a["nested"][0], b["nested"][0])
    assert a["nested"][1:3] == b["nested"][1:3]
    assert np.array_equal(a["nested"][3]["mask"], b["nested"][3]["mask"])
    assert a["scalar"] == b["scalar"]


# --------------------------------------------------------------- framing


def test_framed_payload_slices_byte_identical_to_dumps():
    """FramedPayload is the gather-list encoder for the SAME layout
    dumps_framed materializes: striping any chunk grid over slices() and
    concatenating must reproduce the contiguous frame exactly."""
    value = _pytree()
    blob = bytes(dumps_framed(value))
    payload = FramedPayload(value)
    assert len(payload) == len(blob)
    # chunk sizes chosen to land inside headers, across buffer boundaries,
    # and inside alignment padding
    for chunk in (1 << 20, 4096, 977, len(blob)):
        out = bytearray()
        for off in range(0, len(blob), chunk):
            for piece in payload.slices(off, off + chunk):
                out += piece
        assert bytes(out) == blob, f"chunk={chunk}"
    # write_into (the arena-slot landing) produces the same bytes
    dest = bytearray(len(payload))
    payload.write_into(memoryview(dest))
    assert bytes(dest) == blob


def test_framed_roundtrip_numpy_and_nested_pytree():
    value = _pytree()
    blob = dumps_framed(value)
    got, zero_copy = loads_framed(blob)
    assert zero_copy  # arrays decoded as views into the frame
    _assert_tree_equal(value, got)
    # zero-copy decodes of a sealed frame must be read-only
    assert not got["weights"].flags.writeable
    # arrays genuinely reference the frame, not copies of it
    assert np.shares_memory(got["weights"],
                            np.frombuffer(blob, dtype=np.uint8))


def test_framed_decode_accepts_legacy_plain_pickle():
    value = {"plain": [1, 2, 3]}
    got, zero_copy = loads_framed(pickle.dumps(value))
    assert got == value and not zero_copy


# -------------------------------------------------------- RPC bulk lane


def test_rpc_raw_lane_scatter_gather_roundtrip():
    """A served chunk leaves as a gather list (sendmsg) and lands through
    the client's raw_sink directly in the caller's destination buffer;
    the request direction ships a gather list into ``ctx.raw``."""
    value = _pytree()
    payload = FramedPayload(value)
    blob = bytes(dumps_framed(value))
    pushed = {}

    def handler(ctx):
        if ctx.method == pb.FETCH_OBJECT:
            req = pb.FetchObjectRequest()
            req.ParseFromString(ctx.body)
            end = min(len(payload), req.offset + req.max_bytes)
            rep = pb.FetchObjectReply(found=True, total_size=len(payload),
                                      eof=end >= len(payload))
            ctx.reply(rep.SerializeToString(),
                      raw=payload.slices(req.offset, end))
        elif ctx.method == pb.PUSH_OBJECT:
            pushed["raw"] = bytes(ctx.raw or b"")
            ctx.reply(pb.PushObjectReply(accepted=True).SerializeToString())
        else:
            ctx.reply(b"")

    server = RpcServer(handler)
    client = RpcClient(server.address)
    try:
        dest = bytearray(len(payload))
        chunk = 100_003  # odd: chunk edges cross buffer/padding boundaries
        for off in range(0, len(payload), chunk):
            client.call(
                pb.FETCH_OBJECT, pb.FetchObjectRequest(
                    object_id=b"x" * ObjectID.size(), offset=off,
                    max_bytes=chunk).SerializeToString(),
                timeout=30,
                raw_sink=lambda n, _o=off: memoryview(dest)[_o:_o + n])
        assert bytes(dest) == blob
        got, _ = loads_framed(dest)
        _assert_tree_equal(value, got)

        # request-direction gather list -> one contiguous ctx.raw
        a, b = np.arange(100, dtype=np.uint8), np.arange(50, dtype=np.uint8)
        client.call(
            pb.PUSH_OBJECT, pb.PushObjectRequest(
                object_id=b"y" * ObjectID.size(), offset=0,
                total_size=150, eof=True).SerializeToString(),
            timeout=30, raw=[memoryview(a), memoryview(b)])
        assert pushed["raw"] == a.tobytes() + b.tobytes()
    finally:
        client.close()
        server.close()


# -------------------------------------- two-runtime striped fetch plane


class _FakeState:
    """In-memory stand-in for StateClient: just enough surface for
    DistributedRuntime construction, heartbeats, and directory no-ops.
    One registry per (monkeypatched) class so both runtimes see each
    other as alive."""

    registry = {}

    def __init__(self, address, auth_token=None):
        self.address = address
        self._kv = {}

    # nodes / jobs
    def register_node(self, info):
        stored = pb.NodeInfo()
        stored.CopyFrom(info)
        stored.alive = True
        type(self).registry[stored.node_id] = stored
        return pb.RegisterNodeReply()

    def heartbeat(self, node_id, available=None):
        return node_id in type(self).registry

    def list_nodes(self):
        return list(type(self).registry.values())

    def mark_node_dead(self, node_id, reason=""):
        info = type(self).registry.get(node_id)
        if info is not None:
            info.alive = False

    def register_job(self, info):
        pass

    # pubsub
    def subscribe(self, channels, handler):
        pass

    def publish(self, channel, kind, payload=b""):
        pass

    # kv
    def kv_put(self, key, value, overwrite=True, namespace=b""):
        if not overwrite and (namespace, key) in self._kv:
            return False
        self._kv[(namespace, key)] = value
        return True

    def kv_get(self, key, namespace=b""):
        return self._kv.get((namespace, key))

    def kv_del(self, key, namespace=b""):
        return self._kv.pop((namespace, key), None) is not None

    def kv_keys(self, prefix=b"", namespace=b""):
        return [k for (ns, k) in self._kv if ns == namespace
                and k.startswith(prefix)]

    # object directory (no-op: tests address peers directly)
    def add_location(self, object_id, node_id, size=0):
        pass

    def remove_location(self, object_id, node_id):
        pass

    def flush_locations(self, timeout=10.0):
        return True

    def get_locations(self, object_id):
        return pb.GetLocationsReply()

    def close(self):
        pass


@pytest.fixture
def two_runtimes(monkeypatch):
    from ray_tpu._private import distributed as dist
    from ray_tpu._private.resources import ResourceSet

    saved = {k: _config.get(k) for k in
             ("arena_enabled", "fetch_chunk_bytes", "data_streams_per_peer")}
    # arena off: force the TCP plane (same-host runtimes would otherwise
    # hand objects over through shm); small chunks so a few-MB object
    # stripes into many chunks
    _config.set("arena_enabled", False)
    _config.set("fetch_chunk_bytes", 256 * 1024)
    # pin the stream count: the default (-1) auto-tunes from the
    # transport probe, which would make the assertions box-dependent
    _config.set("data_streams_per_peer", 4)
    _FakeState.registry = {}
    monkeypatch.setattr(dist, "StateClient", _FakeState)
    rts = [dist.DistributedRuntime("fake-state:0", ResourceSet({"CPU": 2.0}),
                                   is_driver=True) for _ in range(2)]
    try:
        yield rts
    finally:
        for rt in rts:
            rt.shutdown()
        for k, v in saved.items():
            _config.set(k, v)


def _put_array(rt, nbytes=4 << 20):
    oid = ObjectID.from_random()
    value = np.random.RandomState(3).randint(
        0, 256, size=nbytes, dtype=np.uint8)
    rt.local_node.store.put(oid, value)
    return oid, value


def test_striped_fetch_lands_sealed_and_byte_identical(two_runtimes):
    rt1, rt2 = two_runtimes
    oid, value = _put_array(rt2)
    got, err = rt1._fetch_from(rt2.address, oid)
    assert err is None
    assert np.array_equal(got, value)
    # a full stream pool was opened to the peer and striped across
    streams = rt1._data_streams._streams.get(rt2.address, [])
    assert len(streams) == _config.get("data_streams_per_peer")
    # the bytes landed in a store recv buffer and sealed IN PLACE: the
    # fetched object is locally served without re-serialization
    assert rt1.local_node.store.contains(oid)
    again = rt1.local_node.store.get(oid, timeout=0)
    assert np.array_equal(again, value)


def test_fetch_serves_raw_frames_with_data_plane_disabled(two_runtimes):
    """data_streams_per_peer=0 falls back to the multiplexed control
    connection but still moves chunks through the raw frame lane — byte
    identity must hold without the pool."""
    rt1, rt2 = two_runtimes
    _config.set("data_streams_per_peer", 0)
    oid, value = _put_array(rt2)
    got, err = rt1._fetch_from(rt2.address, oid)
    assert err is None
    assert np.array_equal(got, value)
    assert not rt1._data_streams._streams.get(rt2.address)
    # heap-destination fallback: the value is returned, not store-sealed
    assert not rt1.local_node.store.contains(oid)


def test_mid_transfer_stream_failure_fails_over(two_runtimes):
    """Chunks queued on a stream that dies mid-transfer are retried on the
    surviving/replenished streams; the sealed result is byte-identical
    (no holes, no stale bytes in the recv destination)."""
    rt1, rt2 = two_runtimes
    oid, value = _put_array(rt2)
    pool = rt1._data_streams
    real_clients = pool.clients
    state = {"fail_left": 3}

    class _FlakyStream:
        """First chunk submissions fail like a reset-mid-send; later ones
        delegate to the real stream."""

        def __init__(self, inner):
            self._inner = inner

        @property
        def closed(self):
            return self._inner.closed

        def call_async(self, method, body, cb, raw_sink=None, raw=None):
            if state["fail_left"] > 0:
                state["fail_left"] -= 1
                cb(None, RpcConnectionError("injected mid-transfer reset"))
                return
            self._inner.call_async(method, body, cb,
                                   raw_sink=raw_sink, raw=raw)

        def close(self):
            self._inner.close()

        def join_reader(self, timeout=None):
            self._inner.join_reader(timeout)

    def flaky_clients(addr):
        cs = real_clients(addr)
        return [_FlakyStream(cs[0])] + cs[1:] if cs else cs

    pool.clients = flaky_clients
    try:
        got, err = rt1._fetch_from(rt2.address, oid)
    finally:
        pool.clients = real_clients
    assert err is None
    assert state["fail_left"] == 0, "injection never fired"
    assert np.array_equal(got, value)
    assert np.array_equal(rt1.local_node.store.get(oid, timeout=0), value)


def test_chaos_reset_mid_fetch_does_not_corrupt_arena(two_runtimes):
    """Under chaos-injected connection resets on FETCH_OBJECT sends the
    pull either completes byte-identical or fails cleanly; the recv
    destination is never left half-sealed (a later fetch of the same
    object must see pristine bytes, not a scribbled arena slot)."""
    rt1, rt2 = two_runtimes
    oid, value = _put_array(rt2)
    prev = chaos.schedule()
    chaos.configure(11, "rpc.client.send[method=FETCH_OBJECT]@3%7=reset")
    try:
        from ray_tpu._private.distributed import _FETCH_MISS
        got = None
        for _ in range(10):
            try:
                v, err = rt1._fetch_from(rt2.address, oid)
            except (RpcConnectionError, TimeoutError):
                continue  # probe died on the control lane: retry
            if err is None and v is not _FETCH_MISS:
                got = v
                break
    finally:
        if prev is not None:
            chaos.install(prev)
        else:
            chaos.clear()
    assert got is not None, "fetch never completed under chaos resets"
    assert np.array_equal(got, value)
    # post-chaos: the sealed local copy (or a clean re-fetch) is pristine
    store = rt1.local_node.store
    if store.contains(oid):
        assert np.array_equal(store.get(oid, timeout=0), value)
    else:
        v2, err = rt1._fetch_from(rt2.address, oid)
        assert err is None and np.array_equal(v2, value)


# --------------------------------------------------- control-plane batching


def test_state_batcher_preserves_update_remove_order():
    """Batched directory ops for one object must reach the service in
    enqueue order (UPDATE→REMOVE flips meaning if reordered), and many
    ops must coalesce into fewer bursts than ops."""
    from ray_tpu._private.state_client import StateClient

    ops = []

    def handler(ctx):
        if ctx.method in (pb.ADD_LOCATION, pb.REMOVE_LOCATION):
            req = pb.ObjectLocRequest()
            req.ParseFromString(ctx.body)
            kind = "ADD" if ctx.method == pb.ADD_LOCATION else "REMOVE"
            ops.append((kind, req.object_id))
            ctx.reply(b"")
        elif ctx.method == pb.GET_LOCATIONS:
            req = pb.GetLocationsRequest()
            req.ParseFromString(ctx.body)
            ops.append(("GET", req.object_id))
            ctx.reply(pb.GetLocationsReply().SerializeToString())
        else:
            ctx.reply(b"")

    # inline: handler runs on the reader thread, so `ops` order IS the
    # per-connection wire order (what the C++ epoll loop guarantees)
    server = RpcServer(handler, inline_methods={
        pb.ADD_LOCATION, pb.REMOVE_LOCATION, pb.GET_LOCATIONS, pb.PING})
    sc = StateClient(server.address)
    try:
        assert sc._batching_on(), "state batching should default on"
        a, b, node = b"A" * 16, b"B" * 16, b"N" * 16
        expect = []
        for i in range(20):
            sc.add_location(a, node, size=i)
            expect.append(("ADD", a))
        sc.add_location(b, node)
        sc.remove_location(a, node)
        sc.add_location(a, node)
        expect += [("ADD", b), ("REMOVE", a), ("ADD", a)]
        assert sc.flush_locations(timeout=10.0)
        assert ops == expect
        assert 1 <= sc._batcher.flushes < len(expect), \
            "ops did not coalesce into bursts"

        # read-your-writes: a get right after an enqueue must observe it
        c = b"C" * 16
        sc.add_location(c, node)
        sc.get_locations(c)
        assert ops[-2:] == [("ADD", c), ("GET", c)]
    finally:
        sc.close()
        server.close()


def test_state_batcher_flush_is_a_barrier():
    """flush_locations returns only after every enqueued op is answered —
    slow replies must not let the barrier pass early."""
    from ray_tpu._private.state_client import StateClient

    seen = threading.Event()

    def handler(ctx):
        if ctx.method == pb.ADD_LOCATION:
            time.sleep(0.05)
            seen.set()
        ctx.reply(b"")

    server = RpcServer(handler, inline_methods={pb.ADD_LOCATION, pb.PING})
    sc = StateClient(server.address)
    try:
        sc.add_location(b"Z" * 16, b"N" * 16)
        assert sc.flush_locations(timeout=10.0)
        assert seen.is_set(), "flush returned before the op was applied"
    finally:
        sc.close()
        server.close()
