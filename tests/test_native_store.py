"""C++ object-store arena tests.

The pytest analogue of the reference's plasma gtest suite
(``src/ray/object_manager/test/``, SURVEY §4.1): allocator behavior,
lifecycle, eviction ordering, and the integration with the Python
ObjectStore's spill path.
"""

import os
import time

import numpy as np
import pytest

from ray_tpu._native import NativeObjectStore

pytestmark = pytest.mark.skipif(
    not NativeObjectStore.available(), reason="no C++ toolchain")


def oid(n: int) -> bytes:
    return n.to_bytes(16, "little")


def test_put_get_roundtrip():
    s = NativeObjectStore(1 << 20)
    assert s.put(oid(1), b"hello world")
    assert s.contains(oid(1))
    assert s.get_bytes(oid(1)) == b"hello world"
    assert s.get_bytes(oid(2)) is None
    assert not s.put(oid(1), b"other")  # immutable: second put refused
    assert s.get_bytes(oid(1)) == b"hello world"


def test_zero_copy_view_pins():
    s = NativeObjectStore(1 << 20)
    payload = bytes(range(256)) * 16
    s.put(oid(3), payload)
    view = s.get(oid(3))
    assert bytes(view) == payload
    # Pinned: not an eviction candidate even when space is demanded.
    assert oid(3) not in s.evict_candidates(1)
    view.release()
    s.release(oid(3))
    assert oid(3) in s.evict_candidates(1)


def test_empty_object():
    s = NativeObjectStore(1 << 20)
    s.put(oid(4), b"")
    assert s.get_bytes(oid(4)) == b""


def test_capacity_and_memoryerror():
    s = NativeObjectStore(1 << 16)  # 64 KiB
    s.put(oid(1), b"x" * 30000)
    with pytest.raises(MemoryError):
        s.put(oid(2), b"y" * 60000)


def test_delete_frees_and_coalesces():
    s = NativeObjectStore(1 << 16)
    # Fill with 3 chunks, free the middle+first, then a large alloc must
    # fit in the coalesced hole.
    s.put(oid(1), b"a" * 20000)
    s.put(oid(2), b"b" * 20000)
    s.put(oid(3), b"c" * 20000)
    assert s.delete(oid(1))
    assert s.delete(oid(2))
    assert s.put(oid(4), b"d" * 39000)
    used, cap, count = s.stats()
    assert count == 2


def test_lru_eviction_order():
    s = NativeObjectStore(1 << 20)
    for i in range(5):
        s.put(oid(i), bytes(1000))
    # Touch 0 and 1 so 2 becomes LRU.
    s.get_bytes(oid(0))
    s.get_bytes(oid(1))
    cands = s.evict_candidates(1)
    assert cands[0] == oid(2)


def test_python_store_uses_arena_and_spills():
    """Integration: big pickled objects land in the arena; over-budget
    eviction spills to disk and get() restores (reference flow:
    plasma eviction -> SpillObjects -> restore)."""
    from ray_tpu._private.object_store import ObjectStore
    from ray_tpu._private.ids import ObjectID, TaskID, JobID
    from ray_tpu._private.config import _config

    store = ObjectStore(capacity_bytes=1 << 20)  # 1 MiB arena
    if store._native is None:
        pytest.skip("native arena disabled")
    old_threshold = _config.get("object_spilling_threshold")
    payloads = {}
    try:
        job = JobID.from_random()
        for i in range(6):
            oid_ = ObjectID.for_put(TaskID.for_task(job), i)
            value = np.arange(40_000 + i).tobytes()  # ~320KB pickled
            payloads[oid_] = value
            store.put(oid_, value)
        stats = store.stats()
        assert stats["native_arena"]
        assert stats["num_spilled"] >= 1, stats
        # Everything is still readable (spilled ones restore from disk).
        for oid_, value in payloads.items():
            assert store.get(oid_) == value
    finally:
        _config.set("object_spilling_threshold", old_threshold)


def _pin_and_die(path, q):
    from ray_tpu._native import NativeStoreClient
    c = NativeStoreClient(path)
    view = c.get(b"pinned-obj")  # pins server-side
    q.put(bytes(view[:4]))
    q.close()
    q.join_thread()  # flush the feeder before the hard exit
    os._exit(0)  # die without unpinning — server must roll back


def test_served_arena_rollback_on_client_death(tmp_path):
    """A client that dies holding pins must not pin objects forever: the
    server rolls its pins back on disconnect (plasma disconnect path)."""
    import multiprocessing as mp
    from ray_tpu._native import NativeObjectStore
    s = NativeObjectStore(1 << 20)
    path = str(tmp_path / "arena.sock")
    assert s.serve(path)
    assert s.put(b"pinned-obj", b"abcd" * 100)
    q = mp.Queue()
    p = mp.Process(target=_pin_and_die, args=(path, q))
    p.start()
    assert q.get(timeout=20) == b"abcd"
    p.join(10)
    # after disconnect rollback the object is deletable (pin released)
    deadline = time.time() + 10
    while time.time() < deadline:
        if s.delete(b"pinned-obj"):
            break
        time.sleep(0.05)
    assert not s.contains(b"pinned-obj")


def test_served_arena_concurrent_clients(tmp_path):
    import multiprocessing as mp
    from ray_tpu._native import NativeObjectStore

    def worker(path, i, q):
        from ray_tpu._native import NativeStoreClient
        c = NativeStoreClient(path)
        key = f"obj-{i}".encode()
        c.put(key, bytes([i]) * 10000)
        data = c.get_bytes(key)
        q.put((i, data == bytes([i]) * 10000))
        c.close()

    s = NativeObjectStore(1 << 22)
    path = str(tmp_path / "arena.sock")
    assert s.serve(path)
    q = mp.Queue()
    procs = [mp.Process(target=worker, args=(path, i, q)) for i in range(4)]
    for p in procs:
        p.start()
    results = [q.get(timeout=30) for _ in procs]
    for p in procs:
        p.join(10)
    assert all(ok for _, ok in results)
    assert s.stats()[2] == 4
