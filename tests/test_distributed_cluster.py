"""Multi-process cluster tests: real state-service + host-daemon processes,
tasks/actors/objects crossing OS process boundaries, chaos recovery.

The process-level analogue of the reference's multi-raylet Cluster tests
(python/ray/tests/test_multi_node*.py, test_chaos.py): every daemon is a
separate process speaking the wire protocol; killing one is a real SIGKILL.
"""

import os
import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu.cluster_utils import ProcessCluster


@pytest.fixture()
def cluster():
    ray_tpu.shutdown()  # earlier module-scoped runtimes must not leak in
    c = ProcessCluster(num_daemons=2, num_cpus=2)
    ray_tpu.init(address=c.address)
    yield c
    ray_tpu.shutdown()
    c.shutdown()


def test_tasks_run_across_daemon_processes(cluster):
    @ray_tpu.remote
    def where(x):
        return os.getpid(), x * 2

    refs = [where.remote(i) for i in range(40)]
    results = ray_tpu.get(refs, timeout=60)
    pids = {pid for pid, _ in results}
    values = [v for _, v in results]
    assert values == [2 * i for i in range(40)]
    assert os.getpid() not in pids, "driver must not execute tasks"
    assert len(pids) == 2, f"expected both daemons used, got {pids}"


def test_task_chaining_across_processes(cluster):
    @ray_tpu.remote
    def a():
        return np.arange(1000)

    @ray_tpu.remote
    def b(arr):
        return int(arr.sum())

    assert ray_tpu.get(b.remote(a.remote()), timeout=60) == 499500


def test_large_object_cross_process_fetch(cluster):
    """A >inline-threshold result stays in the executing daemon's store and
    is pulled chunked by the driver."""
    @ray_tpu.remote
    def big():
        return np.ones((1500, 1500), dtype=np.float64)  # ~18 MB

    arr = ray_tpu.get(big.remote(), timeout=120)
    assert arr.shape == (1500, 1500)
    assert float(arr.sum()) == 1500 * 1500


def test_put_ref_used_by_remote_task(cluster):
    data = np.arange(200000)  # ~1.6MB: fetched from the driver by the daemon
    ref = ray_tpu.put(data)

    @ray_tpu.remote
    def total(arr):
        return int(arr.sum())

    assert ray_tpu.get(total.remote(ref), timeout=60) == int(data.sum())
    # Nested in a container: resolved at execution via the borrow protocol.

    @ray_tpu.remote
    def total_nested(d):
        return int(ray_tpu.get(d["ref"]).sum())

    assert ray_tpu.get(total_nested.remote({"ref": ref}),
                       timeout=60) == int(data.sum())


def test_actor_on_daemon_with_ordered_calls(cluster):
    @ray_tpu.remote
    class Counter:
        def __init__(self):
            self.n = 0
            self.pid = os.getpid()

        def inc(self):
            self.n += 1
            return self.n

        def where(self):
            return self.pid

    c = Counter.remote()
    results = ray_tpu.get([c.inc.remote() for _ in range(20)], timeout=60)
    assert results == list(range(1, 21)), "actor calls must stay ordered"
    assert ray_tpu.get(c.where.remote(), timeout=30) != os.getpid()


def test_named_actor_resolution(cluster):
    @ray_tpu.remote
    class Registry:
        def __init__(self):
            self.data = {}

        def set(self, k, v):
            self.data[k] = v
            return True

        def get(self, k):
            return self.data.get(k)

    reg = Registry.options(name="global-registry").remote()
    assert ray_tpu.get(reg.set.remote("k", 42), timeout=60)
    handle = ray_tpu.get_actor("global-registry")
    assert ray_tpu.get(handle.get.remote("k"), timeout=30) == 42


def test_daemon_death_task_retry(cluster):
    """SIGKILL the daemon running a task: it must retry on the survivor."""
    @ray_tpu.remote(max_retries=3)
    def slow(i):
        time.sleep(1.5)
        return os.getpid(), i

    refs = [slow.remote(i) for i in range(8)]
    time.sleep(0.5)  # let pushes land on both daemons
    cluster.kill_daemon(0)
    results = ray_tpu.get(refs, timeout=120)
    survivor_pid = cluster.daemons[1]["proc"].pid
    assert all(pid == survivor_pid for pid, _ in results)
    assert sorted(i for _, i in results) == list(range(8))


def test_daemon_death_actor_restart(cluster):
    @ray_tpu.remote(max_restarts=2)
    class Stateful:
        def __init__(self):
            self.pid = os.getpid()
            self.n = 0

        def bump(self):
            self.n += 1
            return self.pid, self.n

    s = Stateful.remote()
    pid1, n = ray_tpu.get(s.bump.remote(), timeout=60)
    victim = next(i for i, d in enumerate(cluster.daemons)
                  if d["proc"].pid == pid1)
    cluster.kill_daemon(victim)
    deadline = time.monotonic() + 90
    pid2 = None
    while time.monotonic() < deadline:
        try:
            pid2, _ = ray_tpu.get(s.bump.remote(), timeout=10)
            break
        except ray_tpu.exceptions.RayTpuError:
            time.sleep(0.5)  # raylint: allow(bare-retry) deadline-bounded test poll
    assert pid2 is not None and pid2 != pid1, "actor must restart elsewhere"


def test_owner_daemon_dies_lineage_reconstructs(cluster):
    """Large task result lives only in daemon A's store; kill A; get() must
    re-execute the producing task on the survivor (ObjectRecoveryManager
    role, object_recovery_manager.h:90)."""
    @ray_tpu.remote(max_retries=2)
    def produce():
        return os.getpid(), np.full((1200, 1200), 7.0)  # ~11 MB, not inlined

    ref = produce.remote()
    pid, arr = ray_tpu.get(ref, timeout=120)
    victim = next(i for i, d in enumerate(cluster.daemons)
                  if d["proc"].pid == pid)
    # Drop our cached local copy so the only copy dies with the daemon.
    rt = ray_tpu._private.worker.global_worker().runtime
    from ray_tpu._private.ids import ObjectID
    rt.local_node.store.free(ref.id())
    rt._location_hints.pop(ref.id(), None)
    del arr
    cluster.kill_daemon(victim)
    time.sleep(4)  # heartbeat timeout -> NODE_DEAD -> directory cleanup
    pid2, arr2 = ray_tpu.get(ref, timeout=120)
    assert pid2 != pid
    assert float(arr2[0, 0]) == 7.0


def test_wait_across_processes(cluster):
    @ray_tpu.remote
    def fast():
        return 1

    @ray_tpu.remote
    def slow():
        time.sleep(5)
        return 2

    f, s = fast.remote(), slow.remote()
    ready, pending = ray_tpu.wait([f, s], num_returns=1, timeout=30)
    assert ready == [f] and pending == [s]


def test_spillback_on_infeasible_local(cluster):
    """A request larger than one daemon's capacity but fitting another is
    served; an impossible request errors cleanly."""
    addr = cluster.add_daemon(num_cpus=8)

    @ray_tpu.remote(num_cpus=6)
    def heavy():
        return os.getpid()

    pid = ray_tpu.get(heavy.remote(), timeout=60)
    assert pid == cluster.daemons[-1]["proc"].pid

    @ray_tpu.remote(num_cpus=64)
    def impossible():
        return 0

    with pytest.raises(ray_tpu.exceptions.RayTpuError):
        ray_tpu.get(impossible.remote(), timeout=60)


# -- host-shared object plane ----------------------------------------------

def test_same_host_fetch_goes_through_arena(cluster):
    """Daemons + driver on one host share the shm arena: a large fetch
    lands the payload in the arena (fd-passed memfd pages), not in a TCP
    stream. (plasma store.h role)"""
    rt = ray_tpu._private.worker.global_worker().runtime
    if rt.host_arena is None:
        pytest.skip("native arena unavailable in this environment")

    @ray_tpu.remote
    def produce():
        return np.full((700, 700), 3.25)  # ~3.9 MB

    before = rt.host_arena.stats()[2]
    val = ray_tpu.get(produce.remote(), timeout=60)
    assert float(val[0, 0]) == 3.25
    used, cap, count = rt.host_arena.stats()
    assert count >= before + 1, "payload should be cached in the arena"
    assert used > 3_000_000
    # zero-copy decode: the array is a read-only view over the shared
    # arena pages (protocol-5 out-of-band buffers), not a pickled copy
    assert not val.flags.owndata
    assert not val.flags.writeable


def test_arena_survives_repeat_fetches_and_eviction(cluster):
    rt = ray_tpu._private.worker.global_worker().runtime
    if rt.host_arena is None:
        pytest.skip("native arena unavailable")

    @ray_tpu.remote
    def make(i):
        return np.full((256, 256), float(i))

    refs = [make.remote(i) for i in range(6)]
    for i, r in enumerate(refs):
        v = ray_tpu.get(r, timeout=60)
        assert float(v[0, 0]) == float(i)
    # re-fetch: second consumer path hits the existing arena entries
    for i, r in enumerate(refs):
        rt.local_node.store.free(r.id())
        rt._location_hints.pop(r.id(), None)
        v = ray_tpu.get(r, timeout=60)
        assert float(v[0, 0]) == float(i)


def test_push_path_streams_object_to_peer():
    """With the arena off, large task args are proactively pushed to the
    executing daemon with windowed backpressure (push_manager.h role)."""
    ray_tpu.shutdown()
    os.environ["RAY_TPU_ARENA_ENABLED"] = "0"
    c = ProcessCluster(num_daemons=2, num_cpus=2)
    try:
        ray_tpu.init(address=c.address,
                     _system_config={"arena_enabled": False,
                                     "object_push_threshold_bytes": 4096})
        rt = ray_tpu._private.worker.global_worker().runtime
        assert rt.host_arena is None

        big = ray_tpu.put(np.full((600, 600), 1.5))  # ~2.9 MB driver-local

        # 1) deterministic: push directly to a chosen daemon (no pull race)
        target = c.daemons[1]["address"]
        rt._push_mgr.maybe_push(target, big.id(), 4096)
        deadline = time.monotonic() + 30
        addrs = []
        while time.monotonic() < deadline:
            rep = rt.state.get_locations(big.id().binary())
            addrs = list(rep.addresses)
            if target in addrs:
                break
            time.sleep(0.2)
        assert target in addrs, addrs

        # 2) end-to-end: a dependent task resolves the arg (push or pull)
        before = rt._push_mgr.pushes_initiated

        @ray_tpu.remote
        def consume(arr):
            return float(arr[0, 0]), os.getpid()

        v, pid = ray_tpu.get(consume.remote(big), timeout=60)
        assert v == 1.5
        # the task-push trigger must have initiated a NEW push (beyond the
        # direct one above) toward the executing daemon
        assert rt._push_mgr.pushes_initiated > before
    finally:
        ray_tpu.shutdown()
        c.shutdown()
        os.environ.pop("RAY_TPU_ARENA_ENABLED", None)
        from ray_tpu._private.config import _config
        _config.set("arena_enabled", True)
        _config.set("object_push_threshold_bytes", 256 * 1024)


def test_daemon_admission_backpressure_liveness():
    """A daemon with a tiny admission queue spills back instead of
    absorbing unbounded work — and the submitter's retry machinery still
    completes everything (liveness under backpressure)."""
    ray_tpu.shutdown()
    os.environ["RAY_TPU_DAEMON_ADMISSION_QUEUE_LIMIT"] = "4"
    c = ProcessCluster(num_daemons=2, num_cpus=2)
    try:
        ray_tpu.init(address=c.address)

        @ray_tpu.remote
        def slowish(i):
            time.sleep(0.05)
            return i

        refs = [slowish.remote(i) for i in range(60)]
        out = ray_tpu.get(refs, timeout=120)
        assert out == list(range(60))
    finally:
        ray_tpu.shutdown()
        c.shutdown()
        os.environ.pop("RAY_TPU_DAEMON_ADMISSION_QUEUE_LIMIT", None)


def test_arena_owner_death_degrades_to_tcp(cluster):
    """SIGKILL the arena owner (first daemon): same-host transfers must
    degrade to the TCP plane and the cluster keeps serving objects."""
    rt = ray_tpu._private.worker.global_worker().runtime
    if rt.host_arena is None:
        pytest.skip("native arena unavailable")

    @ray_tpu.remote(max_retries=2)
    def produce(i):
        return np.full((300, 300), float(i))

    assert float(ray_tpu.get(produce.remote(1), timeout=60)[0, 0]) == 1.0
    cluster.kill_daemon(0)  # daemon 0 started first: owns the arena
    time.sleep(4)           # NODE_DEAD
    out = ray_tpu.get([produce.remote(i) for i in range(2, 6)], timeout=120)
    assert [float(v[0, 0]) for v in out] == [2.0, 3.0, 4.0, 5.0]


def test_state_service_restart_cluster_survives(tmp_path):
    """GCS fault tolerance: SIGKILL the state service mid-run and restart
    it on the same port (journal-recovered). Clients reconnect, daemons
    re-register via the unrecognized-heartbeat path, and tasks + actors
    keep working — the cluster must not wedge."""
    ray_tpu.shutdown()
    c = ProcessCluster(num_daemons=2, num_cpus=2,
                       data_dir=str(tmp_path / "gcs"))
    try:
        ray_tpu.init(address=c.address)

        @ray_tpu.remote
        class Keeper:
            def __init__(self):
                self.n = 0

            def bump(self):
                self.n += 1
                return self.n

        k = Keeper.remote()
        assert ray_tpu.get(k.bump.remote(), timeout=60) == 1

        c.restart_state_service()

        # daemons re-register on their next unrecognized heartbeat; the
        # driver's client reconnects on its next call
        @ray_tpu.remote
        def f(x):
            return x + 1

        from ray_tpu._private.rpc import RpcConnectionError
        deadline = time.monotonic() + 60
        out = None
        while time.monotonic() < deadline:
            try:
                out = ray_tpu.get([f.remote(i) for i in range(4)],
                                  timeout=20)
                break
            except (ray_tpu.exceptions.RayTpuError, TimeoutError,
                    RpcConnectionError, OSError):
                # the reconnection window surfaces several shapes
                time.sleep(0.5)  # raylint: allow(bare-retry) deadline-bounded test poll
        assert out == [1, 2, 3, 4]
        # the actor (state preserved in its daemon) keeps serving
        assert ray_tpu.get(k.bump.remote(), timeout=60) == 2
    finally:
        ray_tpu.shutdown()
        c.shutdown()


def test_autoscaler_scales_up_process_cluster():
    """The autoscaler drives a REAL multi-process cluster: an infeasible
    task becomes unmet demand, the provider spawns a daemon process, and
    the task runs there (cluster-level scale-up end to end)."""
    from ray_tpu.autoscaler.autoscaler import (AutoscalerConfig,
                                               StandardAutoscaler)
    ray_tpu.shutdown()
    c = ProcessCluster(num_daemons=1, num_cpus=2)
    try:
        ray_tpu.init(address=c.address)
        rt = ray_tpu._private.worker.global_worker().runtime
        provider = c.node_provider({"big": {"CPU": 8}})
        scaler = StandardAutoscaler(
            AutoscalerConfig(min_workers=0, max_workers=2,
                             idle_timeout_s=1.0,
                             node_types={"big": {"CPU": 8}}),
            provider, runtime=rt)

        @ray_tpu.remote(num_cpus=6)
        def heavy():
            return os.getpid()

        ref = heavy.remote()   # infeasible on the 2-CPU daemon
        deadline = time.monotonic() + 60
        launched = 0
        while time.monotonic() < deadline and not launched:
            launched = scaler.update()["launched"]
            time.sleep(0.3)
        assert launched == 1, "autoscaler never saw the unmet demand"
        pid = ray_tpu.get(ref, timeout=90)
        assert pid == c.daemons[-1]["proc"].pid  # ran on the new daemon

        # scale DOWN: the big daemon goes idle; past idle_timeout_s the
        # autoscaler terminates it (runtime_node_id resolution path)
        deadline = time.monotonic() + 60
        terminated = 0
        while time.monotonic() < deadline and not terminated:
            terminated = scaler.update()["terminated"]
            time.sleep(0.3)
        assert terminated == 1, "idle daemon never terminated"
        assert provider.non_terminated_nodes() == []
    finally:
        ray_tpu.shutdown()
        c.shutdown()


def test_serve_replicas_across_daemon_processes(cluster):
    """Serve on a REAL multi-process cluster: the controller and replicas
    are actors on daemon processes; serve.run blocks until ready so the
    first request cannot race replica placement."""
    from ray_tpu import serve

    @serve.deployment(num_replicas=2)
    def who(req):
        return {"pid": os.getpid()}

    try:
        h = serve.run(who.bind(), name="who")
        pids = {h.remote(None).result(timeout=30)["pid"]
                for _ in range(12)}
        daemon_pids = {d["proc"].pid for d in cluster.daemons}
        # replicas live in daemon processes (pack placement may co-locate
        # them on one daemon, so >= 1 distinct pid)
        assert pids and pids <= daemon_pids, (pids, daemon_pids)
    finally:
        serve.shutdown()


def test_task_push_batching_mode(cluster):
    """task_push_batching=True routes pushes through TaskBatchMsg frames
    with per-task reply seqs: results, errors, and follow-up work all
    behave exactly as unbatched pushes."""
    from ray_tpu._private.config import _config
    _config.set("task_push_batching", True)
    try:
        @ray_tpu.remote(num_cpus=0.01)
        def double(x):
            return x * 2

        @ray_tpu.remote(num_cpus=0.01)
        def boom():
            raise ValueError("batched boom")

        assert ray_tpu.get([double.remote(i) for i in range(200)],
                           timeout=60) == [i * 2 for i in range(200)]
        with pytest.raises(Exception):
            ray_tpu.get(boom.remote(), timeout=30)
        assert ray_tpu.get(double.remote(21), timeout=30) == 42
    finally:
        _config.set("task_push_batching", False)


def test_heartbeat_resource_delta_broadcast(cluster):
    """ray_syncer role: CHANGED availability is pushed to subscribers as
    a NODE_RESOURCES event at heartbeat latency (no ListNodes polling);
    unchanged heartbeats publish nothing for that node."""
    import threading

    from ray_tpu._private.state_client import StateClient
    from ray_tpu.protocol import pb

    rt = ray_tpu._private.worker.global_worker().runtime
    events = []
    got_change = threading.Event()

    def on_event(ev):
        if ev.kind == "NODE_RESOURCES":
            info = pb.NodeInfo()
            info.ParseFromString(ev.payload)
            events.append(dict(info.available.amounts))
            got_change.set()

    sub = StateClient(rt.state_addr)
    sub.subscribe(["nodes"], on_event)
    try:
        @ray_tpu.remote(num_cpus=1)
        def hold():
            import time as _t
            _t.sleep(2.5)
            return 1

        ref = hold.remote()
        # capacity drop (and later recovery) must arrive as pushes
        assert got_change.wait(timeout=15), "no NODE_RESOURCES delta"
        assert ray_tpu.get(ref, timeout=30) == 1
        deadline = time.monotonic() + 15
        while time.monotonic() < deadline:
            if len(events) >= 2:
                break
            time.sleep(0.2)
        assert len(events) >= 2, events  # drop + recovery
    finally:
        sub.close()
