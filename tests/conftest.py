"""Test fixtures.

Multi-chip logic is tested on a virtual 8-device CPU mesh
(``xla_force_host_platform_device_count``), the JAX analogue of the
reference's in-process multi-raylet ``Cluster`` (``cluster_utils.py:99``).
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "")
    + " --xla_force_host_platform_device_count=8").strip()

import pytest  # noqa: E402

import jax  # noqa: E402

# sitecustomize pre-imports jax, so JAX_PLATFORMS env is read before this
# file runs — the config update below is what actually forces CPU (default
# jax.devices() must be the 8 virtual CPUs, not the axon TPU, or the
# multi-device collective paths silently degrade to single-device
# fallbacks). float32 matmuls so sharded-vs-dense comparisons are not
# dominated by bf16 default-precision noise.
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_default_matmul_precision", "highest")


@pytest.fixture
def ray_start_regular():
    """Single-node runtime (reference: conftest.py:244)."""
    import ray_tpu
    ray_tpu.shutdown()
    w = ray_tpu.init(num_cpus=8, ignore_reinit_error=True)
    yield w
    ray_tpu.shutdown()


@pytest.fixture
def ray_start_cluster():
    """Multi-node in-process cluster (reference: conftest.py:325)."""
    import ray_tpu
    from ray_tpu.cluster_utils import Cluster
    ray_tpu.shutdown()
    cluster = Cluster(initialize_head=False)
    yield cluster
    cluster.shutdown()


@pytest.fixture
def eight_device_mesh():
    import jax
    devices = jax.devices("cpu")
    assert len(devices) >= 8, f"need 8 virtual devices, got {len(devices)}"
    yield devices[:8]
