"""Checkpoint engine: sharded round-trip identity, content-hash dedup,
crash-atomic commit under chaos (process death at every choke point),
reshard-on-restore across world sizes, GC, and elastic trainer restart.

The crash tests run the save sequence in a subprocess with a
``RAY_TPU_CHAOS`` schedule that hard-exits mid-write / mid-commit, then
verify from the parent that the store still resolves to a complete,
hash-verified checkpoint — previous or new, never torn.
"""

import os
import subprocess
import sys

import numpy as np
import pytest

import ray_tpu
from ray_tpu import chaos
from ray_tpu.air import (Checkpoint, CheckpointConfig, FailureConfig,
                         RunConfig, ScalingConfig)
from ray_tpu.checkpoint import (CheckpointEngine, CheckpointError,
                                CheckpointNotFound, list_manifest_names,
                                load, read_manifest, resolve_latest)
from ray_tpu.train import JaxTrainer, session

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# -- round-trip identity ------------------------------------------------------

def test_round_trip_identity(tmp_path):
    """A nested pytree with mixed dtypes restores byte-identical: same
    dtypes, same values, non-array leaves (ints, strings, None) intact."""
    tree = {
        "params": {"w": np.arange(12, dtype=np.float32).reshape(3, 4),
                   "b": np.ones(4, dtype=np.float64)},
        "opt": [np.zeros(3, dtype=np.int32),
                np.array([True, False, True])],
        "epoch": 7,
        "tag": "run-a",
        "none": None,
    }
    eng = CheckpointEngine(str(tmp_path))
    name = eng.save(tree, step=7, wait=True).result()
    assert name is not None
    restored = load(str(tmp_path), name)
    assert restored["epoch"] == 7
    assert restored["tag"] == "run-a"
    assert restored["none"] is None
    for orig, back in [(tree["params"]["w"], restored["params"]["w"]),
                       (tree["params"]["b"], restored["params"]["b"]),
                       (tree["opt"][0], restored["opt"][0]),
                       (tree["opt"][1], restored["opt"][1])]:
        assert back.dtype == orig.dtype
        np.testing.assert_array_equal(back, orig)
    eng.close()


def test_latest_and_missing(tmp_path):
    with pytest.raises(CheckpointNotFound):
        load(str(tmp_path / "empty"))
    eng = CheckpointEngine(str(tmp_path))
    eng.save({"x": np.arange(3.0)}, step=1, wait=True)
    eng.save({"x": np.arange(3.0) + 1}, step=2, wait=True)
    assert eng.latest() == resolve_latest(str(tmp_path))
    np.testing.assert_array_equal(load(str(tmp_path))["x"],
                                  np.arange(3.0) + 1)
    eng.close()


# -- content-hash dedup -------------------------------------------------------

def test_warm_save_dedups_to_zero_chunk_bytes(tmp_path):
    """Saving an unchanged tree again writes ~0 new chunk bytes: every
    array chunk AND the skeleton dedup against the content store."""
    tree = {"w": np.random.default_rng(0).normal(size=(64, 64)),
            "b": np.zeros(64)}
    eng = CheckpointEngine(str(tmp_path))
    eng.save(tree, step=1, wait=True)
    cold_chunks = eng.stats.chunks_written
    cold_bytes = eng.stats.chunk_bytes_written
    assert cold_chunks == 3  # w, b, skeleton
    eng.save(tree, step=2, wait=True)
    assert eng.stats.chunks_written == cold_chunks
    assert eng.stats.chunk_bytes_written == cold_bytes
    assert eng.stats.chunks_deduped == 3
    assert eng.stats.bytes_deduped > 0
    # both manifests restore, sharing every chunk
    names = list_manifest_names(str(tmp_path))
    assert len(names) == 2
    assert (read_manifest(str(tmp_path), names[0]).chunk_ids()
            == read_manifest(str(tmp_path), names[1]).chunk_ids())
    eng.close()


# -- warm-save content-hash cache ---------------------------------------------

def test_warm_save_cache_skips_hashing_for_frozen_leaves(tmp_path):
    """Frozen (writeable=False) numpy leaves model immutable device
    buffers: a warm save of an unchanged tree must hit the hash cache —
    no re-hash, no chunk write, full dedup — and still commit a
    restorable manifest."""
    rng = np.random.default_rng(1)
    tree = {}
    for i in range(4):
        a = rng.standard_normal((64, 64))
        a.setflags(write=False)
        tree[f"l{i}"] = a
    eng = CheckpointEngine(str(tmp_path))
    eng.save(tree, step=1, wait=True)
    cold_written = eng.stats.chunks_written
    eng.save(tree, step=2, wait=True)
    assert eng.stats.chunks_written == cold_written
    assert eng.stats.chunks_deduped == 5  # 4 cache hits + skeleton
    assert eng.stats.bytes_deduped >= sum(a.nbytes for a in tree.values())
    back = load(str(tmp_path))
    for k, a in tree.items():
        np.testing.assert_array_equal(back[k], a)
    eng.close()


def test_warm_save_mutation_rehashes_exactly_that_leaf(tmp_path, monkeypatch):
    """Mutating one leaf in place (thaw + scribble) must void exactly its
    cache entry: the warm save re-hashes and re-writes that one leaf, the
    rest stay cache hits, and the dedup accounting stays correct."""
    from ray_tpu.checkpoint import engine as eng_mod
    rng = np.random.default_rng(2)
    tree = {f"l{i}": rng.standard_normal((32, 32 + i)) for i in range(4)}
    for a in tree.values():
        a.setflags(write=False)
    eng = CheckpointEngine(str(tmp_path))
    eng.save(tree, step=1, wait=True)

    hashed = []
    real_hash = eng_mod._hash_array
    monkeypatch.setattr(
        eng_mod, "_hash_array",
        lambda a: (hashed.append(a.shape), real_hash(a))[-1])
    tree["l2"].setflags(write=True)   # thaw: the cache may no longer trust it
    tree["l2"][0, 0] += 1.0
    before_written = eng.stats.chunks_written
    before_dedup = eng.stats.bytes_deduped
    eng.save(tree, step=2, wait=True)
    assert hashed == [(32, 34)]       # exactly leaf l2, nothing else
    assert eng.stats.chunks_written == before_written + 1
    assert eng.stats.bytes_deduped - before_dedup >= sum(
        a.nbytes for k, a in tree.items() if k != "l2")
    back = load(str(tmp_path))
    np.testing.assert_array_equal(back["l2"], tree["l2"])
    np.testing.assert_array_equal(back["l0"], tree["l0"])
    eng.close()


# -- crash atomicity under chaos ----------------------------------------------

_CRASH_PROG = """\
import sys
import numpy as np
from ray_tpu.checkpoint import CheckpointEngine
root = sys.argv[1]
eng = CheckpointEngine(root)
eng.save({"w": np.arange(16.0) * 1, "epoch": 1}, step=1, wait=True)
eng.save({"w": np.arange(16.0) * 2, "epoch": 2}, step=2, wait=True)
print("SURVIVED")
"""

# step 1 fires checkpoint.write twice (array + skeleton) and each commit
# stage once, so these triggers land inside step 2's save exactly.
@pytest.mark.parametrize("spec", [
    "checkpoint.write@3=exit",                  # before step 2's array chunk
    "checkpoint.commit[stage=manifest]@2=exit",  # before step 2's manifest
    "checkpoint.commit[stage=latest]@2=exit",    # manifest in, LATEST not
], ids=["write", "commit-manifest", "commit-latest"])
def test_crash_leaves_consistent_checkpoint(tmp_path, spec):
    root = str(tmp_path / "store")
    env = dict(os.environ, RAY_TPU_CHAOS=f"1:{spec}", JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, "-c", _CRASH_PROG, root],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=180)
    assert proc.returncode != 0, proc.stdout + proc.stderr
    assert "SURVIVED" not in proc.stdout

    # The store must resolve to a COMPLETE checkpoint whose arrays pass
    # hash verification and agree with its step — previous or new, never a
    # mix. A crash before the manifest lands must keep step 1 current.
    name = resolve_latest(root)
    assert name is not None
    m = read_manifest(root, name)
    if spec != "checkpoint.commit[stage=latest]@2=exit":
        assert m.step == 1
    restored = load(root, name)
    assert restored["epoch"] == m.step
    np.testing.assert_array_equal(restored["w"], np.arange(16.0) * m.step)


_POOL_CRASH_PROG = """\
import sys
import numpy as np
from ray_tpu._private.config import _config
from ray_tpu.checkpoint import CheckpointEngine
root = sys.argv[1]
_config.set("checkpoint_io_workers", 4)
eng = CheckpointEngine(root)
def tree(step):
    t = {"epoch": step}
    for i in range(8):
        t[f"l{i}"] = np.arange(4096.0) * (step * 10 + i)
    return t
eng.save(tree(1), step=1, wait=True)
eng.save(tree(2), step=2, wait=True)
print("SURVIVED")
"""


def test_hard_kill_during_pooled_write_leaves_consistent_checkpoint(tmp_path):
    """The worker-pool variant of the crash drill: each save fires
    checkpoint.write 9 times (8 leaves + skeleton) on the writer thread,
    so @12=exit dies during step 2's submission loop while pool workers
    are still writing step-2 chunks concurrently. Whatever half-written
    tmp files the kill strands, the store must still resolve to the
    complete, hash-verified step-1 checkpoint."""
    root = str(tmp_path / "store")
    env = dict(os.environ, RAY_TPU_CHAOS="1:checkpoint.write@12=exit",
               JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, "-c", _POOL_CRASH_PROG, root],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=180)
    assert proc.returncode != 0, proc.stdout + proc.stderr
    assert "SURVIVED" not in proc.stdout
    name = resolve_latest(root)
    assert name is not None
    m = read_manifest(root, name)
    assert m.step == 1
    restored = load(root, name)   # checkpoint_hash_verify re-hashes chunks
    assert restored["epoch"] == 1
    for i in range(8):
        np.testing.assert_array_equal(restored[f"l{i}"],
                                      np.arange(4096.0) * (10 + i))


def test_dropped_write_refuses_torn_manifest(tmp_path):
    """A lost chunk write (chaos drop) fails the save loudly at commit;
    the previous checkpoint stays the restore point."""
    eng = CheckpointEngine(str(tmp_path))
    eng.save({"w": np.arange(4.0)}, step=1, wait=True)
    chaos.configure(3, "checkpoint.write@1=drop")
    try:
        handle = eng.save({"w": np.full(4, 7.0)}, step=2)
        with pytest.raises(CheckpointError, match="torn"):
            handle.result(timeout=30)
    finally:
        chaos.clear()
    assert len(list_manifest_names(str(tmp_path))) == 1
    np.testing.assert_array_equal(load(str(tmp_path))["w"], np.arange(4.0))
    eng.close()


def test_restore_fault_is_loud_and_retryable(tmp_path):
    eng = CheckpointEngine(str(tmp_path))
    eng.save({"w": np.arange(5.0)}, step=1, wait=True)
    eng.close()
    chaos.configure(9, "checkpoint.restore@1=error")
    try:
        with pytest.raises(chaos.ChaosError):
            load(str(tmp_path))
    finally:
        chaos.clear()
    # nothing on disk was harmed; the retry succeeds
    np.testing.assert_array_equal(load(str(tmp_path))["w"], np.arange(5.0))


# -- reshard on restore -------------------------------------------------------

def _rank_shard(rank, world):
    rows = 8 // world
    lo = rank * rows
    return {
        "w": np.arange(24.0).reshape(8, 3)[lo:lo + rows],
        "bias": np.full(3, 0.5),      # replicated: identical on every rank
        "rng": np.full(2, float(rank)),   # replicated but PER-RANK DISTINCT
        "step": 1,
    }


def _save_sharded(root, world=4):
    engines = [CheckpointEngine(root) for _ in range(world)]
    handles = [engines[r].save(_rank_shard(r, world), step=1, rank=r,
                               world_size=world, shard_axis=0,
                               shard_paths=("w",))
               for r in range(world)]
    name = handles[0].result(timeout=60)
    for e in engines:
        e.close()
    return name


def test_sharded_round_trip_same_world(tmp_path):
    root = str(tmp_path)
    name = _save_sharded(root)
    for r in range(4):
        back = load(root, name, rank=r, world_size=4)
        np.testing.assert_array_equal(back["w"], _rank_shard(r, 4)["w"])
        np.testing.assert_array_equal(back["bias"], np.full(3, 0.5))
        # undeclared leaves keep their per-rank values at the same world
        np.testing.assert_array_equal(back["rng"], np.full(2, float(r)))
        assert back["step"] == 1


@pytest.mark.parametrize("new_world", [2, 8], ids=["shrink", "grow"])
def test_restore_reshards_to_new_world(tmp_path, new_world):
    """A 4-way axis-0 save restores onto a different world size: each new
    rank gets its equal slice of the reassembled global array, and
    leaves not declared in shard_paths restore replicated — including
    per-rank-distinct ones of matching shapes (RNG keys), which placement
    inference used to misread as one split array and tile into garbage."""
    root = str(tmp_path)
    name = _save_sharded(root, world=4)
    glob = np.arange(24.0).reshape(8, 3)
    rows = 8 // new_world
    for r in range(new_world):
        back = load(root, name, rank=r, world_size=new_world)
        np.testing.assert_array_equal(back["w"],
                                      glob[r * rows:(r + 1) * rows])
        np.testing.assert_array_equal(back["bias"], np.full(3, 0.5))
        # undeclared ⇒ replicated from rank 0, ORIGINAL shape — never a
        # concatenation of the four per-rank values re-sliced
        np.testing.assert_array_equal(back["rng"], np.zeros(2))


def test_shard_axis_requires_explicit_paths(tmp_path):
    """Placement is declared, never inferred: shard_axis without
    shard_paths (and vice versa) is rejected up front."""
    eng = CheckpointEngine(str(tmp_path))
    with pytest.raises(CheckpointError, match="shard_paths"):
        eng.save({"w": np.arange(4.0)}, step=1, world_size=2, shard_axis=0)
    with pytest.raises(CheckpointError, match="shard_paths"):
        eng.save({"w": np.arange(4.0)}, step=1, shard_paths=("w",))
    eng.close()


def test_declared_shard_mismatch_fails_commit_loudly(tmp_path):
    """A leaf declared axis-split whose shards don't assemble (non-axis
    dims differ across ranks) must abandon the save at commit instead of
    publishing a manifest that reshards into garbage."""
    root = str(tmp_path)
    engines = [CheckpointEngine(root) for _ in range(2)]
    handles = [engines[r].save(
        {"w": np.zeros((2, 3 + r))},   # rank-dependent non-axis dim
        step=1, rank=r, world_size=2, shard_axis=0, shard_paths=("w",))
        for r in range(2)]
    with pytest.raises(CheckpointError, match="non-axis dims"):
        handles[0].result(timeout=60)
    assert list_manifest_names(root) == []
    for e in engines:
        e.close(timeout=5.0)


# -- GC and retention ---------------------------------------------------------

@pytest.fixture
def no_gc_grace():
    from ray_tpu._private.config import _config
    old = _config.get("checkpoint_gc_grace_s")
    _config.set("checkpoint_gc_grace_s", 0.0)
    yield
    _config.set("checkpoint_gc_grace_s", old)


def test_prune_and_gc_reap_unreferenced_chunks(tmp_path, no_gc_grace):
    root = str(tmp_path)
    eng = CheckpointEngine(root, num_to_keep=1)
    eng.save({"w": np.arange(8.0)}, step=1, wait=True)
    eng.save({"w": np.arange(8.0) + 100}, step=2, wait=True)
    # retention pruned step 1's manifest; its now-orphaned chunks are gone
    assert len(list_manifest_names(root)) == 1
    assert eng.stats.chunks_gced >= 1
    np.testing.assert_array_equal(load(root)["w"], np.arange(8.0) + 100)

    # a crashed save's residue (an unreferenced chunk file) is reaped too
    orphan_dir = os.path.join(root, "chunks", "ff")
    os.makedirs(orphan_dir, exist_ok=True)
    with open(os.path.join(orphan_dir, "ff" + "0" * 62), "wb") as f:
        f.write(b"orphaned by a crash")
    assert eng.gc() == 1
    np.testing.assert_array_equal(load(root)["w"], np.arange(8.0) + 100)
    eng.close()


def test_gc_spares_other_processes_inflight_work(tmp_path, no_gc_grace):
    """Every rank runs its own engine on the shared root, so gc must judge
    liveness cross-process: chunks named by a pending/ shard index (a save
    another rank's committer may still publish) and files younger than the
    grace window (a peer's tmp mid-os.replace, or a chunk written before
    its shard index lands) survive; stale residue does not."""
    import json as _json
    from ray_tpu._private.config import _config
    from ray_tpu.checkpoint.manifest import ShardIndex

    root = str(tmp_path)
    eng = CheckpointEngine(root)
    eng.save({"w": np.arange(6.0)}, step=1, wait=True)

    # another process's in-flight save: an indexed chunk, nothing committed
    peer_chunk = "ab" + "1" * 62
    chunk_dir = os.path.join(root, "chunks", "ab")
    os.makedirs(chunk_dir, exist_ok=True)
    peer_path = os.path.join(chunk_dir, peer_chunk)
    with open(peer_path, "wb") as f:
        f.write(b"peer rank's next save")
    os.utime(peer_path, (1.0, 1.0))   # old: only the pending index saves it
    pend = os.path.join(root, "pending", "step-00000002")
    os.makedirs(pend, exist_ok=True)
    shard = ShardIndex(rank=1, skeleton=peer_chunk, skeleton_nbytes=0)
    with open(os.path.join(pend, "shard-1.json"), "w") as f:
        _json.dump({"step": 2, "world_size": 2, "shard": shard.to_json()},
                   f)
    assert eng.gc() == 0
    assert os.path.exists(peer_path)

    # a fresh tmp file (a peer one os.replace away) survives the grace
    # window; with the grace elapsed it is crash residue and is reaped
    _config.set("checkpoint_gc_grace_s", 300.0)
    tmp_file = os.path.join(chunk_dir, "cd" + "2" * 62 + ".tmp-99-1")
    with open(tmp_file, "wb") as f:
        f.write(b"mid-write")
    assert eng.gc() == 0
    assert os.path.exists(tmp_file)
    _config.set("checkpoint_gc_grace_s", 0.0)
    assert eng.gc() == 1
    assert not os.path.exists(tmp_file)

    # once the pending index is stale (older than the committer's
    # shard-wait deadline), it stops protecting its chunks
    idx = os.path.join(pend, "shard-1.json")
    os.utime(idx, (1.0, 1.0))
    assert eng.gc() == 1
    assert not os.path.exists(peer_path)
    eng.close()


def test_retention_keeps_newest_commits_after_step_counter_reset(
        tmp_path, no_gc_grace):
    """A post-crash attempt whose step counter restarted writes low-step
    manifests AFTER stale high-step ones; retention and the LATEST
    fallback scan order by commit time, so the fresh commits survive and
    win — never the pre-crash leftovers."""
    root = str(tmp_path)
    pre = CheckpointEngine(root)   # pre-crash attempt: steps 5 and 6
    pre.save({"w": np.full(4, 5.0)}, step=5, wait=True)
    pre.save({"w": np.full(4, 6.0)}, step=6, wait=True)
    pre.close()

    post = CheckpointEngine(root, num_to_keep=2)   # restarted counter
    post.save({"w": np.full(4, 1.0)}, step=1, wait=True)
    post.save({"w": np.full(4, 2.0)}, step=2, wait=True)
    post.close()

    names = list_manifest_names(root)
    assert len(names) == 2
    assert sorted(read_manifest(root, n).step for n in names) == [1, 2]
    np.testing.assert_array_equal(load(root)["w"], np.full(4, 2.0))
    # even with LATEST gone, the fallback scan resolves the newest COMMIT
    os.unlink(os.path.join(root, "LATEST"))
    name = resolve_latest(root)
    assert read_manifest(root, name).step == 2


def test_session_resumes_step_numbering_from_base_step(tmp_path):
    """The trainer carries base_step across elastic restarts; a restarted
    session numbers its saves after the last committed manifest instead
    of restarting at 1."""
    root = str(tmp_path)
    s = session._init_session(
        world_rank=0, world_size=1,
        checkpoint_spec={"root": root, "num_to_keep": None, "frequency": 1,
                         "run_token": "t2", "base_step": 7})
    try:
        session.report({"m": 1},
                       checkpoint=Checkpoint.from_dict({"epoch": 7}))
        assert s._ckpt_seq == 8
        s._close_engine(had_error=False)
    finally:
        session._shutdown_session()
    name = resolve_latest(root)
    assert read_manifest(root, name).step == 8


# -- trainer integration: elastic restart under chaos -------------------------

def _chaos_loop(config):
    """Reports 6 epochs; a chaos rule kills epoch 3 on the first attempt
    only (the restart resumes past the trigger's event window)."""
    from ray_tpu import chaos as ch
    ch.configure(11, "train.step@4=error")
    try:
        ckpt = session.get_checkpoint()
        start = 0 if ckpt is None else ckpt.to_dict()["epoch"] + 1
        for epoch in range(start, 6):
            ch.inject("train.step")
            session.report(
                {"epoch": epoch},
                checkpoint=Checkpoint.from_dict(
                    {"epoch": epoch, "w": np.full(4, float(epoch))}))
    finally:
        ch.clear()


def test_trainer_elastic_restart_from_committed_manifest(ray_start_regular,
                                                         tmp_path):
    """A deterministic chaos kill mid-run restarts the group from the last
    ENGINE-committed manifest: training resumes at the crash epoch instead
    of from scratch, and the final state is a committed manifest."""
    trainer = JaxTrainer(
        _chaos_loop, train_loop_config={},
        scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(
            name="exp", storage_path=str(tmp_path),
            failure_config=FailureConfig(max_failures=2),
            checkpoint_config=CheckpointConfig(num_to_keep=3)),
        collective_backend=None)
    result = trainer.fit()
    assert result.error is None
    assert result.metrics["epoch"] == 5
    epochs = [m["epoch"] for m in result.metrics_history]
    assert epochs == [0, 1, 2, 3, 4, 5]   # resumed, no epoch re-run
    root = str(tmp_path / "exp" / "checkpoints")
    final = Checkpoint.from_manifest(root).to_dict()
    assert final["epoch"] == 5
    np.testing.assert_array_equal(final["w"], np.full(4, 5.0))
    assert len(list_manifest_names(root)) <= 3
    # step numbering continued across the restart (3 pre-crash commits +
    # 3 post-restart) — a reset counter would let retention reap the
    # fresh commits behind the stale pre-crash manifests
    assert read_manifest(root, resolve_latest(root)).step == 6


# -- executor: partial final-checkpoint collection ----------------------------

def test_get_final_checkpoints_partial_on_dead_worker(ray_start_regular):
    from ray_tpu._private.config import _config
    from ray_tpu.train.backend_executor import BackendExecutor

    def loop(config):
        session.report(
            {"ok": 1},
            checkpoint=Checkpoint.from_dict(
                {"rank": session.get_world_rank()}))

    old = _config.get("checkpoint_final_timeout_s")
    _config.set("checkpoint_final_timeout_s", 2.0)
    ex = BackendExecutor(2, {"CPU": 1}, collective_backend=None)
    try:
        ex.start()
        ex.start_training(loop, {})
        while ex.get_next_results() is not None:
            pass
        ray_tpu.kill(ex.workers[1])
        finals = ex.get_final_checkpoints()
        assert len(finals) == 2
        assert finals[0] is not None
        assert finals[0].to_dict()["rank"] == 0
        assert finals[1] is None     # dead worker: partial result, no hang
    finally:
        _config.set("checkpoint_final_timeout_s", old)
        ex.shutdown()
