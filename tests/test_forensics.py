"""Flight recorder + crash bundles + health doctor (the black box).

Acceptance path for the forensics plane: killing a process mid-task —
deterministically via a chaos ``exit`` rule, or with a raw SIGKILL that
runs no hooks at all — must leave a sealed crash bundle on disk from
which ``python -m ray_tpu.doctor --json`` reconstructs the in-flight
trace_id, the last spans/log lines, and the exit reason. Subprocess
tests cover both sealing paths without needing the C++ state service;
the ProcessCluster tests exercise the same story through a real daemon.
"""

import json
import os
import subprocess
import sys
import time

import pytest

import ray_tpu
from ray_tpu import chaos, observability
from ray_tpu._private.config import _config
from ray_tpu._private.profiling import get_profiler


@pytest.fixture(autouse=True)
def _forensics_hygiene():
    prof_was = _config.get("profiling_enabled")
    yield
    chaos.clear()
    observability.disable()
    _config.set("profiling_enabled", prof_was)
    get_profiler().clear()


def _require_state_service():
    """ProcessCluster needs the C++ state service (protoc + g++)."""
    from ray_tpu._native.build import build_state_service
    try:
        build_state_service()
    except Exception as e:
        pytest.skip(f"state service unavailable: {e}")


def _flight_env(tmp_path, **extra):
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               RAY_TPU_FLIGHT_RECORDER_DIR=str(tmp_path),
               RAY_TPU_FLIGHT_RECORDER_SPOOL_MS="50")
    env.update(extra)
    return env


def _bundles(root):
    out = []
    for name in sorted(os.listdir(root)):
        path = os.path.join(root, name, "BUNDLE.json")
        if os.path.exists(path):
            with open(path) as f:
                out.append(json.load(f))
    return out


def _run_doctor(root, *extra_args, env=None):
    p = subprocess.run(
        [sys.executable, "-m", "ray_tpu.doctor",
         "--flight-dir", str(root), "--json", *extra_args],
        env=env or dict(os.environ, JAX_PLATFORMS="cpu"),
        capture_output=True, text=True, timeout=120)
    assert p.returncode == 0, (p.returncode, p.stdout, p.stderr)
    return json.loads(p.stdout)


# -- self-sealing: chaos exit (the deterministic hard-death vehicle) --------

def test_chaos_exit_seals_bundle_with_inflight_trace(tmp_path):
    """A chaos ``exit`` rule fires while a task is in flight: the
    registered exit hook must seal a bundle naming the task and its
    trace id before ``os._exit`` — the deterministic stand-in for dying
    mid-task."""
    code = """
import os
os.environ["RAY_TPU_CHAOS"] = "7:task.execute[key=boom*]@1=exit(41)"
from ray_tpu.observability import recorder
from ray_tpu import chaos
rec = recorder.install("worker")
assert rec is not None and recorder.ENABLED
recorder.task_started("feedc0de", "boom_task",
                      trace_id="trace-abc", span_id="span-1")
chaos.inject("task.execute", key="boom-1")
raise SystemExit("chaos exit did not fire")
"""
    p = subprocess.run([sys.executable, "-c", code],
                       env=_flight_env(tmp_path),
                       capture_output=True, text=True, timeout=120)
    assert p.returncode == 41, (p.returncode, p.stdout, p.stderr)
    bundles = _bundles(tmp_path)
    assert len(bundles) == 1, os.listdir(tmp_path)
    b = bundles[0]
    assert b["sealed_by"] == "self"
    assert "chaos-exit(41)" in b["exit_reason"]
    assert "task.execute" in b["exit_reason"]
    assert b["trace_ids"] == ["trace-abc"]
    assert b["inflight"]["feedc0de"]["name"] == "boom_task"
    # the chaos tape shows the rule that fired
    assert any("exit(41)" in line for line in b["chaos"])
    # sealing captured every live thread's stack
    assert any("MainThread" in k for k in b["thread_stacks"])


def test_unhandled_exception_seals_bundle(tmp_path):
    code = """
from ray_tpu.observability import recorder
recorder.install("driver")
raise RuntimeError("kaboom-marker")
"""
    p = subprocess.run([sys.executable, "-c", code],
                       env=_flight_env(tmp_path),
                       capture_output=True, text=True, timeout=120)
    assert p.returncode != 0
    assert "kaboom-marker" in p.stderr  # original excepthook still chained
    (b,) = _bundles(tmp_path)
    assert b["exit_reason"].startswith("unhandled-exception: RuntimeError")
    assert b["exception"]["type"] == "RuntimeError"
    assert "kaboom-marker" in b["exception"]["traceback"]


def test_clean_exit_leaves_no_bundle(tmp_path):
    """A normal interpreter exit is NOT a crash: atexit marks the
    recording clean and neither the sweep nor the doctor bundles it."""
    code = """
from ray_tpu.observability import recorder
recorder.install("driver")
"""
    p = subprocess.run([sys.executable, "-c", code],
                       env=_flight_env(tmp_path),
                       capture_output=True, text=True, timeout=120)
    assert p.returncode == 0, p.stderr
    assert _bundles(tmp_path) == []
    from ray_tpu.observability import recorder
    assert recorder.seal_orphans(root=str(tmp_path)) == []
    assert _bundles(tmp_path) == []
    report = recorder.disk_report(root=str(tmp_path))
    assert len(report["recordings"]) == 1
    assert report["recordings"][0]["clean_exit"] is True


# -- posthumous sealing: SIGKILL runs no hooks ------------------------------

def test_sigkill_midtask_doctor_reconstructs(tmp_path):
    """The acceptance criterion: SIGKILL a process mid-task, then
    ``python -m ray_tpu.doctor --json`` seals the orphan posthumously
    and reconstructs the in-flight trace_id, last log lines and exit
    reason from the spool + lastwords the dead process left behind."""
    code = """
import logging, sys, time
from ray_tpu._private import log_ring
log_ring.install()
from ray_tpu.observability import recorder
rec = recorder.install("worker")
logging.getLogger("ray_tpu").warning("lastwords-log-marker")
recorder.task_started("deadbeef", "stuck_task",
                      trace_id="trace-sigkill", span_id="s-9")
print(rec.dir, flush=True)
time.sleep(60)
"""
    p = subprocess.Popen([sys.executable, "-c", code],
                         env=_flight_env(tmp_path),
                         stdout=subprocess.PIPE, text=True)
    try:
        rec_dir = p.stdout.readline().strip()
        assert rec_dir
        # wait for at least one spool tick to hit disk
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline:
            if any(n.startswith("spool-") and
                   os.path.getsize(os.path.join(rec_dir, n)) > 0
                   for n in os.listdir(rec_dir)):
                break
            time.sleep(0.05)
        p.kill()
    finally:
        p.wait(timeout=30)
    assert _bundles(tmp_path) == []  # SIGKILL ran no hooks
    rep = _run_doctor(tmp_path, env=_flight_env(tmp_path))
    assert len(rep["sealed_now"]) == 1
    assert rep["healthy"] is False
    (crash,) = rep["crashes"]
    assert crash["sealed_by"] == "posthumous:doctor"
    assert "external-kill" in crash["exit_reason"]
    assert crash["trace_ids"] == ["trace-sigkill"]
    assert crash["inflight_tasks"] == [
        {"task_id": "deadbeef", "name": "stuck_task",
         "trace_id": "trace-sigkill"}]
    (b,) = _bundles(tmp_path)
    assert any("lastwords-log-marker" in line for line in b["logs"])
    # a second doctor run finds nothing new to seal (idempotent)
    rep2 = _run_doctor(tmp_path, env=_flight_env(tmp_path))
    assert rep2["sealed_now"] == []
    assert len(rep2["crashes"]) == 1


def test_seal_orphans_skips_live_processes(tmp_path):
    code = """
import sys, time
from ray_tpu.observability import recorder
rec = recorder.install("worker")
print(rec.dir, flush=True)
time.sleep(60)
"""
    p = subprocess.Popen([sys.executable, "-c", code],
                         env=_flight_env(tmp_path),
                         stdout=subprocess.PIPE, text=True)
    try:
        assert p.stdout.readline().strip()
        from ray_tpu.observability import recorder
        assert recorder.seal_orphans(root=str(tmp_path)) == []
    finally:
        p.kill()
        p.wait(timeout=30)


# -- doctor diagnosis units -------------------------------------------------

def test_doctor_straggler_detection_on_synthetic_timeline():
    from ray_tpu.doctor import diagnose
    events = []
    for pid, dur in (("node:aa", 100.0), ("node:bb", 100.0),
                     ("node:cc", 1000.0)):
        for _ in range(4):
            events.append({"ph": "X", "cat": "task", "name": "train_step",
                           "pid": pid, "dur": dur, "ts": 0})
    collected = {"ts": 0.0, "errors": [], "sealed_now": [],
                 "local": {"root": "", "recordings": [], "bundles": []},
                 "cluster": {"timeline": {"traceEvents": events,
                                          "missing_hosts": []}}}
    rep = diagnose(collected)
    assert len(rep["stragglers"]) == 1
    s = rep["stragglers"][0]
    assert s["process"] == "node:cc" and s["task"] == "train_step"
    assert s["slowdown"] >= 3.0
    # uniform durations → no stragglers
    for e in events:
        e["dur"] = 100.0
    assert diagnose(collected)["stragglers"] == []


def test_doctor_hang_detection_from_heartbeat_gauge():
    from ray_tpu.doctor import diagnose
    snaps = {"node:ab12cd34": [{
        "name": "heartbeat_consecutive_misses", "type": "gauge",
        "help": "", "samples": [["heartbeat_consecutive_misses",
                                 [["node", "ab12cd34"]], 5.0]]}]}
    forensics = {"nodes": {"ab12cd34ef": {
        "stacks": {"MainThread": "File x, line 1"},
        "inflight": {"t1": {"name": "wedged_task"}}}},
        "missing_hosts": []}
    collected = {"ts": 0.0, "errors": [], "sealed_now": [],
                 "local": {"root": "", "recordings": [], "bundles": []},
                 "cluster": {"metrics": {"snapshots": snaps,
                                         "missing_hosts": []},
                             "forensics": forensics}}
    rep = diagnose(collected)
    assert len(rep["hangs"]) == 1
    h = rep["hangs"][0]
    assert h["consecutive_misses"] == 5.0
    assert h["inflight_tasks"] == ["wedged_task"]
    assert "MainThread" in h["stacks"]


def test_doctor_render_text_mentions_the_story(tmp_path):
    """The human rendering names the crash, the trace and the verdict."""
    code = """
import os
os.environ["RAY_TPU_CHAOS"] = "1:task.execute@1=exit(3)"
from ray_tpu.observability import recorder
from ray_tpu import chaos
recorder.install("worker")
recorder.task_started("cafe0001", "render_task", trace_id="trace-render")
chaos.inject("task.execute")
"""
    p = subprocess.run([sys.executable, "-c", code],
                       env=_flight_env(tmp_path),
                       capture_output=True, text=True, timeout=120)
    assert p.returncode == 3
    out = subprocess.run(
        [sys.executable, "-m", "ray_tpu.doctor", "--flight-dir",
         str(tmp_path)],
        env=dict(os.environ, JAX_PLATFORMS="cpu"),
        capture_output=True, text=True, timeout=120)
    assert out.returncode == 0
    text = out.stdout
    assert "CRASHES (1)" in text
    assert "chaos-exit(3)" in text
    assert "trace-render" in text
    assert "render_task" in text
    assert "verdict:" in text


def test_doctor_healthy_on_empty_dir(tmp_path):
    rep = _run_doctor(tmp_path)
    assert rep["healthy"] is True
    assert rep["crashes"] == []
    # --out writes the same report atomically
    out_path = tmp_path / "report.json"
    rep2 = _run_doctor(tmp_path, "--out", str(out_path))
    assert json.loads(out_path.read_text())["healthy"] is True
    assert rep2["healthy"] is True


# -- through a real cluster (skipped where the state service can't build) ---

def test_cluster_sigkill_daemon_doctor_reconstructs(tmp_path):
    """SIGKILL a real host daemon mid-task; a chaos ``delay`` holds the
    task in flight long enough to die with it. The doctor (disk mode:
    the daemons share this machine's flight dir) must reconstruct the
    in-flight task and its trace id from the posthumous bundle."""
    from ray_tpu.cluster_utils import ProcessCluster
    _require_state_service()
    ray_tpu.shutdown()
    flight_env = {
        "RAY_TPU_FLIGHT_RECORDER_DIR": str(tmp_path),
        "RAY_TPU_FLIGHT_RECORDER_SPOOL_MS": "50",
        # hold task.execute for 30s so the kill lands mid-task
        "RAY_TPU_CHAOS": "5:task.execute[key=*slow_task*]@1=delay(30000)",
    }
    c = ProcessCluster(num_daemons=1, num_cpus=2)
    try:
        c.add_daemon(num_cpus=2, env=flight_env)
        observability.enable()
        ray_tpu.init(address=c.address)

        @ray_tpu.remote
        def slow_task():
            return 1

        with observability.span("doomed-root") as sp:
            trace_id = sp.trace_id
            ref = slow_task.remote()
            # wait until the task is actually in flight on a daemon:
            # its recorder spools an inflight entry with our trace id
            deadline = time.monotonic() + 60
            seen = False
            while time.monotonic() < deadline and not seen:
                for name in os.listdir(tmp_path):
                    lw = os.path.join(tmp_path, name, "lastwords.bin")
                    if os.path.exists(lw) and \
                            trace_id.encode() in open(lw, "rb").read():
                        seen = True
                        break
                time.sleep(0.1)
            assert seen, "task never showed up in a daemon's lastwords"
            c.kill_daemon(len(c.daemons) - 1)
            del ref
        rep = _run_doctor(tmp_path, env=_flight_env(tmp_path))
        crashes = [x for x in rep["crashes"]
                   if trace_id in x["trace_ids"]]
        assert crashes, rep["crashes"]
        crash = crashes[0]
        assert "external-kill" in crash["exit_reason"]
        assert any(t["name"].endswith("slow_task")
                   for t in crash["inflight_tasks"])
        assert crash["role"] == "host_daemon"
        assert crash["chaos_spec"].endswith("delay(30000)")
    finally:
        ray_tpu.shutdown()
        c.shutdown()


def test_cluster_chaos_exit_daemon_seals_itself(tmp_path):
    """chaos ``exit`` inside a daemon: the exit hook seals the bundle
    on the way down (sealed_by=self), no posthumous help needed."""
    from ray_tpu.cluster_utils import ProcessCluster
    _require_state_service()
    ray_tpu.shutdown()
    flight_env = {
        "RAY_TPU_FLIGHT_RECORDER_DIR": str(tmp_path),
        "RAY_TPU_FLIGHT_RECORDER_SPOOL_MS": "50",
        "RAY_TPU_CHAOS": "5:task.execute[key=*dying_task*]@1=exit(19)",
    }
    c = ProcessCluster(num_daemons=1, num_cpus=2)
    try:
        c.add_daemon(num_cpus=2, env=flight_env)
        ray_tpu.init(address=c.address)

        @ray_tpu.remote
        def dying_task():
            return 1

        ref = dying_task.remote()
        deadline = time.monotonic() + 60
        sealed = []
        while time.monotonic() < deadline and not sealed:
            sealed = [b for b in _bundles(tmp_path)
                      if b["sealed_by"] == "self"]
            time.sleep(0.2)
        assert sealed, "daemon did not self-seal on chaos exit"
        b = sealed[0]
        assert "chaos-exit(19)" in b["exit_reason"]
        assert b["role"] == "host_daemon"
        assert any(t["name"].endswith("dying_task")
                   for t in b["inflight"].values())
        del ref
    finally:
        ray_tpu.shutdown()
        c.shutdown()


def test_dashboard_forensics_endpoint(tmp_path):
    """/api/forensics federates stacks + bundle inventories; the head's
    own process always reports."""
    import urllib.request
    from ray_tpu.cluster_utils import ProcessCluster
    from ray_tpu.dashboard import start_dashboard
    _require_state_service()
    ray_tpu.shutdown()
    c = ProcessCluster(num_daemons=1, num_cpus=2)
    try:
        ray_tpu.init(address=c.address)
        head = start_dashboard(c.address)
        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{head.port}/api/forensics",
                    timeout=30) as r:
                payload = json.loads(r.read())
            assert "head" in payload and "nodes" in payload
            assert isinstance(payload["missing_hosts"], list)
            assert payload["head"]["stacks"]  # our own threads at least
            for node in payload["nodes"].values():
                assert "stacks" in node and "forensics" in node
        finally:
            head.stop()
    finally:
        ray_tpu.shutdown()
        c.shutdown()
