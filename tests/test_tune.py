"""Tests for ray_tpu.tune (mirrors the reference's tune/tests strategy:
function + class API, grid/random search, schedulers, checkpoints, resume,
failure handling)."""

import os

import pytest

import ray_tpu
from ray_tpu import tune
from ray_tpu.tune.sample import Domain
from ray_tpu.tune.search import generate_variants
from ray_tpu.tune.trial import ERROR, TERMINATED


@pytest.fixture(scope="module", autouse=True)
def _ray():
    if not ray_tpu.is_initialized():
        ray_tpu.init(num_cpus=8)
    yield


# ---------------------------------------------------------------- search
def test_generate_variants_grid_cross_product():
    space = {"a": tune.grid_search([1, 2, 3]), "b": tune.grid_search([10, 20]),
             "c": "const"}
    variants = list(generate_variants(space, num_samples=1))
    assert len(variants) == 6
    assert {(v["a"], v["b"]) for v in variants} == {
        (a, b) for a in (1, 2, 3) for b in (10, 20)}
    assert all(v["c"] == "const" for v in variants)


def test_generate_variants_sampling_and_nested():
    space = {"lr": tune.loguniform(1e-5, 1e-1),
             "net": {"width": tune.randint(8, 64),
                     "act": tune.choice(["relu", "gelu"])}}
    variants = list(generate_variants(space, num_samples=20, seed=0))
    assert len(variants) == 20
    for v in variants:
        assert 1e-5 <= v["lr"] <= 1e-1
        assert 8 <= v["net"]["width"] < 64
        assert v["net"]["act"] in ("relu", "gelu")


def test_sample_domains():
    import random
    rng = random.Random(0)
    assert 0 <= tune.uniform(0, 1).sample(rng) <= 1
    assert tune.quniform(0, 10, 2).sample(rng) % 2 == 0
    assert tune.randint(5, 6).sample(rng) == 5
    assert tune.choice([3]).sample(rng) == 3
    assert isinstance(tune.sample_from(lambda: 42).sample(rng), int)


# ---------------------------------------------------------------- function API
def test_function_trainable_run(tmp_path):
    def trainable(config):
        for i in range(5):
            tune.report(score=config["x"] * (i + 1))

    analysis = tune.run(trainable, config={"x": tune.grid_search([1, 2])},
                        metric="score", mode="max",
                        local_dir=str(tmp_path), verbose=0)
    assert len(analysis.trials) == 2
    best = analysis.get_best_trial()
    assert best.config["x"] == 2
    assert best.last_result["score"] == 10
    assert all(t.status == TERMINATED for t in analysis.trials)


def test_stop_criteria_dict(tmp_path):
    def trainable(config):
        for i in range(100):
            tune.report(it=i)

    analysis = tune.run(trainable, config={}, stop={"it": 5},
                        local_dir=str(tmp_path), verbose=0)
    t = analysis.trials[0]
    assert t.last_result["it"] == 5


def test_class_trainable_and_checkpoint_freq(tmp_path):
    class MyTrainable(tune.Trainable):
        def setup(self, config):
            self.x = config.get("start", 0)

        def step(self):
            self.x += 1
            return {"x": self.x, "done": self.x >= 6}

        def save_checkpoint(self, d):
            return {"x": self.x}

        def load_checkpoint(self, data):
            self.x = data["x"]

    analysis = tune.run(MyTrainable, config={"start": 0}, checkpoint_freq=2,
                        metric="x", mode="max", local_dir=str(tmp_path),
                        verbose=0)
    t = analysis.trials[0]
    assert t.last_result["x"] == 6
    # trial checkpoints are engine manifest refs, not payload blobs
    assert t.checkpoint is not None
    assert t.checkpoint.load()["data"]["x"] in (4, 6)


def test_trial_failure_restart_from_checkpoint(tmp_path):
    class Flaky(tune.Trainable):
        def setup(self, config):
            self.x = 0
            self.crashed = config  # marker file dir

        def step(self):
            self.x += 1
            marker = os.path.join(self.config["dir"], "crashed")
            if self.x == 3 and not os.path.exists(marker):
                open(marker, "w").close()
                raise RuntimeError("boom")
            return {"x": self.x, "done": self.x >= 5}

        def save_checkpoint(self, d):
            return {"x": self.x}

        def load_checkpoint(self, data):
            self.x = data["x"]

    analysis = tune.run(Flaky, config={"dir": str(tmp_path)},
                        checkpoint_freq=1, max_failures=2, metric="x",
                        mode="max", local_dir=str(tmp_path), verbose=0)
    t = analysis.trials[0]
    assert t.status == TERMINATED
    assert t.num_failures == 1
    assert t.last_result["x"] == 5


def test_trial_error_exhausts_failures(tmp_path):
    def bad(config):
        raise ValueError("always fails")

    analysis = tune.run(bad, config={}, max_failures=0,
                        local_dir=str(tmp_path), verbose=0)
    assert analysis.trials[0].status == ERROR
    assert "always fails" in analysis.trials[0].error


# ---------------------------------------------------------------- schedulers
def test_asha_stops_bad_trials(tmp_path):
    def trainable(config):
        for i in range(20):
            tune.report(score=config["q"] * (i + 1))

    sched = tune.AsyncHyperBandScheduler(max_t=20, grace_period=2,
                                         reduction_factor=2)
    # sequential execution with the best config first = deterministic
    # successive halving: later, worse trials hit populated rung cutoffs
    analysis = tune.run(trainable,
                        config={"q": tune.grid_search([8, 4, 2, 1])},
                        metric="score", mode="max", scheduler=sched,
                        max_concurrent_trials=1,
                        local_dir=str(tmp_path), verbose=0)
    iters = {t.config["q"]: len(t.results) for t in analysis.trials}
    # the best trial must survive to the end, worse ones must be cut early
    assert iters[8] == 20
    assert iters[1] < 20 and iters[2] < 20


def test_median_stopping(tmp_path):
    def trainable(config):
        for i in range(15):
            tune.report(score=config["q"] + i * config["q"])

    sched = tune.MedianStoppingRule(grace_period=3, min_samples_required=2)
    analysis = tune.run(trainable, config={"q": tune.grid_search([1, 5, 10])},
                        metric="score", mode="max", scheduler=sched,
                        max_concurrent_trials=3, local_dir=str(tmp_path),
                        verbose=0)
    assert len(analysis.trials) == 3


def test_pbt_exploits(tmp_path):
    class PBTTrainable(tune.Trainable):
        def setup(self, config):
            self.weight = 0.0

        def step(self):
            self.weight += self.config["lr"]
            return {"score": self.weight, "done": self.iteration >= 14}

        def save_checkpoint(self, d):
            return {"weight": self.weight}

        def load_checkpoint(self, data):
            self.weight = data["weight"]

    sched = tune.PopulationBasedTraining(
        perturbation_interval=3, hyperparam_mutations={"lr": [0.1, 1.0, 10.0]},
        seed=0)
    analysis = tune.run(PBTTrainable,
                        config={"lr": tune.choice([0.1, 1.0, 10.0])},
                        num_samples=4, metric="score", mode="max",
                        scheduler=sched, checkpoint_freq=1,
                        max_concurrent_trials=4, local_dir=str(tmp_path),
                        verbose=0, seed=1)
    assert all(t.status == TERMINATED for t in analysis.trials)
    # at least one trial must have ended above the pure-0.1-lr trajectory,
    # proving exploit/explore happened or a good config won
    best = analysis.get_best_trial()
    assert best.last_result["score"] > 0.1 * 15


# ---------------------------------------------------------------- tuner API
def test_tuner_result_grid(tmp_path):
    def trainable(config):
        tune.report(loss=(config["x"] - 3) ** 2)

    from ray_tpu.air.config import RunConfig
    tuner = tune.Tuner(
        trainable,
        param_space={"x": tune.grid_search([0, 3, 7])},
        tune_config=tune.TuneConfig(metric="loss", mode="min"),
        run_config=RunConfig(storage_path=str(tmp_path)))
    grid = tuner.fit()
    assert len(grid) == 3
    best = grid.get_best_result()
    assert best.metrics["loss"] == 0
    df = grid.get_dataframe()
    assert len(df) == 3 and "loss" in df.columns


def test_experiment_state_saved_and_resume(tmp_path):
    def trainable(config):
        for i in range(3):
            tune.report(v=i)

    analysis = tune.run(trainable, config={"x": tune.grid_search([1, 2])},
                        metric="v", mode="max", name="exp1",
                        local_dir=str(tmp_path), verbose=0)
    exp_dir = os.path.join(str(tmp_path), "exp1")
    assert os.path.exists(os.path.join(exp_dir, "experiment_state.json"))
    # resume: all trials are TERMINATED so nothing re-runs
    analysis2 = tune.run(trainable, metric="v", mode="max",
                         local_dir=str(tmp_path), resume_from=exp_dir,
                         verbose=0)
    assert len(analysis2.trials) == 2
    assert all(t.status == TERMINATED for t in analysis2.trials)


def test_loggers_write_files(tmp_path):
    def trainable(config):
        for i in range(3):
            tune.report(metric=i)

    analysis = tune.run(trainable, config={}, metric="metric", mode="max",
                        local_dir=str(tmp_path), verbose=1)
    logdir = analysis.trials[0].logdir
    assert os.path.exists(os.path.join(logdir, "result.json"))
    assert os.path.exists(os.path.join(logdir, "progress.csv"))


def test_concurrency_limiter_and_searcher():
    gen = tune.BasicVariantGenerator({"x": tune.randint(0, 10)},
                                     num_samples=5, seed=0)
    limited = tune.ConcurrencyLimiter(gen, max_concurrent=2)
    a = limited.suggest("t1")
    b = limited.suggest("t2")
    assert a is not None and b is not None
    assert limited.suggest("t3") is None  # capped
    limited.on_trial_complete("t1")
    assert limited.suggest("t3") is not None


# ---------------------------------------------------------------- hyperband
def test_hyperband_bracket_layout():
    """max_t=9, eta=3 -> s_max+1=3 brackets per band: n0=9@r0=1, n0=5(ceil(1.5*3))@r0=3, n0=3@r0=9."""
    sched = tune.HyperBandScheduler(metric="score", mode="max",
                                    max_t=9, reduction_factor=3)
    trials = [tune.Trial({"i": i}, trial_id=f"t{i}") for i in range(16)]
    for t in trials:
        sched.on_trial_add(t)
    band = sched._bands[0]
    assert [b.s for b in band] == [2, 1, 0]
    assert [b.n0 for b in band] == [9, 5, 3]
    assert [b.milestone for b in band] == [1.0, 3.0, 9.0]
    assert [len(b.members) for b in band] == [9, 5, 2]
    # a 17th trial opens a second band
    sched.on_trial_add(tune.Trial({}, trial_id="t16"))
    assert len(sched._bands) == 1  # third bracket still has room
    sched.on_trial_add(tune.Trial({}, trial_id="t17"))
    assert len(sched._bands) == 2


def test_hyperband_synchronized_halving(tmp_path):
    """9 trials in one bracket: milestone-1 cut keeps top 3, milestone-3 cut
    keeps top 1, and only that one reaches max_t."""
    def trainable(config):
        for i in range(20):
            tune.report(score=config["q"] * (i + 1))

    sched = tune.HyperBandScheduler(max_t=9, reduction_factor=3)
    analysis = tune.run(
        trainable, config={"q": tune.grid_search(list(range(1, 10)))},
        metric="score", mode="max", scheduler=sched,
        max_concurrent_trials=3, local_dir=str(tmp_path), verbose=0)
    iters = {t.config["q"]: len(t.results) for t in analysis.trials}
    assert iters[9] == 9                      # winner runs to max_t
    assert iters[7] == 3 and iters[8] == 3    # survived cut 1, lost cut 2
    for q in range(1, 7):
        assert iters[q] == 1                  # cut at the first milestone
    assert all(t.status == TERMINATED for t in analysis.trials)


# ---------------------------------------------------------------- TPE
def _drive_searcher(searcher, objective, n):
    best = -float("inf")
    for i in range(n):
        cfg = searcher.suggest(f"t{i}")
        if cfg is None:
            break
        score = objective(cfg)
        searcher.on_trial_complete(f"t{i}", {"score": score})
        best = max(best, score)
    return best


def test_tpe_finds_quadratic_optimum():
    from ray_tpu.tune.tpe import TPESearcher

    def objective(cfg):
        return -((cfg["x"] - 0.7) ** 2 + (cfg["y"] + 0.3) ** 2)

    space = {"x": tune.uniform(-2, 2), "y": tune.uniform(-2, 2)}
    tpe = TPESearcher(space, metric="score", mode="max", num_samples=60,
                      n_initial_points=10, seed=0)
    tpe_best = _drive_searcher(tpe, objective, 60)

    import random
    rng = random.Random(0)
    rand_best = max(
        objective({"x": rng.uniform(-2, 2), "y": rng.uniform(-2, 2)})
        for _ in range(60))
    assert tpe_best > -0.05
    assert tpe_best >= rand_best


def test_tpe_mixed_space():
    from ray_tpu.tune.tpe import TPESearcher

    def objective(cfg):
        lr_term = -(abs(__import__("math").log10(cfg["lr"]) + 2.0))  # best 1e-2
        width_term = -abs(cfg["width"] - 32) / 32.0
        act_term = 1.0 if cfg["act"] == "gelu" else 0.0
        return lr_term + width_term + act_term

    space = {"lr": tune.loguniform(1e-5, 1e-1),
             "width": tune.randint(8, 65),
             "act": tune.choice(["relu", "tanh", "gelu"])}
    tpe = TPESearcher(space, metric="score", mode="max", num_samples=80,
                      n_initial_points=12, seed=1)
    best = _drive_searcher(tpe, objective, 80)
    assert best > -0.8
    # the model phase should concentrate on the winning category
    late = [o for o, _ in tpe._obs[-20:]]
    gelu_frac = sum(1 for o in late if o[("act",)] == "gelu") / len(late)
    assert gelu_frac >= 0.5


def test_tpe_minimize_mode():
    from ray_tpu.tune.tpe import TPESearcher

    def objective(cfg):
        return (cfg["x"] - 1.0) ** 2

    tpe = TPESearcher({"x": tune.uniform(-4, 4)}, metric="loss", mode="min",
                      num_samples=50, n_initial_points=8, seed=2)
    best = float("inf")
    for i in range(50):
        cfg = tpe.suggest(f"t{i}")
        loss = objective(cfg)
        tpe.on_trial_complete(f"t{i}", {"loss": loss})
        best = min(best, loss)
    assert best < 0.05


def test_tpe_in_tune_run(tmp_path):
    from ray_tpu.tune.tpe import TPESearcher

    def trainable(config):
        tune.report(score=-(config["x"] - 0.5) ** 2)

    analysis = tune.run(trainable, config={"x": tune.uniform(-1, 1)},
                        num_samples=12, metric="score", mode="max",
                        search_alg=TPESearcher(seed=3, n_initial_points=4),
                        local_dir=str(tmp_path), verbose=0)
    assert len(analysis.trials) == 12
    assert all(t.status == TERMINATED for t in analysis.trials)
    assert analysis.best_result["score"] <= 0.0


def test_tpe_constructor_space_survives_run(tmp_path):
    """run() without config= must not wipe a searcher-supplied space."""
    from ray_tpu.tune.tpe import TPESearcher

    def trainable(config):
        tune.report(score=-abs(config["x"]))

    tpe = TPESearcher({"x": tune.uniform(-1, 1)}, num_samples=6,
                      n_initial_points=2, seed=4)
    analysis = tune.run(trainable, metric="score", mode="max",
                        search_alg=tpe, local_dir=str(tmp_path), verbose=0)
    assert len(analysis.trials) == 6
    assert all("x" in t.config for t in analysis.trials)


def test_searcher_min_mode_not_flipped_by_run_default(tmp_path):
    """A searcher built with mode='min' keeps it when run() defaults to max."""
    from ray_tpu.tune.tpe import TPESearcher

    tpe = TPESearcher({"x": tune.uniform(-4, 4)}, metric="loss", mode="min",
                      num_samples=30, n_initial_points=6, seed=5)

    def trainable(config):
        tune.report(loss=(config["x"] - 1.0) ** 2, score=0.0)

    tune.run(trainable, metric="score", mode="max", search_alg=tpe,
             local_dir=str(tmp_path), verbose=0)
    assert tpe.mode == "min"
    # internal scores are negated losses: best observation near x=1
    best_flat = max(tpe._obs, key=lambda ov: ov[1])[0]
    assert abs(best_flat[("x",)] - 1.0) < 1.0


def test_hyperband_cut_losers_release_limiter_slots(tmp_path):
    """Losers killed by a band cut must notify the searcher, or a
    ConcurrencyLimiter starves (regression for the _apply_cut path)."""
    def trainable(config):
        for i in range(20):
            tune.report(score=config["q"] * (i + 1))

    gen = tune.BasicVariantGenerator(
        {"q": tune.grid_search(list(range(1, 10)))}, num_samples=1)
    limited = tune.ConcurrencyLimiter(gen, max_concurrent=3)
    sched = tune.HyperBandScheduler(max_t=9, reduction_factor=3)
    analysis = tune.run(trainable, metric="score", mode="max",
                        scheduler=sched, search_alg=limited,
                        max_concurrent_trials=3,
                        local_dir=str(tmp_path), verbose=0)
    assert len(analysis.trials) == 9       # limiter never starved
    assert not limited._live               # every slot released
    # paused trials hold limiter slots, so the 9-bracket can never fill;
    # the release_holds fail-safe degrades to halving over each admitted
    # group — verify it stays sane: most trials cut early, winners reach
    # max_t, nothing hangs
    iters = sorted(len(t.results) for t in analysis.trials)
    assert iters[0] == 1 and iters[-1] == 9
    assert sum(1 for i in iters if i < 9) >= 2, iters


def test_hyperband_lazy_admission_exact_halving(tmp_path):
    """Searcher-driven (lazy) trial admission must not trigger premature
    cuts: the bracket waits until full, then halves exactly (9 -> 3 -> 1)."""
    def trainable(config):
        for i in range(20):
            tune.report(score=config["q"] * (i + 1))

    gen = tune.BasicVariantGenerator(
        {"q": tune.grid_search(list(range(1, 10)))}, num_samples=1)
    sched = tune.HyperBandScheduler(max_t=9, reduction_factor=3)
    analysis = tune.run(trainable, metric="score", mode="max",
                        scheduler=sched, search_alg=gen,
                        max_concurrent_trials=3,
                        local_dir=str(tmp_path), verbose=0)
    iters = sorted(len(t.results) for t in analysis.trials)
    assert iters == [1] * 6 + [3] * 2 + [9], iters


def test_tpe_integer_stays_in_domain():
    from ray_tpu.tune.tpe import TPESearcher
    tpe = TPESearcher({"n": tune.randint(0, 4)}, metric="score", mode="max",
                      num_samples=40, n_initial_points=5, seed=6)
    seen = set()
    for i in range(40):
        cfg = tpe.suggest(f"t{i}")
        assert 0 <= cfg["n"] < 4
        seen.add(cfg["n"])
        tpe.on_trial_complete(f"t{i}", {"score": float(cfg["n"])})
    assert 3 in seen


def test_hyperband_min_mode_survives_run_default(tmp_path):
    """A scheduler built with mode='min' must not be flipped to 'max' by
    run()'s default — the lowest-metric trial has to win (regression)."""
    def trainable(config):
        for i in range(20):
            tune.report(loss=config["q"] * (i + 1), score=0.0)

    sched = tune.HyperBandScheduler(metric="loss", mode="min",
                                    max_t=9, reduction_factor=3)
    analysis = tune.run(trainable,
                        config={"q": tune.grid_search(list(range(1, 10)))},
                        metric="score", mode="max", scheduler=sched,
                        max_concurrent_trials=3, local_dir=str(tmp_path),
                        verbose=0)
    iters = {t.config["q"]: len(t.results) for t in analysis.trials}
    assert iters[1] == 9      # lowest loss runs to max_t
    assert iters[9] == 1      # highest loss cut at the first milestone


# ---------------------------------------------------------------- BOHB
def test_bohb_multi_fidelity_model_selection():
    """The model must be fit on the LARGEST budget with enough points —
    low-budget observations that MISLEAD (inverted scores) must be
    superseded once full-budget evidence accumulates."""
    from ray_tpu.tune import BOHBSearcher
    s = BOHBSearcher({"x": tune.uniform(0, 10)}, metric="score", mode="max",
                     num_samples=80, min_points_in_model=5,
                     random_fraction=0.0, seed=3)
    # Budget 1: misleading (higher x looks better). Budget 9: truth
    # (optimum near x=2).
    for i in range(10):
        cfg = s.suggest(f"w{i}")
        s.on_trial_result(f"w{i}", {"score": cfg["x"],
                                    "training_iteration": 1})
        s.on_trial_complete(f"w{i}", {
            "score": -abs(cfg["x"] - 2.0), "training_iteration": 9})
    xs = []
    for i in range(30):
        cfg = s.suggest(f"t{i}")
        xs.append(cfg["x"])
        s.on_trial_complete(f"t{i}", {
            "score": -abs(cfg["x"] - 2.0), "training_iteration": 9})
    # most late suggestions should cluster near the true optimum, not 10
    near = sum(1 for x in xs[-15:] if abs(x - 2.0) < 2.5)
    assert near >= 9, xs


def test_bohb_in_tune_run(tmp_path):
    from ray_tpu.tune import BOHBSearcher, HyperBandForBOHB

    def trainable(config):
        for i in range(10):
            tune.report(score=-abs(config["x"] - 3.0) * (i + 1))

    analysis = tune.run(
        trainable, config={"x": tune.uniform(0, 10)},
        num_samples=12, metric="score", mode="max",
        scheduler=HyperBandForBOHB(max_t=9, reduction_factor=3),
        search_alg=BOHBSearcher(metric="score", mode="max",
                                min_points_in_model=3, seed=0),
        max_concurrent_trials=3, local_dir=str(tmp_path), verbose=0)
    assert len(analysis.trials) == 12
    assert all(t.status == TERMINATED for t in analysis.trials)
    assert analysis.get_best_trial() is not None


# ---------------------------------------------------------------- PB2
def test_pb2_requires_bounds_and_respects_them():
    from ray_tpu.tune import PB2
    with pytest.raises(ValueError):
        PB2(metric="score", mode="max")
    sched = PB2(metric="score", mode="max",
                hyperparam_bounds={"lr": [0.01, 1.0]}, seed=0)
    # GP-free (no data) and GP-fit paths both stay inside the box.
    for trial_no in range(6):
        cfg = sched._select_config({"lr": 0.5})
        assert 0.01 <= cfg["lr"] <= 1.0
        sched._data.append(
            (float(trial_no), sched._param_vec({"lr": 0.1 * trial_no}),
             float(trial_no)))


def test_pb2_exploits_and_learns(tmp_path):
    from ray_tpu.tune import PB2

    class T(tune.Trainable):
        def setup(self, config):
            self.weight = 0.0

        def step(self):
            self.weight += self.config["lr"]
            return {"score": self.weight, "done": self.iteration >= 14}

        def save_checkpoint(self, d):
            return {"weight": self.weight}

        def load_checkpoint(self, data):
            self.weight = data["weight"]

    sched = PB2(perturbation_interval=3,
                hyperparam_bounds={"lr": [0.05, 5.0]}, seed=0)
    analysis = tune.run(T, config={"lr": tune.uniform(0.05, 5.0)},
                        num_samples=4, metric="score", mode="max",
                        scheduler=sched, checkpoint_freq=1,
                        max_concurrent_trials=4, local_dir=str(tmp_path),
                        verbose=0, seed=1)
    assert all(t.status == TERMINATED for t in analysis.trials)
    assert sched._data, "GP observations were collected"
    best = analysis.get_best_trial()
    assert best.last_result["score"] > 0.05 * 15


# ---------------------------------------------------------------- syncer
def test_sync_config_mirrors_experiment_dir(tmp_path):
    from ray_tpu.tune import SyncConfig

    def trainable(config):
        for i in range(3):
            tune.report(v=i)

    upload = tmp_path / "durable"
    analysis = tune.run(trainable, config={"x": tune.grid_search([1, 2])},
                        metric="v", mode="max", name="synced",
                        local_dir=str(tmp_path / "local"),
                        sync_config=SyncConfig(upload_dir=str(upload),
                                               sync_period=0.0),
                        verbose=0)
    assert len(analysis.trials) == 2
    mirrored = upload / "synced"
    assert (mirrored / "experiment_state.json").exists()
    # trial logdirs came along too
    assert any(p.is_dir() for p in mirrored.iterdir())


def test_syncer_incremental_and_schemes(tmp_path):
    from ray_tpu.tune.syncer import SyncConfig, _LocalMirrorSyncer
    src = tmp_path / "src"
    dst = tmp_path / "dst"
    src.mkdir()
    (src / "a.txt").write_text("one")
    s = _LocalMirrorSyncer()
    assert s.sync_up(str(src), f"file://{dst}")
    assert (dst / "a.txt").read_text() == "one"
    # unchanged file is skipped (mtime preserved by copy2)
    before = (dst / "a.txt").stat().st_mtime_ns
    assert s.sync_up(str(src), str(dst))
    assert (dst / "a.txt").stat().st_mtime_ns == before
    # unknown scheme without explicit syncer is an error
    with pytest.raises(ValueError):
        SyncConfig(upload_dir="s3://bucket/x").get_syncer()
    # sync_down restores
    restored = tmp_path / "restored"
    assert s.sync_down(str(dst), str(restored))
    assert (restored / "a.txt").read_text() == "one"


def test_syncer_prunes_stale_mirror_entries(tmp_path):
    """Files and directories deleted at the source (pruned trial
    checkpoints) disappear from the mirror on the next sync; prune_stale
    =False keeps the old accumulate-forever behavior."""
    from ray_tpu.tune.syncer import SyncConfig, _LocalMirrorSyncer
    src = tmp_path / "src"
    dst = tmp_path / "dst"
    (src / "trial" / "ckpt-old").mkdir(parents=True)
    (src / "trial" / "ckpt-old" / "state.json").write_text("{}")
    (src / "keep.txt").write_text("keep")
    s = _LocalMirrorSyncer()
    assert s.sync_up(str(src), str(dst))
    assert (dst / "trial" / "ckpt-old" / "state.json").exists()

    import shutil
    shutil.rmtree(src / "trial" / "ckpt-old")
    (src / "trial" / "new.txt").write_text("new")
    assert s.sync_up(str(src), str(dst))
    assert not (dst / "trial" / "ckpt-old").exists()   # pruned with src
    assert (dst / "trial" / "new.txt").read_text() == "new"
    assert (dst / "keep.txt").read_text() == "keep"

    # opt-out preserves stale mirror entries
    (src / "trial" / "stale.txt").write_text("x")
    s2 = _LocalMirrorSyncer(prune_stale=False)
    assert s2.sync_up(str(src), str(dst))
    os.unlink(src / "trial" / "stale.txt")
    assert s2.sync_up(str(src), str(dst))
    assert (dst / "trial" / "stale.txt").exists()

    # the flag rides through SyncConfig
    assert SyncConfig(upload_dir=str(dst)).get_syncer().prune_stale
    assert not SyncConfig(upload_dir=str(dst),
                          prune_stale=False).get_syncer().prune_stale
