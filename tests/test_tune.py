"""Tests for ray_tpu.tune (mirrors the reference's tune/tests strategy:
function + class API, grid/random search, schedulers, checkpoints, resume,
failure handling)."""

import os

import pytest

import ray_tpu
from ray_tpu import tune
from ray_tpu.tune.sample import Domain
from ray_tpu.tune.search import generate_variants
from ray_tpu.tune.trial import ERROR, TERMINATED


@pytest.fixture(scope="module", autouse=True)
def _ray():
    if not ray_tpu.is_initialized():
        ray_tpu.init(num_cpus=8)
    yield


# ---------------------------------------------------------------- search
def test_generate_variants_grid_cross_product():
    space = {"a": tune.grid_search([1, 2, 3]), "b": tune.grid_search([10, 20]),
             "c": "const"}
    variants = list(generate_variants(space, num_samples=1))
    assert len(variants) == 6
    assert {(v["a"], v["b"]) for v in variants} == {
        (a, b) for a in (1, 2, 3) for b in (10, 20)}
    assert all(v["c"] == "const" for v in variants)


def test_generate_variants_sampling_and_nested():
    space = {"lr": tune.loguniform(1e-5, 1e-1),
             "net": {"width": tune.randint(8, 64),
                     "act": tune.choice(["relu", "gelu"])}}
    variants = list(generate_variants(space, num_samples=20, seed=0))
    assert len(variants) == 20
    for v in variants:
        assert 1e-5 <= v["lr"] <= 1e-1
        assert 8 <= v["net"]["width"] < 64
        assert v["net"]["act"] in ("relu", "gelu")


def test_sample_domains():
    import random
    rng = random.Random(0)
    assert 0 <= tune.uniform(0, 1).sample(rng) <= 1
    assert tune.quniform(0, 10, 2).sample(rng) % 2 == 0
    assert tune.randint(5, 6).sample(rng) == 5
    assert tune.choice([3]).sample(rng) == 3
    assert isinstance(tune.sample_from(lambda: 42).sample(rng), int)


# ---------------------------------------------------------------- function API
def test_function_trainable_run(tmp_path):
    def trainable(config):
        for i in range(5):
            tune.report(score=config["x"] * (i + 1))

    analysis = tune.run(trainable, config={"x": tune.grid_search([1, 2])},
                        metric="score", mode="max",
                        local_dir=str(tmp_path), verbose=0)
    assert len(analysis.trials) == 2
    best = analysis.get_best_trial()
    assert best.config["x"] == 2
    assert best.last_result["score"] == 10
    assert all(t.status == TERMINATED for t in analysis.trials)


def test_stop_criteria_dict(tmp_path):
    def trainable(config):
        for i in range(100):
            tune.report(it=i)

    analysis = tune.run(trainable, config={}, stop={"it": 5},
                        local_dir=str(tmp_path), verbose=0)
    t = analysis.trials[0]
    assert t.last_result["it"] == 5


def test_class_trainable_and_checkpoint_freq(tmp_path):
    class MyTrainable(tune.Trainable):
        def setup(self, config):
            self.x = config.get("start", 0)

        def step(self):
            self.x += 1
            return {"x": self.x, "done": self.x >= 6}

        def save_checkpoint(self, d):
            return {"x": self.x}

        def load_checkpoint(self, data):
            self.x = data["x"]

    analysis = tune.run(MyTrainable, config={"start": 0}, checkpoint_freq=2,
                        metric="x", mode="max", local_dir=str(tmp_path),
                        verbose=0)
    t = analysis.trials[0]
    assert t.last_result["x"] == 6
    assert t.checkpoint is not None and t.checkpoint["data"]["x"] in (4, 6)


def test_trial_failure_restart_from_checkpoint(tmp_path):
    class Flaky(tune.Trainable):
        def setup(self, config):
            self.x = 0
            self.crashed = config  # marker file dir

        def step(self):
            self.x += 1
            marker = os.path.join(self.config["dir"], "crashed")
            if self.x == 3 and not os.path.exists(marker):
                open(marker, "w").close()
                raise RuntimeError("boom")
            return {"x": self.x, "done": self.x >= 5}

        def save_checkpoint(self, d):
            return {"x": self.x}

        def load_checkpoint(self, data):
            self.x = data["x"]

    analysis = tune.run(Flaky, config={"dir": str(tmp_path)},
                        checkpoint_freq=1, max_failures=2, metric="x",
                        mode="max", local_dir=str(tmp_path), verbose=0)
    t = analysis.trials[0]
    assert t.status == TERMINATED
    assert t.num_failures == 1
    assert t.last_result["x"] == 5


def test_trial_error_exhausts_failures(tmp_path):
    def bad(config):
        raise ValueError("always fails")

    analysis = tune.run(bad, config={}, max_failures=0,
                        local_dir=str(tmp_path), verbose=0)
    assert analysis.trials[0].status == ERROR
    assert "always fails" in analysis.trials[0].error


# ---------------------------------------------------------------- schedulers
def test_asha_stops_bad_trials(tmp_path):
    def trainable(config):
        for i in range(20):
            tune.report(score=config["q"] * (i + 1))

    sched = tune.AsyncHyperBandScheduler(max_t=20, grace_period=2,
                                         reduction_factor=2)
    # sequential execution with the best config first = deterministic
    # successive halving: later, worse trials hit populated rung cutoffs
    analysis = tune.run(trainable,
                        config={"q": tune.grid_search([8, 4, 2, 1])},
                        metric="score", mode="max", scheduler=sched,
                        max_concurrent_trials=1,
                        local_dir=str(tmp_path), verbose=0)
    iters = {t.config["q"]: len(t.results) for t in analysis.trials}
    # the best trial must survive to the end, worse ones must be cut early
    assert iters[8] == 20
    assert iters[1] < 20 and iters[2] < 20


def test_median_stopping(tmp_path):
    def trainable(config):
        for i in range(15):
            tune.report(score=config["q"] + i * config["q"])

    sched = tune.MedianStoppingRule(grace_period=3, min_samples_required=2)
    analysis = tune.run(trainable, config={"q": tune.grid_search([1, 5, 10])},
                        metric="score", mode="max", scheduler=sched,
                        max_concurrent_trials=3, local_dir=str(tmp_path),
                        verbose=0)
    assert len(analysis.trials) == 3


def test_pbt_exploits(tmp_path):
    class PBTTrainable(tune.Trainable):
        def setup(self, config):
            self.weight = 0.0

        def step(self):
            self.weight += self.config["lr"]
            return {"score": self.weight, "done": self.iteration >= 14}

        def save_checkpoint(self, d):
            return {"weight": self.weight}

        def load_checkpoint(self, data):
            self.weight = data["weight"]

    sched = tune.PopulationBasedTraining(
        perturbation_interval=3, hyperparam_mutations={"lr": [0.1, 1.0, 10.0]},
        seed=0)
    analysis = tune.run(PBTTrainable,
                        config={"lr": tune.choice([0.1, 1.0, 10.0])},
                        num_samples=4, metric="score", mode="max",
                        scheduler=sched, checkpoint_freq=1,
                        max_concurrent_trials=4, local_dir=str(tmp_path),
                        verbose=0, seed=1)
    assert all(t.status == TERMINATED for t in analysis.trials)
    # at least one trial must have ended above the pure-0.1-lr trajectory,
    # proving exploit/explore happened or a good config won
    best = analysis.get_best_trial()
    assert best.last_result["score"] > 0.1 * 15


# ---------------------------------------------------------------- tuner API
def test_tuner_result_grid(tmp_path):
    def trainable(config):
        tune.report(loss=(config["x"] - 3) ** 2)

    from ray_tpu.air.config import RunConfig
    tuner = tune.Tuner(
        trainable,
        param_space={"x": tune.grid_search([0, 3, 7])},
        tune_config=tune.TuneConfig(metric="loss", mode="min"),
        run_config=RunConfig(storage_path=str(tmp_path)))
    grid = tuner.fit()
    assert len(grid) == 3
    best = grid.get_best_result()
    assert best.metrics["loss"] == 0
    df = grid.get_dataframe()
    assert len(df) == 3 and "loss" in df.columns


def test_experiment_state_saved_and_resume(tmp_path):
    def trainable(config):
        for i in range(3):
            tune.report(v=i)

    analysis = tune.run(trainable, config={"x": tune.grid_search([1, 2])},
                        metric="v", mode="max", name="exp1",
                        local_dir=str(tmp_path), verbose=0)
    exp_dir = os.path.join(str(tmp_path), "exp1")
    assert os.path.exists(os.path.join(exp_dir, "experiment_state.json"))
    # resume: all trials are TERMINATED so nothing re-runs
    analysis2 = tune.run(trainable, metric="v", mode="max",
                         local_dir=str(tmp_path), resume_from=exp_dir,
                         verbose=0)
    assert len(analysis2.trials) == 2
    assert all(t.status == TERMINATED for t in analysis2.trials)


def test_loggers_write_files(tmp_path):
    def trainable(config):
        for i in range(3):
            tune.report(metric=i)

    analysis = tune.run(trainable, config={}, metric="metric", mode="max",
                        local_dir=str(tmp_path), verbose=1)
    logdir = analysis.trials[0].logdir
    assert os.path.exists(os.path.join(logdir, "result.json"))
    assert os.path.exists(os.path.join(logdir, "progress.csv"))


def test_concurrency_limiter_and_searcher():
    gen = tune.BasicVariantGenerator({"x": tune.randint(0, 10)},
                                     num_samples=5, seed=0)
    limited = tune.ConcurrencyLimiter(gen, max_concurrent=2)
    a = limited.suggest("t1")
    b = limited.suggest("t2")
    assert a is not None and b is not None
    assert limited.suggest("t3") is None  # capped
    limited.on_trial_complete("t1")
    assert limited.suggest("t3") is not None
