"""Observability layer: metrics, events, timeline, state API, CLI.

Mirrors the reference's coverage of ``ray.util.metrics`` (tests in
``python/ray/tests/test_metrics_agent.py``), the state API
(``test_state_api.py``), and the timeline (``test_advanced.py``
chrome_tracing_dump assertions).
"""

import json
import os
import time
import urllib.request

import pytest

import ray_tpu
from ray_tpu.util.metrics import (Counter, Gauge, Histogram,
                                  generate_prometheus_text, _registry,
                                  start_metrics_server, stop_metrics_server)


@pytest.fixture(autouse=True)
def _clean_registry():
    _registry.clear()
    yield
    _registry.clear()


# -- metrics ---------------------------------------------------------------

def test_counter_gauge_histogram():
    c = Counter("req_total", "requests", tag_keys=("route",))
    c.inc(tags={"route": "/a"})
    c.inc(2, tags={"route": "/a"})
    c.inc(tags={"route": "/b"})
    g = Gauge("queue_len", "queued items")
    g.set(7)
    h = Hist = Histogram("latency_s", "latency", boundaries=(0.1, 1.0))
    h.observe(0.05)
    h.observe(0.5)
    h.observe(5.0)
    text = generate_prometheus_text()
    assert 'req_total{route="/a"} 3.0' in text
    assert 'req_total{route="/b"} 1.0' in text
    assert "queue_len 7.0" in text
    assert 'latency_s_bucket{le="0.1"} 1.0' in text
    assert 'latency_s_bucket{le="1.0"} 2.0' in text
    assert 'latency_s_bucket{le="+Inf"} 3.0' in text
    assert "latency_s_count 3.0" in text


def test_counter_rejects_negative_and_unknown_tags():
    c = Counter("neg_total", tag_keys=("k",))
    with pytest.raises(ValueError):
        c.inc(-1)
    with pytest.raises(ValueError):
        c.inc(tags={"bogus": "x"})


def test_render_federated_marks_missing_hosts():
    """Unreachable hosts surface as federation_missing_hosts samples so
    one scrape distinguishes 'node quiet' from 'node unscraped'."""
    from ray_tpu.util.metrics import render_federated, snapshot
    Counter("fed_total").inc(2)
    snaps = {"head": snapshot()}
    missing = [{"node_id": "ab12cd34ef567890", "address": "127.0.0.1:1",
                "error": "connection refused"}]
    text = render_federated(snaps, missing_hosts=missing)
    assert 'fed_total{node="head"} 2.0' in text
    assert '# TYPE federation_missing_hosts gauge' in text
    assert ('federation_missing_hosts{node="ab12cd34",'
            'address="127.0.0.1:1"} 1.0') in text
    # no missing hosts → no placeholder family at all
    assert "federation_missing_hosts" not in render_federated(snaps)


def test_metrics_server_scrape():
    Counter("scrape_total").inc(5)
    port = start_metrics_server()
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics", timeout=5) as r:
            body = r.read().decode()
        assert "scrape_total 5.0" in body
    finally:
        stop_metrics_server()


# -- timeline / profiling ---------------------------------------------------

def test_timeline_records_task_and_actor_spans(tmp_path, ray_start_regular):
    from ray_tpu._private.profiling import get_profiler
    get_profiler().clear()

    @ray_tpu.remote
    def traced_task():
        return 1

    @ray_tpu.remote
    class TracedActor:
        def method(self):
            return 2

    ray_tpu.get([traced_task.remote() for _ in range(3)])
    a = TracedActor.remote()
    ray_tpu.get(a.method.remote())

    trace = ray_tpu.timeline()
    names = [e["name"].split(".")[-1] for e in trace]
    assert names.count("traced_task") == 3
    assert "method" in names  # TracedActor.method
    for e in trace:
        assert e["ph"] == "X" and e["dur"] >= 0

    out = tmp_path / "trace.json"
    ray_tpu.timeline(str(out))
    assert json.loads(out.read_text())


def test_profile_span_context_manager():
    from ray_tpu._private.profiling import get_profiler, profile_span
    get_profiler().clear()
    with profile_span("custom_phase", args={"step": 1}):
        pass
    spans = get_profiler().chrome_trace()
    assert spans[-1]["name"] == "custom_phase"
    assert spans[-1]["args"] == {"step": 1}


# -- events -----------------------------------------------------------------

def test_event_log_persists_jsonl(tmp_path):
    from ray_tpu._private.config import _config
    old_dir = _config.get("event_log_dir")
    _config.set("event_log_dir", str(tmp_path))
    _config.set("event_log_enabled", True)
    try:
        ray_tpu.shutdown()
        w = ray_tpu.init(num_cpus=2)

        @ray_tpu.remote
        def f():
            return 1

        ray_tpu.get(f.remote())
        # TASK_DONE is emitted by the executor thread *after* the result
        # seal releases this get(), so persistence is eventually-consistent
        # with respect to the caller — poll briefly before asserting.
        deadline = time.time() + 5.0
        events = []
        while time.time() < deadline:
            files = list(tmp_path.glob("events_*.jsonl"))
            if files:
                events = [json.loads(line) for line in
                          files[0].read_text().splitlines()]
                if any(e["kind"] == "TASK_DONE" for e in events):
                    break
            time.sleep(0.02)
        ray_tpu.shutdown()
        assert any(e["kind"] == "TASK_DONE" for e in events)
    finally:
        _config.set("event_log_enabled", False)
        _config.set("event_log_dir", old_dir)


# -- state API --------------------------------------------------------------

def test_state_api_lists(ray_start_regular):
    from ray_tpu.experimental.state import (list_actors, list_nodes,
                                            list_objects, list_tasks,
                                            summarize_actors,
                                            summarize_tasks)

    @ray_tpu.remote
    def stateful():
        return 1

    @ray_tpu.remote
    class Listed:
        def ping(self):
            return "pong"

    refs = [stateful.remote() for _ in range(4)]
    ray_tpu.get(refs)
    actor = Listed.remote()
    ray_tpu.get(actor.ping.remote())

    tasks = list_tasks()
    assert sum(1 for t in tasks
               if t["name"].endswith("stateful")) == 4
    assert all(t["state"] == "FINISHED" for t in tasks
               if t["name"].endswith("stateful"))
    actors = list_actors()
    assert any(a["class_name"] == "Listed" and a["state"] == "ALIVE"
               for a in actors)
    nodes = list_nodes()
    assert nodes and nodes[0]["state"] == "ALIVE"
    objs = list_objects()
    assert len(objs) >= 4
    ts = summarize_tasks()
    assert ts["by_state"].get("FINISHED", 0) >= 4
    asum = summarize_actors()
    assert asum["by_class"].get("Listed") == 1


def test_state_api_filters(ray_start_regular):
    from ray_tpu.experimental.state import list_tasks

    @ray_tpu.remote
    def filtered_one():
        return 1

    ref = filtered_one.remote()  # held: lineage keeps the task name
    ray_tpu.get(ref)
    name = [t["name"] for t in list_tasks()
            if t["name"].endswith("filtered_one")][0]
    rows = list_tasks(filters=[("name", "=", name)])
    assert rows and all(r["name"] == name for r in rows)
    rows = list_tasks(filters=[("name", "!=", name)], limit=5)
    assert all(r["name"] != name for r in rows)


# -- state server + CLI -----------------------------------------------------

def test_state_server_and_cli(capsys):
    ray_tpu.shutdown()
    w = ray_tpu.init(num_cpus=2, include_dashboard=True)
    try:
        port = w.dashboard_port

        @ray_tpu.remote
        def served():
            return 1

        ray_tpu.get([served.remote() for _ in range(2)])

        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/api/status", timeout=5) as r:
            status = json.loads(r.read().decode())
        assert status["initialized"]
        assert status["task_summary"]["total"] >= 2

        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics", timeout=5) as r:
            assert r.status == 200

        from ray_tpu.scripts.cli import main
        main(["--port", str(port), "status"])
        out = capsys.readouterr().out
        assert "Nodes: 1 alive" in out
        assert "Tasks:" in out
        main(["--port", str(port), "list", "actors"])
        assert json.loads(capsys.readouterr().out) == []
    finally:
        ray_tpu.shutdown()


def test_cluster_timeline_merges_daemon_spans():
    """Cross-process trace propagation: timeline() on a cluster merges
    spans recorded inside daemon processes (reference: `ray timeline`
    over GCS-aggregated profile events)."""
    from ray_tpu.cluster_utils import ProcessCluster
    ray_tpu.shutdown()
    c = ProcessCluster(num_daemons=2, num_cpus=2)
    try:
        ray_tpu.init(address=c.address)
        ray_tpu.set_profiling_enabled(True)

        @ray_tpu.remote
        def traced(x):
            return x + 1

        assert ray_tpu.get([traced.remote(i) for i in range(8)],
                           timeout=60) == list(range(1, 9))
        trace = ray_tpu.timeline()
        task_spans = [s for s in trace
                      if s.get("name", "").endswith(".traced")]
        assert len(task_spans) == 8, trace[:3]
        # spans come from the DAEMON processes (driver runs nothing)
        assert all(s["pid"].startswith("node:") for s in task_spans)
        ray_tpu.set_profiling_enabled(False)
    finally:
        ray_tpu.shutdown()
        c.shutdown()


def test_trace_context_propagates_to_child_tasks(ray_start_regular):
    """Cross-task trace propagation (tracing_helper.py:160-175 role): a
    task tree shares one trace_id, and each child's parent_span_id is
    the submitting task's span_id."""
    from ray_tpu._private.profiling import get_profiler
    get_profiler().clear()
    ray_tpu.set_profiling_enabled(True)

    @ray_tpu.remote
    def leaf():
        return 1

    @ray_tpu.remote
    def root():
        return ray_tpu.get([leaf.remote(), leaf.remote()])

    assert ray_tpu.get(root.remote(), timeout=60) == [1, 1]
    spans = {s["name"].split(".")[-1]: s for s in ray_tpu.timeline()
             if s.get("args", {}).get("trace_id")}
    leafs = [s for s in ray_tpu.timeline()
             if s["name"].endswith("leaf") and "args" in s]
    root_span = next(s for s in ray_tpu.timeline()
                     if s["name"].endswith("root"))
    assert root_span["args"]["trace_id"]
    assert root_span["args"]["parent_span_id"] == ""  # trace root
    assert len(leafs) == 2
    for s in leafs:
        assert s["args"]["trace_id"] == root_span["args"]["trace_id"]
        assert s["args"]["parent_span_id"] == root_span["args"]["span_id"]
    # span ids are unique per span
    ids = [s["args"]["span_id"] for s in leafs] + [
        root_span["args"]["span_id"]]
    assert len(set(ids)) == 3, spans


def test_trace_context_propagates_across_daemons():
    """The trace context rides TaskSpecMsg over the wire: spans recorded
    in DIFFERENT daemon processes still stitch into one trace."""
    from ray_tpu.cluster_utils import ProcessCluster
    ray_tpu.shutdown()
    c = ProcessCluster(num_daemons=2, num_cpus=2)
    try:
        ray_tpu.init(address=c.address)
        ray_tpu.set_profiling_enabled(True)

        @ray_tpu.remote
        def child():
            return 1

        @ray_tpu.remote
        class TracedActor:
            def mark(self):
                return 2

        actor = TracedActor.remote()

        @ray_tpu.remote
        def parent():
            vals = ray_tpu.get([child.remote() for _ in range(3)])
            # cross-daemon ACTOR call from inside the traced task: its
            # span must stitch into the same trace (regression: the
            # remote actor path bypassed trace attachment)
            vals.append(ray_tpu.get(actor.mark.remote()))
            return sum(vals)

        assert ray_tpu.get(parent.remote(), timeout=60) == 5
        trace = ray_tpu.timeline()
        parents = [s for s in trace if s["name"].endswith(".parent")
                   and s.get("args", {}).get("trace_id")]
        children = [s for s in trace if s["name"].endswith(".child")
                    and s.get("args", {}).get("trace_id")]
        marks = [s for s in trace if s["name"].endswith(".mark")
                 and s.get("args", {}).get("trace_id")]
        assert len(parents) == 1 and len(children) == 3, (
            [s["name"] for s in trace][:10])
        assert len(marks) == 1, [s["name"] for s in trace][:10]
        tid = parents[0]["args"]["trace_id"]
        for s in children + marks:
            assert s["args"]["trace_id"] == tid
            assert (s["args"]["parent_span_id"]
                    == parents[0]["args"]["span_id"])
        ray_tpu.set_profiling_enabled(False)
    finally:
        ray_tpu.shutdown()
        c.shutdown()
