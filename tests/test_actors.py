"""Actors: lifecycle, ordering, concurrency, named actors, restart, kill.

Models ``python/ray/tests/test_actor*.py`` coverage.
"""

import asyncio
import time

import pytest

import ray_tpu


@ray_tpu.remote
class Counter:
    def __init__(self, start=0):
        self.n = start

    def incr(self, by=1):
        self.n += by
        return self.n

    def get(self):
        return self.n


def test_actor_basic(ray_start_regular):
    c = Counter.remote()
    assert ray_tpu.get(c.incr.remote()) == 1
    assert ray_tpu.get(c.incr.remote(5)) == 6
    assert ray_tpu.get(c.get.remote()) == 6


def test_actor_ctor_args(ray_start_regular):
    c = Counter.remote(start=100)
    assert ray_tpu.get(c.get.remote()) == 100


def test_actor_method_ordering(ray_start_regular):
    c = Counter.remote()
    refs = [c.incr.remote() for _ in range(100)]
    assert ray_tpu.get(refs) == list(range(1, 101))


def test_actor_method_error(ray_start_regular):
    @ray_tpu.remote
    class Bad:
        def boom(self):
            raise KeyError("nope")

        def ok(self):
            return 1

    b = Bad.remote()
    with pytest.raises(ray_tpu.TaskError):
        ray_tpu.get(b.boom.remote())
    # Actor survives method exceptions.
    assert ray_tpu.get(b.ok.remote()) == 1


def test_actor_init_failure(ray_start_regular):
    @ray_tpu.remote
    class Broken:
        def __init__(self):
            raise RuntimeError("ctor fail")

        def f(self):
            return 1

    b = Broken.remote()
    with pytest.raises(ray_tpu.ActorDiedError):
        ray_tpu.get(b.f.remote(), timeout=10)


def test_named_actor(ray_start_regular):
    Counter.options(name="global_counter").remote(start=7)
    time.sleep(0.05)
    c = ray_tpu.get_actor("global_counter")
    assert ray_tpu.get(c.get.remote()) == 7


def test_named_actor_get_if_exists(ray_start_regular):
    a = Counter.options(name="shared", get_if_exists=True).remote()
    time.sleep(0.05)
    b = Counter.options(name="shared", get_if_exists=True).remote()
    ray_tpu.get(a.incr.remote())
    assert ray_tpu.get(b.get.remote()) == 1  # same actor


def test_kill_actor(ray_start_regular):
    c = Counter.remote()
    assert ray_tpu.get(c.incr.remote()) == 1
    ray_tpu.kill(c)
    time.sleep(0.1)
    with pytest.raises(ray_tpu.ActorDiedError):
        ray_tpu.get(c.incr.remote(), timeout=5)


def test_actor_handle_passing(ray_start_regular):
    @ray_tpu.remote
    def use_actor(handle):
        return ray_tpu.get(handle.incr.remote(10))

    c = Counter.remote()
    assert ray_tpu.get(use_actor.remote(c)) == 10


def test_max_concurrency_threaded(ray_start_regular):
    @ray_tpu.remote(max_concurrency=4)
    class Sleeper:
        def nap(self):
            time.sleep(0.3)
            return 1

    s = Sleeper.remote()
    t0 = time.monotonic()
    ray_tpu.get([s.nap.remote() for _ in range(4)])
    elapsed = time.monotonic() - t0
    assert elapsed < 1.0, f"threaded actor should overlap naps, took {elapsed}"


def test_async_actor(ray_start_regular):
    @ray_tpu.remote
    class AsyncWorker:
        async def work(self, i):
            await asyncio.sleep(0.2)
            return i

    a = AsyncWorker.remote()
    t0 = time.monotonic()
    out = ray_tpu.get([a.work.remote(i) for i in range(5)])
    elapsed = time.monotonic() - t0
    assert sorted(out) == list(range(5))
    assert elapsed < 0.9, f"async actor should overlap awaits, took {elapsed}"


def test_actor_restart(ray_start_regular):
    @ray_tpu.remote(max_restarts=2)
    class Phoenix:
        def __init__(self):
            self.state = "fresh"

        def mark(self):
            self.state = "dirty"
            return self.state

        def get_state(self):
            return self.state

    p = Phoenix.remote()
    assert ray_tpu.get(p.mark.remote()) == "dirty"
    ray_tpu.kill(p, no_restart=False)
    time.sleep(0.3)
    # Restarted: state reset by re-running __init__.
    assert ray_tpu.get(p.get_state.remote(), timeout=10) == "fresh"


def test_actor_ready(ray_start_regular):
    @ray_tpu.remote
    class Slow:
        def __init__(self):
            time.sleep(0.2)

    s = Slow.remote()
    assert ray_tpu.get(s.ready(), timeout=10) is True


def test_detached_semantics_name_released_on_death(ray_start_regular):
    c = Counter.options(name="ephemeral").remote()
    time.sleep(0.05)
    ray_tpu.kill(c)
    time.sleep(0.2)
    with pytest.raises(ValueError):
        ray_tpu.get_actor("ephemeral")
