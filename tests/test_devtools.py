"""Tests for ray_tpu.devtools: the raylint engine (R1-R6) and lockwatch.

Each rule gets one fixture that must fire and one that must stay quiet;
lockwatch gets a real two-thread A->B / B->A inversion; R6 gets a drift
test that mutates a wire field number in a copy of raytpu.proto.
"""

import os
import re
import textwrap
import threading

import pytest

from ray_tpu.devtools import lockwatch
from ray_tpu.devtools.linter import (LintEngine, parse_proto_text)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PROTO = os.path.join(REPO, "ray_tpu", "protocol", "raytpu.proto")
PB2 = os.path.join(REPO, "ray_tpu", "protocol", "raytpu_pb2.py")


def run_rule(tmp_path, rule_id, source, name="fixture.py"):
    path = tmp_path / name
    path.write_text(textwrap.dedent(source))
    eng = LintEngine([str(path)], only_rules={rule_id})
    findings = eng.run()
    assert not eng.errors, eng.errors
    return findings


# -- R1: blocking calls in async def ----------------------------------------

def test_r1_fires_on_blocking_sleep_in_async(tmp_path):
    findings = run_rule(tmp_path, "R1", """\
        import time

        async def handler():
            time.sleep(0.5)
    """)
    assert [f.rule for f in findings] == ["R1"]
    assert "time.sleep" in findings[0].message


def test_r1_quiet_on_awaited_sleep_and_sync_code(tmp_path):
    findings = run_rule(tmp_path, "R1", """\
        import asyncio
        import time

        async def handler():
            await asyncio.sleep(0.5)

        def plain():
            time.sleep(0.5)  # fine: not on the event loop

        async def bounded(fut, lock):
            fut.result(timeout=1.0)
            lock.acquire(timeout=1.0)
    """)
    assert findings == []


# -- R2: inconsistent lock-acquisition order ---------------------------------

def test_r2_fires_on_inverted_nested_with(tmp_path):
    findings = run_rule(tmp_path, "R2", """\
        import threading

        lock_a = threading.Lock()
        lock_b = threading.Lock()

        def forward():
            with lock_a:
                with lock_b:
                    pass

        def backward():
            with lock_b:
                with lock_a:
                    pass
    """)
    assert findings and all(f.rule == "R2" for f in findings)


def test_r2_quiet_on_consistent_order(tmp_path):
    findings = run_rule(tmp_path, "R2", """\
        import threading

        lock_a = threading.Lock()
        lock_b = threading.Lock()

        def forward():
            with lock_a:
                with lock_b:
                    pass

        def also_forward():
            with lock_a:
                with lock_b:
                    pass
    """)
    assert findings == []


# -- R3: unguarded cross-thread shared state ---------------------------------

def test_r3_fires_on_two_sided_unguarded_write(tmp_path):
    findings = run_rule(tmp_path, "R3", """\
        import threading

        class Worker:
            def start(self):
                self._t = threading.Thread(target=self._run)
                self._t.start()

            def _run(self):
                self._status = "running"

            def cancel(self):
                self._status = "cancelled"
    """)
    assert findings and all(f.rule == "R3" for f in findings)
    assert any("_status" in f.message for f in findings)


def test_r3_quiet_when_both_writers_hold_the_lock(tmp_path):
    findings = run_rule(tmp_path, "R3", """\
        import threading

        class Worker:
            def __init__(self):
                self._lock = threading.Lock()
                self._status = "new"

            def start(self):
                self._t = threading.Thread(target=self._run)
                self._t.start()

            def _run(self):
                with self._lock:
                    self._status = "running"

            def cancel(self):
                with self._lock:
                    self._status = "cancelled"
    """)
    assert findings == []


# -- R4: silent exception swallows -------------------------------------------

def test_r4_fires_on_silent_pass(tmp_path):
    findings = run_rule(tmp_path, "R4", """\
        def fragile():
            try:
                risky()
            except Exception:
                pass
    """)
    assert [f.rule for f in findings] == ["R4"]


def test_r4_quiet_on_logged_justified_or_narrow(tmp_path):
    findings = run_rule(tmp_path, "R4", """\
        import logging

        logger = logging.getLogger("ray_tpu")

        def logged():
            try:
                risky()
            except Exception as e:
                logger.warning("risky failed: %s", e)

        def justified():
            try:
                risky()
            except Exception:  # raylint: allow(swallow) fixture says why
                pass

        def narrow():
            try:
                risky()
            except KeyError:
                pass
    """)
    assert findings == []


# -- R5: host-device syncs reachable from jitted code -------------------------

def test_r5_fires_on_float_in_jitted_fn(tmp_path):
    findings = run_rule(tmp_path, "R5", """\
        import jax

        def helper(x):
            return float(x)

        @jax.jit
        def step(x):
            return helper(x) + x.item()
    """)
    assert findings and all(f.rule == "R5" for f in findings)
    lines = sorted(f.line for f in findings)
    assert len(lines) == 2  # float() in helper AND .item() in step


def test_r5_quiet_without_jitted_root(tmp_path):
    findings = run_rule(tmp_path, "R5", """\
        def metrics(x):
            return float(x)  # host-side code may sync freely
    """)
    assert findings == []


# -- R6: proto <-> pb2 wire-schema drift --------------------------------------

def test_r6_quiet_on_committed_pair(tmp_path):
    eng = LintEngine([], only_rules={"R6"},
                     proto_pairs=[(PROTO, PB2, "protocol/raytpu_pb2.py")])
    assert eng.run() == []


def test_r6_fires_when_field_number_mutated(tmp_path):
    src = open(PROTO, encoding="utf-8").read()
    # bump the first scalar field number in the file to a fresh value
    mutated, n = re.subn(r"(=\s*)(\d+)(\s*;)", r"\g<1>9999\g<3>", src, count=1)
    assert n == 1
    bad = tmp_path / "raytpu.proto"
    bad.write_text(mutated)
    eng = LintEngine([], only_rules={"R6"},
                     proto_pairs=[(str(bad), PB2, "protocol/raytpu_pb2.py")])
    findings = eng.run()
    assert findings and all(f.rule == "R6" for f in findings)
    assert any("9999" in f.message or "drifted" in f.message
               for f in findings)


# -- R7: hand-rolled retry loops ---------------------------------------------

def test_r7_fires_on_constant_sleep_retry_loop(tmp_path):
    findings = run_rule(tmp_path, "R7", """\
        import time

        def fetch(fn):
            while True:
                try:
                    return fn()
                except ConnectionError:
                    time.sleep(0.5)
        """)
    assert len(findings) == 1
    assert findings[0].tag == "bare-retry"
    assert "BackoffPolicy" in findings[0].message


def test_r7_fires_on_hardcoded_delay_ladder(tmp_path):
    findings = run_rule(tmp_path, "R7", """\
        from time import sleep

        def fetch(fn):
            for delay in (0.1, 0.5, 2.0):
                try:
                    return fn()
                except OSError:
                    sleep(delay)
        """)
    assert len(findings) == 1


def test_r7_quiet_on_poll_policy_and_allow(tmp_path):
    findings = run_rule(tmp_path, "R7", """\
        import time

        def plain_poll():
            while True:
                time.sleep(0.01)  # no except handler in the loop

        def policy_paced(fn, policy):
            state = policy.start()
            while True:
                try:
                    return fn()
                except ConnectionError:
                    if not state.sleep():
                        raise

        def justified(fn):
            while True:
                try:
                    return fn()
                except ConnectionError:
                    time.sleep(1)  # raylint: allow(bare-retry) spec-fixed cadence

        def variable_delay(fn, policy):
            attempt = 0
            while True:
                try:
                    return fn()
                except ConnectionError:
                    time.sleep(policy.delay_for(attempt))
                    attempt += 1
        """)
    assert findings == []


# -- R9: direct checkpoint directory I/O in train/tune/serve -----------------

def run_rule_in_tree(tmp_path, rule_id, relpath, source):
    """Lint a file placed at ``relpath`` under a package dir, so rules that
    scope on path segments (R9) see a real relative path, not a bare name."""
    path = tmp_path / "pkg" / relpath
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(textwrap.dedent(source))
    eng = LintEngine([str(tmp_path / "pkg")], only_rules={rule_id})
    findings = eng.run()
    assert not eng.errors, eng.errors
    return findings


def test_r9_fires_on_directory_io_in_train(tmp_path):
    findings = run_rule_in_tree(tmp_path, "R9", "train/trainer.py", """\
        def persist(checkpoint, path):
            checkpoint.to_directory(path)

        def resume(cls, path):
            return cls.from_directory(path)
    """)
    assert [f.rule for f in findings] == ["R9", "R9"]
    assert "to_directory" in findings[0].message
    assert "manifest" in findings[0].message


def test_r9_quiet_outside_scope_and_on_allow(tmp_path):
    # air/ is the conversion layer — out of scope by path.
    findings = run_rule_in_tree(tmp_path, "R9", "air/checkpoint.py", """\
        def persist(checkpoint, path):
            checkpoint.to_directory(path)
    """)
    assert findings == []
    # In scope, but justified with an allow comment.
    findings = run_rule_in_tree(tmp_path, "R9", "tune/export.py", """\
        def export(checkpoint, path):
            checkpoint.to_directory(path)  # raylint: allow(direct-checkpoint-io) user-facing blob export
    """)
    assert findings == []


def test_proto_parser_sees_real_schema():
    schema = parse_proto_text(open(PROTO, encoding="utf-8").read())
    assert "TaskSpecMsg" in schema
    assert any(schema.values())


# -- lockwatch ----------------------------------------------------------------

def test_lockwatch_detects_ab_ba_cycle_across_threads():
    lockwatch.reset()
    a = lockwatch.wrap(name="fixture:lock_a")
    b = lockwatch.wrap(name="fixture:lock_b")
    first_done = threading.Event()

    def t1():
        with a:
            with b:
                pass
        first_done.set()

    def t2():
        first_done.wait(timeout=10)
        with b:
            with a:
                pass

    threads = [threading.Thread(target=t1), threading.Thread(target=t2)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=10)
    try:
        cys = lockwatch.cycles()
        assert any(c["kind"] == "site-order" and
                   set(c["sites"]) == {"fixture:lock_a", "fixture:lock_b"}
                   for c in cys), cys
        rep = lockwatch.report()
        assert rep["cycles"]
    finally:
        lockwatch.reset()


def test_lockwatch_quiet_on_consistent_order():
    lockwatch.reset()
    a = lockwatch.wrap(name="fixture:ordered_a")
    b = lockwatch.wrap(name="fixture:ordered_b")

    def use():
        with a:
            with b:
                pass

    threads = [threading.Thread(target=use) for _ in range(2)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=10)
    try:
        assert lockwatch.cycles() == []
    finally:
        lockwatch.reset()


def test_lockwatch_reports_long_hold(monkeypatch):
    monkeypatch.setenv("RAY_TPU_LOCKWATCH_HOLD_S", "0.01")
    lockwatch.reset()
    lk = lockwatch.wrap(name="fixture:slow_lock")
    import time as _time
    with lk:
        _time.sleep(0.05)
    try:
        holds = lockwatch.report()["long_holds"]
        assert any(h["site"] == "fixture:slow_lock" for h in holds), holds
    finally:
        lockwatch.reset()


def test_lockwatch_rpc_pseudo_sites_close_cross_process_cycle():
    """The runtime half of R19's lock-across-RPC arm: a lock held across
    a synchronous call plus a handler that re-acquires it closes a
    site-order cycle through the ``rpc:<METHOD>`` pseudo-site."""
    lockwatch.reset()
    lk = lockwatch.wrap(name="fixture:client_lock")
    try:
        with lk:
            lockwatch.rpc_client_wait("rpc:PING")   # lock -> wire edge
        token = lockwatch.rpc_handler_enter("rpc:PING")
        with lk:                                     # wire -> lock edge
            pass
        lockwatch.rpc_handler_exit(token)
        cys = lockwatch.cycles()
        assert any(c["kind"] == "site-order" and
                   {"rpc:PING", "fixture:client_lock"} <= set(c["sites"])
                   for c in cys), cys
    finally:
        lockwatch.reset()


def test_lockwatch_rpc_pseudo_sites_quiet_without_held_locks():
    lockwatch.reset()
    lk = lockwatch.wrap(name="fixture:free_lock")
    try:
        lockwatch.rpc_client_wait("rpc:PING")   # nothing held: no edge
        token = lockwatch.rpc_handler_enter("rpc:PING")
        with lk:
            pass
        lockwatch.rpc_handler_exit(token)
        assert lockwatch.cycles() == []
    finally:
        lockwatch.reset()


def test_cli_exits_zero_on_clean_tree(tmp_path):
    clean = tmp_path / "clean.py"
    clean.write_text("def ok():\n    return 1\n")
    from ray_tpu.devtools.linter import main
    assert main([str(clean)]) == 0
    bad = tmp_path / "bad.py"
    bad.write_text("try:\n    pass\nexcept Exception:\n    pass\n")
    assert main([str(bad), "--json"]) == 1


# -- whole-program engine: multi-file helper ----------------------------------

def run_tree(tmp_path, rule_id, files):
    """Lint a multi-file tree rooted at ``proj/`` with one rule active."""
    root = tmp_path / "proj"
    for rel, src in files.items():
        p = root / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
    eng = LintEngine([str(root)],
                     only_rules={rule_id} if rule_id else None)
    findings = eng.run()
    assert not eng.errors, eng.errors
    return findings


def build_index(tmp_path, files):
    """ProjectIndex over a written tree, for call-graph unit tests."""
    from ray_tpu.devtools import callgraph
    from ray_tpu.devtools.linter import FileContext
    root = tmp_path / "proj"
    ctxs = []
    for rel, src in files.items():
        p = root / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        text = textwrap.dedent(src)
        p.write_text(text)
        ctxs.append(FileContext(str(p), f"proj/{rel}", text))
    return callgraph.ProjectIndex(ctxs)


# -- call graph: resolution unit tests ----------------------------------------

def test_callgraph_resolves_self_methods_and_module_aliases(tmp_path):
    idx = build_index(tmp_path, {
        "helpers.py": """\
            def util():
                return 1
        """,
        "mod.py": """\
            import proj.helpers as h
            from proj import helpers as h2

            class Worker:
                def run(self):
                    self.step()
                    h.util()
                    h2.util()

                def step(self):
                    return 0
        """,
    })
    run = idx.functions["proj.mod:Worker.run"]
    targets = {s.raw: s.target for s in run.call_sites}
    assert targets["self.step"] == "proj.mod:Worker.step"
    assert targets["h.util"] == "proj.helpers:util"
    assert targets["h2.util"] == "proj.helpers:util"


def test_callgraph_dynamic_call_degrades_to_unknown(tmp_path):
    idx = build_index(tmp_path, {
        "mod.py": """\
            def apply(callback):
                callback()

            def indirect(obj):
                obj.method()
        """,
    })
    for fname in ("proj.mod:apply", "proj.mod:indirect"):
        sites = idx.functions[fname].call_sites
        assert len(sites) == 1
        assert sites[0].target is None   # unknown, never a guess


# -- R10: transitive async blocking -------------------------------------------

def test_r10_fires_on_blocking_reached_through_helpers(tmp_path):
    findings = run_tree(tmp_path, "R10", {
        "svc.py": """\
            import time

            from proj import util

            async def handler():
                util.relay()
        """,
        "util.py": """\
            import time

            def relay():
                backoff()

            def backoff():
                time.sleep(0.5)
        """,
    })
    assert [f.rule for f in findings] == ["R10"]
    assert "handler" in findings[0].message
    assert "relay" in findings[0].message      # witness path is shown
    assert findings[0].path.endswith("util.py")


def test_r10_quiet_on_spawn_edges_dynamic_calls_and_allow(tmp_path):
    findings = run_tree(tmp_path, "R10", {
        "svc.py": """\
            import threading
            import time

            def backoff():
                time.sleep(0.5)

            async def spawns():
                threading.Thread(target=backoff).start()

            async def dynamic(cb):
                cb()

            def allowed_block():
                time.sleep(0.1)  # raylint: allow(async-transitive) shutdown path: loop is gone

            async def uses_allowed():
                allowed_block()
        """,
    })
    assert findings == []


# -- R11: static lock-order graph ---------------------------------------------

def test_r11_fires_on_cross_function_lock_cycle(tmp_path):
    findings = run_tree(tmp_path, "R11", {
        "a.py": """\
            import threading

            from proj import b

            LOCK_A = threading.Lock()

            def with_a_then_b():
                with LOCK_A:
                    b.grab_b()

            def grab_a():
                with LOCK_A:
                    pass
        """,
        "b.py": """\
            import threading

            from proj import a

            LOCK_B = threading.Lock()

            def grab_b():
                with LOCK_B:
                    pass

            def with_b_then_a():
                with LOCK_B:
                    a.grab_a()
        """,
    })
    assert len(findings) == 1 and findings[0].rule == "R11"
    assert "CYCLE (site-order)" in findings[0].message
    assert "LOCK_A" in findings[0].message and "LOCK_B" in findings[0].message


def test_r11_fires_on_cross_file_direct_nesting_inversion(tmp_path):
    # No call edge at all: each file nests both locks directly, in opposite
    # orders.  R2's syntactic identity cannot merge LOCK_B with b.LOCK_B,
    # so this cycle is R11's to report (module-alias lock attributes are
    # resolved to the defining module's node).
    findings = run_tree(tmp_path, "R11", {
        "a.py": """\
            import threading

            from proj import b

            LOCK_A = threading.Lock()

            def a_then_b():
                with LOCK_A:
                    with b.LOCK_B:
                        pass
        """,
        "b.py": """\
            import threading

            from proj import a

            LOCK_B = threading.Lock()

            def b_then_a():
                with LOCK_B:
                    with a.LOCK_A:
                        pass
        """,
    })
    assert len(findings) == 1 and findings[0].rule == "R11"
    assert "proj.a.LOCK_A" in findings[0].message
    assert "proj.b.LOCK_B" in findings[0].message


def test_r11_quiet_on_single_file_direct_nesting_inversion(tmp_path):
    # Both orders written inside one file are R2's finding; R11 stays
    # quiet so the same deadlock is not double-reported.
    findings = run_tree(tmp_path, "R11", {
        "a.py": """\
            import threading

            LOCK_A = threading.Lock()
            LOCK_B = threading.Lock()

            def a_then_b():
                with LOCK_A:
                    with LOCK_B:
                        pass

            def b_then_a():
                with LOCK_B:
                    with LOCK_A:
                        pass
        """,
    })
    assert findings == []


def test_r11_quiet_on_consistent_cross_function_order(tmp_path):
    findings = run_tree(tmp_path, "R11", {
        "a.py": """\
            import threading

            from proj import b

            LOCK_A = threading.Lock()

            def with_a_then_b():
                with LOCK_A:
                    b.grab_b()

            def also_a_then_b():
                with LOCK_A:
                    b.grab_b()
        """,
        "b.py": """\
            import threading

            LOCK_B = threading.Lock()

            def grab_b():
                with LOCK_B:
                    pass
        """,
    })
    assert findings == []


def test_r11_quiet_when_cycle_needs_a_spawn_edge(tmp_path):
    # the "reverse" order only happens on a freshly spawned thread, which
    # starts with an empty hold set: no cycle
    findings = run_tree(tmp_path, "R11", {
        "a.py": """\
            import threading

            from proj import b

            LOCK_A = threading.Lock()

            def with_a_then_b():
                with LOCK_A:
                    b.grab_b()

            def grab_a():
                with LOCK_A:
                    pass
        """,
        "b.py": """\
            import threading

            from proj import a

            LOCK_B = threading.Lock()

            def grab_b():
                with LOCK_B:
                    pass

            def spawn_reverse():
                with LOCK_B:
                    threading.Thread(target=a.grab_a).start()
        """,
    })
    assert findings == []


# -- R12: collective divergence -----------------------------------------------

def test_r12_fires_on_rank_guarded_collective(tmp_path):
    findings = run_tree(tmp_path, "R12", {
        "spmd.py": """\
            def barrier():
                pass

            def commit(rank, state):
                if rank == 0:
                    barrier()
        """,
    })
    assert [f.rule for f in findings] == ["R12"]
    assert "barrier" in findings[0].message


def test_r12_fires_on_except_handler_collective(tmp_path):
    findings = run_tree(tmp_path, "R12", {
        "spmd.py": """\
            def allreduce(x):
                return x

            def step(x):
                try:
                    x = x + 1
                except ValueError:
                    allreduce(x)
                return x
        """,
    })
    assert [f.rule for f in findings] == ["R12"]
    assert "except" in findings[0].message


def test_r12_quiet_on_uniform_schedules_and_allow(tmp_path):
    findings = run_tree(tmp_path, "R12", {
        "spmd.py": """\
            def barrier():
                pass

            def both_arms(rank, state):
                if rank == 0:
                    state["leader"] = True
                    barrier()
                else:
                    barrier()

            def after_branch(rank, state):
                if rank == 0:
                    state["leader"] = True
                barrier()

            def justified(rank):
                if rank == 0:
                    barrier()  # raylint: allow(collective-divergence) single-rank test harness
        """,
    })
    assert findings == []


def test_r12_regression_divergent_commit_deadlocks_under_chaos(tmp_path):
    """The acceptance shape: a rank-divergent checkpoint-commit branch is
    (a) flagged statically, and (b) actually deadlocks when the chaos gate
    faults one rank out of the commit barrier."""
    findings = run_tree(tmp_path, "R12", {"ckpt.py": """\
        def commit_and_sync(rank, tree, results):
            if rank == 0:
                results["manifest"] = tree
                barrier()

        def barrier():
            pass
    """})
    assert [f.rule for f in findings] == ["R12"]

    # runtime: two "ranks", chaos faults rank 1 before the commit barrier
    from ray_tpu import chaos
    from ray_tpu.chaos.engine import ChaosError
    prev = chaos.schedule()
    bar = threading.Barrier(2)
    outcome = {}

    def rank_main(rank):
        try:
            chaos.inject("ckpt.commit", rank=str(rank))
            bar.wait(timeout=1.0)             # the commit barrier
            outcome[rank] = "committed"
        except ChaosError:
            outcome[rank] = "faulted"         # diverged: never arrives
        except threading.BrokenBarrierError:
            outcome[rank] = "deadlocked"      # waited for a rank that won't come

    try:
        chaos.configure(7, "ckpt.commit[rank=1]@1=error")
        threads = [threading.Thread(target=rank_main, args=(r,))
                   for r in (0, 1)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
        assert outcome == {0: "deadlocked", 1: "faulted"}, outcome
    finally:
        if prev is not None:
            chaos.install(prev)
        else:
            chaos.clear()


# -- R13: config-knob and chaos-point drift -----------------------------------

def test_r13_fires_on_dead_and_undefined_knobs(tmp_path):
    findings = run_tree(tmp_path, "R13", {
        "conf.py": """\
            from ray_tpu._private.config import _config

            _config.define("live_knob", int, 1, "read below")
            _config.define("dead_knob", int, 2, "never read")

            def reader():
                return _config.get("live_knob") + _config.get("ghost_knob")
        """,
    })
    msgs = {f.message.split("'")[1]: f for f in findings}
    assert set(msgs) == {"dead_knob", "ghost_knob"}
    assert "never read" in msgs["dead_knob"].message
    assert "never defined" in msgs["ghost_knob"].message


def test_r13_ignores_unrelated_cfg_locals(tmp_path):
    # a plain dict/dataclass named cfg or _config must not be mistaken for
    # the knob registry: only the imported registry counts
    findings = run_tree(tmp_path, "R13", {
        "conf.py": """\
            from ray_tpu._private.config import _config

            _config.define("real_knob", int, 1, "read below")

            def ok():
                return _config.get("real_knob")
        """,
        "algo.py": """\
            def train(cfg, _config):
                cfg.setdefault("lr", 1e-3)
                return cfg.batch_size + _config.get("whatever")
        """,
    })
    assert findings == []


def test_r13_chaos_point_closure(tmp_path):
    findings = run_tree(tmp_path, "R13", {
        "runtime.py": """\
            from ray_tpu import chaos

            def faults():
                chaos.inject("svc.tested")
                chaos.inject("svc.untested")
        """,
        "test_faults.py": """\
            from ray_tpu import chaos as ch

            def test_one():
                ch.configure(3, "svc.tested@1=error")
                spec = "svc.ghost@1=drop"
                ch.inject("svc.direct")   # direct test inject: not "unknown"
                return spec
        """,
    })
    by_point = {f.message.split("'")[1]: f for f in findings}
    assert set(by_point) == {"svc.untested", "svc.ghost"}
    assert "never exercised" in by_point["svc.untested"].message
    assert "no runtime inject" in by_point["svc.ghost"].message


# -- CLI: --rules listing, --json, --changed, --allow-in, --self-check --------

def test_cli_rules_listing_is_machine_readable(capsys):
    import json as _json
    from ray_tpu.devtools.linter import main
    assert main(["--rules"]) == 0
    rows = _json.loads(capsys.readouterr().out)
    ids = [r["id"] for r in rows]
    assert ids == sorted(ids, key=lambda i: int(i[1:]))
    assert {"R1", "R10", "R11", "R12", "R13"} <= set(ids)
    assert all({"id", "tag", "kind", "summary"} <= set(r) for r in rows)


def test_cli_json_output_carries_structured_findings(tmp_path, capsys):
    import json as _json
    from ray_tpu.devtools.linter import main
    bad = tmp_path / "bad.py"
    bad.write_text("try:\n    pass\nexcept Exception:\n    pass\n")
    assert main([str(bad), "--json"]) == 1
    rows = _json.loads(capsys.readouterr().out)
    assert rows and rows[0]["rule"] == "R4"
    assert {"rule", "tag", "path", "line", "message"} <= set(rows[0])


def test_cli_changed_filters_to_git_diff(tmp_path, monkeypatch, capsys):
    import subprocess
    repo = tmp_path / "repo"
    (repo / "pkg").mkdir(parents=True)
    clean = "def ok():\n    return 1\n"
    swallow = "try:\n    pass\nexcept Exception:\n    pass\n"
    (repo / "pkg" / "a.py").write_text(swallow)   # committed: pre-existing
    (repo / "pkg" / "b.py").write_text(clean)
    env = {"GIT_AUTHOR_NAME": "t", "GIT_AUTHOR_EMAIL": "t@t",
           "GIT_COMMITTER_NAME": "t", "GIT_COMMITTER_EMAIL": "t@t"}
    for cmd in (["git", "init", "-q"], ["git", "add", "."],
                ["git", "commit", "-qm", "seed"]):
        subprocess.run(cmd, cwd=repo, check=True,
                       env={**os.environ, **env})
    monkeypatch.chdir(repo)
    from ray_tpu.devtools.linter import main
    # nothing changed: early exit, pre-existing finding in a.py not reported
    assert main(["pkg", "--changed"]) == 0
    assert "0 finding(s)" in capsys.readouterr().out
    # touch b.py with a NEW finding: only b.py is reported
    (repo / "pkg" / "b.py").write_text(swallow)
    assert main(["pkg", "--changed"]) == 1
    out = capsys.readouterr().out
    assert "b.py" in out and "a.py" not in out


def test_cli_allow_in_scopes_suppression_by_prefix(tmp_path):
    root = tmp_path / "proj"
    (root / "tests").mkdir(parents=True)
    (root / "lib").mkdir()
    swallow = "try:\n    pass\nexcept Exception:\n    pass\n"
    (root / "tests" / "test_x.py").write_text(swallow)
    (root / "lib" / "x.py").write_text(swallow)
    eng = LintEngine([str(root)], allow_in=[("proj/tests/", {"R4"})])
    findings = eng.run()
    assert [f.path for f in findings] == ["proj/lib/x.py"]


def test_cli_self_check_round_trips_fixture_corpus():
    from ray_tpu.devtools.linter import main
    assert main(["--self-check"]) == 0


# -- dataflow layer: R16/R17/R18 acceptance -----------------------------------

def test_r16_catches_seeded_socket_leak_with_witness_path(tmp_path):
    findings = run_tree(tmp_path, "R16", {"net.py": """\
        import socket

        def fetch(addr, key):
            sock = socket.create_connection(addr)
            if key is None:
                return None
            data = sock.recv(64)
            sock.close()
            return data
        """})
    assert len(findings) == 1
    f = findings[0]
    assert f.rule == "R16" and f.line == 4
    assert "socket 'sock'" in f.message and "'fetch'" in f.message
    # the witness path names the branch taken to the leaking exit
    assert "the return at line 6" in f.message
    assert "path: then@5" in f.message


def test_r16_quiet_on_release_transfer_and_annotation(tmp_path):
    findings = run_tree(tmp_path, "R16", {"net.py": """\
        import socket

        def closed_on_every_path(addr):
            sock = socket.create_connection(addr)
            try:
                return sock.recv(64)
            finally:
                sock.close()

        def ownership_returned(addr):
            return socket.create_connection(addr)

        def annotated(addr, reg):
            sock = socket.create_connection(addr)  # raylint: transfer(socket) reg owns it
            reg.adopt(sock)
        """})
    assert findings == []


def test_r17_catches_naked_wait_under_deadline_with_witness(tmp_path):
    findings = run_tree(tmp_path, "R17", {"drain.py": """\
        import threading

        DONE = threading.Event()

        def drain(deadline):
            _flush()

        def _flush():
            DONE.wait()
        """})
    assert len(findings) == 1
    f = findings[0]
    assert f.rule == "R17" and f.line == 9
    assert "DONE.wait() without timeout" in f.message
    assert "'drain(deadline)'" in f.message
    # witness chain: root -> call site -> blocking site
    assert "witness: drain@6 -> _flush@9" in f.message


def test_r17_quiet_when_budget_flows_down(tmp_path):
    findings = run_tree(tmp_path, "R17", {"drain.py": """\
        import threading

        DONE = threading.Event()

        def drain(deadline):
            DONE.wait(deadline)

        def unscoped():
            DONE.wait()
        """})
    assert findings == []


def test_r18_catches_seeded_send_without_handler(tmp_path):
    findings = run_tree(tmp_path, "R18", {"proto.py": """\
        def push(client, pb):
            client.call_async(pb.LOST_CALL, b"")

        def dispatch(env, ctx, pb):
            if env.method == pb.PING:
                ctx.reply(b"")
            else:
                ctx.reply_error("unknown")

        def ping(client, pb):
            client.call(pb.PING, b"")
        """})
    assert len(findings) == 1
    f = findings[0]
    assert f.rule == "R18" and f.line == 2
    assert "LOST_CALL" in f.message and "no dispatcher handles it" in f.message


def test_r18_reply_discipline_and_lifecycle_table(tmp_path):
    findings = run_tree(tmp_path, "R18", {"srv.py": """\
        def handler(env, ctx, pb):
            if env.method == pb.ECHO:
                ctx.reply(b"")

        def send(client, pb):
            client.call(pb.ECHO, b"")

        def promote(node):
            if node.state == "DRAINED":
                node.state = "ALIVE"
        """})
    msgs = sorted(f.message for f in findings)
    assert len(findings) == 2
    assert any("never replies" in m for m in msgs)
    assert any("'DRAINED' -> 'ALIVE'" in m for m in msgs)


# -- R19: distributed deadlock over the stitched graph ------------------------

def test_r19_fires_on_cross_daemon_sync_call_cycle(tmp_path):
    """PING's arm reaches a sync POKE send through a helper in another
    file, and POKE's arm sync-sends PING back: a cross-process wait
    cycle the stitched graph must witness."""
    findings = run_tree(tmp_path, "R19", {
        "hub.py": """\
            from proj import spoke

            def dispatch(env, ctx, client, pb):
                if env.method == pb.PING:
                    spoke.relay(client, pb)
                    ctx.reply(b"")
                elif env.method == pb.POKE:
                    client.call(pb.PING, b"")
                    ctx.reply(b"")
                else:
                    ctx.reply_error("unknown")
        """,
        "spoke.py": """\
            def relay(client, pb):
                client.call(pb.POKE, b"")
        """,
    })
    assert [f.rule for f in findings] == ["R19"]
    assert "CYCLE" in findings[0].message
    assert "rpc:PING" in findings[0].message
    assert "rpc:POKE" in findings[0].message


def test_r19_quiet_when_one_leg_is_fire_and_forget(tmp_path):
    findings = run_tree(tmp_path, "R19", {"hub.py": """\
        def dispatch(env, ctx, client, pb):
            if env.method == pb.PING:
                client.call(pb.POKE, b"")
                ctx.reply(b"")
            elif env.method == pb.POKE:
                client.call_async(pb.PING, b"", None)
                ctx.reply(b"")
            else:
                ctx.reply_error("unknown")
        """})
    assert findings == []


def test_r19_fires_when_lock_held_across_send_and_handler_reacquires(tmp_path):
    findings = run_tree(tmp_path, "R19", {"locked.py": """\
        import threading

        _LOCK = threading.Lock()

        def dispatch(env, ctx, pb):
            if env.method == pb.GRAB:
                with _LOCK:
                    pass
                ctx.reply(b"")
            else:
                ctx.reply_error("unknown")

        def send_locked(client, pb):
            with _LOCK:
                client.call(pb.GRAB, b"")
        """})
    assert [f.rule for f in findings] == ["R19"]
    assert "_LOCK" in findings[0].message
    assert "GRAB" in findings[0].message


def test_r19_quiet_when_lock_released_before_send(tmp_path):
    findings = run_tree(tmp_path, "R19", {"locked.py": """\
        import threading

        _LOCK = threading.Lock()

        def dispatch(env, ctx, pb):
            if env.method == pb.GRAB:
                with _LOCK:
                    pass
                ctx.reply(b"")
            else:
                ctx.reply_error("unknown")

        def send_unlocked(client, pb):
            with _LOCK:
                body = b""
            client.call(pb.GRAB, body)
        """})
    assert findings == []


# -- R20: unbounded blocking reachable from a dispatch arm --------------------

def test_r20_catches_naked_wait_reachable_from_dispatch_arm(tmp_path):
    findings = run_tree(tmp_path, "R20", {"srv.py": """\
        def helper(ev):
            ev.wait()

        def dispatch(env, ctx, ev, pb):
            if env.method == pb.WORK:
                helper(ev)
                ctx.reply(b"")
            else:
                ctx.reply_error("unknown")
        """})
    assert [f.rule for f in findings] == ["R20"]
    f = findings[0]
    assert f.line == 2
    assert "WORK" in f.message and "helper" in f.message


def test_r20_quiet_on_deadline_scope_and_bounded_wait(tmp_path):
    findings = run_tree(tmp_path, "R20", {"srv.py": """\
        def scoped_helper(ev, deadline):
            ev.wait()

        def capped_helper(ev):
            ev.wait(1.0)

        def dispatch(env, ctx, ev, pb):
            if env.method == pb.WORK:
                scoped_helper(ev, 1.0)
                capped_helper(ev)
                ctx.reply(b"")
            else:
                ctx.reply_error("unknown")
        """})
    assert findings == []


# -- R21: jit compile-cache stability -----------------------------------------

def test_r21_fires_on_loop_and_per_call_constructions(tmp_path):
    findings = run_rule(tmp_path, "R21", """\
        import jax

        def hot(xs):
            for x in xs:
                g = jax.jit(lambda v: v)
                x = g(x)
            return xs

        def immediate(x):
            return jax.jit(lambda v: v)(x)
        """)
    assert all(f.rule == "R21" for f in findings) and findings
    msgs = " | ".join(f.message for f in findings)
    assert "inside a loop" in msgs
    assert "built and invoked in one expression" in msgs


def test_r21_fires_on_donated_buffer_use_after_call(tmp_path):
    findings = run_rule(tmp_path, "R21", """\
        import jax

        def _impl(state):
            return state

        _STEP = jax.jit(_impl, donate_argnums=(0,))

        def bad(state):
            out = _STEP(state)
            return out, state

        def good(state):
            state = _STEP(state)
            return state
        """)
    assert [f.rule for f in findings] == ["R21"]
    assert "donated" in findings[0].message
    assert findings[0].line == 10


def test_r21_quiet_on_cached_builder_and_padded_scalar(tmp_path):
    findings = run_rule(tmp_path, "R21", """\
        import functools

        import jax

        def pad_items(items, buckets):
            return items

        @functools.lru_cache(maxsize=8)
        def build(n):
            return jax.jit(lambda v: v)

        _STEP = jax.jit(lambda v, k: v, static_argnums=(1,))

        def run(state, items):
            items = pad_items(items, (8,))
            return _STEP(state, len(items))
        """)
    assert findings == []


def test_r21_ignores_non_jax_callables_named_jit(tmp_path):
    findings = run_rule(tmp_path, "R21", """\
        from mytools import jit

        def hot(xs):
            out = []
            for x in xs:
                f = jit(x)
                out.append(f())
            return out
        """)
    assert findings == []


# -- regression guards for the defects R16/R17 found in the real tree ---------

def _lint_repo(rule_id, *relpaths):
    eng = LintEngine([os.path.join(REPO, p) for p in relpaths],
                     only_rules={rule_id})
    findings = eng.run()
    assert not eng.errors, eng.errors
    return findings


def test_r16_regression_rpc_and_runtime_ctors_stay_leak_free():
    # RpcClient/RpcServer/Runtime/ClientAPI constructor aborts and the
    # recorder fallback used to strand sockets, pools and file handles
    assert _lint_repo("R16",
                      "ray_tpu/_private/rpc.py",
                      "ray_tpu/_private/runtime.py",
                      "ray_tpu/observability/recorder.py",
                      "ray_tpu/util/client/client.py") == []


def test_r17_regression_drain_and_checkpoint_stay_bounded():
    # drain/checkpoint/tune/client paths used to block with no bound
    # under their deadline scopes (engine.save wait, client _call wait)
    assert _lint_repo("R17",
                      "ray_tpu/_private/distributed.py",
                      "ray_tpu/checkpoint/engine.py",
                      "ray_tpu/tune/execution.py",
                      "ray_tpu/util/client/client.py") == []


def test_r19_r20_regression_runtime_rpc_plane_stays_clean():
    # the stitched graph over the real dispatcher (_handle_rpc) must not
    # find wait cycles or arm-reachable naked blocking in the runtime
    for rule in ("R19", "R20"):
        assert _lint_repo(rule,
                          "ray_tpu/_private/rpc.py",
                          "ray_tpu/_private/distributed.py",
                          "ray_tpu/_private/state_client.py",
                          "ray_tpu/_private/host_daemon.py") == []


def test_r21_regression_parallel_shard_builders_stay_cached():
    # moe_apply/pipeline_apply/ring_attention used to rebuild shard_map
    # per call; the lru_cached builders must keep them R21-clean
    assert _lint_repo("R21",
                      "ray_tpu/parallel/expert.py",
                      "ray_tpu/parallel/pipeline.py",
                      "ray_tpu/parallel/sequence.py",
                      "ray_tpu/rl/policy.py",
                      "ray_tpu/rl/ppo.py") == []


def test_rpc_server_ctor_abort_closes_listener(monkeypatch):
    import socket as socket_mod
    from ray_tpu._private import rpc as rpc_mod
    blocker = socket_mod.socket()
    blocker.bind(("127.0.0.1", 0))
    blocker.listen(1)
    port = blocker.getsockname()[1]
    created = []
    real_socket = socket_mod.socket

    def spy(*a, **k):
        s = real_socket(*a, **k)
        created.append(s)
        return s

    monkeypatch.setattr(rpc_mod.socket, "socket", spy)
    with pytest.raises(OSError):
        rpc_mod.RpcServer(lambda *a: None, host="127.0.0.1", port=port)
    assert created, "server never made its listener socket"
    assert all(s.fileno() == -1 for s in created), "listener fd leaked"
    blocker.close()


def test_rpc_client_ctor_abort_closes_socket(monkeypatch):
    import socket as socket_mod
    from ray_tpu._private import rpc as rpc_mod
    blocker = socket_mod.socket()
    blocker.bind(("127.0.0.1", 0))
    blocker.listen(1)
    addr = "127.0.0.1:%d" % blocker.getsockname()[1]
    created = []
    real_cc = socket_mod.create_connection

    def spy(*a, **k):
        s = real_cc(*a, **k)
        created.append(s)
        return s

    class Boom(Exception):
        pass

    def boom(*a, **k):
        raise Boom("post-connect ctor failure")

    monkeypatch.setattr(rpc_mod.socket, "create_connection", spy)
    monkeypatch.setattr(rpc_mod.threading, "Thread", boom)
    with pytest.raises(Boom):
        rpc_mod.RpcClient(addr, connect_timeout=5)
    assert created, "client never connected"
    assert created[0].fileno() == -1, "connected fd leaked on ctor abort"
    blocker.close()


def test_checkpoint_save_and_client_call_take_timeouts():
    import inspect
    from ray_tpu.checkpoint.engine import CheckpointEngine
    from ray_tpu.util.client.client import ClientAPI
    assert "timeout_s" in inspect.signature(CheckpointEngine.save).parameters
    assert "timeout" in inspect.signature(ClientAPI._call).parameters


# -- incremental cache + SARIF ------------------------------------------------

def test_incremental_cache_replays_findings(tmp_path, monkeypatch):
    monkeypatch.setenv("RAYLINT_CACHE", str(tmp_path / "cache.json"))
    root = tmp_path / "proj"
    root.mkdir()
    swallow = "try:\n    pass\nexcept Exception:\n    pass\n"
    (root / "a.py").write_text(swallow)

    eng_cold = LintEngine([str(root)], cache=True)
    cold = eng_cold.run()
    assert len(cold) == 1 and cold[0].rule == "R4"
    assert eng_cold.cache_stats == (0, 1, False)

    eng_warm = LintEngine([str(root)], cache=True)
    warm = eng_warm.run()
    assert eng_warm.cache_stats == (1, 1, True)
    assert warm == cold

    (root / "a.py").write_text("x = 1\n" + swallow)
    eng_dirty = LintEngine([str(root)], cache=True)
    dirty = eng_dirty.run()
    assert eng_dirty.cache_stats == (0, 1, False)
    assert len(dirty) == 1 and dirty[0].line == cold[0].line + 1


def test_cache_bypassed_under_rule_restriction(tmp_path, monkeypatch):
    monkeypatch.setenv("RAYLINT_CACHE", str(tmp_path / "cache.json"))
    root = tmp_path / "proj"
    root.mkdir()
    (root / "a.py").write_text("x = 1\n")
    eng = LintEngine([str(root)], only_rules={"R4"}, cache=True)
    eng.run()
    assert not eng.cache_enabled
    assert eng.cache_stats is None
    assert not (tmp_path / "cache.json").exists()


_STITCH_WIRE = """\
class pb:
    FWD = 1
    BACK = 2


def dispatch(env, ctx, client):
    if env.method == pb.FWD:
        client.call(pb.BACK, b"")
        ctx.reply(b"")
    elif env.method == pb.BACK:
        client.call(pb.FWD, b"")
        ctx.reply(b"")
    else:
        ctx.reply_error("unknown method")
"""


def test_stitch_cache_replays_cross_process_graph(tmp_path, monkeypatch):
    """Per-file stitch facts (send sites + dispatcher arms) are cached by
    content hash: an unrelated edit replays wire.py's facts instead of
    re-deriving them, and the stitched R19 finding survives the replay."""
    monkeypatch.setenv("RAYLINT_CACHE", str(tmp_path / "cache.json"))
    root = tmp_path / "proj"
    root.mkdir()
    (root / "wire.py").write_text(_STITCH_WIRE)
    (root / "other.py").write_text("x = 1\n")

    eng_cold = LintEngine([str(root)], cache=True)
    cold = eng_cold.run()
    assert [f.rule for f in cold] == ["R19"]
    assert eng_cold.stitch_stats == (0, 2)   # all facts derived fresh

    (root / "other.py").write_text("x = 2\n")
    eng_part = LintEngine([str(root)], cache=True)
    part = eng_part.run()
    assert eng_part.cache_stats == (1, 2, False)
    assert eng_part.stitch_stats == (1, 2)   # wire.py replayed, other re-derived
    assert [(f.rule, f.path, f.line) for f in part] == \
        [(f.rule, f.path, f.line) for f in cold]

    # editing the wire file itself invalidates its stitch entry
    (root / "wire.py").write_text("# moved\n" + _STITCH_WIRE)
    eng_dirty = LintEngine([str(root)], cache=True)
    dirty = eng_dirty.run()
    assert [f.rule for f in dirty] == ["R19"]
    assert dirty[0].line == cold[0].line + 1
    assert eng_dirty.stitch_stats == (1, 2)  # other.py replays, wire.py does not


def test_r19_acceptance_flagged_cycle_really_deadlocks_two_daemons(tmp_path):
    """The acceptance shape for R19: (a) the cyclic sync-RPC pattern is
    flagged statically; (b) on a real two-daemon cluster the same shape
    wedges — each single-threaded peer waits synchronously on the other,
    so the entangled call misses a budget the one-way hop meets easily."""
    findings = run_tree(tmp_path, "R19", {"wire.py": _STITCH_WIRE})
    assert [f.rule for f in findings] == ["R19"]

    from ray_tpu._native.build import build_state_service
    try:
        build_state_service()
    except Exception as e:
        pytest.skip(f"state service unavailable: {e}")

    import ray_tpu
    from ray_tpu import chaos
    from ray_tpu.cluster_utils import ProcessCluster

    ray_tpu.shutdown()
    prev = chaos.schedule()
    c = ProcessCluster(num_daemons=2, num_cpus=1)
    ray_tpu.init(address=c.address)
    try:
        @ray_tpu.remote
        class Peer:
            def __init__(self):
                self._peer = None

            def set_peer(self, peer):
                self._peer = peer
                return True

            def echo(self):
                return "ok"

            def relay(self):
                import ray_tpu
                return ray_tpu.get(self._peer.echo.remote(), timeout=30)

            def entangle(self):
                import ray_tpu
                return ray_tpu.get(self._peer.entangle.remote(), timeout=8)

        a, b = Peer.remote(), Peer.remote()
        assert ray_tpu.get([a.set_peer.remote(b), b.set_peer.remote(a)],
                           timeout=60) == [True, True]
        # sanity: one synchronous hop across the wire completes fine
        try:
            assert ray_tpu.get(a.relay.remote(), timeout=60) == "ok"
        except Exception as e:
            pytest.skip(f"nested actor calls unavailable: {e}")
        # chaos delay widens the window so both peers are mid-send when
        # the wait cycle closes, the interleaving R19 warns about
        chaos.configure(5, "rpc.client.send@2%3=delay(0.05)")
        ref = a.entangle.remote()
        with pytest.raises(ray_tpu.GetTimeoutError):
            ray_tpu.get(ref, timeout=4)      # the cycle never completes
    finally:
        chaos.clear()
        if prev is not None:
            chaos.install(prev)
        ray_tpu.shutdown()
        c.shutdown()


def test_sarif_log_covers_all_rules_and_anchors_findings():
    from ray_tpu.devtools.linter import Finding, sarif_log
    log = sarif_log([Finding("R4", "swallow", "pkg/a.py", 3, "msg here")])
    assert log["version"] == "2.1.0"
    run = log["runs"][0]
    rules = run["tool"]["driver"]["rules"]
    assert {r["id"] for r in rules} == {f"R{i}" for i in range(1, 30)}
    for r in rules:
        assert r["fullDescription"]["text"], r["id"]
        assert r["helpUri"].startswith("ARCHITECTURE.md#"), r["id"]
    res = run["results"][0]
    assert res["ruleId"] == "R4"
    assert rules[res["ruleIndex"]]["id"] == "R4"
    loc = res["locations"][0]["physicalLocation"]
    assert loc["artifactLocation"]["uri"] == "pkg/a.py"
    assert loc["region"]["startLine"] == 3


# -- R23-R25: field-level thread-safety ---------------------------------------

_RACE_SRC = """\
    import threading


    class RaceyGauge:
        def __init__(self):
            self.level = 0
            self._t = threading.Thread(target=self._drain, daemon=True)
            self._t.start()

        def _drain(self):
            self.level = 1

        def read_level(self):
            return self.level


    def poll(g: RaceyGauge) -> int:
        return g.read_level()
"""


def test_r23_fires_on_unlocked_cross_thread_field(tmp_path):
    findings = run_rule(tmp_path, "R23", _RACE_SRC)
    assert [f.rule for f in findings] == ["R23"]
    f = findings[0]
    assert f.tag == "data-race"
    assert f.line == 11          # the drain thread's unlocked write
    assert "RaceyGauge.level" in f.message


def test_r23_quiet_on_guarded_flag_and_handoff_shapes(tmp_path):
    findings = run_rule(tmp_path, "R23", """\
        import threading


        class GuardedGauge:
            def __init__(self):
                self._lock = threading.Lock()
                self.level = 0  # raylint: guarded-by(self._lock)
                self._t = threading.Thread(target=self._drain, daemon=True)
                self._t.start()

            def _drain(self):
                with self._lock:
                    self.level = 1

            def read_level(self):
                with self._lock:
                    return self.level


        class FlagStop:
            def __init__(self):
                self._stop = False
                self._t = threading.Thread(target=self._step, daemon=True)
                self._t.start()

            def _step(self):
                if not self._stop:
                    pass

            def stop(self):
                self._stop = True


        class Handoff:
            def __init__(self):
                self.payload = []
                self.payload.append(1)
                self._t = threading.Thread(target=self._consume, daemon=True)
                self._t.start()

            def _consume(self):
                return list(self.payload)


        def poll(g: GuardedGauge, f: FlagStop, h: Handoff) -> int:
            f.stop()
            return g.read_level() + len(h.payload)
    """)
    assert findings == []


def test_r23_lockset_propagates_across_call_edges(tmp_path):
    """A lock acquired by the caller covers the callee's field access:
    both thread contexts reach ``_bump`` only through lock-holding
    callers, so the interprocedural must-hold set suppresses the race."""
    findings = run_rule(tmp_path, "R23", """\
        import threading


        class Counter:
            def __init__(self):
                self._lock = threading.Lock()
                self.total = 0
                self._t = threading.Thread(target=self._feed, daemon=True)
                self._t.start()

            def _feed(self):
                with self._lock:
                    self._bump()

            def _bump(self):
                self.total = self.total + 1

            def add(self):
                with self._lock:
                    self._bump()


        def drive(c: Counter) -> None:
            c.add()
    """)
    assert findings == []


def test_field_plan_derives_thread_contexts(tmp_path):
    """``field_plan`` roots every spawn target and Thread-subclass
    ``run``, and a function called from both main and a spawned root
    carries both contexts."""
    idx = build_index(tmp_path, {"mod.py": """\
        import threading


        class Pump(threading.Thread):
            def run(self):
                shared()


        def worker():
            shared()


        def shared():
            pass


        def main():
            t = threading.Thread(target=worker)
            t.start()
            shared()
    """})
    plan = idx.field_plan()
    assert any(q.endswith("worker") for q in plan.roots)   # spawn target
    assert any(q.endswith(".run") for q in plan.roots)     # Thread subclass
    (shared_q,) = [q for q in idx.functions if q.endswith("shared")]
    names = set(plan.contexts[shared_q])
    assert "main" in names
    assert any(n.endswith("worker") for n in names)
    assert any(n.endswith(".run") for n in names)


def test_r24_fires_on_split_read_modify_write(tmp_path):
    findings = run_rule(tmp_path, "R24", """\
        import threading


        class SplitQuota:
            def __init__(self):
                self._lock = threading.Lock()
                self._used = 0  # raylint: guarded-by(self._lock)
                self._t = threading.Thread(target=self._grow, daemon=True)
                self._t.start()

            def _grow(self):
                with self._lock:
                    self._used += 1

            def bump_stale(self):
                with self._lock:
                    n = self._used
                with self._lock:
                    self._used = n + 1


        def drive(q: SplitQuota) -> None:
            q.bump_stale()
    """)
    assert [f.rule for f in findings] == ["R24"]
    f = findings[0]
    assert f.tag == "atomicity-split"
    assert f.line == 19          # the write-back under the second acquire
    assert "SplitQuota._used" in f.message


def test_r24_quiet_on_single_critical_section(tmp_path):
    findings = run_rule(tmp_path, "R24", """\
        import threading


        class WholeQuota:
            def __init__(self):
                self._lock = threading.Lock()
                self._used = 0  # raylint: guarded-by(self._lock)
                self._t = threading.Thread(target=self._grow, daemon=True)
                self._t.start()

            def _grow(self):
                with self._lock:
                    self._used += 1

            def bump(self):
                with self._lock:
                    n = self._used
                    self._used = n + 1


        def drive(q: WholeQuota) -> None:
            q.bump()
    """)
    assert findings == []


def test_r25_fires_on_unlocked_access_to_declared_field(tmp_path):
    findings = run_rule(tmp_path, "R25", """\
        import threading


        class LeakyBox:
            def __init__(self):
                self._lock = threading.Lock()
                self._items = []  # raylint: guarded-by(self._lock)
                self._t = threading.Thread(target=self._fill, daemon=True)
                self._t.start()

            def _fill(self):
                with self._lock:
                    self._items.append(1)

            def peek(self) -> int:
                return len(self._items)


        def drain(a: LeakyBox) -> int:
            return a.peek()
    """)
    assert [f.rule for f in findings] == ["R25"]
    f = findings[0]
    assert f.tag == "guarded-by"
    assert f.line == 16          # the lock-free peek
    # the static message leads with the exact string the level-2
    # runtime watchdog prints, so the two correlate by grep
    assert f.message.startswith(
        lockwatch.format_guard("LeakyBox._items", "self._lock"))


def test_r25_requires_declaration_for_consistently_locked_field(tmp_path):
    findings = run_rule(tmp_path, "R25", """\
        import threading


        class QuietBox:
            def __init__(self):
                self._lock = threading.Lock()
                self._items = []
                self._t = threading.Thread(target=self._fill, daemon=True)
                self._t.start()

            def _fill(self):
                with self._lock:
                    self._items.append(1)

            def peek(self) -> int:
                with self._lock:
                    return len(self._items)


        def drain(b: QuietBox) -> int:
            return b.peek()
    """)
    assert [f.rule for f in findings] == ["R25"]
    f = findings[0]
    assert "guarded-by(self._lock)" in f.message
    assert "carries no declaration" in f.message


def test_r25_quiet_on_declared_and_locked(tmp_path):
    findings = run_rule(tmp_path, "R25", """\
        import threading


        class SealedBox:
            def __init__(self):
                self._lock = threading.Lock()
                self._items = []  # raylint: guarded-by(self._lock)
                self._t = threading.Thread(target=self._fill, daemon=True)
                self._t.start()

            def _fill(self):
                with self._lock:
                    self._items.append(1)

            def peek(self) -> int:
                with self._lock:
                    return len(self._items)


        def drain(c: SealedBox) -> int:
            return c.peek()
    """)
    assert findings == []


def test_lockwatch_guard_fires_on_unlocked_access():
    lockwatch.reset()

    class LeakyDemo:
        def __init__(self):
            self._lock = threading.Lock()
            self._items = []  # raylint: guarded-by(self._lock)

        def unlocked_peek(self):
            return len(self._items)

    try:
        guarded = lockwatch.guard_class(LeakyDemo)
        assert guarded is LeakyDemo
        box = LeakyDemo()
        box.unlocked_peek()
        violations = lockwatch.guard_violations()
        assert len(violations) == 1
        v = violations[0]
        assert v["field"] == "LeakyDemo._items"
        assert v["lock"] == "LeakyDemo._lock"
        assert "guarded-by" in lockwatch.format_guard(v["field"], v["lock"])
    finally:
        lockwatch.reset()


def test_lockwatch_guard_silent_when_lock_held_or_in_init():
    lockwatch.reset()

    class SealedDemo:
        def __init__(self):
            self._lock = threading.Lock()
            self._items = []  # raylint: guarded-by(self._lock)
            self._items.append(0)      # construction write: unarmed

        def locked_peek(self):
            with self._lock:
                return len(self._items)

    try:
        lockwatch.guard_class(SealedDemo)
        box = SealedDemo()
        assert box.locked_peek() == 1
        assert lockwatch.guard_violations() == []
    finally:
        lockwatch.reset()


def test_lockwatch_guard_is_noop_below_level_2(monkeypatch):
    monkeypatch.delenv("RAY_TPU_LOCKWATCH", raising=False)

    class Plain:
        def __init__(self):
            self._lock = threading.Lock()
            self._n = 0  # raylint: guarded-by(self._lock)

    orig_init = Plain.__init__
    assert lockwatch.guard(Plain) is Plain
    assert Plain.__init__ is orig_init
    assert not isinstance(Plain.__dict__.get("_n"), object.__class__)


_CLEAN_FIELD_SRC = """\
import threading


class SealedBox:
    def __init__(self):
        self._lock = threading.Lock()
        self._items = []  # raylint: guarded-by(self._lock)
        self._t = threading.Thread(target=self._fill, daemon=True)
        self._t.start()

    def _fill(self):
        with self._lock:
            self._items.append(1)

    def peek(self) -> int:
        with self._lock:
            return len(self._items)


def drain(c: SealedBox) -> int:
    return c.peek()
"""


def test_field_fact_cache_invalidates_only_the_edited_file(tmp_path,
                                                           monkeypatch):
    """Per-file field facts are cached by content hash: after editing one
    of N files, the warm run replays N-1 fact sets and re-derives only
    the edited file's."""
    monkeypatch.setenv("RAYLINT_CACHE", str(tmp_path / "cache.json"))
    root = tmp_path / "proj"
    root.mkdir()
    names = ("a.py", "b.py", "c.py")
    for name in names:
        (root / name).write_text(_CLEAN_FIELD_SRC)

    eng_cold = LintEngine([str(root)], cache=True)
    assert eng_cold.run() == []
    assert not eng_cold.errors, eng_cold.errors
    assert eng_cold.field_stats == (0, len(names))

    (root / "c.py").write_text("# nudged\n" + _CLEAN_FIELD_SRC)
    eng_warm = LintEngine([str(root)], cache=True)
    assert eng_warm.run() == []
    assert eng_warm.field_stats == (len(names) - 1, len(names))


def test_runtime_modules_stay_field_clean():
    """Regression guard for the races fixed alongside R23-R25: the
    repaired runtime modules must lint clean under the field rules
    without allow comments being added back as suppressions."""
    targets = [os.path.join(REPO, rel) for rel in (
        "ray_tpu/_private/rpc.py",
        "ray_tpu/_private/state_server.py",
        "ray_tpu/_private/memory_monitor.py",
        "ray_tpu/_private/reference_counter.py",
        "ray_tpu/util/client/client.py",
    )]
    eng = LintEngine(targets, only_rules={"R23", "R24", "R25"})
    findings = eng.run()
    assert not eng.errors, eng.errors
    assert [f.format() for f in findings] == []


# -- R27-R29: static SPMD sharding & the comms manifest -----------------------

def test_r27_fires_on_unknown_axis_dup_and_arity(tmp_path):
    findings = run_rule(tmp_path, "R27", """\
        import jax
        from jax.sharding import PartitionSpec as P

        from ray_tpu._private.jax_compat import shard_map

        AXIS_ORDER = ("data", "tensor")

        BAD = P("data", "rows")
        DUP = P("data", "data")

        def _two(a, b):
            return jax.lax.psum(a, "data")

        def build(mesh):
            bad = shard_map(_two, mesh=mesh, in_specs=(P("data"),),
                            out_specs=P("data"), check_vma=False)
            return (bad,)
    """)
    assert [f.rule for f in findings] == ["R27"] * 3
    msgs = " | ".join(f.message for f in findings)
    assert "'rows'" in msgs                 # unknown mesh axis
    assert "two dimensions" in msgs         # duplicate binding
    assert "in_specs carries 1" in msgs     # arity vs _two's 2 params


def test_r27_quiet_on_open_mesh_universe_and_clean_specs(tmp_path):
    # No AXIS_ORDER/Mesh reachable: membership is undecidable, so the
    # unknown-axis check must under-approximate to silence.
    assert run_rule(tmp_path, "R27", """\
        from jax.sharding import PartitionSpec as P
        SPEC = P("data", "rows")
    """) == []
    assert run_rule(tmp_path, "R27", """\
        from jax.sharding import PartitionSpec as P
        AXIS_ORDER = ("data", "tensor")
        SPEC = P(("data", "tensor"), None)
    """) == []


def test_r27_fires_on_unknown_logical_axis(tmp_path):
    findings = run_rule(tmp_path, "R27", """\
        RULES = {"batch": "data", "mlp": "tensor"}

        def make(rules):
            return rules.spec(("batch", "typo"))
    """)
    assert [f.rule for f in findings] == ["R27"]
    assert "'typo'" in findings[0].message


def test_r28_fires_on_producer_consumer_mismatch(tmp_path):
    src = """\
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        from ray_tpu._private.jax_compat import shard_map

        def _one(x):
            return x

        _STEP = shard_map(_one, mesh=None, in_specs=(P("data"),),
                          out_specs=P("data"), check_vma=False)

        def feed(x, mesh):
            x = jax.device_put(x, NamedSharding(mesh, P(%s)))
            return _STEP(x)
    """
    bad = run_rule(tmp_path, "R28", src % "None")
    assert [f.rule for f in bad] == ["R28"]
    assert "resharding" in bad[0].message
    assert run_rule(tmp_path, "R28", src % '"data"') == []


def test_r28_fires_on_wasted_donation(tmp_path):
    src = """\
        import functools

        import jax
        from jax.sharding import PartitionSpec as P

        @functools.partial(jax.jit, donate_argnums=(0,),
                           in_shardings=(P("data"),),
                           out_shardings=P(%s))
        def step(state):
            return state
    """
    bad = run_rule(tmp_path, "R28", src % "None")
    assert [f.rule for f in bad] == ["R28"]
    assert "donated argument 0" in bad[0].message
    assert run_rule(tmp_path, "R28", src % '"data"') == []


def test_r29_fires_on_ghost_axis_quiet_on_dynamic(tmp_path):
    findings = run_rule(tmp_path, "R29", """\
        import jax

        AXIS_ORDER = ("data",)

        def _leak(x):
            return jax.lax.psum(x, "ghost")

        def _dyn(x, axis):
            return jax.lax.psum(x, axis)  # axis unknown -> no finding
    """)
    assert [f.rule for f in findings] == ["R29"]
    assert "'ghost'" in findings[0].message


def test_manifest_build_and_wire_parity_with_ledger(tmp_path):
    from ray_tpu.devtools import shardprop
    from ray_tpu.devtools.linter import FileContext
    from ray_tpu.observability import comms

    src = textwrap.dedent("""\
        import jax

        from ray_tpu import collective

        AXIS_ORDER = ("data",)

        def ring(x):
            return jax.lax.psum(x, "data")

        def sync(t):
            return collective.allreduce(t, group_name="g")
    """)
    p = tmp_path / "m.py"
    p.write_text(src)
    model = shardprop.ShardModel([FileContext(str(p), "m.py", src)])
    man = shardprop.build_manifest(model)
    assert man["mesh_axes"] == ["data"]
    assert "psum" in man["groups"]["axis:data"]
    assert "allreduce" in man["groups"]["g"]
    # Static wire factors must agree numerically with the runtime
    # ledger's busbw table, or doctor's predicted bytes would drift
    # from what the ledger reports for the very same op.
    for op, fac in comms._BUSBW.items():
        if op in shardprop.WIRE_FORMULAS:
            for n in (2, 4, 8, 32):
                assert shardprop.wire_factor(op, n) == pytest.approx(fac(n))


_SPMD_CLEAN_SRC = """\
from jax.sharding import PartitionSpec as P

AXIS_ORDER = ("data",)
SPEC = P("data")
"""


def test_shard_fact_cache_invalidates_only_the_edited_file(tmp_path,
                                                           monkeypatch):
    """Per-file shard facts are cached by content hash exactly like
    stitch/field facts: after editing one of N files, the warm run
    replays N-1 fact sets and re-derives only the edited file's."""
    monkeypatch.setenv("RAYLINT_CACHE", str(tmp_path / "cache.json"))
    root = tmp_path / "proj"
    root.mkdir()
    names = ("a.py", "b.py", "c.py")
    for name in names:
        (root / name).write_text(_SPMD_CLEAN_SRC)

    eng_cold = LintEngine([str(root)], cache=True)
    assert eng_cold.run() == []
    assert not eng_cold.errors, eng_cold.errors
    assert eng_cold.shard_stats == (0, len(names))

    (root / "c.py").write_text("# nudged\n" + _SPMD_CLEAN_SRC)
    eng_warm = LintEngine([str(root)], cache=True)
    assert eng_warm.run() == []
    assert eng_warm.shard_stats == (len(names) - 1, len(names))


def test_spmd_modules_stay_shard_clean():
    """Regression guard for the sharding fixes that landed with R27-R29:
    the parallel/train/models/rl trees must lint clean under the SPMD
    rules without allow comments."""
    targets = [os.path.join(REPO, rel) for rel in (
        "ray_tpu/parallel",
        "ray_tpu/train",
        "ray_tpu/models",
        "ray_tpu/rl",
    )]
    eng = LintEngine(targets, only_rules={"R27", "R28", "R29"})
    findings = eng.run()
    assert not eng.errors, eng.errors
    assert [f.format() for f in findings] == []
