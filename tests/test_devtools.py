"""Tests for ray_tpu.devtools: the raylint engine (R1-R6) and lockwatch.

Each rule gets one fixture that must fire and one that must stay quiet;
lockwatch gets a real two-thread A->B / B->A inversion; R6 gets a drift
test that mutates a wire field number in a copy of raytpu.proto.
"""

import os
import re
import textwrap
import threading

import pytest

from ray_tpu.devtools import lockwatch
from ray_tpu.devtools.linter import (LintEngine, parse_proto_text)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PROTO = os.path.join(REPO, "ray_tpu", "protocol", "raytpu.proto")
PB2 = os.path.join(REPO, "ray_tpu", "protocol", "raytpu_pb2.py")


def run_rule(tmp_path, rule_id, source, name="fixture.py"):
    path = tmp_path / name
    path.write_text(textwrap.dedent(source))
    eng = LintEngine([str(path)], only_rules={rule_id})
    findings = eng.run()
    assert not eng.errors, eng.errors
    return findings


# -- R1: blocking calls in async def ----------------------------------------

def test_r1_fires_on_blocking_sleep_in_async(tmp_path):
    findings = run_rule(tmp_path, "R1", """\
        import time

        async def handler():
            time.sleep(0.5)
    """)
    assert [f.rule for f in findings] == ["R1"]
    assert "time.sleep" in findings[0].message


def test_r1_quiet_on_awaited_sleep_and_sync_code(tmp_path):
    findings = run_rule(tmp_path, "R1", """\
        import asyncio
        import time

        async def handler():
            await asyncio.sleep(0.5)

        def plain():
            time.sleep(0.5)  # fine: not on the event loop

        async def bounded(fut, lock):
            fut.result(timeout=1.0)
            lock.acquire(timeout=1.0)
    """)
    assert findings == []


# -- R2: inconsistent lock-acquisition order ---------------------------------

def test_r2_fires_on_inverted_nested_with(tmp_path):
    findings = run_rule(tmp_path, "R2", """\
        import threading

        lock_a = threading.Lock()
        lock_b = threading.Lock()

        def forward():
            with lock_a:
                with lock_b:
                    pass

        def backward():
            with lock_b:
                with lock_a:
                    pass
    """)
    assert findings and all(f.rule == "R2" for f in findings)


def test_r2_quiet_on_consistent_order(tmp_path):
    findings = run_rule(tmp_path, "R2", """\
        import threading

        lock_a = threading.Lock()
        lock_b = threading.Lock()

        def forward():
            with lock_a:
                with lock_b:
                    pass

        def also_forward():
            with lock_a:
                with lock_b:
                    pass
    """)
    assert findings == []


# -- R3: unguarded cross-thread shared state ---------------------------------

def test_r3_fires_on_two_sided_unguarded_write(tmp_path):
    findings = run_rule(tmp_path, "R3", """\
        import threading

        class Worker:
            def start(self):
                self._t = threading.Thread(target=self._run)
                self._t.start()

            def _run(self):
                self._status = "running"

            def cancel(self):
                self._status = "cancelled"
    """)
    assert findings and all(f.rule == "R3" for f in findings)
    assert any("_status" in f.message for f in findings)


def test_r3_quiet_when_both_writers_hold_the_lock(tmp_path):
    findings = run_rule(tmp_path, "R3", """\
        import threading

        class Worker:
            def __init__(self):
                self._lock = threading.Lock()
                self._status = "new"

            def start(self):
                self._t = threading.Thread(target=self._run)
                self._t.start()

            def _run(self):
                with self._lock:
                    self._status = "running"

            def cancel(self):
                with self._lock:
                    self._status = "cancelled"
    """)
    assert findings == []


# -- R4: silent exception swallows -------------------------------------------

def test_r4_fires_on_silent_pass(tmp_path):
    findings = run_rule(tmp_path, "R4", """\
        def fragile():
            try:
                risky()
            except Exception:
                pass
    """)
    assert [f.rule for f in findings] == ["R4"]


def test_r4_quiet_on_logged_justified_or_narrow(tmp_path):
    findings = run_rule(tmp_path, "R4", """\
        import logging

        logger = logging.getLogger("ray_tpu")

        def logged():
            try:
                risky()
            except Exception as e:
                logger.warning("risky failed: %s", e)

        def justified():
            try:
                risky()
            except Exception:  # raylint: allow(swallow) fixture says why
                pass

        def narrow():
            try:
                risky()
            except KeyError:
                pass
    """)
    assert findings == []


# -- R5: host-device syncs reachable from jitted code -------------------------

def test_r5_fires_on_float_in_jitted_fn(tmp_path):
    findings = run_rule(tmp_path, "R5", """\
        import jax

        def helper(x):
            return float(x)

        @jax.jit
        def step(x):
            return helper(x) + x.item()
    """)
    assert findings and all(f.rule == "R5" for f in findings)
    lines = sorted(f.line for f in findings)
    assert len(lines) == 2  # float() in helper AND .item() in step


def test_r5_quiet_without_jitted_root(tmp_path):
    findings = run_rule(tmp_path, "R5", """\
        def metrics(x):
            return float(x)  # host-side code may sync freely
    """)
    assert findings == []


# -- R6: proto <-> pb2 wire-schema drift --------------------------------------

def test_r6_quiet_on_committed_pair(tmp_path):
    eng = LintEngine([], only_rules={"R6"},
                     proto_pairs=[(PROTO, PB2, "protocol/raytpu_pb2.py")])
    assert eng.run() == []


def test_r6_fires_when_field_number_mutated(tmp_path):
    src = open(PROTO, encoding="utf-8").read()
    # bump the first scalar field number in the file to a fresh value
    mutated, n = re.subn(r"(=\s*)(\d+)(\s*;)", r"\g<1>9999\g<3>", src, count=1)
    assert n == 1
    bad = tmp_path / "raytpu.proto"
    bad.write_text(mutated)
    eng = LintEngine([], only_rules={"R6"},
                     proto_pairs=[(str(bad), PB2, "protocol/raytpu_pb2.py")])
    findings = eng.run()
    assert findings and all(f.rule == "R6" for f in findings)
    assert any("9999" in f.message or "drifted" in f.message
               for f in findings)


# -- R7: hand-rolled retry loops ---------------------------------------------

def test_r7_fires_on_constant_sleep_retry_loop(tmp_path):
    findings = run_rule(tmp_path, "R7", """\
        import time

        def fetch(fn):
            while True:
                try:
                    return fn()
                except ConnectionError:
                    time.sleep(0.5)
        """)
    assert len(findings) == 1
    assert findings[0].tag == "bare-retry"
    assert "BackoffPolicy" in findings[0].message


def test_r7_fires_on_hardcoded_delay_ladder(tmp_path):
    findings = run_rule(tmp_path, "R7", """\
        from time import sleep

        def fetch(fn):
            for delay in (0.1, 0.5, 2.0):
                try:
                    return fn()
                except OSError:
                    sleep(delay)
        """)
    assert len(findings) == 1


def test_r7_quiet_on_poll_policy_and_allow(tmp_path):
    findings = run_rule(tmp_path, "R7", """\
        import time

        def plain_poll():
            while True:
                time.sleep(0.01)  # no except handler in the loop

        def policy_paced(fn, policy):
            state = policy.start()
            while True:
                try:
                    return fn()
                except ConnectionError:
                    if not state.sleep():
                        raise

        def justified(fn):
            while True:
                try:
                    return fn()
                except ConnectionError:
                    time.sleep(1)  # raylint: allow(bare-retry) spec-fixed cadence

        def variable_delay(fn, policy):
            attempt = 0
            while True:
                try:
                    return fn()
                except ConnectionError:
                    time.sleep(policy.delay_for(attempt))
                    attempt += 1
        """)
    assert findings == []


# -- R9: direct checkpoint directory I/O in train/tune/serve -----------------

def run_rule_in_tree(tmp_path, rule_id, relpath, source):
    """Lint a file placed at ``relpath`` under a package dir, so rules that
    scope on path segments (R9) see a real relative path, not a bare name."""
    path = tmp_path / "pkg" / relpath
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(textwrap.dedent(source))
    eng = LintEngine([str(tmp_path / "pkg")], only_rules={rule_id})
    findings = eng.run()
    assert not eng.errors, eng.errors
    return findings


def test_r9_fires_on_directory_io_in_train(tmp_path):
    findings = run_rule_in_tree(tmp_path, "R9", "train/trainer.py", """\
        def persist(checkpoint, path):
            checkpoint.to_directory(path)

        def resume(cls, path):
            return cls.from_directory(path)
    """)
    assert [f.rule for f in findings] == ["R9", "R9"]
    assert "to_directory" in findings[0].message
    assert "manifest" in findings[0].message


def test_r9_quiet_outside_scope_and_on_allow(tmp_path):
    # air/ is the conversion layer — out of scope by path.
    findings = run_rule_in_tree(tmp_path, "R9", "air/checkpoint.py", """\
        def persist(checkpoint, path):
            checkpoint.to_directory(path)
    """)
    assert findings == []
    # In scope, but justified with an allow comment.
    findings = run_rule_in_tree(tmp_path, "R9", "tune/export.py", """\
        def export(checkpoint, path):
            checkpoint.to_directory(path)  # raylint: allow(direct-checkpoint-io) user-facing blob export
    """)
    assert findings == []


def test_proto_parser_sees_real_schema():
    schema = parse_proto_text(open(PROTO, encoding="utf-8").read())
    assert "TaskSpecMsg" in schema
    assert any(schema.values())


# -- lockwatch ----------------------------------------------------------------

def test_lockwatch_detects_ab_ba_cycle_across_threads():
    lockwatch.reset()
    a = lockwatch.wrap(name="fixture:lock_a")
    b = lockwatch.wrap(name="fixture:lock_b")
    first_done = threading.Event()

    def t1():
        with a:
            with b:
                pass
        first_done.set()

    def t2():
        first_done.wait(timeout=10)
        with b:
            with a:
                pass

    threads = [threading.Thread(target=t1), threading.Thread(target=t2)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=10)
    try:
        cys = lockwatch.cycles()
        assert any(c["kind"] == "site-order" and
                   set(c["sites"]) == {"fixture:lock_a", "fixture:lock_b"}
                   for c in cys), cys
        rep = lockwatch.report()
        assert rep["cycles"]
    finally:
        lockwatch.reset()


def test_lockwatch_quiet_on_consistent_order():
    lockwatch.reset()
    a = lockwatch.wrap(name="fixture:ordered_a")
    b = lockwatch.wrap(name="fixture:ordered_b")

    def use():
        with a:
            with b:
                pass

    threads = [threading.Thread(target=use) for _ in range(2)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=10)
    try:
        assert lockwatch.cycles() == []
    finally:
        lockwatch.reset()


def test_lockwatch_reports_long_hold(monkeypatch):
    monkeypatch.setenv("RAY_TPU_LOCKWATCH_HOLD_S", "0.01")
    lockwatch.reset()
    lk = lockwatch.wrap(name="fixture:slow_lock")
    import time as _time
    with lk:
        _time.sleep(0.05)
    try:
        holds = lockwatch.report()["long_holds"]
        assert any(h["site"] == "fixture:slow_lock" for h in holds), holds
    finally:
        lockwatch.reset()


def test_cli_exits_zero_on_clean_tree(tmp_path):
    clean = tmp_path / "clean.py"
    clean.write_text("def ok():\n    return 1\n")
    from ray_tpu.devtools.linter import main
    assert main([str(clean)]) == 0
    bad = tmp_path / "bad.py"
    bad.write_text("try:\n    pass\nexcept Exception:\n    pass\n")
    assert main([str(bad), "--json"]) == 1
