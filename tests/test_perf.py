"""Continuous performance plane: histograms, sampler, federation, SLO gate.

Covers the streaming latency histograms (bucket math, lock-free shard
merge, Prometheus export, cross-process federation), the periodic stack
sampler (folded-stack aggregation, trace tagging, windowed diffs), the
``ray-tpu top`` straggler view, and the drift-detection gates
(``bench_micro --check`` and the doctor's ``--perf-baseline``).
"""

import json
import math
import threading
import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu import observability
from ray_tpu.observability import perf, sampler


@pytest.fixture(autouse=True)
def _perf_state():
    was = perf.ENABLED
    perf.enable()
    perf.reset()
    yield
    sampler.stop()
    perf.reset()
    if not was:
        perf.disable()


def _require_state_service():
    """ProcessCluster needs the C++ state service (protoc + g++)."""
    from ray_tpu._native.build import build_state_service
    try:
        build_state_service()
    except Exception as e:
        pytest.skip(f"state service unavailable: {e}")


# -- histogram core ---------------------------------------------------------

def test_bucket_bounds_layout():
    b = perf.bucket_bounds()
    assert len(b) == 64  # perf_hist_buckets default
    assert b[0] == pytest.approx(1e-3)
    assert b[-1] == math.inf
    assert b[-2] == pytest.approx(60_000.0)
    assert all(x < y for x, y in zip(b, b[1:]))
    # geometric: constant ratio between consecutive finite bounds
    ratios = [b[i + 1] / b[i] for i in range(len(b) - 3)]
    assert max(ratios) / min(ratios) == pytest.approx(1.0, rel=1e-9)


def test_bucket_boundary_exactness():
    """A value exactly on a bucket boundary lands in THAT bucket
    (Prometheus ``le`` is inclusive), never the next one up."""
    b = perf.bucket_bounds()
    h = perf.get("t.boundary")
    for i in (0, 3, 17, len(b) - 2):
        h.observe(b[i])
    counts, _ = h.merged()
    for i in (0, 3, 17, len(b) - 2):
        assert counts[i] == 1, f"bound {i} leaked into another bucket"
    assert sum(counts) == 4
    # below-domain and absurd values clamp to the edge buckets
    h2 = perf.get("t.edges")
    h2.observe(0.0)
    h2.observe(1e12)
    counts2, _ = h2.merged()
    assert counts2[0] == 1 and counts2[-1] == 1


def test_cross_thread_shard_merge():
    h = perf.get("t.threads")
    n_threads, per_thread = 8, 500

    def work():
        for _ in range(per_thread):
            h.observe(1.0)

    threads = [threading.Thread(target=work) for _ in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    counts, sum_ms = h.merged()
    assert sum(counts) == n_threads * per_thread
    assert sum_ms == pytest.approx(n_threads * per_thread * 1.0)
    # one single-writer shard per observing thread
    assert len(h._shards) == n_threads


def test_quantile_within_bucket_error_vs_numpy():
    """Histogram quantiles vs exact numpy percentiles on a lognormal
    latency distribution: the geometric-midpoint estimate must stay
    within the bucket error bound (one bucket of slack for rank
    discretization)."""
    rng = np.random.RandomState(7)
    vals = rng.lognormal(mean=1.0, sigma=0.6, size=5000)  # ~ms scale
    h = perf.get("t.quantile")
    for v in vals:
        h.observe(float(v))
    counts, _ = h.merged()
    bound = 2.0 * (math.sqrt(perf.bucket_ratio()) - 1.0) + 0.02
    for q in (0.50, 0.95, 0.99):
        est = perf.quantile(counts, q)
        ref = float(np.percentile(vals, q * 100))
        assert abs(est - ref) / ref <= bound, \
            f"q={q}: est {est} vs numpy {ref} beyond {bound:.2%}"


def test_summarize_and_merge_counts():
    h = perf.get("t.summarize")
    for _ in range(100):
        h.observe(10.0)
    counts, sum_ms = h.merged()
    s = perf.summarize(counts, sum_ms)
    assert s["count"] == 100
    assert s["mean_ms"] == pytest.approx(10.0)
    for key in ("p50_ms", "p95_ms", "p99_ms"):
        assert abs(s[key] - 10.0) / 10.0 <= \
            math.sqrt(perf.bucket_ratio()) - 1.0
    # federation merge is an exact element-wise sum
    merged = perf.merge_counts([counts, counts, counts])
    assert sum(merged) == 300
    assert perf.summarize(merged, 3 * sum_ms)["p50_ms"] == s["p50_ms"]


def test_enabled_fast_path():
    perf.disable()
    perf.observe("t.off", 5.0)
    assert "t.off" not in perf.snapshot()["hists"]
    perf.enable()
    perf.observe("t.on", 5.0)
    assert perf.snapshot()["hists"]["t.on"]["counts"]


def test_families_export_and_extract_roundtrip():
    perf.observe("t.export", 2.5)
    perf.observe("t.export", 250.0)
    fams = [f for f in perf.families()
            if f["name"] == "raytpu_perf_t_export_ms"]
    assert len(fams) == 1
    fam = fams[0]
    assert fam["type"] == "histogram"
    buckets = [(dict(tags)["le"], v) for name, tags, v in fam["samples"]
               if name.endswith("_bucket")]
    assert buckets[-1][0] == "+Inf" and buckets[-1][1] == 2.0
    cumulative = [v for _le, v in buckets]
    assert cumulative == sorted(cumulative)  # cumulative by construction
    assert any(name.endswith("_count") and v == 2.0
               for name, _t, v in fam["samples"])
    # the raw payload survives a JSON federation hop untouched
    wire = json.loads(json.dumps([fam]))
    got = perf.extract_perf(wire)
    assert sum(got["t.export"]["counts"]) == 2
    assert got["t.export"]["sum_ms"] == pytest.approx(252.5)


def test_metrics_snapshot_carries_perf_families():
    from ray_tpu.util import metrics
    perf.observe("t.metrics_bridge", 1.0)
    snap = metrics.snapshot()
    assert any(f.get("name") == "raytpu_perf_t_metrics_bridge_ms"
               for f in snap)
    text = metrics.generate_prometheus_text()
    assert "raytpu_perf_t_metrics_bridge_ms_bucket" in text


# -- stack sampler ----------------------------------------------------------

def _spin(stop_s):
    x = 0
    while time.monotonic() < stop_s:
        x += 1
    return x


def test_sampler_folds_stacks():
    s = sampler.start(hz=200.0)
    try:
        t = threading.Thread(target=_spin,
                             args=(time.monotonic() + 0.6,),
                             name="spinner", daemon=True)
        t.start()
        t.join()
    finally:
        sampler.stop()
    prof = s.snapshot()
    assert prof["ticks"] > 0
    assert prof["samples"], "no stacks collected"
    spin_rows = [r for r in prof["samples"]
                 if "test_perf.py:_spin" in r["stack"]]
    assert spin_rows, "busy thread never sampled"
    # root-first folding: the thread bootstrap precedes the target frame
    assert all(r["stack"].index("threading.py") <
               r["stack"].index("test_perf.py:_spin")
               for r in spin_rows)
    text = sampler.collapsed(prof)
    assert any(line.rsplit(" ", 1)[1].isdigit()
               for line in text.splitlines())


def test_sampler_trace_tagging():
    """Samples landing while a thread is inside an observability span are
    attributed to that span's trace id."""
    obs_was = observability.ENABLED
    observability.enable()
    s = sampler.start(hz=200.0)
    try:
        with observability.span("perf.tagged") as sp:
            trace_id = sp.trace_id
            _spin(time.monotonic() + 0.6)
    finally:
        sampler.stop()
        if not obs_was:
            observability.disable()
    tagged = [r for r in s.snapshot()["samples"]
              if r["trace"] == trace_id]
    assert tagged, "no sample attributed to the active span"
    assert sampler._trace_stacks == {}  # balanced enter/exit


def test_diff_and_merge_profiles():
    older = {"hz": 10.0, "ticks": 5, "duration_s": 0.5,
             "samples": [{"stack": "a;b", "trace": "", "count": 3},
                         {"stack": "a;c", "trace": "t1", "count": 2}]}
    newer = {"hz": 10.0, "ticks": 9, "duration_s": 0.9,
             "samples": [{"stack": "a;b", "trace": "", "count": 7},
                         {"stack": "a;c", "trace": "t1", "count": 2},
                         {"stack": "d", "trace": "", "count": 1}]}
    win = sampler.diff_profiles(newer, older)
    assert win["ticks"] == 4
    by_key = {(r["stack"], r["trace"]): r["count"]
              for r in win["samples"]}
    assert by_key == {("a;b", ""): 4, ("d", ""): 1}  # unchanged key drops
    merged = sampler.merge_profiles([older, newer])
    assert merged["ticks"] == 14
    total = {(r["stack"], r["trace"]): r["count"]
             for r in merged["samples"]}
    assert total[("a;b", "")] == 10 and total[("a;c", "t1")] == 4
    pp = sampler.pprof_json(win)
    assert pp["sample_type"] == [{"type": "samples", "unit": "count"}]
    assert pp["period"] == pytest.approx(0.1)
    assert {"location": ["a", "b"], "value": [4]} in pp["samples"]


# -- drift detection --------------------------------------------------------

def test_bench_check_drift_pos_neg(tmp_path, monkeypatch):
    import bench_micro
    baseline = tmp_path / "base.json"
    baseline.write_text(json.dumps([
        {"metric": "inproc_task_execute_p99_us", "value": 100.0,
         "unit": "us"},
        {"metric": "inproc_perf_overhead_pct", "value": 15.0, "unit": "%"},
    ]))
    monkeypatch.setattr(bench_micro, "RESULTS", [
        {"metric": "inproc_task_execute_p99_us", "value": 100.0,
         "unit": "us"},
        {"metric": "inproc_perf_overhead_pct", "value": 5.0, "unit": "%"},
    ])
    assert bench_micro.check_against(str(baseline), 0.7) == 0
    monkeypatch.setattr(bench_micro, "RESULTS", [
        {"metric": "inproc_task_execute_p99_us", "value": 500.0,
         "unit": "us"},
    ])
    assert bench_micro.check_against(str(baseline), 0.7) == 1


def test_doctor_perf_section_and_baseline_drift():
    from ray_tpu import doctor
    for _ in range(50):
        perf.observe("task.execute", 10.0)
    collected = {"ts": time.time(), "errors": [],
                 "cluster": {"metrics": {"snapshots": {
                     "head": perf.families()}}}}
    loose = doctor._perf_reports(
        collected, baseline={"task.execute": {"p99_ms": 100.0}})
    assert loose["cluster"]["task.execute"]["count"] == 50
    assert loose["drift"] == []
    tight = doctor._perf_reports(
        collected, baseline={"task.execute": {"p99_ms": 1.0,
                                              "tolerance": 1.5}})
    assert [d["hist"] for d in tight["drift"]] == ["task.execute"]
    report = doctor.diagnose(
        collected, perf_baseline={"task.execute": {"p99_ms": 1.0}})
    assert not report["healthy"]
    assert report["perf"]["drift"]
    rendered = doctor.render_text(report)
    assert "PERF DRIFT" in rendered and "task.execute" in rendered


def test_top_straggler_rule():
    from ray_tpu.scripts.cli import _top_rows
    summ = {"count": 10.0, "mean_ms": 1.0, "p50_ms": 1.0,
            "p95_ms": 1.0, "p99_ms": 1.0}
    slow = dict(summ, p95_ms=50.0, p99_ms=60.0)
    payload = {"nodes": {"node:aa": {"task.execute": summ},
                         "node:bb": {"task.execute": summ},
                         "node:cc": {"task.execute": slow}}}
    flags = {(n, h): f for n, h, _s, f in _top_rows(payload)}
    assert flags[("node:cc", "task.execute")]
    assert not flags[("node:aa", "task.execute")]
    # two samples on the slow node is below the >=3 sample guard
    payload["nodes"]["node:cc"]["task.execute"] = dict(slow, count=2.0)
    flags = {(n, h): f for n, h, _s, f in _top_rows(payload)}
    assert not flags[("node:cc", "task.execute")]


# -- in-process hot-path wiring --------------------------------------------

def test_task_path_records_histograms():
    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=2)
    try:
        @ray_tpu.remote
        def tiny():
            return 1

        assert ray_tpu.get([tiny.remote() for _ in range(20)]) == [1] * 20
        snap = perf.snapshot()["hists"]
        assert sum(snap["task.execute"]["counts"]) >= 20
        assert sum(snap["task.e2e"]["counts"]) >= 1
    finally:
        ray_tpu.shutdown()


# -- federation across real daemons (self-skip without the state service) ---

def test_cluster_top_json_straggler_and_profile():
    """Acceptance drill: a multi-daemon cluster with a chaos-injected
    50ms task delay on ONE node.  ``ray-tpu top --json`` must report
    per-node p50/p95/p99 with counts matching the workload, the slowed
    node must show a shifted p99 and carry the straggler flag, and
    ``/api/profile`` must federate sampler profiles from the daemons."""
    from ray_tpu.cluster_utils import ProcessCluster
    from ray_tpu.dashboard.head import DashboardHead
    from ray_tpu.scripts import cli
    _require_state_service()
    ray_tpu.shutdown()
    c = ProcessCluster(num_daemons=0, num_cpus=2)
    per_node = 8
    try:
        c.add_daemon(num_cpus=2, resources={"n0": float(per_node)})
        c.add_daemon(num_cpus=2, resources={"n1": float(per_node)})
        c.add_daemon(num_cpus=2, resources={"n2": float(per_node)},
                     env={"RAY_TPU_CHAOS":
                          "1:task.execute@1+=delay(0.05)"})
        ray_tpu.init(address=c.address)

        refs = []
        for res in ("n0", "n1", "n2"):
            @ray_tpu.remote(resources={res: 1})
            def pinned():
                return 1

            refs += [pinned.remote() for _ in range(per_node)]
        assert ray_tpu.get(refs, timeout=120) == [1] * (3 * per_node)

        out = []
        real_print = print

        def fake_print(*a, **k):
            out.append(" ".join(str(x) for x in a))

        cli.print = fake_print
        try:
            cli.main(["top", "--address", c.address, "--json"])
        finally:
            cli.print = real_print
        payload = json.loads("\n".join(out))

        cluster = payload["cluster"]
        assert cluster["task.execute"]["count"] >= 3 * per_node
        assert "rpc.call" in cluster  # driver + daemons talk RPC
        node_rows = {node: per["task.execute"]
                     for node, per in payload["nodes"].items()
                     if "task.execute" in per}
        assert len(node_rows) == 3
        for node, s in node_rows.items():
            assert s["count"] >= per_node
            for key in ("p50_ms", "p95_ms", "p99_ms"):
                assert s[key] > 0
        slow = max(node_rows, key=lambda n: node_rows[n]["p95_ms"])
        assert node_rows[slow]["p99_ms"] >= 40.0  # the 50ms injection
        fast_p99 = [s["p99_ms"] for n, s in node_rows.items() if n != slow]
        assert all(node_rows[slow]["p99_ms"] >= 2 * p for p in fast_p99)
        assert {"node": slow, "name": "task.execute"} in \
            payload["stragglers"]

        head = DashboardHead(c.address)
        try:
            prof = head._profile()
            daemon_hosts = [h for h in prof["hosts"] if h != "head"]
            assert len(daemon_hosts) == 3  # every daemon's sampler federated
            assert prof["merged"]["ticks"] > 0
            assert prof["collapsed"]
            assert prof["pprof"]["samples"]
        finally:
            head.stop()
    finally:
        ray_tpu.shutdown()
        c.shutdown()


def test_fresh_histogram_build_does_not_self_deadlock():
    """bucket_bounds() runs inside PerfHistogram.__init__, which get()
    constructs while holding the registry lock — the bounds cache must
    use its own lock or the first observe after a reset() wedges."""
    perf.reset()                       # bounds cache cold
    done = threading.Event()

    def first_observe():
        perf.get("perf.selftest.fresh").observe(1.0)
        done.set()

    t = threading.Thread(target=first_observe, daemon=True)
    t.start()
    assert done.wait(5.0), "histogram construction deadlocked"
    assert perf.get("perf.selftest.fresh").count() == 1
