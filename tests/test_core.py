"""Core API: tasks, get/put/wait, errors, retries, cancellation.

Models the reference's ``python/ray/tests/test_basic*.py`` coverage.
"""

import threading
import time

import numpy as np
import pytest

import ray_tpu


def test_put_get(ray_start_regular):
    ref = ray_tpu.put({"a": 1, "b": [1, 2, 3]})
    assert ray_tpu.get(ref) == {"a": 1, "b": [1, 2, 3]}


def test_put_get_numpy_zero_copy(ray_start_regular):
    x = np.arange(100, dtype=np.float32)
    ref = ray_tpu.put(x)
    y = ray_tpu.get(ref)
    np.testing.assert_array_equal(x, y)
    assert not y.flags.writeable  # immutability, plasma-style


def test_simple_task(ray_start_regular):
    @ray_tpu.remote
    def add(a, b):
        return a + b

    assert ray_tpu.get(add.remote(1, 2)) == 3


def test_task_with_ref_args(ray_start_regular):
    @ray_tpu.remote
    def double(x):
        return 2 * x

    r1 = double.remote(10)
    r2 = double.remote(r1)
    r3 = double.remote(r2)
    assert ray_tpu.get(r3) == 80


def test_task_kwargs_and_options(ray_start_regular):
    @ray_tpu.remote
    def f(a, b=10):
        return a + b

    assert ray_tpu.get(f.options(num_cpus=0.5).remote(1, b=2)) == 3


def test_num_returns(ray_start_regular):
    @ray_tpu.remote(num_returns=3)
    def three():
        return 1, 2, 3

    a, b, c = three.remote()
    assert ray_tpu.get([a, b, c]) == [1, 2, 3]


def test_task_error_propagates(ray_start_regular):
    @ray_tpu.remote
    def boom():
        raise ValueError("bad")

    with pytest.raises(ray_tpu.TaskError) as e:
        ray_tpu.get(boom.remote())
    assert "bad" in str(e.value)


def test_error_propagates_through_dependencies(ray_start_regular):
    @ray_tpu.remote
    def boom():
        raise ValueError("root cause")

    @ray_tpu.remote
    def consume(x):
        return x

    with pytest.raises(ray_tpu.TaskError) as e:
        ray_tpu.get(consume.remote(boom.remote()))
    assert "root cause" in str(e.value)


def test_retry_on_exception(ray_start_regular):
    counter = {"n": 0}
    lock = threading.Lock()

    @ray_tpu.remote(max_retries=3, retry_exceptions=True)
    def flaky():
        with lock:
            counter["n"] += 1
            n = counter["n"]
        if n < 3:
            raise RuntimeError("transient")
        return "ok"

    assert ray_tpu.get(flaky.remote()) == "ok"
    assert counter["n"] == 3


def test_no_retry_by_default_on_app_error(ray_start_regular):
    counter = {"n": 0}

    @ray_tpu.remote
    def fail_once():
        counter["n"] += 1
        raise RuntimeError("app error")

    with pytest.raises(ray_tpu.TaskError):
        ray_tpu.get(fail_once.remote())
    assert counter["n"] == 1


def test_wait(ray_start_regular):
    @ray_tpu.remote
    def slow(t):
        time.sleep(t)
        return t

    fast = slow.remote(0.01)
    slower = slow.remote(5.0)
    ready, not_ready = ray_tpu.wait([fast, slower], num_returns=1, timeout=3)
    assert ready == [fast]
    assert not_ready == [slower]


def test_wait_timeout(ray_start_regular):
    @ray_tpu.remote
    def forever():
        time.sleep(60)

    r = forever.remote()
    t0 = time.monotonic()
    ready, not_ready = ray_tpu.wait([r], num_returns=1, timeout=0.2)
    assert time.monotonic() - t0 < 2
    assert ready == [] and not_ready == [r]


def test_get_timeout(ray_start_regular):
    @ray_tpu.remote
    def forever():
        time.sleep(60)

    with pytest.raises(ray_tpu.GetTimeoutError):
        ray_tpu.get(forever.remote(), timeout=0.2)


def test_cancel_pending_task(ray_start_regular):
    @ray_tpu.remote(num_cpus=8)
    def hog():
        time.sleep(10)

    @ray_tpu.remote(num_cpus=8)
    def queued():
        return 1

    h = hog.remote()
    q = queued.remote()  # cannot start: resources taken
    time.sleep(0.1)
    ray_tpu.cancel(q)
    with pytest.raises(ray_tpu.TaskCancelledError):
        ray_tpu.get(q, timeout=5)


def test_nested_tasks(ray_start_regular):
    @ray_tpu.remote
    def inner(x):
        return x * 2

    @ray_tpu.remote
    def outer(x):
        return ray_tpu.get(inner.remote(x)) + 1

    assert ray_tpu.get(outer.remote(10)) == 21


def test_many_tasks_throughput(ray_start_regular):
    @ray_tpu.remote(num_cpus=0.01)
    def f(i):
        return i

    refs = [f.remote(i) for i in range(500)]
    assert ray_tpu.get(refs) == list(range(500))


def test_cluster_resources(ray_start_regular):
    total = ray_tpu.cluster_resources()
    assert total["CPU"] == 8


def test_fractional_resources(ray_start_regular):
    @ray_tpu.remote(num_cpus=0.5)
    def half():
        return 1

    assert sum(ray_tpu.get([half.remote() for _ in range(16)])) == 16


def test_object_ref_serializable_in_task(ray_start_regular):
    @ray_tpu.remote
    def make():
        return ray_tpu.put(42)

    inner_ref = ray_tpu.get(make.remote())
    assert ray_tpu.get(inner_ref) == 42


def test_inline_dispatch_fast_path():
    """inline_dispatch=True dispatches ref-free tasks on the submitting
    thread (skipping the queue hop) with identical semantics: results,
    ref-dep tasks, and error propagation all behave as on the queue path."""
    from ray_tpu._private.config import _config
    ray_tpu.shutdown()
    _config.set("inline_dispatch", True)
    try:
        ray_tpu.init(num_cpus=4)

        @ray_tpu.remote
        def double(x):
            return x * 2

        @ray_tpu.remote
        def boom():
            raise ValueError("inline boom")

        assert ray_tpu.get([double.remote(i) for i in range(20)],
                           timeout=30) == [i * 2 for i in range(20)]
        # ref-dep chain still goes through the queue path
        r = double.remote(double.remote(3))
        assert ray_tpu.get(r, timeout=30) == 12
        with pytest.raises(Exception):
            ray_tpu.get(boom.remote(), timeout=30)
        # and a follow-up ref-free task still works after the error
        assert ray_tpu.get(double.remote(5), timeout=30) == 10
    finally:
        ray_tpu.shutdown()
        _config.set("inline_dispatch", False)
