"""End-to-end distributed tracing (ray_tpu.observability).

Acceptance path for the tracing plane: one trace_id minted at the driver
must stitch task submit, worker-side execution in OTHER processes, the
cross-daemon object fetch that moved the producer's array, and the
checkpoint engine's write/commit (recorded on its writer thread) — with
chaos injections interleaved as instant events tagged with the same
trace. Reference role: ``python/ray/tests/test_tracing.py`` over the
OpenTelemetry ``tracing_helper.py`` hooks.
"""

import numpy as np
import pytest

import ray_tpu
from ray_tpu import chaos, observability
from ray_tpu._private.config import _config
from ray_tpu._private.profiling import get_profiler


@pytest.fixture(autouse=True)
def _tracing_hygiene():
    """Tracing/chaos/profiling are process-global switches: always restore
    them so a failing assertion here cannot poison later test files."""
    yield
    chaos.clear()
    observability.disable()
    _config.set("profiling_enabled", False)
    get_profiler().clear()


def _with_trace(events, name_suffix, trace_id):
    return [e for e in events if e.get("name", "").endswith(name_suffix)
            and (e.get("args") or {}).get("trace_id") == trace_id]


def _require_state_service():
    """ProcessCluster needs the C++ state service (protoc + g++)."""
    from ray_tpu._native.build import build_state_service
    try:
        build_state_service()
    except Exception as e:
        pytest.skip(f"state service unavailable: {e}")


def test_one_trace_spans_submit_execute_fetch_and_checkpoint(tmp_path):
    """The headline guarantee: a single trace_id covers the driver's
    submit span, execute spans in two different daemon processes, the
    object.fetch that pulled the producer's array into the consumer's
    daemon, and the checkpoint save/write/commit stages — and a chaos
    fault fired mid-save appears as an instant event inside that trace."""
    from ray_tpu.checkpoint import CheckpointEngine
    from ray_tpu.cluster_utils import ProcessCluster
    _require_state_service()
    ray_tpu.shutdown()
    # Distinct custom resources pin producer and consumer to DIFFERENT
    # daemons, forcing a cross-process fetch of the argument object.
    c = ProcessCluster(num_daemons=1, num_cpus=2, resources={"src": 2})
    try:
        c.add_daemon(resources={"dst": 2})
        ray_tpu.init(address=c.address)
        ray_tpu.set_profiling_enabled(True)
        ray_tpu.set_tracing_enabled(True)
        # Driver-local fault schedule: first checkpoint chunk write is
        # delayed — harmless, but it must surface as a chaos instant
        # event INSIDE the submitting trace.
        chaos.configure(20260805, "checkpoint.write@1=delay(0.001)")

        @ray_tpu.remote(resources={"src": 1})
        def produce():
            return np.arange(1 << 18, dtype=np.int64)

        @ray_tpu.remote(resources={"dst": 1})
        def consume(arr):
            return int(arr[-1])

        eng = CheckpointEngine(str(tmp_path / "ckpt"))
        try:
            with observability.span("client.submit", cat="driver") as s:
                tid = s.trace_id
                ref = produce.remote()
                assert ray_tpu.get(consume.remote(ref),
                                   timeout=60) == (1 << 18) - 1
                eng.save({"w": np.ones((64, 64), np.float32)},
                         step=1, wait=True)
        finally:
            eng.close()
        assert tid

        trace = ray_tpu.timeline()
        produces = _with_trace(trace, "produce", tid)
        consumes = _with_trace(trace, "consume", tid)
        assert len(produces) == 1 and len(consumes) == 1, (
            [e.get("name") for e in trace][:20])
        # ... and they really ran in two different daemon processes
        assert all(e["pid"].startswith("node:")
                   for e in produces + consumes)
        assert produces[0]["pid"] != consumes[0]["pid"]

        # the consumer's daemon pulled the argument from the producer's
        # daemon; that fetch is attributed to the same trace
        fetches = _with_trace(trace, "object.fetch", tid)
        assert fetches, [e.get("name") for e in trace][:30]
        assert any(e["pid"].startswith("node:") for e in fetches)

        # checkpoint stage spans adopt the submitting trace across the
        # engine's writer thread
        for stage in ("checkpoint.save", "checkpoint.write",
                      "checkpoint.commit"):
            assert _with_trace(trace, stage, tid), stage

        # the injected fault is an instant event inside the same trace
        chaos_events = [e for e in trace
                        if e.get("name") == "chaos:checkpoint.write"]
        assert chaos_events
        for e in chaos_events:
            assert e["ph"] == "i"
            assert e["args"]["trace_id"] == tid
            assert e["args"]["action"] == "delay"

        # drill-down helper: filtering the merged timeline by trace_id
        # returns exactly the spans asserted above (the /api/trace path)
        only = observability.spans_for_trace(tid, trace)
        assert len(only) >= 6
        assert all(e["args"]["trace_id"] == tid for e in only)

        ray_tpu.set_tracing_enabled(False)
        ray_tpu.set_profiling_enabled(False)
    finally:
        ray_tpu.shutdown()
        c.shutdown()


def test_chaos_retry_spans_share_parent_trace():
    """Retries under an ambient span stay in its trace: each attempt span
    is a child of the same parent, the failed attempt records its error,
    and the chaos fault that forced the retry lands as an instant event
    parented under the attempt it broke."""
    from ray_tpu._private.backoff import BackoffPolicy, retry_call
    get_profiler().clear()
    _config.set("profiling_enabled", True)
    observability.enable()
    # one-shot fault: first call to the point errors, the retry succeeds
    chaos.configure(7, "test.retry.op@1=error(flaky)")
    attempt_span_ids = []

    def op(_timeout):
        with observability.span("retry.attempt", cat="retry") as a:
            attempt_span_ids.append(a.span_id)
            chaos.inject("test.retry.op")
        return 42

    with observability.span("retry.parent", cat="retry") as parent:
        tid, parent_sid = parent.trace_id, parent.span_id
        got = retry_call(op, BackoffPolicy(
            base_s=0.001, max_s=0.002, max_attempts=4,
            retryable=(chaos.ChaosError,), label="test.retry"))
    assert got == 42

    trace = get_profiler().chrome_trace()
    attempts = [e for e in trace if e.get("name") == "retry.attempt"]
    assert len(attempts) == 2  # failed + succeeded
    for e in attempts:
        assert e["args"]["trace_id"] == tid
        assert e["args"]["parent_span_id"] == parent_sid
    assert attempts[0]["args"]["error"] == "ChaosError"
    assert "error" not in attempts[1]["args"]

    instants = [e for e in trace if e.get("name") == "chaos:test.retry.op"]
    assert len(instants) == 1
    assert instants[0]["ph"] == "i"
    assert instants[0]["args"]["trace_id"] == tid
    assert instants[0]["args"]["parent_span_id"] == attempt_span_ids[0]
    assert instants[0]["args"]["action"] == "ChaosError"


def test_span_ring_drop_oldest_counts_dropped():
    """The profiler buffer is a true ring: over-capacity recording drops
    the OLDEST spans and counts them (surfaced as a metric), instead of
    silently refusing new ones."""
    from ray_tpu._private.profiling import Profiler
    prof = Profiler(max_spans=4)
    _config.set("profiling_enabled", True)
    for i in range(7):
        prof.record(f"s{i}", "t", pid="p", start_s=float(i), dur_s=0.0)
    names = [e["name"] for e in prof.chrome_trace()]
    assert names == ["s3", "s4", "s5", "s6"]
    assert prof.dropped == 3
    prof.clear()
    assert prof.dropped == 0


def test_log_ring_filters_by_trace_id():
    """Log lines emitted inside a span carry its trace_id, and a tail()
    can be filtered down to one distributed trace (/api/node_debug's
    ?trace=T path)."""
    import logging
    from ray_tpu._private.log_ring import RingLogHandler
    _config.set("profiling_enabled", True)
    observability.enable()
    handler = RingLogHandler(capacity=16)
    log = logging.getLogger("ray_tpu.test_tracing")
    log.addHandler(handler)
    log.setLevel(logging.INFO)
    try:
        log.info("before any trace")
        with observability.span("logged.op", cat="test") as s:
            tid = s.trace_id
            log.info("inside the traced op")
        log.info("after the trace")
    finally:
        log.removeHandler(handler)
    all_lines = handler.tail(16)
    assert len(all_lines) == 3
    traced = handler.tail(16, trace_id=tid)
    assert len(traced) == 1
    assert "inside the traced op" in traced[0]
    assert f"trace_id={tid}" in traced[0]


def test_wire_context_round_trip():
    """The 'trace_id:span_id' wire encoding survives a round trip, and
    bad strings are rejected rather than adopted."""
    _config.set("profiling_enabled", True)
    observability.enable()
    with observability.span("wire.parent", cat="test") as s:
        tid, sid = s.trace_id, s.span_id
        wire = observability.wire_context()
        assert wire == f"{tid}:{sid}"
    assert observability.parse_wire(wire) == (tid, sid)
    assert observability.parse_wire("") is None
    assert observability.parse_wire("no-separator") is None
    token = observability.adopt_wire(wire)
    try:
        assert observability.current() == (tid, sid)
    finally:
        observability.reset(token)
    observability.disable()
    # disabled: the hot-path helpers collapse to constants
    assert observability.wire_context() == ""
    assert observability.current_trace_id() == ""
