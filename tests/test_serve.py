"""Serve layer tests.

Models the reference's ``python/ray/serve/tests/``: deploy/call/handle,
rolling reconfigure, replica failure recovery, autoscaling, batching,
HTTP ingress, and deployment graphs.
"""

import json
import threading
import time
import urllib.request

import pytest

import ray_tpu
from ray_tpu import serve


@pytest.fixture
def serve_instance(ray_start_regular):
    serve.start()
    yield
    serve.shutdown()


@serve.deployment
class Echo:
    def __call__(self, x):
        return {"echo": x}

    def shout(self, x):
        return str(x).upper()


def test_deploy_and_call(serve_instance):
    h = serve.run(Echo.bind(), route_prefix="/echo")
    assert h.remote(42).result(timeout=30) == {"echo": 42}
    assert h.shout.remote("hi").result(timeout=30) == "HI"


@serve.deployment
def double(x):
    return 2 * x


def test_function_deployment(serve_instance):
    h = serve.run(double.bind())
    assert h.remote(21).result(timeout=30) == 42


def test_function_deployment_rejects_checkpoint():
    """checkpoint= injects the restored tree as an __init__ kwarg, which a
    function deployment has nowhere to receive — declaring one must fail
    loudly instead of silently serving without the weights."""
    from ray_tpu.serve._private.replica import Replica

    with pytest.raises(ValueError, match="class"):
        @serve.deployment(checkpoint={"root": "/tmp/ckpt"})
        def with_ckpt(x):
            return x

    # the replica guards too (config-dict deploy paths bypass the decorator)
    with pytest.raises(ValueError, match="checkpoint"):
        Replica("d", "d#1", double.func_or_class, (), {},
                checkpoint={"root": "/tmp/ckpt"})


def test_num_replicas_and_status(serve_instance):
    h = serve.run(Echo.options(name="echo3", num_replicas=3).bind(),
                  route_prefix="/e3")
    assert h.remote(1).result(timeout=30) == {"echo": 1}
    st = serve.status()
    assert st["echo3"]["running_replicas"] == 3


@serve.deployment
class Configurable:
    def __init__(self):
        self.threshold = 0

    def reconfigure(self, config):
        self.threshold = config["threshold"]

    def __call__(self, x):
        return x > self.threshold


def test_user_config_reconfigure(serve_instance):
    h = serve.run(
        Configurable.options(user_config={"threshold": 5}).bind())
    assert h.remote(10).result(timeout=30) is True
    assert h.remote(3).result(timeout=30) is False
    # Redeploy with only user_config changed: in-place reconfigure.
    serve.run(Configurable.options(user_config={"threshold": 50}).bind())
    assert h.remote(10).result(timeout=30) is False


def test_replica_failure_recovery(serve_instance):
    h = serve.run(Echo.options(name="fragile", num_replicas=2,
                               health_check_period_s=0.2).bind())
    assert h.remote(0).result(timeout=30) == {"echo": 0}
    controller = serve._get_controller() if hasattr(serve, "_get_controller") \
        else serve.api._get_controller()
    info = ray_tpu.get(controller.get_replica_handles.remote("fragile"))
    ray_tpu.kill(info["handles"][0])
    # Controller reconcile replaces the dead replica.
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        ray_tpu.get(controller.autoscale_tick.remote())
        st = ray_tpu.get(controller.list_deployments.remote())["fragile"]
        if st["running_replicas"] == 2:
            break
        time.sleep(0.1)
    # Requests still succeed.
    for i in range(8):
        assert h.remote(i).result(timeout=30) == {"echo": i}


@serve.deployment
class Slow:
    def __call__(self, x):
        time.sleep(0.3)
        return x


def test_autoscaling_up(serve_instance):
    serve.run(Slow.options(
        name="auto",
        autoscaling_config={"min_replicas": 1, "max_replicas": 3,
                            "target_num_ongoing_requests_per_replica": 1.0,
                            "upscale_delay_s": 0.0},
    ).bind())
    h = serve.get_deployment_handle("auto")
    controller = serve.api._get_controller()
    responses = [h.remote(i) for i in range(6)]

    def tick():
        for _ in range(20):
            ray_tpu.get(controller.autoscale_tick.remote())
            time.sleep(0.05)
    t = threading.Thread(target=tick)
    t.start()
    results = [r.result(timeout=60) for r in responses]
    t.join()
    assert sorted(results) == list(range(6))
    st = serve.status()["auto"]
    assert st["target_replicas"] > 1


class _BatchModel:
    def __init__(self):
        self.batch_sizes = []

    @serve.batch(max_batch_size=4, batch_wait_timeout_s=0.2)
    def predict(self, items):
        self.batch_sizes.append(len(items))
        return [i * 10 for i in items]


def test_batching_groups_requests(ray_start_regular):
    model = _BatchModel()
    results = [None] * 8
    threads = [threading.Thread(
        target=lambda i=i: results.__setitem__(i, model.predict(i)))
        for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert results == [i * 10 for i in range(8)]
    assert max(model.batch_sizes) > 1  # actually batched


def test_batching_pad_to_bucket(ray_start_regular):
    seen = []

    @serve.batch(max_batch_size=8, batch_wait_timeout_s=0.1,
                 pad_batch_to=(4, 8))
    def predict(items):
        seen.append(len(items))
        return [x + 1 for x in items]

    results = [None] * 3
    threads = [threading.Thread(
        target=lambda i=i: results.__setitem__(i, predict(i)))
        for i in range(3)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert results == [1, 2, 3]
    assert all(s in (4, 8) for s in seen)  # padded to a bucket


def test_batching_error_propagates(ray_start_regular):
    @serve.batch(max_batch_size=2, batch_wait_timeout_s=0.05)
    def bad(items):
        raise ValueError("nope")

    with pytest.raises(ValueError):
        bad(1)


def test_http_proxy(serve_instance):
    serve.run(Echo.options(name="http_echo").bind(), route_prefix="/api")
    url = serve.start_http_proxy()
    req = urllib.request.Request(
        f"{url}/api", data=json.dumps({"k": 1}).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=30) as resp:
        assert json.loads(resp.read()) == {"echo": {"k": 1}}
    # Unknown route -> 404
    try:
        urllib.request.urlopen(f"{url}/nope-xyzzy", timeout=30)
        assert False
    except urllib.error.HTTPError as e:
        assert e.code in (404, 500)


@serve.deployment
class Preprocessor:
    def __call__(self, x):
        return x + 1


@serve.deployment
class Pipeline:
    def __init__(self, pre):
        self.pre = pre

    def __call__(self, x):
        pre_out = self.pre.remote(x).result(timeout=30)
        return pre_out * 100


def test_deployment_graph_composition(serve_instance):
    h = serve.run(Pipeline.bind(Preprocessor.bind()))
    assert h.remote(4).result(timeout=60) == 500


def test_delete_deployment(serve_instance):
    serve.run(Echo.options(name="todelete").bind(), route_prefix="/td")
    assert "todelete" in serve.status()
    serve.delete("todelete")
    assert "todelete" not in serve.status()


@serve.deployment(name="versioned")
class V1:
    def __call__(self, x):
        return "v1"


@serve.deployment(name="versioned")
class V2:
    def __call__(self, x):
        return "v2"


def test_rolling_update_on_code_change(serve_instance):
    h = serve.run(V1.bind(), route_prefix="/v")
    assert h.remote(0).result(timeout=30) == "v1"
    serve.run(V2.bind(), route_prefix="/v")
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        if h.remote(0).result(timeout=30) == "v2":
            break
        time.sleep(0.1)
    assert h.remote(0).result(timeout=30) == "v2"


def test_http_proxy_health_routes_and_streaming(serve_instance):
    """Proxy-level features (reference http_proxy.py parity): /-/healthz,
    /-/routes, chunked streaming of list results, 404 body shape."""
    @serve.deployment
    class Lister:
        def __call__(self, n):
            return list(range(n or 3))

    serve.run(Lister.options(name="lister").bind(), route_prefix="/list")
    url = serve.start_http_proxy()
    with urllib.request.urlopen(f"{url}/-/healthz", timeout=30) as r:
        assert json.loads(r.read())["status"] == "ok"
    with urllib.request.urlopen(f"{url}/-/routes", timeout=30) as r:
        routes = json.loads(r.read())
    assert routes.get("/list") == "lister"
    # streaming: each element arrives as its own chunk line
    req = urllib.request.Request(
        f"{url}/list", data=json.dumps(4).encode(),
        headers={"Content-Type": "application/json", "X-Serve-Stream": "1"})
    with urllib.request.urlopen(req, timeout=30) as r:
        assert r.headers.get("Transfer-Encoding") == "chunked"
        lines = [json.loads(x) for x in r.read().split(b"\n") if x]
    assert lines == [0, 1, 2, 3]
    # non-streamed default still one JSON body
    req = urllib.request.Request(
        f"{url}/list", data=json.dumps(2).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=30) as r:
        assert json.loads(r.read()) == [0, 1]


def test_http_proxy_concurrency_limit(serve_instance):
    """Over-limit requests are rejected 503 immediately (ingress
    backpressure), not queued behind blocked handlers."""
    import threading
    import time as _time

    @serve.deployment
    class Slow:
        def __call__(self, x):
            _time.sleep(2.0)
            return "done"

    serve.run(Slow.options(name="slowd").bind(), route_prefix="/slow")
    from ray_tpu.serve import api as serve_api
    from ray_tpu.serve._private.http_proxy import HTTPProxy
    proxy = HTTPProxy(serve_api._get_controller(),
                      max_concurrent_requests=1)
    url = proxy.address()
    results = {}

    def call(key):
        req = urllib.request.Request(
            f"{url}/slow", data=b"1",
            headers={"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(req, timeout=30) as r:
                results[key] = ("ok", json.loads(r.read()))
        except urllib.error.HTTPError as e:
            results[key] = ("http", e.code, e.headers.get("Retry-After"))

    t1 = threading.Thread(target=call, args=("a",))
    t1.start()
    _time.sleep(0.5)  # first request is now holding the one slot
    call("b")
    t1.join(timeout=30)
    assert results["a"] == ("ok", "done"), results
    assert results["b"][0] == "http" and results["b"][1] == 503, results
    assert results["b"][2] == "1"  # Retry-After
    proxy.shutdown()
