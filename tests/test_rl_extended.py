"""Learning gates for the round-5 RL additions: DDPG, ES/ARS, QMIX,
DD-PPO, and the LSTM/attention memory models (reference pass-criteria
style: each algorithm must demonstrably improve within a small budget,
and the memory models must SOLVE a task memoryless policies cannot)."""

import numpy as np
import pytest

import ray_tpu


@pytest.fixture(scope="module", autouse=True)
def _ray():
    if not ray_tpu.is_initialized():
        ray_tpu.init(num_cpus=8)
    yield


# ------------------------------------------------------------------- DDPG
def test_ddpg_is_td3_without_the_fixes():
    from ray_tpu.rl import DDPG
    cfg = DDPG.get_default_config()
    assert cfg.twin_q is False
    assert cfg.policy_delay == 1
    assert cfg.target_noise == 0.0


def test_ddpg_learns_pendulum():
    from ray_tpu.rl import DDPG
    algo = (DDPG.get_default_config()
            .environment("Pendulum-v1")
            .training(train_batch_size=128, n_updates_per_iter=8,
                      num_steps_sampled_before_learning_starts=256)
            .debugging(seed=0)
            .build())
    try:
        worst = 0.0
        for i in range(600):
            r = algo.step()
            rew = r.get("episode_reward_mean")
            if rew is not None:
                worst = min(worst, rew)
        final = r["episode_reward_mean"]
        # measured (seed 0): dips to ~-1350 mid-training (random-policy
        # episodes filling the running mean), recovers to ~-916 by 600
        # iters; random level sustains ~-1300
        assert final > -1000, (worst, final)
        assert final > worst + 250, (worst, final)
    finally:
        algo.stop()


# ------------------------------------------------------------------ ES/ARS
def test_es_learns_cartpole():
    from ray_tpu.rl import ES
    algo = (ES.get_default_config().environment("CartPole-v1")
            .debugging(seed=0).build())
    best = 0
    for _ in range(40):
        r = algo.step()
        best = max(best, r["episode_reward_mean"])
    assert best > 150, best


def test_ars_learns_cartpole_fast():
    from ray_tpu.rl import ARS
    algo = (ARS.get_default_config().environment("CartPole-v1")
            .debugging(seed=0).build())
    best = 0
    for _ in range(20):
        r = algo.step()
        best = max(best, r["episode_reward_mean"])
    assert best > 150, best


def test_es_parallel_rollouts_match_serial_api():
    """num_rollout_workers>0 evaluates perturbations as remote tasks."""
    from ray_tpu.rl import ES
    algo = (ES.get_default_config().environment("CartPole-v1")
            .rollouts(num_rollout_workers=2)
            .training(num_perturbations=4)
            .debugging(seed=0).build())
    r = algo.step()
    assert r["timesteps_this_iter"] > 0
    assert "episode_reward_mean" in r


def test_es_checkpoint_roundtrip(tmp_path):
    from ray_tpu.rl import ARS
    algo = (ARS.get_default_config().environment("CartPole-v1")
            .debugging(seed=0).build())
    algo.step()
    d = tmp_path / "ck"
    d.mkdir()
    state = algo.save_checkpoint(str(d))
    theta = algo.theta.copy()
    algo.step()
    assert not np.allclose(theta, algo.theta)
    algo.load_checkpoint(state)
    np.testing.assert_allclose(theta, algo.theta)


# -------------------------------------------------------------------- QMIX
def test_qmix_beats_vdn_ceiling_on_two_step_game():
    """The QMIX paper's gate: the two-step game's optimum (8) requires a
    NON-additive joint value — reaching it proves the monotonic mixing
    network does its job (additive factorization converges to 7)."""
    from ray_tpu.rl import QMIX, TwoStepCooperativeGameEnv
    algo = (QMIX.get_default_config()
            .environment(lambda c: TwoStepCooperativeGameEnv(c))
            .debugging(seed=0)
            .build())
    for _ in range(90):
        algo.step()
    greedy = algo.greedy_joint_return(20)
    assert greedy >= 7.9, greedy


def test_qmix_checkpoint_roundtrip(tmp_path):
    from ray_tpu.rl import QMIX, TwoStepCooperativeGameEnv
    algo = (QMIX.get_default_config()
            .environment(lambda c: TwoStepCooperativeGameEnv(c))
            .debugging(seed=1).build())
    algo.step()
    d = tmp_path / "ck"
    d.mkdir()
    state = algo.save_checkpoint(str(d))
    algo2 = (QMIX.get_default_config()
             .environment(lambda c: TwoStepCooperativeGameEnv(c))
             .debugging(seed=2).build())
    algo2.load_checkpoint(state)
    import jax
    a = jax.flatten_util.ravel_pytree(algo.learner.params)[0]
    b = jax.flatten_util.ravel_pytree(algo2.learner.params)[0]
    np.testing.assert_allclose(np.asarray(a), np.asarray(b))


# ------------------------------------------------------------------ DD-PPO
def test_ddppo_requires_multiple_workers():
    from ray_tpu.rl import DDPPO
    with pytest.raises(ValueError):
        (DDPPO.get_default_config().environment("CartPole-v1")
         .rollouts(num_rollout_workers=1).build())


def test_ddppo_learns_cartpole_decentralized():
    """Decentralized DP gate: workers train via gradient allreduce (no
    central learner), policies stay in lockstep, and the team learns."""
    from ray_tpu.rl import DDPPO
    algo = (DDPPO.get_default_config()
            .environment("CartPole-v1")
            .rollouts(num_rollout_workers=2, num_envs_per_worker=4,
                      rollout_fragment_length=100)
            .training(train_batch_size=400, num_sgd_iter=6, lr=3e-4)
            .debugging(seed=0)
            .build())
    try:
        first = None
        for i in range(35):
            r = algo.step()
            if first is None and "episode_reward_mean" in r:
                first = r["episode_reward_mean"]
        final = r["episode_reward_mean"]
        assert final > max(40.0, first + 10), (first, final)
        # lockstep: every worker holds bit-identical parameters
        import jax
        ws = ray_tpu.get([w.get_weights.remote() for w in algo._workers],
                         timeout=60)
        a = jax.flatten_util.ravel_pytree(ws[0])[0]
        b = jax.flatten_util.ravel_pytree(ws[1])[0]
        np.testing.assert_allclose(np.asarray(a), np.asarray(b))
    finally:
        algo.stop()


# ---------------------------------------------------------- memory models
def test_lstm_ppo_solves_memory_task():
    """Decisive recurrence gate: MemoryCue pays +1 only for recalling a
    cue visible ONLY at t=0 — a memoryless policy averages 0."""
    from ray_tpu.rl import PPO
    algo = (PPO.get_default_config()
            .environment("MemoryCue-v0")
            .rollouts(num_rollout_workers=0, num_envs_per_worker=16,
                      rollout_fragment_length=20)
            .training(train_batch_size=640, sgd_minibatch_size=160,
                      num_sgd_iter=10, lr=1e-3, grad_clip=10.0,
                      entropy_coeff=0.01,
                      model={"use_lstm": True, "lstm_cell_size": 32})
            .debugging(seed=0).build())
    for _ in range(30):
        r = algo.step()
    assert r["episode_reward_mean"] > 0.8, r["episode_reward_mean"]
    algo.stop()


def test_attention_ppo_solves_memory_task():
    from ray_tpu.rl import PPO
    algo = (PPO.get_default_config()
            .environment("MemoryCue-v0")
            .rollouts(num_rollout_workers=0, num_envs_per_worker=16,
                      rollout_fragment_length=20)
            .training(train_batch_size=640, sgd_minibatch_size=160,
                      num_sgd_iter=10, lr=1e-3, grad_clip=10.0,
                      entropy_coeff=0.01,
                      model={"use_attention": True, "attention_dim": 32,
                             "attention_window": 8})
            .debugging(seed=0).build())
    for _ in range(30):
        r = algo.step()
    assert r["episode_reward_mean"] > 0.8, r["episode_reward_mean"]
    algo.stop()


def test_memoryless_policy_cannot_solve_memory_task():
    """Control: plain PPO stays near chance on MemoryCue — proving the
    task actually requires memory (guards against env leakage)."""
    from ray_tpu.rl import PPO
    algo = (PPO.get_default_config()
            .environment("MemoryCue-v0")
            .rollouts(num_rollout_workers=0, num_envs_per_worker=16,
                      rollout_fragment_length=20)
            .training(train_batch_size=640, sgd_minibatch_size=160,
                      num_sgd_iter=10, lr=1e-3, grad_clip=10.0)
            .debugging(seed=0).build())
    for _ in range(20):
        r = algo.step()
    assert r["episode_reward_mean"] < 0.6, r["episode_reward_mean"]
    algo.stop()


def test_lstm_impala_learns_cartpole():
    """Memory models ride IMPALA's V-trace learner too (sequence replay
    + fragment-end bootstrap from the scan's final state)."""
    from ray_tpu.rl import Impala
    algo = (Impala.get_default_config()
            .environment("CartPole-v1")
            .rollouts(num_rollout_workers=1, num_envs_per_worker=8,
                      rollout_fragment_length=50)
            .training(lr=1e-3, entropy_coeff=0.01,
                      model={"use_lstm": True, "lstm_cell_size": 64})
            .debugging(seed=0).build())
    first = None
    for i in range(60):
        r = algo.step()
        if first is None and "episode_reward_mean" in r:
            first = r["episode_reward_mean"]
    final = r["episode_reward_mean"]
    algo.stop()
    assert final > max(45.0, first + 15), (first, final)


def test_recurrent_replay_is_exact():
    """The learner's sequence replay must reproduce the sampling-time
    logps bit-exactly (state_in + in-scan resets contract)."""
    import jax.numpy as jnp

    from ray_tpu.rl import models as _models
    from ray_tpu.rl.recurrent import RecurrentPolicy, memory_forward
    from ray_tpu.rl.rollout_worker import RolloutWorker
    from ray_tpu.rl.sample_batch import SampleBatch

    for cfg in ({"use_lstm": True, "lstm_cell_size": 16},
                {"use_attention": True, "attention_dim": 16,
                 "attention_window": 4}):
        w = RolloutWorker("CartPole-v1", num_envs=4,
                          rollout_fragment_length=20, policy_config=cfg,
                          seed=0, policy_cls=RecurrentPolicy)
        b = w.sample()
        T, n = 20, len(b)
        obs = jnp.asarray(np.asarray(b[SampleBatch.OBS]).reshape(
            n // T, T, -1))
        acts = jnp.asarray(np.asarray(b[SampleBatch.ACTIONS]).reshape(
            n // T, T))
        lp_sampled = np.asarray(b[SampleBatch.ACTION_LOGP]).reshape(
            n // T, T)
        st = jnp.asarray(np.asarray(b["state_in"]).reshape(
            n // T, T, -1)[:, 0])
        dones = (np.asarray(b[SampleBatch.TERMINATEDS])
                 | np.asarray(b[SampleBatch.TRUNCATEDS])
                 ).astype(np.float32).reshape(n // T, T)
        resets = jnp.asarray(np.concatenate(
            [np.zeros((n // T, 1), np.float32), dones[:, :-1]], 1))
        dist_in, _, _ = memory_forward(w.policy.params, cfg, obs, st,
                                       resets)
        lp = np.asarray(_models.make_distribution(
            w.policy.params, dist_in, False).logp(acts))
        np.testing.assert_allclose(lp, lp_sampled, atol=1e-6)


def test_recurrent_ppo_small_batch_pads_sequences():
    """Fewer sequences than one minibatch must pad (tile), not crash
    (regression: reshape ValueError when n_seq < sgd_minibatch_size/T)."""
    from ray_tpu.rl import PPO
    algo = (PPO.get_default_config()
            .environment("CartPole-v1")
            .rollouts(num_rollout_workers=0, num_envs_per_worker=2,
                      rollout_fragment_length=20)
            .training(train_batch_size=40, sgd_minibatch_size=128,
                      num_sgd_iter=2, lr=3e-4,
                      model={"use_lstm": True, "lstm_cell_size": 16})
            .debugging(seed=0).build())
    r = algo.step()
    assert "policy_loss" in r
    algo.stop()


def test_ddppo_checkpoint_restores_weights(tmp_path):
    """Restore must land the trained weights on every worker (regression:
    __setstate__ dropped them, leaving fresh random init)."""
    import jax

    from ray_tpu.rl import DDPPO
    algo = (DDPPO.get_default_config()
            .environment("CartPole-v1")
            .rollouts(num_rollout_workers=2, num_envs_per_worker=2,
                      rollout_fragment_length=50)
            .training(train_batch_size=100, num_sgd_iter=2)
            .debugging(seed=0).build())
    try:
        algo.step()
        trained = algo.get_weights()
        d = tmp_path / "ck"
        d.mkdir()
        state = algo.save_checkpoint(str(d))
    finally:
        algo.stop()
    algo2 = (DDPPO.get_default_config()
             .environment("CartPole-v1")
             .rollouts(num_rollout_workers=2, num_envs_per_worker=2,
                       rollout_fragment_length=50)
             .training(train_batch_size=100, num_sgd_iter=2)
             .debugging(seed=99).build())
    try:
        algo2.load_checkpoint(state)
        restored = algo2.get_weights()
        a = jax.flatten_util.ravel_pytree(trained)[0]
        b = jax.flatten_util.ravel_pytree(restored)[0]
        np.testing.assert_allclose(np.asarray(a), np.asarray(b))
    finally:
        algo2.stop()


# ------------------------------------------------------------------ MARWIL
def _mixed_quality_dataset(n_steps=4000):
    """Half expert, half ANTI-expert CartPole transitions: the two
    behaviors cancel under plain behavior cloning (same states, opposite
    actions), while their returns differ wildly — the regime MARWIL's
    advantage weighting exists for."""
    from ray_tpu.rl import collect_dataset
    from ray_tpu.rl.sample_batch import concat_samples

    class Expert:
        flip = False

        def compute_actions(self, obs, explore=True):
            import numpy as _np
            obs = _np.atleast_2d(obs)
            a = (obs[:, 2] + 0.5 * obs[:, 3] > 0).astype(_np.int64)
            if self.flip:
                a = 1 - a
            z = _np.zeros(len(a), _np.float32)
            return a, z, z

    anti = Expert()
    anti.flip = True
    good = collect_dataset("CartPole-v1", policy=Expert(),
                           n_steps=n_steps // 2, seed=0)
    bad = collect_dataset("CartPole-v1", policy=anti,
                          n_steps=n_steps // 2, seed=1)
    return concat_samples([good, bad])


def test_marwil_beats_bc_on_mixed_data():
    """Advantage weighting must pull the policy toward the expert HALF
    of a mixed dataset; plain BC averages the behaviors (reference
    rllib/algorithms/marwil learning-test role)."""
    from ray_tpu.rl import BC, MARWIL
    ds = _mixed_quality_dataset()
    scores = {}
    for name, cls in (("bc", BC), ("marwil", MARWIL)):
        algo = (cls.get_default_config().environment("CartPole-v1")
                .training(input_=ds, n_updates_per_iter=64)
                .debugging(seed=0).build())
        try:
            for _ in range(12):
                algo.step()
            scores[name] = algo.evaluate(n_episodes=5)
        finally:
            algo.stop()
    assert scores["marwil"] > 150.0, scores
    assert scores["marwil"] > scores["bc"] + 30.0, scores


def test_marwil_beta_zero_is_bc():
    from ray_tpu.rl import MARWIL
    ds = _mixed_quality_dataset(600)
    algo = (MARWIL.get_default_config().environment("CartPole-v1")
            .training(input_=ds, beta=0.0, n_updates_per_iter=8)
            .debugging(seed=0).build())
    try:
        r = algo.step()
        assert "policy_loss" in r and r["dataset_size"] == 600
    finally:
        algo.stop()


# -------------------------------------------------------------- connectors
def test_connector_units():
    from ray_tpu.rl import (ClipActions, ConnectorPipeline, FrameStack,
                            NormalizeObs, build_connectors)
    norm = NormalizeObs()
    batch = np.asarray([[0.0, 10.0], [2.0, 30.0]], np.float64)
    out = norm(batch)
    assert out.shape == batch.shape and abs(out.mean()) < 2.0
    # peek must not advance the running stats
    state_before = norm.state()
    norm.peek(batch * 100)
    assert norm.state()[0] == state_before[0]
    fs = FrameStack(k=3)
    o1 = fs(np.ones((2, 4)))
    assert o1.shape == (2, 12)
    o2 = fs(2 * np.ones((2, 4)))
    assert o2[0, -4:].tolist() == [2.0] * 4  # newest frame last
    peeked = fs.peek(3 * np.ones((2, 4)))
    again = fs.peek(3 * np.ones((2, 4)))
    np.testing.assert_array_equal(peeked, again)  # no state advance
    clip = ClipActions(low=-1.0, high=1.0)
    assert clip(np.asarray([[5.0, -5.0]])).tolist() == [[1.0, -1.0]]
    pipe = ConnectorPipeline(build_connectors(
        ["flatten_obs", ("clip_obs", {"low": -1, "high": 1})]))
    assert pipe(np.full((2, 2, 2), 9.0)).shape == (2, 4)
    assert pipe(np.full((2, 2, 2), 9.0)).max() == 1.0


def test_ppo_with_connectors_learns():
    """normalize_obs + frame_stack end-to-end: the policy is built on
    the TRANSFORMED shape and still learns CartPole; connector stats
    sync to remote workers with the weights."""
    from ray_tpu.rl import PPO
    algo = (PPO.get_default_config()
            .environment("CartPole-v1")
            .rollouts(num_rollout_workers=1, num_envs_per_worker=4,
                      rollout_fragment_length=100)
            .training(train_batch_size=800, sgd_minibatch_size=200,
                      num_sgd_iter=8, lr=3e-4,
                      model={"fcnet_hiddens": (64, 64),
                             "obs_connectors": [
                                 "normalize_obs",
                                 ("frame_stack", {"k": 2})]})
            .debugging(seed=0).build())
    try:
        lw = algo.workers.local_worker
        assert lw.policy.params["pi"]["layers"][0]["w"].shape[0] == 8
        first = None
        for i in range(30):
            r = algo.step()
            if first is None and "episode_reward_mean" in r:
                first = r["episode_reward_mean"]
        final = r["episode_reward_mean"]
        assert final > max(60.0, first + 20), (first, final)
        # stateful connector stats actually synced to the remote worker
        state = lw.get_connector_state()
        assert state[0] is not None and state[0][0] > 1000  # obs count
    finally:
        algo.stop()


def test_scale_actions_connector_on_pendulum():
    from ray_tpu.rl import SAC
    algo = (SAC.get_default_config()
            .environment("Pendulum-v1")
            .training(train_batch_size=64, n_updates_per_iter=2,
                      num_steps_sampled_before_learning_starts=64,
                      model={"fcnet_hiddens": (32, 32),
                             "action_connectors": ["clip_actions"]})
            .debugging(seed=0).build())
    try:
        for _ in range(3):
            r = algo.step()
        assert r["timesteps_this_iter"] > 0
    finally:
        algo.stop()


# ------------------------------------------------------------ external env
def test_ppo_learns_from_external_env():
    """The APPLICATION drives the loop (reference external_env.py): a
    thread wraps CartPole and queries the policy via get_action;
    PPO trains from the drained experiences unchanged and improves."""
    from ray_tpu.rl import PPO, ExternalEnv
    from ray_tpu.rl.env import CartPoleEnv

    class DrivenCartPole(ExternalEnv):
        def __init__(self, config=None):
            inner = CartPoleEnv(dict(config or {}))
            super().__init__(inner.spec)
            self._inner = inner

        def run(self):
            seed = 0
            while True:
                eid = self.start_episode()
                obs = self._inner.reset(seed=seed)
                seed += 1
                while True:
                    action = self.get_action(eid, obs)
                    obs, rew, term, trunc, _ = self._inner.step(
                        int(action))
                    self.log_returns(eid, rew)
                    if term or trunc:
                        self.end_episode(eid, obs)
                        break

    algo = (PPO.get_default_config()
            .environment(lambda c: DrivenCartPole(c))
            .rollouts(num_rollout_workers=0, num_envs_per_worker=1,
                      rollout_fragment_length=400)
            .training(train_batch_size=400, sgd_minibatch_size=128,
                      num_sgd_iter=8, lr=3e-4)
            .debugging(seed=0).build())
    try:
        first = None
        for i in range(25):
            r = algo.step()
            if first is None and "episode_reward_mean" in r:
                first = r["episode_reward_mean"]
        final = r["episode_reward_mean"]
        assert final > max(50.0, first + 15), (first, final)
    finally:
        algo.stop()


def test_external_env_off_policy_logging():
    """log_action records externally-chosen actions into the batch."""
    from ray_tpu.rl import ExternalEnvSampler
    from ray_tpu.rl import ExternalEnv
    from ray_tpu.rl.env import Box, Discrete, EnvSpec
    from ray_tpu.rl.policy import Policy
    from ray_tpu.rl.sample_batch import SampleBatch

    class Logger(ExternalEnv):
        def run(self):
            eid = self.start_episode()
            for i in range(6):
                self.log_action(eid, np.full(3, float(i)), i % 2)
                self.log_returns(eid, 1.0)
            self.end_episode(eid, np.zeros(3))

    spec = EnvSpec(observation_space=Box(-1, 1, (3,)),
                   action_space=Discrete(2), max_episode_steps=100)
    env = Logger(spec)
    sampler = ExternalEnvSampler(env, Policy(spec, seed=0),
                                 fragment_length=6)
    batch = sampler.sample()
    assert len(batch) == 6
    assert list(batch[SampleBatch.ACTIONS]) == [0, 1, 0, 1, 0, 1]
    assert float(np.sum(batch[SampleBatch.REWARDS])) == 6.0
    ms = sampler.pop_metrics()
    assert ms and ms[0]["episode_reward"] == 6.0
