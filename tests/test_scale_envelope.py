"""Scale-envelope suite: many-actors / deep-queues / many-PGs at
CPU-process scale, with wall-clock budgets.

The role of the reference's release-scale benchmarks
(``release/benchmarks/README.md:5-31``: 10k+ actors, 1M queued tasks,
1k placement groups at cluster scale) shrunk to what one CI host can
assert deterministically: the budgets catch complexity regressions
(O(n^2) scans, per-item wakeup storms), not absolute speed.

Budgets are deliberately loose (5-10x observed) so a loaded CI box
doesn't flake, while a quadratic blowup still trips them.
"""

import time

import numpy as np
import pytest

import ray_tpu


@pytest.fixture(scope="module")
def cluster():
    from ray_tpu.cluster_utils import ProcessCluster
    ray_tpu.shutdown()
    c = ProcessCluster(num_daemons=2, num_cpus=500)
    ray_tpu.init(address=c.address)
    yield c
    ray_tpu.shutdown()
    c.shutdown()


def _budget(seconds):
    """Deadline context: asserts the block stayed within budget."""
    class _B:
        def __enter__(self):
            self.t0 = time.perf_counter()
            return self

        def __exit__(self, *exc):
            self.elapsed = time.perf_counter() - self.t0
            if exc[0] is None:
                assert self.elapsed < seconds, (
                    f"scale envelope exceeded: {self.elapsed:.1f}s "
                    f"> {seconds}s budget")
            return False
    return _B()


def test_1k_actors_create_call_kill(cluster):
    """1000 concurrent lightweight actors: create all, one call each,
    kill all (reference release test: many_actors)."""
    @ray_tpu.remote(num_cpus=0.01)
    class Mini:
        def ping(self, i):
            return i

    with _budget(120):
        actors = [Mini.remote() for _ in range(1000)]
        out = ray_tpu.get([a.ping.remote(i) for i, a in enumerate(actors)],
                          timeout=110)
    assert out == list(range(1000))
    for a in actors:
        ray_tpu.kill(a)


def test_10k_queued_tasks_drain(cluster):
    """10k tiny tasks submitted at once must all complete (deep pending
    queues on driver and daemons; admission backpressure may spill but
    nothing may be lost)."""
    @ray_tpu.remote(num_cpus=0.01)
    def tick(i):
        return i

    with _budget(120):
        refs = [tick.remote(i) for i in range(10_000)]
        out = ray_tpu.get(refs, timeout=110)
    assert out == list(range(10_000))


def test_100_placement_groups(cluster):
    """100 PGs created+ready, an actor placed in each, then removed
    (reference release test: many_pgs)."""
    from ray_tpu.util.placement_group import (placement_group,
                                              remove_placement_group)

    @ray_tpu.remote(num_cpus=0.1)
    class Holder:
        def where(self):
            return 1

    with _budget(120):
        pgs = [placement_group([{"CPU": 0.5}], strategy="PACK")
               for _ in range(100)]
        ray_tpu.get([pg.ready() for pg in pgs], timeout=60)
        actors = [Holder.options(placement_group=pg).remote() for pg in pgs]
        assert ray_tpu.get([a.where.remote() for a in actors],
                           timeout=60) == [1] * 100
    for a in actors:
        ray_tpu.kill(a)
    for pg in pgs:
        remove_placement_group(pg)


def test_wait_on_1k_objects(cluster):
    """ray.wait over 1000 refs with partial returns: num_returns
    batching must not degrade quadratically."""
    @ray_tpu.remote(num_cpus=0.01)
    def make(i):
        return i

    with _budget(90):
        refs = [make.remote(i) for i in range(1000)]
        remaining = list(refs)
        seen = 0
        while remaining:
            done, remaining = ray_tpu.wait(
                remaining, num_returns=min(100, len(remaining)), timeout=60)
            assert done, "wait() made no progress inside its timeout"
            seen += len(done)
        assert seen == 1000


def test_broadcast_large_object_to_all_daemons(cluster):
    """One ~8MB object consumed by tasks pinned across both daemons:
    every consumer sees the full payload (push/pull planes at fan-out)."""
    payload = np.arange(1_000_000, dtype=np.float64)  # 8 MB
    ref = ray_tpu.put(payload)

    @ray_tpu.remote(num_cpus=0.01)
    def crc(arr):
        return float(arr.sum())

    with _budget(90):
        out = ray_tpu.get([crc.remote(ref) for _ in range(64)], timeout=80)
    expected = float(payload.sum())
    assert out == [expected] * 64


def test_submission_latency_stays_flat(cluster):
    """Per-task submission cost must not grow with completed-task count
    (leaking per-task state into hot-path scans would show here)."""
    @ray_tpu.remote(num_cpus=0.01)
    def nop():
        return None

    def batch_time(n=500):
        t0 = time.perf_counter()
        ray_tpu.get([nop.remote() for _ in range(n)], timeout=60)
        return time.perf_counter() - t0

    first = batch_time()
    for _ in range(4):
        batch_time()
    last = batch_time()
    # allow generous noise; a linear-in-history scan would be >>3x
    assert last < first * 3 + 1.0, (first, last)
