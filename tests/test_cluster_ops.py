"""Autoscaler, runtime envs, job submission, and chaos.

Mirrors the reference's ``test_autoscaler.py`` (pure-logic with a mocked
provider), ``test_autoscaler_fake_multinode.py`` (in-process fake
provider), ``test_runtime_env*.py``, job manager tests
(``dashboard/modules/job/tests``), and ``test_chaos.py`` (NodeKiller:
task retry + actor restart under node churn, SURVEY §4.2).
"""

import os
import sys
import time
import zipfile

import pytest

import ray_tpu
from ray_tpu.autoscaler import (AutoscalerConfig, FakeNodeProvider,
                                StandardAutoscaler)


# -- autoscaler -------------------------------------------------------------

@pytest.fixture
def small_cluster():
    ray_tpu.shutdown()
    w = ray_tpu.init(num_cpus=1)  # head node: 1 CPU
    yield w
    ray_tpu.shutdown()


def test_autoscaler_scales_up_for_unmet_demand(small_cluster):
    rt = small_cluster.runtime
    provider = FakeNodeProvider(rt, {"cpu-4": {"CPU": 4}})
    autoscaler = StandardAutoscaler(
        AutoscalerConfig(node_types={"cpu-4": {"CPU": 4}}, max_workers=3,
                         idle_timeout_s=3600), provider, rt)

    @ray_tpu.remote(num_cpus=4)
    def big():
        return os.getpid()

    ref = big.remote()  # infeasible on the 1-CPU head
    time.sleep(0.1)
    result = autoscaler.update()
    assert result["launched"] == 1
    assert ray_tpu.get(ref, timeout=20)  # now schedulable
    # No further demand: second pass launches nothing.
    assert autoscaler.update()["launched"] == 0


def test_autoscaler_respects_max_workers(small_cluster):
    rt = small_cluster.runtime
    provider = FakeNodeProvider(rt, {"cpu-2": {"CPU": 2}})
    autoscaler = StandardAutoscaler(
        AutoscalerConfig(node_types={"cpu-2": {"CPU": 2}}, max_workers=2,
                         upscaling_speed=100.0, idle_timeout_s=3600),
        provider, rt)

    @ray_tpu.remote(num_cpus=2)
    def wide(i):
        time.sleep(0.5)
        return i

    refs = [wide.remote(i) for i in range(8)]
    time.sleep(0.1)
    autoscaler.update()
    autoscaler.update()
    assert len(provider.non_terminated_nodes()) <= 2
    ray_tpu.get(refs, timeout=30)


def test_autoscaler_scales_down_idle_nodes(small_cluster):
    rt = small_cluster.runtime
    provider = FakeNodeProvider(rt, {"cpu-4": {"CPU": 4}})
    autoscaler = StandardAutoscaler(
        AutoscalerConfig(node_types={"cpu-4": {"CPU": 4}}, max_workers=3,
                         idle_timeout_s=0.2), provider, rt)
    provider.create_node("cpu-4", 2)
    assert len(provider.non_terminated_nodes()) == 2
    autoscaler.update()          # records idle-since
    time.sleep(0.3)
    result = autoscaler.update()
    assert result["terminated"] == 2
    assert len(provider.non_terminated_nodes()) == 0


def test_autoscaler_min_workers(small_cluster):
    rt = small_cluster.runtime
    provider = FakeNodeProvider(rt, {"cpu-2": {"CPU": 2}})
    autoscaler = StandardAutoscaler(
        AutoscalerConfig(node_types={"cpu-2": {"CPU": 2}}, max_workers=4,
                         min_workers=2, idle_timeout_s=0.0), provider, rt)
    autoscaler.update()
    assert len(provider.non_terminated_nodes()) == 2
    # Idle but protected by min_workers.
    time.sleep(0.05)
    autoscaler.update()
    assert len(provider.non_terminated_nodes()) == 2


# -- runtime env ------------------------------------------------------------

def test_runtime_env_env_vars(ray_start_regular):
    @ray_tpu.remote
    def read_env():
        return os.environ.get("RAY_TPU_TEST_VAR")

    assert ray_tpu.get(read_env.remote()) is None
    ref = read_env.options(
        runtime_env={"env_vars": {"RAY_TPU_TEST_VAR": "42"}}).remote()
    assert ray_tpu.get(ref) == "42"
    # Restored after the task.
    assert ray_tpu.get(read_env.remote()) is None
    assert "RAY_TPU_TEST_VAR" not in os.environ


def test_runtime_env_working_dir_and_py_modules(ray_start_regular, tmp_path):
    pkg = tmp_path / "mypkg"
    pkg.mkdir()
    (pkg / "mymod_rt_env.py").write_text("VALUE = 'from-working-dir'\n")
    zpath = tmp_path / "mods.zip"
    with zipfile.ZipFile(zpath, "w") as z:
        z.writestr("zipped_rt_env.py", "VALUE = 'from-zip'\n")

    @ray_tpu.remote
    def load_both():
        import mymod_rt_env
        import zipped_rt_env
        return mymod_rt_env.VALUE, zipped_rt_env.VALUE

    ref = load_both.options(runtime_env={
        "working_dir": str(pkg),
        "py_modules": [str(zpath)],
    }).remote()
    assert ray_tpu.get(ref) == ("from-working-dir", "from-zip")
    for mod in ("mymod_rt_env", "zipped_rt_env"):
        sys.modules.pop(mod, None)
    with pytest.raises(ImportError):
        import mymod_rt_env  # noqa: F401


def test_runtime_env_rejects_conda_and_container(ray_start_regular):
    @ray_tpu.remote
    def f():
        return 1

    for field in ("conda", "container"):
        with pytest.raises(Exception) as ei:
            ray_tpu.get(f.options(
                runtime_env={field: "x"}).remote(), timeout=10)
        assert "not supported" in str(ei.value)


def _write_tiny_wheel(wheel_dir, name="tinypkg_rt", version="1.0",
                      value=41):
    """Hand-assemble a minimal PEP-427 wheel (no network, no build
    backend): pip installs it from a --find-links dir with --no-index."""
    wheel_dir.mkdir(parents=True, exist_ok=True)
    whl = wheel_dir / f"{name}-{version}-py3-none-any.whl"
    dist = f"{name}-{version}.dist-info"
    with zipfile.ZipFile(whl, "w") as z:
        z.writestr(f"{name}/__init__.py", f"VALUE = {value}\n")
        z.writestr(f"{dist}/METADATA",
                   f"Metadata-Version: 2.1\nName: {name}\n"
                   f"Version: {version}\n")
        z.writestr(f"{dist}/WHEEL",
                   "Wheel-Version: 1.0\nGenerator: test\n"
                   "Root-Is-Purelib: true\nTag: py3-none-any\n")
        z.writestr(f"{dist}/RECORD",
                   f"{name}/__init__.py,,\n{dist}/METADATA,,\n"
                   f"{dist}/WHEEL,,\n{dist}/RECORD,,\n")
    return whl


def test_runtime_env_pip_installs_absent_package(ray_start_regular,
                                                 tmp_path):
    """A task runs with a package ABSENT from the base env, materialized
    offline from a local wheel dir (reference runtime_env/pip.py role,
    redesigned as a --target prefix for the thread-worker runtime)."""
    with pytest.raises(ImportError):
        import tinypkg_rt  # noqa: F401
    _write_tiny_wheel(tmp_path / "wheels")

    @ray_tpu.remote
    def use_pkg():
        import tinypkg_rt
        return tinypkg_rt.VALUE

    env = {"pip": {"packages": ["tinypkg_rt==1.0"],
                   "find_links": str(tmp_path / "wheels")}}
    assert ray_tpu.get(use_pkg.options(runtime_env=env).remote(),
                       timeout=120) == 41
    # gone from sys.path after the task
    sys.modules.pop("tinypkg_rt", None)
    with pytest.raises(ImportError):
        import tinypkg_rt  # noqa: F401


def test_runtime_env_pip_cache_hit_and_invalidation(ray_start_regular,
                                                    tmp_path):
    from ray_tpu._private.runtime_env import get_manager
    _write_tiny_wheel(tmp_path / "wheels", value=7)
    mgr = get_manager()

    @ray_tpu.remote
    def use_pkg():
        import tinypkg_rt
        return tinypkg_rt.VALUE

    env = {"pip": {"packages": ["tinypkg_rt==1.0"],
                   "find_links": str(tmp_path / "wheels")}}
    before = mgr.num_pip_builds
    out = ray_tpu.get([use_pkg.options(runtime_env=env).remote()
                       for _ in range(3)], timeout=120)
    assert out == [7, 7, 7]
    assert mgr.num_pip_builds == before + 1  # one build, two cache hits
    # republish the wheel with different content: the key covers the
    # wheel dir's content hash, so the prefix is REBUILT, not reused
    _write_tiny_wheel(tmp_path / "wheels", value=8)
    sys.modules.pop("tinypkg_rt", None)
    assert ray_tpu.get(use_pkg.options(runtime_env=env).remote(),
                       timeout=120) == 8
    assert mgr.num_pip_builds == before + 2
    sys.modules.pop("tinypkg_rt", None)


def test_runtime_env_pip_install_failure_surfaces(ray_start_regular,
                                                  tmp_path):
    (tmp_path / "empty").mkdir()

    @ray_tpu.remote
    def f():
        return 1

    env = {"pip": {"packages": ["definitely_not_a_pkg==9.9"],
                   "find_links": str(tmp_path / "empty")}}
    with pytest.raises(Exception) as ei:
        ray_tpu.get(f.options(runtime_env=env).remote(), timeout=120)
    assert "pip install" in str(ei.value)


def test_runtime_env_cached_once(ray_start_regular, tmp_path):
    from ray_tpu._private.runtime_env import get_manager
    d = tmp_path / "wd"
    d.mkdir()
    (d / "cached_rt_env.py").write_text("X = 1\n")
    before = get_manager().num_materialized

    @ray_tpu.remote
    def touch():
        return 1

    env = {"working_dir": str(d)}
    ray_tpu.get([touch.options(runtime_env=env).remote()
                 for _ in range(4)])
    assert get_manager().num_materialized == before + 1


# -- job submission ---------------------------------------------------------

def test_job_submission_lifecycle(tmp_path):
    from ray_tpu.job import JobStatus, JobSubmissionClient
    client = JobSubmissionClient.__new__(JobSubmissionClient)
    from ray_tpu.job import JobManager
    client._manager = JobManager(job_dir=str(tmp_path))

    job_id = client.submit_job(
        entrypoint=f"{sys.executable} -c \"print('job ran ok')\"")
    status = client._manager.wait_until_finished(job_id, timeout=30)
    assert status == JobStatus.SUCCEEDED
    assert "job ran ok" in client.get_job_logs(job_id)
    assert client.get_job_info(job_id).return_code == 0

    bad = client.submit_job(entrypoint=f"{sys.executable} -c 'exit(3)'")
    assert client._manager.wait_until_finished(bad, 30) == JobStatus.FAILED
    assert client.get_job_info(bad).return_code == 3

    ids = [j.job_id for j in client.list_jobs()]
    assert job_id in ids and bad in ids


def test_job_stop(tmp_path):
    from ray_tpu.job import JobManager, JobStatus
    mgr = JobManager(job_dir=str(tmp_path))
    job_id = mgr.submit_job(
        entrypoint=f"{sys.executable} -c 'import time; time.sleep(60)'")
    time.sleep(0.3)
    assert mgr.stop_job(job_id)
    assert mgr.wait_until_finished(job_id, 10) == JobStatus.STOPPED


def test_job_persistence_across_manager_restart(tmp_path):
    from ray_tpu.job import JobManager, JobStatus
    mgr = JobManager(job_dir=str(tmp_path))
    job_id = mgr.submit_job(entrypoint=f"{sys.executable} -c 'print(1)'")
    mgr.wait_until_finished(job_id, 30)
    mgr2 = JobManager(job_dir=str(tmp_path))
    assert mgr2.get_job_status(job_id) == JobStatus.SUCCEEDED


# -- chaos ------------------------------------------------------------------

def test_chaos_node_killer(ray_start_cluster):
    """Kill random worker nodes while tasks run: retries + lineage keep
    results correct (reference: ``test_chaos.py:66`` + NodeKillerActor
    ``test_utils.py:1084``)."""
    import random
    cluster = ray_start_cluster
    cluster.add_node(num_cpus=2)  # head
    import ray_tpu as rt
    workers = [cluster.add_node(num_cpus=2) for _ in range(3)]

    @ray_tpu.remote(max_retries=10)
    def churn(i):
        time.sleep(0.05)
        return i * 2

    stop = [False]

    def killer():
        rng = random.Random(0)
        while not stop[0] and workers:
            time.sleep(0.3)
            node = workers.pop(rng.randrange(len(workers)))
            cluster.remove_node(node)

    import threading
    t = threading.Thread(target=killer, daemon=True)
    t.start()
    try:
        refs = [churn.remote(i) for i in range(60)]
        results = ray_tpu.get(refs, timeout=120)
        assert results == [i * 2 for i in range(60)]
    finally:
        stop[0] = True
        t.join(timeout=5)


def test_chaos_actor_restart_under_node_kill(ray_start_cluster):
    cluster = ray_start_cluster
    cluster.add_node(num_cpus=1)  # head
    worker_node = cluster.add_node(num_cpus=4, resources={"pin": 1})

    @ray_tpu.remote(max_restarts=5, max_task_retries=5, resources={"pin": 0.1})
    class Survivor:
        def __init__(self):
            self.n = 0

        def bump(self):
            self.n += 1
            return self.n

    a = Survivor.remote()
    assert ray_tpu.get(a.bump.remote()) == 1
    cluster.remove_node(worker_node)
    cluster.add_node(num_cpus=4, resources={"pin": 1})
    # Restarted actor loses in-memory state but keeps serving.
    out = ray_tpu.get(a.bump.remote(), timeout=30)
    assert out == 1


def test_runtime_env_same_env_tasks_run_concurrently(ray_start_regular):
    """The env gate admits same-env tasks together (the old global lock
    serialized the whole task body, killing concurrency)."""
    import time as _time

    @ray_tpu.remote(num_cpus=1)
    def slow():
        _time.sleep(0.4)
        return os.environ.get("RAY_TPU_GATE_VAR")

    env = {"env_vars": {"RAY_TPU_GATE_VAR": "shared"}}
    t0 = _time.monotonic()
    out = ray_tpu.get([slow.options(runtime_env=env).remote()
                       for _ in range(4)], timeout=30)
    elapsed = _time.monotonic() - t0
    assert out == ["shared"] * 4
    # serialized would be >= 1.6s; concurrent on 8 cpus is ~0.4s
    assert elapsed < 1.2, elapsed


def test_runtime_env_distinct_envs_never_bleed(ray_start_regular):
    """Tasks with different env_vars must each see exactly their own
    values (distinct envs serialize through the gate)."""
    @ray_tpu.remote(num_cpus=0.5)
    def read(expect):
        import time as _time
        _time.sleep(0.02)
        v = os.environ.get("RAY_TPU_BLEED_VAR")
        return (expect, v)

    refs = []
    for i in range(12):
        env = {"env_vars": {"RAY_TPU_BLEED_VAR": f"v{i % 3}"}}
        refs.append(read.options(runtime_env=env).remote(f"v{i % 3}"))
    for expect, got in ray_tpu.get(refs, timeout=60):
        assert got == expect, (expect, got)
    assert "RAY_TPU_BLEED_VAR" not in os.environ


# -- TPU pod-slice provider -------------------------------------------------

class _FakeGcloud:
    """Simulates the queued-resources API: create -> PROVISIONING, a later
    list() promotes to ACTIVE; delete removes."""

    def __init__(self):
        self.nodes = {}       # qr id -> state
        self.commands = []

    def __call__(self, args):
        self.commands.append(args)
        verb = args[3] if len(args) > 3 else ""
        if verb == "create":
            self.nodes[args[4]] = "PROVISIONING"
            return ""
        if verb == "list":
            out = []
            for name, state in self.nodes.items():
                out.append({"name": f"projects/p/zones/z/queuedResources/"
                                    f"{name}",
                            "state": {"state": state}})
                if state == "PROVISIONING":
                    self.nodes[name] = "ACTIVE"
            return __import__("json").dumps(out)
        if verb == "delete":
            self.nodes.pop(args[4], None)
            return ""
        raise AssertionError(f"unexpected gcloud args {args}")


def test_tpu_provider_lifecycle():
    from ray_tpu.autoscaler.tpu_provider import TPUPodSliceProvider
    fake = _FakeGcloud()
    prov = TPUPodSliceProvider({
        "project": "p", "zone": "us-central2-b",
        "cluster_address": "head:6379",
        "auth_token": "s3cret",
        "node_types": {
            "v5e-8": {"accelerator_type": "v5litepod-8",
                      "resources": {"CPU": 208, "TPU": 8}}},
    }, command_runner=fake)

    ids = prov.create_node("v5e-8", count=2)
    assert len(ids) == 2 and all(i.startswith("raytpu-v5e-8-") for i in ids)
    create_cmd = fake.commands[0]
    assert "--accelerator-type=v5litepod-8" in create_cmd
    assert "--project=p" in create_cmd and "--zone=us-central2-b" in create_cmd
    script = next(a for a in create_cmd if "startup-script" in a)
    assert "head:6379" in script
    # the slice must present the cluster's auth token when joining
    assert "RAY_TPU_AUTH_TOKEN=s3cret" in script

    live = prov.non_terminated_nodes()
    assert sorted(live) == sorted(ids)
    assert prov.node_resources(ids[0]) == {"CPU": 208, "TPU": 8}
    assert prov.node_type(ids[0]) == "v5e-8"

    prov.terminate_node(ids[0])
    assert sorted(prov.non_terminated_nodes()) == [ids[1]]


def test_tpu_provider_rediscovers_foreign_nodes():
    """Nodes created by a previous autoscaler incarnation (present in the
    cloud but unknown locally) are re-adopted with their type parsed from
    the id."""
    from ray_tpu.autoscaler.tpu_provider import TPUPodSliceProvider
    fake = _FakeGcloud()
    fake.nodes["raytpu-v5e-8-deadbeef"] = "ACTIVE"
    prov = TPUPodSliceProvider({
        "project": "p", "zone": "z",
        "node_types": {"v5e-8": {"accelerator_type": "v5litepod-8",
                                 "resources": {"TPU": 8}}}},
        command_runner=fake)
    live = prov.non_terminated_nodes()
    assert live == ["raytpu-v5e-8-deadbeef"]
    assert prov.node_type(live[0]) == "v5e-8"
    assert prov.node_resources(live[0]) == {"TPU": 8}


def test_tpu_provider_rejects_bad_config():
    from ray_tpu.autoscaler.tpu_provider import TPUPodSliceProvider
    with pytest.raises(ValueError):
        TPUPodSliceProvider({"project": "p"})
    prov = TPUPodSliceProvider(
        {"project": "p", "zone": "z", "node_types": {}},
        command_runner=lambda a: "")
    with pytest.raises(ValueError):
        prov.create_node("nope")


def test_runtime_env_nested_different_env_restores():
    """A nested applied() with a DIFFERENT env must fully undo its
    mutations at its own exit (regression: nested mutations leaked)."""
    from ray_tpu._private.runtime_env import MaterializedEnv
    outer = MaterializedEnv({"RAY_TPU_NEST_A": "outer"}, [])
    inner = MaterializedEnv({"RAY_TPU_NEST_B": "inner"}, [])
    with outer.applied():
        assert os.environ["RAY_TPU_NEST_A"] == "outer"
        with inner.applied():
            assert os.environ["RAY_TPU_NEST_B"] == "inner"
        assert "RAY_TPU_NEST_B" not in os.environ  # nested undone
        assert os.environ["RAY_TPU_NEST_A"] == "outer"
    assert "RAY_TPU_NEST_A" not in os.environ
    assert "RAY_TPU_NEST_B" not in os.environ


def test_arena_owner_liveness_probe(tmp_path):
    """Claim-repair liveness: a listening socket means a live owner; a
    missing or refused socket means a dead one (advisor r4 — never delete
    a healthy owner's claim, always repair a verifiably dead one)."""
    import socket

    from ray_tpu._private.distributed import DistributedRuntime
    dead = DistributedRuntime._arena_owner_dead
    # No socket at all -> dead.
    assert dead(str(tmp_path / f"ray_tpu_arena_{os.getpid()}_1.sock"))
    # Bound but not accepting (closed listener) -> refused -> dead.
    path = str(tmp_path / "ray_tpu_arena_999999_2.sock")
    s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    s.bind(path)
    s.listen(1)
    assert not dead(path)  # live listener -> alive
    s.close()
    assert dead(path)  # socket file remains, nobody listening -> dead
    # Distinct machine ids for isolated /tmp would need a mount namespace;
    # at least assert the id is stable and carries all three components.
    mid = DistributedRuntime._machine_id()
    assert mid == DistributedRuntime._machine_id()
    assert mid.count("|") == 2


def test_runtime_env_nested_blocks_new_entrants():
    """While a nested DIFFERENT env is applied, new same-outer-env tasks
    must be held at the gate — admitting them would let them observe the
    nested env's env_vars (regression: exclusivity was checked only at
    nested entry, not held for its duration)."""
    import threading
    import time as _time

    from ray_tpu._private.runtime_env import MaterializedEnv
    outer = MaterializedEnv({"RAY_TPU_GATE_A": "outer"}, [])
    inner = MaterializedEnv({"RAY_TPU_GATE_B": "inner"}, [])
    seen_inside = []
    nested_applied = threading.Event()
    release_nested = threading.Event()

    def holder():
        with outer.applied():
            with inner.applied():
                nested_applied.set()
                release_nested.wait(timeout=10)

    def entrant():
        nested_applied.wait(timeout=10)
        with outer.applied():
            # Must NOT see the nested env's variable.
            seen_inside.append(os.environ.get("RAY_TPU_GATE_B"))

    t1 = threading.Thread(target=holder)
    t2 = threading.Thread(target=entrant)
    t1.start()
    t2.start()
    # Give the entrant a moment to (incorrectly) slip through, then
    # release the nested env so the entrant can legitimately proceed.
    nested_applied.wait(timeout=10)
    _time.sleep(0.3)
    assert not seen_inside, "entrant admitted while nested env active"
    release_nested.set()
    t1.join(timeout=10)
    t2.join(timeout=10)
    assert seen_inside == [None]
    assert "RAY_TPU_GATE_A" not in os.environ
    assert "RAY_TPU_GATE_B" not in os.environ
