"""Model stack: transformer + resnet forward/grad, sharded training step."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from ray_tpu.models import resnet, transformer
from ray_tpu.models.transformer import TransformerConfig
from ray_tpu.parallel import (MeshConfig, ShardingRules, batch_sharding,
                              build_mesh, shard_pytree)

TINY = TransformerConfig(vocab_size=256, d_model=64, n_layers=2, n_heads=4,
                         max_seq_len=128, dtype=jnp.float32, use_flash=False)


def test_transformer_forward_shapes():
    params = transformer.init_params(jax.random.PRNGKey(0), TINY)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, 256)
    logits = transformer.apply(params, tokens, TINY)
    assert logits.shape == (2, 16, 256)
    assert logits.dtype == jnp.float32


def test_transformer_loss_decreases():
    cfg = TINY
    params = transformer.init_params(jax.random.PRNGKey(0), cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 32), 0, 256)
    opt = optax.adam(1e-3)
    opt_state = opt.init(params)

    @jax.jit
    def step(params, opt_state):
        loss, grads = jax.value_and_grad(transformer.loss_fn)(
            params, tokens, cfg)
        updates, opt_state = opt.update(grads, opt_state)
        return optax.apply_updates(params, updates), opt_state, loss

    losses = []
    for _ in range(10):
        params, opt_state, loss = step(params, opt_state)
        losses.append(float(loss))
    assert losses[-1] < losses[0] - 0.5, losses


def test_transformer_causality():
    """Changing a future token must not affect earlier logits."""
    params = transformer.init_params(jax.random.PRNGKey(0), TINY)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (1, 16), 0, 256)
    logits1 = transformer.apply(params, tokens, TINY)
    tokens2 = tokens.at[0, -1].set((tokens[0, -1] + 1) % 256)
    logits2 = transformer.apply(params, tokens2, TINY)
    np.testing.assert_allclose(np.asarray(logits1[0, :-1]),
                               np.asarray(logits2[0, :-1]),
                               rtol=1e-4, atol=1e-4)


def test_transformer_flash_matches_dense():
    cfg_dense = TINY
    cfg_flash = TransformerConfig(**{**cfg_dense.__dict__, "use_flash": True})
    params = transformer.init_params(jax.random.PRNGKey(0), cfg_dense)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 128), 0, 256)
    l_dense = transformer.apply(params, tokens, cfg_dense)
    l_flash = transformer.apply(params, tokens, cfg_flash)
    np.testing.assert_allclose(np.asarray(l_dense), np.asarray(l_flash),
                               rtol=2e-4, atol=2e-4)


def test_transformer_sharded_train_step(eight_device_mesh):
    """Full fsdp+tp sharded train step over the 8-device mesh."""
    mesh = build_mesh(MeshConfig(data=2, fsdp=2, tensor=2),
                      eight_device_mesh)
    cfg = TINY
    rules = ShardingRules()
    params = transformer.init_params(jax.random.PRNGKey(0), cfg)
    axes = transformer.logical_axes(cfg)
    params = shard_pytree(params, axes, mesh, rules)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (8, 32), 0, 256)
    tokens = jax.device_put(tokens, batch_sharding(mesh, rules, ndim=2))

    @jax.jit
    def step(params, tokens):
        loss, grads = jax.value_and_grad(transformer.loss_fn)(
            params, tokens, cfg)
        return loss, grads

    loss, grads = step(params, tokens)
    assert np.isfinite(float(loss))
    # Gradient shardings follow parameter shardings.
    g = grads["blocks"]["mlp"]["wi"]
    p = params["blocks"]["mlp"]["wi"]
    assert g.sharding == p.sharding


def test_transformer_seq_parallel_matches(eight_device_mesh):
    """Ring-attention path (seq axis > 1) matches single-device output."""
    cfg = TINY
    params = transformer.init_params(jax.random.PRNGKey(0), cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 64), 0, 256)
    ref = transformer.apply(params, tokens, cfg, mesh=None)
    mesh = build_mesh(MeshConfig(data=2, seq=4), eight_device_mesh)
    out = transformer.apply(params, tokens, cfg, mesh=mesh)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-3, atol=2e-3)


def test_resnet_forward_and_grad():
    cfg = resnet.resnet18(num_classes=10)
    params = resnet.init_params(jax.random.PRNGKey(0), cfg)
    images = jax.random.normal(jax.random.PRNGKey(1), (2, 32, 32, 3))
    logits = resnet.apply(params, images, cfg)
    assert logits.shape == (2, 10)
    labels = jnp.array([1, 2])
    loss, grads = jax.value_and_grad(resnet.loss_fn)(params, images, labels,
                                                     cfg)
    assert np.isfinite(float(loss))
    gw = grads["head"]["w"]
    assert np.isfinite(np.asarray(gw)).all()


def test_resnet50_params_count():
    cfg = resnet.resnet50()
    params = resnet.init_params(jax.random.PRNGKey(0), cfg)
    n = transformer.num_params(params)
    # torchvision resnet50 has ~25.6M params
    assert 20e6 < n < 30e6, n
