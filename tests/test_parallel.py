"""Parallelism strategies on the 8-device CPU mesh: mesh planning, sharding
rules, pipeline parallelism, ring attention, MoE all_to_all."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from ray_tpu.parallel import (MeshConfig, ShardingRules, batch_sharding,
                              build_mesh, moe_apply, pipeline_apply,
                              ring_attention, shard_pytree,
                              stack_stage_params)


def test_mesh_config_resolution(eight_device_mesh):
    cfg = MeshConfig(data=-1, tensor=2).resolved(8)
    assert cfg.data == 4 and cfg.tensor == 2
    with pytest.raises(ValueError):
        MeshConfig(data=3, tensor=2).resolved(8)


def test_build_mesh_axes(eight_device_mesh):
    mesh = build_mesh(MeshConfig(data=2, fsdp=2, tensor=2),
                      eight_device_mesh)
    assert mesh.shape["data"] == 2
    assert mesh.shape["fsdp"] == 2
    assert mesh.shape["tensor"] == 2
    assert mesh.shape["pipe"] == 1


def test_sharding_rules_spec():
    rules = ShardingRules()
    # embed -> fsdp is already used by batch, so spec() dedups it to None
    # rather than binding fsdp to a second dimension (an invalid spec).
    spec = rules.spec(("batch", "seq", "embed"))
    assert spec == P(("data", "fsdp"), "seq", None)


def test_sharding_rules_no_duplicate_axis():
    rules = ShardingRules()
    # embed -> fsdp, batch -> (data, fsdp): fsdp must not appear twice.
    spec = rules.spec(("batch", "embed"))
    flat = []
    for part in spec:
        if isinstance(part, tuple):
            flat.extend(part)
        elif part is not None:
            flat.append(part)
    assert len(flat) == len(set(flat))


def test_shard_pytree_places_params(eight_device_mesh):
    mesh = build_mesh(MeshConfig(data=2, tensor=4), eight_device_mesh)
    params = {"w": jnp.ones((16, 32)), "b": jnp.ones((32,))}
    axes = {"w": ("embed", "mlp"), "b": ("mlp",)}
    sharded = shard_pytree(params, axes, mesh)
    assert sharded["w"].sharding.spec == P(None, "tensor")
    # 4-way tensor sharding of dim 32 -> shard dim 8
    assert sharded["w"].addressable_shards[0].data.shape == (16, 8)


def test_pipeline_matches_sequential(eight_device_mesh):
    mesh = build_mesh(MeshConfig(data=2, pipe=4), eight_device_mesh)
    n_stages, d = 4, 16
    key = jax.random.PRNGKey(0)
    ws = [jax.random.normal(jax.random.fold_in(key, i), (d, d)) * 0.1
          for i in range(n_stages)]
    stage_params = stack_stage_params([{"w": w} for w in ws])

    def stage_fn(params, x):
        return jnp.tanh(x @ params["w"])

    x = jax.random.normal(key, (8, d))
    out = pipeline_apply(stage_fn, stage_params, x, mesh,
                         num_microbatches=2)
    expected = x
    for w in ws:
        expected = jnp.tanh(expected @ w)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected),
                               rtol=2e-5, atol=2e-5)


def test_pipeline_single_stage_short_circuit(eight_device_mesh):
    mesh = build_mesh(MeshConfig(data=8), eight_device_mesh)
    stage_params = stack_stage_params([{"w": jnp.eye(4)}])
    out = pipeline_apply(lambda p, x: x @ p["w"], stage_params,
                         jnp.ones((4, 4)), mesh, num_microbatches=2)
    np.testing.assert_allclose(np.asarray(out), np.ones((4, 4)))


@pytest.mark.parametrize("causal", [True, False])
def test_ring_attention_matches_full(eight_device_mesh, causal):
    mesh = build_mesh(MeshConfig(data=2, seq=4), eight_device_mesh)
    B, L, H, D = 4, 32, 2, 8
    key = jax.random.PRNGKey(1)
    q, k, v = (jax.random.normal(jax.random.fold_in(key, i), (B, L, H, D))
               for i in range(3))
    out = ring_attention(q, k, v, mesh, causal=causal)

    # Reference: dense attention.
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) / np.sqrt(D)
    if causal:
        mask = jnp.tril(jnp.ones((L, L), bool))
        scores = jnp.where(mask[None, None], scores, -1e30)
    ref = jnp.einsum("bhqk,bkhd->bqhd", jax.nn.softmax(scores, -1), v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)


def test_ring_attention_single_shard(eight_device_mesh):
    mesh = build_mesh(MeshConfig(data=8), eight_device_mesh)
    B, L, H, D = 2, 16, 2, 4
    q = k = v = jnp.ones((B, L, H, D))
    out = ring_attention(q, k, v, mesh, causal=True)
    np.testing.assert_allclose(np.asarray(out), 1.0, rtol=1e-5)


def test_moe_routes_and_preserves_shape(eight_device_mesh):
    mesh = build_mesh(MeshConfig(data=1, expert=4), eight_device_mesh[:4])
    T, d, E = 64, 8, 4
    key = jax.random.PRNGKey(2)
    x = jax.random.normal(key, (T, d))
    rw = jax.random.normal(jax.random.fold_in(key, 1), (d, E))
    # identity experts scaled by (i+1): output distinguishes routing
    expert_params = {"scale": jnp.arange(1.0, E + 1)[:, None]}
    out = moe_apply(x, rw, expert_params,
                    lambda p, toks: toks * p["scale"], mesh,
                    capacity_factor=4.0)
    assert out.shape == (T, d)
    # Every token got routed (capacity ample): out = x + gate * scale_e * x
    gates = jax.nn.softmax(x @ rw, -1)
    idx = jnp.argmax(gates, -1)
    gv = jnp.take_along_axis(gates, idx[:, None], -1)[:, 0]
    expected = x + gv[:, None] * x * (idx + 1.0)[:, None]
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected),
                               rtol=1e-4, atol=1e-4)


def test_mesh_config_two_wildcards_rejected():
    with pytest.raises(ValueError, match="at most one axis may be -1"):
        MeshConfig(data=-1, fsdp=-1).resolved(8)


def test_mesh_config_wildcard_not_divisible():
    with pytest.raises(ValueError, match="not divisible"):
        MeshConfig(data=-1, tensor=3).resolved(8)


def test_sharding_drops_size_one_axes(eight_device_mesh):
    # batch -> ("data", "fsdp") and mlp -> tensor, but on a data-only
    # mesh fsdp/tensor are size 1: both must drop out of the spec.
    mesh = build_mesh(MeshConfig(data=8), eight_device_mesh)
    sh = ShardingRules().sharding(mesh, ("batch", "mlp"))
    assert sh.spec in (P("data", None), P(("data",), None))


def test_sharding_rules_strict_raises_on_typo():
    rules = ShardingRules()
    with pytest.raises(ValueError, match="unknown logical axis"):
        rules.spec(("batch", "typo"), strict=True)
    # the default path replicates the unknown dimension instead
    assert rules.spec(("batch", "typo")) == P(("data", "fsdp"), None)


def test_sharding_strict_rejects_mesh_geometry_drift(eight_device_mesh):
    from jax.sharding import Mesh
    mesh = Mesh(np.asarray(eight_device_mesh), ("rows",))
    rules = ShardingRules()
    with pytest.raises(ValueError, match="absent from this mesh"):
        rules.sharding(mesh, ("batch",), strict=True)
    # non-strict: geometry drift quietly degrades to replication
    assert rules.sharding(mesh, ("batch",)).spec == P(None)


def test_shard_pytree_mismatched_axes_tree_names_path(eight_device_mesh):
    mesh = build_mesh(MeshConfig(data=2, tensor=4), eight_device_mesh)
    params = {"w": jnp.ones((16, 32)), "b": jnp.ones((32,))}
    with pytest.raises(ValueError, match="does not mirror tree at") as ei:
        shard_pytree(params, {"w": ("embed", "mlp")}, mesh)
    assert "missing keys ['b']" in str(ei.value)
    with pytest.raises(ValueError, match="does not mirror tree at") as ei:
        shard_pytree(params, ("embed", "mlp"), mesh)
    assert "tree has a dict" in str(ei.value)
    # a strict-mode rules error passes through untranslated: the shapes
    # mirror fine, the axis name is what is wrong
    with pytest.raises(ValueError, match="unknown logical axis"):
        shard_pytree(params, {"w": ("embed", "typo"), "b": ("mlp",)},
                     mesh, strict=True)
