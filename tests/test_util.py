"""Tests for ray_tpu.util: ActorPool, Queue, multiprocessing Pool.

Models the reference's tests for ``python/ray/util/actor_pool.py``,
``util/queue.py`` and ``util/multiprocessing``.
"""

import pytest

import ray_tpu
from ray_tpu.util import ActorPool
from ray_tpu.util.multiprocessing import Pool
from ray_tpu.util.queue import Empty, Full, Queue


@ray_tpu.remote
class _Doubler:
    def double(self, v):
        return 2 * v


@pytest.fixture
def pool4(ray_start_regular):
    return ActorPool([_Doubler.remote() for _ in range(4)])


def test_actor_pool_map_ordered(ray_start_regular, pool4):
    out = list(pool4.map(lambda a, v: a.double.remote(v), range(10)))
    assert out == [2 * i for i in range(10)]


def test_actor_pool_map_unordered(ray_start_regular, pool4):
    out = list(pool4.map_unordered(lambda a, v: a.double.remote(v), range(10)))
    assert sorted(out) == [2 * i for i in range(10)]


def test_actor_pool_submit_get_next(ray_start_regular, pool4):
    for i in range(6):
        pool4.submit(lambda a, v: a.double.remote(v), i)
    assert pool4.has_next()
    assert [pool4.get_next() for _ in range(6)] == [0, 2, 4, 6, 8, 10]
    assert not pool4.has_next()
    with pytest.raises(StopIteration):
        pool4.get_next()


def test_actor_pool_more_tasks_than_actors(ray_start_regular):
    pool = ActorPool([_Doubler.remote()])
    out = list(pool.map(lambda a, v: a.double.remote(v), range(5)))
    assert out == [0, 2, 4, 6, 8]


def test_actor_pool_push_pop(ray_start_regular, pool4):
    a = pool4.pop_idle()
    assert a is not None
    pool4.push(a)
    with pytest.raises(ValueError):
        pool4.push(a)


def test_queue_basic(ray_start_regular):
    q = Queue()
    assert q.empty()
    q.put(1)
    q.put(2)
    assert q.qsize() == 2
    assert q.get() == 1
    assert q.get() == 2
    assert q.empty()


def test_queue_maxsize_and_nowait(ray_start_regular):
    q = Queue(maxsize=2)
    q.put_nowait(1)
    q.put_nowait(2)
    assert q.full()
    with pytest.raises(Full):
        q.put_nowait(3)
    with pytest.raises(Full):
        q.put(3, timeout=0.05)
    assert q.get_nowait() == 1
    q.put(3)
    assert q.get_nowait_batch(2) == [2, 3]
    with pytest.raises(Empty):
        q.get_nowait()
    with pytest.raises(Empty):
        q.get(timeout=0.05)


def test_queue_cross_task(ray_start_regular):
    q = Queue()

    @ray_tpu.remote
    def producer(q, n):
        for i in range(n):
            q.put(i)
        return n

    ray_tpu.get(producer.remote(q, 5))
    assert [q.get(timeout=5) for _ in range(5)] == list(range(5))


def _sq(x):
    return x * x


def _add(x, y):
    return x + y


def test_mp_pool_map(ray_start_regular):
    with Pool(processes=4) as p:
        assert p.map(_sq, range(10)) == [i * i for i in range(10)]


def test_mp_pool_apply_and_starmap(ray_start_regular):
    with Pool(processes=2) as p:
        assert p.apply(_add, (3, 4)) == 7
        r = p.apply_async(_add, (1, 2))
        assert r.get(timeout=30) == 3
        assert p.starmap(_add, [(1, 2), (3, 4)]) == [3, 7]


def test_mp_pool_imap(ray_start_regular):
    with Pool(processes=2) as p:
        assert list(p.imap(_sq, range(6), chunksize=2)) == [i * i for i in range(6)]
        assert sorted(p.imap_unordered(_sq, range(6))) == sorted(
            i * i for i in range(6))


def _boom(x):
    raise RuntimeError("boom")


def test_mp_pool_error_propagates(ray_start_regular):
    with Pool(processes=2) as p:
        with pytest.raises(Exception):
            p.map(_boom, [1])


def test_mp_pool_closed_rejects(ray_start_regular):
    p = Pool(processes=1)
    p.close()
    with pytest.raises(ValueError):
        p.map(_sq, [1])


@ray_tpu.remote
class _Flaky:
    def work(self, v):
        if v == 1:
            raise RuntimeError("bad input")
        return v


def test_actor_pool_survives_task_error(ray_start_regular):
    pool = ActorPool([_Flaky.remote()])
    pool.submit(lambda a, v: a.work.remote(v), 1)
    pool.submit(lambda a, v: a.work.remote(v), 2)
    with pytest.raises(Exception):
        pool.get_next()
    # The actor was returned to the pool before the error re-raised, so the
    # queued submit still runs.
    assert pool.get_next() == 2


def test_mp_pool_async_callback_fires_without_get(ray_start_regular):
    import time as _time
    seen = []
    with Pool(processes=2) as p:
        p.map_async(_sq, [1, 2, 3], chunksize=3, callback=seen.append)
        deadline = _time.monotonic() + 30
        while not seen and _time.monotonic() < deadline:
            _time.sleep(0.01)
    assert seen == [[1, 4, 9]]


def test_mp_pool_imap_checks_closed_at_call_time(ray_start_regular):
    p = Pool(processes=1)
    p.close()
    with pytest.raises(ValueError):
        p.imap(_sq, [1])
