"""Memory monitor / OOM admission guard (memory_monitor.h role)."""

import time

import pytest

import ray_tpu
from ray_tpu._private.memory_monitor import MemoryMonitor


def test_monitor_thresholds_and_snapshot():
    usage = {"used": 10, "total": 100}
    m = MemoryMonitor(threshold=0.5, refresh_ms=10,
                      usage_reader=lambda: (usage["used"], usage["total"]))
    assert not m.is_over_threshold()
    snap = m.snapshot()
    assert snap["used_frac"] == 0.1 and not snap["over_threshold"]
    usage["used"] = 60
    m._sample()
    assert m.is_over_threshold()
    assert m.snapshot()["over_threshold"]
    usage["used"] = 20
    m._sample()
    assert not m.is_over_threshold()


def test_monitor_disabled_never_blocks():
    m = MemoryMonitor(threshold=0.0, refresh_ms=0,
                      usage_reader=lambda: (100, 100))
    assert not m.enabled
    assert not m.is_over_threshold()


def test_monitor_background_sampling():
    usage = {"used": 0, "total": 100}
    m = MemoryMonitor(threshold=0.5, refresh_ms=10,
                      usage_reader=lambda: (usage["used"], usage["total"]))
    m.start()
    try:
        usage["used"] = 99
        deadline = time.monotonic() + 5
        while not m.is_over_threshold() and time.monotonic() < deadline:
            time.sleep(0.02)
        assert m.is_over_threshold()
    finally:
        m.stop()


def test_system_usage_reads_something():
    used, total = MemoryMonitor._system_usage()
    assert total > 0 and 0 <= used <= total


def test_over_threshold_daemon_sheds_admissions():
    """A pushed task hitting an over-threshold executor gets a spillback
    reply (saturated: zero availability advertised), not admission."""
    from ray_tpu.cluster_utils import ProcessCluster
    from ray_tpu.protocol import pb

    ray_tpu.shutdown()
    c = ProcessCluster(num_daemons=1, num_cpus=4)
    ray_tpu.init(address=c.address)
    try:
        rt = ray_tpu._private.worker.global_worker().runtime
        # The DRIVER runtime owns the executor half too — but registers
        # zero executor resources; grant some so admission reaches the
        # memory check (and phase 2 can actually execute locally).
        from ray_tpu._private.resources import NodeResources, ResourceSet
        rt.local_node.resources = NodeResources(ResourceSet({"CPU": 4}))
        # Force the monitor over threshold and push a task through the
        # real handler, capturing the wire reply.
        rt.memory_monitor = MemoryMonitor(
            threshold=0.5, refresh_ms=10,
            usage_reader=lambda: (99, 100))

        class _Ctx:
            body = b""
            replies = []

            def reply(self, body=b"", raw=None):
                self.replies.append(body)

        import cloudpickle
        fn_hash = rt._export_callable(lambda: 1)
        msg = pb.TaskSpecMsg(task_id=b"T" * 16, job_id=b"J" * 4,
                             function_name="f", num_returns=1,
                             return_ids=[b"T" * 16 + b"\0" * 4],
                             fn_hash=fn_hash,
                             args_pickle=cloudpickle.dumps(((), {})))
        msg.resources.amounts["CPU"] = 1.0
        ctx = _Ctx()
        ctx.body = msg.SerializeToString()
        rt._handle_push_task(ctx)
        assert ctx.replies, "no reply sent"
        rep = pb.PushTaskReply()
        rep.ParseFromString(ctx.replies[0])
        assert rep.status == "spillback"
        assert not dict(rep.available.amounts)  # saturated: zero avail
        # pressure released -> the same push is admitted
        rt.memory_monitor = MemoryMonitor(
            threshold=0.5, refresh_ms=10, usage_reader=lambda: (1, 100))
        ctx2 = _Ctx()
        ctx2.replies = []
        ctx2.body = ctx.body
        rt._handle_push_task(ctx2)
        deadline = time.monotonic() + 20
        while not ctx2.replies and time.monotonic() < deadline:
            time.sleep(0.05)
        # ADMITTED and executed: the reply is a completion
        assert ctx2.replies
        rep2 = pb.PushTaskReply()
        rep2.ParseFromString(ctx2.replies[0])
        assert rep2.status == "ok"
        assert not rep2.error_pickle
    finally:
        ray_tpu.shutdown()
        c.shutdown()
