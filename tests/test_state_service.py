"""Tests for the C++ state service + Python client (the control plane's
GCS analogue). Each test spawns a real daemon process and talks protobuf
over TCP — nothing in-process, matching how the reference tests its GCS
(python/ray/tests/test_gcs_fault_tolerance.py)."""

import os
import signal
import threading
import time

import pytest

from ray_tpu._private.state_client import StateClient, start_state_service
from ray_tpu.protocol import pb


@pytest.fixture()
def svc(tmp_path):
    proc, addr = start_state_service(
        data_dir=str(tmp_path / "state"), heartbeat_timeout_ms=1500,
        snapshot_interval_s=300)
    client = StateClient(addr)
    yield proc, addr, client, str(tmp_path / "state")
    client.close()
    if proc.poll() is None:
        proc.terminate()
        proc.wait(timeout=10)


def _node(node_id=b"n" * 16, addr="127.0.0.1:7001", cpus=4.0):
    info = pb.NodeInfo(node_id=node_id, address=addr)
    info.total.amounts["CPU"] = cpus
    info.available.amounts["CPU"] = cpus
    return info


def test_ping_and_stats(svc):
    _, _, client, _ = svc
    assert client.ping() > 0
    stats = client.stats()
    assert stats["nodes_total"] == 0
    assert stats["cluster_epoch"] >= 1


def test_node_register_heartbeat_list(svc):
    _, _, client, _ = svc
    client.register_node(_node())
    nodes = client.list_nodes()
    assert len(nodes) == 1 and nodes[0].alive
    assert nodes[0].address == "127.0.0.1:7001"
    assert client.heartbeat(b"n" * 16, {"CPU": 2.5})
    nodes = client.list_nodes()
    assert nodes[0].available.amounts["CPU"] == 2.5
    # Unknown node is told to re-register.
    assert not client.heartbeat(b"x" * 16)


def test_heartbeat_timeout_marks_dead_and_publishes(svc):
    _, addr, client, _ = svc
    events = []
    done = threading.Event()

    def on_event(ev):
        events.append(ev)
        if ev.kind == "NODE_DEAD":
            done.set()

    client.subscribe(["nodes"], on_event)
    client.register_node(_node())
    assert done.wait(timeout=6), "NODE_DEAD was not published"
    nodes = client.list_nodes()
    assert not nodes[0].alive
    assert "heartbeat" in nodes[0].death_reason
    kinds = [e.kind for e in events]
    assert "NODE_ADDED" in kinds and "NODE_DEAD" in kinds


def test_kv_roundtrip(svc):
    _, _, client, _ = svc
    assert client.kv_put(b"k1", b"v1")
    assert client.kv_get(b"k1") == b"v1"
    assert client.kv_get(b"k1", namespace=b"other") is None
    assert not client.kv_put(b"k1", b"v2", overwrite=False)
    assert client.kv_get(b"k1") == b"v1"
    client.kv_put(b"k2", b"v2")
    client.kv_put(b"j1", b"x", namespace=b"other")
    assert sorted(client.kv_keys(b"k")) == [b"k1", b"k2"]
    assert client.kv_del(b"k1")
    assert client.kv_get(b"k1") is None


def test_object_directory(svc):
    _, _, client, _ = svc
    client.register_node(_node(b"a" * 16, "127.0.0.1:7001"))
    client.register_node(_node(b"b" * 16, "127.0.0.1:7002"))
    client.add_location(b"o" * 20, b"a" * 16, size=123)
    client.add_location(b"o" * 20, b"b" * 16)
    rep = client.get_locations(b"o" * 20)
    assert set(rep.node_ids) == {b"a" * 16, b"b" * 16}
    assert set(rep.addresses) == {"127.0.0.1:7001", "127.0.0.1:7002"}
    assert rep.size == 123
    # Dead node's locations vanish.
    client.mark_node_dead(b"a" * 16, "test")
    rep = client.get_locations(b"o" * 20)
    assert list(rep.node_ids) == [b"b" * 16]


def test_actor_table_and_named_resolution(svc):
    _, _, client, _ = svc
    info = pb.ActorInfo(actor_id=b"A" * 16, name="counter",
                        namespace="default", class_name="Counter",
                        state="ALIVE", address="127.0.0.1:7001")
    client.register_actor(info)
    got = client.get_named_actor("counter")
    assert got is not None and got.class_name == "Counter"
    assert client.get_named_actor("counter", "other") is None
    # Duplicate name rejected while alive.
    dup = pb.ActorInfo(actor_id=b"B" * 16, name="counter",
                       namespace="default", class_name="Counter2",
                       state="PENDING")
    from ray_tpu._private.rpc import RpcRemoteError
    with pytest.raises(RpcRemoteError, match="name already taken"):
        client.register_actor(dup)
    # Death frees the name.
    info.state = "DEAD"
    client.update_actor(info)
    assert client.get_named_actor("counter") is None
    client.register_actor(dup)
    assert client.get_named_actor("counter").class_name == "Counter2"


def test_pubsub_custom_channel(svc):
    _, addr, client, _ = svc
    got = threading.Event()
    payloads = []

    def handler(ev):
        payloads.append((ev.kind, ev.payload))
        got.set()

    client.subscribe(["my-channel"], handler)
    other = StateClient(addr)
    other.publish("my-channel", "HELLO", b"payload")
    assert got.wait(timeout=5)
    assert payloads == [("HELLO", b"payload")]
    other.close()


def test_head_restart_rebuilds_state(svc, tmp_path):
    """Kill + restart the head: KV, actor table, named actors survive
    (the reference's GCS fault-tolerance contract)."""
    proc, addr, client, data_dir = svc
    client.register_node(_node())
    client.kv_put(b"persist-key", b"persist-value")
    client.register_actor(pb.ActorInfo(
        actor_id=b"A" * 16, name="survivor", namespace="default",
        class_name="Counter", state="ALIVE", address="127.0.0.1:7001"))
    epoch1 = client.stats()["cluster_epoch"]
    # Hard kill (no graceful snapshot — journal must carry the state).
    proc.send_signal(signal.SIGKILL)
    proc.wait(timeout=10)
    client.close()

    proc2, addr2 = start_state_service(
        data_dir=data_dir, heartbeat_timeout_ms=1500)
    try:
        c2 = StateClient(addr2)
        assert c2.kv_get(b"persist-key") == b"persist-value"
        got = c2.get_named_actor("survivor")
        assert got is not None and got.class_name == "Counter"
        nodes = c2.list_nodes()
        assert len(nodes) == 1
        assert c2.stats()["cluster_epoch"] == epoch1 + 1
        # The restored node is recognized when it resumes heartbeating.
        assert c2.heartbeat(b"n" * 16)
        c2.close()
    finally:
        proc2.terminate()
        proc2.wait(timeout=10)


def test_pg_and_job_tables(svc):
    _, _, client, _ = svc
    pg = pb.PgInfo(pg_id=b"P" * 16, name="mypg", strategy="PACK",
                   state="CREATED")
    b0 = pg.bundles.add()
    b0.amounts["CPU"] = 2.0
    pg.bundle_nodes.append(b"n" * 16)
    client.register_pg(pg)
    pgs = client.list_pgs()
    assert len(pgs) == 1 and pgs[0].strategy == "PACK"
    assert pgs[0].bundles[0].amounts["CPU"] == 2.0
    client.remove_pg(b"P" * 16)
    assert client.list_pgs() == []

    client.register_job(pb.JobInfo(job_id=b"J" * 4, state="RUNNING",
                                   driver_address="127.0.0.1:9999"))
    jobs = client.list_jobs()
    assert len(jobs) == 1 and jobs[0].state == "RUNNING"


def test_concurrent_kv_clients(svc):
    """Many clients hammer the KV concurrently; single-threaded epoll server
    must serialize without loss."""
    _, addr, _, _ = svc
    n_clients, n_keys = 8, 50
    errs = []

    def worker(i):
        try:
            c = StateClient(addr)
            for k in range(n_keys):
                c.kv_put(f"c{i}-k{k}".encode(), str(k).encode())
            for k in range(n_keys):
                assert c.kv_get(f"c{i}-k{k}".encode()) == str(k).encode()
            c.close()
        except Exception as e:  # noqa: BLE001
            errs.append(e)

    threads = [threading.Thread(target=worker, args=(i,))
               for i in range(n_clients)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    assert not errs, errs
    c = StateClient(addr)
    assert len(c.kv_keys(b"c")) == n_clients * n_keys
    c.close()
