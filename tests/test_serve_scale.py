"""Interactive-scale serving tests: replica-side continuous batching,
latency-aware routing, SLO autoscaling, and overload shedding.

Covers the serving plane end to end — pad-to-bucket recompile avoidance,
per-item error isolation inside a batch, queue-deadline shedding (the
"never hangs" contract), the power-of-two-choices router, the
scale-from-target autoscaler fix, and two deterministic chaos drills
(routing away from a chaos-delayed replica; the SLO autoscaler tripping
under injected latency within a bounded number of ticks).
"""

import threading
import time
import urllib.error
import urllib.request

import jax
import jax.numpy as jnp
import pytest

import ray_tpu
from ray_tpu import chaos, serve
from ray_tpu._private.backoff import BreakerBoard
from ray_tpu._private.config import _config
from ray_tpu.serve._private.router import Router


@pytest.fixture
def serve_instance(ray_start_regular):
    serve.start()
    yield
    serve.shutdown()


def _burst(handle, values, timeout=60):
    """Fire all values concurrently through the handle; returns a list of
    results or the exception each caller got."""
    out = [None] * len(values)
    barrier = threading.Barrier(len(values))

    def call(i, v):
        barrier.wait()
        try:
            out[i] = handle.remote(v).result(timeout=timeout)
        except BaseException as e:  # noqa: BLE001 - tests inspect errors
            out[i] = e

    threads = [threading.Thread(target=call, args=(i, v))
               for i, v in enumerate(values)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=timeout)
    return out


class _Driver:
    """Closed-loop load: n threads calling the handle back to back."""

    def __init__(self, handle, n_threads=4):
        self._h = handle
        self._stop = threading.Event()
        self.errors = []
        self._threads = [threading.Thread(target=self._loop, daemon=True)
                         for _ in range(n_threads)]

    def _loop(self):
        i = 0
        while not self._stop.is_set():
            try:
                self._h.remote(i).result(timeout=30)
            except Exception as e:  # noqa: BLE001 - drills tolerate sheds
                self.errors.append(e)
            i += 1

    def start(self):
        for t in self._threads:
            t.start()
        return self

    def stop(self):
        self._stop.set()
        for t in self._threads:
            t.join(timeout=30)


# -- continuous batching: pad-to-bucket recompile avoidance ----------------

_TRACE_SHAPES = []


@jax.jit
def _bucketed_fwd(xs):
    # Python side effects run only while jax TRACES (i.e. compiles) — the
    # list records one entry per distinct input shape.
    _TRACE_SHAPES.append(xs.shape)
    return xs * 2.0


@serve.deployment(max_batch_size=8, batch_wait_timeout_s=0.05,
                  pad_batch_to=(2, 4, 8))
class Bucketed:
    def __call__(self, items):
        xs = jnp.asarray([float(v) for v in items], dtype=jnp.float32)
        return [float(v) for v in _bucketed_fwd(xs)]


def test_pad_to_bucket_limits_recompiles(serve_instance):
    """Every batch the replica forms is padded to a configured bucket, so
    the jitted forward compiles at most len(buckets) times no matter how
    request-count varies burst to burst."""
    del _TRACE_SHAPES[:]
    h = serve.run(Bucketed.bind(), name="bucketed", route_prefix=None)
    for values in ([1, 2, 3], [5, 6], [1, 2, 3, 4, 5, 6], [9],
                   [1, 2, 3, 4, 5, 6, 7, 8]):
        results = _burst(h, values)
        assert results == [2 * v for v in values]
    assert len(_TRACE_SHAPES) >= 1
    assert set(_TRACE_SHAPES) <= {(2,), (4,), (8,)}, _TRACE_SHAPES
    # jit caches per shape: one trace per bucket, never per batch size.
    assert len(_TRACE_SHAPES) <= 3, _TRACE_SHAPES


# -- per-item error isolation ----------------------------------------------

@serve.deployment(max_batch_size=4, batch_wait_timeout_s=0.2)
class Picky:
    def __call__(self, items):
        if any(v == "poison" for v in items):
            raise ValueError("poisoned batch")
        return [v + "!" for v in items]


def test_batch_error_isolated_per_item(serve_instance):
    """A poisoned request fails alone (singleton re-run); its innocent
    batchmates still get their answers."""
    assert _config.get("serve_batch_retry_singletons")
    h = serve.run(Picky.bind(), name="picky", route_prefix=None)
    a, poison, b = _burst(h, ["a", "poison", "b"])
    assert a == "a!"
    assert b == "b!"
    # The poisoned caller gets its OWN error (the singleton re-run's
    # ValueError, riding the usual TaskError wrapper) — not a batch-level
    # tag, and the innocents above were not collateral.
    assert isinstance(poison, Exception)
    assert not isinstance(poison, serve.BatchExecutionError)
    assert "poisoned batch" in str(poison)


def test_batch_execution_error_tags_batch():
    """With singleton retry off, a failed multi-item batch delivers a
    BatchExecutionError naming the batch size and every member request id
    — callers can tell "my request was bad" from "I was collateral"."""

    @serve.batch(max_batch_size=4, batch_wait_timeout_s=0.25)
    def explode(items):
        raise RuntimeError("boom")

    old = _config.get("serve_batch_retry_singletons")
    _config.set("serve_batch_retry_singletons", False)
    try:
        errs = [None] * 3
        barrier = threading.Barrier(3)

        def call(i):
            barrier.wait()
            try:
                explode(i)
            except BaseException as e:  # noqa: BLE001
                errs[i] = e

        threads = [threading.Thread(target=call, args=(i,))
                   for i in range(3)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
        assert all(isinstance(e, serve.BatchExecutionError) for e in errs)
        tag = errs[0]
        assert tag.batch_size == 3
        assert len(tag.request_ids) == 3
        assert isinstance(tag.cause, RuntimeError)
        assert "batch of 3" in str(tag)
    finally:
        _config.set("serve_batch_retry_singletons", old)

    # A singleton batch gets its own error RAW — no batch-level wrapper.
    with pytest.raises(RuntimeError, match="boom"):
        explode("solo")


# -- queue-deadline shedding -----------------------------------------------

@serve.deployment(max_batch_size=2, batch_wait_timeout_s=0.005)
class Sluggish:
    def __call__(self, items):
        time.sleep(0.08)
        return list(items)


def test_queue_deadline_sheds_not_hangs(serve_instance):
    """Flooding a slow replica: requests that age past
    serve_queue_deadline_ms are shed with ServeOverloadedError (carrying a
    Retry-After hint); every caller returns promptly — nobody hangs."""
    old = _config.get("serve_queue_deadline_ms")
    _config.set("serve_queue_deadline_ms", 150.0)
    try:
        h = serve.run(Sluggish.bind(), name="sluggish", route_prefix=None)
        t0 = time.monotonic()
        results = _burst(h, [[i] for i in range(16)], timeout=30)
        elapsed = time.monotonic() - t0
    finally:
        _config.set("serve_queue_deadline_ms", old)
    assert elapsed < 20.0
    ok = [r for r in results if isinstance(r, list)]
    shed = [r for r in results if isinstance(r, serve.ServeOverloadedError)]
    assert len(ok) + len(shed) == 16, results
    assert ok, results
    assert shed, results
    assert all(e.retry_after_s > 0 for e in shed)


# -- router: power-of-two-choices scoring + shedding (unit) ----------------

def _bare_router(tags, p95=None, queue_est=None, target=0.0,
                 max_concurrent=100):
    r = object.__new__(Router)
    r._deployment_name = "unit"
    r._lock = threading.Condition()
    r._replicas = [f"replica:{t}" for t in tags]
    r._tags = list(tags)
    r._max_concurrent = max_concurrent
    r._in_flight = {}
    r._p95_ms = dict(p95 or {})
    r._queue_est_ms = dict(queue_est or {})
    r._target_latency_ms = target
    r._breakers = BreakerBoard()
    return r


def test_router_prefers_low_latency_replica():
    router = _bare_router(["slow", "fast"],
                          p95={"slow": 50.0, "fast": 1.0})
    for _ in range(20):
        _, tag = router._pick(timeout=1)
        assert tag == "fast"
        router._release(tag)
    # Load still matters: pile in-flight onto the fast replica until its
    # score crosses the slow one's, and the pick flips.
    router._in_flight["fast"] = 99
    _, tag = router._pick(timeout=1)
    assert tag == "slow"


def test_router_breaker_removes_replica():
    router = _bare_router(["a", "b"])
    for _ in range(int(_config.get("circuit_failure_threshold"))):
        router._breakers.record_failure("a")
    for _ in range(10):
        _, tag = router._pick(timeout=1)
        assert tag == "b"
        router._release(tag)


def test_router_sheds_when_all_over_budget():
    router = _bare_router(["a", "b"],
                          queue_est={"a": 500.0, "b": 300.0},
                          target=100.0)
    with pytest.raises(serve.ServeOverloadedError) as info:
        router._pick(timeout=1)
    assert info.value.retry_after_s > 0


def test_router_pick_is_bounded():
    """No replicas and a timeout: the pick raises instead of hanging."""
    router = _bare_router([])
    t0 = time.monotonic()
    with pytest.raises(TimeoutError):
        router._pick(timeout=0.3)
    assert time.monotonic() - t0 < 5.0


# -- autoscaler: scale from target, not live count (unit) ------------------

def test_autoscale_scales_from_target_not_live():
    """While a scale-up is in flight the live count lags the target;
    desired must be computed from the target or every tick over-requests
    again (overshoot/oscillation)."""
    from ray_tpu.serve._private.deployment_state import (DeploymentState,
                                                         ReplicaInfo)
    from ray_tpu.serve.controller import ServeController

    ctrl = object.__new__(ServeController)
    ctrl._autoscale_state = {}
    state = DeploymentState("scaling")
    state.config = serve.DeploymentConfig(
        autoscaling_config=serve.AutoscalingConfig(
            min_replicas=1, max_replicas=20,
            target_num_ongoing_requests_per_replica=1.0,
            upscale_delay_s=0.0, downscale_delay_s=3600.0,
            smoothing_factor=2.0))
    # Scale-up in progress: 4 replicas requested, only 1 live yet.
    state.target_replicas = 4
    state.replicas = [ReplicaInfo("scaling#0", None, "v1")]
    metrics = {"total_ongoing": 8.0, "replicas": {}, "p95_ms": 0.0}

    ServeController._autoscale(ctrl, state, metrics)
    # From target=4: error=2 -> desired = 4*(1+2*(2-1)) = 12.  The old
    # live-count policy computed 1*(1+2*(8-1)) = 15 (overshoot).
    assert state.target_replicas == 12

    # Re-running with the same demand while replicas are STILL starting
    # must not keep inflating the target.
    for _ in range(3):
        ServeController._autoscale(ctrl, state, metrics)
    assert state.target_replicas == 12


def test_long_poll_notify_if_changed_dedups():
    from ray_tpu.serve._private.long_poll import LongPollHost
    host = LongPollHost()
    assert host.notify_if_changed("k", {"a": 1}) is True
    snap = dict(host._snapshot_ids)
    assert host.notify_if_changed("k", {"a": 1}) is False
    assert host._snapshot_ids == snap  # no listener wakeup for a no-op
    assert host.notify_if_changed("k", {"a": 2}) is True


# -- chaos drill: routing away from a delayed replica ----------------------

@serve.deployment(num_replicas=2)
class Steady:
    def __call__(self, x):
        return x


def _replica_totals(handles):
    metrics = [ray_tpu.get(h.get_metrics.remote(), timeout=10)
               for h in handles]
    return {m["replica_tag"]: m["num_total_requests"] for m in metrics}


def test_chaos_delay_shifts_routing_to_healthy_replica(serve_instance):
    """A deterministic 50ms chaos delay on one of two replicas: the
    router's latency-aware scoring moves >= 90% of traffic to the healthy
    one once its published execute p95 reflects the injury."""
    controller = serve.start()
    h = serve.run(Steady.options(name="reroute").bind(), route_prefix=None)
    info = ray_tpu.get(controller.get_replica_handles.remote("reroute"))
    tags, handles = info["tags"], info["handles"]
    assert len(tags) == 2
    slow_tag, healthy_tag = tags[0], tags[1]
    chaos.configure(
        20260805, f"serve.replica.execute[replica={slow_tag}]@1+=delay(0.05)")
    driver = _Driver(h, n_threads=4).start()
    try:
        # Learning phase: wait (bounded) until the router has seen the
        # slow replica's published p95 via long-poll membership.
        deadline = time.monotonic() + 20.0
        while time.monotonic() < deadline:
            router = h._router
            if router is not None and \
                    router._p95_ms.get(slow_tag, 0) >= 10:
                break
            time.sleep(0.1)
        else:
            pytest.fail("router never learned the slow replica's p95")
        before = _replica_totals(handles)
        time.sleep(2.0)
        after = _replica_totals(handles)
    finally:
        driver.stop()
        chaos.clear()
    healthy_delta = after[healthy_tag] - before[healthy_tag]
    slow_delta = after[slow_tag] - before[slow_tag]
    total = healthy_delta + slow_delta
    assert total > 50, (before, after)
    assert healthy_delta / total >= 0.9, (before, after)


# -- chaos drill: SLO autoscaler trips under injected latency --------------

@serve.deployment
class SlightlySteady:
    def __call__(self, x):
        return x


def test_chaos_delay_trips_slo_autoscaler(serve_instance):
    """Injected 30ms latency against a 10ms SLO: the EWMA-smoothed p95
    sensor crosses the target and the autoscaler scales up within a
    bounded number of autoscale_tick() calls — and never past
    max_replicas (hysteresis/clamp contract)."""
    controller = serve.start()
    dep = SlightlySteady.options(
        name="slo_dep",
        autoscaling_config=serve.AutoscalingConfig(
            min_replicas=1, max_replicas=3, upscale_delay_s=0.0,
            downscale_delay_s=3600.0, smoothing_factor=1.0,
            target_latency_ms=10.0))
    h = serve.run(dep.bind(), name="slo", route_prefix=None)
    chaos.configure(
        20260805, "serve.replica.execute[deployment=slo_dep]@1+=delay(0.03)")
    driver = _Driver(h, n_threads=2).start()
    scaled = False
    try:
        for _ in range(50):
            ray_tpu.get(controller.autoscale_tick.remote(), timeout=30)
            target = serve.status()["slo_dep"]["target_replicas"]
            assert target <= 3
            if target >= 2:
                scaled = True
                break
            time.sleep(0.05)
    finally:
        driver.stop()
        chaos.clear()
    assert scaled, "SLO autoscaler never scaled up within 50 ticks"


# -- HTTP: overload presents as 503 + Retry-After --------------------------

@serve.deployment(max_batch_size=2, batch_wait_timeout_s=0.005,
                  max_concurrent_queries=32)
class VerySlow:
    def __call__(self, items):
        time.sleep(0.3)
        return list(items)


def test_proxy_maps_shed_to_503_retry_after(serve_instance):
    """Saturating a slow deployment over HTTP: shed requests come back as
    a prompt 503 with a Retry-After header — overload is never a hang."""
    old = _config.get("serve_queue_deadline_ms")
    _config.set("serve_queue_deadline_ms", 120.0)
    try:
        serve.run(VerySlow.bind(), name="shed", route_prefix="/shed")
        base = serve.start_http_proxy()
        out = []
        barrier = threading.Barrier(8)

        def post(i):
            barrier.wait()
            req = urllib.request.Request(
                f"{base}/shed", data=str(i).encode(),
                headers={"Content-Type": "application/json"})
            try:
                with urllib.request.urlopen(req, timeout=20) as resp:
                    out.append((resp.status, None))
            except urllib.error.HTTPError as e:
                out.append((e.code, e.headers.get("Retry-After")))

        threads = [threading.Thread(target=post, args=(i,))
                   for i in range(8)]
        t0 = time.monotonic()
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
        elapsed = time.monotonic() - t0
    finally:
        _config.set("serve_queue_deadline_ms", old)
    assert len(out) == 8, out
    assert elapsed < 25.0
    codes = {code for code, _ in out}
    assert codes <= {200, 503}, out
    assert 200 in codes, out
    retry_afters = [ra for code, ra in out if code == 503]
    assert retry_afters, out
    assert any(ra is not None and int(ra) >= 1 for ra in retry_afters), out
