"""Multi-host tensor plane tests: compiled collectives across daemon
PROCESSES (the reference's NCCL-group contract,
``nccl_collective_group.py:127`` + ``train/torch/config.py:54-96``), run
on CPU daemons with virtual devices + Gloo — the process-level analogue of
a multi-host TPU slice.
"""

import os
import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu.cluster_utils import ProcessCluster


@pytest.fixture()
def tp_cluster():
    ray_tpu.shutdown()
    c = ProcessCluster(num_daemons=2, num_cpus=2, tp_cpu_devices=2)
    ray_tpu.init(address=c.address)
    yield c
    ray_tpu.shutdown()
    c.shutdown()


@ray_tpu.remote(num_cpus=2)  # fills a daemon: one rank per process
class Rank:
    def __init__(self):
        self.pid = os.getpid()

    def where(self):
        return self.pid

    def plane_info(self):
        import jax
        return {"pid": self.pid,
                "process_index": jax.process_index(),
                "process_count": jax.process_count(),
                "local": len(jax.local_devices()),
                "global": len(jax.devices())}

    def run(self, op, tensor, group_name, **kw):
        from ray_tpu import collective as col
        return np.asarray(getattr(col, op)(tensor, group_name=group_name,
                                           **kw))

    def p2p(self, group_name, peer, send_first):
        from ray_tpu import collective as col
        if send_first:
            col.send(np.arange(4.0), peer, group_name)
            return None
        return np.asarray(col.recv(peer, group_name))


def _spawn_plane(cluster, n=2, gname="tp-test"):
    from ray_tpu.collective import create_collective_group
    actors = [Rank.remote() for _ in range(n)]
    pids = ray_tpu.get([a.where.remote() for a in actors], timeout=60)
    daemon_pids = {d["proc"].pid for d in cluster.daemons}
    assert set(pids) <= daemon_pids and len(set(pids)) == n, \
        f"ranks must land on distinct daemons: {pids}"
    create_collective_group(actors, n, list(range(n)), backend="xla",
                            group_name=gname)
    return actors


def test_cross_process_allreduce(tp_cluster):
    """Two daemon processes allreduce through ONE compiled collective:
    jax.process_count() == 2 in each rank proves the plane spans OS
    processes, not threads."""
    actors = _spawn_plane(tp_cluster, gname="tp-ar")
    infos = ray_tpu.get([a.plane_info.remote() for a in actors], timeout=120)
    assert {i["process_index"] for i in infos} == {0, 1}
    assert all(i["process_count"] == 2 for i in infos)
    assert all(i["global"] == 2 * i["local"] for i in infos)
    assert len({i["pid"] for i in infos}) == 2

    refs = [a.run.remote("allreduce", np.arange(8.0) + 10 * r, "tp-ar")
            for r, a in enumerate(actors)]
    out = ray_tpu.get(refs, timeout=120)
    expected = (np.arange(8.0)) + (np.arange(8.0) + 10)
    for o in out:
        np.testing.assert_allclose(o, expected)


def test_cross_process_ops(tp_cluster):
    actors = _spawn_plane(tp_cluster, gname="tp-ops")
    # broadcast from rank 1
    refs = [a.run.remote("broadcast", np.full(4, float(r)), "tp-ops",
                         src_rank=1)
            for r, a in enumerate(actors)]
    for o in ray_tpu.get(refs, timeout=120):
        np.testing.assert_allclose(o, np.full(4, 1.0))
    # allgather
    refs = [a.run.remote("allgather", np.full(3, float(r)), "tp-ops")
            for r, a in enumerate(actors)]
    for o in ray_tpu.get(refs, timeout=120):
        np.testing.assert_allclose(o, np.stack([np.zeros(3), np.ones(3)]))
    # reducescatter: rank r gets chunk r of the sum
    base = np.arange(4.0)
    refs = [a.run.remote("reducescatter", base + r, "tp-ops")
            for r, a in enumerate(actors)]
    out = ray_tpu.get(refs, timeout=120)
    full = (base) + (base + 1)
    np.testing.assert_allclose(out[0], full[:2])
    np.testing.assert_allclose(out[1], full[2:])


def test_cross_process_p2p(tp_cluster):
    actors = _spawn_plane(tp_cluster, gname="tp-p2p")
    s = actors[0].p2p.remote("tp-p2p", 1, True)
    r = actors[1].p2p.remote("tp-p2p", 0, False)
    got = ray_tpu.get([s, r], timeout=60)[1]
    np.testing.assert_allclose(got, np.arange(4.0))


@ray_tpu.remote(num_cpus=2)  # fills a daemon: one rank per process
class BulkRank:
    def send_big(self, group_name, peer, n):
        import numpy as _np

        from ray_tpu import collective as col
        col.send(_np.arange(n, dtype=_np.float32).reshape(-1, 1024),
                 peer, group_name)
        return True

    def recv_big(self, group_name, peer, n):
        import numpy as _np

        from ray_tpu import collective as col
        out = _np.asarray(col.recv(peer, group_name))
        assert out.shape == (n // 1024, 1024)
        assert float(out[-1, -1]) == float(n - 1)
        # bulk transfers must NOT transit the state-KV p2p namespace
        import ray_tpu as _rt
        state = _rt._private.worker.global_worker().runtime.state
        leftovers = [k for k in state.kv_keys(namespace=b"tplane-p2p")
                     if b">" in k]
        return leftovers


def test_cross_process_p2p_bulk_lane(tp_cluster):
    """A multi-MB tensor rides the raw-lane P2P_DATA path (NCCL-send
    role): correct bytes, nothing parked in the control-plane KV."""
    from ray_tpu.collective import create_collective_group
    actors = [BulkRank.remote() for _ in range(2)]
    create_collective_group(actors, 2, [0, 1], backend="xla",
                            group_name="tp-bulk")
    n = 2 * 1024 * 1024  # 8 MB of float32
    s = actors[0].send_big.remote("tp-bulk", 1, n)
    r = actors[1].recv_big.remote("tp-bulk", 0, n)
    sent, leftovers = ray_tpu.get([s, r], timeout=120)
    assert sent is True
    assert leftovers == []


# ---------------------------------------------------------------- trainer

def _make_dp_loop():
    """Returns the train loop as a CLOSURE: daemons cannot import this test
    module, so the loop must cloudpickle by value (same constraint as the
    reference — worker nodes need importable code or by-value functions)."""

    def _dp_loop(config):
        # Least-squares DP training over the session's (possibly
        # process-spanning) mesh; gradients allreduce inside the step.
        import jax
        import jax.numpy as jnp
        import numpy as np
        import time
        from jax.sharding import NamedSharding, PartitionSpec as P
        from ray_tpu.air.checkpoint import Checkpoint
        from ray_tpu.train import session

        mesh = session.get_mesh()
        rank = session.get_world_rank()
        start, w = 0, np.zeros(3, np.float32)
        ckpt = session.get_checkpoint()
        if ckpt is not None:
            d = ckpt.to_dict()
            start, w = d["step"], d["w"]

        rng = np.random.RandomState(rank)
        w_true = np.array([1.0, -2.0, 0.5], np.float32)
        X_local = rng.randn(8, 3).astype(np.float32)
        y_local = X_local @ w_true

        w_dev = jax.device_put(jnp.asarray(w), NamedSharding(mesh, P()))
        X = session.shard_batch(X_local)
        y = session.shard_batch(y_local)

        @jax.jit
        def step(w, X, y):
            loss, g = jax.value_and_grad(
                lambda w: jnp.mean((X @ w - y) ** 2))(w)
            return w - 0.2 * g, loss

        for s in range(start, config["steps"]):
            w_dev, loss = step(w_dev, X, y)
            if config.get("step_sleep"):
                time.sleep(config["step_sleep"])
            ck = None
            if rank == 0:
                ck = Checkpoint.from_dict(
                    {"step": s + 1, "w": np.asarray(w_dev)})
            session.report({"loss": float(loss), "step": s,
                            "procs": jax.process_count(),
                            "global_devices": len(jax.devices())},
                           checkpoint=ck)

    return _dp_loop


def test_trainer_dp_across_daemons(tp_cluster):
    """JaxTrainer DP step spanning two daemon PROCESSES: the session mesh
    covers both processes' devices and the gradient psum is compiled
    across them."""
    from ray_tpu.air.config import RunConfig, ScalingConfig
    from ray_tpu.train import JaxTrainer

    trainer = JaxTrainer(
        _make_dp_loop(), train_loop_config={"steps": 15},
        scaling_config=ScalingConfig(
            num_workers=2, resources_per_worker={"CPU": 2},
            placement_strategy="STRICT_SPREAD"),
        collective_backend="xla")
    res = trainer.fit()
    assert res.error is None, res.error
    assert res.metrics_history, "no results streamed"
    assert all(m["procs"] == 2 for m in res.metrics_history)
    assert all(m["global_devices"] == 4 for m in res.metrics_history)
    losses = [m["loss"] for m in res.metrics_history if m["step"] in (0, 14)]
    assert min(losses) < max(losses), "loss did not move"
    final = res.checkpoint.to_dict()
    np.testing.assert_allclose(final["w"], [1.0, -2.0, 0.5], atol=0.35)


@pytest.fixture()
def tp_cluster4():
    ray_tpu.shutdown()
    c = ProcessCluster(num_daemons=4, num_cpus=2, tp_cpu_devices=2)
    ray_tpu.init(address=c.address)
    yield c
    ray_tpu.shutdown()
    c.shutdown()


def test_trainer_resumes_across_daemon_kill(tp_cluster4):
    """SIGKILL one worker's daemon mid-training: the JAX coordination
    service fails the whole plane (its peers abort — device-owner
    processes are expendable), and the trainer restarts the group on the
    spare daemons FROM THE CHECKPOINT (reference contract:
    backend_executor.py:461-531 elastic restart)."""
    import threading
    from ray_tpu.air.config import FailureConfig, RunConfig, ScalingConfig
    from ray_tpu.train import JaxTrainer

    killed = threading.Event()

    trainer = JaxTrainer(
        _make_dp_loop(),
        train_loop_config={"steps": 8, "step_sleep": 0.4},
        scaling_config=ScalingConfig(
            num_workers=2, resources_per_worker={"CPU": 2},
            placement_strategy="STRICT_SPREAD"),
        run_config=RunConfig(failure_config=FailureConfig(max_failures=2)),
        collective_backend="xla")

    def kill_after_delay():
        time.sleep(6)  # group up + a few steps in
        for i, d in enumerate(tp_cluster4.daemons):
            if d["proc"].poll() is None:
                tp_cluster4.kill_daemon(i)
                killed.set()
                return

    t = threading.Thread(target=kill_after_delay, daemon=True)
    t.start()
    res = trainer.fit()
    assert killed.is_set(), "chaos never fired"
    assert res.error is None, f"trainer did not recover: {res.error}"
    steps_seen = sorted({m["step"] for m in res.metrics_history})
    assert steps_seen[-1] == 7, steps_seen
    final = res.checkpoint.to_dict()
    assert final["step"] == 8
