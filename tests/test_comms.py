"""Communication observability plane: the comms ledger and its surfaces.

Covers the per-op collective ledger (bytes/duration -> algbw/busbw,
NCCL-tests factors), rendezvous arrival-skew attribution (the laggard
rank is *named*, not averaged away), the runtime collective-fingerprint
check (divergence raises with both fingerprints instead of hanging —
the runtime mirror of lint R12), the StripedTransfer peer link matrix,
exact federation math (``merge_payloads`` / ``/api/comms``), the
``ray-tpu top --comms`` and doctor ``--comms-baseline`` surfaces, the
tensor-plane epoch gauge, and a ProcessCluster chaos drill (self-skips
without the C++ state service) where a rank-filtered collective delay
must be attributed to that rank end-to-end.
"""

import json
import threading
import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu.observability import comms


@pytest.fixture(autouse=True)
def _comms_state():
    was = comms.ENABLED
    comms.enable()
    comms.reset()
    yield
    comms.reset()
    if not was:
        comms.disable()


def _require_state_service():
    """ProcessCluster needs the C++ state service (protoc + g++)."""
    from ray_tpu._native.build import build_state_service
    try:
        build_state_service()
    except Exception as e:
        pytest.skip(f"state service unavailable: {e}")


# -- op ledger ---------------------------------------------------------------

def test_record_op_derives_algbw_and_busbw():
    # 8 MiB allreduce in 8 ms: algbw = 8MiB / 8ms ~ 1.049 GB/s;
    # busbw at world=4 applies the nccl-tests 2(n-1)/n factor (1.5x).
    comms.record_op("g", "allreduce", 8 << 20, "float32", 0.008,
                    world_size=4)
    g = comms.snapshot()["groups"]["g"]
    rec = g["ops"]["allreduce"]
    assert rec["count"] == 1 and rec["bytes"] == 8 << 20
    assert rec["algbw_gbps"] == pytest.approx((8 << 20) / 0.008 / 1e9)
    assert rec["busbw_gbps"] == pytest.approx(rec["algbw_gbps"] * 1.5)
    assert g["world_size"] == 4 and g["seq"] == 1
    # non-factored op: busbw == algbw
    comms.record_op("g", "broadcast", 1 << 20, "float32", 0.004)
    bc = comms.snapshot()["groups"]["g"]["ops"]["broadcast"]
    assert bc["busbw_gbps"] == pytest.approx(bc["algbw_gbps"])
    # the recent ring carries (group, seq, op, bytes, dtype, ms)
    recent = comms.snapshot()["recent"]
    assert recent[-1][0] == "g" and recent[-1][2] == "broadcast"


def test_recent_ring_is_bounded():
    for i in range(200):
        comms.record_op("g", "allreduce", 8, "float32", 1e-6)
    snap = comms.snapshot()
    assert len(snap["recent"]) == comms._RECENT_CAP
    assert snap["groups"]["g"]["ops"]["allreduce"]["count"] == 200


# -- arrival skew ------------------------------------------------------------

def test_arrival_skew_names_the_laggard_rank():
    # rank 1 arrives ~50ms after rank 0 at every rendezvous
    for _ in range(5):
        comms.record_arrivals("g", {0: 0.0002, 1: 0.050}, world_size=2)
    snap = comms.snapshot()
    report = comms.skew_report(snap["groups"], bounds=snap["bounds"])
    assert report["g"]["1"]["p95_ms"] >= 40.0
    assert report["g"]["0"]["p95_ms"] <= 1.0
    flags = comms.skew_flags(snap["groups"], bounds=snap["bounds"])
    assert [(f["group"], f["rank"]) for f in flags] == [("g", "1")]
    assert flags[0]["samples"] == 5
    assert flags[0]["p95_ms"] >= 3.0 * flags[0]["median_ms"]


def test_skew_flags_guards():
    # below min_samples: no flag, however skewed
    comms.record_arrivals("g", {0: 0.0, 1: 0.050})
    snap = comms.snapshot()
    assert comms.skew_flags(snap["groups"], bounds=snap["bounds"]) == []
    comms.reset()
    # symmetric sub-millisecond jitter is noise, not a straggler
    for _ in range(10):
        comms.record_arrivals("g", {0: 0.0, 1: 0.0004})
    snap = comms.snapshot()
    assert comms.skew_flags(snap["groups"], bounds=snap["bounds"]) == []
    # a single-rank group can have no laggard
    comms.reset()
    for _ in range(10):
        comms.record_arrivals("solo", {0: 5.0})
    snap = comms.snapshot()
    assert comms.skew_flags(snap["groups"], bounds=snap["bounds"]) == []


# -- fingerprint check -------------------------------------------------------

def test_check_fingerprints_raises_with_both_fingerprints():
    fp0 = comms.fingerprint("allreduce:SUM", (4, 4), "float32")
    fp1 = comms.fingerprint("allreduce:SUM", (8,), "float32")
    comms.check_fingerprints({0: fp0, 1: fp0}, group="g", seq=3)  # agree
    with pytest.raises(comms.CollectiveDivergenceError) as ei:
        comms.check_fingerprints({0: fp0, 1: fp1}, group="g", seq=4)
    err = ei.value
    assert err.group == "g" and err.seq == 4
    assert err.fingerprint_a == fp0 and err.fingerprint_b == fp1
    msg = str(err)
    assert "(4, 4)" in msg and "(8,)" in msg and "R12" in msg
    # the mismatch is counted into the group ledger for the doctor
    assert comms.snapshot()["groups"]["g"]["mismatches"] == 1


def test_threaded_group_divergence_raises_on_every_rank():
    """Two ranks of a thread-shared CPU group submit different shapes:
    both get the divergence error instead of a silently-wrong compute."""
    from ray_tpu.collective.collective_group.cpu_group import CPUGroupShared
    from ray_tpu.collective.types import ReduceOp
    shared = CPUGroupShared(2, label="tdiv")
    errs = {}

    def run(rank, shape):
        try:
            shared.collective(rank, np.ones(shape), ("allreduce",
                                                     ReduceOp.SUM))
        except Exception as e:  # noqa: BLE001 — the divergence under test
            errs[rank] = e

    ts = [threading.Thread(target=run, args=(0, (4,))),
          threading.Thread(target=run, args=(1, (8,)))]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=30)
    assert set(errs) == {0, 1}
    for e in errs.values():
        assert isinstance(e, comms.CollectiveDivergenceError)


def test_disabled_fast_path_is_a_noop():
    comms.disable()
    comms.record_op("g", "allreduce", 1 << 20, "float32", 0.001)
    comms.record_arrivals("g", {0: 0.0, 1: 9.0})
    comms.link_observe("peer", "object.fetch", nbytes=1, seconds=1.0)
    # divergent fingerprints do not raise while the plane is off
    comms.check_fingerprints({0: ("a", (1,), "f"), 1: ("b", (2,), "f")})
    snap = comms.snapshot()
    assert snap["groups"] == {} and snap["links"] == {}
    assert comms.families() == []
    comms.enable()


# -- collective API instrumentation ------------------------------------------

def _spawn_group(n, gname):
    @ray_tpu.remote(num_cpus=0.1)
    class Member:
        def run(self, fn_name, *args, **kwargs):
            from ray_tpu import collective as col
            return getattr(col, fn_name)(*args, **kwargs)

    actors = [Member.remote() for _ in range(n)]
    from ray_tpu.collective import create_collective_group
    create_collective_group(actors, n, list(range(n)), backend="cpu",
                            group_name=gname)
    return actors


def test_collective_api_records_ops_and_arrivals(ray_start_regular):
    actors = _spawn_group(2, "gapi")
    for _ in range(3):
        refs = [a.run.remote("allreduce", np.ones(1024), "gapi")
                for a in actors]
        ray_tpu.get(refs)
    snap = comms.snapshot()
    g = snap["groups"]["gapi"]
    rec = g["ops"]["allreduce"]
    assert rec["count"] == 6                      # 2 ranks x 3 ops
    assert rec["bytes"] == 6 * 1024 * 8           # float64 tensors
    assert g["world_size"] == 2
    # every rendezvous stamped both ranks' arrivals
    assert {r["arrivals"] for r in g["ranks"].values()} == {3}


def test_collective_api_divergence_raises_not_hangs(ray_start_regular):
    from ray_tpu.exceptions import TaskError
    actors = _spawn_group(2, "gdiv")
    refs = [actors[0].run.remote("allreduce", np.ones(4), "gdiv"),
            actors[1].run.remote("allreduce", np.ones(8), "gdiv")]
    with pytest.raises(TaskError, match="collective divergence"):
        ray_tpu.get(refs, timeout=60)


# -- link matrix -------------------------------------------------------------

class _FakeClient:
    closed = False


class _FakePool:
    def clients(self, address):
        return [_FakeClient()]


def test_striped_transfer_feeds_link_matrix():
    from ray_tpu._private.transport import StripedTransfer

    def submit(client, off, done_cb):
        done_cb(None)

    st = StripedTransfer(_FakePool(), "10.0.0.9:7000",
                         consumer="object.fetch", streams=[_FakeClient()])
    st.run([0, 1, 2, 3], submit)
    links = comms.snapshot()["links"]
    rec = links["10.0.0.9:7000|object.fetch"]
    assert rec["chunks"] == 4 and rec["bytes"] > 0
    assert rec["retries"] == 0 and rec["failovers"] == 0


def test_striped_transfer_failover_recorded_and_flagged():
    from ray_tpu._private.rpc import RpcConnectionError
    from ray_tpu._private.transport import StripedTransfer
    attempts = {}

    def submit(client, off, done_cb):
        attempts[off] = attempts.get(off, 0) + 1
        if off == 1 and attempts[off] == 1:
            done_cb(RpcConnectionError("stripe died"))
        else:
            done_cb(None)

    st = StripedTransfer(_FakePool(), "10.0.0.9:7000",
                         consumer="ckpt.restore", streams=[_FakeClient()])
    st.run([0, 1], submit)
    assert attempts[1] == 2
    merged = comms.merge_payloads([comms.snapshot()])
    rec = merged["links"]["10.0.0.9:7000|ckpt.restore"]
    assert rec["retries"] == 1 and rec["failovers"] == 1
    flags = comms.link_flags(merged["links"])
    assert [f["link"] for f in flags] == ["10.0.0.9:7000|ckpt.restore"]
    assert "failover" in flags[0]["why"]


def test_link_flags_bandwidth_outlier():
    # three rated links; one runs at ~1/500th of the others' GB/s
    for peer, secs in (("a:1", 0.001), ("b:1", 0.001), ("c:1", 0.5)):
        for _ in range(3):
            comms.link_observe(peer, "object.fetch", nbytes=1 << 20,
                               seconds=secs, chunks=1)
    merged = comms.merge_payloads([comms.snapshot()])
    flags = comms.link_flags(merged["links"])
    assert [f["peer"] for f in flags] == ["c:1"]
    assert "vs link median" in flags[0]["why"]
    # a lone link is never an outlier of itself
    assert comms.link_flags(
        {"a:1|object.fetch": merged["links"]["c:1|object.fetch"]}) == []


# -- federation --------------------------------------------------------------

def test_merge_payloads_adds_exactly_and_rederives():
    comms.record_op("g", "allreduce", 1 << 20, "float32", 0.002,
                    world_size=2)
    for _ in range(4):
        comms.record_arrivals("g", {0: 0.0, 1: 0.040}, world_size=2)
    comms.link_observe("p:1", "object.fetch", nbytes=1 << 20, seconds=0.01,
                       chunks=1)
    snap = json.loads(json.dumps(comms.snapshot()))  # a federation hop
    merged = comms.merge_payloads([snap, snap])
    g = merged["groups"]["g"]
    assert g["ops"]["allreduce"]["count"] == 2
    assert g["ops"]["allreduce"]["bytes"] == 2 << 20
    # bandwidth is recomputed from summed bytes/seconds, not averaged
    assert g["ops"]["allreduce"]["algbw_gbps"] == pytest.approx(
        (2 << 20) / 0.004 / 1e9)
    assert g["world_size"] == 2
    assert g["ranks"]["1"]["arrivals"] == 8
    assert sum(g["ranks"]["1"]["counts"]) == 8
    assert merged["links"]["p:1|object.fetch"]["bytes"] == 2 << 20
    # a doubled histogram still names the same laggard
    flags = comms.skew_flags(merged["groups"], bounds=merged["bounds"])
    assert [(f["group"], f["rank"]) for f in flags] == [("g", "1")]
    # malformed node payloads are skipped, not fatal
    again = comms.merge_payloads([None, "bogus", {"groups": {"g": 7}},
                                  snap])
    assert again["groups"]["g"]["ops"]["allreduce"]["count"] == 1


def test_families_export_and_extract_roundtrip():
    comms.record_op("g", "allgather", 2048, "int8", 0.001, world_size=4)
    fams = comms.families()
    assert len(fams) == 1 and fams[0]["type"] == "gauge"
    assert fams[0]["name"] == comms.COMMS_FAMILY
    (name, tags, value), = fams[0]["samples"]
    assert dict(tags) == {"group": "g", "op": "allgather"}
    assert value == 2048.0
    # the raw payload survives a JSON federation hop untouched
    wire = json.loads(json.dumps(fams))
    payload = comms.extract_comms(wire)
    assert payload["groups"]["g"]["ops"]["allgather"]["count"] == 1
    assert comms.extract_comms([{"name": "x", "samples": []}]) is None
    assert comms.extract_comms(None) is None


def test_metrics_snapshot_carries_comms_family():
    from ray_tpu.util import metrics
    comms.record_op("g", "allreduce", 64, "float32", 0.001)
    snap = metrics.snapshot()
    assert any(f.get("name") == comms.COMMS_FAMILY for f in snap)


def test_head_comms_merges_and_degrades():
    """_comms merges per-node payloads, attributes skew, and surfaces
    unreachable hosts without failing the endpoint."""
    from ray_tpu.dashboard.head import DashboardHead
    for _ in range(5):
        comms.record_arrivals("g", {0: 0.0002, 1: 0.050}, world_size=2)
    comms.record_op("g", "allreduce", 1 << 20, "float32", 0.002,
                    world_size=2)
    head = DashboardHead.__new__(DashboardHead)
    fams = comms.families()
    head._metric_snapshots = lambda: (
        {"head": fams, "node:aa": fams, "node:bb": []}, ["node:cc"])
    payload = head._comms()
    assert payload["missing_hosts"] == ["node:cc"]
    assert set(payload["nodes"]) == {"head", "node:aa"}
    assert payload["groups"]["g"]["ops"]["allreduce"]["count"] == 2
    assert [(f["group"], f["rank"]) for f in payload["skew_flags"]] == \
        [("g", "1")]
    assert payload["link_flags"] == []
    assert payload["bounds"]


# -- surfaces: top render / doctor -------------------------------------------

def test_render_comms_table():
    from ray_tpu.scripts.cli import _render_comms
    for _ in range(5):
        comms.record_arrivals("g", {0: 0.0002, 1: 0.050}, world_size=2)
    comms.record_op("g", "allreduce", 8 << 20, "float32", 0.008,
                    world_size=2)
    comms.link_observe("p:1", "object.fetch", nbytes=1 << 20,
                       seconds=0.001, chunks=4, retries=2, failovers=1)
    merged = comms.merge_payloads([comms.snapshot()])
    payload = dict(merged,
                   skew_flags=comms.skew_flags(merged["groups"],
                                               bounds=merged["bounds"]),
                   link_flags=comms.link_flags(merged["links"]),
                   missing_hosts=["node:dead"])
    text = _render_comms(payload)
    assert "ALGBW" in text and "BUSBW" in text
    assert any("allreduce" in ln for ln in text.splitlines())
    assert "LAGGARD" in text           # rank 1 marked in the skew table
    assert "DEGRADED" in text          # the failover link marked
    assert "1 unreachable host(s) omitted" in text
    empty = _render_comms({"groups": {}, "links": {}})
    assert "no collective ops recorded" in empty


def test_doctor_comms_section_and_baseline_drift():
    from ray_tpu import doctor
    for _ in range(5):
        comms.record_arrivals("g", {0: 0.0002, 1: 0.050}, world_size=2)
    comms.record_op("g", "allreduce", 8 << 20, "float32", 0.008,
                    world_size=2)
    collected = {"ts": time.time(), "errors": [],
                 "cluster": {"metrics": {"snapshots": {
                     "head": comms.families()}}}}
    loose = doctor._comms_reports(
        collected, baseline={"g": {"allreduce_gbps": 0.001,
                                   "skew_p95_ms": 1000.0,
                                   "mismatches": 0.0}})
    assert loose["drift"] == []
    assert [(f["group"], f["rank"]) for f in loose["skew_flags"]] == \
        [("g", "1")]
    tight = doctor._comms_reports(
        collected, baseline={"g": {"allreduce_gbps": 99.0,
                                   "skew_p95_ms": 1.0,
                                   "tolerance": 1.0}})
    assert {d["metric"] for d in tight["drift"]} == \
        {"allreduce_gbps", "skew_p95_ms"}
    # unknown groups in the baseline are ignored, not phantom drift
    assert doctor._comms_reports(
        collected, baseline={"ghost": {"allreduce_gbps": 9.0}})["drift"] \
        == []
    report = doctor.diagnose(
        collected, comms_baseline={"g": {"allreduce_gbps": 99.0}})
    assert not report["healthy"]        # the skew flag alone is an issue
    assert report["comms"]["drift"]
    rendered = doctor.render_text(report)
    assert "COMMS" in rendered and "COMMS DRIFT" in rendered
    assert "LAGGARD" in rendered and "allreduce" in rendered


def test_doctor_counts_mismatches_as_drift():
    from ray_tpu import doctor
    fp0 = comms.fingerprint("allreduce:SUM", (4,), "float32")
    fp1 = comms.fingerprint("allreduce:SUM", (8,), "float32")
    with pytest.raises(comms.CollectiveDivergenceError):
        comms.check_fingerprints({0: fp0, 1: fp1}, group="g")
    collected = {"ts": time.time(), "errors": [],
                 "cluster": {"metrics": {"snapshots": {
                     "head": comms.families()}}}}
    rep = doctor._comms_reports(collected,
                                baseline={"g": {"mismatches": 0.0}})
    assert [d["metric"] for d in rep["drift"]] == ["mismatches"]


def test_doctor_wire_ratio_budget_gates_compression():
    """``"<op>_wire_ratio"`` baseline budgets are ceilings on the merged
    wire/logical ratio: a quantized group drifting back toward 1.0 means
    compression silently stopped paying for itself."""
    from ray_tpu import doctor
    comms.record_op("gq", "allreduce", 1 << 20, "float32", 0.004,
                    world_size=2, wire_bytes=(1 << 20) * 68 // 256)
    collected = {"ts": time.time(), "errors": [],
                 "cluster": {"metrics": {"snapshots": {
                     "head": comms.families()}}}}
    loose = doctor._comms_reports(
        collected, baseline={"gq": {"allreduce_wire_ratio": 0.30}})
    assert loose["drift"] == []
    tight = doctor._comms_reports(
        collected, baseline={"gq": {"allreduce_wire_ratio": 0.10}})
    assert [d["metric"] for d in tight["drift"]] == ["allreduce_wire_ratio"]
    assert tight["drift"][0]["got_ratio"] == pytest.approx(68 / 256,
                                                           abs=1e-3)


def test_doctor_manifest_cross_check_flags_unplanned_collectives(tmp_path):
    """R29 acceptance: the ``__manifest__`` comms-baseline key cross-
    checks the runtime ledger against raylint's static collective plan —
    ledgered ops absent from comms_manifest.json report as
    ``<op>_unplanned`` drift, matching plans stay clean, and an
    unreadable manifest path fails loudly instead of silently passing."""
    from ray_tpu import doctor
    groups = {"gman": {"world_size": 2, "ops": {
        "allreduce": {"count": 3, "bytes": float(3 << 20),
                      "wire_bytes": float(3 << 20), "seconds": 0.01}}}}
    plan = {"version": 1, "tool": "raylint/R29",
            "groups": {"gman": {"allreduce":
                                {"wire_formula": "2*(n-1)/n"}}}}
    assert doctor._manifest_drift(groups, plan) == []
    # planned entries get the predicted per-link bytes annotated:
    # wire_bytes x busbw_factor(world=2) = wire_bytes x 1.0 for allreduce
    ent = plan["groups"]["gman"]["allreduce"]
    assert ent["predicted_link_bytes"] == pytest.approx(float(3 << 20))
    drift = doctor._manifest_drift(groups, {"version": 1, "groups": {}})
    assert [(d["group"], d["metric"], d["got"]) for d in drift] == \
        [("gman", "allreduce_unplanned", 3)]
    # "*" wildcard covers statically-unresolvable group names
    assert doctor._manifest_drift(
        groups, {"groups": {"*": {"allreduce": {}}}}) == []
    # wire_ratio_max ceilings gate compression on planned ops
    ratio = doctor._manifest_drift(
        groups, {"groups": {"gman": {"allreduce":
                                     {"wire_ratio_max": 0.5}}}})
    assert [d["metric"] for d in ratio] == ["allreduce_wire_ratio"]

    # end-to-end: a live ledger vs a manifest file on disk
    comms.record_op("gman", "allreduce", 1 << 20, "float32", 0.004,
                    world_size=2)
    collected = {"ts": time.time(), "errors": [],
                 "cluster": {"metrics": {"snapshots": {
                     "head": comms.families()}}}}
    man_path = tmp_path / "comms_manifest.json"
    man_path.write_text(json.dumps(
        {"version": 1, "groups": {"gman": {"allreduce": {}}}}))
    clean = doctor._comms_reports(
        collected, baseline={"__manifest__": str(man_path)})
    assert clean["drift"] == []
    report = doctor.diagnose(
        collected,
        comms_baseline={"__manifest__": {"version": 1, "groups": {}}})
    assert not report["healthy"]
    unplanned = [d for d in report["comms"]["drift"]
                 if d["metric"] == "allreduce_unplanned"
                 and d["group"] == "gman"]
    assert unplanned and unplanned[0]["got"] == 1
    assert "unplanned collective" in doctor.render_text(report)
    broken = doctor._comms_reports(
        collected,
        baseline={"__manifest__": str(tmp_path / "missing.json")})
    assert [d["metric"] for d in broken["drift"]] == ["manifest_unreadable"]


# -- tensor-plane epoch gauge ------------------------------------------------

def test_tensor_plane_mark_sets_epoch_gauge():
    from ray_tpu.collective import tensor_plane
    from ray_tpu.observability.metric_names import TPLANE_EPOCH_GAUGE
    tensor_plane._mark("join", "gx", 3, rank=0, world=2)
    gauge = tensor_plane._epoch_gauge
    assert gauge is not None
    assert any(name == TPLANE_EPOCH_GAUGE
               and dict(tags).get("group") == "gx" and v == 3.0
               for name, tags, v in gauge.samples())
    # shutdown parks the group at epoch -1 instead of vanishing
    tensor_plane._mark("shutdown", "gx", -1, last_epoch=3)
    assert any(dict(tags).get("group") == "gx" and v == -1.0
               for _n, tags, v in gauge.samples())


# -- acceptance drill (self-skip without the C++ state service) --------------

def test_cluster_comms_chaos_drill():
    """A rank-filtered chaos delay (`collective.op[rank=1]`) makes rank 1
    arrive ~120ms late at every rendezvous on its daemon: the federated
    /api/comms skew attribution must NAME that rank, the doctor COMMS
    section must flag it, and a --comms-baseline must gate on it (pos +
    neg)."""
    from ray_tpu.cluster_utils import ProcessCluster
    from ray_tpu.dashboard.head import DashboardHead
    from ray_tpu import doctor
    _require_state_service()
    ray_tpu.shutdown()
    c = ProcessCluster(num_daemons=1, num_cpus=2)
    # both ranks live on the chaos daemon (thread-shared CPU group);
    # the label filter delays only rank 1's collectives
    c.add_daemon(resources={"pin": 2.0},
                 env={"RAY_TPU_CHAOS":
                      "7:collective.op[rank=1]@1+=delay(0.12)"})
    try:
        ray_tpu.init(address=c.address)

        @ray_tpu.remote(num_cpus=0.1)
        class Member:
            def run(self, fn_name, *args, **kwargs):
                from ray_tpu import collective as col
                return getattr(col, fn_name)(*args, **kwargs)

        actors = [Member.options(resources={"pin": 1.0}).remote()
                  for _ in range(2)]
        from ray_tpu.collective import create_collective_group
        create_collective_group(actors, 2, [0, 1], backend="cpu",
                                group_name="gdrill")
        for _ in range(6):
            refs = [a.run.remote("allreduce", np.ones(1024), "gdrill")
                    for a in actors]
            ray_tpu.get(refs, timeout=60)

        head = DashboardHead(c.address)
        try:
            payload = head._comms()
            g = payload["groups"].get("gdrill")
            assert g is not None, payload
            assert g["ops"]["allreduce"]["count"] == 12
            flagged = {(f["group"], f["rank"])
                       for f in payload["skew_flags"]}
            assert ("gdrill", "1") in flagged, payload["skew_flags"]
            assert ("gdrill", "0") not in flagged
            report = comms.skew_report(payload["groups"],
                                       bounds=payload["bounds"])
            assert report["gdrill"]["1"]["p95_ms"] >= 50.0

            # the doctor names the same rank and gates on the baseline
            snaps, _missing = head._metric_snapshots()
            collected = {"ts": time.time(), "errors": [],
                         "cluster": {"metrics": {"snapshots": snaps}}}
            rep = doctor.diagnose(
                collected,
                comms_baseline={"gdrill": {"skew_p95_ms": 1.0}})
            assert not rep["healthy"]
            assert ("gdrill", "1") in {
                (f["group"], f["rank"])
                for f in rep["comms"]["skew_flags"]}
            assert [d["metric"] for d in rep["comms"]["drift"]] == \
                ["skew_p95_ms"]
            rendered = doctor.render_text(rep)
            assert "LAGGARD gdrill rank 1" in rendered
            # negative control: a loose baseline records no drift
            loose = doctor._comms_reports(
                collected,
                baseline={"gdrill": {"skew_p95_ms": 100000.0,
                                     "mismatches": 10.0}})
            assert loose["drift"] == []
        finally:
            head.stop()
    finally:
        ray_tpu.shutdown()
        c.shutdown()
