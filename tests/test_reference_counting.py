"""Borrowing-refcount protocol tests.

Parity with the reference's ``ReferenceCounter`` semantics
(``src/ray/core_worker/reference_count.h:61``): the owner frees an object
only when local refs AND task pins AND remote borrows are all gone; a
borrower's death drops its borrows; N deserializations at one borrower
pair with exactly one removal (presence, not counting).
"""

import gc
import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu._private.ids import ObjectID
from ray_tpu._private.reference_counter import ReferenceCounter


def _oid(i: int = 1) -> ObjectID:
    return ObjectID(bytes([i]) * ObjectID.size())


class TestUnitBorrowAwareZero:
    def test_local_ref_zero_with_borrow_does_not_free(self):
        freed = []
        rc = ReferenceCounter(freed.append)
        oid = _oid()
        rc.add_local_ref(oid)
        rc.add_borrow(oid, "peer:1")
        rc.remove_local_ref(oid)
        assert freed == [], "owner freed object a borrower still holds"
        rc.remove_borrow(oid, "peer:1")
        assert freed == [oid]

    def test_pin_zero_with_borrow_does_not_free(self):
        freed = []
        rc = ReferenceCounter(freed.append)
        oid = _oid()
        rc.pin_for_task(oid)
        rc.add_borrow(oid, "peer:1")
        rc.unpin_for_task(oid)
        assert freed == []
        rc.remove_borrow(oid, "peer:1")
        assert freed == [oid]

    def test_add_borrow_idempotent_per_borrower(self):
        """N deserializations at one borrower send N ADD_BORROWs but only
        one REMOVE_BORROW (when the borrower's own count hits zero): the
        owner must track presence, not a count."""
        freed = []
        rc = ReferenceCounter(freed.append)
        oid = _oid()
        rc.add_borrow(oid, "peer:1")
        rc.add_borrow(oid, "peer:1")
        rc.add_borrow(oid, "peer:1")
        rc.remove_borrow(oid, "peer:1")
        assert freed == [oid], "asymmetric borrow accounting leaked"

    def test_borrower_death_drops_all_its_borrows(self):
        freed = []
        rc = ReferenceCounter(freed.append)
        a, b = _oid(1), _oid(2)
        rc.add_borrow(a, "peer:1")
        rc.add_borrow(b, "peer:1")
        rc.add_borrow(b, "peer:2")
        rc.remove_borrower("peer:1")
        assert a in freed and b not in freed
        rc.remove_borrower("peer:2")
        assert b in freed

    def test_multiple_borrowers(self):
        freed = []
        rc = ReferenceCounter(freed.append)
        oid = _oid()
        rc.add_borrow(oid, "peer:1")
        rc.add_borrow(oid, "peer:2")
        rc.remove_borrow(oid, "peer:1")
        assert freed == []
        rc.remove_borrow(oid, "peer:2")
        assert freed == [oid]


@pytest.fixture()
def cluster():
    from ray_tpu.cluster_utils import ProcessCluster
    ray_tpu.shutdown()
    c = ProcessCluster(num_daemons=2, num_cpus=2)
    ray_tpu.init(address=c.address)
    yield c
    ray_tpu.shutdown()
    c.shutdown()


def test_owner_drop_while_borrower_holds(cluster):
    """Driver puts an object, hands the ref to a long-lived actor, drops its
    own handle: the object must survive at the owner until the borrower
    releases it (reference_count.h:61 owned-by-borrowed-from contract)."""
    from ray_tpu._private import worker as _worker

    @ray_tpu.remote
    class Holder:
        def __init__(self):
            self.box = None

        def hold(self, box):
            self.box = box  # keeps the nested ref alive on the daemon
            return True

        def read(self):
            return int(ray_tpu.get(self.box["ref"]).sum())

    data = np.arange(100000)  # ~800KB: too big to inline
    expected = int(data.sum())
    ref = ray_tpu.put(data)
    oid = ref.id()
    rt = _worker.global_worker().runtime

    h = Holder.remote()
    assert ray_tpu.get(h.hold.remote({"ref": ref}), timeout=60)
    # Wait until the daemon's ADD_BORROW lands at the owner (async, FIFO).
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        if rt.reference_counter._borrows.get(oid):
            break
        time.sleep(0.05)
    assert rt.reference_counter._borrows.get(oid), "borrow never registered"

    del ref
    gc.collect()
    time.sleep(0.5)
    assert rt.local_node.store.contains(oid), \
        "owner freed the object while a borrower still holds it"
    assert ray_tpu.get(h.read.remote(), timeout=60) == expected


def test_borrower_death_frees_object(cluster):
    """When the borrowing daemon dies, its borrows are dropped; once the
    driver also drops its handle the object is freed."""
    from ray_tpu._private import worker as _worker

    @ray_tpu.remote
    class Holder:
        def __init__(self):
            self.box = None

        def hold(self, box):
            self.box = box
            return True

    data = np.arange(100000)
    ref = ray_tpu.put(data)
    oid = ref.id()
    rt = _worker.global_worker().runtime

    h = Holder.remote()
    assert ray_tpu.get(h.hold.remote({"ref": ref}), timeout=60)
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        if rt.reference_counter._borrows.get(oid):
            break
        time.sleep(0.05)
    assert rt.reference_counter._borrows.get(oid)

    # Find which daemon hosts the actor via its borrow address.
    borrower_addr = next(iter(rt.reference_counter._borrows[oid]))
    victim = next(i for i, d in enumerate(cluster.daemons)
                  if d["address"] == borrower_addr)
    cluster.kill_daemon(victim)

    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        if not rt.reference_counter._borrows.get(oid):
            break
        time.sleep(0.1)
    assert not rt.reference_counter._borrows.get(oid), \
        "dead borrower's borrow never dropped"

    del ref
    gc.collect()
    # The serialize-time pin of the hold() push is released after a
    # borrow-registration grace period; allow for it before asserting.
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        if not rt.local_node.store.contains(oid):
            break
        time.sleep(0.1)
    assert not rt.local_node.store.contains(oid), \
        "object not freed after all refs and borrows gone"
