"""RL layer tests.

Mirrors the reference's RLlib test strategy (SURVEY §4.2): unit tests for
batch/buffer/GAE math, rollout shape checks, and short learning-criteria
runs (CartPole reward improves within a step budget, the in-repo analogue
of ``release/rllib_tests/multi_gpu_learning_tests``'s pass_criteria).
"""

import numpy as np
import pytest

import ray_tpu
from ray_tpu.rl import (DQN, PPO, CartPoleEnv, Impala, PendulumEnv,
                        PrioritizedReplayBuffer, ReplayBuffer, RolloutWorker,
                        SampleBatch, VectorEnv, concat_samples)
from ray_tpu.rl.postprocessing import compute_gae
from ray_tpu.rl.sample_batch import SampleBatch as SB


# -- envs ------------------------------------------------------------------

def test_cartpole_env_contract():
    env = CartPoleEnv({"seed": 0})
    obs = env.reset(seed=1)
    assert obs.shape == (4,)
    total = 0
    for _ in range(600):
        obs, r, term, trunc, _ = env.step(env.spec.action_space.sample(
            np.random.default_rng(0)))
        total += r
        if term or trunc:
            break
    assert term or trunc  # random policy can't balance 600 steps


def test_pendulum_env_contract():
    env = PendulumEnv({"seed": 0})
    obs = env.reset(seed=2)
    assert obs.shape == (3,)
    obs, r, term, trunc, _ = env.step(np.array([0.5]))
    assert r <= 0 and not term


def test_vector_env_autoreset():
    venv = VectorEnv(lambda c: CartPoleEnv(c), num_envs=3, seed=0)
    obs = venv.reset(seed=0)
    assert obs.shape == (3, 4)
    done_seen = False
    for _ in range(400):
        obs, r, terms, truncs, infos = venv.step(np.ones(3, np.int64))
        for i in range(3):
            if terms[i] or truncs[i]:
                done_seen = True
                assert "terminal_obs" in infos[i]
    assert done_seen
    assert obs.shape == (3, 4)


def test_jax_cartpole_matches_numpy():
    import jax.numpy as jnp
    from ray_tpu.rl.env import jax_cartpole_step
    env = CartPoleEnv()
    obs = env.reset(seed=3)
    state = jnp.asarray(obs)[None]
    for a in [0, 1, 1, 0, 1]:
        np_obs, _, np_done, _, _ = env.step(a)
        state, _, done = jax_cartpole_step(state, jnp.array([a]))
        np.testing.assert_allclose(np.asarray(state[0]), np_obs, rtol=1e-5)
        assert bool(done[0]) == np_done


# -- sample batch ----------------------------------------------------------

def test_sample_batch_ops():
    b = SampleBatch({SB.OBS: np.arange(10).reshape(5, 2),
                     SB.REWARDS: np.ones(5)})
    assert len(b) == 5
    assert len(b.slice(1, 3)) == 2
    mbs = list(b.minibatches(2))
    assert len(mbs) == 2
    c = concat_samples([b, b])
    assert len(c) == 10
    assert len(b.pad_to(8)) == 8
    shuffled = b.shuffle(np.random.default_rng(0))
    assert set(shuffled[SB.REWARDS]) == {1.0}


def test_split_by_episode():
    b = SampleBatch({SB.EPS_ID: np.array([1, 1, 2, 2, 2, 3]),
                     SB.REWARDS: np.arange(6)})
    parts = b.split_by_episode()
    assert [len(p) for p in parts] == [2, 3, 1]


# -- GAE -------------------------------------------------------------------

def test_gae_matches_hand_computed():
    gamma, lam = 0.9, 0.8
    batch = SampleBatch({
        SB.REWARDS: np.array([1.0, 1.0, 1.0]),
        SB.VF_PREDS: np.array([0.5, 0.4, 0.3]),
        SB.TERMINATEDS: np.array([False, False, True]),
        SB.TRUNCATEDS: np.array([False, False, False]),
    })
    compute_gae(batch, last_value=99.0, gamma=gamma, lambda_=lam)
    # t=2 terminal: delta2 = 1 - 0.3 = 0.7 ; adv2 = 0.7
    # t=1: delta1 = 1 + .9*.3 - .4 = .87 ; adv1 = .87 + .9*.8*.7 = 1.374
    # t=0: delta0 = 1 + .9*.4 - .5 = .86 ; adv0 = .86 + .72*1.374
    np.testing.assert_allclose(
        batch[SB.ADVANTAGES], [0.86 + 0.72 * 1.374, 1.374, 0.7], rtol=1e-5)
    np.testing.assert_allclose(
        batch[SB.VALUE_TARGETS],
        np.array([0.86 + 0.72 * 1.374, 1.374, 0.7]) + [0.5, 0.4, 0.3],
        rtol=1e-5)


def test_gae_bootstraps_nonterminal_tail():
    batch = SampleBatch({
        SB.REWARDS: np.array([0.0]),
        SB.VF_PREDS: np.array([0.0]),
        SB.TERMINATEDS: np.array([False]),
        SB.TRUNCATEDS: np.array([False]),
    })
    compute_gae(batch, last_value=2.0, gamma=0.5, lambda_=1.0)
    np.testing.assert_allclose(batch[SB.ADVANTAGES], [1.0])


# -- replay buffers --------------------------------------------------------

def test_replay_buffer_ring():
    buf = ReplayBuffer(capacity=8, seed=0)
    for i in range(3):
        buf.add(SampleBatch({SB.OBS: np.full((4, 2), i),
                             SB.REWARDS: np.full(4, i)}))
    assert len(buf) == 8  # 12 added, capacity 8
    s = buf.sample(16)
    assert len(s) == 16
    assert set(np.unique(s[SB.REWARDS])) <= {1.0, 2.0}  # batch 0 evicted


def test_prioritized_replay_prefers_high_priority():
    buf = PrioritizedReplayBuffer(capacity=16, alpha=1.0, seed=0)
    buf.add(SampleBatch({SB.OBS: np.arange(16).reshape(16, 1)}))
    idx = np.arange(16)
    prios = np.zeros(16)
    prios[5] = 100.0
    buf.update_priorities(idx, prios)
    s = buf.sample(64, beta=0.4)
    frac_5 = np.mean(s["batch_indexes"] == 5)
    assert frac_5 > 0.9
    assert s["weights"].max() <= 1.0 + 1e-6


# -- rollout worker --------------------------------------------------------

def test_rollout_worker_shapes_and_gae_columns():
    w = RolloutWorker("CartPole-v1", num_envs=2,
                      rollout_fragment_length=10, seed=0)
    batch = w.sample()
    assert len(batch) == 20
    for k in (SB.OBS, SB.ACTIONS, SB.REWARDS, SB.ADVANTAGES,
              SB.VALUE_TARGETS, SB.ACTION_LOGP, SB.EPS_ID):
        assert k in batch, k
    assert batch[SB.OBS].shape == (20, 4)
    metrics = w.pop_metrics()
    assert all("episode_reward" in m for m in metrics)


def test_rollout_worker_continuous():
    w = RolloutWorker("Pendulum-v1", num_envs=1,
                      rollout_fragment_length=5, seed=0)
    batch = w.sample()
    assert batch[SB.ACTIONS].shape == (5, 1)
    assert np.all(np.abs(batch[SB.ACTIONS]) <= 2.0)


# -- vtrace ----------------------------------------------------------------

def test_vtrace_on_policy_reduces_to_td_lambda1_targets():
    """With rho=c=1 and identical policies, vs_t is the n-step return."""
    import jax.numpy as jnp
    from ray_tpu.rl.impala import vtrace
    T, B = 4, 1
    logp = jnp.zeros((T, B))
    rewards = jnp.ones((T, B))
    values = jnp.zeros((T, B))
    boot = jnp.zeros((B,))
    discounts = jnp.full((T, B), 0.5)
    vs, pg_adv = vtrace(logp, logp, rewards, values, boot, discounts)
    # vs_t = sum_{k>=t} gamma^(k-t) * r_k  with gamma=0.5
    np.testing.assert_allclose(
        np.asarray(vs[:, 0]), [1.875, 1.75, 1.5, 1.0], rtol=1e-5)


# -- learning criteria -----------------------------------------------------

def test_ppo_learns_cartpole():
    algo = (PPO.get_default_config()
            .environment("CartPole-v1")
            .rollouts(num_rollout_workers=0, num_envs_per_worker=8,
                      rollout_fragment_length=100)
            .training(train_batch_size=800, sgd_minibatch_size=256,
                      num_sgd_iter=8, lr=3e-4, entropy_coeff=0.01,
                      kl_coeff=0.0, clip_param=0.2)
            .debugging(seed=0)
            .build())
    first = None
    result = None
    for _ in range(25):
        result = algo.train()
        if first is None and "episode_reward_mean" in result:
            first = result["episode_reward_mean"]
    final = result["episode_reward_mean"]
    algo.stop()
    # Same shape as the reference's multi_gpu_learning_tests pass_criteria:
    # reward threshold within a timestep budget (20k env steps).
    assert final > max(80.0, first * 2.0), (first, final)


def test_ppo_checkpoint_restore_roundtrip():
    config = (PPO.get_default_config()
              .environment("CartPole-v1")
              .rollouts(num_rollout_workers=0, num_envs_per_worker=2,
                        rollout_fragment_length=20)
              .training(train_batch_size=40, sgd_minibatch_size=20,
                        num_sgd_iter=2)
              .debugging(seed=0))
    algo = config.build()
    algo.train()
    state = algo.__getstate__()
    w0 = algo.get_weights()
    algo.stop()

    algo2 = PPO(config=config)
    algo2.__setstate__(state)
    w1 = algo2.get_weights()
    import jax
    for a, b in zip(jax.tree_util.tree_leaves(w0),
                    jax.tree_util.tree_leaves(w1)):
        np.testing.assert_array_equal(a, b)
    algo2.stop()


def test_worker_set_recreates_killed_worker(ray_start_regular):
    """Dead rollout workers are replaced transparently (reference:
    ``worker_set.py`` recreate_failed_workers; chaos test §4.2)."""
    algo = (PPO.get_default_config()
            .environment("CartPole-v1")
            .rollouts(num_rollout_workers=2, num_envs_per_worker=1,
                      rollout_fragment_length=25)
            .training(train_batch_size=50, sgd_minibatch_size=25,
                      num_sgd_iter=2)
            .build())
    algo.train()
    ray_tpu.kill(algo.workers.remote_workers[0])
    algo.train()  # absorbs the failure, recreates
    result = algo.train()
    assert result["timesteps_this_iter"] >= 50
    assert len(algo.workers.remote_workers) == 2
    algo.stop()


def test_ppo_with_remote_workers(ray_start_regular):
    algo = (PPO.get_default_config()
            .environment("CartPole-v1")
            .rollouts(num_rollout_workers=2, num_envs_per_worker=1,
                      rollout_fragment_length=25)
            .training(train_batch_size=50, sgd_minibatch_size=25,
                      num_sgd_iter=2)
            .build())
    result = algo.train()
    assert result["timesteps_this_iter"] >= 50
    algo.stop()


def test_dqn_learns_cartpole():
    algo = (DQN.get_default_config()
            .environment("CartPole-v1")
            .rollouts(num_rollout_workers=0, num_envs_per_worker=4,
                      rollout_fragment_length=16)
            .training(train_batch_size=64, gamma=0.99, lr=1e-3,
                      replay_buffer_capacity=20_000,
                      num_steps_sampled_before_learning_starts=1000,
                      epsilon_timesteps=8000, n_updates_per_iter=8,
                      target_network_update_freq=100, grad_clip=10.0)
            .debugging(seed=0)
            .build())
    result = None
    for _ in range(250):
        result = algo.train()
    final = result["episode_reward_mean"]
    algo.stop()
    assert final > 50.0, final


def test_dqn_prioritized_replay_runs():
    algo = (DQN.get_default_config()
            .environment("CartPole-v1")
            .rollouts(num_rollout_workers=0, num_envs_per_worker=2,
                      rollout_fragment_length=8)
            .training(train_batch_size=16, prioritized_replay=True,
                      num_steps_sampled_before_learning_starts=32,
                      n_updates_per_iter=2)
            .build())
    for _ in range(5):
        result = algo.train()
    assert result["learning"]
    algo.stop()


def test_impala_runs_and_improves(ray_start_regular):
    algo = (Impala.get_default_config()
            .environment("CartPole-v1")
            .rollouts(num_rollout_workers=2, num_envs_per_worker=2,
                      rollout_fragment_length=40)
            .training(lr=3e-3, entropy_coeff=0.01)
            .debugging(seed=0)
            .build())
    result = None
    for _ in range(30):
        result = algo.train()
    algo.stop()
    assert result["timesteps_total"] > 1000
    assert "policy_loss" in result


def test_algorithm_is_tune_trainable():
    """Algorithm can be driven by the Tuner (reference: Algorithm is a
    Trainable; ``tune.run(PPO)``)."""
    from ray_tpu.tune import run as tune_run

    def make_algo(config):
        return (PPO.get_default_config()
                .environment("CartPole-v1")
                .rollouts(num_rollout_workers=0, num_envs_per_worker=2,
                          rollout_fragment_length=20)
                .training(train_batch_size=40, sgd_minibatch_size=20,
                          num_sgd_iter=2, lr=config["lr"]))

    class TunablePPO(PPO):
        def __init__(self, config=None, logdir=None):
            super().__init__(config=make_algo(config or {"lr": 3e-4}),
                             logdir=logdir)

    analysis = tune_run(TunablePPO, config={"lr": 3e-4}, num_samples=1,
                        stop={"training_iteration": 2},
                        metric="episode_reward_mean", mode="max")
    assert len(analysis.trials) == 1


# -- SAC -------------------------------------------------------------------

def test_sac_policy_actions_squashed_in_bounds():
    from ray_tpu.rl.sac import SquashedGaussianPolicy
    env = PendulumEnv({"seed": 0})
    pol = SquashedGaussianPolicy(env.spec, seed=0)
    obs = np.stack([env.reset(seed=i) for i in range(16)])
    a, logp, vf = pol.compute_actions(obs)
    assert a.shape == (16, 1)
    assert np.all(a >= -2.0) and np.all(a <= 2.0)
    # deterministic mode returns the squashed mean
    a2, _, _ = pol.compute_actions(obs, explore=False)
    a3, _, _ = pol.compute_actions(obs, explore=False)
    np.testing.assert_allclose(a2, a3)


def test_sac_requires_continuous_actions():
    from ray_tpu.rl.sac import SquashedGaussianPolicy
    env = CartPoleEnv({})
    with pytest.raises(ValueError):
        SquashedGaussianPolicy(env.spec, seed=0)


def test_sac_learns_pendulum():
    """Learning gate (reference pass-criteria style): SAC must lift
    Pendulum return from the ~-1300 random level to > -1000 within a
    small step budget."""
    from ray_tpu.rl import SAC
    algo = (SAC.get_default_config()
            .environment("Pendulum-v1")
            .training(train_batch_size=128, n_updates_per_iter=8,
                      num_steps_sampled_before_learning_starts=256)
            .debugging(seed=0)
            .build())
    try:
        early = []
        for i in range(900):
            r = algo.step()
            rew = r.get("episode_reward_mean")
            if rew is not None and len(early) < 5:
                early.append(rew)
        final = r["episode_reward_mean"]
        # measured trajectory (seed 0): -1300s at start, ~-680 by 800
        # iters, -387 by 1800. The reported mean lags (100-episode
        # window), so gate at -800 with a 100-pt improvement check.
        assert final > -800, (early, final)
        assert final - float(np.mean(early)) > 100, (early, final)
    finally:
        algo.stop()


def test_sac_checkpoint_restore_roundtrip(tmp_path):
    from ray_tpu.rl import SAC
    algo = (SAC.get_default_config()
            .environment("Pendulum-v1")
            .training(train_batch_size=32, n_updates_per_iter=1,
                      num_steps_sampled_before_learning_starts=16)
            .debugging(seed=1)
            .build())
    try:
        for _ in range(5):
            algo.step()
        state = algo.__getstate__()
        algo2 = (SAC.get_default_config()
                 .environment("Pendulum-v1")
                 .debugging(seed=2)
                 .build())
        try:
            algo2.__setstate__(state)
            w1 = algo.get_weights()
            w2 = algo2.get_weights()
            for a, b in zip(np.asarray(w1["actor"]["layers"][0]["w"]).flat,
                            np.asarray(w2["actor"]["layers"][0]["w"]).flat):
                assert a == b
        finally:
            algo2.stop()
    finally:
        algo.stop()


# -- multi-agent -----------------------------------------------------------

def test_multi_agent_env_contract():
    from ray_tpu.rl import CoordinationGameEnv, RockPaperScissorsEnv
    for env_cls in (CoordinationGameEnv, RockPaperScissorsEnv):
        env = env_cls({"episode_len": 5})
        obs = env.reset()
        assert set(obs) == set(env.agent_ids)
        for t in range(5):
            acts = {a: env.action_spaces[a].sample(
                np.random.default_rng(t)) for a in env.agent_ids}
            obs, rews, terms, truncs, infos = env.step(acts)
            assert set(rews) == set(env.agent_ids)
            assert "__all__" in terms and "__all__" in truncs
        assert truncs["__all__"]  # episode_len reached


def test_rock_paper_scissors_zero_sum():
    from ray_tpu.rl import RockPaperScissorsEnv
    env = RockPaperScissorsEnv({"episode_len": 50})
    env.reset()
    for m0 in range(3):
        for m1 in range(3):
            _, rews, _, _, _ = env.step(
                {"player_0": m0, "player_1": m1})
            assert rews["player_0"] + rews["player_1"] == 0.0


def test_multi_agent_rollout_worker_per_policy_batches():
    from ray_tpu.rl import CoordinationGameEnv, MultiAgentRolloutWorker
    w = MultiAgentRolloutWorker(lambda c: CoordinationGameEnv(c),
                                rollout_fragment_length=40, seed=0)
    ma = w.sample()
    assert sorted(ma) == ["agent_0", "agent_1"]
    assert ma.env_steps == 40 and ma.agent_steps() == 80
    for b in ma.values():
        assert SB.ADVANTAGES in b and SB.VALUE_TARGETS in b
        assert len(b[SB.OBS]) == 40


def test_multi_agent_policy_mapping_shares_policy():
    from ray_tpu.rl import CoordinationGameEnv, MultiAgentRolloutWorker
    w = MultiAgentRolloutWorker(lambda c: CoordinationGameEnv(c),
                                policy_mapping_fn=lambda aid: "shared",
                                rollout_fragment_length=10, seed=0)
    assert sorted(w.policies) == ["shared"]
    ma = w.sample()
    assert sorted(ma) == ["shared"]
    assert len(ma["shared"]) == 20  # both agents' steps in one batch
    assert ma.env_steps == 10       # but only 10 true env steps


def test_independent_ppo_learns_coordination():
    """Independent learners must find the payoff-dominant equilibrium of
    the coordination game (both pick 0 -> 1.0/step; max 25/episode)."""
    from ray_tpu.rl import MultiAgentPPO
    algo = (MultiAgentPPO.get_default_config()
            .environment("CoordinationGame")
            .training(train_batch_size=200, sgd_minibatch_size=50,
                      num_sgd_iter=8, lr=3e-3, entropy_coeff=0.01)
            .debugging(seed=0)
            .build())
    try:
        for _ in range(25):
            r = algo.step()
        assert r["episode_reward_mean"] > 15.0, r["episode_reward_mean"]
    finally:
        algo.stop()


def test_shared_policy_batches_are_agent_contiguous():
    """With a shared policy, each agent's trajectory must be a contiguous
    GAE'd segment — interleaving rows would chain one agent's value
    recursion through the other's rewards (regression)."""
    from ray_tpu.rl import MultiAgentRolloutWorker, RockPaperScissorsEnv
    w = MultiAgentRolloutWorker(lambda c: RockPaperScissorsEnv(c),
                                env_config={"episode_len": 10},
                                policy_mapping_fn=lambda aid: "shared",
                                rollout_fragment_length=10, seed=0)
    ma = w.sample()
    b = ma["shared"]
    assert len(b) == 20 and ma.env_steps == 10
    truncs = np.nonzero(b[SB.TRUNCATEDS])[0].tolist()
    # one truncation at the end of EACH agent's contiguous 10-row block
    assert truncs == [9, 19], truncs
    # zero-sum: per-episode rewards of the two blocks are exact negations
    np.testing.assert_allclose(b[SB.REWARDS][:10], -b[SB.REWARDS][10:])
    assert np.isfinite(b[SB.ADVANTAGES]).all()
    assert "bootstrap_values" in b  # truncation bootstraps V(terminal obs)


# -- offline RL ------------------------------------------------------------

class _CartPoleExpert:
    """Hand-coded balance controller: near-optimal behavior policy."""
    continuous = False

    def compute_actions(self, obs, explore=True):
        a = (obs[:, 2] + 0.5 * obs[:, 3] > 0).astype(np.int64)
        z = np.zeros(len(a), np.float32)
        return a, z, z


def _expert_dataset(n_steps=4000):
    from ray_tpu.rl import collect_dataset
    return collect_dataset("CartPole-v1", policy=_CartPoleExpert(),
                           n_steps=n_steps, seed=0)


def test_offline_dataset_io_roundtrip(tmp_path):
    from ray_tpu.rl import read_dataset, write_dataset
    ds = _expert_dataset(300)
    write_dataset(ds.slice(0, 150), str(tmp_path / "shard-000.npz"))
    write_dataset(ds.slice(150, 300), str(tmp_path / "shard-001.npz"))
    back = read_dataset(str(tmp_path / "shard-*.npz"))
    assert len(back) == 300
    np.testing.assert_array_equal(back[SB.OBS], ds[SB.OBS])
    np.testing.assert_array_equal(back[SB.ACTIONS], ds[SB.ACTIONS])


def test_bc_clones_expert(tmp_path):
    """BC on an expert CartPole dataset must reach near-expert return
    (reference: rllib/algorithms/bc learning tests)."""
    from ray_tpu.rl import BC, write_dataset
    ds = _expert_dataset()
    path = str(tmp_path / "expert.npz")
    write_dataset(ds, path)   # exercise the path-input route
    bc = (BC.get_default_config().environment("CartPole-v1")
          .training(input_=path, n_updates_per_iter=64)
          .debugging(seed=0).build())
    try:
        for _ in range(10):
            r = bc.step()
        assert r["dataset_size"] == len(ds)
        assert bc.evaluate(n_episodes=3) >= 300.0
    finally:
        bc.stop()


def test_cql_learns_from_offline_data():
    """CQL (TD + conservative penalty) on the same dataset also recovers
    a balancing policy without any environment interaction."""
    from ray_tpu.rl import CQL
    cql = (CQL.get_default_config().environment("CartPole-v1")
           .training(input_=_expert_dataset(), n_updates_per_iter=64,
                     cql_alpha=1.0)
           .debugging(seed=0).build())
    try:
        for _ in range(15):
            r = cql.step()
        assert r["cql_penalty"] < 2.0   # OOD gap driven down
        assert cql.evaluate(n_episodes=3) >= 300.0
    finally:
        cql.stop()


# -- TD3 -------------------------------------------------------------------

def test_td3_policy_deterministic_and_bounded():
    from ray_tpu.rl.td3 import DeterministicPolicy
    env = PendulumEnv({"seed": 0})
    pol = DeterministicPolicy(env.spec, seed=0)
    obs = np.stack([env.reset(seed=i) for i in range(8)])
    a1, _, _ = pol.compute_actions(obs, explore=False)
    a2, _, _ = pol.compute_actions(obs, explore=False)
    np.testing.assert_allclose(a1, a2)          # deterministic
    ae, _, _ = pol.compute_actions(obs, explore=True)
    assert not np.allclose(a1, ae)              # exploration noise
    for a in (a1, ae):
        assert np.all(a >= -2.0) and np.all(a <= 2.0)


def test_td3_learns_pendulum():
    """TD3 (twin critics, target smoothing, delayed actor) must lift
    Pendulum return well above the ~-1300 random level."""
    from ray_tpu.rl import TD3
    algo = (TD3.get_default_config()
            .environment("Pendulum-v1")
            .training(train_batch_size=128, n_updates_per_iter=8,
                      num_steps_sampled_before_learning_starts=256)
            .debugging(seed=0)
            .build())
    try:
        early = []
        for _ in range(900):
            r = algo.step()
            rew = r.get("episode_reward_mean")
            if rew is not None and len(early) < 5:
                early.append(rew)
        final = r["episode_reward_mean"]
        # measured (seed 0): -1285 at the trough, -746 by iter 900
        assert final > -850, (early, final)
        assert final - float(np.mean(early)) > 150, (early, final)
    finally:
        algo.stop()


def test_a2c_learns_cartpole():
    """A2C (single-pass vanilla PG with baseline) improves CartPole —
    the PPO program evaluated at its ratio=1 fixed point."""
    from ray_tpu.rl import A2C
    algo = (A2C.get_default_config()
            .environment("CartPole-v1")
            .rollouts(num_rollout_workers=0, num_envs_per_worker=8,
                      rollout_fragment_length=25)
            .debugging(seed=0).build())
    try:
        first = None
        for _ in range(200):
            r = algo.train()
            if first is None and "episode_reward_mean" in r:
                first = r["episode_reward_mean"]
        final = r["episode_reward_mean"]
        assert final > 100, (first, final)   # measured: 16 -> 164 (seed 0)
        assert final > first + 50
    finally:
        algo.stop()


def test_appo_learns_cartpole(ray_start_regular):
    """APPO: async workers + V-trace + PPO clipped surrogate improves
    CartPole within a small budget."""
    from ray_tpu.rl import APPO
    algo = (APPO.get_default_config()
            .environment("CartPole-v1")
            .rollouts(num_rollout_workers=2, num_envs_per_worker=4,
                      rollout_fragment_length=50)
            .debugging(seed=0).build())
    try:
        first = None
        for _ in range(200):
            r = algo.train()
            if first is None and "episode_reward_mean" in r:
                first = r["episode_reward_mean"]
        final = r["episode_reward_mean"]
        # measured (seed 0): 21.9 -> 159 over 200 async rounds
        assert final > first + 40, (first, final)
        assert final > 80, (first, final)
    finally:
        algo.stop()
