"""Collective API over the 8-device CPU mesh.

Models ``python/ray/util/collective/tests/`` (single/multi-process variants).
The xla backend binds each rank to one virtual device; ops compile as one
shard_map program over the group mesh.
"""

import numpy as np
import pytest

import ray_tpu
from ray_tpu.collective import ReduceOp


def _spawn_group(n, backend):
    @ray_tpu.remote(num_cpus=0.1)
    class Member:
        def __init__(self, rank):
            self.rank = rank

        def run(self, fn_name, *args, **kwargs):
            from ray_tpu import collective as col
            return getattr(col, fn_name)(*args, **kwargs)

    actors = [Member.remote(i) for i in range(n)]
    from ray_tpu.collective import create_collective_group
    create_collective_group(actors, n, list(range(n)), backend=backend,
                            group_name=f"g_{backend}_{n}")
    return actors, f"g_{backend}_{n}"


@pytest.mark.parametrize("backend", ["xla", "cpu"])
def test_allreduce(ray_start_regular, backend):
    n = 4
    actors, gname = _spawn_group(n, backend)
    refs = [a.run.remote("allreduce", np.full((8, 16), float(i + 1)), gname)
            for i, a in enumerate(actors)]
    out = ray_tpu.get(refs)
    expected = sum(range(1, n + 1))
    for o in out:
        np.testing.assert_allclose(np.asarray(o), expected)


@pytest.mark.parametrize("backend", ["xla", "cpu"])
def test_allreduce_max(ray_start_regular, backend):
    n = 4
    actors, gname = _spawn_group(n, backend)
    refs = [a.run.remote("allreduce", np.full((4,), float(i)), gname,
                         ReduceOp.MAX)
            for i, a in enumerate(actors)]
    for o in ray_tpu.get(refs):
        np.testing.assert_allclose(np.asarray(o), n - 1)


@pytest.mark.parametrize("backend", ["xla", "cpu"])
def test_broadcast(ray_start_regular, backend):
    n = 4
    actors, gname = _spawn_group(n, backend)
    refs = [a.run.remote("broadcast", np.full((4,), float(i)), 2, gname)
            for i, a in enumerate(actors)]
    for o in ray_tpu.get(refs):
        np.testing.assert_allclose(np.asarray(o), 2.0)


@pytest.mark.parametrize("backend", ["xla", "cpu"])
def test_allgather(ray_start_regular, backend):
    n = 4
    actors, gname = _spawn_group(n, backend)
    refs = [a.run.remote("allgather", np.full((2,), float(i)), gname)
            for i, a in enumerate(actors)]
    for o in ray_tpu.get(refs):
        arr = np.asarray(o)
        assert arr.shape == (n, 2)
        np.testing.assert_allclose(arr[:, 0], np.arange(n, dtype=float))


@pytest.mark.parametrize("backend", ["xla", "cpu"])
def test_reducescatter(ray_start_regular, backend):
    n = 4
    actors, gname = _spawn_group(n, backend)
    # Each rank contributes an (n*2,) tensor; rank r receives chunk r of sum.
    refs = [a.run.remote("reducescatter",
                         np.arange(n * 2, dtype=float) + i, gname)
            for i, a in enumerate(actors)]
    out = ray_tpu.get(refs)
    full = sum(np.arange(n * 2, dtype=float) + i for i in range(n))
    for r, o in enumerate(out):
        np.testing.assert_allclose(np.asarray(o).ravel(),
                                   full[r * 2:(r + 1) * 2])


@pytest.mark.parametrize("backend", ["xla", "cpu"])
def test_reduce_only_root(ray_start_regular, backend):
    n = 4
    actors, gname = _spawn_group(n, backend)
    refs = [a.run.remote("reduce", np.full((3,), float(i + 1)), 1, gname)
            for i, a in enumerate(actors)]
    out = ray_tpu.get(refs)
    np.testing.assert_allclose(np.asarray(out[1]), 10.0)
    np.testing.assert_allclose(np.asarray(out[0]), 1.0)  # non-root unchanged


@pytest.mark.parametrize("backend", ["xla", "cpu"])
def test_send_recv(ray_start_regular, backend):
    n = 2
    actors, gname = _spawn_group(n, backend)
    r_send = actors[0].run.remote("send", np.arange(5, dtype=float), 1, gname)
    r_recv = actors[1].run.remote("recv", 0, gname)
    ray_tpu.get(r_send)
    np.testing.assert_allclose(np.asarray(ray_tpu.get(r_recv)),
                               np.arange(5, dtype=float))


def test_barrier(ray_start_regular):
    n = 4
    actors, gname = _spawn_group(n, "cpu")
    refs = [a.run.remote("barrier", gname) for a in actors]
    ray_tpu.get(refs)  # completes without deadlock


def test_group_rank_introspection(ray_start_regular):
    n = 3
    actors, gname = _spawn_group(n, "cpu")
    refs = [a.run.remote("get_rank", gname) for a in actors]
    assert sorted(ray_tpu.get(refs)) == [0, 1, 2]
    refs = [a.run.remote("get_collective_group_size", gname) for a in actors]
    assert ray_tpu.get(refs) == [3, 3, 3]
