"""Regression tests for review findings on the core runtime."""

import threading
import time

import pytest

import ray_tpu
from ray_tpu.util.placement_group import placement_group


def test_wait_returns_at_most_num_returns(ray_start_regular):
    @ray_tpu.remote
    def f(i):
        return i

    refs = [f.remote(i) for i in range(3)]
    ray_tpu.get(refs)  # all done
    ready, not_ready = ray_tpu.wait(refs, num_returns=1)
    assert len(ready) == 1 and len(not_ready) == 2


def test_infeasible_placement_group_wait_returns_false(ray_start_regular):
    pg = placement_group([{"CPU": 10000}], strategy="PACK")
    assert pg.wait(2) is False


def test_actor_restart_releases_resources(ray_start_regular):
    """A restarting actor must not leak its old allocation (the node only
    has capacity for one incarnation)."""
    @ray_tpu.remote(num_cpus=8, max_restarts=2)
    class Big:
        def ping(self):
            return "pong"

    a = Big.remote()
    assert ray_tpu.get(a.ping.remote(), timeout=10) == "pong"
    ray_tpu.kill(a, no_restart=False)
    time.sleep(0.3)
    assert ray_tpu.get(a.ping.remote(), timeout=10) == "pong"


def test_kill_with_restart_on_infinite_restarts(ray_start_regular):
    @ray_tpu.remote(max_restarts=-1)
    class Eternal:
        def ping(self):
            return 1

    a = Eternal.remote()
    assert ray_tpu.get(a.ping.remote(), timeout=10) == 1
    ray_tpu.kill(a, no_restart=False)
    time.sleep(0.3)
    assert ray_tpu.get(a.ping.remote(), timeout=10) == 1


def test_hard_affinity_waits_for_busy_node(ray_start_cluster):
    from ray_tpu.util.scheduling_strategies import NodeAffinitySchedulingStrategy
    cluster = ray_start_cluster
    node = cluster.add_node(num_cpus=1)

    @ray_tpu.remote(num_cpus=1)
    def busy():
        time.sleep(0.3)
        return "first"

    @ray_tpu.remote(num_cpus=1)
    def queued():
        return "second"

    strat = NodeAffinitySchedulingStrategy(node_id=node.node_id.hex(), soft=False)
    r1 = busy.options(scheduling_strategy=strat).remote()
    r2 = queued.options(scheduling_strategy=strat).remote()
    assert ray_tpu.get([r1, r2], timeout=10) == ["first", "second"]


def test_concurrent_driver_puts_unique(ray_start_regular):
    results = {}

    def do_puts(tag):
        refs = [ray_tpu.put((tag, i)) for i in range(50)]
        results[tag] = ray_tpu.get(refs)

    threads = [threading.Thread(target=do_puts, args=(t,)) for t in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    for tag in range(4):
        assert results[tag] == [(tag, i) for i in range(50)]


# -- data-race regressions (raylint R23) -------------------------------------
# Deterministic two-thread schedules reproducing races the field-level
# lockset analysis surfaced.  Each failed on the pre-fix code: the
# interleaving is forced with events/barriers, not sleeps.


def test_perf_bounds_reset_race_publishes_fresh_layout():
    """A ``bucket_bounds()`` compute in flight across a ``reset()`` must
    not publish its stale layout over the freshly computed one.  Pre-fix
    the loser thread's unconditional store clobbered ``_bounds_cache``
    with the old bucket count, and every histogram minted afterwards
    disagreed with the config."""
    from ray_tpu._private.config import _config
    from ray_tpu.observability import perf

    old_n = _config.get("perf_hist_buckets")
    real_get = _config.get
    entered = threading.Event()
    release = threading.Event()

    def slow_get(name):
        if name == "perf_hist_buckets" and not entered.is_set():
            entered.set()
            release.wait(5)
            return 8            # the stale pre-reset layout
        return real_get(name)

    perf.reset()
    out = {}

    def compute():
        out["bounds"] = perf.bucket_bounds()

    try:
        _config.get = slow_get
        t = threading.Thread(target=compute, daemon=True)
        t.start()
        assert entered.wait(5), "compute thread never reached the config read"
        _config.get = real_get
        _config.set("perf_hist_buckets", 16)
        perf.reset()            # invalidates the in-flight compute
        assert len(perf.bucket_bounds()) == 16
        release.set()
        t.join(5)
        assert not t.is_alive()
        # pre-fix: the resumed thread overwrote the cache with 8 bounds
        assert len(perf.bucket_bounds()) == 16
        assert len(out["bounds"]) in (8, 16)  # the loser saw one layout or the other
    finally:
        _config.get = real_get
        release.set()
        _config.set("perf_hist_buckets", old_n)
        perf.reset()


def test_backoff_retry_counter_minted_once_under_race():
    """Two first-retry threads racing through ``_count_retry`` must mint
    ONE ``Counter``.  Pre-fix both saw the ``None`` singleton and each
    constructed+registered its own — the first thread's increments landed
    on an orphaned series the exposition never showed.  The barrier in
    the patched constructor proves both threads were inside construction
    simultaneously on the racy code; with the creation lock only one
    ever gets there."""
    from ray_tpu._private import backoff
    from ray_tpu.util import metrics

    saved_counter = backoff._retry_counter
    real_counter_cls = metrics.Counter
    with metrics._registry._lock:
        saved_reg = metrics._registry._metrics.pop("backoff_retries_total", None)
    backoff._retry_counter = None

    made = []
    barrier = threading.Barrier(2)

    class RacyCounter(real_counter_cls):
        def __init__(self, *a, **k):
            made.append(threading.get_ident())
            try:
                barrier.wait(0.5)   # pre-fix: both racers meet here
            except threading.BrokenBarrierError:
                pass
            super().__init__(*a, **k)

    try:
        metrics.Counter = RacyCounter
        ts = [threading.Thread(target=backoff._count_retry, args=("site-a",))
              for _ in range(2)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(10)
        assert len(made) == 1, f"counter constructed {len(made)}x under race"
        assert backoff._retry_counter is not None
    finally:
        metrics.Counter = real_counter_cls
        backoff._retry_counter = saved_counter
        with metrics._registry._lock:
            if saved_reg is not None:
                metrics._registry._metrics["backoff_retries_total"] = saved_reg
            else:
                metrics._registry._metrics.pop("backoff_retries_total", None)
