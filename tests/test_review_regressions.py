"""Regression tests for review findings on the core runtime."""

import threading
import time

import pytest

import ray_tpu
from ray_tpu.util.placement_group import placement_group


def test_wait_returns_at_most_num_returns(ray_start_regular):
    @ray_tpu.remote
    def f(i):
        return i

    refs = [f.remote(i) for i in range(3)]
    ray_tpu.get(refs)  # all done
    ready, not_ready = ray_tpu.wait(refs, num_returns=1)
    assert len(ready) == 1 and len(not_ready) == 2


def test_infeasible_placement_group_wait_returns_false(ray_start_regular):
    pg = placement_group([{"CPU": 10000}], strategy="PACK")
    assert pg.wait(2) is False


def test_actor_restart_releases_resources(ray_start_regular):
    """A restarting actor must not leak its old allocation (the node only
    has capacity for one incarnation)."""
    @ray_tpu.remote(num_cpus=8, max_restarts=2)
    class Big:
        def ping(self):
            return "pong"

    a = Big.remote()
    assert ray_tpu.get(a.ping.remote(), timeout=10) == "pong"
    ray_tpu.kill(a, no_restart=False)
    time.sleep(0.3)
    assert ray_tpu.get(a.ping.remote(), timeout=10) == "pong"


def test_kill_with_restart_on_infinite_restarts(ray_start_regular):
    @ray_tpu.remote(max_restarts=-1)
    class Eternal:
        def ping(self):
            return 1

    a = Eternal.remote()
    assert ray_tpu.get(a.ping.remote(), timeout=10) == 1
    ray_tpu.kill(a, no_restart=False)
    time.sleep(0.3)
    assert ray_tpu.get(a.ping.remote(), timeout=10) == 1


def test_hard_affinity_waits_for_busy_node(ray_start_cluster):
    from ray_tpu.util.scheduling_strategies import NodeAffinitySchedulingStrategy
    cluster = ray_start_cluster
    node = cluster.add_node(num_cpus=1)

    @ray_tpu.remote(num_cpus=1)
    def busy():
        time.sleep(0.3)
        return "first"

    @ray_tpu.remote(num_cpus=1)
    def queued():
        return "second"

    strat = NodeAffinitySchedulingStrategy(node_id=node.node_id.hex(), soft=False)
    r1 = busy.options(scheduling_strategy=strat).remote()
    r2 = queued.options(scheduling_strategy=strat).remote()
    assert ray_tpu.get([r1, r2], timeout=10) == ["first", "second"]


def test_concurrent_driver_puts_unique(ray_start_regular):
    results = {}

    def do_puts(tag):
        refs = [ray_tpu.put((tag, i)) for i in range(50)]
        results[tag] = ray_tpu.get(refs)

    threads = [threading.Thread(target=do_puts, args=(t,)) for t in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    for tag in range(4):
        assert results[tag] == [(tag, i) for i in range(50)]
