"""Preemption-aware node lifecycle: graceful drain with live workload
migration (reference: the autoscaler drain protocol + node manager
DrainRaylet, src/ray/raylet/node_manager.cc; here the drain orchestrator
in distributed.py).

Two layers:

- unit coverage that runs everywhere: scheduler DRAINING exclusion, the
  ``node.preempt`` chaos watcher, drain-aware doctor triage, replica
  drain-snapshot pickling, WAIT_OBJECT backoff pacing;
- ProcessCluster drills (skip without the C++ state service): the
  explicit ``ray_tpu.drain_node`` migration and the chaos preemption
  drill — zero task loss, actor state continuity through the checkpoint
  engine, sole-copy object availability WITHOUT lineage re-execution.
"""

import os
import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu.cluster_utils import ProcessCluster


def _require_state_service():
    """ProcessCluster needs the C++ state service (protoc + g++)."""
    from ray_tpu._native.build import build_state_service
    try:
        build_state_service()
    except Exception as e:
        pytest.skip(f"state service unavailable: {e}")


# -- unit: scheduler exclusion ----------------------------------------------

def _node(tag: int, draining: bool = False, alive: bool = True):
    from ray_tpu._private.ids import NodeID
    from ray_tpu._private.resources import NodeResources, ResourceSet
    from ray_tpu._private.scheduler import NodeState
    nr = NodeResources(ResourceSet({"CPU": 4.0}))
    return NodeState(NodeID(bytes([tag]) * 16), nr, alive,
                     draining=draining)


def test_draining_node_not_schedulable():
    assert _node(1).schedulable
    assert not _node(1, draining=True).schedulable
    assert not _node(1, alive=False).schedulable


def test_policies_exclude_draining_nodes():
    from ray_tpu._private.resources import ResourceSet
    from ray_tpu._private.scheduler import (HybridPolicy, NodeAffinityPolicy,
                                            SpreadPolicy)
    req = ResourceSet({"CPU": 1.0})
    healthy, draining = _node(1), _node(2, draining=True)
    nodes = [draining, healthy]
    for _ in range(8):
        assert HybridPolicy(seed=0).select(nodes, req) == healthy.node_id
        assert SpreadPolicy().select(nodes, req) == healthy.node_id
    # every candidate draining -> nothing selectable (callers queue)
    assert HybridPolicy(seed=0).select([draining], req) is None
    assert SpreadPolicy().select([draining], req) is None
    # soft affinity to a draining node falls through to a healthy one
    assert NodeAffinityPolicy().select(
        nodes, req, node_id_hex=draining.node_id.hex(),
        soft=True) == healthy.node_id


def test_flatten_reports_draining_as_not_alive():
    """The native kernels have no DRAINING notion: _flatten folds
    schedulability into their alive[] array."""
    from ray_tpu._private.resources import ResourceSet
    from ray_tpu._private.scheduler import _flatten
    _avail, _total, alive, _req, n, _r = _flatten(
        [_node(1), _node(2, draining=True)], ResourceSet({"CPU": 1.0}))
    assert n == 2
    assert list(alive) == [1, 0]


# -- unit: preemption watcher (node.preempt chaos point) --------------------

def test_preempt_watcher_fires_on_chaos_signal():
    from ray_tpu import chaos
    from ray_tpu._private.host_daemon import _preempt_signaled
    chaos.configure(7, "node.preempt@2=drop")
    try:
        assert _preempt_signaled("abcd1234") is None       # poll 1: clean
        reason = _preempt_signaled("abcd1234")             # poll 2: notice
        assert reason and "preempt" in reason
    finally:
        chaos.clear()
    assert _preempt_signaled("abcd1234") is None           # chaos off


# -- unit: WAIT_OBJECT pacing ----------------------------------------------

def test_wait_object_backoff_pacing():
    """The WAIT_OBJECT handler paces its seal re-checks with BackoffPolicy
    (5ms first wake, capped at the old fixed 0.25s) instead of a constant
    0.25s sleep per attempt."""
    from ray_tpu._private.backoff import BackoffPolicy
    pace = BackoffPolicy(base_s=0.005, max_s=0.25, deadline_s=0,
                         jitter=False)
    delays = [pace.delay_for(a) for a in range(12)]
    assert delays[0] == pytest.approx(0.005)
    assert all(b >= a for a, b in zip(delays, delays[1:]))
    assert max(delays) == pytest.approx(0.25)


# -- unit: actor restore hook ----------------------------------------------

def test_base_runtime_restore_hook_is_noop():
    from ray_tpu._private.runtime import Runtime
    rt = Runtime.__new__(Runtime)
    assert rt._restore_drained_actor(object()) is None


# -- unit: serve replica drain snapshot -------------------------------------

def test_replica_pickles_without_lock_and_undrained():
    import cloudpickle
    from ray_tpu.serve._private.replica import Replica
    r = Replica("d", "d#1", lambda req: req, (), {})
    with r._lock:
        pass  # the lock exists and works
    r._draining = True
    r._ongoing = 3
    r._total = 9
    clone = cloudpickle.loads(cloudpickle.dumps(r))
    # migrated snapshot: fresh lock, accepting requests, no phantom
    # in-flight counts — but served-total history survives
    assert not clone._draining
    assert clone._ongoing == 0
    assert clone._total == 9
    with clone._lock:
        pass


# -- unit: doctor drain triage ----------------------------------------------

def _synthetic_collection(nid_draining, nid_drained, nid_dead):
    return {
        "ts": 1.0, "errors": [], "sealed_now": [],
        "local": {"root": "/tmp/x", "recordings": [], "bundles": []},
        "cluster": {
            "nodes": {"nodes": [
                {"node_id": nid_draining, "alive": True,
                 "state": "DRAINING",
                 "drain_reason": "preemption notice (chaos)"},
                {"node_id": nid_drained, "alive": False, "state": "DRAINED",
                 "death_reason": "drained: operator"},
                {"node_id": nid_dead, "alive": False, "state": "DEAD",
                 "death_reason": "heartbeat timeout"},
            ]},
            "forensics": {"nodes": {}, "missing_hosts": [
                {"node_id": nid_draining, "address": "x", "error": "conn"}]},
            "timeline": {"traceEvents": []},
            "metrics": {
                "snapshots": {nid_draining[:8]: [
                    {"name": "heartbeat_consecutive_misses",
                     "samples": [("hb", (("node", nid_draining[:8]),),
                                  3.0)]}]},
                "missing_hosts": []},
            "drain": {nid_draining: {"phase": "objects",
                                     "tasks_pending": 0,
                                     "actors_checkpointed": 1,
                                     "objects_migrated": 2}},
        },
    }


def test_doctor_classifies_draining_as_expected_not_hang():
    from ray_tpu import doctor
    rep = doctor.diagnose(_synthetic_collection("aa" * 14, "bb" * 14,
                                                "cc" * 14))
    assert rep["hangs"] == []                  # draining misses != hang
    assert rep["unreachable_hosts"] == []      # mid-decommission: expected
    (d,) = rep["draining_nodes"]
    assert d["progress"]["objects_migrated"] == 2
    assert d["heartbeat_misses"] == [3.0]
    assert len(rep["drained_nodes"]) == 1      # clean decommission
    assert len(rep["dead_nodes"]) == 1         # only the real death counts
    assert rep["num_issues"] == 1
    text = doctor.render_text(rep)
    assert "draining (expected)" in text
    assert "DRAINED NODES (1)" in text


def test_doctor_genuine_hang_still_reported():
    from ray_tpu import doctor
    coll = _synthetic_collection("aa" * 14, "bb" * 14, "cc" * 14)
    coll["cluster"]["nodes"]["nodes"][0]["state"] = "ALIVE"
    del coll["cluster"]["drain"]
    rep = doctor.diagnose(coll)
    assert len(rep["hangs"]) == 1
    assert len(rep["unreachable_hosts"]) == 1
    assert rep["draining_nodes"] == []


# -- ProcessCluster drills ---------------------------------------------------

@ray_tpu.remote(max_restarts=2)
class Keeper:
    """Stateful actor whose continuity proves checkpoint/restore: a
    fresh ``__init__`` would reset ``n`` to 0."""

    def __init__(self):
        self.n = 0
        self.blob_calls = 0
        self.resumed = False

    def inc(self):
        self.n += 1
        return self.n

    def where(self):
        import ray_tpu._private.worker as w
        return (w.global_worker().runtime.local_node.node_id.hex(),
                os.getpid())

    def make_blob(self):
        self.blob_calls += 1
        return np.full((900, 900), 4.5)  # ~6.5 MB: lives in the daemon store

    def stats(self):
        return self.n, self.blob_calls, self.resumed

    def resume_after_drain(self):
        self.resumed = True


def _actor_call_with_retry(method, deadline_s, *call_args):
    """An actor mid-restart surfaces transient errors; poll to a deadline."""
    deadline = time.monotonic() + deadline_s
    last = None
    while time.monotonic() < deadline:
        try:
            return ray_tpu.get(method.remote(*call_args), timeout=15)
        except (ray_tpu.exceptions.RayTpuError, TimeoutError) as e:
            last = e
            time.sleep(0.5)  # raylint: allow(bare-retry) deadline-bounded test poll
    raise AssertionError(f"actor never came back: {last!r}")


def test_drain_node_explicit_migration():
    """ray_tpu.drain_node on the node hosting an actor + a sole-copy
    object: every task completes, the actor resumes FROM CHECKPOINT on a
    survivor, and the object is fetched from its migrated copy without
    lineage re-execution."""
    _require_state_service()
    ray_tpu.shutdown()
    c = ProcessCluster(num_daemons=3, num_cpus=2)
    try:
        ray_tpu.init(address=c.address)
        rt = ray_tpu._private.worker.global_worker().runtime

        k = Keeper.remote()
        assert ray_tpu.get([k.inc.remote() for _ in range(3)],
                           timeout=60) == [1, 2, 3]
        victim_node, victim_pid = ray_tpu.get(k.where.remote(), timeout=30)
        blob = k.make_blob.remote()          # sole copy on the victim node
        ray_tpu.wait([blob], timeout=60)     # sealed before the drain

        @ray_tpu.remote(max_retries=3)
        def slow(i):
            time.sleep(0.3)
            return i

        refs = [slow.remote(i) for i in range(24)]
        time.sleep(0.5)                      # let pushes land cluster-wide

        ray_tpu.drain_node(victim_node, reason="test migration",
                           deadline_s=30.0)

        # 1) zero task loss
        assert sorted(ray_tpu.get(refs, timeout=120)) == list(range(24))

        # 2) the node decommissions with the drained stamp
        deadline = time.monotonic() + 60
        stamped = None
        while time.monotonic() < deadline:
            info = {n.node_id.hex(): n for n in rt.state.list_nodes()}
            n = info.get(victim_node)
            if n is not None and not n.alive:
                stamped = n
                break
            time.sleep(0.5)
        assert stamped is not None, "victim node never decommissioned"
        assert stamped.death_reason.startswith("drained"), \
            stamped.death_reason

        # 3) actor state continuity: n continues from the checkpointed 3
        assert _actor_call_with_retry(k.inc, 90) == 4
        new_node, new_pid = _actor_call_with_retry(k.where, 30)
        assert new_node != victim_node and new_pid != victim_pid
        n, blob_calls, resumed = _actor_call_with_retry(k.stats, 30)
        assert n == 4 and resumed, (n, resumed)

        # 4) sole-copy object: fetched from the migrated replica, not
        #    re-executed through lineage
        arr = ray_tpu.get(blob, timeout=60)
        assert float(arr[0, 0]) == 4.5 and arr.shape == (900, 900)
        assert _actor_call_with_retry(k.stats, 30)[1] == 1, \
            "make_blob re-executed: migration failed"
        assert not any(e["kind"] == "OBJECT_RECONSTRUCT"
                       for e in rt._events), \
            "object went through lineage re-execution"
    finally:
        ray_tpu.shutdown()
        c.shutdown()


def test_serve_requests_survive_drain():
    """Drain the node hosting a serve replica mid-stream: the replica
    migrates (drain snapshot -> checkpoint -> restart on a survivor) and
    the router's retry path keeps every request 503-free."""
    _require_state_service()
    ray_tpu.shutdown()
    c = ProcessCluster(num_daemons=3, num_cpus=2)
    try:
        ray_tpu.init(address=c.address)
        from ray_tpu import serve

        @serve.deployment(num_replicas=2)
        def who(req):
            return {"pid": os.getpid(), "v": req}

        h = serve.run(who.bind(), name="who")
        try:
            first = h.remote(-1).result(timeout=30)
            rt = ray_tpu._private.worker.global_worker().runtime
            victim_addr = next(d["address"] for d in c.daemons
                               if d["proc"].pid == first["pid"])
            victim_node = next(n.node_id.hex()
                               for n in rt.state.list_nodes()
                               if n.address == victim_addr)
            ray_tpu.drain_node(victim_node, reason="serve drill",
                               deadline_s=30.0)
            # every request through and past the drain must complete —
            # retried onto the surviving/migrated replica, never failed
            results = [h.remote(i).result(timeout=60) for i in range(40)]
            assert [r["v"] for r in results] == list(range(40))
        finally:
            serve.shutdown()
    finally:
        ray_tpu.shutdown()
        c.shutdown()


def test_preemption_chaos_drill():
    """node.preempt chaos on one daemon mid-run: the watcher turns the
    eviction notice into a graceful drain with a 20s lead — all tasks
    complete and the daemon exits 0 after a clean decommission."""
    _require_state_service()
    ray_tpu.shutdown()
    c = ProcessCluster(num_daemons=2, num_cpus=2)
    # third daemon carries the schedule: its 6th watcher poll (~3s after
    # boot at the 500ms default cadence) returns the eviction notice
    c.add_daemon(env={"RAY_TPU_CHAOS": "7:node.preempt@6=drop",
                      "RAY_TPU_PREEMPT_LEAD_S": "20"})
    try:
        ray_tpu.init(address=c.address)
        rt = ray_tpu._private.worker.global_worker().runtime

        @ray_tpu.remote(max_retries=3)
        def slow(i):
            time.sleep(0.4)
            return i

        refs = [slow.remote(i) for i in range(60)]
        out = ray_tpu.get(refs, timeout=180)
        assert sorted(out) == list(range(60)), "tasks lost to preemption"

        deadline = time.monotonic() + 60
        stamped = None
        while time.monotonic() < deadline:
            for n in rt.state.list_nodes():
                if not n.alive and n.death_reason.startswith("drained"):
                    stamped = n
                    break
            if stamped is not None:
                break
            time.sleep(0.5)
        assert stamped is not None, "chaos daemon never drained"
        assert "preempt" in (stamped.drain_reason or stamped.death_reason)

        proc = c.daemons[-1]["proc"]
        assert proc.wait(timeout=60) == 0, "daemon did not exit cleanly"
    finally:
        ray_tpu.shutdown()
        c.shutdown()
