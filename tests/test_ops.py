"""Pallas kernels vs dense references (interpreter mode on the CPU mesh)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.ops import flash_attention


def _dense_attention(q, k, v, causal):
    D = q.shape[-1]
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) / np.sqrt(D)
    if causal:
        L, Lk = q.shape[1], k.shape[1]
        mask = jnp.tril(jnp.ones((L, Lk), bool))
        s = jnp.where(mask[None, None], s, -1e30)
    return jnp.einsum("bhqk,bkhd->bqhd", jax.nn.softmax(s, -1), v)


@pytest.mark.parametrize("causal", [True, False])
def test_flash_attention_forward(causal):
    B, L, H, D = 2, 256, 2, 64
    key = jax.random.PRNGKey(0)
    q, k, v = (jax.random.normal(jax.random.fold_in(key, i), (B, L, H, D))
               for i in range(3))
    out = flash_attention(q, k, v, causal=causal, block_q=128, block_k=128)
    ref = _dense_attention(q, k, v, causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_flash_attention_multi_block_seq():
    B, L, H, D = 1, 512, 1, 64
    key = jax.random.PRNGKey(3)
    q, k, v = (jax.random.normal(jax.random.fold_in(key, i), (B, L, H, D))
               for i in range(3))
    out = flash_attention(q, k, v, causal=True, block_q=128, block_k=128)
    ref = _dense_attention(q, k, v, True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("causal", [True, False])
def test_flash_attention_grad(causal):
    B, L, H, D = 1, 256, 2, 32
    key = jax.random.PRNGKey(1)
    q, k, v = (jax.random.normal(jax.random.fold_in(key, i), (B, L, H, D))
               for i in range(3))

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=causal,
                                       block_q=128, block_k=128) ** 2)

    def loss_dense(q, k, v):
        return jnp.sum(_dense_attention(q, k, v, causal) ** 2)

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_dense = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for gf, gd, name in zip(g_flash, g_dense, "qkv"):
        np.testing.assert_allclose(
            np.asarray(gf), np.asarray(gd), rtol=2e-4, atol=2e-4,
            err_msg=f"grad mismatch for {name}")


def test_flash_attention_bf16():
    B, L, H, D = 2, 128, 2, 64
    key = jax.random.PRNGKey(2)
    q, k, v = (jax.random.normal(jax.random.fold_in(key, i), (B, L, H, D),
                                 dtype=jnp.bfloat16) for i in range(3))
    out = flash_attention(q, k, v, causal=True)
    assert out.dtype == jnp.bfloat16
    ref = _dense_attention(q.astype(jnp.float32), k.astype(jnp.float32),
                           v.astype(jnp.float32), True)
    np.testing.assert_allclose(np.asarray(out, dtype=np.float32),
                               np.asarray(ref), rtol=2e-2, atol=2e-2)


@pytest.mark.parametrize("causal", [True, False])
def test_flash_attention_ragged_seqlen(causal):
    """Seqlen not divisible by block size: pad columns must not leak."""
    B, L, H, D = 1, 200, 2, 32
    key = jax.random.PRNGKey(4)
    q, k, v = (jax.random.normal(jax.random.fold_in(key, i), (B, L, H, D))
               for i in range(3))
    out = flash_attention(q, k, v, causal=causal, block_q=128, block_k=128)
    ref = _dense_attention(q, k, v, causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_flash_attention_ragged_grad():
    B, L, H, D = 1, 200, 1, 32
    key = jax.random.PRNGKey(5)
    q, k, v = (jax.random.normal(jax.random.fold_in(key, i), (B, L, H, D))
               for i in range(3))
    g_flash = jax.grad(lambda q: jnp.sum(
        flash_attention(q, k, v, causal=False) ** 2))(q)
    g_dense = jax.grad(lambda q: jnp.sum(
        _dense_attention(q, k, v, False) ** 2))(q)
    np.testing.assert_allclose(np.asarray(g_flash), np.asarray(g_dense),
                               rtol=2e-4, atol=2e-4)
