"""Train layer: JaxTrainer end-to-end (the minimum e2e slice, SURVEY §7),
checkpoint/resume, failure handling, collective use inside the loop."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

import ray_tpu
from ray_tpu.air import Checkpoint, FailureConfig, RunConfig, ScalingConfig
from ray_tpu.train import JaxTrainer, session


def _linear_loop(config):
    """Tiny synthetic regression trained data-parallel via collective."""
    from ray_tpu import collective as col
    rank = session.get_world_rank()
    world = session.get_world_size()
    key = jax.random.PRNGKey(rank)
    w = jnp.zeros((4,))
    ckpt = session.get_checkpoint()
    start_epoch = 0
    if ckpt is not None:
        state = ckpt.to_dict()
        w = jnp.asarray(state["w"])
        start_epoch = state["epoch"] + 1
    x = jax.random.normal(key, (64, 4))
    true_w = jnp.array([1.0, -2.0, 3.0, 0.5])
    y = x @ true_w

    for epoch in range(start_epoch, config["epochs"]):
        grad = jax.grad(lambda w: jnp.mean((x @ w - y) ** 2))(w)
        if world > 1:
            grad = jnp.asarray(
                col.allreduce(np.asarray(grad),
                              config["group_name"])) / world
        w = w - 0.1 * grad
        loss = float(jnp.mean((x @ w - y) ** 2))
        session.report(
            {"loss": loss, "epoch": epoch},
            checkpoint=Checkpoint.from_dict(
                {"w": np.asarray(w), "epoch": epoch}))


def test_trainer_single_worker(ray_start_regular):
    trainer = JaxTrainer(
        _linear_loop,
        train_loop_config={"epochs": 20, "group_name": None},
        scaling_config=ScalingConfig(num_workers=1),
        collective_backend=None)
    result = trainer.fit()
    assert result.error is None
    assert result.metrics["loss"] < 1.0
    assert result.checkpoint is not None
    assert result.checkpoint.to_dict()["epoch"] == 19


def test_trainer_data_parallel(ray_start_regular):
    trainer = JaxTrainer(
        _linear_loop,
        train_loop_config={"epochs": 15, "group_name": None},
        scaling_config=ScalingConfig(num_workers=4,
                                     resources_per_worker={"CPU": 1}),
        collective_backend="cpu")

    # The executor-created group is exposed on the session (public API).
    def loop(config):
        config = dict(config)
        config["group_name"] = session.get_collective_group_name()
        assert config["group_name"] is not None
        _linear_loop(config)

    trainer._train_loop = loop
    result = trainer.fit()
    assert result.error is None
    assert result.metrics["loss"] < 2.0
    assert len(result.metrics_history) == 15 * 4


def test_trainer_resume_from_checkpoint(ray_start_regular):
    ckpt = Checkpoint.from_dict({"w": np.zeros(4), "epoch": 9})
    trainer = JaxTrainer(
        _linear_loop,
        train_loop_config={"epochs": 12, "group_name": None},
        scaling_config=ScalingConfig(num_workers=1),
        collective_backend=None,
        resume_from_checkpoint=ckpt)
    result = trainer.fit()
    assert result.error is None
    # only epochs 10 and 11 ran
    assert len(result.metrics_history) == 2
    assert result.metrics_history[0]["epoch"] == 10


def test_trainer_worker_failure_restarts(ray_start_regular):
    """A crashing worker triggers group restart from the last checkpoint
    (reference: backend_executor.py:510-531)."""

    def crashy_loop(config):
        ckpt = session.get_checkpoint()
        start = 0 if ckpt is None else ckpt.to_dict()["epoch"] + 1
        for epoch in range(start, 6):
            if epoch == 3 and ckpt is None:
                raise RuntimeError("simulated worker crash")
            session.report({"epoch": epoch},
                           checkpoint=Checkpoint.from_dict({"epoch": epoch}))

    trainer = JaxTrainer(
        crashy_loop, train_loop_config={},
        scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(failure_config=FailureConfig(max_failures=2)),
        collective_backend=None)
    result = trainer.fit()
    assert result.error is None
    assert result.metrics["epoch"] == 5


def test_trainer_failure_exhausted(ray_start_regular):
    def always_crash(config):
        raise RuntimeError("boom")

    trainer = JaxTrainer(
        always_crash, train_loop_config={},
        scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(failure_config=FailureConfig(max_failures=1)),
        collective_backend=None)
    result = trainer.fit()
    assert result.error is not None


def test_checkpoint_directory_roundtrip(tmp_path):
    ckpt = Checkpoint.from_dict({
        "params": {"w": jnp.arange(8.0)},
        "epoch": 3,
    })
    path = ckpt.to_directory(str(tmp_path / "ckpt"))
    restored = Checkpoint.from_directory(path).to_dict()
    assert restored["epoch"] == 3
    np.testing.assert_allclose(np.asarray(restored["params"]["w"]),
                               np.arange(8.0))


def test_trainer_persists_checkpoints_with_pruning(ray_start_regular,
                                                   tmp_path):
    """storage_path routes reported checkpoints through the engine:
    manifests are pruned to num_to_keep and the newest commit restores."""

    def loop(config):
        for epoch in range(5):
            session.report({"epoch": epoch},
                           checkpoint=Checkpoint.from_dict({"epoch": epoch}))

    trainer = JaxTrainer(
        loop, train_loop_config={},
        scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(
            name="exp", storage_path=str(tmp_path),
            checkpoint_config=__import__(
                "ray_tpu.air", fromlist=["CheckpointConfig"]
            ).CheckpointConfig(num_to_keep=2)),
        collective_backend=None)
    result = trainer.fit()
    assert result.error is None
    from ray_tpu.checkpoint import list_manifest_names
    root = str(tmp_path / "exp" / "checkpoints")
    kept = list_manifest_names(root)
    assert len(kept) == 2
    restored = Checkpoint.from_manifest(root).to_dict()
    assert restored["epoch"] == 4


def test_trainer_dataset_shards(ray_start_regular):
    """datasets= splits across the worker group; each worker reads its own
    shard via session.get_dataset_shard (DataParallelTrainer contract)."""
    import ray_tpu.data as rd

    ds = rd.from_items([{"x": float(i)} for i in range(40)])

    def loop(config):
        shard = session.get_dataset_shard("train")
        total, rows = 0.0, 0
        for batch in shard.iter_batches(batch_size=8, batch_format="numpy",
                                        prefetch_batches=1):
            total += float(batch["x"].sum())
            rows += len(batch["x"])
        session.report({"total": total, "rows": rows})

    result = JaxTrainer(
        loop, scaling_config=ScalingConfig(num_workers=2),
        collective_backend=None,
        datasets={"train": ds}).fit()
    assert result.error is None
    rows = [m["rows"] for m in result.metrics_history]
    totals = [m["total"] for m in result.metrics_history]
    assert sum(rows) == 40          # full partition, no overlap/loss
    assert abs(max(rows) - min(rows)) <= 1
    assert sum(totals) == float(sum(range(40)))
