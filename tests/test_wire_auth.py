"""Wire authentication tests.

An unauthenticated socket that reaches a daemon is remote code execution
by design (PUSH_TASK carries cloudpickle), so every daemon/state
connection must open with the cluster's shared secret (reference
analogue: the redis password raylets and drivers must present). The
token rides the first frame of each connection (AUTH method) and is
checked constant-time on both the Python servers and the C++ state
service.
"""

import os

import pytest

import ray_tpu
from ray_tpu._private.rpc import RpcClient, RpcConnectionError
from ray_tpu.cluster_utils import ProcessCluster
from ray_tpu.protocol import pb

TOKEN = "test-secret-token-1234"


@pytest.fixture()
def auth_cluster():
    ray_tpu.shutdown()
    old = os.environ.get("RAY_TPU_AUTH_TOKEN")
    os.environ["RAY_TPU_AUTH_TOKEN"] = TOKEN
    c = ProcessCluster(num_daemons=2, num_cpus=2)
    ray_tpu.init(address=c.address)
    yield c
    ray_tpu.shutdown()
    c.shutdown()
    if old is None:
        os.environ.pop("RAY_TPU_AUTH_TOKEN", None)
    else:
        os.environ["RAY_TPU_AUTH_TOKEN"] = old


def _expect_rejected(address: str, method: int, body: bytes,
                     token: bytes | None):
    """A client with the wrong (or no) token must be dropped before its
    request reaches any handler."""
    try:
        client = RpcClient(address, auth_token=token or b"")
    except RpcConnectionError:
        return  # refused at connect: fine
    try:
        with pytest.raises((RpcConnectionError, TimeoutError)):
            client.call(method, body, timeout=5)
    finally:
        client.close()


def test_authenticated_cluster_works(auth_cluster):
    @ray_tpu.remote
    def f(x):
        return x + 1

    assert ray_tpu.get([f.remote(i) for i in range(8)],
                       timeout=60) == list(range(1, 9))

    @ray_tpu.remote
    class A:
        def ping(self):
            return os.getpid()

    a = A.remote()
    assert ray_tpu.get(a.ping.remote(), timeout=30) != os.getpid()


def test_daemon_rejects_unauthenticated_push(auth_cluster):
    daemon_addr = auth_cluster.daemons[0]["address"]
    msg = pb.TaskSpecMsg(task_id=b"x" * 16, job_id=b"j" * 4,
                         function_name="evil")
    _expect_rejected(daemon_addr, pb.PUSH_TASK, msg.SerializeToString(),
                     token=None)
    _expect_rejected(daemon_addr, pb.PUSH_TASK, msg.SerializeToString(),
                     token=b"wrong-token")


def test_state_service_rejects_unauthenticated(auth_cluster):
    _expect_rejected(auth_cluster.address, pb.LIST_NODES, b"", token=None)
    _expect_rejected(auth_cluster.address, pb.KV_GET,
                     pb.KvGetRequest(ns=b"", key=b"k").SerializeToString(),
                     token=b"wrong")


def test_correct_token_accepted_raw(auth_cluster):
    client = RpcClient(auth_cluster.address, auth_token=TOKEN.encode())
    try:
        rep = pb.ListNodesReply()
        rep.ParseFromString(client.call(pb.LIST_NODES, b"", timeout=10).body)
        assert len(rep.nodes) >= 2
    finally:
        client.close()


def test_oversized_preauth_frame_dropped(auth_cluster):
    """An unauthenticated peer declaring a huge first frame must be
    disconnected immediately — servers must not buffer toward MAX_FRAME
    for a socket that has not authenticated (anti-OOM)."""
    import socket
    import struct

    for address in (auth_cluster.address,
                    auth_cluster.daemons[0]["address"]):
        host, port = address.rsplit(":", 1)
        s = socket.create_connection((host, int(port)), timeout=5)
        try:
            s.settimeout(5)
            # declare a 512 MiB frame and start streaming garbage
            s.sendall(struct.pack(">I", 512 << 20))
            dropped = False
            try:
                for _ in range(64):
                    s.sendall(b"\x00" * (1 << 16))
                # server should have closed on us: recv sees EOF
                s.settimeout(2)
                dropped = s.recv(1) == b""
            except (BrokenPipeError, ConnectionResetError, socket.timeout,
                    OSError):
                dropped = True
            assert dropped, f"{address} kept buffering an unauthenticated " \
                            "oversized frame"
        finally:
            s.close()
