"""Workflow tests: durable execution, failure, resume, events.

Models the reference's ``python/ray/workflow/tests/`` (basic workflows,
recovery, events).
"""

import time

import pytest

import ray_tpu
from ray_tpu import workflow


@pytest.fixture
def wf(ray_start_regular, tmp_path):
    workflow.init(storage_base_dir=str(tmp_path))
    yield str(tmp_path)


@ray_tpu.remote
def add(a, b):
    return a + b


@ray_tpu.remote
def mul(a, b):
    return a * b


def test_run_simple_dag(wf):
    dag = add.bind(mul.bind(2, 3), mul.bind(4, 5))
    assert workflow.run(dag, workflow_id="w1") == 26
    assert workflow.get_status("w1") == "SUCCESS"
    assert workflow.get_output("w1") == 26


def test_rerun_returns_stored_output(wf):
    calls = []

    @ray_tpu.remote
    def effect(x):
        calls.append(x)
        return x

    # Side-effect function defined locally still runs through the runtime;
    # calls list is shared because tasks execute in-process threads.
    assert workflow.run(effect.bind(7), workflow_id="w2") == 7
    assert workflow.run(effect.bind(7), workflow_id="w2") == 7
    assert calls == [7]  # second run replayed, not re-executed


def test_failure_and_resume_skips_completed_tasks(wf, tmp_path):
    # Resume executes the DAG persisted at run time (closures are pickled),
    # so transient state must live outside the process — files here.
    fail_marker = tmp_path / "fail"
    runs_file = tmp_path / "slow_runs"
    fail_marker.write_text("1")
    runs_file.write_text("0")

    @ray_tpu.remote
    def slow_expensive(runs_path):
        import pathlib
        p = pathlib.Path(runs_path)
        p.write_text(str(int(p.read_text()) + 1))
        return 100

    @ray_tpu.remote
    def maybe_fail(x, marker_path):
        import os
        if os.path.exists(marker_path):
            raise RuntimeError("transient failure")
        return x + 1

    dag = maybe_fail.bind(slow_expensive.bind(str(runs_file)),
                          str(fail_marker))
    with pytest.raises(workflow.WorkflowExecutionError):
        workflow.run(dag, workflow_id="w3")
    assert workflow.get_status("w3") == "FAILED"
    assert runs_file.read_text() == "1"

    fail_marker.unlink()  # "fix the environment", then resume
    assert workflow.resume("w3") == 101
    assert workflow.get_status("w3") == "SUCCESS"
    # The expensive upstream task was replayed from storage, not re-run.
    assert runs_file.read_text() == "1"


def test_resume_unknown_workflow_raises(wf):
    with pytest.raises(ValueError):
        workflow.resume("nonexistent")


def test_run_async_and_list(wf):
    wid = workflow.run_async(add.bind(1, 2), workflow_id="w4")
    assert workflow.get_output(wid, wait=True, timeout=30) == 3
    all_wfs = {w["workflow_id"]: w["status"] for w in workflow.list_all()}
    assert all_wfs["w4"] == "SUCCESS"
    workflow.delete("w4")
    assert "w4" not in {w["workflow_id"] for w in workflow.list_all()}


def test_diamond_dag_runs_shared_node_once(wf):
    runs = []

    @ray_tpu.remote
    def base():
        runs.append(1)
        return 10

    @ray_tpu.remote
    def left(x):
        return x + 1

    @ray_tpu.remote
    def right(x):
        return x + 2

    shared = base.bind()
    dag = add.bind(left.bind(shared), right.bind(shared))
    assert workflow.run(dag, workflow_id="w5") == 23
    assert len(runs) == 1


def test_wait_for_event(wf):
    box = {"ready": None}

    def poll():
        return box["ready"]

    import threading

    def fire():
        time.sleep(0.3)
        box["ready"] = {"payload": 42}

    threading.Thread(target=fire).start()
    ev = workflow.wait_for_event(poll, poll_interval_s=0.05)

    @ray_tpu.remote
    def unpack(e):
        return e["payload"]

    dag = add.bind(1, unpack.bind(ev))
    assert workflow.run(dag, workflow_id="w6") == 43


# -- continuations ----------------------------------------------------------

def test_workflow_continuation_recursive_factorial(wf):
    """A step returning workflow.continuation(dag) hands execution to the
    sub-DAG (the reference's dynamic-workflow core): recursive factorial."""
    from ray_tpu import workflow

    @ray_tpu.remote
    def fact(n, acc):
        if n <= 1:
            return acc
        return workflow.continuation(fact.bind(n - 1, acc * n))

    assert workflow.run(fact.bind(5, 1), workflow_id="wf-cont") == 120
    assert workflow.get_status("wf-cont") == "SUCCESS"
    # replay: result comes from storage, steps are not re-run
    assert workflow.resume("wf-cont") == 120


def test_workflow_continuation_resume_midway(wf, tmp_path):
    """Crash inside a continuation chain: resume replays persisted
    sub-steps and completes the rest."""
    from ray_tpu import workflow
    import os
    marker = str(tmp_path / "crashed")

    @ray_tpu.remote
    def countdown(n):
        if n == 2 and not os.path.exists(marker):
            open(marker, "w").close()
            raise RuntimeError("boom at n=2")
        if n <= 0:
            return "done"
        return workflow.continuation(countdown.bind(n - 1))

    import pytest as _pytest
    with _pytest.raises(Exception):
        workflow.run(countdown.bind(4), workflow_id="wf-crash")
    assert workflow.get_status("wf-crash") == "FAILED"
    assert workflow.resume("wf-crash") == "done"
    assert workflow.get_status("wf-crash") == "SUCCESS"


def test_workflow_deep_continuation_chain(wf):
    """1500 continuation links: the chain is loop-driven (one stack frame,
    bounded id length) — the recursive form would blow the interpreter's
    recursion limit (regression)."""
    from ray_tpu import workflow

    @ray_tpu.remote
    def step(n):
        if n <= 0:
            return "bottom"
        return workflow.continuation(step.bind(n - 1))

    assert workflow.run(step.bind(1500), workflow_id="wf-deep") == "bottom"


def test_workflow_nonroot_continuation_rejected(wf):
    from ray_tpu import workflow

    @ray_tpu.remote
    def inner():
        return workflow.continuation(leaf.bind())

    @ray_tpu.remote
    def leaf():
        return 1

    @ray_tpu.remote
    def outer(x):
        return x

    with pytest.raises(Exception) as ei:
        workflow.run(outer.bind(inner.bind()), workflow_id="wf-nonroot")
    assert "not the (sub-)workflow root" in str(ei.value)
