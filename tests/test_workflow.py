"""Workflow tests: durable execution, failure, resume, events.

Models the reference's ``python/ray/workflow/tests/`` (basic workflows,
recovery, events).
"""

import time

import pytest

import ray_tpu
from ray_tpu import workflow


@pytest.fixture
def wf(ray_start_regular, tmp_path):
    workflow.init(storage_base_dir=str(tmp_path))
    yield str(tmp_path)


@ray_tpu.remote
def add(a, b):
    return a + b


@ray_tpu.remote
def mul(a, b):
    return a * b


def test_run_simple_dag(wf):
    dag = add.bind(mul.bind(2, 3), mul.bind(4, 5))
    assert workflow.run(dag, workflow_id="w1") == 26
    assert workflow.get_status("w1") == "SUCCESS"
    assert workflow.get_output("w1") == 26


def test_rerun_returns_stored_output(wf):
    calls = []

    @ray_tpu.remote
    def effect(x):
        calls.append(x)
        return x

    # Side-effect function defined locally still runs through the runtime;
    # calls list is shared because tasks execute in-process threads.
    assert workflow.run(effect.bind(7), workflow_id="w2") == 7
    assert workflow.run(effect.bind(7), workflow_id="w2") == 7
    assert calls == [7]  # second run replayed, not re-executed


def test_failure_and_resume_skips_completed_tasks(wf, tmp_path):
    # Resume executes the DAG persisted at run time (closures are pickled),
    # so transient state must live outside the process — files here.
    fail_marker = tmp_path / "fail"
    runs_file = tmp_path / "slow_runs"
    fail_marker.write_text("1")
    runs_file.write_text("0")

    @ray_tpu.remote
    def slow_expensive(runs_path):
        import pathlib
        p = pathlib.Path(runs_path)
        p.write_text(str(int(p.read_text()) + 1))
        return 100

    @ray_tpu.remote
    def maybe_fail(x, marker_path):
        import os
        if os.path.exists(marker_path):
            raise RuntimeError("transient failure")
        return x + 1

    dag = maybe_fail.bind(slow_expensive.bind(str(runs_file)),
                          str(fail_marker))
    with pytest.raises(workflow.WorkflowExecutionError):
        workflow.run(dag, workflow_id="w3")
    assert workflow.get_status("w3") == "FAILED"
    assert runs_file.read_text() == "1"

    fail_marker.unlink()  # "fix the environment", then resume
    assert workflow.resume("w3") == 101
    assert workflow.get_status("w3") == "SUCCESS"
    # The expensive upstream task was replayed from storage, not re-run.
    assert runs_file.read_text() == "1"


def test_resume_unknown_workflow_raises(wf):
    with pytest.raises(ValueError):
        workflow.resume("nonexistent")


def test_run_async_and_list(wf):
    wid = workflow.run_async(add.bind(1, 2), workflow_id="w4")
    assert workflow.get_output(wid, wait=True, timeout=30) == 3
    all_wfs = {w["workflow_id"]: w["status"] for w in workflow.list_all()}
    assert all_wfs["w4"] == "SUCCESS"
    workflow.delete("w4")
    assert "w4" not in {w["workflow_id"] for w in workflow.list_all()}


def test_diamond_dag_runs_shared_node_once(wf):
    runs = []

    @ray_tpu.remote
    def base():
        runs.append(1)
        return 10

    @ray_tpu.remote
    def left(x):
        return x + 1

    @ray_tpu.remote
    def right(x):
        return x + 2

    shared = base.bind()
    dag = add.bind(left.bind(shared), right.bind(shared))
    assert workflow.run(dag, workflow_id="w5") == 23
    assert len(runs) == 1


def test_wait_for_event(wf):
    box = {"ready": None}

    def poll():
        return box["ready"]

    import threading

    def fire():
        time.sleep(0.3)
        box["ready"] = {"payload": 42}

    threading.Thread(target=fire).start()
    ev = workflow.wait_for_event(poll, poll_interval_s=0.05)

    @ray_tpu.remote
    def unpack(e):
        return e["payload"]

    dag = add.bind(1, unpack.bind(ev))
    assert workflow.run(dag, workflow_id="w6") == 43
