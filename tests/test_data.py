"""Tests for ray_tpu.data (mirrors the reference's data/tests strategy:
transforms, shuffle/sort/groupby, IO round trips, splits, pipelines)."""

import os

import numpy as np
import pandas as pd
import pytest

import ray_tpu
from ray_tpu import data as rd


@pytest.fixture(scope="module", autouse=True)
def _ray():
    if not ray_tpu.is_initialized():
        ray_tpu.init(num_cpus=8)
    yield


def test_range_and_count():
    ds = rd.range(100, parallelism=5)
    assert ds.count() == 100
    assert ds.num_blocks() == 5
    assert ds.take(3) == [0, 1, 2]


def test_from_items_map_filter_flat_map():
    ds = rd.from_items(list(range(20)))
    out = (ds.map(lambda x: x * 2)
             .filter(lambda x: x % 4 == 0)
             .flat_map(lambda x: [x, x + 1]))
    rows = out.take_all()
    assert rows[:4] == [0, 1, 4, 5]
    assert out.count() == 20


def test_stage_fusion_single_task_per_block():
    # consecutive one-to-one stages must fuse: the plan has 3 stages but
    # execution yields exactly num_blocks output refs
    ds = rd.range(10, parallelism=2).map(lambda x: x + 1).filter(
        lambda x: True).map(lambda x: x * 2)
    refs = ds.get_internal_block_refs()
    assert len(refs) == 2
    assert ds.take_all() == [(i + 1) * 2 for i in range(10)]


def test_map_batches_pandas_and_numpy():
    df = pd.DataFrame({"a": range(10), "b": range(10)})
    ds = rd.from_pandas(df)
    out = ds.map_batches(lambda d: d.assign(c=d.a + d.b),
                         batch_format="pandas")
    assert out.to_pandas()["c"].tolist() == [2 * i for i in range(10)]

    out2 = rd.range_table(8).map_batches(
        lambda batch: {"value": batch["value"] * 3}, batch_format="numpy",
        batch_size=3)
    assert out2.to_pandas()["value"].tolist() == [3 * i for i in range(8)]


def test_column_ops():
    ds = rd.range_table(5).add_column("sq", lambda df: df["value"] ** 2)
    assert ds.select_columns(["sq"]).to_pandas()["sq"].tolist() == [
        0, 1, 4, 9, 16]
    assert ds.rename_columns({"sq": "square"}).columns() == [
        "value", "square"]
    assert ds.drop_columns(["value"]).columns() == ["sq"]


def test_repartition():
    ds = rd.range(100, parallelism=10).repartition(3)
    assert ds.num_blocks() == 3
    assert ds.count() == 100
    assert sorted(ds.take_all()) == list(range(100))


def test_random_shuffle_preserves_multiset():
    ds = rd.range(200, parallelism=4).random_shuffle(seed=7)
    rows = ds.take_all()
    assert sorted(rows) == list(range(200))
    assert rows != list(range(200))  # astronomically unlikely to be sorted


def test_sort_simple_and_tabular():
    import random as _r
    items = list(range(50))
    _r.Random(3).shuffle(items)
    ds = rd.from_items(items, parallelism=4).sort()
    assert ds.take_all() == list(range(50))

    df = pd.DataFrame({"k": items, "v": [i * 2 for i in items]})
    ds2 = rd.from_pandas(df).sort("k")
    assert ds2.to_pandas()["k"].tolist() == list(range(50))

    ds3 = rd.from_items(items, parallelism=4).sort(descending=True)
    assert ds3.take_all() == list(range(49, -1, -1))


def test_groupby_aggregates():
    df = pd.DataFrame({"g": [i % 3 for i in range(30)],
                       "x": list(range(30))})
    ds = rd.from_pandas(df)
    out = ds.groupby("g").sum("x").to_pandas().sort_values("g")
    expected = df.groupby("g")["x"].sum()
    assert out["sum(x)"].tolist() == expected.tolist()

    cnt = ds.groupby("g").count().to_pandas().sort_values("g")
    assert cnt["count()"].tolist() == [10, 10, 10]

    mx = ds.groupby("g").max("x").to_pandas().sort_values("g")
    assert mx["max(x)"].tolist() == [27, 28, 29]


def test_groupby_map_groups():
    ds = rd.from_items([{"g": i % 2, "x": i} for i in range(10)])
    out = ds.groupby(lambda r: r["g"]).map_groups(
        lambda block: [{"g": block.iloc[0]["g"], "n": len(block)}])
    rows = sorted(out.take_all(), key=lambda r: r["g"])
    assert rows == [{"g": 0, "n": 5}, {"g": 1, "n": 5}]


def test_global_aggregates():
    ds = rd.range(10)
    assert ds.sum() == 45
    assert ds.min() == 0
    assert ds.max() == 9
    assert ds.mean() == 4.5
    tab = rd.range_table(10)
    assert tab.sum("value") == 45


def test_zip_and_union():
    a = rd.range(5)
    b = rd.range(5).map(lambda x: x * 10)
    z = a.zip(b)
    assert z.take_all() == [(i, i * 10) for i in range(5)]
    u = a.union(b)
    assert sorted(u.take_all()) == sorted(
        list(range(5)) + [i * 10 for i in range(5)])


def test_limit_take_show(capsys):
    ds = rd.range(100, parallelism=4)
    assert ds.limit(7).count() == 7
    ds.show(2)
    assert capsys.readouterr().out == "0\n1\n"


def test_split_and_split_at_indices():
    ds = rd.range(30, parallelism=6)
    parts = ds.split(3)
    assert len(parts) == 3
    assert sum(p.count() for p in parts) == 30
    eq = ds.split(4, equal=True)
    counts = [p.count() for p in eq]
    assert sum(counts) == 30
    assert max(counts) - min(counts) <= 1, counts

    a, b = ds.split_at_indices([10])
    assert a.count() == 10 and b.count() == 20


def test_train_test_split():
    tr, te = rd.range(100).train_test_split(0.2)
    assert tr.count() == 80 and te.count() == 20


def test_iter_batches_formats():
    ds = rd.range_table(25)
    batches = list(ds.iter_batches(batch_size=10, batch_format="pandas"))
    assert [len(b) for b in batches] == [10, 10, 5]
    npb = list(ds.iter_batches(batch_size=25, batch_format="numpy"))
    assert isinstance(npb[0], np.ndarray) or isinstance(npb[0], dict)
    dropped = list(ds.iter_batches(batch_size=10, drop_last=True))
    assert [len(b) for b in dropped] == [10, 10]


def test_iter_torch_and_jax_batches():
    ds = rd.range_table(8)
    tb = next(ds.iter_torch_batches(batch_size=8))
    import torch
    t = tb if not isinstance(tb, dict) else tb["value"]
    assert isinstance(t, torch.Tensor) and t.shape[0] == 8

    jb = next(ds.iter_jax_batches(batch_size=8))
    import jax
    j = jb if not isinstance(jb, dict) else jb["value"]
    assert isinstance(j, jax.Array) and j.shape[0] == 8


def test_local_shuffle_buffer():
    rows = list(rd.range(50).iter_batches(
        batch_size=50, batch_format="numpy",
        local_shuffle_buffer_size=20, local_shuffle_seed=1))[0]
    assert sorted(rows.tolist()) == list(range(50))
    assert rows.tolist() != list(range(50))


def test_io_roundtrips(tmp_path):
    df = pd.DataFrame({"a": range(20), "b": [f"s{i}" for i in range(20)]})
    ds = rd.from_pandas(df).repartition(3)

    pq = str(tmp_path / "pq")
    ds.write_parquet(pq)
    back = rd.read_parquet(pq)
    assert back.count() == 20
    assert sorted(back.to_pandas()["a"].tolist()) == list(range(20))

    cs = str(tmp_path / "csv")
    ds.write_csv(cs)
    assert rd.read_csv(cs).count() == 20

    js = str(tmp_path / "json")
    ds.write_json(js)
    assert rd.read_json(js).count() == 20

    npdir = str(tmp_path / "np")
    rd.range_table(10).write_numpy(npdir, column="value")
    assert rd.read_numpy(npdir).count() == 10


def test_read_text_binary(tmp_path):
    p = tmp_path / "t.txt"
    p.write_text("a\nb\nc\n")
    assert rd.read_text(str(p)).take_all() == ["a", "b", "c"]
    assert rd.read_binary_files(str(p)).take_all() == [b"a\nb\nc\n"]


def test_actor_pool_compute():
    ds = rd.range(40, parallelism=4).map(
        lambda x: x + 1, compute=rd.ActorPoolStrategy(min_size=2))
    assert sorted(ds.take_all()) == list(range(1, 41))


def test_pipeline_window_repeat():
    pipe = rd.range(20, parallelism=4).window(blocks_per_window=2)
    assert pipe.count() == 20
    rows = pipe.map(lambda x: x * 2).take(5)
    assert rows == [0, 2, 4, 6, 8]

    rep = rd.range(5).repeat(3)
    assert rep.count() == 15
    epochs = list(rep.iter_epochs())
    assert len(epochs) == 3 and epochs[0].count() == 5


def test_pipeline_split():
    pipe = rd.range(20, parallelism=4).window(blocks_per_window=2)
    shards = pipe.split(2)
    assert sum(s.count() for s in shards) == 20


def test_random_sample():
    ds = rd.range(1000).random_sample(0.1, seed=5)
    n = ds.count()
    assert 50 < n < 200


# -- streaming split + prefetch ---------------------------------------------

def test_streaming_split_partitions_all_rows(ray_start_regular):
    import ray_tpu.data as rd
    ds = rd.from_items(list(range(100))).repartition(8)
    shards = ds.streaming_split(3)
    assert len(shards) == 3
    seen = []
    for it in shards:
        seen.extend(it.iter_rows())
    assert sorted(seen) == list(range(100))
    # equal split balances rows
    eq = ds.streaming_split(4, equal=True)
    counts = [it.count() for it in eq]
    assert sum(counts) == 100 and max(counts) - min(counts) <= 1


def test_iter_batches_prefetch_matches_and_overlaps(ray_start_regular):
    import ray_tpu.data as rd
    ds = rd.from_items([{"x": i} for i in range(64)])
    plain = [b["x"].tolist() for b in
             ds.iter_batches(batch_size=16, batch_format="numpy")]
    pref = [b["x"].tolist() for b in
            ds.iter_batches(batch_size=16, batch_format="numpy",
                            prefetch_batches=2)]
    assert plain == pref


def test_prefetch_propagates_producer_error(ray_start_regular):
    import ray_tpu.data as rd
    ds = rd.from_items(list(range(32)))

    def boom(x):
        if x == 20:
            raise ValueError("producer boom")
        return x

    bad = ds.map(boom)
    with pytest.raises(Exception) as ei:
        for _ in bad.iter_batches(batch_size=8, prefetch_batches=2):
            pass
    assert "producer boom" in str(ei.value)


def test_data_iterator_feeds_jax(ray_start_regular):
    import ray_tpu.data as rd
    ds = rd.from_items([{"x": float(i)} for i in range(32)])
    it = ds.iterator()
    batches = list(it.iter_jax_batches(batch_size=8, prefetch_batches=1))
    assert len(batches) == 4
    import jax.numpy as jnp
    assert float(jnp.sum(batches[0]["x"])) == sum(range(8))


def test_equal_split_balances_uneven_rows(ray_start_regular):
    """103 rows over 4 shards must give 26/26/26/25 (max diff 1) — a
    remainder-heavy shard would desynchronize per-batch collectives in a
    training group (regression)."""
    import ray_tpu.data as rd
    ds = rd.from_items(list(range(103))).repartition(7)
    shards = ds.split(4, equal=True)
    counts = [s.count() for s in shards]
    assert sum(counts) == 103
    assert max(counts) - min(counts) <= 1, counts
    seen = sorted(r for s in shards for r in s.take_all())
    assert seen == list(range(103))


def test_split_at_indices_preserves_order_without_driver_rows(
        ray_start_regular):
    import ray_tpu.data as rd
    ds = rd.from_items(list(range(50))).repartition(6)
    a, b, c = ds.split_at_indices([10, 35])
    assert a.take_all() == list(range(10))
    assert b.take_all() == list(range(10, 35))
    assert c.take_all() == list(range(35, 50))


def test_prefetch_iterator_abandonment_releases_producer(ray_start_regular):
    import threading
    import time as _time
    import ray_tpu.data as rd
    ds = rd.from_items(list(range(1000)))
    before = {t.name for t in threading.enumerate()}
    for _ in range(5):
        it = ds.iter_batches(batch_size=10, prefetch_batches=2)
        next(it)
        it.close()   # abandon early
    deadline = _time.time() + 5
    while _time.time() < deadline:
        lingering = [t for t in threading.enumerate()
                     if t.name == "data-prefetch" and t.is_alive()
                     and t.name not in before]
        if not lingering:
            break
        _time.sleep(0.05)
    assert not lingering, f"{len(lingering)} prefetch threads leaked"


def test_random_access_dataset(ray_start_regular):
    """Point lookups + batched multiget over a sorted, actor-partitioned
    dataset (reference RandomAccessDataset semantics)."""
    import ray_tpu.data as rd
    from ray_tpu.data import RandomAccessDataset
    rows = [{"id": i, "val": i * 10} for i in range(200)]
    import random as _r
    _r.Random(0).shuffle(rows)
    ds = rd.from_items(rows).repartition(6)
    rad = RandomAccessDataset(ds, "id", num_workers=3)

    assert rad.get(0)["val"] == 0
    assert rad.get(199)["val"] == 1990
    assert rad.get(123)["val"] == 1230
    assert rad.get(777) is None          # absent key

    keys = [5, 150, 42, 999, 63]
    got = rad.multiget(keys)
    assert [g["val"] if g else None for g in got] == [50, 1500, 420,
                                                      None, 630]
    stats = rad.stats()
    assert sum(s["rows"] for s in stats) == 200


def test_random_access_skewed_and_empty(ray_start_regular):
    """Skewed keys (empty sort ranges) and empty datasets must not crash
    construction (regression: empty partitions are typeless [] blocks)."""
    import ray_tpu.data as rd
    from ray_tpu.data import RandomAccessDataset
    # 10 distinct keys over 100 rows across 5 blocks: some sort ranges empty
    rows = [{"id": i // 10, "val": i} for i in range(100)]
    ds = rd.from_items(rows).repartition(5)
    rad = RandomAccessDataset(ds, "id", num_workers=4)
    assert rad.get(0) is not None
    assert rad.get(9) is not None
    assert rad.get(10) is None
    assert sum(s["rows"] for s in rad.stats()) == 100

    empty = RandomAccessDataset(rd.from_items([]), "id", num_workers=2)
    assert empty.get(1) is None
    assert empty.multiget([1, 2]) == [None, None]


def test_all_empty_tabular_combine_preserves_schema(ray_start_regular):
    """Filtering everything out must keep the schema: empty DataFrames
    carry type information and must not collapse to typeless [] blocks
    (regression from the empty-partition combine fix)."""
    import ray_tpu.data as rd
    ds = (rd.from_items([{"id": i, "val": i} for i in range(10)])
          .filter(lambda r: False).repartition(2))
    df = ds.to_pandas()
    assert list(df.columns) == ["id", "val"], list(df.columns)
    assert len(df) == 0


def test_tfrecords_roundtrip(ray_start_regular, tmp_path):
    """TFRecord + tf.train.Example write/read round trip over mixed
    feature types (bytes, str, float lists, int scalars)."""
    from ray_tpu import data as rdata
    rows = [{"name": f"item-{i}", "score": float(i) / 2,
             "tags": [i, i * 2, i * 3], "blob": bytes([i, i + 1])}
            for i in range(20)]
    ds = rdata.from_items(rows, parallelism=3).map(lambda r: r)
    # from_items of dicts -> tabular blocks
    import pandas as pd
    ds2 = rdata.from_pandas(pd.DataFrame(rows))
    out = tmp_path / "tfr"
    ds2.write_tfrecords(str(out))
    files = sorted(out.iterdir())
    assert files and all(f.suffix == ".tfrecord" for f in files)
    back = rdata.read_tfrecords(str(out)).to_pandas().sort_values(
        "score").reset_index(drop=True)
    assert len(back) == 20
    assert back["name"][0] in (b"item-0", "item-0")  # bytes on the wire
    assert float(back["score"][19]) == 9.5
    assert list(back["tags"][3]) == [3, 6, 9]
    assert bytes(back["blob"][1]) == bytes([1, 2])
    del ds


def test_tfrecord_crc_rejects_corruption(tmp_path):
    from ray_tpu.data.tfrecords import (encode_example, decode_example,
                                        read_tfrecord_file,
                                        write_tfrecord_file)
    p = tmp_path / "x.tfrecord"
    write_tfrecord_file(str(p), [encode_example({"a": 1})])
    raw = bytearray(p.read_bytes())
    raw[-5] ^= 0xFF  # flip a data byte
    p.write_bytes(bytes(raw))
    import pytest as _pytest
    with _pytest.raises(ValueError, match="CRC"):
        list(read_tfrecord_file(str(p)))
    # negative ints survive the zigzag-free int64 path
    rec = encode_example({"neg": -7, "many": [-1, 0, 1]})
    out = decode_example(rec)
    assert out["neg"] == -7 and out["many"] == [-1, 0, 1]


def test_tfrecord_golden_crc():
    """Pin the CRC32C implementation to known vectors (RFC 3720) so the
    files we write stay TF-readable."""
    from ray_tpu.data.tfrecords import crc32c
    assert crc32c(b"") == 0
    assert crc32c(b"123456789") == 0xE3069283  # canonical check value
    assert crc32c(bytes(32)) == 0x8A9136AA     # all-zeros vector


def test_tfrecords_mixed_numeric_keeps_int64(ray_start_regular, tmp_path):
    """Regression: a mixed int/float frame must keep int64 ids exact —
    row-wise iteration would coerce ids into lossy float32."""
    import pandas as pd

    from ray_tpu import data as rdata
    big = 16_777_217  # 2**24 + 1: not representable in float32
    ds = rdata.from_pandas(pd.DataFrame({"id": [big, big + 1],
                                         "score": [0.5, 1.5]}))
    out = tmp_path / "mixed"
    ds.write_tfrecords(str(out))
    back = rdata.read_tfrecords(str(out)).to_pandas().sort_values(
        "id").reset_index(drop=True)
    assert list(back["id"]) == [big, big + 1]
    assert list(back["score"]) == [0.5, 1.5]
    # empty value lists (legitimate TF output) decode to []
    from ray_tpu.data.tfrecords import _ld, _varint, decode_example
    empty_float = _ld(1, _ld(1, b"e") + _ld(2, _ld(2, b"")))
    assert decode_example(bytes(_ld(1, bytes(empty_float))))["e"] == []
