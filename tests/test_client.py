"""Thin-client protocol tests.

Mirrors the reference's Ray Client suite (``python/ray/tests/
test_client.py``): tasks, actors, put/get/wait, ref passing, errors,
cross-process connection.
"""

import subprocess
import sys
import textwrap

import pytest

import ray_tpu
from ray_tpu.util.client import connect
from ray_tpu.util.client.server import ClientServer


@pytest.fixture
def client_pair(ray_start_regular):
    server = ClientServer(port=0)
    api = connect(f"127.0.0.1:{server.port}")
    yield api
    api.disconnect()
    server.stop()


def test_client_task_roundtrip(client_pair):
    api = client_pair

    def add(a, b):
        return a + b

    f = api.remote(add)
    ref = f.remote(2, 3)
    assert api.get(ref) == 5


def test_client_put_get_and_ref_args(client_pair):
    api = client_pair
    x = api.put([1, 2, 3])

    def total(v):
        return sum(v)

    f = api.remote(total)
    assert api.get(f.remote(x)) == 6


def test_client_wait(client_pair):
    import time
    api = client_pair

    def slow(t):
        time.sleep(t)
        return t

    f = api.remote(slow)
    fast = f.remote(0.01)
    slow_ref = f.remote(5.0)
    ready, pending = api.wait([fast, slow_ref], num_returns=1, timeout=10)
    assert ready[0].ref_id == fast.ref_id
    assert pending[0].ref_id == slow_ref.ref_id


def test_client_actor(client_pair):
    api = client_pair

    class Counter:
        def __init__(self, start):
            self.n = start

        def add(self, k):
            self.n += k
            return self.n

    C = api.remote(Counter)
    c = C.remote(10)
    assert api.get(c.add.remote(5)) == 15
    assert api.get(c.add.remote(1)) == 16
    api.kill(c)


def test_client_named_actor_and_options(client_pair):
    api = client_pair

    class Named:
        def who(self):
            return "named"

    C = api.remote(Named)
    C.options(name="client_named", lifetime="detached").remote()
    h = api.get_actor("client_named")
    assert api.get(h.who.remote()) == "named"


def test_client_error_propagates(client_pair):
    api = client_pair

    def boom():
        raise ValueError("client boom")

    f = api.remote(boom)
    with pytest.raises(Exception) as ei:
        api.get(f.remote())
    assert "client boom" in str(ei.value)


def test_client_num_returns(client_pair):
    api = client_pair

    def pair():
        return 1, 2

    f = api.remote(pair, num_returns=2)
    refs = f.remote()
    assert api.get(refs) == [1, 2]


def test_client_from_separate_process(ray_start_regular):
    """A real remote driver: second interpreter connects over TCP."""
    server = ClientServer(port=0)
    code = textwrap.dedent(f"""
        import jax
        jax.config.update("jax_platforms", "cpu")
        from ray_tpu.util.client import connect
        api = connect("127.0.0.1:{server.port}")
        f = api.remote(lambda x: x * 7)
        print("RESULT", api.get(f.remote(6)))
    """)
    out = subprocess.run([sys.executable, "-c", code], cwd="/root/repo",
                         capture_output=True, text=True, timeout=120)
    assert "RESULT 42" in out.stdout, (out.stdout, out.stderr)
    server.stop()


def test_client_nested_refs_in_containers(client_pair):
    """Refs nested inside lists/dicts restore at any depth on the server
    (regression: top-level-only restoration handed tasks bare markers)."""
    api = client_pair
    a = api.put(10)
    b = api.put(32)

    def add_nested(payload):
        import ray_tpu
        return ray_tpu.get(payload["left"]) + ray_tpu.get(
            payload["rights"][0][0])

    f = api.remote(add_nested)
    out = api.get(f.remote({"left": a, "rights": [[b]]}), timeout=30)
    assert out == 42


def test_client_refs_in_exotic_containers(client_pair):
    """Namedtuples keep their type; refs restore in dict keys and
    frozensets too (regression trio from review)."""
    import collections
    api = client_pair
    Point = collections.namedtuple("Point", "x y")
    r = api.put(5)

    def probe(pt, keyed, frozen):
        import ray_tpu
        assert type(pt).__name__ == "Point" and pt.x == 1
        (ref_key, label), = keyed.items()
        (f_ref,), = [tuple(frozen)]
        return ray_tpu.get(ref_key) + ray_tpu.get(f_ref) + pt.y

    f = api.remote(probe)
    out = api.get(f.remote(Point(1, 2), {r: "lbl"}, frozenset({r})),
                  timeout=30)
    assert out == 12


def test_client_calls_multiplex(client_pair):
    """A quick call issued WHILE a long get() blocks must complete first
    (regression: one socket + one lock serialized all calls)."""
    import threading
    import time as _time

    api = client_pair

    def slow():
        _time.sleep(3.0)
        return "slow-done"

    f = api.remote(slow)
    ref = f.remote()
    got = {}

    def getter():
        got["slow"] = api.get(ref, timeout=30)

    t = threading.Thread(target=getter)
    t.start()
    _time.sleep(0.2)  # the get() is now blocking server-side
    t0 = _time.perf_counter()
    quick = api.get(api.put("quick"), timeout=10)
    quick_elapsed = _time.perf_counter() - t0
    t.join(timeout=30)
    assert quick == "quick"
    assert got.get("slow") == "slow-done"
    assert quick_elapsed < 2.0, (
        f"quick call serialized behind the slow get ({quick_elapsed:.1f}s)")
