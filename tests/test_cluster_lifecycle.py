"""Cluster lifecycle tests: start/stop/status CLI + supervised restart.

Parity with the reference's ``ray start --head`` / ``--address`` / ``ray
stop`` flow (``python/ray/scripts/scripts.py:532``) and the node process
supervisor (``python/ray/_private/node.py:1061``): a head node and a
worker node come up as supervised processes, a driver attaches via the
published address, a SIGKILLed daemon is restarted by its supervisor, and
``stop`` tears everything down.
"""

import os
import signal
import time

import pytest

import ray_tpu
from ray_tpu.scripts import cluster as cl


def _read_pid(run_dir, name):
    with open(os.path.join(run_dir, name)) as f:
        return int(f.read().strip())


def _wait(pred, timeout_s, what):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(0.25)
    raise TimeoutError(f"timed out waiting for {what}")


@pytest.fixture()
def lifecycle_dirs(tmp_path):
    ray_tpu.shutdown()
    old_token = os.environ.get("RAY_TPU_AUTH_TOKEN")
    head_dir = str(tmp_path / "head")
    worker_dir = str(tmp_path / "worker")
    yield head_dir, worker_dir
    ray_tpu.shutdown()
    for d in (worker_dir, head_dir):
        cl.stop(d)
    if old_token is None:
        os.environ.pop("RAY_TPU_AUTH_TOKEN", None)
    else:
        os.environ["RAY_TPU_AUTH_TOKEN"] = old_token


def test_start_attach_restart_stop(lifecycle_dirs):
    head_dir, worker_dir = lifecycle_dirs

    # Terminal 1: start the head (state service + daemon, supervised).
    # Auth is on by default: the head mints the cluster token.
    addr = cl.start(head=True, num_cpus=2, run_dir=head_dir,
                    heartbeat_timeout_ms=3000)
    assert addr == cl.read_address(head_dir)
    with open(os.path.join(head_dir, "token")) as f:
        token = f.read().strip()
    assert token

    # Terminal 2: start a worker against the published address, presenting
    # the head's token.
    cl.start(address=addr, num_cpus=2, run_dir=worker_dir,
             heartbeat_timeout_ms=3000, auth_token=token)

    info = cl.status(run_dir=head_dir)
    assert sum(1 for n in info["nodes"] if n["alive"]) == 2

    # Terminal 3: a driver attaches (with the token) and uses both nodes.
    ray_tpu.init(address=addr, auth_token=token)

    @ray_tpu.remote
    def where(i):
        return os.getpid(), i

    res = ray_tpu.get([where.remote(i) for i in range(16)], timeout=60)
    pids = {p for p, _ in res}
    assert len(pids) == 2 and os.getpid() not in pids
    assert sorted(i for _, i in res) == list(range(16))

    # Chaos: SIGKILL the worker daemon; its supervisor must restart it
    # and the replacement must register as a fresh alive node.
    old_daemon_pid = _read_pid(worker_dir, "daemon.pid")
    os.kill(old_daemon_pid, signal.SIGKILL)

    def _restarted():
        try:
            return _read_pid(worker_dir, "daemon.pid") != old_daemon_pid
        except OSError:
            return False

    _wait(_restarted, 60, "supervisor restart of the daemon")
    # Alive nodes: head daemon + attached driver + REPLACEMENT worker
    # (the killed incarnation shows dead).
    _wait(lambda: sum(1 for n in cl.status(run_dir=head_dir)["nodes"]
                      if n["alive"]) == 3, 60, "replacement node alive")

    # The replacement node runs work (retry machinery drains the kill).
    res = ray_tpu.get([where.options(max_retries=5).remote(i)
                       for i in range(8)], timeout=90)
    assert len({p for p, _ in res}) >= 1
    ray_tpu.shutdown()

    # Stop both. The supervisor's graceful shutdown removes its pidfile
    # (the process itself lingers as a zombie under pytest — nothing
    # reaps grandchildren here — so poll the pidfile, not the pid).
    assert cl.stop(worker_dir)
    assert cl.stop(head_dir)
    for d in (worker_dir, head_dir):
        _wait(lambda d=d: not os.path.exists(
            os.path.join(d, "supervisor.pid")), 20,
            f"supervisor pidfile cleanup in {d}")
