"""Quantized block-wise and hierarchical collectives (the compression
tier, ``collective/quantization.py``).

Layers, fastest first: kernel-level round-trip error bounds
(property-style over shapes/dtypes including non-multiple-of-block
tails), native-vs-numpy payload parity, fused-reduction accuracy,
hierarchical==flat equivalence, thread-group drills through the public
API (wire-byte ledger ratio, mixed-scheme divergence, chaos
fail-loudly), and a two-daemon ProcessCluster quantized allreduce that
self-skips without the C++ state service.
"""

import threading

import numpy as np
import pytest

import ray_tpu
from ray_tpu import chaos
from ray_tpu.collective import CollectiveConfig
from ray_tpu.collective import quantization as qz
from ray_tpu.collective.types import ReduceOp
from ray_tpu.observability import comms
from ray_tpu.observability.comms import CollectiveDivergenceError


@pytest.fixture()
def comms_plane():
    was = comms.ENABLED
    comms.enable()
    comms.reset()
    yield
    comms.reset()
    if not was:
        comms.disable()


def _require_state_service():
    """ProcessCluster needs the C++ state service (protoc + g++)."""
    from ray_tpu._native.build import build_state_service
    try:
        build_state_service()
    except Exception as e:
        pytest.skip(f"state service unavailable: {e}")


# -- round-trip error bounds (property-style) --------------------------------

# Shapes chosen so block boundaries land everywhere interesting: smaller
# than one block, exact multiples, and ragged tails.
_SHAPES = [(7,), (64,), (65,), (256,), (1000,), (17, 33), (3, 5, 7)]


@pytest.mark.parametrize("shape", _SHAPES)
@pytest.mark.parametrize("dtype", [np.float32, np.float64])
def test_q8_round_trip_error_bound(shape, dtype):
    """Per-element q8 error is bounded by half the block scale: the
    round-to-nearest guarantee, checked per block against that block's
    own absmax (not a global tolerance that would hide a scale bug)."""
    rng = np.random.default_rng(hash((shape, np.dtype(dtype).num)) % 2**32)
    x = (rng.standard_normal(shape) * rng.uniform(0.01, 100)).astype(dtype)
    cfg = CollectiveConfig(compression="q8", quant_block_bytes=256)
    q = qz.quantize(x, cfg)
    y = qz.dequantize(q)
    assert y.shape == x.shape and y.dtype == x.dtype
    err = np.abs(y.astype(np.float64) - np.float32(x).astype(np.float64))
    flat_err = err.reshape(-1)
    for b, scale in enumerate(q.scales):
        blk = flat_err[b * q.block:(b + 1) * q.block]
        # + eps: the f32 multiply in dequant rounds once more
        assert blk.max() <= scale / 2 + 1e-5 * max(scale, 1e-30)


@pytest.mark.parametrize("shape", [(63,), (256,), (17, 33)])
def test_fp8_round_trip_error_bound(shape):
    """fp8 (e4m3) keeps ~2^-4 relative error across the block's dynamic
    range — looser than q8 near absmax, tighter near zero."""
    rng = np.random.default_rng(3)
    x = rng.standard_normal(shape).astype(np.float32)
    cfg = CollectiveConfig(compression="fp8", quant_block_bytes=256)
    y = qz.dequantize(qz.quantize(x, cfg))
    rel = np.abs(y - x).mean() / np.abs(x).mean()
    assert rel < 0.05


def test_wire_bytes_ratio_exact():
    """At 256-byte blocks an f32 tensor ships at exactly 68/256 = 0.2656x
    (64 one-byte payloads + one f32 scale per block)."""
    x = np.ones(1 << 16, np.float32)
    cfg = CollectiveConfig(compression="q8", quant_block_bytes=256)
    q = qz.quantize(x, cfg)
    assert q.nbytes == x.nbytes
    assert q.wire_bytes / q.nbytes == pytest.approx(68 / 256)


def test_non_finite_blocks_poison_and_refuse_dequant():
    x = np.ones(512, np.float32)
    x[100] = np.inf
    cfg = CollectiveConfig(compression="q8", quant_block_bytes=256)
    q = qz.quantize(x, cfg)
    # only the block holding the inf is poisoned
    assert (q.scales < 0).sum() == 1
    with pytest.raises(ValueError, match="non-finite"):
        qz.dequantize(q)
    with pytest.raises(ValueError, match="non-finite"):
        qz.reduce_quantized([q, q])


def test_native_and_numpy_payloads_match():
    """The native kernel and the numpy fallback must agree to the last
    bit of rounding — scales within one f32 ULP (the kernel divides in
    f32, numpy in f64), payloads within 1 LSB where that ULP flips a
    round — because callers may mix them across ranks."""
    lib = qz._native()
    if lib is None:
        pytest.skip("native quant kernel unavailable")
    rng = np.random.default_rng(11)
    for n in (64, 100, 4096, 4099):
        flat = rng.standard_normal(n).astype(np.float32)
        be = qz.block_elems(256, np.float32)
        qn, sn = qz._q8_quantize_native(flat, be, lib)
        qp, sp = qz._np_quantize(flat, be, "q8")
        np.testing.assert_allclose(qn.astype(np.int16),
                                   qp.astype(np.int16), atol=1)
        np.testing.assert_allclose(sn, sp, rtol=5e-7)


def test_reduce_quantized_accumulates_at_full_precision():
    """Summing N quantized payloads carries N independent round-trip
    errors, not compounding int8 saturation: the error stays O(N * q8
    step), far below what int8 accumulation would produce."""
    rng = np.random.default_rng(5)
    cfg = CollectiveConfig(compression="q8", quant_block_bytes=256)
    xs = [rng.standard_normal(4096).astype(np.float32) for _ in range(8)]
    qs = [qz.quantize(x, cfg) for x in xs]
    red = qz.reduce_quantized(qs)
    ref = np.sum(xs, axis=0)
    rel = np.abs(red - ref).mean() / np.abs(ref).mean()
    assert rel < 0.05
    # MAX path widens before reducing
    redm = qz.reduce_quantized(qs, lambda a: np.max(a, axis=0))
    refm = np.max(xs, axis=0)
    assert np.abs(redm - refm).mean() / np.abs(refm).mean() < 0.05


def test_hierarchical_matches_flat_within_tolerance():
    """Two-level (intra-host fp, inter-host quantized) must agree with
    both the exact f32 sum and the flat quantized sum within the quant
    tolerance — and ship FEWER wire bytes per rank than flat."""
    rng = np.random.default_rng(9)
    cfg = CollectiveConfig(compression="q8", quant_block_bytes=256,
                           ranks_per_host=2)
    xs = [rng.standard_normal(4096).astype(np.float32) for _ in range(4)]
    ref = np.sum(xs, axis=0)
    hier, wire = qz.hierarchical_allreduce(xs, cfg, None)
    assert np.abs(hier - ref).mean() / np.abs(ref).mean() < 0.02
    flat = qz.reduce_quantized([qz.quantize(x, cfg) for x in xs])
    assert np.abs(hier - flat).mean() / np.abs(ref).mean() < 0.02
    # 2 hosts quantize 2 partials; flat would quantize 4 full tensors
    flat_wire = qz.quantize(xs[0], cfg).wire_bytes
    assert wire < flat_wire


def test_hierarchical_validates_geometry():
    cfg = CollectiveConfig(compression="q8", ranks_per_host=3)
    xs = [np.ones(8, np.float32)] * 4
    with pytest.raises(ValueError, match="ranks_per_host"):
        qz.hierarchical_allreduce(xs, cfg, None)


# -- thread-group drills through the public API ------------------------------

def _thread_group_allreduce(configs, xs, gname, op=ReduceOp.SUM,
                            backend="cpu"):
    """Run one allreduce per rank on its own thread; returns (outs, errs)."""
    from ray_tpu import collective as col
    world = len(xs)
    outs, errs = [None] * world, [None] * world

    def run(r):
        try:
            col.init_collective_group(world, r, backend=backend,
                                      group_name=gname, config=configs[r])
            outs[r] = np.asarray(col.allreduce(xs[r].copy(), gname, op))
        except Exception as e:  # noqa: BLE001 — asserted by callers
            errs[r] = e

    ts = [threading.Thread(target=run, args=(r,)) for r in range(world)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    return outs, errs


@pytest.mark.parametrize("backend", ["cpu", "xla"])
def test_group_q8_allreduce_and_ledger_wire_ratio(comms_plane, backend):
    """Quantized allreduce through the public API: result within quant
    tolerance, and the comms ledger books wire ~0.27x logical — the
    ledger-verified compression ratio the bench gates on."""
    cfg = CollectiveConfig(compression="q8", quant_block_bytes=256)
    rng = np.random.default_rng(13)
    xs = [rng.standard_normal(1 << 14).astype(np.float32) for _ in range(2)]
    gname = f"q8_{backend}"
    outs, errs = _thread_group_allreduce([cfg, cfg], xs, gname,
                                         backend=backend)
    assert errs == [None, None]
    ref = xs[0] + xs[1]
    assert np.abs(outs[0] - ref).mean() / np.abs(ref).mean() < 0.02
    np.testing.assert_array_equal(outs[0], outs[1])
    rec = comms.snapshot()["groups"][gname]["ops"]["allreduce"]
    assert rec["wire_bytes"] / rec["bytes"] == pytest.approx(68 / 256)
    assert rec["compression_ratio"] == pytest.approx(68 / 256)
    # algbw is wire-honest; logical_gbps is the user-facing rate
    assert rec["logical_gbps"] > rec["algbw_gbps"]


def test_group_hierarchical_books_less_wire(comms_plane):
    """A 4-rank, 2-per-host hierarchical allreduce matches flat within
    tolerance and books strictly less wire than flat quantized."""
    rng = np.random.default_rng(17)
    xs = [rng.standard_normal(1 << 12).astype(np.float32) for _ in range(4)]
    ref = np.sum(xs, axis=0)
    hcfg = CollectiveConfig(compression="q8", quant_block_bytes=256,
                            ranks_per_host=2)
    fcfg = CollectiveConfig(compression="q8", quant_block_bytes=256)
    houts, herrs = _thread_group_allreduce([hcfg] * 4, xs, "hier4")
    fouts, ferrs = _thread_group_allreduce([fcfg] * 4, xs, "flat4")
    assert herrs == [None] * 4 and ferrs == [None] * 4
    assert np.abs(houts[0] - ref).mean() / np.abs(ref).mean() < 0.02
    assert np.abs(houts[0] - fouts[0]).mean() / np.abs(ref).mean() < 0.02
    ops = comms.snapshot()["groups"]
    hier = ops["hier4"]["ops"]["allreduce"]
    flat = ops["flat4"]["ops"]["allreduce"]
    assert hier["wire_bytes"] < flat["wire_bytes"]
    assert hier["compression_ratio"] < flat["compression_ratio"]


def test_mixed_scheme_ranks_diverge_loudly(comms_plane):
    """A q8 rank meeting an uncompressed rank must raise
    CollectiveDivergenceError naming BOTH schemes — never a
    half-quantized accumulate."""
    xs = [np.ones(1024, np.float32), np.ones(1024, np.float32)]
    cfgs = [CollectiveConfig(compression="q8"),
            CollectiveConfig(compression="none")]  # raylint: allow(collective-divergence) deliberate mixed-scheme drill: the divergence is the assertion
    _outs, errs = _thread_group_allreduce(cfgs, xs, "mixed")
    assert all(isinstance(e, CollectiveDivergenceError) for e in errs), errs
    msg = str(errs[0])
    assert "q8" in msg and "none" in msg


def test_mixed_block_sizes_diverge_loudly(comms_plane):
    xs = [np.ones(1024, np.float32), np.ones(1024, np.float32)]
    cfgs = [CollectiveConfig(compression="q8", quant_block_bytes=256),
            CollectiveConfig(compression="q8", quant_block_bytes=512)]  # raylint: allow(collective-divergence) deliberate mixed-block drill: the divergence is the assertion
    _outs, errs = _thread_group_allreduce(cfgs, xs, "mixedblk")
    assert all(isinstance(e, CollectiveDivergenceError) for e in errs), errs


def test_chaos_faulted_quant_fails_loudly_then_retries_clean():
    """The ``collective.quant`` chaos seam: an error scheduled on rank
    1's quantization step must surface on EVERY rank (the rendezvous
    propagates the fault sentinel instead of stranding peers at their
    timeout), and the same group must complete clean once the schedule
    is lifted."""
    prev = chaos.schedule()
    chaos.configure(7, "collective.quant[rank=1]@1=error")
    try:
        cfg = CollectiveConfig(compression="q8", quant_block_bytes=256)
        xs = [np.ones(2048, np.float32) * (r + 1) for r in range(2)]
        _outs, errs = _thread_group_allreduce([cfg, cfg], xs, "chaosq")
        assert all(isinstance(e, chaos.ChaosError) for e in errs), errs
    finally:
        chaos.install(prev) if prev is not None else chaos.clear()
    outs, errs = _thread_group_allreduce([cfg, cfg], xs, "chaosq")
    assert errs == [None, None]
    np.testing.assert_allclose(outs[0], np.full(2048, 3.0), atol=0.1)


def test_quantize_perf_histogram_records():
    from ray_tpu.observability import perf
    was = perf.ENABLED
    perf.enable()
    try:
        cfg = CollectiveConfig(compression="q8")
        qz.quantize(np.ones(4096, np.float32), cfg)
        assert "collective.quantize" in perf.snapshot()["hists"]
    finally:
        perf.reset()
        if not was:
            perf.disable()


def test_config_knobs_resolve_default_group_config():
    """The ``collective_compression`` / ``quant_block_bytes`` config
    knobs feed groups created without an explicit CollectiveConfig."""
    from ray_tpu._private.config import _config
    from ray_tpu.collective.collective import GroupManager
    resolved = GroupManager._resolve_config(None)
    assert resolved.compression == _config.get("collective_compression")
    assert resolved.quant_block_bytes == _config.get("quant_block_bytes")
    explicit = CollectiveConfig(compression="fp8")
    assert GroupManager._resolve_config(explicit) is explicit


def test_collective_config_validates():
    with pytest.raises(ValueError):
        CollectiveConfig(compression="int4")
    with pytest.raises(ValueError):
        CollectiveConfig(quant_block_bytes=4)
    with pytest.raises(ValueError):
        CollectiveConfig(ranks_per_host=-1)


# -- acceptance drill (self-skips without the C++ state service) -------------

@pytest.fixture()
def tp_cluster():
    from ray_tpu.cluster_utils import ProcessCluster
    _require_state_service()
    ray_tpu.shutdown()
    c = ProcessCluster(num_daemons=2, num_cpus=2, tp_cpu_devices=2)
    ray_tpu.init(address=c.address)
    yield c
    ray_tpu.shutdown()
    c.shutdown()


@ray_tpu.remote(num_cpus=2)  # fills a daemon: one rank per process
class QRank:
    def run(self, op, tensor, group_name, **kw):
        from ray_tpu import collective as col
        return np.asarray(getattr(col, op)(tensor, group_name=group_name,
                                           **kw))

    def last_op_ledger(self, group_name):
        snap = comms.snapshot()
        return snap["groups"].get(group_name, {}).get("ops", {})


def test_cluster_two_daemon_quantized_allreduce(tp_cluster):
    """Two daemon PROCESSES allreduce with q8 compression: the payload
    crosses the KV/TCP seam quantized (the real DCN-analogue hop), the
    result lands within quant tolerance on both ranks, and each rank's
    ledger books wire ~0.27x logical."""
    from ray_tpu.collective import create_collective_group
    actors = [QRank.remote() for _ in range(2)]
    cfg = CollectiveConfig(compression="q8", quant_block_bytes=256)
    create_collective_group(actors, 2, [0, 1], backend="xla",
                            group_name="qd", config=cfg)
    base = np.arange(4096, dtype=np.float32) / 7.0
    refs = [a.run.remote("allreduce", base + r, "qd")
            for r, a in enumerate(actors)]
    out = ray_tpu.get(refs, timeout=120)
    expected = base + (base + 1)
    for o in out:
        assert np.abs(o - expected).max() <= \
            np.abs(expected).max() / 254 + 1e-3
    ledgers = ray_tpu.get([a.last_op_ledger.remote("qd") for a in actors],
                          timeout=60)
    for led in ledgers:
        if "allreduce" in led:  # comms plane on in daemons
            rec = led["allreduce"]
            assert rec["wire_bytes"] / rec["bytes"] == \
                pytest.approx(68 / 256)
