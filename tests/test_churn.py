"""Elastic preemptible-fleet orchestration: predictive drains, risk-tuned
checkpoint cadence, and gang replacement.

Three layers:

- unit coverage that runs everywhere: the hazard math (decayed rates,
  probe penalties, window pruning), the Young–Daly cadence solver and its
  re-tuning controller, the session's distance-gated "auto" save path,
  drain-aware load metrics / scale-down, pending-drain last-choice
  placement, preempt-probe backoff, the storm-spec grammar helper, and
  doctor blind-watcher triage;
- in-process integration: a seeded hazard estimator drives one proactive
  drain and its same-type gang replacement through a full
  ``StandardAutoscaler.update`` pass;
- the ProcessCluster fleet-churn drill (slow; run by the
  run_sanitizers.sh preemption-storm gate): a seeded ``node.preempt``
  storm cycles real daemons while an elastic train job checkpoints on
  auto cadence — zero task loss, monotone checkpoint steps, journaled
  preemptions feeding proactive drains and replacements, and the merged
  goodput gate holding above its floor.
"""

import json
import os
import time

import pytest

import ray_tpu
from ray_tpu._private.config import _config
from ray_tpu.autoscaler import (AutoscalerConfig, FakeNodeProvider,
                                HazardEstimator, StandardAutoscaler)
from ray_tpu.autoscaler import hazard
from ray_tpu.checkpoint import CadenceController, solve_interval_steps
from ray_tpu.cluster_utils import ProcessCluster


def _require_state_service():
    """ProcessCluster needs the C++ state service (protoc + g++)."""
    from ray_tpu._native.build import build_state_service
    try:
        build_state_service()
    except Exception as e:
        pytest.skip(f"state service unavailable: {e}")


# -- unit: hazard math -------------------------------------------------------

def test_decayed_rate_monotone_in_count_and_freshness():
    h, w = 900.0, 3600.0
    one_fresh = hazard.decayed_rate_per_hour([0.0], h, w)
    # one fresh event at halflife h reads ~3600*ln2/h events/hour
    assert one_fresh == pytest.approx(3600.0 * 0.6931 / h, rel=1e-3)
    assert hazard.decayed_rate_per_hour([0.0, 0.0], h, w) > one_fresh
    assert hazard.decayed_rate_per_hour([600.0], h, w) < one_fresh
    # events past the window (or from the future) contribute nothing
    assert hazard.decayed_rate_per_hour([w + 1.0, -5.0], h, w) == 0.0


def test_node_hazard_probe_penalty():
    base = hazard.node_hazard_score(3.0, probe_failures=0, probe_weight=2.0)
    blind = hazard.node_hazard_score(3.0, probe_failures=4, probe_weight=2.0)
    assert base == pytest.approx(3.0)
    assert blind == pytest.approx(3.0 + 8.0)
    # negative failure counts never LOWER the score
    assert hazard.node_hazard_score(3.0, -2, 2.0) == pytest.approx(3.0)


def test_estimator_prunes_events_past_window():
    est = HazardEstimator()
    now = 1_000_000.0
    est.record("tpu-v5e", "aa" * 16, ts=now - 10.0)
    est.record("tpu-v5e", "bb" * 16, ts=now - _config.get("hazard_window_s")
               - 100.0)  # stale: outside the window
    est.refresh(now=now)
    assert len(est._events) == 1
    assert est.type_rate("tpu-v5e", now=now) > 0.0
    assert est.type_rate("other-type", now=now) == 0.0
    # node hazard folds the probe penalty on top of the type rate
    est._probe_failures["aa" * 16] = 3
    assert est.node_hazard("tpu-v5e", "aa" * 16, now=now) > \
        est.node_hazard("tpu-v5e", "cc" * 16, now=now)


def test_fleet_rate_floor_applies_to_cold_fleet():
    est = HazardEstimator()
    floor_was = _config.get("hazard_rate_floor_per_hour")
    _config.set("hazard_rate_floor_per_hour", 1.5)
    try:
        assert est.fleet_rate(now=0.0) == pytest.approx(1.5)
    finally:
        _config.set("hazard_rate_floor_per_hour", floor_was)


# -- unit: cadence solver ----------------------------------------------------

def test_cadence_risk_up_means_denser_checkpoints():
    """ISSUE contract "risk up => cadence up": a hotter fleet checkpoints
    MORE often, i.e. fewer steps between checkpoints."""
    calm = solve_interval_steps(1.0, 1.0, 0.5, min_steps=1, max_steps=1000)
    hot = solve_interval_steps(10.0, 1.0, 0.5, min_steps=1, max_steps=1000)
    assert hot < calm


def test_cadence_step_cost_up_means_fewer_steps_per_interval():
    """"step-cost up => cadence down" in steps: the same optimal wall
    interval spans fewer (slower) steps."""
    fast = solve_interval_steps(10.0, 1.0, 0.5, min_steps=1, max_steps=1000)
    slow = solve_interval_steps(10.0, 5.0, 0.5, min_steps=1, max_steps=1000)
    assert slow < fast


def test_cadence_ckpt_cost_and_restart_cost_shift_the_optimum():
    cheap = solve_interval_steps(10.0, 1.0, 0.5, min_steps=1, max_steps=1000)
    pricey = solve_interval_steps(10.0, 1.0, 5.0, min_steps=1, max_steps=1000)
    assert pricey > cheap  # costly checkpoints => stretch the interval
    # a costly restart eats into the useful MTBF => checkpoint sooner
    slow_restart = solve_interval_steps(10.0, 1.0, 0.5, restart_cost_s=300.0,
                                        min_steps=1, max_steps=1000)
    assert slow_restart < cheap


def test_cadence_degenerate_inputs_hit_the_ceiling_and_clamps():
    assert solve_interval_steps(0.0, 1.0, 0.5, min_steps=1,
                                max_steps=77) == 77
    assert solve_interval_steps(5.0, 0.0, 0.5, min_steps=1,
                                max_steps=77) == 77
    # clamped to [min, max] whatever the math says
    assert solve_interval_steps(10_000.0, 10.0, 1e-9, min_steps=4,
                                max_steps=77) == 4
    assert solve_interval_steps(1e-9, 1e-3, 100.0, min_steps=4,
                                max_steps=77) == 77


def test_cadence_controller_retunes_when_hazard_changes():
    """The drill's mid-run contract in miniature: the controller re-solves
    every refresh window, so a hazard jump visibly shrinks the interval."""
    rate = {"v": 1.0}
    ctl = CadenceController(hazard_source=lambda: rate["v"], refresh_steps=4,
                            min_steps=1, max_steps=1000)
    for _ in range(4):
        ctl.observe_step(1.0)
    ctl.observe_ckpt(0.5)
    calm = ctl.interval_steps()
    assert ctl.last_hazard_per_hour == pytest.approx(1.0)
    # inside the refresh window the cached interval holds
    rate["v"] = 50.0
    ctl.observe_step(1.0)
    assert ctl.interval_steps() == calm
    # once the window elapses the new hazard re-tunes the cadence
    for _ in range(4):
        ctl.observe_step(1.0)
    hot = ctl.interval_steps()
    assert hot < calm
    assert ctl.last_hazard_per_hour == pytest.approx(50.0)


# -- unit: session "auto" save gating ---------------------------------------

class _FixedCadence:
    def __init__(self, interval):
        self.interval = interval
        self.ckpt_obs = 0

    def interval_steps(self):
        return self.interval

    def observe_ckpt(self, seconds):
        self.ckpt_obs += 1


class _RecordingEngine:
    def __init__(self):
        self.steps = []

    def save(self, tree, step, rank, world_size, save_key):
        self.steps.append(step)


def test_session_auto_frequency_gates_saves_by_distance():
    """frequency="auto" gates engine saves on seq distance from the last
    save (modulo breaks when the interval re-solves mid-run); the first
    reported checkpoint always anchors."""
    from ray_tpu.train.session import _TrainSession
    s = _TrainSession(world_rank=0, world_size=1,
                      checkpoint_spec={"root": "/tmp/unused",
                                       "frequency": "auto",
                                       "run_token": "t"})
    assert s._cadence is not None  # "auto" spec builds a controller
    s._cadence = _FixedCadence(3)
    s.checkpoint_engine = eng = _RecordingEngine()
    for _ in range(9):
        s._engine_save({"x": 1})
    assert eng.steps == [1, 4, 7]
    assert s._cadence.ckpt_obs == 3  # each real save feeds the EWMA


def test_session_int_frequency_path_unchanged():
    from ray_tpu.train.session import _TrainSession
    s = _TrainSession(world_rank=0, world_size=1,
                      checkpoint_spec={"root": "/tmp/unused", "frequency": 2,
                                       "run_token": "t"})
    assert s._cadence is None
    s.checkpoint_engine = eng = _RecordingEngine()
    for _ in range(6):
        s._engine_save({"x": 1})
    assert eng.steps == [1, 3, 5]


# -- unit: drain-aware load metrics & scale-down -----------------------------

@pytest.fixture
def small_cluster():
    ray_tpu.shutdown()
    w = ray_tpu.init(num_cpus=1)
    yield w
    ray_tpu.shutdown()


def test_load_metrics_hide_draining_capacity(small_cluster):
    from ray_tpu._private.resources import ResourceSet
    from ray_tpu.autoscaler.autoscaler import LoadMetrics
    rt = small_cluster.runtime
    node = rt.add_node(ResourceSet({"CPU": 4.0}))
    lm = LoadMetrics(rt)
    assert node.node_id.hex() in lm.node_utilization()
    node.draining = True
    rt._kick()
    # a quiesced draining node LOOKS idle — it must vanish from the
    # utilization view (else scale-down terminates it mid-drain and
    # bin-packing counts capacity that is about to leave)...
    assert node.node_id.hex() not in lm.node_utilization()
    # ...but stays visible to the lifecycle scan gang replacement uses
    assert lm.lifecycle()[node.node_id.hex()]["draining"] is True


def test_scale_down_never_terminates_a_draining_node(small_cluster):
    rt = small_cluster.runtime
    provider = FakeNodeProvider(rt, {"cpu-4": {"CPU": 4}})
    autoscaler = StandardAutoscaler(
        AutoscalerConfig(node_types={"cpu-4": {"CPU": 4}}, max_workers=3,
                         idle_timeout_s=0.1), provider, rt)
    draining_pid, victim_pid = provider.create_node("cpu-4", 2)
    draining_node = provider._nodes[draining_pid]
    draining_node.draining = True
    rt._kick()
    autoscaler._replaced.add(draining_pid)  # isolate from gang replacement
    autoscaler.update()                     # records idle-since
    time.sleep(0.15)
    autoscaler.update()
    # the idle node went; the (equally quiet) draining node survived
    assert victim_pid not in provider.non_terminated_nodes()
    assert draining_pid in provider.non_terminated_nodes()
    assert draining_node.alive and draining_node.draining


# -- unit: pending-drain last-choice placement -------------------------------

def _node_state(tag, pending=False, draining=False):
    from ray_tpu._private.ids import NodeID
    from ray_tpu._private.resources import NodeResources, ResourceSet
    from ray_tpu._private.scheduler import NodeState
    nr = NodeResources(ResourceSet({"CPU": 4.0}))
    return NodeState(NodeID(bytes([tag]) * 16), nr, True,
                     draining=draining, pending_drain=pending)


def test_pending_drain_is_last_choice_not_excluded():
    from ray_tpu._private.resources import ResourceSet
    from ray_tpu._private.scheduler import HybridPolicy, SpreadPolicy
    req = ResourceSet({"CPU": 1.0})
    stable, risky = _node_state(1), _node_state(2, pending=True)
    for _ in range(8):
        assert HybridPolicy(seed=0).select([risky, stable],
                                           req) == stable.node_id
        assert SpreadPolicy().select([risky, stable], req) == stable.node_id
    # unlike DRAINING, a pending-drain node still schedules when it is
    # the only option — it is a hint, not a lifecycle state
    assert HybridPolicy(seed=0).select([risky], req) == risky.node_id
    assert SpreadPolicy().select([risky], req) == risky.node_id


def test_runtime_pending_drain_hint_roundtrip(small_cluster):
    rt = small_cluster.runtime
    nid = rt.node_states()[0].node_id.hex()
    rt.set_pending_drain(nid, True)
    (ns,) = [s for s in rt.node_states() if s.node_id.hex() == nid]
    assert ns.pending_drain and ns.schedulable
    rt.set_pending_drain(nid, False)
    (ns,) = [s for s in rt.node_states() if s.node_id.hex() == nid]
    assert not ns.pending_drain


# -- unit: preempt-probe backoff ---------------------------------------------

def test_probe_state_backoff_paces_and_resets():
    from ray_tpu._private.host_daemon import _ProbeState
    p = _ProbeState(runtime=None)
    now = 100.0
    assert not p.throttled(now)
    p.failure(now)
    assert p.failures == 1 and p.throttled(now + 0.01)
    gap1 = p._not_before - now
    t2 = p._not_before
    p.failure(t2)
    gap2 = p._not_before - t2
    assert p.failures == 2 and gap2 >= gap1  # deterministic growth
    # paces from the poll period up to the shared backoff cap
    assert gap1 >= _config.get("preempt_poll_ms") / 1e3 - 1e-9
    p.success(1e9)
    assert p.failures == 0 and not p.throttled(1e9)


def test_preempt_signaled_backs_off_failing_probe():
    from ray_tpu._private.host_daemon import _ProbeState, _preempt_signaled
    url_was = _config.get("preempt_probe_url")
    _config.set("preempt_probe_url", "http://127.0.0.1:9/preempted")
    try:
        probe = _ProbeState(runtime=None)
        assert _preempt_signaled("unit00", probe=probe) is None
        assert probe.failures == 1
        # the immediate next poll is throttled: no second connect attempt
        assert _preempt_signaled("unit00", probe=probe) is None
        assert probe.failures == 1
    finally:
        _config.set("preempt_probe_url", url_was)


def test_doctor_flags_blind_preemption_watcher():
    from ray_tpu import doctor
    nid = "ab" * 16
    threshold = _config.get("preempt_probe_failure_threshold")
    collected = {
        "ts": 1.0, "errors": [], "sealed_now": [],
        "local": {"root": "/tmp/x", "recordings": [], "bundles": []},
        "cluster": {
            "nodes": {"nodes": []},
            "preempt": {"probe_failures": {nid: threshold,
                                           "cd" * 16: threshold - 1},
                        "fleet_rate_per_hour": 2.5},
        },
    }
    rep = doctor.diagnose(collected)
    (flag,) = rep["probe_flags"]          # only the node AT threshold
    assert flag["node_id"] == nid
    assert flag["consecutive_failures"] == threshold
    assert rep["num_issues"] >= 1
    text = doctor.render_text(rep)
    assert "BLIND PREEMPTION WATCHERS (1)" in text


# -- unit: storm grammar helper ----------------------------------------------

def test_preempt_storm_spec_grammar():
    from ray_tpu import chaos
    # 720/hour at a 500ms poll => a notice every 10th poll
    spec = chaos.preempt_storm_spec(720.0, 500.0)
    assert spec == "node.preempt@10%10=drop"
    sched = chaos.parse_spec(3, spec)
    fired = [i + 1 for i in range(35)
             if sched.fire("node.preempt", {"node": "x"}) == "drop"]
    assert fired == [10, 20, 30]
    assert "[node=w1]" in chaos.preempt_storm_spec(720.0, 500.0, node="w1")
    with pytest.raises(ValueError):
        chaos.preempt_storm_spec(0.0, 500.0)


# -- integration: proactive drain + gang replacement (in-process) ------------

def test_proactive_drain_and_gang_replacement(small_cluster):
    rt = small_cluster.runtime
    provider = FakeNodeProvider(rt, {"cpu-2": {"CPU": 2}})
    est = HazardEstimator()
    # three fresh journaled preemptions of this type push its rate past
    # hazard_drain_threshold (3 * 3600*ln2/900 ~ 8.3 >= 6.0)
    for _ in range(3):
        est.record("cpu-2", "ee" * 16)
    autoscaler = StandardAutoscaler(
        AutoscalerConfig(node_types={"cpu-2": {"CPU": 2}}, max_workers=4,
                         idle_timeout_s=3600), provider, rt, hazard=est)
    pid_a, pid_b = provider.create_node("cpu-2", 2)
    result = autoscaler.update()
    # exactly ONE node proactively drained (worst-first, not the fleet),
    # and its same-type replacement launched in the same pass
    assert result["proactively_drained"] == 1
    assert result["replaced"] == 1
    draining = [provider._nodes[p] for p in (pid_a, pid_b)
                if provider._nodes[p].draining]
    assert len(draining) == 1
    assert len(provider.non_terminated_nodes()) == 3
    # the surviving high-hazard node carries the last-choice hint
    survivor = next(provider._nodes[p] for p in (pid_a, pid_b)
                    if not provider._nodes[p].draining)
    assert survivor.pending_drain
    # the in-flight drain gates further proactive drains (no cascade),
    # and the replacement is not replaced again
    result2 = autoscaler.update()
    assert result2["proactively_drained"] == 0
    assert result2["replaced"] == 0
    assert autoscaler.num_proactive_drains == 1
    assert autoscaler.num_replacements == 1


def test_journal_roundtrip_feeds_estimator(tmp_path):
    """journal_preemption -> KV -> refresh() -> type_rate, including GC of
    events past the window — against a dict-backed fake state client."""

    class FakeState:
        def __init__(self):
            self.kv = {}

        def kv_put(self, key, value, namespace=b""):
            self.kv[(namespace, bytes(key))] = bytes(value)

        def kv_get(self, key, namespace=b""):
            return self.kv.get((namespace, bytes(key)))

        def kv_del(self, key, namespace=b""):
            self.kv.pop((namespace, bytes(key)), None)

        def kv_keys(self, prefix=b"", namespace=b""):
            return [k for (ns, k) in self.kv
                    if ns == namespace and k.startswith(prefix)]

    state = FakeState()
    now = time.time()
    hazard.journal_preemption(state, "aa" * 16, "tpu-v5e",
                              "preemption notice (chaos)", ts=now - 5.0)
    hazard.journal_preemption(state, "bb" * 16, "tpu-v5e",
                              "preemption notice (chaos)",
                              ts=now - _config.get("hazard_window_s") - 60.0)
    hazard.publish_probe_health(state, "aa" * 16, 4)
    est = HazardEstimator(state)
    est.refresh(now=now)
    assert est.type_rate("tpu-v5e", now=now) > 0.0
    # the stale event was GC'd out of the KV, not just skipped
    assert len([k for k in state.kv if k[1].startswith(b"event:")]) == 1
    assert est._probe_failures["aa" * 16] == 4
    # publish + read back the fleet rate the cadence solver consumes
    rate = est.publish_fleet_rate(now=now)
    assert hazard.read_fleet_rate(state) == pytest.approx(rate)


# -- ProcessCluster fleet-churn drill ----------------------------------------

@pytest.mark.slow
def test_fleet_churn_storm_drill(tmp_path):
    """The gated goodput-under-churn drill (run_sanitizers.sh): a seeded
    node.preempt storm cycles every worker daemon (~every 10s of life)
    while the autoscaler journals the notices, proactively drains, and
    gang-replaces — and an elastic train job on auto cadence rides the
    churn to completion with monotone committed checkpoint steps."""
    from ray_tpu import chaos, doctor
    from ray_tpu.air.config import (CheckpointConfig, FailureConfig,
                                    RunConfig, ScalingConfig)
    from ray_tpu.dashboard.head import DashboardHead
    from ray_tpu.observability import goodput
    from ray_tpu.train import JaxTrainer, session
    _require_state_service()
    ray_tpu.shutdown()
    # one notice every 20th watcher poll (~10s at the 500ms default) on
    # every worker daemon, replacements included (daemon_env rides along)
    spec = chaos.preempt_storm_spec(360.0, 500.0)
    assert spec == "node.preempt@20%20=drop"
    c = ProcessCluster(num_daemons=0, num_cpus=2,
                       daemon_env={"RAY_TPU_CHAOS": f"11:{spec}",
                                   "RAY_TPU_PREEMPT_LEAD_S": "20"})
    provider = c.node_provider({"worker": {"CPU": 2}})
    provider.create_node("worker", 2)
    autoscaler = None
    try:
        ray_tpu.init(address=c.address)
        rt = ray_tpu._private.worker.global_worker().runtime
        autoscaler = StandardAutoscaler(
            AutoscalerConfig(node_types={"worker": {"CPU": 2}},
                             max_workers=4, idle_timeout_s=3600,
                             update_interval_s=0.5), provider, rt)
        autoscaler.start()

        # -- phase 1: task plane under churn — zero loss ------------------
        @ray_tpu.remote(max_retries=5)
        def slow(i):
            time.sleep(0.3)
            return i

        refs = [slow.remote(i) for i in range(40)]
        assert sorted(ray_tpu.get(refs, timeout=240)) == list(range(40)), \
            "tasks lost to the preemption storm"

        # -- phase 2: the storm was journaled and acted on ----------------
        deadline = time.monotonic() + 120
        events = []
        while time.monotonic() < deadline:
            events = [k for k in rt.state.kv_keys(
                prefix=hazard.EVENT_PREFIX, namespace=hazard.NAMESPACE)]
            if len(events) >= 2 and autoscaler.num_replacements >= 1:
                break
            time.sleep(1.0)
        assert len(events) >= 2, "storm preemptions never journaled"
        assert autoscaler.num_replacements >= 1, \
            "no gang replacement launched"
        fleet_rate = hazard.read_fleet_rate(rt.state)
        assert fleet_rate is not None and fleet_rate > 0.0, \
            "hazard estimator never published a fleet rate"

        # -- phase 3: elastic train job, auto cadence ---------------------
        def loop(config):
            from ray_tpu.air.checkpoint import Checkpoint
            start = 0
            ckpt = session.get_checkpoint()
            if ckpt is not None:
                start = ckpt.to_dict().get("step", 0)
            for step in range(start, 30):
                time.sleep(0.05)
                session.report({"step": step},
                               checkpoint=Checkpoint.from_dict(
                                   {"step": step + 1}))

        trainer = JaxTrainer(
            loop,
            scaling_config=ScalingConfig(num_workers=2),
            run_config=RunConfig(
                name="churn", storage_path=str(tmp_path),
                failure_config=FailureConfig(max_failures=-1),
                checkpoint_config=CheckpointConfig(
                    checkpoint_frequency="auto")),
            collective_backend=None)
        result = trainer.fit()
        assert result.error is None, result.error
        assert result.metrics.get("step") == 29

        # monotone committed checkpoint steps: the auto cadence + carried
        # base_step never let a post-restart counter shadow older commits
        from ray_tpu.checkpoint import list_manifest_names, read_manifest
        root = os.path.join(str(tmp_path), "churn", "checkpoints")
        steps = [read_manifest(root, n).step
                 for n in list_manifest_names(root)]
        assert steps, "auto cadence committed no checkpoints"
        assert steps == sorted(steps) and len(set(steps)) == len(steps), \
            f"checkpoint steps not monotone: {steps}"

        # -- phase 4: merged goodput gate above the floor -----------------
        head = DashboardHead(c.address)
        try:
            merged = head._goodput()["jobs"].get(goodput.DEFAULT_JOB)
            assert merged is not None, "no goodput ledger federated"
            assert merged["goodput_pct"] > 1.0, merged
            snaps, _missing = head._metric_snapshots()
            collected = {"ts": time.time(), "errors": [],
                         "cluster": {"metrics": {"snapshots": snaps}}}
            report = doctor.diagnose(
                collected,
                goodput_baseline={goodput.DEFAULT_JOB:
                                  {"goodput_pct": 1.0, "tolerance": 1.0}})
            assert report["goodput"]["drift"] == [], \
                report["goodput"]["drift"]
        finally:
            head.stop()
    finally:
        if autoscaler is not None:
            autoscaler.stop()
        ray_tpu.shutdown()
        c.shutdown()
