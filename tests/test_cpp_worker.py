"""C++ worker/driver API tests: a native binary joins a live cluster,
round-trips the KV, and invokes Python named functions with JSON args
(the reference's cross-language C++ frontend role)."""

import json
import subprocess

import pytest

import ray_tpu
from ray_tpu._native.build import NativeBuildError, build_cpp_worker_demo
from ray_tpu.cluster_utils import ProcessCluster


@pytest.fixture(scope="module")
def demo_bin():
    try:
        return build_cpp_worker_demo()
    except NativeBuildError as e:
        pytest.skip(f"cpp worker demo unbuildable: {e}")


@pytest.fixture()
def cluster():
    ray_tpu.shutdown()
    c = ProcessCluster(num_daemons=2, num_cpus=2)
    ray_tpu.init(address=c.address)
    yield c
    ray_tpu.shutdown()
    c.shutdown()


def test_cpp_driver_end_to_end(cluster, demo_bin):
    @ray_tpu.register_named_function("cpp_add")
    def add(a, b):
        return a + b

    proc = subprocess.run([demo_bin, cluster.address],
                          capture_output=True, text=True, timeout=60)
    assert proc.returncode == 0, proc.stderr
    out = proc.stdout
    assert "nodes=3" in out or "nodes=2" in out, out  # 2 daemons (+driver)
    assert "kv=from-cpp" in out
    assert "cpp_add(2,3)=5" in out, out


def test_cpp_driver_task_error_is_language_neutral(cluster, demo_bin):
    @ray_tpu.register_named_function("cpp_add")
    def bad(a, b):
        raise ValueError("deliberate")

    proc = subprocess.run([demo_bin, cluster.address],
                          capture_output=True, text=True, timeout=60)
    assert proc.returncode == 1
    assert "deliberate" in proc.stderr  # error_message, not a pickle


def test_named_function_from_python_side(cluster):
    """Named functions are callable from Python too (registry + JSON)."""
    rt = ray_tpu._private.worker.global_worker().runtime

    @ray_tpu.register_named_function("sq")
    def sq(x):
        return x * x

    fn = rt._load_named_function("sq")
    assert fn(7) == 49
    with pytest.raises(ray_tpu.exceptions.RayTpuError):
        rt._load_named_function("nope")


def test_cpp_driver_with_auth(demo_bin):
    import os
    ray_tpu.shutdown()
    os.environ["RAY_TPU_AUTH_TOKEN"] = "cpp-secret"
    c = ProcessCluster(num_daemons=1, num_cpus=2)
    try:
        ray_tpu.init(address=c.address)

        @ray_tpu.register_named_function("cpp_add")
        def add(a, b):
            return a * 10 + b

        ok = subprocess.run([demo_bin, c.address, "cpp-secret"],
                            capture_output=True, text=True, timeout=60)
        assert ok.returncode == 0, ok.stderr
        assert "cpp_add(2,3)=23" in ok.stdout
        # wrong token: rejected at the wire, no result
        bad = subprocess.run([demo_bin, c.address, "wrong"],
                             capture_output=True, text=True, timeout=60)
        assert bad.returncode != 0
    finally:
        ray_tpu.shutdown()
        c.shutdown()
        os.environ.pop("RAY_TPU_AUTH_TOKEN", None)


def test_cpp_typed_task_and_actor_api(cluster, demo_bin):
    """The typed C++ surface (task_caller.h / actor_creator.h /
    object_ref.h roles): Task(...).Remote<int64_t>() -> ObjectRef Get(),
    Actor(...).Remote() -> typed method calls -> Kill()."""
    @ray_tpu.register_named_function("cpp_add")
    def add(a, b):
        return a + b

    @ray_tpu.register_named_actor_class("Counter")
    class Counter:
        def __init__(self, start):
            self.v = start

        def add(self, x):
            self.v += x
            return self.v

        def total(self):
            return self.v

    proc = subprocess.run([demo_bin, cluster.address, "--typed"],
                          capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stderr
    out = proc.stdout
    assert "typed_add=5" in out, out
    assert "counter_add=15" in out, out
    assert "counter_add2=22" in out, out
    assert "counter_total=22" in out, out
    assert "typed-ok" in out, out
    # Kill() took effect: the named actor is gone from Python's view too
    import time
    actor_name = next(line.split("=", 1)[1] for line in out.splitlines()
                      if line.startswith("actor_name="))
    deadline = time.monotonic() + 15
    gone = False
    while time.monotonic() < deadline and not gone:
        try:
            h = ray_tpu.get_actor(actor_name)
            ray_tpu.get(h.total.remote(), timeout=5)
            time.sleep(0.2)  # raylint: allow(bare-retry) deadline-bounded test poll
        except Exception:  # raylint: allow(swallow) any failure means the actor is gone (the pass condition)
            gone = True
    assert gone, f"actor {actor_name} still alive after Kill()"


def test_named_actor_class_from_python(cluster):
    """register_named_actor_class protocol is language-neutral: the same
    three named functions drive it from Python."""
    rt = ray_tpu._private.worker.global_worker().runtime

    @ray_tpu.register_named_actor_class("Acc")
    class Acc:
        def __init__(self, base):
            self.v = base

        def bump(self, n):
            self.v += n
            return self.v

    new = rt._load_named_function("__actor_new__::Acc")
    name = new("acc-py-1", 100)
    assert name == "acc-py-1"
    call = rt._load_named_function("__actor_call__")
    assert call("acc-py-1", "bump", 11) == 111
    assert call("acc-py-1", "bump", 1) == 112
    kill = rt._load_named_function("__actor_kill__")
    assert kill("acc-py-1") is True
