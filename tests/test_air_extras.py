"""AIR predictors/preprocessors, native scheduler kernels, util extras.

Mirrors the reference's ``air/tests/test_batch_predictor.py``,
``data/tests/test_preprocessors.py``, scheduling policy gtests
(``scheduling_policy_test.cc``), ``test_check_serialize``, and the
joblib backend tests.
"""

import numpy as np
import pytest

import ray_tpu
from ray_tpu import data as rt_data
from ray_tpu.air.checkpoint import Checkpoint
from ray_tpu.air.predictor import BatchPredictor, JaxPredictor
from ray_tpu.air.preprocessors import (BatchMapper, Chain, LabelEncoder,
                                       MinMaxScaler, OneHotEncoder,
                                       SimpleImputer, StandardScaler)


# -- preprocessors ----------------------------------------------------------

def _tabular_ds():
    rows = [{"x": float(i), "y": float(i * 2), "label": "ab"[i % 2]}
            for i in range(20)]
    return rt_data.from_items(rows, parallelism=4)


def test_standard_scaler(ray_start_regular):
    ds = _tabular_ds()
    scaler = StandardScaler(columns=["x"])
    out = scaler.fit_transform(ds)
    xs = np.array([r["x"] for r in out.take_all()])
    assert abs(xs.mean()) < 1e-6
    assert abs(xs.std() - 1.0) < 1e-6


def test_minmax_label_onehot_imputer(ray_start_regular):
    ds = _tabular_ds()
    out = MinMaxScaler(columns=["y"]).fit_transform(ds)
    ys = np.array([r["y"] for r in out.take_all()])
    assert ys.min() == 0.0 and ys.max() == 1.0

    out = LabelEncoder("label").fit_transform(ds)
    labels = {r["label"] for r in out.take_all()}
    assert labels == {0, 1}

    out = OneHotEncoder(columns=["label"]).fit_transform(ds)
    row = out.take(1)[0]
    assert "label_onehot" in row and len(row["label_onehot"]) == 2

    rows = [{"v": 1.0}, {"v": float("nan")}, {"v": 3.0}]
    ds2 = rt_data.from_items(rows, parallelism=1)
    out = SimpleImputer(columns=["v"]).fit_transform(ds2)
    vs = [r["v"] for r in out.take_all()]
    assert vs[1] == 2.0  # mean of 1 and 3


def test_chain_and_batch_mapper(ray_start_regular):
    ds = _tabular_ds()
    chain = Chain(StandardScaler(columns=["x"]),
                  BatchMapper(lambda b: {**b, "x2": b["x"] * 2}))
    out = chain.fit_transform(ds)
    row = out.take(1)[0]
    assert "x2" in row
    # transform_batch composes for serving-time use.
    batch = chain.transform_batch({"x": np.array([0.0]),
                                   "y": np.array([1.0]),
                                   "label": np.array(["a"])})
    assert "x2" in batch


# -- predictors -------------------------------------------------------------

def _linear_apply(params, batch):
    x = batch["x"] if isinstance(batch, dict) else batch
    return x * params["w"] + params["b"]


def test_jax_predictor_from_checkpoint():
    ckpt = Checkpoint.from_dict({"params": {"w": 3.0, "b": 1.0}})
    pred = JaxPredictor.from_checkpoint(ckpt, apply_fn=_linear_apply)
    out = pred.predict({"x": np.array([1.0, 2.0])})
    np.testing.assert_allclose(out, [4.0, 7.0])


def test_batch_predictor_over_dataset(ray_start_regular):
    ds = rt_data.from_items([{"x": float(i)} for i in range(10)],
                            parallelism=2)
    ckpt = Checkpoint.from_dict({"params": {"w": 2.0, "b": 0.0}})
    bp = BatchPredictor.from_checkpoint(ckpt, JaxPredictor,
                                        apply_fn=_linear_apply)
    out = bp.predict(ds, batch_size=4, keep_columns=["x"])
    rows = out.take_all()
    for r in rows:
        assert r["predictions"] == r["x"] * 2.0


def test_predictor_with_preprocessor(ray_start_regular):
    ds = rt_data.from_items([{"x": float(i)} for i in range(10)],
                            parallelism=2)
    pre = StandardScaler(columns=["x"]).fit(ds)
    ckpt = Checkpoint.from_dict({"params": {"w": 1.0, "b": 0.0}})
    pred = JaxPredictor.from_checkpoint(ckpt, apply_fn=_linear_apply,
                                        preprocessor=pre)
    out = pred.predict({"x": np.array([4.5])})  # the mean -> 0
    assert abs(out[0]) < 1e-6


# -- native scheduler kernels ----------------------------------------------

def test_native_scheduler_matches_python():
    from ray_tpu._private import scheduler as sched
    from ray_tpu._private.ids import NodeID
    from ray_tpu._private.resources import NodeResources, ResourceSet

    if sched._native() is None:
        pytest.skip("no C++ toolchain")

    def make_nodes(utils):
        nodes = []
        for u in utils:
            res = NodeResources(ResourceSet({"CPU": 10.0}))
            res.allocate(ResourceSet({"CPU": u * 10.0}))
            nodes.append(sched.NodeState(NodeID.from_random(), res))
        return nodes

    request = ResourceSet({"CPU": 1.0})
    # Pack regime: below-threshold nodes all score 0 -> preferred wins.
    nodes = make_nodes([0.1, 0.2, 0.3])
    native = sched.HybridPolicy(spread_threshold=0.5, top_k_fraction=0.01,
                                seed=0)
    chosen = native.select(nodes, request, preferred=nodes[1].node_id)
    assert chosen == nodes[1].node_id
    # Spread regime: all above threshold -> lightest node wins.
    nodes = make_nodes([0.9, 0.6, 0.8])
    chosen = sched.HybridPolicy(spread_threshold=0.5,
                                top_k_fraction=0.01).select(nodes, request)
    assert chosen == nodes[1].node_id
    # Infeasible request -> None.
    assert sched.HybridPolicy().select(
        nodes, ResourceSet({"CPU": 100.0})) is None
    # Spread policy round-robins over feasible nodes.
    nodes = make_nodes([0.0, 0.0])
    sp = sched.SpreadPolicy()
    picks = {sp.select(nodes, request).hex() for _ in range(4)}
    assert len(picks) == 2


def test_native_scheduler_dead_nodes_skipped():
    from ray_tpu._private import scheduler as sched
    from ray_tpu._private.ids import NodeID
    from ray_tpu._private.resources import NodeResources, ResourceSet

    if sched._native() is None:
        pytest.skip("no C++ toolchain")
    alive = sched.NodeState(NodeID.from_random(),
                            NodeResources(ResourceSet({"CPU": 4.0})))
    dead = sched.NodeState(NodeID.from_random(),
                           NodeResources(ResourceSet({"CPU": 4.0})),
                           alive=False)
    chosen = sched.HybridPolicy().select([dead, alive],
                                         ResourceSet({"CPU": 1.0}))
    assert chosen == alive.node_id


# -- util extras ------------------------------------------------------------

def test_inspect_serializability():
    from ray_tpu.util.check_serialize import inspect_serializability
    import threading
    ok, failures = inspect_serializability(lambda: 1)
    assert ok and not failures

    lock = threading.Lock()

    def closure_over_lock():
        return lock

    ok, failures = inspect_serializability(closure_over_lock)
    assert not ok
    assert any(f.name == "lock" for f in failures)


def test_joblib_backend(ray_start_regular):
    import joblib
    from ray_tpu.util.joblib import register_ray_tpu
    register_ray_tpu()
    with joblib.parallel_backend("ray_tpu", n_jobs=4):
        out = joblib.Parallel()(joblib.delayed(lambda x: x * x)(i)
                                for i in range(10))
    assert out == [i * i for i in range(10)]
