"""Autopilot: policies, the guardrailed actuator layer, the SLO
watch/revert loop, the decision journal, the doctor's --explain surface,
and the A/B acceptance drill.

Everything here runs against private actuator registries and dict-backed
knob stores (never the process ``_config``), with virtual clocks — the
same isolation the drill uses — so the suite is deterministic and leaves
no knob moved behind it.
"""

import json

import pytest

from ray_tpu import chaos
from ray_tpu._private.config import _config
from ray_tpu.autopilot import actuators, drill, journal as journal_mod
from ray_tpu.autopilot import policies
from ray_tpu.autopilot.controller import Autopilot, slo_value
from ray_tpu.autopilot.journal import (APPLIED, CLAMPED, FAILED, REJECTED,
                                       REVERTED, Decision, Journal,
                                       flap_counts, read_from_state)


class FakeClock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t


def make_registry(store=None):
    store = store if store is not None else dict(drill.DRILL_KNOBS)
    reg = actuators.ActuatorRegistry()
    actuators.register_config_actuators(reg=reg, store=store)
    return reg, store


def goodput_snapshot(compute, data_wait, wall=100.0):
    """Minimal controller snapshot: one ledger job, no comms/perf."""
    return {"goodput": {"jobs": {"train": {
        "wall_s": wall,
        "cats": {"compute": compute, "data_wait": data_wait}}}}}


# -- actuator layer ---------------------------------------------------------

def test_apply_clamps_to_bounds():
    reg, store = make_registry()
    j = Journal(clock=FakeClock())
    spec = drill.DRILL_KNOBS
    assert spec["data_streams_per_peer"] == 1
    dec = actuators.apply("data_streams_per_peer", 10_000, {"why": "test"},
                          journal=j, reg=reg)
    hi = reg.get("data_streams_per_peer").hi
    assert store["data_streams_per_peer"] == hi
    assert dec.action == CLAMPED
    assert dec.new == hi
    assert dec.bounds == [reg.get("data_streams_per_peer").lo, hi]
    # and below the floor clamps up
    dec = actuators.apply("data_streams_per_peer", -3, {}, journal=j,
                          reg=reg)
    assert store["data_streams_per_peer"] == \
        reg.get("data_streams_per_peer").lo
    assert dec.action == CLAMPED


def test_apply_rejects_bad_enum_and_unknown_knob():
    reg, store = make_registry()
    j = Journal(clock=FakeClock())
    with pytest.raises(ValueError):
        actuators.apply("collective_compression", "zstd", {}, journal=j,
                        reg=reg)
    assert store["collective_compression"] == "none"  # untouched
    with pytest.raises(KeyError):
        actuators.apply("no_such_knob", 1, {}, journal=j, reg=reg)
    assert [d.action for d in j.records()] == [REJECTED, REJECTED]


def test_apply_noop_is_not_journaled():
    reg, store = make_registry()
    j = Journal(clock=FakeClock())
    assert actuators.apply("data_prefetch_batches",
                           store["data_prefetch_batches"], {},
                           journal=j, reg=reg) is None
    assert j.records() == []


def test_apply_chaos_fault_leaves_previous_value_intact():
    """An injected fault at the actuation choke point must restore the
    old value and journal ``failed`` — a half-applied decision can
    never survive."""
    reg, store = make_registry()
    j = Journal(clock=FakeClock())
    prev_schedule = chaos.schedule()
    chaos.configure(7, "autopilot.apply@1=error")
    try:
        with pytest.raises(RuntimeError):
            actuators.apply("data_streams_per_peer", 4, {"src": "chaos"},
                            journal=j, reg=reg)
        assert store["data_streams_per_peer"] == 1  # previous value intact
        recs = j.records()
        assert [d.action for d in recs] == [FAILED]
        assert recs[0].old == 1 and recs[0].new == 4
        # the @1 trigger fired once: the retry lands clean
        dec = actuators.apply("data_streams_per_peer", 4, {"src": "retry"},
                              journal=j, reg=reg)
        assert dec.action == APPLIED
        assert store["data_streams_per_peer"] == 4
    finally:
        if prev_schedule is not None:
            chaos.install(prev_schedule)
        else:
            chaos.clear()


# -- controller: watch, revert, freeze --------------------------------------

def test_slo_regression_triggers_journaled_revert():
    """Synthetic regression: the prefetch policy fires, the next tick's
    telemetry shows goodput down >revert_pct vs the pre-change baseline,
    and the controller rolls the knob back within that one watch tick."""
    reg, store = make_registry()
    clock = FakeClock()
    j = Journal(clock=clock)
    pilot = Autopilot(lambda: {}, journal=j, reg=reg, clock=clock)

    # data_wait is 20% of wall: prefetch_policy proposes 0 -> 2
    decisions = pilot.tick(goodput_snapshot(compute=80.0, data_wait=20.0))
    assert [d.knob for d in decisions] == ["data_prefetch_batches"]
    assert store["data_prefetch_batches"] == 2
    baseline = slo_value(goodput_snapshot(80.0, 20.0), {"kind": "goodput_pct"})
    assert baseline == pytest.approx(80.0)

    # next tick: goodput collapsed to 60% (> 5% regression) -> revert
    clock.t += 10.0
    decisions = pilot.tick(goodput_snapshot(compute=60.0, data_wait=5.0))
    assert [d.action for d in decisions] == [REVERTED]
    assert store["data_prefetch_batches"] == 0
    rev = decisions[0]
    assert rev.old == 2 and rev.new == 0
    assert rev.evidence["baseline"] == pytest.approx(80.0)
    assert rev.evidence["observed"] == pytest.approx(60.0)
    assert pilot.status()["watches"] == []  # the experiment is closed


def test_watch_retires_after_window_without_revert():
    reg, store = make_registry()
    clock = FakeClock()
    pilot = Autopilot(lambda: {}, journal=Journal(clock=clock), reg=reg,
                      clock=clock)
    pilot.tick(goodput_snapshot(compute=80.0, data_wait=20.0))
    assert store["data_prefetch_batches"] == 2
    assert len(pilot.status()["watches"]) == 1
    # goodput holds at baseline: the change is kept, the watch expires
    for _ in range(int(_config.get("autopilot_watch_ticks"))):
        clock.t += 1.0
        assert pilot.tick(goodput_snapshot(compute=80.0, data_wait=5.0)) == []
    assert pilot.status()["watches"] == []
    assert store["data_prefetch_batches"] == 2


def test_flap_freeze_blocks_oscillating_knob():
    reg, store = make_registry()
    clock = FakeClock()
    j = Journal(clock=clock)
    for val in (2, 0, 2):  # three actuations inside the flap window
        j.record(Decision(knob="data_prefetch_batches", old=0, new=val,
                          action=APPLIED))
    pilot = Autopilot(lambda: {}, journal=j, reg=reg, clock=clock)
    assert pilot.tick(goodput_snapshot(compute=80.0, data_wait=20.0)) == []
    assert store["data_prefetch_batches"] == 0  # frozen, not re-actuated
    assert "data_prefetch_batches" in pilot.status()["flapping"]


def test_max_changes_per_tick_budget():
    reg, store = make_registry()
    clock = FakeClock()
    pilot = Autopilot(lambda: {}, journal=Journal(clock=clock), reg=reg,
                      clock=clock)
    # data_wait >10% (prefetch) + hazard feed (cadence) + clean saturated
    # links (transport): three eligible policies, budget of two
    snapshot = goodput_snapshot(compute=70.0, data_wait=20.0)
    snapshot["hazard_rate_per_hour"] = 6.0
    snapshot["cadence_inputs"] = {"step_cost_s": 1.0, "ckpt_cost_s": 0.5}
    snapshot["comms"] = {"links": {"a|b": {
        "bytes": 10 * 2 ** 30, "seconds": 1.0, "chunks": 64,
        "retries": 0, "failovers": 0}}}
    decisions = pilot.tick(snapshot)
    assert len(decisions) == int(_config.get("autopilot_max_changes_per_tick"))


# -- policies ---------------------------------------------------------------

def test_serve_batch_policy_halves_linger():
    budget = float(_config.get("serve_target_latency_ms"))
    snapshot = {"perf": {"cluster": {
        "serve.queue_wait": {"count": 32.0, "p95_ms": 0.8 * budget},
        "serve.execute": {"count": 32.0, "p50_ms": 2.0}}}}
    out = policies.serve_batch_policy(snapshot, lambda k: 40.0,
                                      ["serve.d.linger_ms"])
    assert [p["value"] for p in out] == [20.0]
    assert out[0]["slo"] == {"kind": "perf_p95", "hist": "serve.queue_wait"}
    assert out[0]["evidence"]["queue_wait_p95_ms"] == 0.8 * budget
    # under half the budget: leave the operator's linger alone
    snapshot["perf"]["cluster"]["serve.queue_wait"]["p95_ms"] = 0.4 * budget
    assert policies.serve_batch_policy(snapshot, lambda k: 40.0,
                                       ["serve.d.linger_ms"]) == []
    # at the floor there is nothing left to shrink
    snapshot["perf"]["cluster"]["serve.queue_wait"]["p95_ms"] = 0.8 * budget
    assert policies.serve_batch_policy(snapshot, lambda k: 1.0,
                                       ["serve.d.linger_ms"]) == []


def test_transport_policy_failover_vs_clean_links():
    def get(knob):
        return {"fetch_chunk_bytes": 4 * 2 ** 20,
                "data_streams_per_peer": 2}[knob]
    link = {"bytes": 2 ** 30, "seconds": 1.0, "chunks": 64,
            "retries": 0, "failovers": 0}
    # failover: halve the re-ship unit
    bad = dict(link, failovers=3)
    out = policies.transport_policy({"comms": {"links": {"a|b": bad}}}, get)
    assert [(p["knob"], p["value"]) for p in out] == \
        [("fetch_chunk_bytes", 2 * 2 ** 20)]
    # clean and saturated (64 chunks >= 4*2 streams*1 link): add a lane
    out = policies.transport_policy({"comms": {"links": {"a|b": link}}}, get)
    assert [(p["knob"], p["value"]) for p in out] == \
        [("data_streams_per_peer", 3)]
    # retries mean stress: neither grow nor shrink
    assert policies.transport_policy(
        {"comms": {"links": {"a|b": dict(link, retries=2)}}}, get) == []


def _slow_group(busbw):
    return {"groups": {"g": {"world_size": 8, "ops": {"allreduce": {
        "count": 4, "bytes": 2 ** 30, "busbw_gbps": busbw,
        "compression_ratio": 1.0}}}}}


def test_collective_policy_quantize_then_hierarchy():
    floor = float(_config.get("autopilot_busbw_floor_gbps"))
    store = {"collective_compression": "none", "collective_ranks_per_host": 0}
    out = policies.collective_policy({"comms": _slow_group(floor / 2)},
                                     store.__getitem__)
    assert [(p["knob"], p["value"]) for p in out] == \
        [("collective_compression", "q8")]
    assert out[0]["evidence"]["busbw_floor_gbps"] == floor
    # already quantized and still slow: cross the seam hierarchically
    store["collective_compression"] = "q8"
    out = policies.collective_policy({"comms": _slow_group(floor / 2)},
                                     store.__getitem__)
    assert [(p["knob"], p["value"]) for p in out] == \
        [("collective_ranks_per_host", 2)]
    # fp8's rel err only fits a loosened budget, and only under floor/2
    was = _config.get("autopilot_rel_err_budget")
    _config.set("autopilot_rel_err_budget", 2e-2)
    try:
        out = policies.collective_policy({"comms": _slow_group(floor / 4)},
                                         store.__getitem__)
        assert [(p["knob"], p["value"]) for p in out] == \
            [("collective_compression", "fp8")]
    finally:
        _config.set("autopilot_rel_err_budget", was)
    # healthy busbw: no proposal at all
    assert policies.collective_policy({"comms": _slow_group(floor * 2)},
                                      store.__getitem__) == []


def test_prefetch_policy_grows_and_gives_back():
    grow = policies.prefetch_policy(goodput_snapshot(70.0, 20.0),
                                    lambda k: 2)
    assert [(p["knob"], p["value"]) for p in grow] == \
        [("data_prefetch_batches", 4)]
    shrink = policies.prefetch_policy(goodput_snapshot(99.5, 0.5),
                                      lambda k: 2)
    assert [(p["knob"], p["value"]) for p in shrink] == \
        [("data_prefetch_batches", 1)]
    assert policies.prefetch_policy(goodput_snapshot(95.0, 5.0),
                                    lambda k: 2) == []


def test_cadence_policy_solves_young_daly():
    from ray_tpu.checkpoint.cadence import solve_interval_steps
    snapshot = {"hazard_rate_per_hour": 6.0,
                "cadence_inputs": {"step_cost_s": 1.0, "ckpt_cost_s": 0.5,
                                   "restart_cost_s": 0.0}}
    out = policies.cadence_policy(snapshot, lambda k: 0)
    want = solve_interval_steps(6.0, 1.0, 0.5)
    assert [(p["knob"], p["value"]) for p in out] == \
        [("checkpoint_cadence_autopilot_steps", want)]
    assert out[0]["evidence"]["solved_interval_steps"] == want
    # no hazard feed: local control keeps the knob
    assert policies.cadence_policy(
        {"cadence_inputs": {"step_cost_s": 1.0}}, lambda k: 0) == []


def test_cadence_override_clamped_by_operator_bounds():
    from ray_tpu.checkpoint.cadence import CadenceController
    was = _config.get("checkpoint_cadence_autopilot_steps")
    ctrl = CadenceController(hazard_source=lambda: 0.0, min_steps=5,
                             max_steps=100)
    try:
        _config.set("checkpoint_cadence_autopilot_steps", 10_000)
        assert ctrl.interval_steps() == 100
        _config.set("checkpoint_cadence_autopilot_steps", 2)
        assert ctrl.interval_steps() == 5
        _config.set("checkpoint_cadence_autopilot_steps", 24)
        assert ctrl.interval_steps() == 24
    finally:
        _config.set("checkpoint_cadence_autopilot_steps", was)


# -- journal ----------------------------------------------------------------

def test_journal_kv_roundtrip_skips_malformed():
    class FakeState:
        def __init__(self):
            self.kv = {}

        def kv_put(self, key, value, overwrite=True, namespace=b""):
            self.kv[(namespace, bytes(key))] = bytes(value)

        def kv_keys(self, prefix=b"", namespace=b""):
            return [k for (ns, k) in self.kv
                    if ns == namespace and k.startswith(prefix)]

        def kv_get(self, key, namespace=b""):
            return self.kv.get((namespace, bytes(key)))

    state = FakeState()
    clock = FakeClock()
    j = Journal(state=state, clock=clock)
    for i, val in enumerate((2, 4), start=1):
        clock.t += 1.0
        j.record(Decision(knob="data_prefetch_batches", old=val - 2,
                          new=val, evidence={"tick": i}))
    state.kv[(journal_mod.NAMESPACE,
              journal_mod.DECISION_PREFIX + b"0000000000000:000099")] = \
        b"not json"
    recs = read_from_state(state)
    assert [(r["old"], r["new"]) for r in recs] == [(0, 2), (2, 4)]
    assert recs[0]["evidence"] == {"tick": 1}
    # the knob:<name> latest pointer tracks the newest record
    latest = json.loads(state.kv_get(
        journal_mod.KNOB_PREFIX + b"data_prefetch_batches",
        namespace=journal_mod.NAMESPACE))
    assert latest["new"] == 4
    assert read_from_state(state, knob="nope") == []


def test_flap_counts_window_and_verbs():
    now = 1000.0
    recs = [{"knob": "k", "action": APPLIED, "ts": now - 10},
            {"knob": "k", "action": REVERTED, "ts": now - 5},
            {"knob": "k", "action": CLAMPED, "ts": now - 1},
            {"knob": "k", "action": REJECTED, "ts": now},       # not a change
            {"knob": "k", "action": APPLIED, "ts": now - 999},  # outside
            {"knob": "quiet", "action": APPLIED, "ts": now}]
    assert flap_counts(recs, window_s=60.0, threshold=3, now=now) == {"k": 3}
    assert flap_counts(recs, window_s=60.0, threshold=4, now=now) == {}


# -- doctor explain ---------------------------------------------------------

def test_doctor_explain_knob_renders_journal():
    from ray_tpu.doctor import explain_knob, render_explain
    decisions = [
        {"knob": "data_streams_per_peer", "old": 1, "new": 4,
         "action": "applied", "reason": "clean chunks over 1 stream",
         "evidence": {"chunks": 64}, "bounds": [1, 16], "ttl_s": 600.0,
         "ts": 1000.0},
        {"knob": "data_streams_per_peer", "old": 4, "new": 1,
         "action": "reverted", "reason": "SLO regressed",
         "evidence": {"baseline": 80.0, "observed": 60.0},
         "bounds": [1, 16], "ts": 1010.0},
        {"knob": "other", "old": 0, "new": 2, "action": "applied",
         "ts": 1020.0},
    ]
    report = {"autopilot": {
        "decisions": decisions,
        "flap_flags": [{"knob": "data_streams_per_peer", "actuations": 4}],
        "flap_window_s": 600.0}}
    ex = explain_knob(report, "data_streams_per_peer")
    assert len(ex["decisions"]) == 2
    assert len(ex["reverts"]) == 1
    assert ex["current"] == 1
    assert ex["flapping"]["actuations"] == 4
    text = render_explain(ex)
    assert "1 -> 4" in text and "4 -> 1" in text
    assert "why: SLO regressed" in text
    assert "guardrail bounds: [1, 16]" in text
    assert "chunks=64" in text
    assert "FLAPPING" in text
    # a knob the autopilot never touched says so instead of erroring
    empty = render_explain(explain_knob(report, "untouched_knob"))
    assert "no journaled decisions" in empty


# -- the A/B acceptance drill ------------------------------------------------

def test_drill_chaos_spec_is_golden():
    """The acceptance schedule everyone reasons about is the one that
    executes — and its points exist in the drill runtime."""
    assert drill.DRILL_SEED == 1303
    assert drill.DRILL_CHAOS_SPEC == \
        "drill.reader@1+=drop;drill.collective[rank=1]@1+=drop"


def test_drill_ab_autopilot_wins_and_journals_everything():
    ab = drill.run_ab()
    assert ab["gain_pct"] > 0
    assert ab["on"]["goodput_pct"] > ab["off"]["goodput_pct"]
    # the OFF arm never moved a knob
    assert ab["off"]["journal"] == []
    assert ab["off"]["knobs"]["data_streams_per_peer"] == 1
    # every ON-arm change is journaled with evidence, bounds and a verb
    recs = ab["on"]["journal"]
    assert recs, "autopilot arm journaled nothing"
    for rec in recs:
        assert rec["action"] in (APPLIED, CLAMPED, REVERTED, FAILED,
                                 REJECTED)
        assert rec["evidence"], f"unevidenced decision: {rec}"
        assert rec["bounds"] is not None
        assert rec["reason"]
    touched = {r["knob"] for r in recs}
    # each tentpole loop fired: serve linger, transport, collective
    # compression + hierarchy, prefetch, and the migrated cadence loop
    assert {drill.LINGER_KNOB, "data_streams_per_peer",
            "collective_compression", "collective_ranks_per_host",
            "data_prefetch_batches",
            "checkpoint_cadence_autopilot_steps"} <= touched
    # the serve loop actually moved the observed queue tail
    assert ab["on"]["queue_p95_ms"][-1] < ab["on"]["queue_p95_ms"][0]
    assert ab["off"]["queue_p95_ms"][-1] == ab["off"]["queue_p95_ms"][0]
    # and the ledger shows WHERE the wins came from
    assert ab["on"]["cats"]["data_wait"] < ab["off"]["cats"]["data_wait"]
    assert ab["on"]["cats"]["collective_wait"] < \
        ab["off"]["cats"]["collective_wait"]


def test_drill_is_deterministic():
    assert drill.run_ab()["gain_pct"] == drill.run_ab()["gain_pct"]
