"""Shared striped transport tests.

Covers the three things ``ray_tpu/_private/transport.py`` owns: the
startup bandwidth probe and its knob resolution (explicit value wins,
probe fills the "auto" holes, disabled probe leaves static fallbacks);
striped drain migration over the shared pool with an out-of-order,
duplicate-tolerant receiver; and striped checkpoint-chunk restore with
mid-stripe failover under the ``transport.stream`` chaos point. Object
fetch's striping/failover tests live in test_data_plane — together the
three consumers prove the pool's failover loop on every path.

The two-runtime harness matches test_data_plane: real sockets, real
stream pools, only the directory service stubbed.
"""

import shutil

import numpy as np
import pytest

from test_data_plane import _FakeState

from ray_tpu import chaos
from ray_tpu._private import transport
from ray_tpu._private.config import _config
from ray_tpu._private.ids import ObjectID
from ray_tpu.checkpoint import CheckpointEngine, load
from ray_tpu.checkpoint import manifest as mf
from ray_tpu.protocol import pb


# ------------------------------------------------------------ probe/knobs


@pytest.fixture
def fresh_probe():
    keys = ("transport_probe_bytes", "fetch_chunk_bytes",
            "data_streams_per_peer", "data_socket_buffer_bytes")
    saved = {k: _config.get(k) for k in keys}
    transport._reset_probe_for_tests()
    try:
        yield
    finally:
        for k, v in saved.items():
            _config.set(k, v)
        transport._reset_probe_for_tests()


def test_probe_autotunes_chunk_streams_and_sockbuf(fresh_probe):
    _config.set("transport_probe_bytes", 4 << 20)
    _config.set("fetch_chunk_bytes", 0)
    _config.set("data_streams_per_peer", -1)
    _config.set("data_socket_buffer_bytes", 0)
    rep = transport.probe_report()
    assert rep["probe_gbps"] > 0
    assert transport.fetch_chunk_bytes() == rep["chunk_bytes"]
    assert transport.fetch_chunk_bytes() in transport._PROBE_CANDIDATES
    # candidates larger than the probe transfer are never picked
    assert transport.fetch_chunk_bytes() <= 4 << 20
    assert transport.streams_per_peer() >= 2
    assert transport.data_sock_buf() == rep["sock_buf"]
    assert 1 << 20 <= transport.data_sock_buf() <= 64 << 20
    # one-shot: a second report reuses the measurement
    assert transport.probe_report() == rep


def test_probe_disabled_leaves_static_defaults(fresh_probe):
    _config.set("transport_probe_bytes", 0)
    _config.set("fetch_chunk_bytes", 0)
    _config.set("data_streams_per_peer", -1)
    _config.set("data_socket_buffer_bytes", 0)
    assert transport.probe_report() == {"probe_gbps": 0.0}
    assert transport.fetch_chunk_bytes() == transport.DEFAULT_CHUNK
    assert transport.streams_per_peer() == 4
    assert transport.data_sock_buf() >= 1 << 20


def test_explicit_knobs_override_probe(fresh_probe):
    _config.set("transport_probe_bytes", 4 << 20)
    _config.set("fetch_chunk_bytes", 123 * 1024)
    _config.set("data_streams_per_peer", 7)
    _config.set("data_socket_buffer_bytes", 2 << 20)
    transport.ensure_probed()
    assert transport.fetch_chunk_bytes() == 123 * 1024
    assert transport.streams_per_peer() == 7
    assert transport.data_sock_buf() == 2 << 20
    _config.set("data_streams_per_peer", 0)  # 0 = pool disabled
    assert transport.streams_per_peer() == 0


# ----------------------------------------------------- two-runtime harness


@pytest.fixture
def two_runtimes(monkeypatch):
    from ray_tpu._private import distributed as dist
    from ray_tpu._private.resources import ResourceSet

    saved = {k: _config.get(k) for k in
             ("arena_enabled", "fetch_chunk_bytes", "data_streams_per_peer")}
    # arena off: force the TCP plane; small chunks so a few-MB transfer
    # stripes into many chunks; pinned stream count (the -1 default
    # auto-tunes, which would make assertions box-dependent)
    _config.set("arena_enabled", False)
    _config.set("fetch_chunk_bytes", 64 * 1024)
    _config.set("data_streams_per_peer", 4)
    _FakeState.registry = {}
    monkeypatch.setattr(dist, "StateClient", _FakeState)
    rts = [dist.DistributedRuntime("fake-state:0", ResourceSet({"CPU": 2.0}),
                                   is_driver=True) for _ in range(2)]
    try:
        yield rts
    finally:
        for rt in rts:
            rt.shutdown()
        for k, v in saved.items():
            _config.set(k, v)


def _put_array(rt, nbytes=4 << 20):
    oid = ObjectID.from_random()
    value = np.random.RandomState(3).randint(
        0, 256, size=nbytes, dtype=np.uint8)
    rt.local_node.store.put(oid, value)
    return oid, value


def _chaos(seed, spec):
    prev = chaos.schedule()
    chaos.configure(seed, spec)
    return prev


def _unchaos(prev):
    if prev is not None:
        chaos.install(prev)
    else:
        chaos.clear()


# ------------------------------------------------------- drain migration


def test_drain_push_stripes_concurrently_and_seals(two_runtimes):
    """A sole-copy drain push stripes the object across the shared pool
    (any-order chunks) and the receiver seals a byte-identical copy."""
    rt1, rt2 = two_runtimes
    oid, value = _put_array(rt1)
    assert rt1._drain_push_object(oid, rt2.address) is True
    store2 = rt2.local_node.store
    assert store2.contains(oid)
    assert np.array_equal(store2.get(oid, timeout=0), value)
    # a full stream pool to the peer was actually opened (not the
    # control-lane fallback)
    assert len(rt1._data_streams._streams.get(rt2.address, [])) == 4


def test_drain_push_to_holder_reports_existing_copy(two_runtimes):
    """First-chunk rejection = the receiver already holds the object; the
    push must report success (a copy exists) without transferring."""
    rt1, rt2 = two_runtimes
    oid, value = _put_array(rt1)
    rt2.local_node.store.put(oid, value)
    assert rt1._drain_push_object(oid, rt2.address) is True


def test_drain_push_survives_mid_stripe_failure(two_runtimes):
    """Chaos kills stripes of the drain.migrate consumer mid-transfer:
    failed chunks must retry on surviving streams and the receiver must
    still seal a complete, byte-identical object."""
    rt1, rt2 = two_runtimes
    oid, value = _put_array(rt1)
    prev = _chaos(17, "transport.stream[consumer=drain.migrate]@2%4=reset")
    try:
        assert rt1._drain_push_object(oid, rt2.address) is True
    finally:
        _unchaos(prev)
    store2 = rt2.local_node.store
    assert store2.contains(oid)
    assert np.array_equal(store2.get(oid, timeout=0), value)


def test_push_receiver_accepts_out_of_order_and_duplicate_chunks(
        two_runtimes):
    """The receive path is order-independent by contract: chunks of one
    object may arrive on different sockets in any interleaving, and a
    failover retry may deliver the same chunk twice. Reverse order +
    duplicates must still seal byte-identical."""
    rt1, rt2 = two_runtimes
    oid, value = _put_array(rt1, nbytes=1 << 20)
    payload = rt1._serialized_for_fetch(oid)
    total = len(payload)
    chunk = 256 * 1024
    client = rt1.pool.get(rt2.address)

    def send(off):
        end = min(total, off + chunk)
        rep = pb.PushObjectReply()
        rep.ParseFromString(client.call(
            pb.PUSH_OBJECT, pb.PushObjectRequest(
                object_id=oid.binary(), offset=off, total_size=total,
                eof=end >= total).SerializeToString(),
            timeout=30, raw=payload.slices(off, end)).body)
        return rep.accepted

    offsets = list(range(0, total, chunk))
    for i, off in enumerate(reversed(offsets)):   # eof chunk arrives FIRST
        assert send(off) is True
        if i < len(offsets) - 1:
            # duplicate delivery (a failover retry) before the object
            # completes: must be an idempotent overwrite, not a reject
            assert send(off) is True
    store2 = rt2.local_node.store
    assert store2.contains(oid)
    assert np.array_equal(store2.get(oid, timeout=0), value)


# ------------------------------------------- checkpoint restore (striped)


def _save_remote_checkpoint(tmp_path):
    """Commit a checkpoint under src/, then build dst/ holding ONLY the
    manifest metadata — every chunk must come over the wire."""
    rng = np.random.default_rng(0)
    tree = {"w": rng.standard_normal((512, 1024)),   # 4 MiB -> 64 stripes
            "b": rng.standard_normal(64),
            "meta": {"step": 7}}
    src = tmp_path / "src"
    eng = CheckpointEngine(str(src))
    name = eng.save(tree, step=1, wait=True).result()
    eng.close()
    dst = tmp_path / "dst"
    dst.mkdir()
    shutil.copytree(str(src / mf.MANIFESTS_DIR), str(dst / mf.MANIFESTS_DIR))
    # resolve_latest() only returns manifests whose chunks are present, so
    # a chunkless replica must name the manifest it wants restored
    return tree, str(dst), name


def test_checkpoint_restore_fetches_chunks_over_striped_transport(
        two_runtimes, tmp_path):
    rt1, rt2 = two_runtimes
    tree, dst, name = _save_remote_checkpoint(tmp_path)
    got = load(dst, name, fetch_from=rt1.ckpt_fetcher(rt2.address))
    assert np.array_equal(got["w"], tree["w"])
    assert np.array_equal(got["b"], tree["b"])
    assert got["meta"] == {"step": 7}
    # write-through: a second restore reads locally (no fetcher needed;
    # resolve_latest now sees a fully-present manifest)
    again = load(dst)
    assert np.array_equal(again["w"], tree["w"])


def test_checkpoint_restore_survives_mid_stripe_failure(two_runtimes,
                                                        tmp_path):
    """Deterministic mid-stripe failure for the ckpt.restore consumer:
    chaos resets stripes of the chunk fetch; failover must retry them on
    the surviving streams and the restore must hash-verify clean."""
    rt1, rt2 = two_runtimes
    tree, dst, name = _save_remote_checkpoint(tmp_path)
    prev = _chaos(13, "transport.stream[consumer=ckpt.restore]@2%5=reset")
    try:
        got = load(dst, name, fetch_from=rt1.ckpt_fetcher(rt2.address))
    finally:
        _unchaos(prev)
    assert np.array_equal(got["w"], tree["w"])
    assert np.array_equal(got["b"], tree["b"])


def test_served_chunk_ids_are_validated(two_runtimes, tmp_path):
    """The wire value is a path component: anything but a bare content
    hash must be refused (and a well-formed but unknown hash is a clean
    not-found, which load() surfaces as corruption, not a hang)."""
    from ray_tpu.checkpoint import engine as ckpt_engine
    assert ckpt_engine.read_served_chunk("../../etc/passwd") is None
    assert ckpt_engine.read_served_chunk("AB" * 32) is None   # not lowercase
    assert ckpt_engine.read_served_chunk("ab" * 31) is None   # wrong length
    rt1, rt2 = two_runtimes
    assert rt1.fetch_ckpt_chunk(rt2.address, "ab" * 32) is None
