"""Goodput & efficiency ledger: wall-clock attribution, compile
accounting, clock-skew correction, federation, and the SLO surfaces.

Covers the exclusive-category ledger (interval nesting, step marks,
derived idle summing to wall-clock), jit first-trace/recompile
detection, the checkpoint bounded-queue stall hook, the data-iterator
wait hook, cross-node federation math (``merge_payloads`` /
``/api/goodput``), the ``ray-tpu top --goodput`` and doctor
``--goodput-baseline`` surfaces, the NTP-style clock-offset estimator
feeding ``task.e2e`` skew correction, and a ProcessCluster preemption
drill (self-skips without the C++ state service).
"""

import json
import time

import pytest

import ray_tpu
from ray_tpu._private import clocksync
from ray_tpu.observability import goodput, perf


@pytest.fixture(autouse=True)
def _goodput_state():
    was = goodput.ENABLED
    goodput.enable()
    goodput.reset()
    goodput.set_job(goodput.DEFAULT_JOB)
    yield
    goodput.reset()
    goodput.set_job(goodput.DEFAULT_JOB)
    if not was:
        goodput.disable()


def _require_state_service():
    """ProcessCluster needs the C++ state service (protoc + g++)."""
    from ray_tpu._native.build import build_state_service
    try:
        build_state_service()
    except Exception as e:
        pytest.skip(f"state service unavailable: {e}")


# -- ledger core ------------------------------------------------------------

def test_categories_are_exclusive_and_sum_to_wall():
    with goodput.interval("data_wait"):
        time.sleep(0.03)
    with goodput.interval("collective_wait"):
        time.sleep(0.02)
    snap = goodput.snapshot()["jobs"][goodput.DEFAULT_JOB]
    cats = snap["cats"]
    assert set(cats) == set(goodput.CATEGORIES)
    assert sum(cats.values()) == pytest.approx(snap["wall_s"], rel=1e-9)
    assert cats["data_wait"] >= 0.025
    assert cats["collective_wait"] >= 0.015
    assert cats["idle"] >= 0.0
    assert snap["goodput_pct"] == pytest.approx(
        100.0 * cats["compute"] / snap["wall_s"], abs=1e-6)


def test_unknown_category_rejected():
    with pytest.raises(ValueError):
        goodput.account("checkpoint_stall", 1.0)  # raylint: allow(metric-registry) the rejection under test
    with pytest.raises(ValueError):
        goodput.account("idle", 1.0)  # derived, never accounted
    with pytest.raises(ValueError):
        goodput.interval("not_a_category")  # raylint: allow(metric-registry) the rejection under test


def test_nested_intervals_pause_the_outer():
    """Inner interval time is attributed once, to the inner category:
    the enclosing interval accrues only its own exclusive time."""
    with goodput.interval("data_wait"):
        time.sleep(0.02)
        with goodput.interval("compile"):
            time.sleep(0.04)
        time.sleep(0.02)
    cats = goodput.snapshot()["jobs"][goodput.DEFAULT_JOB]["cats"]
    assert cats["compile"] >= 0.035
    assert 0.03 <= cats["data_wait"] <= 0.06  # ~0.04, never the full 0.08


def test_step_mark_credits_unattributed_time_as_compute():
    goodput.step_mark()                   # anchor the ledger/step window
    led_t0 = time.monotonic()
    time.sleep(0.03)                      # unclaimed -> compute
    with goodput.interval("data_wait"):   # claimed -> not compute
        time.sleep(0.03)
    credited = goodput.step_mark()
    elapsed = time.monotonic() - led_t0
    assert 0.02 <= credited <= elapsed - 0.025
    cats = goodput.snapshot()["jobs"][goodput.DEFAULT_JOB]["cats"]
    assert cats["compute"] == pytest.approx(credited, abs=1e-3)
    # a second immediate mark credits ~nothing (attributed counter reset)
    assert goodput.step_mark() <= 0.01


def test_instrument_jit_counts_compiles_and_recompiles():
    calls = []

    def fn(x):
        calls.append(x)
        time.sleep(0.01)
        return x

    wrapped = goodput.instrument_jit(fn, name="t.step")
    assert wrapped(1.0) == 1.0            # first trace: compile
    assert wrapped(2.0) == 2.0            # same signature: steady state
    assert wrapped("s") == "s"            # new signature: recompile
    snap = goodput.snapshot()["jobs"][goodput.DEFAULT_JOB]
    assert snap["compile_count"] == 2
    assert snap["recompile_count"] == 1
    assert snap["cats"]["compile"] >= 0.015
    assert len(calls) == 3                # wrapper never swallows calls
    # perf mirror: compile durations land in the jit.compile histogram
    if perf.ENABLED:
        hists = perf.snapshot()["hists"]
        assert sum(hists.get("jit.compile", {"counts": [0]})["counts"]) >= 2


def test_disabled_fast_path_is_a_noop():
    goodput.disable()
    goodput.account("data_wait", 5.0)
    with goodput.interval("compile"):
        pass
    assert goodput.step_mark() == 0.0
    wrapped = goodput.instrument_jit(lambda x: x, name="t.off")
    assert wrapped(3) == 3
    assert goodput.snapshot()["jobs"] == {}
    goodput.enable()


def test_merge_payloads_adds_seconds_and_recomputes_pct():
    node_a = {"jobs": {"j": {"wall_s": 100.0, "compile_count": 1,
                             "recompile_count": 0,
                             "cats": {"compute": 90.0, "idle": 10.0}}}}
    node_b = {"jobs": {"j": {"wall_s": 100.0, "compile_count": 2,
                             "recompile_count": 1,
                             "cats": {"compute": 10.0, "idle": 90.0}}}}
    merged = goodput.merge_payloads([node_a, node_b])
    rec = merged["j"]
    assert rec["wall_s"] == 200.0 and rec["nodes"] == 2
    assert rec["cats"]["compute"] == 100.0
    assert rec["compile_count"] == 3 and rec["recompile_count"] == 1
    # recomputed from merged seconds (50%), not averaged pcts
    assert rec["goodput_pct"] == pytest.approx(50.0)
    # malformed node payloads are skipped, not fatal
    assert goodput.merge_payloads([None, {"jobs": {"j": "bogus"}},
                                   node_a])["j"]["wall_s"] == 100.0


def test_families_export_and_extract_roundtrip():
    goodput.account("data_wait", 1.25)
    fams = goodput.families()
    assert len(fams) == 1 and fams[0]["type"] == "gauge"
    by_tags = {tuple(sorted(dict(tags).items())): v
               for _n, tags, v in fams[0]["samples"]}
    key = (("category", "data_wait"), ("job", goodput.DEFAULT_JOB))
    assert by_tags[key] == pytest.approx(1.25)
    # the raw payload survives a JSON federation hop untouched
    wire = json.loads(json.dumps(fams))
    payload = goodput.extract_goodput(wire)
    assert payload["jobs"][goodput.DEFAULT_JOB]["cats"]["data_wait"] == \
        pytest.approx(1.25)
    assert goodput.extract_goodput([{"name": "x", "samples": []}]) is None


def test_metrics_snapshot_carries_goodput_family():
    from ray_tpu.util import metrics
    goodput.account("collective_wait", 0.5)
    snap = metrics.snapshot()
    assert any(f.get("name") == "raytpu_goodput_seconds" for f in snap)


# -- instrumentation hooks --------------------------------------------------

def test_ckpt_stall_accounted_on_full_queue(tmp_path):
    """save() on a full bounded queue blocks under the ckpt_stall
    interval; a drain from another thread unblocks it."""
    import threading

    import numpy as np
    from ray_tpu._private.config import _config
    from ray_tpu.checkpoint.engine import CheckpointEngine

    depth_was = _config.checkpoint_queue_depth
    _config.set("checkpoint_queue_depth", 1)
    try:
        eng = CheckpointEngine(str(tmp_path / "ckpt"))
        eng._ensure_writer = lambda: None   # keep the queue full
        eng._queue.put_nowait(None)         # occupy the single slot

        def drain():
            time.sleep(0.1)
            eng._queue.get()

        t = threading.Thread(target=drain, daemon=True)
        t.start()
        eng.save({"x": np.zeros(4)}, step=1)
        t.join(timeout=10)
        cats = goodput.snapshot()["jobs"][goodput.DEFAULT_JOB]["cats"]
        assert cats["ckpt_stall"] >= 0.08
    finally:
        _config.set("checkpoint_queue_depth", depth_was)


def test_data_wait_iterator_attribution():
    from ray_tpu.data.dataset import _data_wait_iter

    def slow_batches():
        for i in range(3):
            time.sleep(0.02)
            yield i

    assert list(_data_wait_iter(slow_batches())) == [0, 1, 2]
    cats = goodput.snapshot()["jobs"][goodput.DEFAULT_JOB]["cats"]
    assert cats["data_wait"] >= 0.05


def test_collective_wait_decorator():
    from ray_tpu.collective.collective import _collective_wait

    @_collective_wait
    def fake_allreduce(x):
        time.sleep(0.03)
        return x

    assert fake_allreduce(7) == 7
    cats = goodput.snapshot()["jobs"][goodput.DEFAULT_JOB]["cats"]
    assert cats["collective_wait"] >= 0.025


def test_session_report_marks_steps():
    """session.report drives step_mark: per-step wall time no explicit
    interval claimed accrues as compute on the training process."""
    from ray_tpu.train import session

    session._init_session(world_rank=0, world_size=1)
    try:
        goodput.step_mark()           # open the step window
        time.sleep(0.03)              # the "device step"
        with goodput.interval("data_wait"):
            time.sleep(0.03)          # claimed: must not become compute
        session.report({"loss": 1.0})
    finally:
        session._shutdown_session()
    cats = goodput.snapshot()["jobs"][goodput.DEFAULT_JOB]["cats"]
    assert cats["compute"] >= 0.02
    assert cats["compute"] <= 0.05    # the data_wait slice stayed out


# -- clock-skew correction --------------------------------------------------

@pytest.fixture()
def _clocksync_state():
    was = clocksync.ENABLED
    clocksync.ENABLED = True
    clocksync.reset()
    yield
    clocksync.reset()
    clocksync.ENABLED = was


def test_clocksync_lowest_rtt_sample_wins(_clocksync_state):
    # congested sample: rtt 0.4s, midpoint 10.2, offset +1.2
    clocksync.observe(10.0, 10.4, 9.0)
    assert clocksync.offset_s() == pytest.approx(1.2)
    # clean sample: rtt 0.02s, midpoint 10.51, offset +1.51 -> wins
    clocksync.observe(10.5, 10.52, 9.0)
    assert clocksync.offset_s() == pytest.approx(1.51)
    assert clocksync.synced()
    # a later congested sample never displaces the low-RTT estimate
    clocksync.observe(11.0, 11.8, 9.0)
    assert clocksync.offset_s() == pytest.approx(1.51)


def test_clocksync_rebase_roundtrip_and_guards(_clocksync_state):
    clocksync.observe(100.0, 100.02, 90.01)   # offset ~ +10.0
    local = 123.456
    assert clocksync.to_local_s(clocksync.to_server_s(local)) == \
        pytest.approx(local)
    assert clocksync.to_server_s(local) == pytest.approx(local - 10.0,
                                                         abs=0.02)
    before = clocksync.offset_s()
    clocksync.observe(50.0, 49.0, 40.0)   # negative rtt: clock stepped
    clocksync.observe(50.0, 50.01, 0.0)   # beacon absent (old service)
    assert clocksync.offset_s() == before
    clocksync.reset()
    assert clocksync.offset_s() == 0.0 and not clocksync.synced()


def test_clocksync_exports_skew_gauge(_clocksync_state):
    clocksync.observe(10.0, 10.02, 9.51)  # offset ~ +0.5s
    samples = clocksync._skew_gauge().samples()
    assert any(name == "clock_skew_ms" and v == pytest.approx(500.0, abs=20)
               for name, _t, v in samples)


def test_spec_stamp_rebases_through_service_timebase(_clocksync_state):
    """_spec_to_msg ships perf_submit_s in the service timebase;
    _msg_to_spec rebases onto the receiving clock. With one process
    playing both sides the round trip is identity; the wire stamp is
    shifted by the estimated offset."""
    from ray_tpu.protocol import pb
    clocksync.observe(200.0, 200.02, 150.01)  # offset ~ +50s
    stamp = time.time()
    wire = clocksync.to_server_s(stamp)
    assert wire == pytest.approx(stamp - 50.0, abs=0.1)
    msg = pb.TaskSpecMsg(perf_submit_s=wire)
    parsed = pb.TaskSpecMsg()
    parsed.ParseFromString(msg.SerializeToString())
    assert clocksync.to_local_s(parsed.perf_submit_s) == \
        pytest.approx(stamp, abs=1e-6)


def test_heartbeat_reply_carries_server_time_field():
    from ray_tpu.protocol import pb
    rep = pb.HeartbeatReply(recognized=True, server_time_ms=1234.5)
    parsed = pb.HeartbeatReply()
    parsed.ParseFromString(rep.SerializeToString())
    assert parsed.server_time_ms == 1234.5
    # absent field reads 0.0 — the "service predates the beacon" marker
    assert pb.HeartbeatReply().server_time_ms == 0.0


# -- surfaces: top / render / doctor ----------------------------------------

def test_top_partial_federation_renders_placeholder():
    """A node that never recorded a family gets a '—' placeholder row
    instead of silently vanishing from the table."""
    from ray_tpu.scripts.cli import _render_top, _top_rows
    summ = {"count": 10.0, "mean_ms": 1.0, "p50_ms": 1.0,
            "p95_ms": 1.0, "p99_ms": 1.0}
    payload = {"nodes": {"node:aa": {"task.execute": summ,
                                     "rpc.call": summ},
                         "node:bb": {"rpc.call": summ}}}
    rows = {(n, h): s for n, h, s, _f in _top_rows(payload)}
    assert rows[("node:bb", "task.execute")] is None
    assert rows[("node:aa", "task.execute")] == summ
    text = _render_top(payload)
    placeholder = [ln for ln in text.splitlines()
                   if ln.startswith("node:bb") and "task.execute" in ln]
    assert len(placeholder) == 1 and "—" in placeholder[0]
    assert not any("—" in ln for ln in text.splitlines()
                   if "rpc.call" in ln)


def test_render_goodput_table():
    from ray_tpu.scripts.cli import _render_goodput
    rec = {"wall_s": 100.0, "goodput_pct": 90.0,
           "cats": {c: 0.0 for c in goodput.CATEGORIES}}
    rec["cats"].update(compute=90.0, idle=10.0)
    payload = {"categories": list(goodput.CATEGORIES),
               "jobs": {"train-1": rec},
               "nodes": {"node:aa": {"train-1": rec}},
               "missing_hosts": ["node:dead"]}
    text = _render_goodput(payload)
    lines = text.splitlines()
    assert "GOODPUT%" in lines[0] and "restart_" in lines[0]
    assert any(ln.startswith("CLUSTER") and "90.0%" in ln for ln in lines)
    assert any(ln.startswith("node:aa") for ln in lines)
    assert "1 unreachable host(s) omitted" in lines[-1]
    empty = _render_goodput({"categories": list(goodput.CATEGORIES)})
    assert "no goodput ledgers" in empty


def test_doctor_goodput_section_and_baseline_drift():
    from ray_tpu import doctor
    goodput.account("data_wait", 2.0)
    goodput.account("restart_downtime", 30.0)
    goodput.step_mark()
    collected = {"ts": time.time(), "errors": [],
                 "cluster": {"metrics": {"snapshots": {
                     "head": goodput.families()}}}}
    job = goodput.DEFAULT_JOB
    loose = doctor._goodput_reports(
        collected, baseline={job: {"goodput_pct": 0.0,
                                   "restart_downtime_s": 60.0}})
    assert loose["jobs"][job]["cats"]["restart_downtime"] == \
        pytest.approx(30.0)
    assert loose["drift"] == []
    tight = doctor._goodput_reports(
        collected, baseline={job: {"goodput_pct": 99.0,
                                   "restart_downtime_s": 1.0,
                                   "tolerance": 1.0}})
    assert {d["metric"] for d in tight["drift"]} == \
        {"goodput_pct", "restart_downtime_s"}
    # unknown jobs in the baseline are ignored, not phantom drift
    assert doctor._goodput_reports(
        collected, baseline={"ghost": {"goodput_pct": 99.0}})["drift"] == []
    report = doctor.diagnose(
        collected, goodput_baseline={job: {"goodput_pct": 99.0}})
    assert not report["healthy"]
    assert report["goodput"]["drift"]
    rendered = doctor.render_text(report)
    assert "GOODPUT" in rendered and "GOODPUT DRIFT" in rendered
    assert "restart_downtime" in rendered


def test_head_goodput_merges_and_degrades():
    """_goodput merges per-node payloads and surfaces unreachable hosts
    without failing the endpoint."""
    from ray_tpu.dashboard.head import DashboardHead
    goodput.account("data_wait", 1.0)
    head = DashboardHead.__new__(DashboardHead)
    fams = goodput.families()
    head._metric_snapshots = lambda: (
        {"head": fams, "node:aa": fams, "node:bb": []}, ["node:cc"])
    payload = head._goodput()
    job = goodput.DEFAULT_JOB
    assert payload["missing_hosts"] == ["node:cc"]
    assert set(payload["nodes"]) == {"head", "node:aa"}
    merged = payload["jobs"][job]
    assert merged["nodes"] == 2
    assert merged["cats"]["data_wait"] == pytest.approx(2.0)
    assert merged["wall_s"] == pytest.approx(
        2 * fams[0]["goodput"]["jobs"][job]["wall_s"], rel=0.5)
    assert set(payload["categories"]) == set(goodput.CATEGORIES)


# -- acceptance drill (self-skip without the C++ state service) --------------

def test_cluster_goodput_preemption_drill():
    """node.preempt chaos evicts the daemon hosting a stateful actor:
    the survivor's restore accounts the cross-process downtime gap, the
    federated /api/goodput shows it (categories still summing to
    wall-clock within 1%), goodput_pct recovers as compute resumes, and
    a doctor goodput baseline flags the lowered budget."""
    from ray_tpu.cluster_utils import ProcessCluster
    from ray_tpu.dashboard.head import DashboardHead
    from ray_tpu import doctor
    from tests.test_drain import Keeper, _actor_call_with_retry
    _require_state_service()
    ray_tpu.shutdown()
    c = ProcessCluster(num_daemons=2, num_cpus=2)
    # the chaos daemon's 6th watcher poll (~3s) returns the eviction
    # notice; the pin resource forces the actor onto it
    c.add_daemon(resources={"pin": 1.0},
                 env={"RAY_TPU_CHAOS": "7:node.preempt@6=drop",
                      "RAY_TPU_PREEMPT_LEAD_S": "20"})
    try:
        ray_tpu.init(address=c.address)
        rt = ray_tpu._private.worker.global_worker().runtime

        k = Keeper.options(resources={"pin": 1.0}).remote()
        assert ray_tpu.get(k.inc.remote(), timeout=60) == 1
        victim_node, _pid = ray_tpu.get(k.where.remote(), timeout=30)

        # wait out the eviction: the victim drains and decommissions
        deadline = time.monotonic() + 90
        gone = False
        while time.monotonic() < deadline:
            info = {n.node_id.hex(): n for n in rt.state.list_nodes()}
            n = info.get(victim_node)
            if n is not None and not n.alive:
                gone = True
                break
            time.sleep(0.5)
        assert gone, "chaos daemon never decommissioned"

        # actor migrates + resumes; the survivor accounts the gap
        assert _actor_call_with_retry(k.inc, 90) == 2

        head = DashboardHead(c.address)
        try:
            payload = head._goodput()
            job = goodput.DEFAULT_JOB
            merged = payload["jobs"].get(job)
            assert merged is not None, payload
            downtime = merged["cats"].get("restart_downtime", 0.0)
            assert downtime > 0.0, "preemption gap never attributed"
            pct_before = merged["goodput_pct"]
            # per-node and merged ledgers: categories sum to wall-clock
            # within 1% (the exclusivity acceptance bound)
            for node, jobs in payload["nodes"].items():
                for jname, rec in jobs.items():
                    total = sum(rec["cats"].values())
                    assert total == pytest.approx(
                        rec["wall_s"], rel=0.01), (node, jname)
            assert sum(merged["cats"].values()) == pytest.approx(
                merged["wall_s"], rel=0.01)

            # goodput recovers: steady compute on the driver raises the
            # merged percentage above the post-eviction reading (the
            # drill's wall is dominated by idle/downtime, so a ~1s
            # compute burst moves the merged ratio up)
            compute_before = merged["cats"].get("compute", 0.0)
            goodput.step_mark()
            for _ in range(20):
                time.sleep(0.05)
                goodput.step_mark()
            after = head._goodput()["jobs"][job]
            assert after["cats"]["compute"] >= compute_before + 0.5
            assert after["goodput_pct"] > pct_before

            # the doctor gate flags the preemption-lowered budget
            snaps, _missing = head._metric_snapshots()
            collected = {"ts": time.time(), "errors": [],
                         "cluster": {"metrics": {"snapshots": snaps}}}
            report = doctor.diagnose(
                collected,
                goodput_baseline={job: {"goodput_pct": 99.0,
                                        "restart_downtime_s": 0.001}})
            metrics_flagged = {d["metric"]
                               for d in report["goodput"]["drift"]}
            assert "restart_downtime_s" in metrics_flagged
        finally:
            head.stop()
    finally:
        ray_tpu.shutdown()
        c.shutdown()
