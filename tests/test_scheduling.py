"""Multi-node scheduling, placement groups, node failure, lineage recovery.

Models ``python/ray/tests/test_placement_group*.py``, ``test_multi_node*.py``,
``test_chaos.py`` coverage on the in-process Cluster.
"""

import time

import pytest

import ray_tpu
from ray_tpu.util.placement_group import (placement_group,
                                          placement_group_table,
                                          remove_placement_group)
from ray_tpu.util.scheduling_strategies import (
    NodeAffinitySchedulingStrategy, PlacementGroupSchedulingStrategy)


def test_spread_scheduling(ray_start_cluster):
    cluster = ray_start_cluster
    for _ in range(4):
        cluster.add_node(num_cpus=2)

    @ray_tpu.remote(scheduling_strategy="SPREAD")
    def where():
        return ray_tpu.get_runtime_context().node_id.hex()

    nodes = set(ray_tpu.get([where.remote() for _ in range(16)]))
    assert len(nodes) >= 3, f"SPREAD should use most nodes, got {nodes}"


def test_node_affinity(ray_start_cluster):
    cluster = ray_start_cluster
    n1 = cluster.add_node(num_cpus=2)
    n2 = cluster.add_node(num_cpus=2)

    @ray_tpu.remote
    def where():
        return ray_tpu.get_runtime_context().node_id.hex()

    target = n2.node_id.hex()
    strategy = NodeAffinitySchedulingStrategy(node_id=target, soft=False)
    got = ray_tpu.get([where.options(scheduling_strategy=strategy).remote()
                       for _ in range(5)])
    assert all(g == target for g in got)


def test_custom_resources(ray_start_cluster):
    cluster = ray_start_cluster
    cluster.add_node(num_cpus=2)
    cluster.add_node(num_cpus=2, resources={"special": 2})

    @ray_tpu.remote(resources={"special": 1})
    def needs_special():
        return ray_tpu.get_runtime_context().node_id.hex()

    special_node = cluster._nodes[1].node_id.hex()
    assert ray_tpu.get(needs_special.remote()) == special_node


def test_infeasible_task_errors(ray_start_cluster):
    cluster = ray_start_cluster
    cluster.add_node(num_cpus=1)

    @ray_tpu.remote(num_cpus=64)
    def impossible():
        return 1

    with pytest.raises(ray_tpu.RayTpuError):
        ray_tpu.get(impossible.remote(), timeout=10)


def test_placement_group_strict_spread(ray_start_cluster):
    cluster = ray_start_cluster
    for _ in range(3):
        cluster.add_node(num_cpus=2)
    pg = placement_group([{"CPU": 1}] * 3, strategy="STRICT_SPREAD")
    assert pg.wait(10)
    table = placement_group_table()[pg.id.hex()]
    assert table["state"] == "CREATED"
    assert len(set(table["bundle_nodes"])) == 3


def test_placement_group_strict_pack(ray_start_cluster):
    cluster = ray_start_cluster
    cluster.add_node(num_cpus=4)
    cluster.add_node(num_cpus=4)
    pg = placement_group([{"CPU": 2}, {"CPU": 2}], strategy="STRICT_PACK")
    assert pg.wait(10)
    table = placement_group_table()[pg.id.hex()]
    assert len(set(table["bundle_nodes"])) == 1


def test_task_in_placement_group(ray_start_cluster):
    cluster = ray_start_cluster
    cluster.add_node(num_cpus=2)
    cluster.add_node(num_cpus=2)
    pg = placement_group([{"CPU": 1}, {"CPU": 1}], strategy="STRICT_SPREAD")
    assert pg.wait(10)

    @ray_tpu.remote(num_cpus=1)
    def where():
        return ray_tpu.get_runtime_context().node_id.hex()

    strategy = PlacementGroupSchedulingStrategy(
        placement_group=pg, placement_group_bundle_index=0)
    n0 = ray_tpu.get(where.options(scheduling_strategy=strategy).remote())
    strategy1 = PlacementGroupSchedulingStrategy(
        placement_group=pg, placement_group_bundle_index=1)
    n1 = ray_tpu.get(where.options(scheduling_strategy=strategy1).remote())
    table = placement_group_table()[pg.id.hex()]
    assert [n0, n1] == table["bundle_nodes"]


def test_remove_placement_group_releases_resources(ray_start_cluster):
    cluster = ray_start_cluster
    cluster.add_node(num_cpus=2)
    pg = placement_group([{"CPU": 2}], strategy="PACK")
    assert pg.wait(10)
    assert ray_tpu.available_resources().get("CPU", 0) == 0
    remove_placement_group(pg)
    time.sleep(0.1)
    assert ray_tpu.available_resources().get("CPU", 0) == 2


def test_actor_in_placement_group(ray_start_cluster):
    cluster = ray_start_cluster
    cluster.add_node(num_cpus=2)
    cluster.add_node(num_cpus=2)
    pg = placement_group([{"CPU": 1}, {"CPU": 1}], strategy="STRICT_SPREAD")
    assert pg.wait(10)

    @ray_tpu.remote(num_cpus=1)
    class Pinned:
        def where(self):
            return ray_tpu.get_runtime_context().node_id.hex()

    strategy = PlacementGroupSchedulingStrategy(
        placement_group=pg, placement_group_bundle_index=1)
    a = Pinned.options(scheduling_strategy=strategy).remote()
    loc = ray_tpu.get(a.where.remote())
    assert loc == placement_group_table()[pg.id.hex()]["bundle_nodes"][1]


def test_node_failure_kills_actors(ray_start_cluster):
    cluster = ray_start_cluster
    cluster.add_node(num_cpus=2)
    victim = cluster.add_node(num_cpus=2)

    @ray_tpu.remote(num_cpus=2)
    class Pinned:
        def ping(self):
            return "pong"

    strategy = NodeAffinitySchedulingStrategy(
        node_id=victim.node_id.hex(), soft=False)
    a = Pinned.options(scheduling_strategy=strategy).remote()
    assert ray_tpu.get(a.ping.remote()) == "pong"
    cluster.remove_node(victim)
    time.sleep(0.2)
    with pytest.raises(ray_tpu.ActorDiedError):
        ray_tpu.get(a.ping.remote(), timeout=5)


def test_lineage_reconstruction_on_node_loss(ray_start_cluster):
    """Objects lost with their node are recomputed from lineage
    (reference: ObjectRecoveryManager, test_chaos.py)."""
    cluster = ray_start_cluster
    stable = cluster.add_node(num_cpus=2)
    victim = cluster.add_node(num_cpus=2)

    @ray_tpu.remote(max_retries=2)
    def produce():
        return list(range(1000))

    strategy = NodeAffinitySchedulingStrategy(
        node_id=victim.node_id.hex(), soft=False)
    ref = produce.options(scheduling_strategy=strategy).remote()
    assert len(ray_tpu.get(ref)) == 1000
    cluster.remove_node(victim)
    # Object is gone with the node; get() must reconstruct via lineage.
    assert len(ray_tpu.get(ref, timeout=15)) == 1000


def test_actor_restart_after_node_death(ray_start_cluster):
    cluster = ray_start_cluster
    cluster.add_node(num_cpus=2)
    victim = cluster.add_node(num_cpus=2)

    @ray_tpu.remote(max_restarts=1, num_cpus=1)
    class Survivor:
        def ping(self):
            return ray_tpu.get_runtime_context().node_id.hex()

    strategy = NodeAffinitySchedulingStrategy(
        node_id=victim.node_id.hex(), soft=True)
    a = Survivor.options(scheduling_strategy=strategy).remote()
    first = ray_tpu.get(a.ping.remote())
    cluster.remove_node(victim)
    time.sleep(0.5)
    second = ray_tpu.get(a.ping.remote(), timeout=10)
    assert second != first or first != victim.node_id.hex()
