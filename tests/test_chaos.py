"""Chaos engine + unified backoff/deadline/breaker policy tests.

Three layers:

1. engine unit tests — spec grammar, trigger semantics, seeded
   determinism (same seed => byte-identical fault trace);
2. backoff/breaker unit tests — jittered delays, deadline budgets,
   retry_call classification, circuit state machine under a fake clock;
3. integration — injected resets/drops through the real RPC stack, a
   StateClient surviving a state-service restart, and a multi-process
   cluster completing a workload after chaos kills a node mid-run.

An autouse fixture snapshots/restores the process-wide schedule so these
tests compose with an ambient ``RAY_TPU_CHAOS`` gate (run_sanitizers.sh
runs other suites under a delay-only schedule; this suite manages its
own).
"""

import os
import socket
import subprocess
import sys
import time

import pytest

import ray_tpu
from ray_tpu import chaos
from ray_tpu._private.backoff import (BackoffPolicy, BreakerBoard,
                                      CircuitBreaker, retry_call)
from ray_tpu._private.config import _config
from ray_tpu._private.ids import ObjectID
from ray_tpu._private.object_store import ObjectLostError, ObjectStore
from ray_tpu._private.rpc import (RpcClient, RpcConnectionError, RpcServer)
from ray_tpu._private.state_client import StateClient, start_state_service
from ray_tpu.chaos.engine import (ChaosConnectionReset, ChaosError,
                                  parse_env, parse_spec)
from ray_tpu.cluster_utils import ProcessCluster
from ray_tpu.protocol import pb


@pytest.fixture(autouse=True)
def _isolate_chaos():
    """Each test starts fault-free and restores whatever schedule (e.g.
    from an ambient RAY_TPU_CHAOS gate) was installed before it."""
    prev = chaos.schedule()
    chaos.clear()
    yield
    if prev is not None:
        chaos.install(prev)
    else:
        chaos.clear()


# -- engine: grammar ----------------------------------------------------------

def test_parse_spec_fields():
    sched = parse_spec(42, "rpc.client.send[method=PUSH_*]@3%5=delay(0.25); "
                           "task.execute@2+=drop")
    assert sched.seed == 42 and len(sched.rules) == 2
    r0, r1 = sched.rules
    assert (r0.point_glob, r0.label_key, r0.label_glob) == \
        ("rpc.client.send", "method", "PUSH_*")
    assert (r0.trig_kind, r0.trig_n, r0.trig_m) == ("every", 3, 5)
    assert (r0.action, r0.arg) == ("delay", 0.25)
    assert (r1.trig_kind, r1.trig_n, r1.action) == ("from", 2, "drop")


def test_parse_env_roundtrip():
    sched = parse_env("7:task.execute@1=exit(3)")
    assert sched.seed == 7
    r = sched.rules[0]
    assert (r.action, r.arg, r.trig_kind) == ("exit", 3, "nth")


@pytest.mark.parametrize("bad", [
    "no-action-here",
    "p@x=drop",                 # bad trigger
    "p@0=drop",                 # ordinal must be >= 1
    "p@2%0=drop",               # zero modulus
    "p@1=explode",              # unknown action
    "p@1=delay",                # delay needs seconds
    "p@1=delay(-1)",            # negative delay
    "p@1=drop(5)",              # drop takes no argument
    "",                         # no rules at all
])
def test_parse_spec_rejects(bad):
    with pytest.raises(ValueError):
        parse_spec(1, bad)


@pytest.mark.parametrize("bad_env", ["nocolon", "abc:p@1=drop", ":p@1=drop"])
def test_parse_env_rejects(bad_env):
    with pytest.raises(ValueError):
        parse_env(bad_env)


# -- engine: trigger semantics -----------------------------------------------

def _fire_seq(sched, n, point="p", **labels):
    out = []
    for _ in range(n):
        try:
            out.append(sched.fire(point, labels))
        except ChaosConnectionReset:
            out.append("reset")
        except ChaosError:
            out.append("error")
    return out


def test_trigger_nth_is_one_shot():
    sched = parse_spec(1, "p@2=drop")
    assert _fire_seq(sched, 5) == [None, "drop", None, None, None]


def test_trigger_from():
    sched = parse_spec(1, "p@3+=drop")
    assert _fire_seq(sched, 5) == [None, None, "drop", "drop", "drop"]


def test_trigger_every():
    sched = parse_spec(1, "p@2%3=drop")
    assert _fire_seq(sched, 9) == [None, "drop", None, None, "drop",
                                   None, None, "drop", None]


def test_point_glob_and_label_filter():
    sched = parse_spec(1, "rpc.client.*@1+=drop; "
                          "state.call[method=HEART*]@1+=drop")
    assert sched.fire("rpc.client.send", {"peer": "x"}) == "drop"
    assert sched.fire("rpc.server.send", {}) is None
    assert sched.fire("state.call", {"method": "KV_GET"}) is None
    assert sched.fire("state.call", {"method": "HEARTBEAT"}) == "drop"


def test_actions_raise_typed_exceptions():
    sched = parse_spec(1, "r@1=reset; e@1=error(boom)")
    with pytest.raises(ChaosConnectionReset) as ri:
        sched.fire("r", {})
    assert isinstance(ri.value, ConnectionError)   # transport-shaped
    with pytest.raises(ChaosError, match="boom"):
        sched.fire("e", {})


def test_delay_sleeps_and_reports():
    sched = parse_spec(1, "d@1=delay(0.05)")
    t0 = time.monotonic()
    assert sched.fire("d", {}) == "delay"
    assert time.monotonic() - t0 >= 0.04


def test_first_rule_wins_but_later_counters_advance():
    # Both rules match every "p" event; rule#0 fires first on event 2,
    # rule#1's counter still advanced so its @2 one-shot is spent.
    sched = parse_spec(1, "p@2=drop; p@2=delay(0)")
    assert _fire_seq(sched, 4) == [None, "drop", None, None]
    assert sched.rules[1].count == 4 and not \
        any("rule#1" in ln for ln in sched.trace_lines())


# -- engine: determinism ------------------------------------------------------

def test_same_seed_byte_identical_trace():
    spec = "p@p0.4=drop; q@2%3=delay(0)"
    a, b = parse_spec(99, spec), parse_spec(99, spec)
    for sched in (a, b):
        for i in range(50):
            sched.fire("p", {"k": str(i % 3)})
            sched.fire("q", {})
    assert a.trace_text() == b.trace_text()
    assert a.trace_lines()  # the schedule actually fired


def test_different_seed_different_prob_decisions():
    spec = "p@p0.5=drop"
    a, b = parse_spec(1, spec), parse_spec(2, spec)
    seq_a = _fire_seq(a, 64)
    seq_b = _fire_seq(b, 64)
    assert seq_a != seq_b          # deterministic given the seeds above
    assert "drop" in seq_a and "drop" in seq_b


def test_prob_rules_draw_even_when_another_rule_fires():
    # An earlier always-firing rule must not desync a later prob rule:
    # its counter and RNG stream advance on every MATCHING event, so the
    # decision stream is a pure function of (seed, rule index, ordinal).
    spec = "p@1+=delay(0); p@p0.5=drop"
    a = parse_spec(7, spec)
    _fire_seq(a, 32)
    assert a.rules[1].count == 32          # advanced despite never winning
    # a fresh schedule's rule#1, driven directly, reproduces the stream
    b = parse_spec(7, spec)
    direct = [b.rules[1].should_fire() for _ in range(32)]
    c = parse_spec(7, spec)
    via_fire = []
    for _ in range(32):
        c.fire("p", {})
        via_fire.append(c.rules[1].count)
    assert c.rules[1].count == 32
    assert any(direct) and not all(direct)  # p0.5 over 32 draws mixes


def test_trace_file_identical_across_processes(tmp_path):
    """Acceptance: two subprocess runs with the same RAY_TPU_CHAOS and the
    same event sequence write byte-identical trace files."""
    snippet = (
        "from ray_tpu import chaos\n"
        "for i in range(20):\n"
        "    try:\n"
        "        chaos.inject('p', k=str(i % 4))\n"
        "    except Exception:\n"
        "        pass\n"
    )
    traces = []
    for run in ("a", "b"):
        path = tmp_path / f"trace-{run}.log"
        env = dict(os.environ,
                   JAX_PLATFORMS="cpu",
                   RAY_TPU_CHAOS="123:p@p0.5=drop;p@3%4=error(x)",
                   RAY_TPU_CHAOS_TRACE=str(path))
        subprocess.run([sys.executable, "-c", snippet], env=env, check=True,
                       timeout=120)
        # strip the pid prefix — it is the one legitimately varying field
        lines = [ln.split("] ", 1)[1] for ln in
                 path.read_text().splitlines()]
        traces.append("\n".join(lines))
    assert traces[0] == traces[1] and traces[0]


# -- module API ---------------------------------------------------------------

def test_configure_install_clear():
    assert chaos.ENABLED is False
    assert chaos.inject("p") is None          # no schedule -> no-op
    chaos.configure(5, "p@1=drop")
    assert chaos.ENABLED is True
    assert chaos.inject("p") == "drop"
    assert chaos.trace_lines() and "p" in chaos.trace_text()
    chaos.clear()
    assert chaos.ENABLED is False and chaos.schedule() is None


# -- backoff policy -----------------------------------------------------------

def test_delay_for_bounds_and_cap():
    p = BackoffPolicy(base_s=0.1, max_s=0.8, multiplier=2.0, deadline_s=0,
                      jitter=False)
    assert [p.delay_for(i) for i in range(5)] == [0.1, 0.2, 0.4, 0.8, 0.8]
    j = BackoffPolicy(base_s=0.1, max_s=0.8, multiplier=2.0, deadline_s=0,
                      seed=3)
    st = j.start()
    for i in range(20):
        d = st.next_delay()
        assert 0.0 <= d <= min(0.8, 0.1 * 2 ** i)


def test_seeded_backoff_deterministic():
    mk = lambda: BackoffPolicy(base_s=0.1, max_s=5.0, deadline_s=0,
                               seed=42).start()
    a, b = mk(), mk()
    assert [a.next_delay() for _ in range(10)] == \
        [b.next_delay() for _ in range(10)]


def test_deadline_budget_exhausts():
    now = [0.0]
    clock = lambda: now[0]
    st = BackoffPolicy(base_s=1.0, max_s=1.0, deadline_s=10.0,
                       jitter=False).start(clock)
    assert st.remaining() == 10.0
    now[0] = 9.5
    assert st.next_delay() == 0.5          # clamped: never sleep past it
    now[0] = 10.1
    assert st.next_delay() is None         # budget spent
    assert st.sleep(lambda s: None) is False


def test_max_attempts_bounds():
    st = BackoffPolicy(base_s=0.0, max_s=0.0, deadline_s=0,
                       max_attempts=3).start()
    assert st.next_delay() is not None
    assert st.next_delay() is not None
    assert st.next_delay() is None         # 3rd failed attempt: give up


def test_attempt_timeout_is_min_of_per_attempt_and_remaining():
    now = [0.0]
    st = BackoffPolicy(base_s=0.1, deadline_s=10.0,
                       attempt_timeout_s=3.0).start(lambda: now[0])
    assert st.attempt_timeout() == 3.0
    now[0] = 8.0
    assert st.attempt_timeout() == pytest.approx(2.0)
    unbounded = BackoffPolicy(base_s=0.1, deadline_s=0).start(lambda: 0.0)
    assert unbounded.attempt_timeout() is None


def test_retry_call_retries_then_succeeds():
    calls, slept = [], []
    def fn(timeout):
        calls.append(timeout)
        if len(calls) < 3:
            raise ConnectionError("flaky")
        return "ok"
    out = retry_call(fn, BackoffPolicy(base_s=0.01, max_s=0.01, deadline_s=0),
                     sleep=slept.append)
    assert out == "ok" and len(calls) == 3 and len(slept) == 2


def test_retry_call_non_retryable_raises_once():
    calls = []
    def fn(timeout):
        calls.append(1)
        raise ValueError("handler bug")
    with pytest.raises(ValueError):
        retry_call(fn, BackoffPolicy(base_s=0.01, deadline_s=5))
    assert len(calls) == 1


def test_retry_call_budget_exhausted_reraises_original():
    def fn(timeout):
        raise TimeoutError("still down")
    with pytest.raises(TimeoutError, match="still down"):
        retry_call(fn, BackoffPolicy(base_s=0.0, max_s=0.0, deadline_s=0,
                                     max_attempts=4), sleep=lambda s: None)


def test_classification_defaults():
    p = BackoffPolicy()
    from ray_tpu._private.rpc import RpcRemoteError
    assert p.classify(ConnectionError())
    assert p.classify(ChaosConnectionReset())
    assert p.classify(TimeoutError())
    assert p.classify(OSError())
    assert not p.classify(RpcRemoteError("remote handler raised"))
    assert not p.classify(ValueError())


# -- circuit breaker ----------------------------------------------------------

def test_breaker_full_cycle():
    now = [0.0]
    br = CircuitBreaker(failure_threshold=3, reset_s=5.0, clock=lambda: now[0])
    assert br.state == "closed" and br.allow()
    assert br.record_failure() is False
    assert br.record_failure() is False
    assert br.record_failure() is True     # edge: third consecutive opens it
    assert br.state == "open" and not br.allow() and br.state_code() == 2
    now[0] = 5.1
    assert br.state == "half_open" and br.state_code() == 1
    assert br.allow() is True              # the single probe
    assert br.allow() is False             # everyone else still shed
    br.record_success()
    assert br.state == "closed" and br.allow()


def test_breaker_failed_probe_reopens():
    now = [0.0]
    br = CircuitBreaker(failure_threshold=1, reset_s=2.0, clock=lambda: now[0])
    br.record_failure()
    now[0] = 2.5
    assert br.allow()                      # probe goes out...
    assert br.record_failure() is True     # ...and fails: straight back open
    assert br.state == "open" and not br.allow()
    now[0] = 3.0                           # reset clock restarted at 2.5
    assert br.state == "open"
    now[0] = 4.6
    assert br.state == "half_open"


def test_breaker_success_resets_failure_run():
    br = CircuitBreaker(failure_threshold=3, reset_s=5.0)
    br.record_failure(); br.record_failure()
    br.record_success()                    # run broken: counter resets
    assert br.record_failure() is False and br.state == "closed"


def test_breaker_board_on_open_and_snapshot():
    now = [0.0]
    opened = []
    board = BreakerBoard(failure_threshold=2, reset_s=5.0,
                         clock=lambda: now[0], on_open=opened.append)
    board.record_failure("a:1")
    assert opened == []
    board.record_failure("a:1")
    assert opened == ["a:1"]
    board.record_success("b:2")
    assert board.snapshot() == {"a:1": 2, "b:2": 0}
    assert not board.allow("a:1") and board.allow("b:2")
    board.drop("a:1")
    assert board.snapshot() == {"b:2": 0}


# -- integration: RPC layer ---------------------------------------------------

@pytest.fixture()
def echo_server():
    def handler(ctx):
        ctx.reply(ctx.body)
    srv = RpcServer(handler, auth_token=b"")
    yield srv
    srv.close()


def test_rpc_injected_send_reset_fails_call_with_peer_address(echo_server):
    chaos.configure(3, "rpc.client.send@2=reset")
    client = RpcClient(echo_server.address, auth_token=b"")
    try:
        assert client.call(pb.PING, b"x", timeout=10).body == b"x"
        with pytest.raises(RpcConnectionError) as ei:
            client.call(pb.PING, b"y", timeout=10)
        assert echo_server.address in str(ei.value)
        assert client.closed                    # reset tore the conn down
    finally:
        client.close()
    # the one-shot rule is spent: a fresh client recovers cleanly
    c2 = RpcClient(echo_server.address, auth_token=b"")
    try:
        assert c2.call(pb.PING, b"z", timeout=10).body == b"z"
    finally:
        c2.close()
    trace = chaos.trace_text()
    assert "rpc.client.send" in trace and "reset" in trace


def test_rpc_injected_reply_drop_times_out(echo_server):
    chaos.configure(3, "rpc.server.send@1=drop")
    client = RpcClient(echo_server.address, auth_token=b"")
    try:
        with pytest.raises(TimeoutError):
            client.call(pb.PING, b"x", timeout=0.5)
        # connection survives a dropped reply; next call works
        assert client.call(pb.PING, b"y", timeout=10).body == b"y"
    finally:
        client.close()


def test_rpc_injected_connect_reset_names_peer(echo_server):
    chaos.configure(3, "rpc.client.connect@1=reset")
    with pytest.raises(RpcConnectionError) as ei:
        RpcClient(echo_server.address, auth_token=b"")
    assert echo_server.address in str(ei.value)


def test_rpc_injected_client_recv_drop_times_out_then_recovers(echo_server):
    """A reply frame vanishing inside the client reader (torn read, kernel
    buffer loss) must surface as a per-call timeout, not poison the
    connection for subsequent calls."""
    chaos.configure(5, "rpc.client.recv@1=drop")
    client = RpcClient(echo_server.address, auth_token=b"")
    try:
        with pytest.raises(TimeoutError):
            client.call(pb.PING, b"x", timeout=0.5)
        assert client.call(pb.PING, b"y", timeout=10).body == b"y"
    finally:
        client.close()
    assert "rpc.client.recv" in chaos.trace_text()


def test_rpc_injected_server_recv_drop_times_out_then_recovers(echo_server):
    """A request frame lost server-side ("never arrived") times out the
    one call; the connection and later requests on it stay healthy."""
    chaos.configure(5, "rpc.server.recv@1=drop")
    client = RpcClient(echo_server.address, auth_token=b"")
    try:
        with pytest.raises(TimeoutError):
            client.call(pb.PING, b"x", timeout=0.5)
        assert client.call(pb.PING, b"y", timeout=10).body == b"y"
    finally:
        client.close()
    assert "rpc.server.recv" in chaos.trace_text()


# -- integration: object plane ------------------------------------------------

def test_object_store_injected_get_drop_simulates_local_loss():
    """A chaos drop on the local store read is the eviction-race shape:
    get() raises ObjectLostError once (callers fall back to remote fetch /
    reconstruction) while the entry itself survives for the next reader."""
    store = ObjectStore(capacity_bytes=1 << 20)
    oid = ObjectID.from_random()
    store.put(oid, {"k": 1})
    chaos.configure(5, "object.store.get@1=drop")
    with pytest.raises(ObjectLostError) as ei:
        store.get(oid)
    assert "chaos" in str(ei.value)
    assert store.get(oid) == {"k": 1}   # one-shot spent; object intact
    assert "object.store.get" in chaos.trace_text()


# -- integration: state client ------------------------------------------------

def _state_service_available() -> bool:
    try:
        from ray_tpu._native.build import build_state_service
        build_state_service()
        return True
    except Exception:  # raylint: allow(swallow) any build failure means "skip"
        return False


needs_state_service = pytest.mark.skipif(
    not _state_service_available(),
    reason="state-service binary cannot be built here (protoc/g++ missing)")

def test_state_reconnect_point_fires_when_service_stays_down():
    """The reconnect path's chaos point fires between the failed probe and
    the fresh dial — a plain RpcServer stands in for the state service so
    this runs without the native binary."""
    srv = RpcServer(lambda ctx: ctx.reply(b""), auth_token=b"")
    client = StateClient(srv.address, auth_token=b"")
    try:
        srv.close()                       # service down: fresh dials refused
        # Kill the client's side too so the probe ping fails deterministically
        # (a handler thread can outlive srv.close() and answer it), and drain
        # the accept backlog: while the accept loop is still blocked in
        # accept(), the kernel keeps the listener alive for one more connect.
        client._client.close()
        host, port = srv.address.rsplit(":", 1)
        state = BackoffPolicy(base_s=0.01, max_s=0.1, deadline_s=10.0).start()
        while True:
            try:
                socket.create_connection((host, int(port)), timeout=1.0).close()
            except OSError:
                break
            if not state.sleep():
                pytest.fail("listener never went down after srv.close()")
        chaos.configure(9, "state.reconnect@1=delay(0.001)")
        with pytest.raises((RpcConnectionError, OSError)):
            client._reconnect()           # fresh dial is refused too
        assert "state.reconnect" in chaos.trace_text()
    finally:
        client.close()


@needs_state_service
def test_state_client_retries_through_injected_reset(tmp_path):
    proc, addr = start_state_service(data_dir=str(tmp_path / "s"))
    client = StateClient(addr)
    try:
        client.kv_put(b"k", b"v1")
        # every state.call RPC attempt #2 and #5 dies mid-flight; the
        # unified retry path reconnects and the calls still succeed
        chaos.configure(3, "state.call@2=reset; state.call@5=reset")
        assert client.kv_get(b"k") == b"v1"
        client.kv_put(b"k", b"v2")
        assert client.kv_get(b"k") == b"v2"
        assert "state.call" in chaos.trace_text()
    finally:
        client.close()
        if proc.poll() is None:
            proc.terminate()
            proc.wait(timeout=10)


@needs_state_service
def test_state_client_survives_service_restart(tmp_path):
    proc, addr = start_state_service(data_dir=str(tmp_path / "s"))
    client = StateClient(addr)
    try:
        client.kv_put(b"durable", b"yes")
        port = int(addr.rsplit(":", 1)[1])
        proc.kill()
        proc.wait(timeout=10)
        proc, addr2 = start_state_service(port=port,
                                          data_dir=str(tmp_path / "s"))
        assert addr2 == addr
        # the client's socket is dead; _call must reconnect within its
        # deadline budget and read the journal-recovered value
        assert client.kv_get(b"durable") == b"yes"
    finally:
        client.close()
        if proc.poll() is None:
            proc.terminate()
            proc.wait(timeout=10)


@needs_state_service
def test_state_client_gives_up_with_budget_in_error(tmp_path):
    proc, addr = start_state_service(data_dir=str(tmp_path / "s"))
    client = StateClient(addr)
    try:
        client.kv_put(b"k", b"v")
        proc.kill()
        proc.wait(timeout=10)
        t0 = time.monotonic()
        with pytest.raises(RpcConnectionError) as ei:
            client._call(pb.KV_GET,
                         pb.KvGetRequest(ns=b"", key=b"k"),
                         timeout=5.0, deadline_s=2.0)
        msg = str(ei.value)
        assert "unreachable" in msg and addr in msg
        assert time.monotonic() - t0 < 30
    finally:
        client.close()


# -- integration: cluster under chaos ----------------------------------------

def test_in_process_task_retry_under_injected_execute_faults():
    """Single-process runtime: chaos faults the first two task executions;
    retry_exceptions + the jittered resubmission backoff must converge to
    the right answers with the one-shot rules spent."""
    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=2)
    try:
        chaos.configure(17, "task.execute@1=error(injected worker fault); "
                            "task.execute@3=error(injected worker fault)")

        @ray_tpu.remote(max_retries=5, retry_exceptions=[ChaosError])
        def f(i):
            return i * 10

        assert ray_tpu.get([f.remote(i) for i in range(6)],
                           timeout=60) == [i * 10 for i in range(6)]
        trace = chaos.trace_lines()
        assert len([ln for ln in trace if "task.execute" in ln]) == 2
    finally:
        chaos.clear()
        ray_tpu.shutdown()


@needs_state_service
def test_object_fetch_retries_through_injected_drop():
    """A non-inline task result (> INLINE_RESULT_MAX) stays on the daemon;
    the driver's pull survives a chaos drop ("source didn't have it") by
    re-probing locations on the seal-wait backoff."""
    ray_tpu.shutdown()
    c = ProcessCluster(num_daemons=1, num_cpus=2)
    ray_tpu.init(address=c.address)
    try:
        @ray_tpu.remote
        def big():
            return os.urandom(512 * 1024)   # above the inline cutoff

        ref = big.remote()
        chaos.configure(13, "object.fetch@1=drop")
        data = ray_tpu.get(ref, timeout=120)
        assert len(data) == 512 * 1024
        assert "object.fetch" in chaos.trace_text()
    finally:
        chaos.clear()
        ray_tpu.shutdown()
        c.shutdown()


@needs_state_service
def test_object_push_drop_falls_back_to_pull():
    """An abandoned proactive arg push must be invisible to correctness:
    the executing daemon's pull path is authoritative. Arena off so the
    same-host short-circuit doesn't skip the push entirely."""
    ray_tpu.shutdown()
    prev_arena = _config.get("arena_enabled")
    _config.set("arena_enabled", False)
    c = ProcessCluster(num_daemons=1, num_cpus=2)
    ray_tpu.init(address=c.address)
    try:
        payload = ray_tpu.put(os.urandom(512 * 1024))  # above push threshold
        chaos.configure(13, "object.push@1=drop")

        @ray_tpu.remote
        def size(b):
            return len(b)

        assert ray_tpu.get(size.remote(payload), timeout=120) == 512 * 1024
        assert "object.push" in chaos.trace_text()
    finally:
        chaos.clear()
        ray_tpu.shutdown()
        c.shutdown()
        _config.set("arena_enabled", prev_arena)


@needs_state_service
def test_mid_flight_resubmission_under_injected_rpc_resets():
    """Driver-side chaos resets the task-push connections mid-run; the
    resubmission + reconnect paths must still complete the workload."""
    ray_tpu.shutdown()
    c = ProcessCluster(num_daemons=2, num_cpus=2)
    ray_tpu.init(address=c.address)
    try:
        chaos.configure(11, "rpc.client.send[method=PUSH_TASK*]@3=reset; "
                            "rpc.client.send[method=PUSH_TASK*]@9=reset")

        @ray_tpu.remote
        def f(i):
            return i + 1

        out = ray_tpu.get([f.remote(i) for i in range(12)], timeout=120)
        assert out == list(range(1, 13))
        assert "rpc.client.send" in chaos.trace_text()
    finally:
        chaos.clear()
        ray_tpu.shutdown()
        c.shutdown()


@needs_state_service
def test_node_loss_mid_run_completes_after_resubmission(monkeypatch):
    """Chaos hard-kills one daemon (os._exit from its heartbeat loop, the
    process-death shape of a lost host) while tasks are in flight; the
    driver must resubmit onto the survivor and finish with correct
    results."""
    ray_tpu.shutdown()
    c = ProcessCluster(num_daemons=1, num_cpus=2, heartbeat_timeout_ms=2000,
                       daemon_heartbeat_s=0.25)
    # only the second daemon carries the chaos schedule: it exits at its
    # 8th heartbeat (~2s in), deterministically
    monkeypatch.setenv("RAY_TPU_CHAOS", "3:state.heartbeat@8=exit(41)")
    c.add_daemon()
    monkeypatch.delenv("RAY_TPU_CHAOS")
    doomed = c.daemons[-1]["proc"]
    ray_tpu.init(address=c.address)
    try:
        @ray_tpu.remote(max_retries=5)
        def slow(i):
            time.sleep(0.4)
            return i * i

        refs = [slow.remote(i) for i in range(12)]
        out = ray_tpu.get(refs, timeout=180)
        assert out == [i * i for i in range(12)]
        assert doomed.wait(timeout=60) == 41   # chaos did kill the node
    finally:
        ray_tpu.shutdown()
        c.shutdown()
