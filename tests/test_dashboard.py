"""Dashboard head + node reporter agent tests (reference:
dashboard/head.py, dashboard/agent.py — here one HTTP head over the
state service plus a /proc sampler thread per daemon)."""

import json
import time
import urllib.request

import pytest

import ray_tpu
from ray_tpu.cluster_utils import ProcessCluster
from ray_tpu.dashboard import start_dashboard


@pytest.fixture()
def cluster():
    ray_tpu.shutdown()
    c = ProcessCluster(num_daemons=2, num_cpus=2)
    ray_tpu.init(address=c.address)
    yield c
    ray_tpu.shutdown()
    c.shutdown()


def _get(port, path):
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=10) as r:
        return json.loads(r.read())


def _require_state_service():
    """ProcessCluster needs the C++ state service (protoc + g++)."""
    from ray_tpu._native.build import build_state_service
    try:
        build_state_service()
    except Exception as e:
        pytest.skip(f"state service unavailable: {e}")


def test_dashboard_cluster_and_reporter_stats(cluster):
    head = start_dashboard(cluster.address)
    try:
        # daemons publish reporter blobs every ~2s; wait for both
        deadline = time.monotonic() + 20
        nodes = []
        while time.monotonic() < deadline:
            nodes = _get(head.port, "/api/cluster")["nodes"]
            daemon_nodes = [n for n in nodes
                            if n["alive"] and n["address"]
                            and n["stats"] is not None]
            if len(daemon_nodes) >= 2:
                break
            time.sleep(0.3)
        assert len(daemon_nodes) >= 2, nodes
        s = daemon_nodes[0]["stats"]
        assert s["rss_mb"] > 10           # a real process
        assert "cpu_percent" in s and "resources" in s
        assert any(n.get("stats", {}) and "arena" in (n["stats"] or {})
                   for n in daemon_nodes), "arena stats missing"
    finally:
        head.stop()


def test_dashboard_actor_and_job_tables(cluster):
    @ray_tpu.remote
    class Counter:
        def ping(self):
            return 1

    a = Counter.remote()
    assert ray_tpu.get(a.ping.remote(), timeout=30) == 1
    head = start_dashboard(cluster.address)
    try:
        deadline = time.monotonic() + 15
        actors = []
        while time.monotonic() < deadline:
            actors = _get(head.port, "/api/actors")
            if any(x["class_name"] == "Counter" and x["state"] == "ALIVE"
                   for x in actors):
                break
            time.sleep(0.3)
        assert any(x["class_name"] == "Counter" for x in actors), actors
        jobs = _get(head.port, "/api/jobs")
        assert any(j["state"] == "RUNNING" for j in jobs)
        # UI page is served
        with urllib.request.urlopen(
                f"http://127.0.0.1:{head.port}/", timeout=10) as r:
            page = r.read().decode()
        assert "ray_tpu cluster" in page and "/api/cluster" in page
    finally:
        head.stop()


def test_init_include_dashboard_on_cluster():
    """init(address=..., include_dashboard=True) serves the full dashboard
    head for cluster drivers (not just the local state server)."""
    ray_tpu.shutdown()
    c = ProcessCluster(num_daemons=1, num_cpus=2)
    try:
        w = ray_tpu.init(address=c.address, include_dashboard=True)
        nodes = _get(w.dashboard_port, "/api/cluster")["nodes"]
        assert any(n["alive"] for n in nodes)
        with urllib.request.urlopen(
                f"http://127.0.0.1:{w.dashboard_port}/", timeout=10) as r:
            assert "ray_tpu cluster" in r.read().decode()
    finally:
        ray_tpu.shutdown()
        c.shutdown()


def test_dashboard_node_debug_logs_and_tasks(cluster):
    """Per-node drill-down: the head fetches a daemon's recent log ring
    and local task rows over NODE_DEBUG (log_agent.py role)."""
    @ray_tpu.remote
    def marked_task():
        import logging
        logging.getLogger("ray_tpu").warning("drilldown-marker-line")
        return 1

    assert ray_tpu.get([marked_task.remote() for _ in range(4)],
                       timeout=60) == [1] * 4
    head = start_dashboard(cluster.address)
    try:
        nodes = [n for n in _get(head.port, "/api/cluster")["nodes"]
                 if n["alive"] and n["address"]]
        assert nodes
        found_logs = found_tasks = False
        for n in nodes:
            d = _get(head.port,
                     f"/api/node_debug?node={n['node_id']}&lines=300")
            assert "error" not in d, d
            if any("drilldown-marker-line" in ln for ln in d.get("logs", [])):
                found_logs = True
            if any(t["name"].endswith("marked_task")
                   for t in d.get("tasks", [])):
                found_tasks = True
        assert found_logs, "marker log line not found on any daemon"
        assert found_tasks, "task rows missing from every daemon"
        # dead/unknown node yields a clean error, not a 500
        d = _get(head.port, "/api/node_debug?node=00ff00ff")
        assert "error" in d
    finally:
        head.stop()


def test_federation_partial_failure_returns_missing_hosts():
    """The federation endpoints must DEGRADE when a daemon dies, not
    error: /api/timeline and /api/metrics return the surviving hosts'
    data plus a ``missing_hosts`` entry for the corpse (still marked
    alive in the state service until its heartbeat times out), and the
    Prometheus exposition advertises the gap as a sample."""
    _require_state_service()
    ray_tpu.shutdown()
    c = ProcessCluster(num_daemons=2, num_cpus=2)
    try:
        ray_tpu.init(address=c.address)

        @ray_tpu.remote
        def touch():
            return 1

        assert ray_tpu.get([touch.remote() for _ in range(4)],
                           timeout=60) == [1] * 4
        from ray_tpu.dashboard import start_dashboard
        head = start_dashboard(c.address)
        try:
            # healthy baseline: both daemons answer, nothing missing
            tl = _get(head.port, "/api/timeline")
            assert tl["missing_hosts"] == []
            assert isinstance(tl["traceEvents"], list)
            mx = _get(head.port, "/api/metrics")
            assert mx["missing_hosts"] == []
            assert "head" in mx["snapshots"]
            n_sources = len(mx["snapshots"])

            c.kill_daemon(0)  # SIGKILL: still registered alive for a beat

            tl = _get(head.port, "/api/timeline")   # not a 500
            assert "error" not in tl
            assert len(tl["missing_hosts"]) == 1
            assert tl["missing_hosts"][0]["node_id"]
            assert tl["missing_hosts"][0]["error"]
            mx = _get(head.port, "/api/metrics")
            assert len(mx["missing_hosts"]) == 1
            # survivors still report (head + the remaining daemon)
            assert len(mx["snapshots"]) == n_sources - 1
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{head.port}/metrics",
                    timeout=10) as r:
                text = r.read().decode()
            assert "federation_missing_hosts{" in text
        finally:
            head.stop()
    finally:
        ray_tpu.shutdown()
        c.shutdown()


def test_dashboard_actor_detail(cluster):
    @ray_tpu.remote
    class Detailed:
        def ping(self):
            return 1

    a = Detailed.remote()
    assert ray_tpu.get(a.ping.remote(), timeout=30) == 1
    head = start_dashboard(cluster.address)
    try:
        deadline = time.monotonic() + 15
        actors = []
        while time.monotonic() < deadline:
            actors = [x for x in _get(head.port, "/api/actors")
                      if x["class_name"] == "Detailed"]
            if actors:
                break
            time.sleep(0.3)
        assert actors
        detail = _get(head.port, f"/api/actor?id={actors[0]['actor_id']}")
        assert detail["class_name"] == "Detailed"
        assert "address" in detail and "num_restarts" in detail
        missing = _get(head.port, "/api/actor?id=deadbeef")
        assert "error" in missing
    finally:
        head.stop()
