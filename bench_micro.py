"""Core-runtime microbenchmarks, ray_perf style.

The task/actor/object-plane latency suite the reference tracks in
``python/ray/_private/ray_perf.py:93`` (tasks/sec, actor calls/sec,
put/get latency) — run against BOTH the in-process runtime and a real
two-daemon ``ProcessCluster`` so the wire protocol, scheduler, and object
plane are measured, not just Python dispatch.

Usage:
    python bench_micro.py [--mode inproc|cluster|both] [--out FILE]

Prints one JSON line per metric; --out also writes them as a JSON array
(tracked round-over-round in BENCH_MICRO.json).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

RESULTS = []


def emit(metric: str, value: float, unit: str):
    row = {"metric": metric, "value": round(value, 2), "unit": unit}
    RESULTS.append(row)
    print(json.dumps(row), flush=True)


def bench_tasks(prefix: str, n: int = 2000):
    import ray_tpu

    @ray_tpu.remote(num_cpus=0.01)
    def tiny():
        return 1

    ray_tpu.get([tiny.remote() for _ in range(50)])  # warm the path
    t0 = time.perf_counter()
    ray_tpu.get([tiny.remote() for _ in range(n)])
    el = time.perf_counter() - t0
    emit(f"{prefix}_tasks_per_second", n / el, "tasks/s")


def bench_actor_calls(prefix: str, n: int = 1000):
    import ray_tpu

    @ray_tpu.remote(num_cpus=0.01)
    class Counter:
        def __init__(self):
            self.n = 0

        def inc(self):
            self.n += 1
            return self.n

    c = Counter.remote()
    ray_tpu.get(c.inc.remote())
    # Sequential round-trips (latency-bound).
    t0 = time.perf_counter()
    for _ in range(n // 4):
        ray_tpu.get(c.inc.remote())
    el = time.perf_counter() - t0
    emit(f"{prefix}_actor_roundtrips_per_second", (n // 4) / el, "calls/s")
    # Pipelined (throughput-bound; the reference's async actor bench).
    t0 = time.perf_counter()
    ray_tpu.get([c.inc.remote() for _ in range(n)])
    el = time.perf_counter() - t0
    emit(f"{prefix}_actor_calls_per_second", n / el, "calls/s")
    ray_tpu.kill(c)


def bench_put_get(prefix: str):
    import ray_tpu
    small = np.zeros(128, np.int64)  # ~1KB
    t0 = time.perf_counter()
    n = 1000
    for _ in range(n):
        ray_tpu.get(ray_tpu.put(small))
    el = time.perf_counter() - t0
    emit(f"{prefix}_put_get_1kb_us", el / n * 1e6, "us")

    big = np.zeros((64, 1024, 1024), np.uint8)  # 64 MB
    t0 = time.perf_counter()
    for _ in range(3):
        ray_tpu.get(ray_tpu.put(big))
    el = time.perf_counter() - t0
    emit(f"{prefix}_put_get_64mb_gbps", 3 * big.nbytes / el / 1e9, "GB/s")


def bench_remote_fetch(prefix: str, mb: int = 32):
    """Cross-daemon object pull, both transfer planes: the shared host
    arena (fd-passed memfd pages, zero-copy decode) and chunked TCP
    (the cross-host path / fallback)."""
    import ray_tpu

    @ray_tpu.remote
    def produce():
        return np.zeros((mb, 1024, 1024), np.uint8)

    rt = ray_tpu._private.worker.global_worker().runtime
    ref = produce.remote()
    warm = ray_tpu.get(ref, timeout=120)
    nbytes = warm.nbytes
    del warm

    def measure():
        # re-fetch the SAME sealed object (producer keeps the primary
        # copy): timing covers the transfer plane only, not the task
        rates = []
        for _ in range(3):
            rt.local_node.store.free(ref.id())
            rt._location_hints.pop(ref.id(), None)
            t0 = time.perf_counter()
            out = ray_tpu.get(ref, timeout=120)
            el = time.perf_counter() - t0
            del out
            rates.append(nbytes / el / 1e9)
        return sorted(rates)[1]

    arena = getattr(rt, "host_arena", None)
    if arena is not None:
        emit(f"{prefix}_remote_fetch_shm_gbps", measure(), "GB/s")
        # force the TCP plane: clear BOTH the client handle and the key —
        # a lingering key would still negotiate in_arena and pay an extra
        # miss round-trip the real cross-host path never executes
        saved_key = rt.host_arena_key
        rt.host_arena, rt.host_arena_key = None, ""
        try:
            emit(f"{prefix}_remote_fetch_tcp_gbps", measure(), "GB/s")
        finally:
            rt.host_arena, rt.host_arena_key = arena, saved_key
    else:
        emit(f"{prefix}_remote_fetch_gbps", measure(), "GB/s")


def bench_trace_overhead(prefix: str, n: int = 800):
    """Tracing cost on the hottest runtime path (1KB put/get), A/B'd by
    flipping ``observability.ENABLED`` around identical loops:

    - ``_trace_overhead_enabled_pct``: full-tracing latency (context
      mint + span record per op) vs the disabled fast path;
    - ``_trace_overhead_disabled_pct``: the disabled fast path measured
      AFTER tracing ran and was turned off, vs before it ever ran — any
      residual cost of the instrumentation when off (the module-bool
      guard plus leaked state) shows up here.  The ``--check`` gate
      bounds both from above (``_pct`` metrics are smaller-is-better).
    """
    import statistics

    import ray_tpu
    from ray_tpu import observability
    from ray_tpu._private.config import _config
    small = np.zeros(128, np.int64)

    def put_get_us():
        t0 = time.perf_counter()
        for _ in range(n):
            ray_tpu.get(ray_tpu.put(small))
        return (time.perf_counter() - t0) / n * 1e6

    put_get_us()  # warm
    off_before = statistics.median(put_get_us() for _ in range(3))
    prof_was = bool(_config.get("profiling_enabled"))
    _config.set("profiling_enabled", True)  # spans must actually record
    observability.enable()
    try:
        on = statistics.median(put_get_us() for _ in range(3))
    finally:
        observability.disable()
        _config.set("profiling_enabled", prof_was)
    off_after = statistics.median(put_get_us() for _ in range(3))
    base = min(off_before, off_after)
    emit(f"{prefix}_put_get_traced_us", on, "us")
    emit(f"{prefix}_trace_overhead_enabled_pct",
         100.0 * (on - base) / base, "%")
    emit(f"{prefix}_trace_overhead_disabled_pct",
         100.0 * (off_after - off_before) / off_before, "%")


def bench_recorder_overhead(prefix: str, n: int = 800):
    """Always-on flight recorder cost on the 1KB put/get hot path, A/B'd
    by pausing/resuming the process-wide spool thread around identical
    loops (the recorder cannot be uninstalled — it records the process).
    ``_recorder_overhead_pct`` is a smaller-is-better budget: the spool
    runs off-path at ``flight_recorder_spool_ms`` cadence, so steady
    state must stay within a couple percent of the paused baseline."""
    import statistics

    import ray_tpu
    from ray_tpu.observability import recorder as _flight
    rec = _flight.get_recorder() or _flight.install("driver")
    if rec is None:  # flight_recorder_enabled=0 in the env: nothing to A/B
        emit(f"{prefix}_recorder_overhead_pct", 0.0, "%")
        return
    small = np.zeros(128, np.int64)

    def put_get_us():
        t0 = time.perf_counter()
        for _ in range(n):
            ray_tpu.get(ray_tpu.put(small))
        return (time.perf_counter() - t0) / n * 1e6

    put_get_us()  # warm
    # paired A/B: alternate paused/running back-to-back so slow machine
    # drift cancels inside each pair instead of polluting the delta
    pcts = []
    for _ in range(5):
        rec.pause()
        try:
            off = put_get_us()
        finally:
            rec.resume()
        on = put_get_us()
        pcts.append(100.0 * (on - off) / off)
    emit(f"{prefix}_recorder_overhead_pct", statistics.median(pcts), "%")


def bench_perf_overhead(prefix: str, n: int = 300):
    """Perf-plane cost, two paired A/Bs (recorder-style pairing so slow
    machine drift cancels inside each pair):

    - ``_perf_overhead_pct``: latency histograms recording vs the
      module-bool fast path, on the tiny-task round trip (the task path
      observes execute/e2e/sched inline, so this measures the real
      observe cost, not an uninstrumented loop);
    - ``_sampler_overhead_pct``: the periodic stack sampler at its
      default hz on top of enabled histograms, on the 1KB put/get hot
      path (the sampler is a background thread — its cost is stolen
      cycles, not inline work).

    Also emits the task.execute quantiles the whole inproc run
    accumulated (p50/p99, us) so ``--check`` gates latency
    *distribution* drift against the recorded baseline, not just
    throughput means."""
    import statistics

    import ray_tpu
    from ray_tpu.observability import perf, sampler

    @ray_tpu.remote
    def tiny():
        return None

    def task_us():
        t0 = time.perf_counter()
        for _ in range(n):
            ray_tpu.get(tiny.remote())
        return (time.perf_counter() - t0) / n * 1e6

    small = np.zeros(128, np.int64)

    def put_get_us():
        t0 = time.perf_counter()
        for _ in range(800):
            ray_tpu.get(ray_tpu.put(small))
        return (time.perf_counter() - t0) / 800 * 1e6

    was = perf.ENABLED
    task_us()  # warm
    pcts = []
    for _ in range(5):
        perf.disable()
        off = task_us()
        perf.enable()
        on = task_us()
        pcts.append(100.0 * (on - off) / off)
    if not was:
        perf.disable()
    emit(f"{prefix}_perf_overhead_pct", statistics.median(pcts), "%")

    put_get_us()  # warm
    spcts = []
    for _ in range(5):
        base_run = put_get_us()
        sampler.start()
        try:
            with_sampler = put_get_us()
        finally:
            sampler.stop()
        spcts.append(100.0 * (with_sampler - base_run) / base_run)
    emit(f"{prefix}_sampler_overhead_pct", statistics.median(spcts), "%")

    counts, sum_ms = perf.get("task.execute").merged()
    if sum(counts):
        s = perf.summarize(counts, sum_ms)
        emit(f"{prefix}_task_execute_p50_us", s["p50_ms"] * 1e3, "us")
        emit(f"{prefix}_task_execute_p99_us", s["p99_ms"] * 1e3, "us")


def bench_goodput(prefix: str, n: int = 150):
    """Goodput-ledger cost plus the fleet-goodput SLO row.

    - ``_goodput_overhead_pct``: a synthetic training step — one batch
      pulled through the ledger-wrapped data iterator, a host matmul as
      the "device step", a ``step_mark`` — with the ledger recording vs
      the module-bool fast path, paired A/B so machine drift cancels.
      Smaller-is-better: the acceptance budget is the ledger staying in
      low single digits on a real (sub-millisecond) step.
    - ``_fleet_goodput_pct``: the federation math on a deterministic
      two-node fleet, one node preempted (4.5 node-seconds of
      restart_downtime plus an idle tail).  The inputs are fixed
      ledgers, so the row moves only when ``merge_payloads`` /
      ``goodput_pct`` change — a floor, gated as bigger-is-better by
      ``check_against``'s goodput carve-out."""
    import statistics

    from ray_tpu.data.dataset import _data_wait_iter
    from ray_tpu.observability import goodput

    # 512x512 dgemm ~ 1ms of host work: the scale of a small real step.
    # Undersizing it would bill the ledger's ~µs per step against a
    # denominator no training loop has.
    a = np.random.rand(512, 512)

    def step_us():
        t0 = time.perf_counter()
        it = _data_wait_iter(iter([a] * n))
        for b in it:
            (b @ b).sum()
            goodput.step_mark()
        return (time.perf_counter() - t0) / n * 1e6

    was = goodput.ENABLED
    step_us()  # warm
    pcts = []
    for _ in range(5):
        goodput.disable()
        off = step_us()
        goodput.enable()
        on = step_us()
        pcts.append(100.0 * (on - off) / off)
    if not was:
        goodput.disable()
    goodput.reset()  # synthetic ledgers must not federate
    emit(f"{prefix}_goodput_overhead_pct", statistics.median(pcts), "%")

    healthy = {"jobs": {"train": {
        "wall_s": 60.0, "compile_count": 1, "recompile_count": 0,
        "cats": {"compute": 57.0, "compile": 0.6, "data_wait": 1.2,
                 "collective_wait": 0.6, "ckpt_stall": 0.6,
                 "restart_downtime": 0.0, "idle": 0.0}}}}
    preempted = {"jobs": {"train": {
        "wall_s": 60.0, "compile_count": 2, "recompile_count": 0,
        "cats": {"compute": 54.0, "compile": 0.0, "data_wait": 0.0,
                 "collective_wait": 0.0, "ckpt_stall": 0.0,
                 "restart_downtime": 4.5, "idle": 1.5}}}}
    fleet = goodput.merge_payloads([healthy, preempted])
    emit(f"{prefix}_fleet_goodput_pct", fleet["train"]["goodput_pct"], "%")


def bench_comms(prefix: str):
    """Comms-plane rows:

    - ``_allreduce_f32_gbps``: two-rank CPU-backend allreduce of a 4 MiB
      f32 tensor, algorithm bandwidth read back from the comms ledger
      itself (summed bytes over summed seconds across both ranks) — the
      seed of the ROADMAP ``allreduce_{f32,q8}_gbps`` quantization gate,
      which will compare a q8 row against this f32 floor.
    - ``_comms_overhead_pct``: what the full plane (fingerprint,
      arrival stamps, op ledger) adds to a 4 MiB allreduce, relative
      to the op itself.  Budget row, smaller-is-better.  Measured
      differentially: a direct A/B at 4 MiB has wall-clock noise
      several times the percent-level effect, so the ledger's per-op
      cost is taken where it dominates the signal — a tiny-tensor
      pair, plane on vs off, min-of-N on each side — and billed
      against the measured 4 MiB op time.  The ledger's work is
      size-independent (shape tuple, stamps, counters), so the
      tiny-op delta is an upper bound on what the big op pays (there
      the two ranks' ledger writes partly overlap the peer's
      compute).  The two ranks are a thread pair calling the public
      collective API directly — the same wrapper / rendezvous /
      ledger path the actor route takes, minus actor dispatch, whose
      scheduling noise would drown the signal.  A 4 MiB op (~ms) is
      the scale of a small real collective; undersizing the
      denominator would bill the ledger's ~µs per op against an op
      time no training loop has (the goodput bench makes the same
      call).
    - ``_collective_skew_detect``: the attribution detector on fixed
      inputs — a rank arriving 50 ms late, five times, folded through
      snapshot -> merge -> ``skew_flags`` must name exactly that rank.
      Emits 1.0 only when end-to-end attribution works (a floor: the
      row moves only when the detector breaks)."""
    import threading

    from ray_tpu import collective as col
    from ray_tpu.observability import comms

    big = np.ones(1 << 20, np.float32)        # 4 MiB per rank
    tiny = np.ones(8, np.float32)

    def rounds(n, gname, arr, config=None, out=None):
        errs = []

        def worker(rank):
            try:
                if not col.is_group_initialized(gname):
                    col.init_collective_group(2, rank, backend="cpu",
                                              group_name=gname,
                                              config=config)
                for _ in range(n):
                    res = col.allreduce(arr, gname)
                if out is not None and rank == 0:
                    out.append(res)
            except Exception as e:  # noqa: BLE001 — surfaced below
                errs.append(e)

        ts = [threading.Thread(target=worker, args=(r,)) for r in (0, 1)]
        t0 = time.perf_counter()
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        if errs:
            raise errs[0]
        return (time.perf_counter() - t0) / n * 1e6  # us per op

    was = comms.ENABLED
    comms.enable()
    rounds(4, "bench_comms", big)             # warm: first rendezvous
    comms.reset()
    big_us = rounds(16, "bench_comms", big)
    rec = comms.snapshot()["groups"]["bench_comms"]["ops"]["allreduce"]
    emit(f"{prefix}_allreduce_f32_gbps", rec["algbw_gbps"], "GB/s")

    # Quantized tier (ROADMAP item 3): the same two-rank drill on a q8
    # group.  The gbps row is LOGICAL bytes/sec — compression only pays
    # off if shipping ~0.27x the bytes makes the op *faster* than the
    # f32 floor on the same logical tensor (check_against also gates the
    # q8 row against the f32 baseline cross-metric).  The wire-ratio and
    # round-trip-error rows are the honesty companions: ledger-verified
    # compression and a gated accuracy ceiling, so a quant-kernel
    # regression cannot buy speed with silent error.
    from ray_tpu.collective.types import CollectiveConfig
    qcfg = CollectiveConfig(compression="q8", quant_block_bytes=256)
    qarr = np.random.default_rng(7).standard_normal(1 << 20) \
        .astype(np.float32)
    qout = []
    rounds(4, "bench_comms_q8", qarr, config=qcfg)        # warm
    comms.reset()
    rounds(16, "bench_comms_q8", qarr, config=qcfg, out=qout)
    qrec = comms.snapshot()["groups"]["bench_comms_q8"]["ops"]["allreduce"]
    emit(f"{prefix}_allreduce_q8_gbps", qrec["logical_gbps"], "GB/s")
    emit("allreduce_q8_wire_ratio", qrec["compression_ratio"], "x")
    ref = qarr * 2.0
    emit("quant_allreduce_rel_err",
         float(np.abs(np.asarray(qout[-1]) - ref).mean()
               / np.abs(ref).mean()), "x")

    # Best-of-N on each side: runtime background threads (heartbeats,
    # samplers) only ever inflate a sample, so the min of each side
    # isolates the intrinsic per-op cost where a per-pair ratio would
    # gate on scheduler noise.  Pair order alternates so cache/clock
    # warming inside a pair cannot systematically bill one side.
    off_us, on_us = [], []
    for i in range(10):
        for state in ((False, True) if i % 2 else (True, False)):
            (comms.enable if state else comms.disable)()
            (on_us if state else off_us).append(
                rounds(24, "bench_comms", tiny))
    comms.enable()
    delta_us = max(0.0, min(on_us) - min(off_us))
    emit(f"{prefix}_comms_overhead_pct", 100.0 * delta_us / big_us, "%")

    comms.reset()
    for _ in range(5):
        comms.record_arrivals("bench_skew", {0: 0.0002, 1: 0.050},
                              world_size=2)
    merged = comms.merge_payloads([comms.snapshot()])
    flags = comms.skew_flags(merged["groups"], bounds=merged["bounds"])
    named = [(f["group"], f["rank"]) for f in flags]
    emit(f"{prefix}_collective_skew_detect",
         1.0 if named == [("bench_skew", "1")] else 0.0, "bool")

    if not was:
        comms.disable()
    comms.reset()  # synthetic ledgers must not federate


def bench_transport():
    """Startup bandwidth probe: what the transport auto-tuner measured on
    this host — and therefore which chunk size, stream count and socket
    buffers every bulk-bytes path (fetch/push/checkpoint/drain) runs
    with. Tracked so a probe regression (or a kernel/stack change that
    tanks loopback throughput) is visible round-over-round."""
    from ray_tpu._private import transport
    rep = transport.probe_report()
    emit("transport_probe_gbps", rep.get("probe_gbps", 0.0), "GB/s")


def bench_checkpoint(mb: int = 64):
    """Checkpoint-engine data path, no cluster needed: cold save throughput
    (content-hash + framed chunk writes + atomic commit), warm save of an
    unchanged tree (pure dedup: latency and fraction of bytes NOT
    rewritten), and restore of a 4-way sharded save onto a 2-rank world
    (global reassembly + slice)."""
    import shutil
    import tempfile
    from ray_tpu.checkpoint import CheckpointEngine, load

    rng = np.random.default_rng(0)
    leaves = mb // 2
    tree = {f"layer{i}": rng.standard_normal((256, 1024))  # 2 MiB each
            for i in range(leaves)}
    for a in tree.values():
        # Frozen leaves model immutable device buffers (the training
        # steady state): warm saves may trust the per-leaf hash cache and
        # skip the host copy + sha256 entirely. A writeable array never
        # cache-hits by design.
        a.setflags(write=False)
    nbytes = sum(a.nbytes for a in tree.values())

    root = tempfile.mkdtemp(prefix="ckpt_bench_")
    try:
        eng = CheckpointEngine(root)
        t0 = time.perf_counter()
        eng.save(tree, step=1, wait=True)
        el = time.perf_counter() - t0
        emit("ckpt_cold_save_gbps", nbytes / el / 1e9, "GB/s")

        best = float("inf")
        for step in range(2, 5):
            t0 = time.perf_counter()
            eng.save(tree, step=step, wait=True)
            best = min(best, time.perf_counter() - t0)
        emit("ckpt_warm_save_us", best * 1e6, "us")
        total_saved = 4 * nbytes
        emit("ckpt_warm_dedup_ratio",
             eng.stats.bytes_deduped / (total_saved - nbytes), "frac")
        eng.close()
    finally:
        shutil.rmtree(root, ignore_errors=True)

    # 4-way axis-0 sharded save, restored onto a different world size
    root = tempfile.mkdtemp(prefix="ckpt_bench_shard_")
    try:
        world = 4
        glob = rng.standard_normal((world * 1024, mb * 32))
        engines = [CheckpointEngine(root) for _ in range(world)]
        handles = [
            engines[r].save({"w": glob[r * 1024:(r + 1) * 1024]}, step=1,
                            rank=r, world_size=world, shard_axis=0,
                            shard_paths=("w",))
            for r in range(world)]
        name = handles[0].result(timeout=600)
        for e in engines:
            e.close()
        t0 = time.perf_counter()
        for r in range(2):
            load(root, name, rank=r, world_size=2)
        el = time.perf_counter() - t0
        # each resharded rank reads + reassembles the full global array
        emit("ckpt_restore_reshard_gbps", 2 * glob.nbytes / el / 1e9, "GB/s")
    finally:
        shutil.rmtree(root, ignore_errors=True)


def bench_drain(mb: int = 32):
    """Graceful-drain migration path on a live 3-daemon ProcessCluster:
    drain the node holding an actor and a sole-copy ``mb``-MiB object
    while tasks keep arriving. ``drain_migration_gbps`` times notice ->
    decommission (quiesce + checkpoint + sole-copy PUSH_OBJECT, so it
    lower-bounds the migration plane); ``drain_zero_loss`` is the binary
    gate — 1.0 only when every task completed AND the object survived."""
    import ray_tpu
    from ray_tpu.cluster_utils import ProcessCluster
    ray_tpu.shutdown()
    c = ProcessCluster(num_daemons=3, num_cpus=float(os.cpu_count() or 8))
    ray_tpu.init(address=c.address)
    try:
        rt = ray_tpu._private.worker.global_worker().runtime

        @ray_tpu.remote(max_restarts=1)
        class Holder:
            def where(self):
                import ray_tpu._private.worker as w
                return w.global_worker().runtime.local_node.node_id.hex()

            def blob(self):
                return np.zeros((mb, 1024, 1024), np.uint8)

        h = Holder.remote()
        victim = ray_tpu.get(h.where.remote(), timeout=60)
        ref = h.blob.remote()           # sole copy on the victim node
        ray_tpu.wait([ref], timeout=120)

        @ray_tpu.remote(max_retries=3)
        def tick(i):
            time.sleep(0.05)
            return i

        n = 200
        refs = [tick.remote(i) for i in range(n)]
        t0 = time.perf_counter()
        ray_tpu.drain_node(victim, reason="bench", deadline_s=60.0)
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            info = {x.node_id.hex(): x for x in rt.state.list_nodes()}
            nd = info.get(victim)
            if nd is not None and not nd.alive:
                break
            time.sleep(0.1)
        el = time.perf_counter() - t0
        out = ray_tpu.get(refs, timeout=180)
        arr = ray_tpu.get(ref, timeout=120)
        nbytes = arr.nbytes
        del arr
        emit("drain_migration_gbps", nbytes / el / 1e9, "GB/s")
        emit("drain_zero_loss",
             1.0 if (sorted(out) == list(range(n))
                     and nbytes == mb * 1024 * 1024) else 0.0, "bool")
    finally:
        ray_tpu.shutdown()
        c.shutdown()


def bench_churn_goodput():
    """``goodput_under_churn_pct``: modeled fleet goodput riding out a
    preemption storm at the proactive-drain threshold hazard (6/hour)
    with the risk-tuned checkpoint cadence actually produced by
    ``solve_interval_steps`` for that hazard. The ledger is built from
    the solver's interval — checkpoint stalls at the solved cadence,
    plus per-preemption restart downtime and half-an-interval of lost
    work — then folded through ``merge_payloads``/``goodput_pct``. All
    inputs are fixed, so the row moves only when the cadence solver or
    the federation math changes: a solver regression toward too-dense
    or too-sparse checkpoints drops modeled goodput below the floor
    (gated bigger-is-better by ``check_against``'s goodput carve-out)."""
    from ray_tpu.checkpoint import solve_interval_steps
    from ray_tpu.observability import goodput

    hazard = 6.0          # preempts/hour — the hazard_drain_threshold
    step_s, ckpt_s, restart_s = 1.0, 2.0, 30.0
    interval = solve_interval_steps(hazard, step_s, ckpt_s,
                                    restart_cost_s=restart_s,
                                    min_steps=1, max_steps=10_000)
    wall = 3600.0
    ckpt_stall = wall / (interval * step_s) * ckpt_s
    # Each preemption costs the restart plus on average half a
    # checkpoint interval of recomputed work.
    restart_down = hazard * (restart_s + interval * step_s / 2.0)
    compute = wall - ckpt_stall - restart_down
    ledger = {"jobs": {"train": {
        "wall_s": wall, "compile_count": 1, "recompile_count": 0,
        "cats": {"compute": compute, "compile": 0.0, "data_wait": 0.0,
                 "collective_wait": 0.0, "ckpt_stall": ckpt_stall,
                 "restart_downtime": restart_down, "idle": 0.0}}}}
    fleet = goodput.merge_payloads([ledger])
    emit("goodput_under_churn_pct", fleet["train"]["goodput_pct"], "%")


def bench_autopilot():
    """``autopilot_goodput_gain_pct``: the deterministic A/B drill from
    ``ray_tpu/autopilot/drill.py`` — the same synthetic workload run
    under the same fixed seeded chaos schedule (a starved reader plus a
    skewed collective rank) with the controller OFF and ON, both arms
    folded through the real goodput ledger. The row is the ON−OFF
    goodput delta in percentage points; every input is fixed and the
    clock is virtual, so it moves only when the policy/actuator/guard
    loop changes. Gated bigger-is-better (a floor > 0) by
    ``check_against``'s goodput carve-out: an autopilot that stops
    helping fails the gate."""
    from ray_tpu.autopilot import drill

    ab = drill.run_ab()
    emit("autopilot_goodput_gain_pct", ab["gain_pct"], "pct-points")


def bench_preempt_notice(poll_ms: float = 200.0):
    """``preempt_notice_to_drain_ms``: the live eviction-notice pipeline.
    One fresh daemon whose preemption watcher receives a chaos eviction
    notice on its FIRST poll (``node.preempt@1%1000000=drop``); measured
    from the node first showing alive in ``list_nodes`` to its state
    flipping DRAINING — watcher wakeup, notice, ``begin_drain`` (hazard
    journaling included) and the state-service flip, the whole path the
    real GCE notice takes. Ceiling row (``_ms``): a regression here
    means preempted nodes burn their eviction lead time before
    migration even starts."""
    import ray_tpu
    from ray_tpu._private.state_client import StateClient
    from ray_tpu.cluster_utils import ProcessCluster
    ray_tpu.shutdown()
    c = ProcessCluster(num_daemons=0, num_cpus=1)
    try:
        c.add_daemon(env={
            "RAY_TPU_CHAOS": "5:node.preempt@1%1000000=drop",
            "RAY_TPU_PREEMPT_POLL_MS": str(poll_ms),
            "RAY_TPU_PREEMPT_LEAD_S": "30",
        })
        state = StateClient(c.address)
        try:
            t_alive = None
            ms = 60_000.0   # timeout sentinel: fails the ceiling gate
            deadline = time.monotonic() + 60
            while time.monotonic() < deadline:
                nodes = state.list_nodes()
                if t_alive is None:
                    if any(n.alive for n in nodes):
                        t_alive = time.perf_counter()
                elif any(n.state == "DRAINING" for n in nodes):
                    ms = (time.perf_counter() - t_alive) * 1e3
                    break
                time.sleep(0.01)
            emit("preempt_notice_to_drain_ms", ms, "ms")
        finally:
            state.close()
    finally:
        c.shutdown()


def _serve_drive(handle, rate_hz: float, duration_s: float,
                 pool_size: int = 64):
    """Open-loop arrival process: requests fire at fixed intervals
    regardless of completions (no coordinated omission — latency is
    measured from the INTENDED arrival time, so server-side queueing a
    closed-loop driver would hide shows up in the tail)."""
    import concurrent.futures as cf
    import threading
    n = max(1, int(rate_hz * duration_s))
    interval = 1.0 / rate_hz
    lat_ms, errors = [], [0]
    lock = threading.Lock()

    def fire(i: int, t_arrival: float):
        try:
            handle.remote(float(i % 13)).result(timeout=30)
        except Exception:  # raylint: allow(swallow) shed/overload requests are the counted outcome
            with lock:
                errors[0] += 1
            return
        ms = (time.perf_counter() - t_arrival) * 1e3
        with lock:
            lat_ms.append(ms)

    with cf.ThreadPoolExecutor(pool_size) as ex:
        t0 = time.perf_counter()
        futs = []
        for i in range(n):
            target = t0 + i * interval
            now = time.perf_counter()
            if target > now:
                time.sleep(target - now)
            futs.append(ex.submit(fire, i, target))
        for f in futs:
            f.result()
        elapsed = time.perf_counter() - t0
    qps = len(lat_ms) / elapsed if elapsed > 0 else 0.0
    p99 = (float(np.percentile(lat_ms, 99)) if lat_ms else float("inf"))
    return qps, p99, errors[0]


def bench_serve(duration_s: float = 6.0):
    """Interactive-serving A/B: the same weights-dominated model served
    unbatched (max_batch_size=1) vs through the replica-side continuous
    batcher, both under the SAME open-loop arrival rate (~3x the measured
    unbatched capacity, so the unbatched arm saturates and sheds while
    the batcher amortizes the per-forward cost across its batch).

    The model emulates large-model inference economics on the CI box: a
    fixed per-forward matmul (the "weights" share, identical for any
    batch size) plus a tiny per-item share — exactly the shape where
    continuous batching pays.  Emits ``serve_qps`` / ``serve_p99_ms``
    for the batched arm and ``serve_batch_speedup`` (batched qps /
    unbatched qps); the acceptance bar is speedup >= 2 at
    equal-or-better p99."""
    import ray_tpu
    from ray_tpu import serve
    ray_tpu.shutdown()
    # Serve needs logical slots for the controller actor plus replicas;
    # a 1-CPU box would otherwise never place the first replica.
    ray_tpu.init(num_cpus=max(8.0, float(os.cpu_count() or 8)))
    try:
        serve.start()
        dim = 320

        class Model:
            def __init__(self, batched: bool):
                rng = np.random.default_rng(0)
                self._w = rng.standard_normal((dim, dim)).astype(
                    np.float32) / np.sqrt(dim)
                self._batched = batched

            def __call__(self, request):
                items = request if self._batched else [request]
                # Fixed per-forward share: same cost for any batch size
                # (the "weights" term of large-model inference).
                acc = self._w @ self._w @ self._w
                # Per-item share: one row per request.
                xs = (np.asarray(items, np.float32)[:, None]
                      * np.ones((1, dim), np.float32))
                out = xs @ acc
                results = [float(r.sum()) for r in out]
                return results if self._batched else results[0]

        def deploy(batched: bool):
            dep = serve.deployment(
                Model, name="bench_model",
                max_concurrent_queries=128,
                max_batch_size=(16 if batched else 1),
                batch_wait_timeout_s=0.002,
                pad_batch_to=((1, 2, 4, 8, 16) if batched else None))
            return serve.run(dep.bind(batched), route_prefix=None)

        # Calibrate: serial unbatched latency sets the offered rate.
        h = deploy(batched=False)
        t0 = time.perf_counter()
        n_cal = 30
        for i in range(n_cal):
            h.remote(float(i)).result(timeout=30)
        service_s = (time.perf_counter() - t0) / n_cal
        rate_hz = min(3.0 / service_s, 2000.0)

        un_qps, un_p99, un_errs = _serve_drive(h, rate_hz, duration_s)
        serve.delete("bench_model")

        h = deploy(batched=True)
        for i in range(20):   # warm the batcher / bucket shapes
            h.remote(float(i)).result(timeout=30)
        qps, p99, errs = _serve_drive(h, rate_hz, duration_s)
        serve.delete("bench_model")

        emit("serve_qps", qps, "req/s")
        emit("serve_p99_ms", p99, "ms")
        emit("serve_batch_speedup", qps / un_qps if un_qps > 0 else 0.0,
             "ratio")
        print(f"[bench_serve] offered={rate_hz:.0f}/s unbatched="
              f"{un_qps:.0f}/s p99={un_p99:.0f}ms shed={un_errs} | "
              f"batched={qps:.0f}/s p99={p99:.0f}ms shed={errs}",
              flush=True)
        try:
            import jax
            on_tpu = jax.devices()[0].platform == "tpu"
        except Exception:  # raylint: allow(swallow) jax optional for this bench
            on_tpu = False
        if on_tpu:
            # TPU-scale rows only exist where they can be honest; on the
            # CI box the baseline rows are skipped targets (PR 9 pattern).
            emit("tpu_serve_qps", qps, "req/s")
            emit("tpu_serve_p99_ms", p99, "ms")
    finally:
        try:
            serve.shutdown()
        except Exception as e:  # noqa: BLE001 — bench teardown best-effort
            print(f"[bench_serve] shutdown: {e}", file=sys.stderr)
        ray_tpu.shutdown()


def run_inproc():
    import ray_tpu
    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=float(os.cpu_count() or 8))
    bench_transport()
    bench_tasks("inproc")
    bench_actor_calls("inproc")
    bench_put_get("inproc")
    bench_trace_overhead("inproc")
    bench_recorder_overhead("inproc")
    bench_perf_overhead("inproc")
    bench_goodput("inproc")
    bench_churn_goodput()
    bench_autopilot()
    bench_comms("inproc")
    ray_tpu.shutdown()


def run_cluster():
    import ray_tpu
    from ray_tpu.cluster_utils import ProcessCluster
    ray_tpu.shutdown()
    c = ProcessCluster(num_daemons=2, num_cpus=float(os.cpu_count() or 8))
    ray_tpu.init(address=c.address)
    try:
        bench_tasks("cluster", n=1000)
        bench_actor_calls("cluster", n=500)
        bench_remote_fetch("cluster")
    finally:
        ray_tpu.shutdown()
        c.shutdown()


def check_against(baseline_path: str, tolerance: float) -> int:
    """Regression gate: compare this run's metrics against a tracked
    baseline. Throughput-style metrics (tasks/s, GB/s, calls/s) must stay
    >= baseline * tolerance; latency metrics (``_us``/``_ms``) and
    overhead percentages (``_pct``) are inverted and must stay <=
    baseline / tolerance (for ``_pct`` the baseline is the budget itself
    — e.g. the 1% disabled-tracing bound — not a past measurement).
    Exception: goodput percentage rows (``*goodput_pct``,
    ``goodput_under_churn_pct``, ``autopilot_goodput_gain_pct``) are
    efficiency *floors* — higher is better, like throughput — so they
    gate as >= baseline * tolerance.
    Metrics missing from either side are skipped (a cluster-less
    environment still gates the inproc set, and TPU-scale target rows
    like ``tpu_serve_qps`` stay dormant until a run on real TPU emits
    them). Returns the number of regressions (exit code)."""
    with open(baseline_path) as f:
        baseline = {row["metric"]: row["value"] for row in json.load(f)}
    measured = {row["metric"]: row["value"] for row in RESULTS}
    failures = []
    for metric, base in sorted(baseline.items()):
        got = measured.get(metric)
        if got is None or base <= 0:
            continue
        if metric.endswith(("goodput_pct", "goodput_under_churn_pct",
                            "autopilot_goodput_gain_pct")):
            # goodput is the one percentage where bigger is better: it
            # is a fraction of wall-clock doing useful work, not an
            # overhead budget
            ok = got >= base * tolerance
            bound = f">= {base * tolerance:.2f}"
        elif metric.endswith(("_ratio", "_rel_err")):
            # deterministic budget ceilings (compression ratio, quant
            # round-trip error): the baseline IS the bound, untoleranced
            # — these rows are not timing-noisy, so slack would only
            # let a quant regression buy speed with silent error
            ok = got <= base
            bound = f"<= {base:.4f}"
        elif metric.endswith(("_us", "_ms", "_pct")):
            ok = got <= base / tolerance
            bound = f"<= {base / tolerance:.2f}"
        else:
            ok = got >= base * tolerance
            bound = f">= {base * tolerance:.2f}"
        status = "ok" if ok else "REGRESSION"
        print(f"[check] {metric}: {got:.2f} vs baseline {base:.2f} "
              f"(need {bound}) {status}", flush=True)
        if not ok:
            failures.append(metric)
    # Cross-metric rule: the quantized tier must beat the *f32 floor* on
    # logical bytes/sec, not merely its own past self — a q8 path slower
    # than uncompressed f32 is a pure accuracy loss and must fail the
    # gate even if the q8 baseline row drifted down with it.
    q8 = measured.get("inproc_allreduce_q8_gbps")
    f32_floor = baseline.get("inproc_allreduce_f32_gbps")
    if q8 is not None and f32_floor and f32_floor > 0:
        ok = q8 >= f32_floor * tolerance
        status = "ok" if ok else "REGRESSION"
        print(f"[check] inproc_allreduce_q8_gbps: {q8:.2f} vs f32 floor "
              f"{f32_floor:.2f} (need >= {f32_floor * tolerance:.2f}) "
              f"{status}", flush=True)
        if not ok:
            failures.append("inproc_allreduce_q8_gbps_vs_f32_floor")
    if failures:
        print(f"[check] {len(failures)} regression(s): "
              f"{', '.join(failures)}", flush=True)
    return len(failures)


def main():
    # Honor JAX_PLATFORMS even when a site hook pre-registered a device
    # plugin that overrides the default platform (same pin host_daemon
    # applies): these benches measure the RUNTIME, not the accelerator.
    plat = os.environ.get("JAX_PLATFORMS")
    if plat:
        try:
            import jax
            jax.config.update("jax_platforms", plat)
        except Exception as e:
            print(f"bench_micro: could not pin jax platform to {plat!r}: {e}",
                  file=sys.stderr)
    ap = argparse.ArgumentParser()
    ap.add_argument("--mode", choices=["inproc", "cluster", "both"],
                    default="both")
    ap.add_argument("--out", default=None)
    ap.add_argument("--check", default=None, metavar="BASELINE_JSON",
                    help="compare against a tracked baseline; exit nonzero "
                         "on regression beyond --tolerance")
    ap.add_argument("--tolerance", type=float, default=0.7,
                    help="allowed fraction of a throughput baseline "
                         "(latency baselines are inverted)")
    args = ap.parse_args()
    if args.mode in ("inproc", "both"):
        run_inproc()
        bench_checkpoint()   # filesystem-local; no cluster involved
        bench_serve()        # interactive serving A/B (in-proc cluster)
    if args.mode in ("cluster", "both"):
        run_cluster()
        bench_drain()   # graceful-drain migration + zero-loss gate
        bench_preempt_notice()   # eviction notice -> DRAINING latency
    if args.out:
        with open(args.out, "w") as f:
            json.dump(RESULTS, f, indent=1)
    if args.check:
        raise SystemExit(min(check_against(args.check, args.tolerance), 125))


if __name__ == "__main__":
    main()
