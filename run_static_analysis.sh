#!/usr/bin/env bash
# Static-analysis gate for ray_tpu (ARCHITECTURE.md "Static analysis &
# concurrency invariants"). Three stages, all must pass:
#
#   1. raylint — the framework-aware AST linter (R1..R7) over the Python
#      tree plus bench.py; any non-allowlisted finding fails the gate.
#   2. lockwatch — the tier-1 test suite once under RAY_TPU_LOCKWATCH=1;
#      every process summary line must report zero lock-order cycles.
#   3. gcc -fanalyzer — syntax-only analyzer pass over the four
#      _native/*.cc translation units (protobuf-dependent ones are
#      skipped with a notice when protoc is unavailable to generate
#      raytpu.pb.h).
#
#   ./run_static_analysis.sh              # all three stages
#   SKIP_LOCKWATCH_TESTS=1 ./run_static_analysis.sh   # lint + analyzer only
set -uo pipefail
cd "$(dirname "$0")"

fail=0

echo "== [1/3] raylint =="
if ! python -m ray_tpu.devtools.lint ray_tpu bench.py; then
  fail=1
fi

echo "== [2/3] lockwatch (tier-1 under RAY_TPU_LOCKWATCH=1) =="
if [ "${SKIP_LOCKWATCH_TESTS:-0}" = "1" ]; then
  echo "skipped (SKIP_LOCKWATCH_TESTS=1)"
else
  LW_LOG="$(mktemp /tmp/raytpu_lockwatch.XXXXXX.log)"
  RAY_TPU_LOCKWATCH=1 JAX_PLATFORMS=cpu \
    timeout -k 10 870 python -m pytest tests/ -q -m 'not slow' \
      --continue-on-collection-errors -p no:cacheprovider \
      -p no:xdist -p no:randomly 2>&1 | tee "$LW_LOG" | tail -5
  # Every LOCKWATCH summary line (one per process that created locks)
  # must report zero cycles; the suite's own pass/fail is tier-1's job.
  if grep -a "^LOCKWATCH: " "$LW_LOG" | grep -av ", 0 cycles," | grep -aq .; then
    echo "FAIL: lock-order cycles observed:" >&2
    grep -a "^LOCKWATCH" "$LW_LOG" | grep -av ", 0 cycles," >&2
    fail=1
  elif ! grep -aq "^LOCKWATCH: " "$LW_LOG"; then
    echo "FAIL: no LOCKWATCH summary seen — watchdog did not install" >&2
    fail=1
  else
    echo "lockwatch: zero cycles across $(grep -ac '^LOCKWATCH: ' "$LW_LOG") process summaries"
  fi
fi

echo "== [3/3] gcc -fanalyzer over _native/*.cc =="
GEN_DIR="ray_tpu/_native/gen"
if command -v protoc >/dev/null 2>&1; then
  mkdir -p "$GEN_DIR"
  protoc --proto_path=ray_tpu/protocol --cpp_out="$GEN_DIR" \
    ray_tpu/protocol/raytpu.proto
fi
PY_INC="$(python3-config --includes)"
for src in ray_tpu/_native/cpp_worker.cc ray_tpu/_native/object_store.cc \
           ray_tpu/_native/scheduling.cc ray_tpu/_native/state_service.cc; do
  # the protobuf-linked units need the generated header
  if grep -q 'raytpu\.pb\.h' "$src" && [ ! -f "$GEN_DIR/raytpu.pb.h" ]; then
    echo "skip $src (no protoc to generate raytpu.pb.h)"
    continue
  fi
  echo "-- $src"
  # shellcheck disable=SC2086
  if ! g++ -fanalyzer -fsyntax-only -std=c++17 $PY_INC \
        -I "$GEN_DIR" -I ray_tpu/_native "$src"; then
    fail=1
  fi
done

if [ "$fail" -ne 0 ]; then
  echo "static analysis: FAIL" >&2
  exit 1
fi
echo "static analysis: OK"
