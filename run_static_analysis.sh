#!/usr/bin/env bash
# Static-analysis gate for ray_tpu (ARCHITECTURE.md "Static analysis &
# concurrency invariants"). Four stages, all must pass:
#
#   0. self-check — raylint lints its own engine (ray_tpu/devtools/), the
#      shipped fixture corpus round-trips expected.json exactly, and the
#      machine-readable `--rules` listing is cross-checked against this
#      header and the ARCHITECTURE.md rule table so neither can drift.
#   1. raylint — the framework-aware AST linter (R1..R29, including the
#      whole-program call-graph rules, the path-sensitive dataflow
#      rules, the cross-process stitched-graph rules, the
#      field-level thread-safety rules R23-R25, and the static SPMD
#      sharding rules R27-R29) over
#      ray_tpu/, bench.py, bench_micro.py, and tests/; any
#      non-allowlisted finding fails the gate. tests/ runs under a
#      scoped allow profile (see below). Emits a SARIF 2.1.0 artifact
#      and the R29 collective-cost plan (comms_manifest.json, the
#      input to `ray-tpu doctor --comms-baseline`'s __manifest__ gate)
#      next to the JSON summary, reports the incremental-cache hit rate
#      in the timing summary, and warns when the stage outruns its
#      recorded cold-cache baseline by >50%.
#   2. lockwatch — the tier-1 test suite once under RAY_TPU_LOCKWATCH=1;
#      every process summary line must report zero lock-order cycles.
#      Static R11 findings and these runtime reports share one cycle
#      format, so a cycle seen here should have a matching R11 site list.
#   3. gcc -fanalyzer — syntax-only analyzer pass over the four
#      _native/*.cc translation units (protobuf-dependent ones are
#      skipped with a notice when protoc is unavailable to generate
#      raytpu.pb.h).
#
#   ./run_static_analysis.sh              # all four stages
#   SKIP_LOCKWATCH_TESTS=1 ./run_static_analysis.sh   # skip stage 2
set -uo pipefail
cd "$(dirname "$0")"

fail=0
declare -a STAGE_TIMES=()

stage_done() {  # stage_done <label> <t0> <status>
  local el=$(( SECONDS - $2 ))
  STAGE_TIMES+=("$1: $3 in ${el}s")
  echo "-- $1: $3 (${el}s)"
}

echo "== [stage 0] raylint self-check =="
t0=$SECONDS
st=OK
# (a) the analyzer must be clean under its own rules
if ! python -m ray_tpu.devtools.lint ray_tpu/devtools; then
  st=FAIL; fail=1
fi
# (b) the fixture corpus must round-trip expected.json exactly
if ! python -m ray_tpu.devtools.lint --self-check; then
  st=FAIL; fail=1
fi
# (c) docs drift: the registry is the source of truth for "R1..RN" above
# and for the ARCHITECTURE.md rule table
if ! python - <<'EOF'
import json, re, subprocess, sys
listing = json.loads(subprocess.run(
    [sys.executable, "-m", "ray_tpu.devtools.lint", "--rules"],
    capture_output=True, text=True, check=True).stdout)
ids = [r["id"] for r in listing]
rmax = max(int(i[1:]) for i in ids)
header = open("run_static_analysis.sh", encoding="utf-8").read()
if f"R1..R{rmax}" not in header:
    print(f"drift: run_static_analysis.sh header does not say R1..R{rmax}")
    sys.exit(1)
arch = open("ARCHITECTURE.md", encoding="utf-8").read()
missing = [i for i in ids
           if not re.search(rf"\*\*{i}\b", arch)]
if missing:
    print(f"drift: ARCHITECTURE.md rule table is missing {missing}")
    sys.exit(1)
print(f"docs in sync with registry ({len(ids)} rules, R1..R{rmax})")
EOF
then
  st=FAIL; fail=1
fi
stage_done "stage 0 (self-check)" "$t0" "$st"

echo "== [stage 1] raylint (ray_tpu bench.py bench_micro.py tests) =="
t0=$SECONDS
st=OK
# tests/ allow profile: test code legitimately pokes checkpoint
# directories (R9), simulates rank-divergent schedules on purpose (R12),
# registers throwaway metrics (R22), hammers shared state from
# deliberately-racing helper threads (R23-R25), and pins autopilot-owned
# knobs to build deterministic scenarios (R26); scoped here so
# production code can never ride on it.
LINT_JSON="$(mktemp /tmp/raytpu_lint.XXXXXX.json)"
LINT_ERR="$(mktemp /tmp/raytpu_lint.XXXXXX.err)"
# CI artifact: SARIF 2.1.0 log of every finding (empty `results` on a
# clean tree), for editor/code-scanning ingestion
LINT_SARIF="${RAYLINT_SARIF_OUT:-/tmp/raytpu_lint.sarif.json}"
# CI artifact: the static collective plan R29 derives from the sharding
# model — ships next to the SARIF log and feeds the runtime
# manifest-vs-ledger cross-check (doctor --comms-baseline __manifest__,
# run_sanitizers.sh).
LINT_MANIFEST="${RAYLINT_MANIFEST_OUT:-/tmp/raytpu_comms_manifest.json}"
if python -m ray_tpu.devtools.lint ray_tpu bench.py bench_micro.py tests \
     --allow-in "tests/:R9,R12,R22,R23,R24,R25,R26" --json --sarif "$LINT_SARIF" \
     --comms-manifest "$LINT_MANIFEST" \
     > "$LINT_JSON" 2> "$LINT_ERR"; then
  python - "$LINT_JSON" <<'EOF'
import json, sys
rows = json.load(open(sys.argv[1]))
print(f"raylint: {len(rows)} finding(s) across the widened file set")
EOF
else
  st=FAIL; fail=1
  python - "$LINT_JSON" <<'EOF'
import collections, json, sys
rows = json.load(open(sys.argv[1]))
per = collections.Counter(r["rule"] for r in rows)
summary = ", ".join(f"{k}: {v}" for k, v in sorted(per.items()))
print(f"raylint: {len(rows)} finding(s) ({summary})", file=sys.stderr)
for r in rows:
    print(f"{r['path']}:{r['line']}: {r['rule']}({r['tag']}): "
          f"{r['message']}", file=sys.stderr)
EOF
fi
cat "$LINT_ERR" >&2
CACHE_LINE="$(grep -o 'raylint-cache: .*' "$LINT_ERR" | tail -1)"
# Per-rule wall time for the project rules (plus the shared graph
# build), straight from the engine — the first place to look when the
# stage-1 budget check below trips.
TIMES_LINE="$(grep -o 'raylint-times: .*' "$LINT_ERR" | tail -1)"
rm -f "$LINT_JSON" "$LINT_ERR"
stage_done "stage 1 (raylint)" "$t0" "$st"
STAGE_TIMES+=("stage 1 cache: ${CACHE_LINE#raylint-cache: }")
STAGE_TIMES+=("stage 1 rule times: ${TIMES_LINE#raylint-times: }")
# Budget check against the recorded cold-cache baseline (full R1..R29
# run over the widened file set, incl. the stitch pass, the R23-R25
# field plan, and the R27-R29 sharding model, 2026-08): a >50%
# overshoot means a rule regressed into super-linear work or the cache
# stopped landing.
STAGE1_BASELINE_S="${RAYLINT_STAGE1_BASELINE_S:-45}"
st1_el=$(( SECONDS - t0 ))
if [ "$st1_el" -gt $(( STAGE1_BASELINE_S * 3 / 2 )) ]; then
  echo "WARNING: stage 1 took ${st1_el}s, >50% over its recorded" \
       "baseline of ${STAGE1_BASELINE_S}s — check rule cost or cache" >&2
  STAGE_TIMES+=("stage 1 budget: OVER (${st1_el}s vs ${STAGE1_BASELINE_S}s baseline)")
fi

echo "== [stage 2] lockwatch (tier-1 under RAY_TPU_LOCKWATCH=1) =="
t0=$SECONDS
st=OK
if [ "${SKIP_LOCKWATCH_TESTS:-0}" = "1" ]; then
  st=SKIPPED
  echo "skipped (SKIP_LOCKWATCH_TESTS=1)"
else
  LW_LOG="$(mktemp /tmp/raytpu_lockwatch.XXXXXX.log)"
  RAY_TPU_LOCKWATCH=1 JAX_PLATFORMS=cpu \
    timeout -k 10 870 python -m pytest tests/ -q -m 'not slow' \
      --continue-on-collection-errors -p no:cacheprovider \
      -p no:xdist -p no:randomly 2>&1 | tee "$LW_LOG" | tail -5
  # Every LOCKWATCH summary line (one per process that created locks)
  # must report zero cycles; the suite's own pass/fail is tier-1's job.
  if grep -a "^LOCKWATCH: " "$LW_LOG" | grep -av ", 0 cycles," | grep -aq .; then
    echo "FAIL: lock-order cycles observed:" >&2
    grep -a "^LOCKWATCH" "$LW_LOG" | grep -av ", 0 cycles," >&2
    st=FAIL; fail=1
  elif ! grep -aq "^LOCKWATCH: " "$LW_LOG"; then
    echo "FAIL: no LOCKWATCH summary seen — watchdog did not install" >&2
    st=FAIL; fail=1
  else
    echo "lockwatch: zero cycles across $(grep -ac '^LOCKWATCH: ' "$LW_LOG") process summaries"
  fi
fi
stage_done "stage 2 (lockwatch)" "$t0" "$st"

echo "== [stage 3] gcc -fanalyzer over _native/*.cc =="
t0=$SECONDS
st=OK
GEN_DIR="ray_tpu/_native/gen"
if command -v protoc >/dev/null 2>&1; then
  mkdir -p "$GEN_DIR"
  protoc --proto_path=ray_tpu/protocol --cpp_out="$GEN_DIR" \
    ray_tpu/protocol/raytpu.proto
fi
PY_INC="$(python3-config --includes)"
for src in ray_tpu/_native/cpp_worker.cc ray_tpu/_native/object_store.cc \
           ray_tpu/_native/scheduling.cc ray_tpu/_native/state_service.cc; do
  # the protobuf-linked units need the generated header
  if grep -q 'raytpu\.pb\.h' "$src" && [ ! -f "$GEN_DIR/raytpu.pb.h" ]; then
    echo "skip $src (no protoc to generate raytpu.pb.h)"
    continue
  fi
  echo "-- $src"
  # shellcheck disable=SC2086
  if ! g++ -fanalyzer -fsyntax-only -std=c++17 $PY_INC \
        -I "$GEN_DIR" -I ray_tpu/_native "$src"; then
    st=FAIL; fail=1
  fi
done
stage_done "stage 3 (gcc -fanalyzer)" "$t0" "$st"

echo "== stage timings =="
for line in "${STAGE_TIMES[@]}"; do
  echo "  $line"
done

if [ "$fail" -ne 0 ]; then
  echo "static analysis: FAIL" >&2
  exit 1
fi
echo "static analysis: OK"
