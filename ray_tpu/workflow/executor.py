"""Workflow executor: durable DAG evaluation.

Parity with ``python/ray/workflow/workflow_executor.py:32`` +
``workflow_state_from_dag.py``: the DAG is flattened into tasks with
deterministic IDs (structural position + function name), each task runs as
a cluster task, its result is persisted before dependents are scheduled,
and resume replays persisted results instead of recomputing
(``workflow_state_from_storage.py`` semantics).
"""

from __future__ import annotations

import logging
from typing import Any, Dict, Optional

from ray_tpu import dag as dag_mod
from ray_tpu.workflow.storage import WorkflowStorage

logger = logging.getLogger("ray_tpu.workflow")


class WorkflowExecutionError(Exception):
    def __init__(self, workflow_id: str, cause: BaseException):
        super().__init__(f"Workflow {workflow_id!r} failed: {cause!r}")
        self.cause = cause


def _node_children(node: dag_mod.DAGNode):
    for a in list(node._bound_args) + list(node._bound_kwargs.values()):
        if isinstance(a, dag_mod.DAGNode):
            yield a


def topo_order(root: dag_mod.DAGNode) -> list:
    """Dependencies-before-dependents node list, iteratively (deep chains
    must not hit the recursion limit). The single source of truth for DAG
    traversal order — task-id assignment and execution both use it, so
    resume matching can never desynchronize from run order."""
    order = []
    stack = [(root, False)]
    seen = set()
    while stack:
        node, processed = stack.pop()
        if processed:
            order.append(node)
            continue
        if id(node) in seen:
            continue
        seen.add(id(node))
        stack.append((node, True))
        # Reversed so the first child is visited (and ordered) first,
        # matching depth-first order.
        for child in reversed(list(_node_children(node))):
            stack.append((child, False))
    return order


def assign_task_ids(root: dag_mod.DAGNode) -> Dict[int, str]:
    """Deterministic structural task IDs: depth-first position + name.

    The same DAG built twice gets the same IDs, which is what makes
    resume able to match persisted results to nodes.
    """
    def name_of(node) -> str:
        if isinstance(node, dag_mod.FunctionNode):
            fn = getattr(node._remote_fn, "_function", None)
            return getattr(fn, "__name__", "task")
        return type(node).__name__.lower()

    return {id(node): f"{i:04d}_{name_of(node)}"
            for i, node in enumerate(topo_order(root))}


class WorkflowExecutor:
    def __init__(self, workflow_id: str, storage: WorkflowStorage):
        self.workflow_id = workflow_id
        self.storage = storage

    def execute(self, root: dag_mod.DAGNode) -> Any:
        """Run the DAG to completion, persisting each task result.

        Iterative (deep chains must not hit the recursion limit) and
        submission-eager: every task whose dependencies are submitted is
        itself submitted with the upstream ``ObjectRef``s as arguments, so
        independent branches run concurrently on the cluster; results are
        then gathered and persisted in topological order. Crash-safety is
        unchanged — an unpersisted task is simply re-run on resume.

        The workflow's ROOT step may return ``workflow.continuation(dag)``:
        the sub-DAG runs in its place (the reference's dynamic-workflow
        core, supporting recursive tail chains of unbounded length). The
        chain is driven by a LOOP — one stack frame and one id segment
        total, regardless of length — and every link's result (including
        the continuation markers themselves) is persisted, so a resume
        replays completed links and re-runs only the unfinished tail.
        Non-root steps may not return continuations in this engine: their
        dependents are submitted eagerly and would consume the marker.
        """
        from ray_tpu.workflow.api import Continuation
        self.storage.save_status("RUNNING")
        try:
            result, top_id = self._run_level(root, prefix="")
            depth = 0
            while isinstance(result, Continuation):
                result, _ = self._run_level(result.dag,
                                            prefix=f"{top_id}/c{depth}/")
                depth += 1
            if depth:
                # expose the chain's FINAL value under the root id so
                # get_output/resume read a value, not a marker
                self.storage.save_task_result(top_id, result)
        except Exception as e:
            self.storage.save_status("FAILED", error=repr(e))
            raise WorkflowExecutionError(self.workflow_id, e) from e
        except BaseException as e:
            # KeyboardInterrupt/SystemExit: persist FAILED (resumable) but
            # let the interrupt propagate unwrapped.
            self.storage.save_status("FAILED", error=repr(e))
            raise
        self.storage.save_status("SUCCESS", root_task_id=top_id)
        return result

    def _run_level(self, root: dag_mod.DAGNode, prefix: str):
        """One DAG level; returns (value, root_task_id). The root's value
        may be a ``Continuation`` marker (persisted as such — a replayed
        marker resumes the chain exactly where it left off); the caller's
        loop drives the chain."""
        import ray_tpu
        from ray_tpu.workflow.api import Continuation
        order = topo_order(root)
        ids = {k: prefix + t for k, t in assign_task_ids(root).items()}

        refs: Dict[int, Any] = {}      # submitted this run
        memo: Dict[int, Any] = {}      # replayed from storage

        def resolve(v):
            if isinstance(v, dag_mod.DAGNode):
                k = id(v)
                return memo[k] if k in memo else refs[k]
            return v

        for node in order:
            key = id(node)
            task_id = ids[key]
            if self.storage.has_task_result(task_id):
                logger.info("workflow %s: task %s replayed from storage",
                            self.workflow_id, task_id)
                memo[key] = self.storage.load_task_result(task_id)
                continue
            if not isinstance(node, dag_mod.FunctionNode):
                # InputNode included: workflows take no runtime input,
                # so an InputNode in the DAG is a user error.
                raise TypeError(
                    f"Workflows support function nodes, got "
                    f"{type(node)}; wrap stateful steps in tasks")
            args = tuple(resolve(a) for a in node._bound_args)
            kwargs = {k: resolve(v)
                      for k, v in node._bound_kwargs.items()}
            refs[key] = node._remote_fn.remote(*args, **kwargs)
        for node in order:
            key = id(node)
            if key in refs:
                value = ray_tpu.get(refs[key])
                if isinstance(value, Continuation) and node is not root:
                    raise TypeError(
                        f"step {ids[key]} returned a continuation but is "
                        f"not the (sub-)workflow root; this engine "
                        f"supports continuations only as the final step "
                        f"of a DAG (tail recursion)")
                self.storage.save_task_result(ids[key], value)
                memo[key] = value
        return memo[id(root)], ids[id(root)]
