"""Workflow executor: durable DAG evaluation.

Parity with ``python/ray/workflow/workflow_executor.py:32`` +
``workflow_state_from_dag.py``: the DAG is flattened into tasks with
deterministic IDs (structural position + function name), each task runs as
a cluster task, its result is persisted before dependents are scheduled,
and resume replays persisted results instead of recomputing
(``workflow_state_from_storage.py`` semantics).
"""

from __future__ import annotations

import logging
from typing import Any, Dict, Optional

from ray_tpu import dag as dag_mod
from ray_tpu.workflow.storage import WorkflowStorage

logger = logging.getLogger("ray_tpu.workflow")


class WorkflowExecutionError(Exception):
    def __init__(self, workflow_id: str, cause: BaseException):
        super().__init__(f"Workflow {workflow_id!r} failed: {cause!r}")
        self.cause = cause


def _node_children(node: dag_mod.DAGNode):
    for a in list(node._bound_args) + list(node._bound_kwargs.values()):
        if isinstance(a, dag_mod.DAGNode):
            yield a


def assign_task_ids(root: dag_mod.DAGNode) -> Dict[int, str]:
    """Deterministic structural task IDs: depth-first position + name.

    The same DAG built twice gets the same IDs, which is what makes
    resume able to match persisted results to nodes.
    """
    ids: Dict[int, str] = {}
    counter = [0]

    def name_of(node) -> str:
        if isinstance(node, dag_mod.FunctionNode):
            fn = getattr(node._remote_fn, "_function", None)
            return getattr(fn, "__name__", "task")
        return type(node).__name__.lower()

    def visit(node):
        if id(node) in ids:
            return
        for child in _node_children(node):
            visit(child)
        ids[id(node)] = f"{counter[0]:04d}_{name_of(node)}"
        counter[0] += 1

    visit(root)
    return ids


class WorkflowExecutor:
    def __init__(self, workflow_id: str, storage: WorkflowStorage):
        self.workflow_id = workflow_id
        self.storage = storage

    def execute(self, root: dag_mod.DAGNode) -> Any:
        """Run the DAG to completion, persisting each task result."""
        import ray_tpu
        ids = assign_task_ids(root)
        self.storage.save_status("RUNNING")
        memo: Dict[int, Any] = {}

        def evaluate(node: dag_mod.DAGNode) -> Any:
            key = id(node)
            if key in memo:
                return memo[key]
            task_id = ids[key]
            if self.storage.has_task_result(task_id):
                logger.info("workflow %s: task %s replayed from storage",
                            self.workflow_id, task_id)
                memo[key] = self.storage.load_task_result(task_id)
                return memo[key]

            def resolve(v):
                if isinstance(v, dag_mod.DAGNode):
                    return evaluate(v)
                return v

            args = tuple(resolve(a) for a in node._bound_args)
            kwargs = {k: resolve(v) for k, v in node._bound_kwargs.items()}
            if isinstance(node, dag_mod.FunctionNode):
                ref = node._remote_fn.remote(*args, **kwargs)
                result = ray_tpu.get(ref)
            else:
                # InputNode included: workflows take no runtime input, so
                # an InputNode in the DAG is a user error, not a None.
                raise TypeError(
                    f"Workflows support function nodes, got {type(node)}; "
                    f"wrap stateful steps in tasks")
            self.storage.save_task_result(task_id, result)
            memo[key] = result
            return result

        try:
            result = evaluate(root)
        except Exception as e:
            self.storage.save_status("FAILED", error=repr(e))
            raise WorkflowExecutionError(self.workflow_id, e) from e
        except BaseException as e:
            # KeyboardInterrupt/SystemExit: persist FAILED (resumable) but
            # let the interrupt propagate unwrapped.
            self.storage.save_status("FAILED", error=repr(e))
            raise
        self.storage.save_status("SUCCESS", root_task_id=ids[id(root)])
        return result
