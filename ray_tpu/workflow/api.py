"""Public workflow API.

Parity with ``python/ray/workflow/api.py``: ``workflow.run(dag,
workflow_id=...)`` executes a DAG durably; ``workflow.resume`` restarts a
crashed/failed run from its last persisted task; listing/status/output
accessors; ``wait_for_event`` integrates external events as durable tasks
(reference ``event_listener.py``).
"""

from __future__ import annotations

import threading
import time
import uuid
from typing import Any, Dict, List, Optional

import ray_tpu
from ray_tpu.workflow.executor import (WorkflowExecutionError,
                                       WorkflowExecutor)
from ray_tpu.workflow.storage import WorkflowStorage

_base_dir: Optional[str] = None
_async_runs: Dict[str, threading.Thread] = {}


def init(storage_base_dir: Optional[str] = None) -> None:
    """Configure workflow storage (default: ~/.ray_tpu/workflows)."""
    global _base_dir
    _base_dir = storage_base_dir  # raylint: allow(data-race) configured once at workflow init before any run launches
    if not ray_tpu.is_initialized():
        ray_tpu.init()


def run(dag, *, workflow_id: Optional[str] = None) -> Any:
    """Execute a DAG durably; returns its result."""
    workflow_id = workflow_id or f"workflow-{uuid.uuid4().hex[:8]}"
    if not ray_tpu.is_initialized():
        ray_tpu.init()
    storage = WorkflowStorage(workflow_id, _base_dir)
    status = storage.load_status()["status"]
    if status == "SUCCESS":
        # Idempotent re-run: return the stored output.
        return get_output(workflow_id)
    storage.save_dag(dag)
    return WorkflowExecutor(workflow_id, storage).execute(dag)


def run_async(dag, *, workflow_id: Optional[str] = None) -> str:
    """Start a durable run in the background; returns the workflow id."""
    workflow_id = workflow_id or f"workflow-{uuid.uuid4().hex[:8]}"

    def target():
        # Storage is the authoritative result (status + per-task values);
        # nothing is cached in process globals, so finished runs leave no
        # unbounded state behind. The run itself persists SUCCESS/FAILED.
        try:
            run(dag, workflow_id=workflow_id)
        except BaseException:  # raylint: allow(swallow) executor already persisted FAILED in storage
            pass  # recorded in storage as FAILED by the executor
        finally:
            _async_runs.pop(workflow_id, None)  # raylint: allow(data-race) GIL-atomic dict op on the run registry

    t = threading.Thread(target=target, daemon=True,
                         name=f"workflow-{workflow_id}")
    _async_runs[workflow_id] = t  # raylint: allow(data-race) GIL-atomic dict op on the run registry
    t.start()
    return workflow_id


def resume(workflow_id: str) -> Any:
    """Resume a workflow from persisted state: completed tasks replay from
    storage, the rest re-execute."""
    storage = WorkflowStorage(workflow_id, _base_dir)
    if not storage.exists():
        raise ValueError(f"No workflow with id {workflow_id!r}")
    if not ray_tpu.is_initialized():
        ray_tpu.init()
    dag = storage.load_dag()
    return WorkflowExecutor(workflow_id, storage).execute(dag)


def get_status(workflow_id: str) -> str:
    return WorkflowStorage(workflow_id, _base_dir).load_status()["status"]


def get_output(workflow_id: str, *, wait: bool = False,
               timeout: Optional[float] = None) -> Any:
    """Return the root task's stored result (optionally waiting for an
    async run to finish)."""
    storage = WorkflowStorage(workflow_id, _base_dir)
    if wait:
        # Join before the existence check: an async run may not have
        # created its storage directory yet. Storage stays authoritative
        # afterwards (a deleted workflow must raise, not return a stale
        # in-memory value).
        t = _async_runs.get(workflow_id)
        if t is not None:
            t.join(timeout)
            if t.is_alive():
                raise TimeoutError(
                    f"Workflow {workflow_id!r} still running after "
                    f"{timeout}s")
    if not storage.exists():
        raise ValueError(f"No workflow with id {workflow_id!r}")
    info = storage.load_status()
    status = info["status"]
    if status == "FAILED":
        raise WorkflowExecutionError(
            workflow_id, RuntimeError(info["error"]))
    if status != "SUCCESS":
        raise RuntimeError(
            f"Workflow {workflow_id!r} has status {status}; output not "
            f"available")
    root_id = info.get("root_task_id")
    if root_id is None:
        # Legacy runs without a recorded root: highest structural index
        # (numeric prefix, not lexicographic).
        task_ids = storage.list_task_results()
        if not task_ids:
            return None
        root_id = max(task_ids, key=lambda t: int(t.split("_", 1)[0]))
    return storage.load_task_result(root_id)


def list_all() -> List[Dict[str, str]]:
    out = []
    for wid in WorkflowStorage.list_workflows(_base_dir):
        out.append({"workflow_id": wid,
                    "status": get_status(wid)})
    return out


def delete(workflow_id: str) -> None:
    WorkflowStorage(workflow_id, _base_dir).delete()


class Continuation:
    """Marker a step returns to hand execution to a sub-DAG (reference
    ``workflow.continuation``): the sub-DAG's result replaces the step's
    result, enabling recursive/dynamic workflows."""

    def __init__(self, dag):
        self.dag = dag


def continuation(dag) -> Continuation:
    """Wrap a ``.bind()`` DAG so returning it from a workflow step
    CONTINUES the workflow with that DAG instead of finishing with the
    node object itself."""
    return Continuation(dag)


def wait_for_event(poll_fn, *, poll_interval_s: float = 0.5,
                   timeout_s: Optional[float] = None):
    """Durable event task (reference ``event_listener.py``): returns a DAG
    node that polls ``poll_fn`` until it returns a non-None payload; the
    payload is checkpointed like any task result, so resumed workflows do
    not wait for the event again."""

    @ray_tpu.remote
    def _event_task():
        deadline = (None if timeout_s is None
                    else time.monotonic() + timeout_s)
        while True:
            payload = poll_fn()
            if payload is not None:
                return payload
            if deadline is not None and time.monotonic() >= deadline:
                raise TimeoutError("event did not arrive in time")
            time.sleep(poll_interval_s)

    return _event_task.bind()
