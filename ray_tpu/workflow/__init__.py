"""ray_tpu.workflow — durable DAG execution (reference: python/ray/workflow/)."""

from ray_tpu.workflow.api import (Continuation, continuation,  # noqa: F401
                                  delete, get_output, get_status,
                                  init, list_all, resume, run, run_async,
                                  wait_for_event)
from ray_tpu.workflow.executor import WorkflowExecutionError  # noqa: F401
from ray_tpu.workflow.storage import WorkflowStorage  # noqa: F401

__all__ = ["init", "run", "run_async", "resume", "get_status", "get_output",
           "list_all", "delete", "wait_for_event", "continuation", "Continuation", "WorkflowStorage",
           "WorkflowExecutionError"]
