"""Durable workflow storage.

Parity with ``python/ray/workflow/workflow_storage.py``: every task result
is persisted before the workflow advances, so a crashed run resumes from
the last completed task instead of recomputing.  Layout (filesystem; the
base directory can live on NFS/GCS-fuse for multi-host durability)::

    <base>/<workflow_id>/
        dag.pkl            # cloudpickled DAG for resume
        status.json        # RUNNING | SUCCESS | FAILED | CANCELED
        tasks/<task_id>.pkl    # one durable result per task

Writes are atomic (tmp file + rename) so a crash mid-write never leaves a
corrupt result that resume would trust.
"""

from __future__ import annotations

import json
import os
import cloudpickle as pickle
import tempfile
import time
from typing import Any, Dict, List, Optional

_DEFAULT_BASE = os.path.expanduser("~/.ray_tpu/workflows")


def _atomic_write(path: str, data: bytes) -> None:
    directory = os.path.dirname(path)
    os.makedirs(directory, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=directory)
    try:
        with os.fdopen(fd, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())  # data durable before the rename
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


class WorkflowStorage:
    def __init__(self, workflow_id: str, base_dir: Optional[str] = None):
        self.workflow_id = workflow_id
        self.base = os.path.join(base_dir or _DEFAULT_BASE, workflow_id)
        self.tasks_dir = os.path.join(self.base, "tasks")

    # -- dag ---------------------------------------------------------------

    def save_dag(self, dag) -> None:
        import cloudpickle
        _atomic_write(os.path.join(self.base, "dag.pkl"),
                      cloudpickle.dumps(dag))

    def load_dag(self):
        with open(os.path.join(self.base, "dag.pkl"), "rb") as f:
            return pickle.load(f)

    # -- task results ------------------------------------------------------

    def _task_path(self, task_id: str) -> str:
        # continuation task ids are namespaced with "/" — they become
        # nested directories under tasks_dir
        return os.path.join(self.tasks_dir, f"{task_id}.pkl")

    def save_task_result(self, task_id: str, result: Any) -> None:
        path = self._task_path(task_id)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        _atomic_write(path, pickle.dumps(result))

    def has_task_result(self, task_id: str) -> bool:
        return os.path.exists(self._task_path(task_id))

    def load_task_result(self, task_id: str) -> Any:
        with open(self._task_path(task_id), "rb") as f:
            return pickle.load(f)

    def list_task_results(self) -> List[str]:
        if not os.path.isdir(self.tasks_dir):
            return []
        return [f[:-4] for f in os.listdir(self.tasks_dir)
                if f.endswith(".pkl")]

    # -- status ------------------------------------------------------------

    def save_status(self, status: str, error: Optional[str] = None,
                    root_task_id: Optional[str] = None) -> None:
        _atomic_write(
            os.path.join(self.base, "status.json"),
            json.dumps({"status": status, "error": error,
                        "root_task_id": root_task_id,
                        "updated_at": time.time()}).encode())

    def load_status(self) -> Dict[str, Any]:
        try:
            with open(os.path.join(self.base, "status.json")) as f:
                return json.load(f)
        except FileNotFoundError:
            return {"status": "NOT_FOUND", "error": None}

    def exists(self) -> bool:
        return os.path.isdir(self.base)

    @staticmethod
    def list_workflows(base_dir: Optional[str] = None) -> List[str]:
        base = base_dir or _DEFAULT_BASE
        if not os.path.isdir(base):
            return []
        return sorted(
            d for d in os.listdir(base)
            if os.path.isdir(os.path.join(base, d)))

    def delete(self) -> None:
        import shutil
        shutil.rmtree(self.base, ignore_errors=True)
