"""Runtime context for the current driver/task/actor.

Parity with ``python/ray/runtime_context.py``. TPU-native addition:
``get_tpu_devices()`` returns the concrete ``jax.Device`` objects granted to
this task/actor — the analogue of the reference's CUDA_VISIBLE_DEVICES
assignment (``_raylet.pyx:563``), but as live device handles usable in
``jax.device_put`` / ``jax.jit(..., device=...)``.
"""

from __future__ import annotations

from typing import List, Optional

from ray_tpu._private.runtime import task_context


class RuntimeContext:
    @property
    def job_id(self):
        from ray_tpu._private import worker as _worker
        return task_context.job_id or _worker.global_worker().runtime.job_id

    @property
    def node_id(self):
        from ray_tpu._private import worker as _worker
        nid = task_context.node_id
        if nid is None:
            rt = _worker.global_worker().runtime
            nid = rt.head_node.node_id
        return nid

    @property
    def task_id(self):
        return task_context.task_id

    @property
    def actor_id(self):
        return task_context.actor_id

    @property
    def was_current_actor_reconstructed(self) -> bool:
        from ray_tpu._private import worker as _worker
        aid = task_context.actor_id
        if aid is None:
            return False
        state = _worker.global_worker().runtime.actors.get(aid)
        return state is not None and state.restart_count > 0

    def get_tpu_devices(self) -> List:
        """jax devices granted to the current task/actor (empty for CPU tasks)."""
        return list(task_context.devices or [])

    def get_placement_group(self):
        return task_context.placement_group

    def get_assigned_resources(self):
        return {}


_context = RuntimeContext()


def get_runtime_context() -> RuntimeContext:
    return _context
