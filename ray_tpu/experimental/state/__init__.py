from ray_tpu.experimental.state.api import (list_actors, list_nodes,
                                            list_objects,
                                            list_placement_groups,
                                            list_tasks, summarize_actors,
                                            summarize_tasks)

__all__ = [
    "list_tasks", "list_actors", "list_objects", "list_nodes",
    "list_placement_groups", "summarize_tasks", "summarize_actors",
]
