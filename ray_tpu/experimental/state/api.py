"""Cluster state introspection.

Parity with ``python/ray/experimental/state/api.py`` (+ the server-side
``dashboard/state_aggregator.py``): list/summarize tasks, actors,
objects, nodes, and placement groups. The host-granular runtime holds
these tables in-process, so the aggregator hop disappears — readers
snapshot the Runtime's tables directly.
"""

from __future__ import annotations

from collections import Counter as _Counter
from typing import Any, Dict, List, Optional


def _runtime():
    from ray_tpu._private import worker as _worker
    rt = _worker.try_global_runtime()
    if rt is None:
        raise RuntimeError("ray_tpu is not initialized")
    return rt


def _filtered(rows: List[dict], filters, limit: int) -> List[dict]:
    if filters:
        for key, op, value in filters:
            if op == "=":
                rows = [r for r in rows if str(r.get(key)) == str(value)]
            elif op == "!=":
                rows = [r for r in rows if str(r.get(key)) != str(value)]
            else:
                raise ValueError(f"unsupported filter op {op!r}")
    return rows[:limit]


def list_tasks(filters=None, limit: int = 10_000) -> List[dict]:
    rt = _runtime()
    with rt.lock:  # one block: a torn snapshot renders names as "?"
        states = dict(rt.task_states)
        name_by_task = {spec.task_id.hex(): spec.function_name
                        for spec in rt.lineage.values()}
    rows = [{"task_id": task_id.hex(), "state": state,
             "name": name_by_task.get(task_id.hex(), "?")}
            for task_id, state in states.items()]
    return _filtered(rows, filters, limit)


def list_actors(filters=None, limit: int = 10_000) -> List[dict]:
    rt = _runtime()
    with rt.lock:
        actors = list(rt.actors.values())
    rows = [{
        "actor_id": a.actor_id.hex(),
        "class_name": a.cls.__name__,
        "state": a.status,
        "name": a.name or "",
        "node_id": a.node_id.hex() if a.node_id else None,
        "restarts": a.restart_count,
    } for a in actors]
    return _filtered(rows, filters, limit)


def list_objects(filters=None, limit: int = 10_000) -> List[dict]:
    rt = _runtime()
    with rt.lock:
        locations = dict(rt.object_locations)
    rows = []
    for oid, nid in locations.items():
        node = rt.nodes.get(nid)
        entry = {
            "object_id": oid.hex(),
            "node_id": nid.hex(),
            "ref_count": rt.reference_counter.count(oid),
        }
        if node is not None:
            entry["in_store"] = node.store.contains(oid)
        rows.append(entry)
    return _filtered(rows, filters, limit)


def list_nodes(filters=None, limit: int = 10_000) -> List[dict]:
    rt = _runtime()
    rows = [{
        "node_id": ns.node_id.hex(),
        "state": "ALIVE" if ns.alive else "DEAD",
        "resources_total": ns.resources.total.to_dict(),
        "resources_available": ns.resources.available.to_dict(),
    } for ns in rt.node_states()]
    return _filtered(rows, filters, limit)


def list_placement_groups(filters=None, limit: int = 10_000) -> List[dict]:
    rt = _runtime()
    with rt.lock:
        pgs = list(rt.placement_groups.values())
    rows = [{
        "placement_group_id": pg.pg_id.hex(),
        "state": pg.state,
        "strategy": pg.strategy,
        "bundles": [b.to_dict() for b in pg.bundles],
    } for pg in pgs]
    return _filtered(rows, filters, limit)


def summarize_tasks() -> Dict[str, Any]:
    rows = list_tasks()
    by_state = _Counter(r["state"] for r in rows)
    by_name = _Counter(r.get("name", "?") for r in rows)
    return {"total": len(rows), "by_state": dict(by_state),
            "by_func_name": dict(by_name.most_common(20))}


def summarize_actors() -> Dict[str, Any]:
    rows = list_actors()
    return {"total": len(rows),
            "by_state": dict(_Counter(r["state"] for r in rows)),
            "by_class": dict(_Counter(r["class_name"] for r in rows))}


def list_events(limit: int = 10_000) -> List[dict]:
    return _runtime().events()[-limit:]
