"""Black-box flight recorder: crash-safe on-disk spool + sealed bundles.

Every ray_tpu process (driver, host daemon, standalone tool) can install
ONE process-wide :class:`FlightRecorder`. A background thread spools the
process's observable state — new profiler spans, trace-stamped log-ring
lines, chaos trace lines, periodic metrics snapshots, and the in-flight
task registry — into an on-disk ring under::

    <flight_recorder_dir>/<role>-<pid>-<uid8>/
        index.json        # atomic-written recording header + cursor state
        spool-<k>.jsonl   # append-only JSONL segments (2 kept = the ring)
        lastwords.bin     # fixed-size mmap'd region, freshest state wins
        faulthandler.log  # fatal-signal stacks (SIGSEGV/SIGABRT/...)
        BUNDLE.json       # present only once the recording is SEALED

Sealing paths (who writes BUNDLE.json):

1. **self** — ``sys.excepthook`` (unhandled exception), a chained SIGTERM
   handler when the process had no handler of its own, a registered chaos
   ``exit`` hook (:func:`ray_tpu.chaos.register_exit_hook` — the
   deterministic test vehicle for hard death), or ``atexit`` when the
   process dies without marking a clean exit.
2. **posthumous** — :func:`seal_orphans`: a survivor (the host daemon's
   periodic sweep, or ``python -m ray_tpu.doctor``) finds a recording
   whose pid is dead with no bundle and no clean-exit mark (SIGKILL, OOM
   kill, machine loss) and synthesizes the bundle from the spool tail,
   ``lastwords.bin`` and ``faulthandler.log``.

Cost model: nothing on the put/get/task hot paths except the module-bool
``ENABLED`` check guarding :func:`task_started`/:func:`task_finished`
(two dict ops per task when on). Everything else happens on the spool
thread at ``flight_recorder_spool_ms`` cadence — gated ≤2% on the 1KB
put/get path by ``bench_micro.py``'s ``recorder_overhead_pct``.
"""

from __future__ import annotations

import atexit
import json
import os
import signal
import sys
import threading
import time
import traceback
from typing import Any, Callable, Dict, List, Optional, Tuple

from ray_tpu._private.config import _config

# Fast-path guard: the runtime's task-execute path checks this bool and
# nothing else when no recorder is installed (chaos.ENABLED pattern).
ENABLED: bool = False

_recorder: Optional["FlightRecorder"] = None
_install_lock = threading.Lock()

BUNDLE_NAME = "BUNDLE.json"
INDEX_NAME = "index.json"
LASTWORDS_NAME = "lastwords.bin"
FAULTLOG_NAME = "faulthandler.log"
_LASTWORDS_SIZE = 16384

# -- in-flight task registry -------------------------------------------------
# What was RUNNING when the process died: the runtime registers task
# start/finish here (guarded by ENABLED), the spool thread and the sealers
# snapshot it. A SIGKILL'd daemon's last spool record / lastwords therefore
# names the in-flight task and its trace_id.

_inflight_lock = threading.Lock()
_inflight: Dict[str, dict] = {}  # raylint: guarded-by(_inflight_lock)

# Extra per-tick state providers (the distributed runtime registers one
# reporting node identity / heartbeat-loop liveness). Registration instead
# of imports keeps this module cycle-free below the runtime.
_providers_lock = threading.Lock()
_state_providers: List[Callable[[], Optional[dict]]] = []  # raylint: guarded-by(_providers_lock)


def register_state_provider(fn: Callable[[], Optional[dict]]) -> None:
    with _providers_lock:
        if fn not in _state_providers:
            _state_providers.append(fn)


def task_started(task_id: str, name: str, trace_id: str = "",
                 span_id: str = "") -> None:
    entry = {"name": name, "trace_id": trace_id, "span_id": span_id,
             "started_ts": time.time(),
             "thread": threading.current_thread().name}
    with _inflight_lock:
        _inflight[task_id] = entry


def task_finished(task_id: str) -> None:
    with _inflight_lock:
        _inflight.pop(task_id, None)


def inflight_snapshot() -> Dict[str, dict]:
    with _inflight_lock:
        return {k: dict(v) for k, v in _inflight.items()}


def _provider_state() -> dict:
    state: dict = {}
    with _providers_lock:
        providers = list(_state_providers)
    for fn in providers:
        try:
            got = fn()
        except Exception:  # noqa: BLE001  # raylint: allow(swallow) spool tick must survive a broken provider
            got = None
        if got:
            state.update(got)
    return state


def thread_stacks() -> Dict[str, str]:
    """Python stacks of every live thread, keyed by thread name — the
    'where was everyone' part of a crash bundle / hang diagnosis."""
    names = {t.ident: t.name for t in threading.enumerate()}
    out = {}
    for ident, frame in sys._current_frames().items():
        label = names.get(ident, f"tid-{ident}")
        out[label] = "".join(traceback.format_stack(frame))
    return out


def _pid_alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except PermissionError:
        return True
    except OSError:
        return True
    return True


def _atomic_write(path: str, payload: dict) -> None:
    # Lazy: checkpoint.manifest pulls numpy via the package __init__; the
    # recorder must stay importable in skinny tool processes until needed.
    from ray_tpu.checkpoint.manifest import atomic_write_bytes
    atomic_write_bytes(path, json.dumps(payload).encode())


class FlightRecorder:
    """One per-process always-on recorder. Use :func:`install`."""

    def __init__(self, role: str, label: str = "",
                 root: Optional[str] = None):
        self.role = role
        self.label = label or role
        self.root = root or str(_config.get("flight_recorder_dir"))
        self.pid = os.getpid()
        self.uid = os.urandom(4).hex()
        self.dir = os.path.join(self.root, f"{role}-{self.pid}-{self.uid}")
        self.start_ts = time.time()
        self._spool_s = max(0.01,
                            int(_config.get("flight_recorder_spool_ms")) / 1e3)
        self._segment_bytes = int(_config.get("flight_recorder_segment_bytes"))
        self._tail = int(_config.get("flight_recorder_tail_events"))
        self._seq = 0  # raylint: guarded-by(self._lock)
        self._segment_idx = 0  # raylint: guarded-by(self._lock)
        self._segment_file = None  # raylint: guarded-by(self._lock)
        self._span_cursor = 0  # raylint: guarded-by(self._lock)
        self._log_cursor = 0  # raylint: guarded-by(self._lock)
        self._chaos_cursor = 0  # raylint: guarded-by(self._lock)
        self._tick_count = 0  # raylint: guarded-by(self._lock)
        self._sealed = False
        self._clean = False
        self._exc_info: Optional[tuple] = None
        self._stop = threading.Event()
        self._paused = threading.Event()
        self._lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None
        self._lw_map = None       # mmap when available  # raylint: guarded-by(self._lock)
        self._lw_file = None      # plain-file fallback  # raylint: guarded-by(self._lock)
        self._fault_file = None
        self._orig_excepthook = None

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        os.makedirs(self.dir, exist_ok=True)
        # under _lock so the spool thread's view of the segment/lastwords
        # handles is ordered after this setup
        with self._lock:
            self._open_segment(0)
            self._open_lastwords()
            self._install_hooks()
            self._write_index()
        self._thread = threading.Thread(target=self._spool_loop,
                                        name="flight-recorder", daemon=True)
        self._thread.start()

    def pause(self) -> None:
        """Stop spooling without tearing down (A/B benching: the
        recorder is process-wide and cannot be uninstalled). Sealing
        hooks stay armed while paused."""
        self._paused.set()

    def resume(self) -> None:
        self._paused.clear()

    def set_label(self, label: str) -> None:
        """Adopt the process's real identity once known (daemons learn
        their ``node:<hex8>`` tag only after registering)."""
        with self._lock:
            self.label = label
            self._write_index()

    def close(self, clean: bool = True) -> None:
        """Stop spooling and mark the recording finished. ``clean=True``
        records a deliberate shutdown: no bundle is sealed at exit and
        posthumous sweeps leave the recording alone."""
        self._stop.set()
        if self._thread is not None and \
                self._thread is not threading.current_thread():
            self._thread.join(timeout=2.0)
        with self._lock:
            self._spool_once_locked(final=True)
            self._clean = bool(clean)
            self._write_index()

    # -- on-disk plumbing ----------------------------------------------------

    def _open_segment(self, idx: int) -> None:
        if self._segment_file is not None:
            try:
                self._segment_file.close()
            except OSError:
                pass
        self._segment_idx = idx
        path = os.path.join(self.dir, f"spool-{idx}.jsonl")
        self._segment_file = open(path, "a", encoding="utf-8")
        # the ring keeps two segments: current + previous
        stale = os.path.join(self.dir, f"spool-{idx - 2}.jsonl")
        if idx >= 2 and os.path.exists(stale):
            try:
                os.unlink(stale)
            except OSError:
                pass

    def _open_lastwords(self) -> None:
        path = os.path.join(self.dir, LASTWORDS_NAME)
        f = None
        try:
            import mmap
            f = open(path, "w+b")
            f.truncate(_LASTWORDS_SIZE)
            self._lw_map = mmap.mmap(f.fileno(), _LASTWORDS_SIZE)
            self._lw_file = f
        except (OSError, ValueError, ImportError):
            # plain-file fallback: pwrite the same length-prefixed payload
            self._lw_map = None
            if f is not None:
                # the mmap attempt left the first handle open
                try:
                    f.close()
                except OSError:
                    pass
            try:
                self._lw_file = open(path, "w+b")
                self._lw_file.truncate(_LASTWORDS_SIZE)
            except OSError:
                self._lw_file = None

    def _write_lastwords(self, payload: dict) -> None:
        data = json.dumps(payload).encode()
        if len(data) > _LASTWORDS_SIZE - 8:
            data = data[:_LASTWORDS_SIZE - 8]  # fixed region: freshest wins
        framed = len(data).to_bytes(4, "big") + data
        try:
            if self._lw_map is not None:
                self._lw_map[0:len(framed)] = framed
            elif self._lw_file is not None:
                self._lw_file.seek(0)
                self._lw_file.write(framed)
                self._lw_file.flush()
        except (OSError, ValueError):
            pass

    def _install_hooks(self) -> None:
        import faulthandler
        try:
            self._fault_file = open(  # raylint: guarded-by(self._lock)
                os.path.join(self.dir, FAULTLOG_NAME), "w")
            faulthandler.enable(file=self._fault_file)
        except (OSError, RuntimeError):
            self._fault_file = None
        self._orig_excepthook = sys.excepthook  # raylint: allow(data-race) saved before sys.excepthook is swapped in; the installed hook reads it strictly afterwards
        sys.excepthook = self._on_unhandled
        atexit.register(self._on_atexit)
        # chaos `exit` = deterministic SIGKILL stand-in; seal on the way down
        from ray_tpu import chaos
        chaos.register_exit_hook(self._on_chaos_exit)
        # Chain a SIGTERM sealer only when the process has no handler of
        # its own (the default action skips atexit entirely); daemons
        # install their graceful-stop handler after us and win.
        try:
            if threading.current_thread() is threading.main_thread() and \
                    signal.getsignal(signal.SIGTERM) == signal.SIG_DFL:
                signal.signal(signal.SIGTERM, self._on_sigterm)
        except (ValueError, OSError):
            pass

    # -- sealing hooks -------------------------------------------------------

    def _on_unhandled(self, exc_type, exc, tb) -> None:
        self._exc_info = (exc_type, exc, tb)
        self.seal(f"unhandled-exception: {exc_type.__name__}: {exc}")
        if self._orig_excepthook is not None:
            self._orig_excepthook(exc_type, exc, tb)

    def _on_chaos_exit(self, point: str, code: int) -> None:
        self.seal(f"chaos-exit({code}) at {point}")

    def _on_sigterm(self, signum, frame) -> None:
        self.seal(f"signal {signal.Signals(signum).name}")
        signal.signal(signum, signal.SIG_DFL)
        os.kill(os.getpid(), signum)

    def _on_atexit(self) -> None:
        if self._clean or self._sealed or self._exc_info is not None:
            return  # already closed clean / already sealed
        # interpreter exiting without an explicit close(): still a normal
        # exit — record it clean rather than crying wolf with a bundle
        self.close(clean=True)

    def seal(self, reason: str) -> Optional[str]:
        """Write the crash bundle (idempotent; first reason wins).
        Returns the bundle path, or None when already sealed."""
        with self._lock:
            if self._sealed:
                return None
            self._sealed = True
        self._stop.set()
        bundle = {
            "version": 1,
            "sealed_ts": time.time(),
            "sealed_by": "self",
            "role": self.role,
            "pid": self.pid,
            "label": self.label,
            "start_ts": self.start_ts,
            "exit_reason": reason,
            "clean": False,
            "thread_stacks": self._safe(thread_stacks, {}),
            "inflight": self._safe(inflight_snapshot, {}),
            "state": self._safe(_provider_state, {}),
            "spans": self._safe(self._span_tail, []),
            "logs": self._safe(self._log_tail, []),
            "chaos": self._safe(self._chaos_tail, []),
            "metrics": self._safe(self._metrics_snapshot, []),
            "config": self._safe(_config.to_dict, {}),
        }
        if self._exc_info is not None:
            et, ev, tb = self._exc_info
            bundle["exception"] = {
                "type": et.__name__, "message": str(ev),
                "traceback": "".join(
                    traceback.format_exception(et, ev, tb)),
            }
        bundle["trace_ids"] = sorted({
            t["trace_id"] for t in bundle["inflight"].values()
            if t.get("trace_id")})
        path = os.path.join(self.dir, BUNDLE_NAME)
        try:
            _atomic_write(path, bundle)
        except OSError:
            return None
        with self._lock:
            self._write_index()
        _bundles_sealed_metric()
        return path

    @staticmethod
    def _safe(fn, default):
        try:
            return fn()
        except BaseException:  # noqa: BLE001  # raylint: allow(swallow) crash sealing must never throw
            return default

    # -- tick sources --------------------------------------------------------

    def _span_tail(self) -> List[dict]:
        from ray_tpu._private.profiling import get_profiler
        return get_profiler().chrome_trace()[-self._tail:]

    def _log_tail(self) -> List[str]:
        from ray_tpu._private import log_ring
        return log_ring.tail(self._tail)

    def _chaos_tail(self) -> List[str]:
        from ray_tpu import chaos
        return list(chaos.trace_lines())[-self._tail:]

    def _metrics_snapshot(self) -> List[dict]:
        from ray_tpu.util import metrics
        return metrics.snapshot()

    def _chaos_spec(self) -> str:
        return os.environ.get("RAY_TPU_CHAOS", "")

    # -- the spool loop ------------------------------------------------------

    def _spool_loop(self) -> None:
        while not self._stop.wait(self._spool_s):
            if self._paused.is_set():
                continue
            with self._lock:
                if self._sealed:
                    return
                try:
                    self._spool_once_locked()
                except Exception:  # noqa: BLE001  # raylint: allow(swallow) recorder must never take the process down
                    pass

    def _spool_once_locked(self, final: bool = False) -> None:
        from ray_tpu._private import log_ring
        from ray_tpu._private.profiling import get_profiler
        self._tick_count += 1
        now = time.time()
        rec: Dict[str, Any] = {"ts": now, "seq": self._seq}
        self._span_cursor, spans = \
            get_profiler().events_since(self._span_cursor)
        if spans:
            rec["spans"] = spans[-self._tail:]
        self._log_cursor, logs = log_ring.tail_since(self._log_cursor)
        if logs:
            rec["logs"] = logs[-self._tail:]
        chaos_lines = self._chaos_tail()
        if len(chaos_lines) > self._chaos_cursor:
            rec["chaos"] = chaos_lines[self._chaos_cursor:]
            self._chaos_cursor = len(chaos_lines)
        inflight = inflight_snapshot()
        if inflight:
            rec["inflight"] = inflight
        state = _provider_state()
        if state:
            rec["state"] = state
        # metrics are the bulkiest part: every 4th tick (and the final one)
        if final or self._tick_count % 4 == 1:
            rec["metrics"] = self._safe(self._metrics_snapshot, [])
        line = json.dumps(rec)
        if self._segment_file is not None:
            try:
                if self._segment_file.tell() + len(line) > \
                        self._segment_bytes:
                    self._open_segment(self._segment_idx + 1)
                    self._write_index()
                self._segment_file.write(line + "\n")
                self._segment_file.flush()
            except (OSError, ValueError):
                pass
        self._write_lastwords({
            "ts": now, "seq": self._seq, "inflight": inflight,
            "state": state,
            "trace_ids": sorted({t["trace_id"] for t in inflight.values()
                                 if t.get("trace_id")})})
        self._seq += 1
        if self._tick_count % 8 == 1 or final:
            self._write_index()
        _ticks_metric()

    def _write_index(self) -> None:
        index = {
            "version": 1,
            "role": self.role,
            "pid": self.pid,
            "label": self.label,
            "start_ts": self.start_ts,
            "updated_ts": time.time(),
            "seq": self._seq,
            "segments": [f"spool-{i}.jsonl"
                         for i in (self._segment_idx - 1, self._segment_idx)
                         if i >= 0],
            "chaos_spec": self._chaos_spec(),
            "clean_exit": self._clean,
            "sealed": self._sealed,
            "argv": list(sys.argv),
        }
        try:
            _atomic_write(os.path.join(self.dir, INDEX_NAME), index)
        except OSError:
            pass


# -- metrics (lazy; profiling.py pattern) ------------------------------------

_metrics_lock = threading.Lock()
_ticks_counter = None  # raylint: guarded-by(_metrics_lock)
_bundles_counter = None  # raylint: guarded-by(_metrics_lock)


def _ticks_metric():
    global _ticks_counter
    with _metrics_lock:
        c = _ticks_counter
        if c is None:
            from ray_tpu.util.metrics import Counter
            c = _ticks_counter = Counter(
                "flight_recorder_ticks", "spool-thread ticks recorded")
    c.inc()


def _bundles_sealed_metric():
    global _bundles_counter
    with _metrics_lock:
        c = _bundles_counter
        if c is None:
            from ray_tpu.util.metrics import Counter
            c = _bundles_counter = Counter(
                "flight_recorder_bundles_sealed", "crash bundles sealed")
    c.inc()


# -- module-level install ----------------------------------------------------

def install(role: str, label: str = "") -> Optional[FlightRecorder]:
    """Install the process-wide recorder (idempotent: the first caller's
    role wins — a recorder outlives ``ray_tpu.shutdown()`` because it
    records the PROCESS, not one runtime). Returns None when disabled."""
    global _recorder, ENABLED
    if not _config.get("flight_recorder_enabled"):
        return None
    with _install_lock:
        if _recorder is None:
            rec = FlightRecorder(role, label)
            _gc(rec.root)
            rec.start()
            _recorder = rec  # raylint: allow(data-race) GIL-atomic unlocked read of the module singleton; install/uninstall serialize under _install_lock
            ENABLED = True
        return _recorder


def get_recorder() -> Optional[FlightRecorder]:
    return _recorder


# -- posthumous sealing + disk inventory -------------------------------------

def _read_json(path: str) -> Optional[dict]:
    try:
        with open(path, encoding="utf-8") as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def _read_lastwords(path: str) -> Optional[dict]:
    try:
        with open(path, "rb") as f:
            framed = f.read(_LASTWORDS_SIZE)
    except OSError:
        return None
    if len(framed) < 4:
        return None
    n = int.from_bytes(framed[:4], "big")
    if n <= 0 or n > len(framed) - 4:
        return None
    try:
        return json.loads(framed[4:4 + n].decode("utf-8", "replace"))
    except ValueError:
        return None


def _spool_records(rec_dir: str, index: dict, limit: int = 64) -> List[dict]:
    """Last ``limit`` spool records across the (≤2) live segments."""
    records: List[dict] = []
    for seg in index.get("segments") or []:
        try:
            with open(os.path.join(rec_dir, seg), encoding="utf-8") as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        records.append(json.loads(line))
                    except ValueError:
                        continue  # torn final line after a hard kill
        except OSError:
            continue
    return records[-limit:]


def _merge_tail(records: List[dict], key: str, tail: int) -> list:
    out: list = []
    for rec in records:
        out.extend(rec.get(key) or [])
    return out[-tail:]


def seal_orphans(root: Optional[str] = None,
                 sealed_by: str = "doctor") -> List[str]:
    """Posthumously seal every recording under ``root`` whose process died
    without running its own hooks (SIGKILL, OOM kill, machine loss). Safe
    to run from any surviving process — the host daemon sweeps its local
    root periodically; the doctor sweeps at collect time. Returns the
    bundle paths written."""
    root = root or str(_config.get("flight_recorder_dir"))
    sealed: List[str] = []
    try:
        entries = sorted(os.listdir(root))
    except OSError:
        return sealed
    tail = int(_config.get("flight_recorder_tail_events"))
    for name in entries:
        rec_dir = os.path.join(root, name)
        if not os.path.isdir(rec_dir) or \
                os.path.exists(os.path.join(rec_dir, BUNDLE_NAME)):
            continue
        index = _read_json(os.path.join(rec_dir, INDEX_NAME))
        if not index or index.get("clean_exit"):
            continue
        pid = int(index.get("pid") or 0)
        if pid <= 0 or _pid_alive(pid):
            continue
        records = _spool_records(rec_dir, index)
        lastwords = _read_lastwords(
            os.path.join(rec_dir, LASTWORDS_NAME)) or {}
        fault_text = ""
        try:
            with open(os.path.join(rec_dir, FAULTLOG_NAME),
                      encoding="utf-8", errors="replace") as f:
                fault_text = f.read().strip()
        except OSError:
            pass
        if fault_text:
            reason = "fatal-signal (stacks in faulthandler log)"
        else:
            reason = ("external-kill (process died without running exit "
                      "hooks; SIGKILL, OOM kill, or machine loss)")
        inflight = lastwords.get("inflight") or {}
        if not inflight and records:
            inflight = records[-1].get("inflight") or {}
        metrics_tail: list = []
        for rec in reversed(records):
            if rec.get("metrics"):
                metrics_tail = rec["metrics"]
                break
        bundle = {
            "version": 1,
            "sealed_ts": time.time(),
            "sealed_by": f"posthumous:{sealed_by}",
            "role": index.get("role", "?"),
            "pid": pid,
            "label": index.get("label", ""),
            "start_ts": index.get("start_ts"),
            "exit_reason": reason,
            "clean": False,
            "inflight": inflight,
            "trace_ids": sorted(
                set(lastwords.get("trace_ids") or []) |
                {t.get("trace_id") for t in inflight.values()
                 if t.get("trace_id")}),
            "state": lastwords.get("state") or {},
            "lastwords": lastwords,
            "spans": _merge_tail(records, "spans", tail),
            "logs": _merge_tail(records, "logs", tail),
            "chaos": _merge_tail(records, "chaos", tail),
            "metrics": metrics_tail,
            "faulthandler": fault_text,
            "chaos_spec": index.get("chaos_spec", ""),
        }
        path = os.path.join(rec_dir, BUNDLE_NAME)
        try:
            _atomic_write(path, bundle)
        except OSError:
            continue
        sealed.append(path)
    return sealed


def disk_report(root: Optional[str] = None) -> dict:
    """Inventory of recordings + sealed bundles under ``root`` — the
    payload a daemon returns for NODE_DEBUG ``include_bundles`` and the
    doctor's local collection unit."""
    root = root or str(_config.get("flight_recorder_dir"))
    recordings: List[dict] = []
    bundles: List[dict] = []
    try:
        entries = sorted(os.listdir(root))
    except OSError:
        entries = []
    for name in entries:
        rec_dir = os.path.join(root, name)
        if not os.path.isdir(rec_dir):
            continue
        index = _read_json(os.path.join(rec_dir, INDEX_NAME))
        if index is not None:
            index["dir"] = rec_dir
            index["alive"] = _pid_alive(int(index.get("pid") or 0))
            recordings.append(index)
        bundle = _read_json(os.path.join(rec_dir, BUNDLE_NAME))
        if bundle is not None:
            bundle["dir"] = rec_dir
            bundles.append(bundle)
    return {"root": root, "recordings": recordings, "bundles": bundles}


def _gc(root: str) -> None:
    """Prune finished recordings (clean exit or sealed, pid dead) older
    than the retention window, so always-on spooling cannot grow /tmp
    without bound across many short-lived test processes."""
    import shutil
    keep_s = int(_config.get("flight_recorder_retention_s"))
    cutoff = time.time() - max(60, keep_s)
    try:
        entries = os.listdir(root)
    except OSError:
        return
    for name in entries:
        rec_dir = os.path.join(root, name)
        index = _read_json(os.path.join(rec_dir, INDEX_NAME))
        if not index or _pid_alive(int(index.get("pid") or 0)):
            continue
        done = index.get("clean_exit") or \
            os.path.exists(os.path.join(rec_dir, BUNDLE_NAME))
        if done and (index.get("updated_ts") or 0) < cutoff:
            try:
                shutil.rmtree(rec_dir)
            except OSError:
                pass
