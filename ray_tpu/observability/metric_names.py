"""Declared metric-name registry — the source of truth lint rule R22
checks call sites against.

A typo'd histogram name (``perf.observe("task.exeute", ...)``) does not
fail; it silently creates a parallel family that every consumer (head
quantiles, ``ray-tpu top``, doctor baselines) ignores.  Same for a
misspelled goodput ledger category, which would break the ledger's
exclusivity-sums-to-wall-clock invariant.  So: every literal name passed
to ``perf.observe(...)`` and every ledger category passed to
``goodput.account(...)`` / ``goodput.interval(...)`` must appear here
(or be imported from this module); raylint R22 flags the rest.

This module is deliberately import-free (no config, no runtime) so the
linter and the hot paths can both load it for nothing.
"""

from __future__ import annotations

# Goodput ledger categories, in display order.  Exclusive: every wall-
# clock second of a job lands in exactly one.  ``idle`` is derived
# (wall minus everything attributed), never accounted directly.
LEDGER_CATEGORIES = (
    "compute",
    "compile",
    "data_wait",
    "collective_wait",
    "ckpt_stall",
    "restart_downtime",
    "idle",
)

# Every perf-plane histogram family the runtime records.  Grouped by
# subsystem prefix (the ``--subsystem`` filter in ``ray-tpu top``).
PERF_HISTOGRAMS = frozenset({
    # rpc
    "rpc.call",
    "rpc.connect",
    # task plane
    "task.execute",
    "task.e2e",
    "task.sched",
    # object plane
    "fetch.object",
    "fetch.stripe",
    "push.object",
    # striped transport
    "transport.striped_run",
    "transport.chunk",
    # checkpoint engine
    "ckpt.save",
    "ckpt.hash",
    "ckpt.write",
    "ckpt.commit",
    # serve
    "serve.request",
    "serve.queue_wait",
    "serve.execute",
    "serve.serialize",
    "serve.ingress_put",
    "serve.replica_exec",
    # train loop
    "train.step",
    "train.report",
    "train.ckpt_enqueue",
    # jit compile detection (goodput ledger's runtime mirror of R21)
    "jit.compile",
    # drain / lifecycle
    "drain.migrate",
    # comms plane (collective rendezvous phases; observability/comms.py)
    "collective.op",       # full API-layer op duration (collective.py seam)
    "collective.launch",   # last-arrival compute / compiled-program run
    "collective.collect",  # per-rank blocked time from arrival to result
    "collective.quantize",  # per-rank block-quantization cost (compression
                            # tier, collective/quantization.py)
})

# Comms-plane sample families.  Not literal-checked by a lint rule the
# way perf.observe names are — they are declared here so the exporters
# (observability/comms.py, collective/tensor_plane.py) and their
# consumers (dashboard head, doctor, tests) share one spelling.
COMMS_FAMILY = "raytpu_comms_bytes"
TPLANE_EPOCH_GAUGE = "tplane_epoch"
