"""End-to-end distributed tracing: W3C-style context + span recording.

A trace is a ``trace_id`` minted at an entry point (task submit, serve
request, checkpoint save) plus a tree of spans, each ``(span_id,
parent_span_id)``.  The context travels three ways:

- **TaskSpec** — ``trace_id``/``parent_span_id`` fields, so a task's
  worker-side execute span joins the submit-side trace (``runtime.py``).
- **RPC envelope** — ``Envelope.trace`` carries ``"trace_id:span_id"``;
  the server adopts it around handler dispatch (``_private/rpc.py``).
- **RTF5 frame index** — an optional trailing blob in the frame index
  (``_private/framing.py``) stamps serialized objects with the trace
  that produced them, so a striped fetch can attribute the bytes it
  moved.  Absent trace keeps frames byte-identical to the pre-trace
  format (checkpoint chunk dedup depends on this).

Spans land in the process-local :class:`~ray_tpu._private.profiling.Profiler`
ring; the dashboard head federates every host's ring into one merged
chrome://tracing timeline (``/api/timeline``, ``/api/trace?id=X``).

Cost model mirrors :mod:`ray_tpu.chaos`: a module-level ``ENABLED`` bool
is the only thing the hot paths touch when tracing is off (guarded by
``bench_micro.py``'s ``trace_overhead_pct`` gate).  ``enable()`` flips it
and installs the chaos observer so injected faults appear as instant
events inside the traces they perturb.
"""

from __future__ import annotations

import contextvars
import os
import threading
import time
from typing import Any, Callable, Dict, Optional, Tuple

from ray_tpu._private.config import _config
from ray_tpu._private.profiling import get_profiler
from ray_tpu.observability import sampler as _sampler

# Fast-path switch: hot paths check this module bool and nothing else
# when tracing is off (same pattern as chaos.ENABLED).
ENABLED: bool = bool(_config.get("tracing_enabled"))

# chrome-tracing process label for spans recorded in this process;
# daemons relabel to "node:<hex8>" at startup so the merged timeline
# separates hosts.
_pid_label: str = "driver"

_ctx_var: "contextvars.ContextVar[Optional[Tuple[str, str]]]" = \
    contextvars.ContextVar("ray_tpu_obs_ctx", default=None)

# Fallback context sources (the runtime registers one that reads its
# per-task thread-local / async ContextVar), consulted when no explicit
# span context is active.  Registration instead of an import keeps
# observability import-light and cycle-free (runtime imports us).
_providers: list = []

Context = Tuple[str, str]  # (trace_id, span_id)


def register_context_provider(fn: Callable[[], Optional[Context]]) -> None:
    if fn not in _providers:
        _providers.append(fn)  # raylint: allow(data-race) providers registered during process bootstrap; iteration sees a GIL-atomic list snapshot


def set_process_label(label: str) -> None:
    global _pid_label
    _pid_label = label  # raylint: allow(data-race) process label set once at bootstrap; plain string store is GIL-atomic


def process_label() -> str:
    return _pid_label


def enable() -> None:
    """Turn tracing on (also flips the config knob so child runtimes and
    ``Profiler.enabled`` agree) and hook chaos instant events."""
    global ENABLED
    _config.set("tracing_enabled", True)
    ENABLED = True
    from ray_tpu import chaos
    chaos.set_observer(_chaos_observer)


def disable() -> None:
    global ENABLED
    _config.set("tracing_enabled", False)
    ENABLED = False
    from ray_tpu import chaos
    chaos.set_observer(None)


def mint_id() -> str:
    """A fresh 64-bit hex id (trace or span)."""
    return os.urandom(8).hex()


def current() -> Optional[Context]:
    """The active (trace_id, span_id), from the innermost enclosing
    ``span(...)`` or, failing that, a registered provider (task ctx)."""
    ctx = _ctx_var.get()
    if ctx is not None:
        return ctx
    for fn in _providers:
        got = fn()
        if got:
            return got
    return None


def current_trace_id() -> str:
    """The active trace id, or ``""``. Cheap enough for log records."""
    if not ENABLED:
        return ""
    ctx = current()
    return ctx[0] if ctx else ""


def set_current(trace_id: str, span_id: str):
    """Explicitly adopt a context; returns a token for :func:`reset`."""
    return _ctx_var.set((trace_id, span_id))


def reset(token) -> None:
    _ctx_var.reset(token)


# -- wire helpers -----------------------------------------------------------

def wire_context() -> str:
    """The active context encoded for the wire (``"trace_id:span_id"``),
    or ``""`` when tracing is off / no context is active."""
    if not ENABLED:
        return ""
    ctx = current()
    return f"{ctx[0]}:{ctx[1]}" if ctx else ""


def parse_wire(ctx_str: str) -> Optional[Context]:
    if not ctx_str:
        return None
    trace_id, sep, span_id = ctx_str.partition(":")
    if not sep or not trace_id:
        return None
    return (trace_id, span_id)


def adopt_wire(ctx_str: str):
    """Adopt a wire-encoded context for the current execution context.
    Returns a reset token, or ``None`` when ``ctx_str`` is empty/bad."""
    ctx = parse_wire(ctx_str)
    if ctx is None:
        return None
    return _ctx_var.set(ctx)


# -- span recording ---------------------------------------------------------

class span:
    """Record a timed span parented under the active context.

    Context-manager only (raylint R14 enforces this outside the
    observability package): the span closes on every exit path, and the
    context var is always reset.  Near-free when ``ENABLED`` is False —
    ``__enter__``/``__exit__`` return after one bool check.
    """

    __slots__ = ("name", "cat", "args", "pid", "_t0", "_ids", "_token",
                 "_tagged")

    def __init__(self, name: str, cat: str = "obs",
                 pid: Optional[str] = None, **args: Any):
        self.name = name
        self.cat = cat
        self.args = args
        self.pid = pid
        self._t0 = None
        self._token = None
        self._tagged = False

    def __enter__(self) -> "span":
        if not ENABLED:
            return self
        parent = current()
        if parent is None:
            trace_id, parent_span = mint_id(), ""
        else:
            trace_id, parent_span = parent
        span_id = mint_id()
        self._ids = (trace_id, span_id, parent_span)
        self._token = _ctx_var.set((trace_id, span_id))
        if _sampler.TAGGING:
            # stack-sampler attribution: samples landing on this thread
            # while the span is open are tagged with its trace id
            _sampler.note_span_enter(trace_id)
            self._tagged = True
        self._t0 = time.time()
        return self

    @property
    def trace_id(self) -> str:
        return self._ids[0] if self._t0 is not None else ""

    @property
    def span_id(self) -> str:
        return self._ids[1] if self._t0 is not None else ""

    def __exit__(self, exc_type, exc, tb) -> None:
        if self._t0 is None:  # ENABLED was off at __enter__
            return
        try:
            dur = time.time() - self._t0
            trace_id, span_id, parent_span = self._ids
            args = dict(self.args)
            args.update(trace_id=trace_id, span_id=span_id,
                        parent_span_id=parent_span)
            if exc_type is not None:
                args["error"] = exc_type.__name__
            get_profiler().record(self.name, self.cat,
                                  pid=self.pid or _pid_label,
                                  start_s=self._t0, dur_s=dur, args=args)
        finally:
            if self._tagged:
                _sampler.note_span_exit()
                self._tagged = False
            _ctx_var.reset(self._token)
            self._t0 = None


def instant(name: str, cat: str = "obs", pid: Optional[str] = None,
            **args: Any) -> None:
    """Record a point-in-time event tagged with the active context."""
    if not ENABLED:
        return
    ctx = current()
    if ctx:
        args.setdefault("trace_id", ctx[0])
        args.setdefault("parent_span_id", ctx[1])
    get_profiler().instant(name, cat, pid=pid or _pid_label, args=args)


def _chaos_observer(point: str, labels: Dict[str, Any], action: str) -> None:
    """Installed into ray_tpu.chaos by enable(): every fired fault becomes
    an instant event carrying the fault spec, interleaved with the spans
    it perturbed."""
    args = {"action": action}
    for k, v in labels.items():
        args[k] = str(v)
    instant(f"chaos:{point}", cat="chaos", **args)


# -- trace querying ---------------------------------------------------------

def spans_for_trace(trace_id: str, events=None) -> list:
    """Filter chrome events down to one trace (spans whose args carry the
    trace_id, plus its instant events)."""
    if events is None:
        events = get_profiler().chrome_trace()
    return [e for e in events
            if (e.get("args") or {}).get("trace_id") == trace_id]
