"""Goodput & efficiency ledger: per-job wall-clock attribution.

The perf plane (:mod:`ray_tpu.observability.perf`) answers "how long do
operations take"; this module answers "where does a job's wall-clock
*go*" — the quantity that decides whether preemptible-fleet economics
work (ROADMAP item 2's ``fleet_goodput_pct``).  Every interval of a
job's life in this process is classified into exactly one of the
exclusive categories in :data:`ray_tpu.observability.metric_names
.LEDGER_CATEGORIES`:

``compute``
    Steady-state device/step work.  Mostly attributed implicitly: the
    train session calls :func:`step_mark` once per step, and whatever
    wall time since the previous mark no explicit interval claimed is
    compute.
``compile``
    First-trace (and re-trace) time of jitted entry points, detected by
    :func:`instrument_jit` per abstract argument signature — the runtime
    mirror of lint rule R21 (a second distinct signature for the same
    function is a *recompile* and counted as such).
``data_wait`` / ``collective_wait`` / ``ckpt_stall``
    Explicit :class:`interval` / :func:`account` sites: input pipeline
    stalls, collective/barrier wait in :mod:`ray_tpu.collective`, and
    blocking time on the checkpoint engine's bounded queue.
``restart_downtime``
    Drain / preemption / elastic-restart gaps stamped by
    ``_private/distributed.py`` and the trainer: the time between a
    node's actors checkpointing for eviction and their restore on a
    survivor (wall-clock stamps ride the drain KV record, so the gap is
    measured across processes).
``idle``
    Derived, never accounted directly: wall since the ledger started
    minus everything attributed, clamped at zero.  This makes the
    categories sum to wall-clock by construction.

**Exclusivity** is enforced two ways: nested :class:`interval`\\ s pause
the enclosing interval (inner time is attributed once, to the inner
category), and :func:`account` feeds a per-job "attributed since last
step mark" counter that :func:`step_mark` subtracts before crediting
compute.

Cost model mirrors chaos/tracing/perf: a module-level ``ENABLED`` bool
is all the hot paths touch when the ledger is off (guarded by
``bench_micro.py``'s ``goodput_overhead_pct`` row).  Export rides the
perf plane's channel: :func:`families` emits one Prometheus gauge
family whose non-standard ``"goodput"`` payload carries the raw ledgers
through the JSON ``/api/metrics`` federation; the dashboard head merges
per-node payloads into per-job totals at ``/api/goodput`` with
:func:`merge_payloads`.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, Iterable, List, Optional, Tuple

from ray_tpu._private.config import _config
from ray_tpu.observability.metric_names import LEDGER_CATEGORIES

# Fast-path switch: instrumented code checks this module bool and
# nothing else when the ledger is off (same pattern as chaos.ENABLED).
ENABLED: bool = bool(_config.get("goodput_enabled"))

CATEGORIES: Tuple[str, ...] = LEDGER_CATEGORIES
_ACCOUNTABLE = frozenset(c for c in CATEGORIES if c != "idle")

DEFAULT_JOB = "default"


def enable() -> None:
    """Turn the ledger on (also flips the config knob so child runtimes
    agree)."""
    global ENABLED
    _config.set("goodput_enabled", True)
    ENABLED = True


def disable() -> None:
    global ENABLED
    _config.set("goodput_enabled", False)
    ENABLED = False


class _Ledger:
    """Accumulated seconds per category for one job in this process.
    Mutated only under the module lock — accounting events are per-step
    / per-wait, not per-operation, so a lock (unlike perf's per-thread
    shards) costs nothing measurable."""

    __slots__ = ("job", "t0", "acc", "attributed", "mark_s",
                 "compile_count", "recompile_count", "signatures")

    def __init__(self, job: str):
        self.job = job
        self.t0 = time.monotonic()
        self.acc: Dict[str, float] = {c: 0.0 for c in _ACCOUNTABLE}
        self.attributed = 0.0       # accounted since the last step mark
        self.mark_s = self.t0
        self.compile_count = 0
        self.recompile_count = 0
        self.signatures: set = set()  # (label, abstract arg signature)


_ledgers: Dict[str, _Ledger] = {}
_lock = threading.Lock()
_job = DEFAULT_JOB


def set_job(job: str) -> None:
    """Set this process's default job label (the train session sets it
    from its run name so multi-job clusters get separate ledgers)."""
    global _job
    _job = job or DEFAULT_JOB


def current_job() -> str:
    return _job


def _ledger(job: Optional[str]) -> _Ledger:
    j = job or _job
    led = _ledgers.get(j)
    if led is None:
        with _lock:
            led = _ledgers.get(j)
            if led is None:
                led = _Ledger(j)
                _ledgers[j] = led
    return led


def account(category: str, seconds: float,
            job: Optional[str] = None) -> None:
    """Attribute ``seconds`` of wall-clock to ``category``.  No-op when
    the ledger is off; prefer gating the clock reads on
    ``goodput.ENABLED`` at the call site so they are free too."""
    if not ENABLED:
        return
    if category not in _ACCOUNTABLE:
        raise ValueError(
            f"unknown ledger category {category!r} (idle is derived); "
            f"declare categories in observability/metric_names.py")
    if seconds <= 0.0:
        return
    led = _ledger(job)
    with _lock:
        led.acc[category] += seconds
        led.attributed += seconds


def step_mark(job: Optional[str] = None) -> float:
    """Close out one training step: wall time since the previous mark
    that no explicit interval/account claimed is credited to
    ``compute``.  Returns the compute seconds attributed."""
    if not ENABLED:
        return 0.0
    led = _ledger(job)
    now = time.monotonic()
    with _lock:
        unattributed = (now - led.mark_s) - led.attributed
        led.mark_s = now
        led.attributed = 0.0
        if unattributed > 0.0:
            led.acc["compute"] += unattributed
            return unattributed
    return 0.0


class interval:
    """Attribute the enclosed wall time to ``category``.

    Context-manager only (the span discipline of R14 applies): the time
    is accounted on every exit path.  Nested intervals are *exclusive*:
    entering an inner interval pauses the enclosing one — the outer
    category accrues only its own time, the inner second is attributed
    once.  Near-free when ``ENABLED`` is off.
    """

    __slots__ = ("category", "job", "_t0", "_open")

    _stack = threading.local()

    def __init__(self, category: str, job: Optional[str] = None):
        if category not in _ACCOUNTABLE:
            raise ValueError(f"unknown ledger category {category!r}")
        self.category = category
        self.job = job
        self._t0 = None
        self._open = False

    def __enter__(self) -> "interval":
        if not ENABLED:
            return self
        _ledger(self.job)  # anchor the wall clock before time accrues
        stack = getattr(interval._stack, "v", None)
        if stack is None:
            stack = interval._stack.v = []
        now = time.monotonic()
        if stack:
            outer = stack[-1]
            if outer._t0 is not None:
                account(outer.category, now - outer._t0, outer.job)
                outer._t0 = None  # paused until this interval closes
        self._t0 = now
        self._open = True
        stack.append(self)
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        if not self._open:  # ENABLED was off at __enter__
            return
        now = time.monotonic()
        stack = getattr(interval._stack, "v", None)
        if stack and stack[-1] is self:
            stack.pop()
        if self._t0 is not None:
            account(self.category, now - self._t0, self.job)
            self._t0 = None
        self._open = False
        if stack:
            stack[-1]._t0 = now  # outer resumes accruing


# -- jit compile detection ---------------------------------------------------


def _abstract_one(x: Any) -> Any:
    """Shape/dtype abstraction of one argument — what jax retraces on.
    Values of python scalars don't retrigger tracing, so only their type
    participates; arrays/pytrees reduce to dtype+shape structure."""
    shape = getattr(x, "shape", None)
    dtype = getattr(x, "dtype", None)
    if shape is not None:
        return ("arr", str(dtype), tuple(shape))
    if isinstance(x, dict):
        return ("dict", tuple(sorted(
            (str(k), _abstract_one(v)) for k, v in x.items())))
    if isinstance(x, (tuple, list)):
        return ("seq", tuple(_abstract_one(v) for v in x))
    return ("py", type(x).__name__)


def abstract_signature(args: tuple, kwargs: dict) -> Tuple:
    return (_abstract_one(list(args)), _abstract_one(kwargs))


def instrument_jit(fn: Callable, name: Optional[str] = None,
                   job: Optional[str] = None) -> Callable:
    """Wrap a jitted callable with first-trace compile detection.

    The first call per abstract argument signature (shapes/dtypes —
    what XLA keys its executable cache on) is attributed to the
    ``compile`` category and counted; a *second* distinct signature for
    the same function is a recompile (the runtime mirror of lint rule
    R21's static shape-stability check) and additionally bumps
    ``recompile_count``.  Steady-state calls pass straight through —
    their time is the step-level ``compute`` accounting's job, so
    nothing is double-counted.
    """
    label = name or getattr(fn, "__name__", "jit") or "jit"

    def wrapper(*args: Any, **kwargs: Any):
        if not ENABLED:
            return fn(*args, **kwargs)
        sig = (label, abstract_signature(args, kwargs))
        led = _ledger(job)
        if sig in led.signatures:
            return fn(*args, **kwargs)
        t0 = time.monotonic()
        with interval("compile", job):
            out = fn(*args, **kwargs)
        dur_ms = (time.monotonic() - t0) * 1e3
        with _lock:
            recompile = any(s[0] == label for s in led.signatures)
            led.signatures.add(sig)
            led.compile_count += 1
            if recompile:
                led.recompile_count += 1
        from ray_tpu.observability import perf
        if perf.ENABLED:
            perf.observe("jit.compile", dur_ms)
        return out

    wrapper.__name__ = getattr(fn, "__name__", "jit")
    wrapper.__wrapped__ = fn  # type: ignore[attr-defined]
    return wrapper


# -- read side ---------------------------------------------------------------


def goodput_pct(cats: Dict[str, float]) -> float:
    """Percent of wall-clock spent in ``compute`` (wall = the category
    sum, idle included)."""
    wall = sum(float(v) for v in cats.values())
    if wall <= 0.0:
        return 0.0
    return 100.0 * float(cats.get("compute", 0.0)) / wall


def snapshot() -> Dict[str, object]:
    """This process's ledgers — the unit that federates.  ``idle`` is
    derived here (wall since start minus everything attributed), so the
    categories sum to ``wall_s`` exactly."""
    now = time.monotonic()
    with _lock:
        jobs: Dict[str, Dict[str, object]] = {}
        for j, led in _ledgers.items():
            attributed = sum(led.acc.values())
            wall = max(now - led.t0, attributed)
            cats = dict(led.acc)
            cats["idle"] = wall - attributed
            jobs[j] = {
                "wall_s": wall,
                "cats": cats,
                "goodput_pct": goodput_pct(cats),
                "compile_count": led.compile_count,
                "recompile_count": led.recompile_count,
            }
    return {"jobs": jobs}


def reset() -> None:
    """Drop every ledger (tests re-enter with a clean slate)."""
    with _lock:
        _ledgers.clear()


def merge_payloads(payloads: Iterable[Dict[str, object]]
                   ) -> Dict[str, Dict[str, object]]:
    """Cross-node federation math: per-job category seconds and wall
    (node-seconds) add; ``goodput_pct`` is recomputed from the merged
    categories, never averaged from per-node percentages."""
    jobs: Dict[str, Dict[str, object]] = {}
    for payload in payloads:
        if not isinstance(payload, dict):
            continue
        for job, rec in (payload.get("jobs") or {}).items():
            if not isinstance(rec, dict):
                continue
            agg = jobs.get(job)
            if agg is None:
                agg = jobs[job] = {
                    "wall_s": 0.0,
                    "cats": {c: 0.0 for c in CATEGORIES},
                    "compile_count": 0,
                    "recompile_count": 0,
                    "nodes": 0,
                }
            agg["wall_s"] += float(rec.get("wall_s", 0.0))
            for c, v in (rec.get("cats") or {}).items():
                agg["cats"][c] = agg["cats"].get(c, 0.0) + float(v)
            agg["compile_count"] += int(rec.get("compile_count", 0))
            agg["recompile_count"] += int(rec.get("recompile_count", 0))
            agg["nodes"] += 1
    for agg in jobs.values():
        agg["goodput_pct"] = goodput_pct(agg["cats"])
    return jobs


# -- export ------------------------------------------------------------------


def families() -> List[Dict[str, object]]:
    """Metrics-snapshot family dicts: one gauge per (job, category),
    plus the raw ``"goodput"`` payload riding the JSON federation the
    same way perf's ``"perf"`` key does."""
    snap = snapshot()
    jobs = snap["jobs"]
    if not jobs:
        return []
    samples = []
    for job, rec in sorted(jobs.items()):  # type: ignore[union-attr]
        for cat in CATEGORIES:
            samples.append(["raytpu_goodput_seconds",
                            [["job", job], ["category", cat]],
                            float(rec["cats"].get(cat, 0.0))])
    return [{
        "name": "raytpu_goodput_seconds",
        "type": "gauge",
        "help": "goodput ledger wall-clock attribution per job/category (s)",
        "samples": samples,
        "goodput": snap,
    }]


def extract_goodput(families_list: Iterable[Dict[str, object]]
                    ) -> Optional[Dict[str, object]]:
    """Pull the raw ``"goodput"`` payload back out of a (possibly
    federated/JSON-round-tripped) metrics snapshot, or None."""
    for fam in families_list:
        p = fam.get("goodput") if isinstance(fam, dict) else None
        if isinstance(p, dict) and "jobs" in p:
            return p
    return None


def _register() -> None:
    from ray_tpu.util import metrics
    metrics.register_sample_source(families)


_register()
