"""Low-overhead periodic stack sampler (pure-Python, per process).

A daemon thread wakes at ``perf_sampler_hz`` and walks
``sys._current_frames()``, folding each thread's stack into a
``file:func;file:func;...`` string (root first) and bumping its count.
Cost per tick is a few frame-pointer chases per live thread — at the
default ~19 Hz that is well under the 2% overhead budget enforced by
``bench_micro.py``'s ``sampler_overhead_pct`` row.

Trace tagging: when :data:`TAGGING` is on, ``observability.span`` pushes
the active trace id into a per-thread stack here on enter and pops on
exit; a sample that lands while a thread is inside a span is attributed
to that trace.  The hooks are two dict operations and only run when a
sampler wants them, so tracing's own overhead budget is unaffected.

Profiles are cumulative since :func:`start` (or the last
:func:`reset`).  Windowed profiles — ``/api/profile?seconds=N`` — are
computed by the dashboard head as the difference of two cumulative
snapshots, which keeps this module free of timers and the wire protocol
free of new fields.
"""

from __future__ import annotations

import os
import sys
import threading
import time
from typing import Dict, List, Optional, Tuple

from ray_tpu._private.config import _config

# Flipped by start()/stop(); observability.span consults it before
# touching the trace-stack map so span cost stays flat when no sampler
# is running.
TAGGING: bool = False

# tid -> stack of active trace ids for that thread.  Mutated only by the
# owning thread (span enter/exit), read by the sampler thread; every
# operation is a single dict/list op under the GIL.
_trace_stacks: Dict[int, List[str]] = {}


def note_span_enter(trace_id: str) -> None:
    _trace_stacks.setdefault(threading.get_ident(), []).append(trace_id)  # raylint: allow(data-race) single dict/list op under the GIL (see module note); the sampler reads a best-effort snapshot


def note_span_exit() -> None:
    tid = threading.get_ident()
    stack = _trace_stacks.get(tid)
    if stack:
        stack.pop()
        if not stack:
            _trace_stacks.pop(tid, None)  # raylint: allow(data-race) single dict op under the GIL (see module note); the sampler reads a best-effort snapshot


_MAX_DEPTH = 64


class StackSampler:
    """One sampling thread; counts keyed (folded stack, trace id)."""

    def __init__(self, hz: float):
        self.hz = float(hz)
        self._counts: Dict[Tuple[str, str], int] = {}  # raylint: guarded-by(self._lock)
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._started_s = 0.0
        self._ticks = 0  # raylint: guarded-by(self._lock)

    # -- lifecycle -------------------------------------------------------

    def start(self) -> "StackSampler":
        if self._thread is not None:
            return self
        self._started_s = time.time()
        self._thread = threading.Thread(
            target=self._run, name="perf-sampler", daemon=True)
        self._thread.start()
        global TAGGING
        TAGGING = True
        return self

    def stop(self) -> None:
        global TAGGING
        TAGGING = False
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=2.0)
        self._thread = None

    # -- sampling loop ---------------------------------------------------

    def _run(self) -> None:
        interval = 1.0 / max(self.hz, 0.1)
        me = threading.get_ident()
        while not self._stop.wait(interval):
            self._sample_once(me)

    def _sample_once(self, skip_tid: int) -> None:
        frames = sys._current_frames()
        rows: List[Tuple[str, str]] = []
        for tid, frame in frames.items():
            if tid == skip_tid:
                continue
            parts: List[str] = []
            f = frame
            depth = 0
            while f is not None and depth < _MAX_DEPTH:
                code = f.f_code
                parts.append(
                    f"{os.path.basename(code.co_filename)}:{code.co_name}")
                f = f.f_back
                depth += 1
            parts.reverse()
            stack = _trace_stacks.get(tid)
            trace = stack[-1] if stack else ""
            rows.append((";".join(parts), trace))
        del frames
        with self._lock:
            self._ticks += 1
            for key in rows:
                self._counts[key] = self._counts.get(key, 0) + 1

    # -- read side -------------------------------------------------------

    def snapshot(self) -> Dict[str, object]:
        with self._lock:
            samples = [{"stack": k[0], "trace": k[1], "count": c}
                       for k, c in sorted(self._counts.items())]
            ticks = self._ticks
        return {
            "hz": self.hz,
            "ticks": ticks,
            "since_s": self._started_s,
            "duration_s": (time.time() - self._started_s
                           if self._started_s else 0.0),
            "samples": samples,
        }

    def reset(self) -> None:
        with self._lock:
            self._counts.clear()
            self._ticks = 0
            self._started_s = time.time()


# -- profile post-processing (also used head-side on federated dicts) --------


def diff_profiles(newer: Dict[str, object],
                  older: Dict[str, object]) -> Dict[str, object]:
    """``newer - older`` per (stack, trace) key: the samples that landed
    in the window between two cumulative snapshots."""
    base: Dict[Tuple[str, str], int] = {
        (str(s["stack"]), str(s.get("trace", ""))): int(s["count"])
        for s in older.get("samples", [])}  # type: ignore[union-attr]
    out = []
    for s in newer.get("samples", []):  # type: ignore[union-attr]
        key = (str(s["stack"]), str(s.get("trace", "")))
        delta = int(s["count"]) - base.get(key, 0)
        if delta > 0:
            out.append({"stack": key[0], "trace": key[1], "count": delta})
    return {
        "hz": newer.get("hz"),
        "ticks": int(newer.get("ticks", 0)) - int(older.get("ticks", 0)),
        "duration_s": (float(newer.get("duration_s", 0.0))
                       - float(older.get("duration_s", 0.0))),
        "samples": out,
    }


def merge_profiles(parts: List[Dict[str, object]]) -> Dict[str, object]:
    """Sum same-keyed samples across processes/hosts."""
    counts: Dict[Tuple[str, str], int] = {}
    ticks = 0
    for p in parts:
        ticks += int(p.get("ticks", 0))
        for s in p.get("samples", []):  # type: ignore[union-attr]
            key = (str(s["stack"]), str(s.get("trace", "")))
            counts[key] = counts.get(key, 0) + int(s["count"])
    return {"ticks": ticks,
            "samples": [{"stack": k[0], "trace": k[1], "count": c}
                        for k, c in sorted(counts.items())]}


def collapsed(profile: Dict[str, object]) -> str:
    """Brendan-Gregg collapsed-stack text (``stack count`` per line),
    trace tags folded together — feed straight to flamegraph.pl."""
    agg: Dict[str, int] = {}
    for s in profile.get("samples", []):  # type: ignore[union-attr]
        agg[str(s["stack"])] = agg.get(str(s["stack"]), 0) + int(s["count"])
    return "\n".join(f"{stack} {c}" for stack, c in sorted(agg.items()))


def pprof_json(profile: Dict[str, object]) -> Dict[str, object]:
    """pprof-shaped JSON: sample_type header + location-list samples."""
    samples = []
    for s in profile.get("samples", []):  # type: ignore[union-attr]
        row: Dict[str, object] = {
            "location": str(s["stack"]).split(";"),
            "value": [int(s["count"])],
        }
        if s.get("trace"):
            row["trace_id"] = s["trace"]
        samples.append(row)
    return {"sample_type": [{"type": "samples", "unit": "count"}],
            "period": (1.0 / float(profile["hz"])
                       if profile.get("hz") else None),
            "samples": samples}


# -- process-wide singleton --------------------------------------------------

_sampler: Optional[StackSampler] = None
_sampler_lock = threading.Lock()


def start(hz: Optional[float] = None) -> Optional[StackSampler]:
    """Start (or return) the process sampler.  ``hz`` defaults to the
    ``perf_sampler_hz`` knob; <= 0 disables and returns None."""
    global _sampler
    if hz is None:
        hz = float(_config.get("perf_sampler_hz"))
    if hz <= 0:
        return None
    with _sampler_lock:
        if _sampler is None:
            _sampler = StackSampler(hz).start()  # raylint: allow(data-race) get_sampler's unlocked peek is a GIL-atomic read of the singleton
        return _sampler


def stop() -> None:
    global _sampler
    with _sampler_lock:
        s = _sampler
        _sampler = None  # raylint: allow(data-race) get_sampler's unlocked peek is a GIL-atomic read of the singleton
    if s is not None:
        s.stop()


def get_sampler() -> Optional[StackSampler]:
    return _sampler


def profile_snapshot() -> Optional[Dict[str, object]]:
    """The running sampler's cumulative profile, or None."""
    s = _sampler
    return s.snapshot() if s is not None else None
