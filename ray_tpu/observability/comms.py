"""Communication observability plane: the comms ledger.

The task plane can already explain itself (tracing, perf histograms,
goodput attribution); the communication fabric could not — a slow rank
or a degraded peer link surfaced only as undifferentiated
``collective_wait`` goodput.  This module is the per-process comms
ledger behind ``/api/comms``, ``ray-tpu top --comms`` and the doctor's
COMMS section:

- **Op ledger** — every collective op through the public API records
  (group, seq, op, bytes, wire_bytes, dtype, duration) and derives
  algorithm / bus bandwidth NCCL-tests-style (busbw = algbw x 2(n-1)/n
  for allreduce, (n-1)/n for allgather/reducescatter, 1 otherwise).
  ``bytes`` is the logical tensor size; ``wire_bytes`` is what crossed
  the link (quantized payload + scales for compressed groups), and
  algbw/busbw rate the wire while ``logical_gbps`` /
  ``compression_ratio`` keep the application-side view honest.

- **Arrival-skew attribution** — every rank stamps its arrival at the
  rendezvous; the last arrival converts the stamps into per-rank
  "how late after the first arrival" observations.  Those land in
  fixed-layout bucket histograms (``perf.bucket_bounds()``), so the
  cluster merge is exact count addition and ``skew_flags`` can name
  the laggard rank: p95 skew >= ``factor`` x the median of the other
  ranks (and >= 1 ms, below which skew is not actionable).

- **Collective-fingerprint check** — ranks publish (op, shape, dtype)
  per (group, seq); a mismatch raises :class:`CollectiveDivergenceError`
  carrying *both* fingerprints instead of letting the group hang.
  This is the runtime mirror of lint rule R12 (same-op-order check).

- **Link matrix** — ``StripedTransfer`` feeds per peer x consumer
  observed bytes/seconds/chunks plus retry and failover counts;
  GB/s is derived at snapshot time, never stored.

Everything federates exactly like goodput: ``families()`` exports one
gauge family plus the raw payload under a ``"comms"`` key that rides
``/api/metrics`` untouched; the head extracts per-node payloads and
``merge_payloads`` adds seconds/bytes/counts and *recomputes* derived
bandwidths — merged values are exact, never averaged.

Off by knob (``comms_enabled``) the plane is a module-bool check per
op, the same fast-path contract as chaos/tracing/perf/goodput.
"""

from __future__ import annotations

import threading
from bisect import bisect_left
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

from ray_tpu._private.config import _config
from ray_tpu.observability import perf
from ray_tpu.observability.metric_names import COMMS_FAMILY

ENABLED: bool = bool(_config.get("comms_enabled"))


def enable() -> None:
    global ENABLED
    _config.set("comms_enabled", True)
    ENABLED = True


def disable() -> None:
    global ENABLED
    _config.set("comms_enabled", False)
    ENABLED = False


# -- divergence --------------------------------------------------------------


class CollectiveDivergenceError(RuntimeError):
    """Two ranks brought different collectives to the same rendezvous.

    Without the check the group either hangs (cross-process) or computes
    with whichever op description arrived last (threaded rendezvous).
    The error names both ranks and carries both fingerprints so the
    divergence is debuggable from either side.
    """

    def __init__(self, group: str, seq: int,
                 rank_a: int, fp_a: Tuple, rank_b: int, fp_b: Tuple):
        self.group = group
        self.seq = seq
        self.rank_a, self.fingerprint_a = rank_a, fp_a
        self.rank_b, self.fingerprint_b = rank_b, fp_b
        super().__init__(
            f"collective divergence in group {group!r} seq {seq}: "
            f"rank {rank_a} submitted {fp_a!r} but rank {rank_b} "
            f"submitted {fp_b!r} (runtime mirror of lint R12: every rank "
            f"must issue the same collective in the same order)")


def fingerprint(op: Any, shape: Sequence[int], dtype: Any,
                scheme: Any = "none", block: int = 0) -> Tuple:
    """(op, shape, dtype, scheme, block) identity of one rank's collective
    submission. ``scheme``/``block`` are the compression identity
    (``CollectiveConfig``): a rank quantizing q8 payloads into a
    rendezvous where another rank submits f32 is a divergence exactly
    like an op or shape mismatch — the reduction would silently mix
    payload types — so both schemes are named in the raised error."""
    return (str(op), tuple(int(s) for s in shape), str(dtype),
            str(scheme), int(block))


def check_fingerprints(fps: Dict[int, Tuple], group: str = "default",
                       seq: int = 0) -> None:
    """Raise :class:`CollectiveDivergenceError` unless all ranks agree."""
    if not ENABLED or len(fps) < 2:
        return
    it = iter(sorted(fps.items()))
    rank_a, fp_a = next(it)
    for rank_b, fp_b in it:
        if tuple(fp_b) != tuple(fp_a):
            _count_mismatch(group)
            raise CollectiveDivergenceError(group, seq, rank_a, tuple(fp_a),
                                            rank_b, tuple(fp_b))


# -- ledger state ------------------------------------------------------------

# busbw = algbw x factor(world); factors from nccl-tests' performance doc.
_BUSBW = {
    "allreduce": lambda n: 2.0 * (n - 1) / n if n else 1.0,
    "allgather": lambda n: (n - 1) / n if n else 1.0,
    "reducescatter": lambda n: (n - 1) / n if n else 1.0,
}

_RECENT_CAP = 64

_lock = threading.Lock()
_groups: Dict[str, Dict[str, Any]] = {}
_links: Dict[Tuple[str, str], Dict[str, float]] = {}
_recent: List[List[Any]] = []


def _group(name: str) -> Dict[str, Any]:
    g = _groups.get(name)
    if g is None:
        g = _groups[name] = {
            "world_size": 0,
            "seq": 0,
            "mismatches": 0,
            "ops": {},    # op -> {count, bytes, seconds}
            "ranks": {},  # str(rank) -> {arrivals, counts, sum_ms}
        }
    return g


def _count_mismatch(group: str) -> None:
    with _lock:
        _group(group)["mismatches"] += 1


def record_op(group: str, op: str, nbytes: int, dtype: str,
              seconds: float, world_size: int = 0,
              seq: Optional[int] = None,
              wire_bytes: Optional[int] = None) -> None:
    """One completed collective into the op ledger (bandwidths are
    derived at snapshot time from the summed bytes/seconds).

    ``nbytes`` is the *logical* tensor size; ``wire_bytes`` is what
    actually crossed the link when the op shipped compressed payloads
    (quantized blocks + scales). None means wire == logical. Keeping
    both is what makes the ledger honest for compressed collectives:
    algbw/busbw derive from wire bytes (real link usage), while the
    logical rate and the wire/logical compression ratio are derived
    alongside so ``top --comms`` can show all three."""
    if not ENABLED:
        return
    with _lock:
        g = _group(group)
        if world_size:
            g["world_size"] = int(world_size)
        if seq is None:
            seq = g["seq"]
        g["seq"] = max(g["seq"], int(seq) + 1)
        rec = g["ops"].get(op)
        if rec is None:
            rec = g["ops"][op] = {"count": 0, "bytes": 0, "wire_bytes": 0,
                                  "seconds": 0.0}
        rec["count"] += 1
        rec["bytes"] += int(nbytes)
        rec["wire_bytes"] += int(nbytes if wire_bytes is None
                                 else wire_bytes)
        rec["seconds"] += float(seconds)
        _recent.append([group, int(seq), op, int(nbytes), str(dtype),
                        float(seconds) * 1e3])
        del _recent[:-_RECENT_CAP]


def record_arrivals(group: str, skew_by_rank: Dict[int, float],
                    world_size: int = 0) -> None:
    """Per-rank arrival skew (seconds after the first arrival) for one
    rendezvous, folded into fixed-layout lateness histograms."""
    if not ENABLED:
        return
    bounds = perf.bucket_bounds()
    with _lock:
        g = _group(group)
        if world_size:
            g["world_size"] = int(world_size)
        for rank, skew_s in skew_by_rank.items():
            r = g["ranks"].get(str(rank))
            if r is None:
                r = g["ranks"][str(rank)] = {
                    "arrivals": 0, "counts": [0] * len(bounds),
                    "sum_ms": 0.0}
            ms = max(0.0, float(skew_s)) * 1e3
            r["arrivals"] += 1
            r["counts"][bisect_left(bounds, ms)] += 1
            r["sum_ms"] += ms


def link_observe(peer: str, consumer: str, *, nbytes: int = 0,
                 seconds: float = 0.0, chunks: int = 0,
                 retries: int = 0, failovers: int = 0) -> None:
    """Fold one striped-transfer observation into the peer x consumer
    link matrix (GB/s derived at snapshot, never stored)."""
    if not ENABLED:
        return
    key = (str(peer), str(consumer))
    with _lock:
        rec = _links.get(key)
        if rec is None:
            rec = _links[key] = {"bytes": 0, "seconds": 0.0, "chunks": 0,
                                 "retries": 0, "failovers": 0}
        rec["bytes"] += int(nbytes)
        rec["seconds"] += float(seconds)
        rec["chunks"] += int(chunks)
        rec["retries"] += int(retries)
        rec["failovers"] += int(failovers)


# -- snapshot / merge --------------------------------------------------------


def _derive_ops(ops: Dict[str, Dict[str, Any]],
                world: int) -> Dict[str, Dict[str, Any]]:
    out: Dict[str, Dict[str, Any]] = {}
    for op, rec in ops.items():
        secs = float(rec.get("seconds", 0.0))
        nbytes = int(rec.get("bytes", 0))
        # pre-compression records carry no wire column: wire == logical
        wire = int(rec.get("wire_bytes", nbytes) or nbytes)
        algbw = (wire / secs / 1e9) if secs > 0 else 0.0
        factor = _BUSBW.get(op, lambda n: 1.0)(world)
        out[op] = {"count": int(rec.get("count", 0)), "bytes": nbytes,
                   "wire_bytes": wire, "seconds": secs,
                   # algbw/busbw rate the LINK (wire bytes); logical_gbps
                   # rates the application-visible tensor throughput —
                   # for compressed ops it exceeds algbw by 1/ratio
                   "algbw_gbps": algbw, "busbw_gbps": algbw * factor,
                   "logical_gbps": (nbytes / secs / 1e9) if secs > 0
                   else 0.0,
                   "compression_ratio": (wire / nbytes) if nbytes else 1.0}
    return out


def _derive_links(links: Dict[str, Dict[str, Any]]) -> Dict[str, Dict[str, Any]]:
    out: Dict[str, Dict[str, Any]] = {}
    for key, rec in links.items():
        secs = float(rec.get("seconds", 0.0))
        nbytes = int(rec.get("bytes", 0))
        d = dict(rec)
        d["gbps"] = (nbytes / secs / 1e9) if secs > 0 else 0.0
        out[key] = d
    return out


def snapshot() -> Dict[str, Any]:
    """JSON-safe copy of this process's ledger: groups (ops + per-rank
    lateness histograms + histogram bounds), link matrix, recent ops."""
    with _lock:
        groups: Dict[str, Any] = {}
        for name, g in _groups.items():
            groups[name] = {
                "world_size": g["world_size"],
                "seq": g["seq"],
                "mismatches": g["mismatches"],
                "ops": _derive_ops(g["ops"], g["world_size"]),
                "ranks": {r: dict(rec, counts=list(rec["counts"]))
                          for r, rec in g["ranks"].items()},
            }
        payload: Dict[str, Any] = {
            "groups": groups,
            "links": {f"{p}|{c}": dict(rec)
                      for (p, c), rec in _links.items()},
            "recent": [list(r) for r in _recent],
        }
    payload["bounds"] = list(perf.bucket_bounds()[:-1])  # drop the inf cap
    return payload


def reset() -> None:
    with _lock:
        _groups.clear()
        _links.clear()
        del _recent[:]


def merge_payloads(payloads: Iterable[Dict[str, Any]]) -> Dict[str, Any]:
    """Exact cluster merge of per-node ``snapshot()`` payloads: bytes,
    seconds, counts and bucket counts add; bandwidths are recomputed
    from the sums (never averaged).  Malformed payloads are skipped —
    a degraded node must not poison the fleet view."""
    groups: Dict[str, Dict[str, Any]] = {}
    links: Dict[str, Dict[str, float]] = {}
    recent: List[List[Any]] = []
    bounds: Optional[List[float]] = None
    for p in payloads:
        if not isinstance(p, dict):
            continue
        if bounds is None and isinstance(p.get("bounds"), list):
            bounds = list(p["bounds"])
        for name, g in (p.get("groups") or {}).items():
            if not isinstance(g, dict):
                continue
            m = groups.setdefault(name, {"world_size": 0, "seq": 0,
                                         "mismatches": 0, "ops": {},
                                         "ranks": {}})
            m["world_size"] = max(m["world_size"],
                                  int(g.get("world_size") or 0))
            m["seq"] = max(m["seq"], int(g.get("seq") or 0))
            m["mismatches"] += int(g.get("mismatches") or 0)
            for op, rec in (g.get("ops") or {}).items():
                if not isinstance(rec, dict):
                    continue
                t = m["ops"].setdefault(op, {"count": 0, "bytes": 0,
                                             "wire_bytes": 0,
                                             "seconds": 0.0})
                t["count"] += int(rec.get("count") or 0)
                t["bytes"] += int(rec.get("bytes") or 0)
                # nodes predating the wire column report wire == logical
                t["wire_bytes"] += int(rec.get("wire_bytes")
                                       or rec.get("bytes") or 0)
                t["seconds"] += float(rec.get("seconds") or 0.0)
            for rank, rec in (g.get("ranks") or {}).items():
                if not isinstance(rec, dict):
                    continue
                t = m["ranks"].get(rank)
                if t is None:
                    t = m["ranks"][rank] = {"arrivals": 0, "counts": [],
                                            "sum_ms": 0.0}
                t["arrivals"] += int(rec.get("arrivals") or 0)
                t["counts"] = perf.merge_counts(
                    [t["counts"], rec.get("counts") or []])
                t["sum_ms"] += float(rec.get("sum_ms") or 0.0)
        for key, rec in (p.get("links") or {}).items():
            if not isinstance(rec, dict):
                continue
            t = links.setdefault(key, {"bytes": 0, "seconds": 0.0,
                                       "chunks": 0, "retries": 0,
                                       "failovers": 0})
            for k in t:
                t[k] += type(t[k])(rec.get(k) or 0)
        if isinstance(p.get("recent"), list):
            recent.extend(r for r in p["recent"] if isinstance(r, list))
    for g in groups.values():
        g["ops"] = _derive_ops(g["ops"], g["world_size"])
    return {"groups": groups, "links": _derive_links(links),
            "recent": recent[-_RECENT_CAP:], "bounds": bounds}


# -- attribution -------------------------------------------------------------


def skew_report(groups: Dict[str, Any],
                bounds: Optional[Sequence[float]] = None) -> Dict[str, Any]:
    """Per-group, per-rank arrival-skew summaries (count/mean/p50/p95/p99
    ms) from the merged lateness histograms."""
    if bounds is not None:
        bounds = tuple(bounds) + (float("inf"),)
    out: Dict[str, Any] = {}
    for name, g in (groups or {}).items():
        ranks = {}
        for rank, rec in (g.get("ranks") or {}).items():
            ranks[rank] = perf.summarize(rec.get("counts") or [],
                                         float(rec.get("sum_ms") or 0.0),
                                         bounds)
        if ranks:
            out[name] = ranks
    return out


def skew_flags(groups: Dict[str, Any], factor: float = 3.0,
               min_ms: float = 1.0, min_samples: int = 3,
               bounds: Optional[Sequence[float]] = None
               ) -> List[Dict[str, Any]]:
    """Name laggard ranks: p95 arrival skew >= ``factor`` x the median of
    the *other* ranks' p95 (robust at world-size 2, where a global
    median would be half-poisoned by the laggard itself) and >= ``min_ms``
    (sub-millisecond skew is noise, not a straggler)."""
    import statistics
    flags: List[Dict[str, Any]] = []
    for name, ranks in skew_report(groups, bounds).items():
        if len(ranks) < 2:
            continue
        for rank, summ in sorted(ranks.items()):
            if summ["count"] < min_samples:
                continue
            others = [s["p95_ms"] for r, s in ranks.items() if r != rank]
            med = statistics.median(others)
            p95 = summ["p95_ms"]
            if p95 >= min_ms and p95 >= factor * max(med, 1e-6):
                flags.append({"group": name, "rank": rank,
                              "p95_ms": p95, "median_ms": med,
                              "samples": int(summ["count"])})
    return flags


def link_flags(links: Dict[str, Any], factor: float = 3.0,
               min_chunks: int = 3) -> List[Dict[str, Any]]:
    """Name degraded links: any failover, or observed GB/s below
    1/``factor`` of the median of the other links (>= 2 comparable
    links with >= ``min_chunks`` chunks each, so a lone cold link is
    not an outlier of itself)."""
    import statistics
    flags: List[Dict[str, Any]] = []
    rated = {k: rec for k, rec in (links or {}).items()
             if isinstance(rec, dict)
             and int(rec.get("chunks") or 0) >= min_chunks}
    for key, rec in sorted((links or {}).items()):
        if not isinstance(rec, dict):
            continue
        reasons = []
        if int(rec.get("failovers") or 0) > 0:
            reasons.append(f"{rec['failovers']} failover(s)")
        others = [float(r.get("gbps") or 0.0)
                  for k, r in rated.items() if k != key]
        if (key in rated and len(others) >= 1 and len(rated) >= 2):
            med = statistics.median(others)
            gbps = float(rec.get("gbps") or 0.0)
            if med > 0 and gbps < med / factor:
                reasons.append(
                    f"{gbps:.2f} GB/s vs link median {med:.2f}")
        if reasons:
            peer, _, consumer = key.partition("|")
            flags.append({"link": key, "peer": peer, "consumer": consumer,
                          "gbps": float(rec.get("gbps") or 0.0),
                          "retries": int(rec.get("retries") or 0),
                          "failovers": int(rec.get("failovers") or 0),
                          "why": "; ".join(reasons)})
    return flags


# -- federation --------------------------------------------------------------


def families() -> List[Dict[str, Any]]:
    """Export for the metrics endpoint: one gauge family (per-group,
    per-op bytes moved) plus the raw ledger under the ``"comms"`` key,
    which rides the JSON federation untouched for exact cluster merge
    (the goodput pattern)."""
    snap = snapshot()
    if not snap["groups"] and not snap["links"]:
        return []
    samples = []
    for gname, g in snap["groups"].items():
        for op, rec in g["ops"].items():
            # Tag cardinality is bounded: group names and op names are
            # small fixed sets chosen by the application, not ids.
            samples.append([COMMS_FAMILY,
                            [["group", gname], ["op", op]],
                            float(rec["bytes"])])
    return [{
        "name": COMMS_FAMILY,
        "type": "gauge",
        "help": "bytes moved per collective group x op (comms ledger)",
        "samples": samples,
        "comms": snap,
    }]


def extract_comms(families_list: Any) -> Optional[Dict[str, Any]]:
    """Recover the raw comms payload from a node's /api/metrics families."""
    if not isinstance(families_list, list):
        return None
    for fam in families_list:
        if isinstance(fam, dict) and fam.get("name") == COMMS_FAMILY:
            payload = fam.get("comms")
            if isinstance(payload, dict):
                return payload
    return None


def _register() -> None:
    from ray_tpu.util import metrics
    metrics.register_sample_source(families)


_register()
