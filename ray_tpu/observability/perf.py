"""Continuous performance plane: streaming log-scale latency histograms.

Every hot path — RPC call/connect, task submit→execute, object fetch/push
(per-chunk and per-stripe), checkpoint save/hash/write/commit, serve
dispatch, drain migration — feeds a fixed-bucket HDR-style histogram
here.  Design constraints, in order:

- **Hot-path cost.** A module-level ``ENABLED`` bool is the only thing
  instrumented code touches when the plane is off (the chaos/tracing
  pattern, guarded by ``bench_micro.py``'s ``perf_overhead_pct`` row).
  When on, one ``observe()`` is a bisect over ~64 precomputed bounds
  plus two writes into a shard this thread exclusively owns.
- **Lock-free recording.** Each histogram keeps one shard per writer
  thread (created once under a lock, then owned single-writer).  Readers
  merge shards without stopping writers; a merge may miss an in-flight
  increment, never corrupt one.
- **Mergeable everywhere.** Bucket bounds are fixed at geometric steps
  from 1µs to 60s (``perf_hist_buckets`` bounds, ratio ≈ 1.33 at the
  default 64 → ≤ ~16% relative quantile error), so counts add across
  threads, processes and hosts; the dashboard head federates raw counts
  and computes cluster quantiles from the sum.
- **Exported two ways.** Through :func:`families` each histogram becomes
  a Prometheus ``histogram`` family (cumulative ``_bucket`` + ``_sum`` +
  ``_count``) registered as a :func:`ray_tpu.util.metrics
  .register_sample_source` extra source; each family also carries a raw
  ``"perf"`` payload (bounds + per-bucket counts) that rides the
  existing ``/api/metrics`` JSON federation untouched, so consumers
  (head, ``ray-tpu top``, doctor, ``bench_micro.py --check``) never
  parse ``le`` tags back out of sample rows.
"""

from __future__ import annotations

import math
import threading
from bisect import bisect_left
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from ray_tpu._private.config import _config

# Fast-path switch: instrumented code checks this module bool and
# nothing else when the plane is off (same pattern as chaos.ENABLED).
ENABLED: bool = bool(_config.get("perf_enabled"))

# Histogram domain: 1µs .. 60s, in milliseconds.  Bucket 0 catches
# everything at/below _MIN_MS, the last bucket is the +inf overflow.
_MIN_MS = 1e-3
_MAX_MS = 60_000.0


def enable() -> None:
    """Turn the plane on (also flips the config knob so child runtimes
    agree)."""
    global ENABLED
    _config.set("perf_enabled", True)
    ENABLED = True


def disable() -> None:
    global ENABLED
    _config.set("perf_enabled", False)
    ENABLED = False


# Dedicated lock: bucket_bounds() runs inside PerfHistogram.__init__,
# which get() constructs while holding _hists_lock — reusing that lock
# here would self-deadlock.
_bounds_lock = threading.Lock()
_bounds_cache: Optional[Tuple[float, ...]] = None  # raylint: guarded-by(_bounds_lock)
# Bumped by reset() so an in-flight bucket_bounds() compute that started
# before the reset cannot publish its now-stale layout over the fresh one.
_bounds_gen = 0  # raylint: guarded-by(_bounds_lock)


def bucket_bounds() -> Tuple[float, ...]:
    """Upper bounds (ms) of every bucket; the last is ``inf``.  Computed
    once from ``perf_hist_buckets`` so every histogram in the process —
    and, config being uniform, the cluster — shares one bucket layout."""
    global _bounds_cache
    b = _bounds_cache  # raylint: allow(guarded-by) double-checked fast path: immutable tuple publish, losers recompute
    while b is None:
        with _bounds_lock:
            gen = _bounds_gen
        n = max(8, int(_config.get("perf_hist_buckets")))
        # n-1 finite bounds spanning [_MIN_MS, _MAX_MS] geometrically.
        ratio = (_MAX_MS / _MIN_MS) ** (1.0 / (n - 2))
        b = tuple(_MIN_MS * ratio ** i for i in range(n - 1)) + (math.inf,)
        with _bounds_lock:
            if _bounds_cache is not None:
                b = _bounds_cache     # another thread won the publish
            elif gen == _bounds_gen:
                _bounds_cache = b
            else:
                b = None              # reset() raced the compute: retry
    return b


def bucket_ratio() -> float:
    b = bucket_bounds()
    return b[1] / b[0]


class _Shard:
    """Single-writer bucket counts for one thread.  No lock: only the
    owning thread mutates, readers tolerate a stale element."""

    __slots__ = ("counts", "sum_ms")

    def __init__(self, n: int):
        self.counts = [0] * n
        self.sum_ms = 0.0


class PerfHistogram:
    """One named latency distribution with per-thread shards."""

    __slots__ = ("name", "_bounds", "_local", "_shards", "_lock")

    def __init__(self, name: str):
        self.name = name
        self._bounds = bucket_bounds()
        self._local = threading.local()
        self._shards: List[_Shard] = []
        self._lock = threading.Lock()

    def observe(self, ms: float) -> None:
        shard = getattr(self._local, "shard", None)
        if shard is None:
            shard = _Shard(len(self._bounds))
            with self._lock:
                self._shards.append(shard)
            self._local.shard = shard
        # bisect_left: first bound >= ms, so a value exactly on a bucket
        # boundary lands in that bucket (Prometheus `le` semantics).
        idx = bisect_left(self._bounds, ms)
        if idx >= len(shard.counts):  # nan or beyond +inf comparison quirks
            idx = len(shard.counts) - 1
        shard.counts[idx] += 1
        shard.sum_ms += ms

    # -- read side (any thread) ------------------------------------------

    def merged(self) -> Tuple[List[int], float]:
        """(bucket counts, sum_ms) summed across shards."""
        with self._lock:
            shards = list(self._shards)
        counts = [0] * len(self._bounds)
        total_ms = 0.0
        for s in shards:
            for i, c in enumerate(s.counts):
                counts[i] += c
            total_ms += s.sum_ms
        return counts, total_ms

    def count(self) -> int:
        return sum(self.merged()[0])


_hists: Dict[str, PerfHistogram] = {}  # raylint: guarded-by(_hists_lock)
_hists_lock = threading.Lock()


def get(name: str) -> PerfHistogram:
    h = _hists.get(name)  # raylint: allow(guarded-by) double-checked fast path: re-checked under the lock below
    if h is None:
        with _hists_lock:
            h = _hists.get(name)
            if h is None:
                h = PerfHistogram(name)
                _hists[name] = h
    return h


def observe(name: str, ms: float) -> None:
    """Record one latency (milliseconds) into histogram ``name``.  No-op
    when the plane is off — but prefer gating the *timing capture* on
    ``perf.ENABLED`` at the call site so the clock reads are free too."""
    if not ENABLED:
        return
    get(name).observe(ms)


def reset() -> None:
    """Drop every histogram and the cached bounds (tests re-enter with a
    different ``perf_hist_buckets``)."""
    global _bounds_cache, _bounds_gen
    with _hists_lock:
        _hists.clear()
    with _bounds_lock:
        _bounds_cache = None
        _bounds_gen += 1


# -- quantiles ---------------------------------------------------------------


def quantile(counts: Sequence[int], q: float,
             bounds: Optional[Sequence[float]] = None) -> float:
    """Estimate the q-quantile (ms) from bucket counts.  The returned
    value is the geometric midpoint of the selected bucket, so the
    relative error is bounded by sqrt(bucket ratio) - 1 (~16% at the
    default 64 buckets)."""
    if bounds is None:
        bounds = bucket_bounds()
    total = sum(counts)
    if total <= 0:
        return 0.0
    rank = q * total
    seen = 0
    for i, c in enumerate(counts):
        seen += c
        if seen >= rank and c:
            lo = bounds[i - 1] if i > 0 else 0.0
            hi = bounds[i]
            if hi == math.inf:  # overflow bucket: best effort, report max
                return float(bounds[-2])
            if lo <= 0.0:
                return float(hi)
            return float(math.sqrt(lo * hi))
    return float(bounds[-2])


def summarize(counts: Sequence[int], sum_ms: float,
              bounds: Optional[Sequence[float]] = None) -> Dict[str, float]:
    total = sum(counts)
    return {
        "count": float(total),
        "mean_ms": (sum_ms / total) if total else 0.0,
        "p50_ms": quantile(counts, 0.50, bounds),
        "p95_ms": quantile(counts, 0.95, bounds),
        "p99_ms": quantile(counts, 0.99, bounds),
    }


def merge_counts(parts: Iterable[Sequence[int]]) -> List[int]:
    """Element-wise sum of same-layout bucket counts (cross-process or
    cross-host federation)."""
    out: List[int] = []
    for counts in parts:
        if not out:
            out = list(counts)
        else:
            for i, c in enumerate(counts):
                out[i] += c
    return out


# -- export ------------------------------------------------------------------


def snapshot() -> Dict[str, object]:
    """This process's raw histogram state — the unit that federates."""
    with _hists_lock:
        hists = list(_hists.values())
    out: Dict[str, Dict[str, object]] = {}
    for h in hists:
        counts, sum_ms = h.merged()
        if sum(counts) == 0:
            continue
        out[h.name] = {"counts": counts, "sum_ms": sum_ms}
    return {"bounds": list(bucket_bounds()), "hists": out}


def _prom_name(name: str) -> str:
    return "raytpu_perf_" + name.replace(".", "_").replace("-", "_") + "_ms"


def families() -> List[Dict[str, object]]:
    """Metrics-snapshot family dicts, one Prometheus histogram per
    PerfHistogram.  Registered as an extra sample source with
    :mod:`ray_tpu.util.metrics`; the non-standard ``"perf"`` key carries
    the raw counts through JSON federation (``render_federated`` only
    reads name/help/type/samples, so it rides along untouched)."""
    snap = snapshot()
    bounds = snap["bounds"]
    fams: List[Dict[str, object]] = []
    for name, h in sorted(snap["hists"].items()):  # type: ignore[union-attr]
        counts = h["counts"]
        sum_ms = h["sum_ms"]
        pname = _prom_name(name)
        samples = []
        cum = 0
        for i, c in enumerate(counts):
            cum += c
            le = "+Inf" if bounds[i] == math.inf else repr(bounds[i])
            samples.append([pname + "_bucket", [["le", le]], float(cum)])
        samples.append([pname + "_sum", [], float(sum_ms)])
        samples.append([pname + "_count", [], float(cum)])
        fams.append({
            "name": pname,
            "type": "histogram",
            "help": f"perf plane latency for {name} (ms)",
            "samples": samples,
            "perf": {"hist": name, "bounds": list(bounds),
                     "counts": list(counts), "sum_ms": float(sum_ms)},
        })
    return fams


def extract_perf(families_list: Iterable[Dict[str, object]]
                 ) -> Dict[str, Dict[str, object]]:
    """Pull the raw ``"perf"`` payloads back out of a (possibly
    federated/JSON-round-tripped) metrics snapshot: name -> {bounds,
    counts, sum_ms}."""
    out: Dict[str, Dict[str, object]] = {}
    for fam in families_list:
        p = fam.get("perf") if isinstance(fam, dict) else None
        if isinstance(p, dict) and "hist" in p and "counts" in p:
            out[str(p["hist"])] = p
    return out


def _register() -> None:
    from ray_tpu.util import metrics
    metrics.register_sample_source(families)


_register()
