"""Driver-script job submission.

Parity with the reference's job module
(``dashboard/modules/job/job_manager.py:305`` ``JobManager``,
``submit_job`` :449 runs the entrypoint as a supervisor-managed
subprocess; SDK ``dashboard/modules/job/sdk.py:34``
``JobSubmissionClient``). Here jobs are subprocess drivers launched and
watched by a monitor thread in the head process; stdout/stderr land in a
per-job log file; metadata persists as JSON so listings survive the
manager object.
"""

from __future__ import annotations

import json
import logging
import os
import signal
import subprocess
import threading
import time
import uuid
from dataclasses import asdict, dataclass, field
from typing import Any, Dict, List, Optional

logger = logging.getLogger("ray_tpu")


class JobStatus:
    PENDING = "PENDING"
    RUNNING = "RUNNING"
    SUCCEEDED = "SUCCEEDED"
    FAILED = "FAILED"
    STOPPED = "STOPPED"

    TERMINAL = (SUCCEEDED, FAILED, STOPPED)


@dataclass
class JobInfo:
    job_id: str
    entrypoint: str
    status: str = JobStatus.PENDING
    submission_time: float = field(default_factory=time.time)
    start_time: Optional[float] = None
    end_time: Optional[float] = None
    return_code: Optional[int] = None
    metadata: Dict[str, str] = field(default_factory=dict)
    log_path: str = ""


class JobManager:
    """Launches entrypoint subprocesses and tracks their lifecycle."""

    def __init__(self, job_dir: str = "/tmp/ray_tpu/jobs"):
        self.job_dir = job_dir
        os.makedirs(job_dir, exist_ok=True)
        self._lock = threading.Lock()
        self._jobs: Dict[str, JobInfo] = {}  # raylint: guarded-by(self._lock)
        self._procs: Dict[str, subprocess.Popen] = {}  # raylint: guarded-by(self._lock)
        self._load_persisted()

    # -- persistence (listings survive restarts, job_manager checkpoints) --

    def _meta_path(self, job_id: str) -> str:
        return os.path.join(self.job_dir, f"{job_id}.json")

    def _persist(self, info: JobInfo):
        with open(self._meta_path(info.job_id), "w") as f:
            json.dump(asdict(info), f)

    def _load_persisted(self):
        for name in os.listdir(self.job_dir):
            if not name.endswith(".json"):
                continue
            try:
                with open(os.path.join(self.job_dir, name)) as f:
                    data = json.load(f)
                info = JobInfo(**data)
                # A manager restart orphans RUNNING jobs: mark FAILED.
                if info.status not in JobStatus.TERMINAL:
                    info.status = JobStatus.FAILED
                self._jobs[info.job_id] = info
            except (json.JSONDecodeError, TypeError, OSError) as e:
                logger.warning("job manager: dropping unreadable job "
                               "record %s: %s", name, e)
                continue

    # -- API ----------------------------------------------------------------

    def submit_job(self, *, entrypoint: str,
                   submission_id: Optional[str] = None,
                   metadata: Optional[Dict[str, str]] = None,
                   env: Optional[Dict[str, str]] = None,
                   cwd: Optional[str] = None) -> str:
        job_id = submission_id or f"raytpu-job-{uuid.uuid4().hex[:10]}"
        with self._lock:
            if job_id in self._jobs and (
                    self._jobs[job_id].status not in JobStatus.TERMINAL):
                raise ValueError(f"job {job_id!r} already running")
            log_path = os.path.join(self.job_dir, f"{job_id}.log")
            info = JobInfo(job_id=job_id, entrypoint=entrypoint,
                           metadata=metadata or {}, log_path=log_path)
            self._jobs[job_id] = info
            self._persist(info)
        log_f = open(log_path, "ab")
        full_env = dict(os.environ)
        if env:
            full_env.update(env)
        proc = subprocess.Popen(
            entrypoint, shell=True, stdout=log_f, stderr=log_f,
            cwd=cwd, env=full_env, start_new_session=True)
        log_f.close()
        with self._lock:
            info.status = JobStatus.RUNNING
            info.start_time = time.time()
            self._procs[job_id] = proc
            self._persist(info)
        threading.Thread(target=self._watch, args=(job_id, proc),
                         daemon=True, name=f"job-watch-{job_id}").start()
        return job_id

    def _watch(self, job_id: str, proc: subprocess.Popen):
        rc = proc.wait()
        with self._lock:
            info = self._jobs[job_id]
            info.end_time = time.time()
            info.return_code = rc
            if info.status != JobStatus.STOPPED:
                info.status = (JobStatus.SUCCEEDED if rc == 0
                               else JobStatus.FAILED)
            self._procs.pop(job_id, None)
            self._persist(info)

    def stop_job(self, job_id: str) -> bool:
        with self._lock:
            proc = self._procs.get(job_id)
            info = self._jobs.get(job_id)
            if info is None:
                raise ValueError(f"no job {job_id!r}")
            if proc is None:
                return False
            info.status = JobStatus.STOPPED
            self._persist(info)
        try:
            os.killpg(proc.pid, signal.SIGTERM)
        except ProcessLookupError as e:
            logger.info("job manager: job %s process group already gone "
                        "during stop: %s", job_id, e)
        return True

    def get_job_status(self, job_id: str) -> str:
        with self._lock:
            info = self._jobs.get(job_id)
            if info is None:
                raise ValueError(f"no job {job_id!r}")
            return info.status

    def get_job_info(self, job_id: str) -> JobInfo:
        with self._lock:
            info = self._jobs.get(job_id)
            if info is None:
                raise ValueError(f"no job {job_id!r}")
            return info

    def get_job_logs(self, job_id: str) -> str:
        info = self.get_job_info(job_id)
        try:
            with open(info.log_path) as f:
                return f.read()
        except OSError as e:
            logger.debug("job manager: no logs for %s at %s: %s",
                         job_id, info.log_path, e)
            return ""

    def list_jobs(self) -> List[JobInfo]:
        with self._lock:
            return list(self._jobs.values())

    def wait_until_finished(self, job_id: str,
                            timeout: Optional[float] = None) -> str:
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            status = self.get_job_status(job_id)
            if status in JobStatus.TERMINAL:
                return status
            if deadline is not None and time.monotonic() > deadline:
                raise TimeoutError(f"job {job_id} still {status}")
            time.sleep(0.1)


class JobSubmissionClient:
    """SDK face (``sdk.py:34``); wraps a JobManager (in-process head)."""

    def __init__(self, manager: Optional[JobManager] = None):
        self._manager = manager or JobManager()

    def submit_job(self, *, entrypoint: str, **kwargs) -> str:
        return self._manager.submit_job(entrypoint=entrypoint, **kwargs)

    def get_job_status(self, job_id: str) -> str:
        return self._manager.get_job_status(job_id)

    def get_job_info(self, job_id: str) -> JobInfo:
        return self._manager.get_job_info(job_id)

    def get_job_logs(self, job_id: str) -> str:
        return self._manager.get_job_logs(job_id)

    def list_jobs(self) -> List[JobInfo]:
        return self._manager.list_jobs()

    def stop_job(self, job_id: str) -> bool:
        return self._manager.stop_job(job_id)

    def tail_job_logs(self, job_id: str, poll_s: float = 0.2):
        """Generator yielding new log chunks until the job terminates."""
        info = self._manager.get_job_info(job_id)
        pos = 0
        while True:
            try:
                with open(info.log_path) as f:
                    f.seek(pos)
                    chunk = f.read()
                    pos = f.tell()
            except OSError as e:
                logger.debug("job log tail: %s unreadable yet: %s",
                             info.log_path, e)
                chunk = ""
            if chunk:
                yield chunk
            if self._manager.get_job_status(job_id) in JobStatus.TERMINAL:
                break
            time.sleep(poll_s)
