from ray_tpu.job.job_manager import (JobInfo, JobManager, JobStatus,
                                     JobSubmissionClient)

__all__ = ["JobManager", "JobSubmissionClient", "JobStatus", "JobInfo"]
