"""Actors: stateful remote workers.

Parity with ``python/ray/actor.py`` (``ActorClass`` :377, ``_remote`` :657,
``ActorHandle``, ``ActorMethod``; named/detached actors; ``max_restarts`` /
``max_task_retries``). TPU-native difference: actors holding device state run
as mailbox-ordered threads inside the device-owner process, so a sharded
``jax.Array`` held by an actor stays resident in HBM across method calls
(no host round-trip) — the design goal the reference could never offer for
accelerator state (its actors are separate processes).
"""

from __future__ import annotations

import functools
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Optional

from ray_tpu._private.ids import ActorID, TaskID
from ray_tpu._private.resources import ResourceSet, resources_from_options
from ray_tpu._private.task_spec import TaskOptions, TaskSpec
from ray_tpu.object_ref import ObjectRef


@dataclass
class ActorOptions(TaskOptions):
    max_restarts: int = 0
    max_task_retries: int = 0
    max_concurrency: int = 1
    lifetime: Optional[str] = None  # None | "detached"
    namespace: Optional[str] = None
    get_if_exists: bool = False


def _build_actor_options(opts: Dict[str, Any]) -> ActorOptions:
    resources = resources_from_options(
        num_cpus=opts.get("num_cpus"),
        num_tpus=opts.get("num_tpus"),
        num_gpus=opts.get("num_gpus"),
        memory=opts.get("memory"),
        resources=opts.get("resources"),
        default_cpus=opts.get("num_cpus") if opts.get("num_cpus") is not None else 1.0,
    )
    return ActorOptions(
        resources=resources,
        max_retries=0,
        scheduling_strategy=opts.get("scheduling_strategy", "DEFAULT"),
        placement_group=opts.get("placement_group"),
        placement_group_bundle_index=opts.get("placement_group_bundle_index", -1),
        name=opts.get("name"),
        runtime_env=opts.get("runtime_env"),
        max_restarts=opts.get("max_restarts", 0),
        max_task_retries=opts.get("max_task_retries", 0),
        max_concurrency=opts.get("max_concurrency", 1),
        lifetime=opts.get("lifetime"),
        namespace=opts.get("namespace"),
        get_if_exists=opts.get("get_if_exists", False),
    )


class ActorMethod:
    def __init__(self, handle: "ActorHandle", method_name: str,
                 num_returns: int = 1):
        self._handle = handle
        self._method_name = method_name
        self._num_returns = num_returns

    def options(self, **updates) -> "ActorMethod":
        m = ActorMethod(self._handle, self._method_name,
                        updates.get("num_returns", self._num_returns))
        return m

    def remote(self, *args, **kwargs):
        return self._handle._submit_method(
            self._method_name, args, kwargs, num_returns=self._num_returns)

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"Actor method {self._method_name} cannot be called directly; "
            "use .remote()")


class ActorHandle:
    def __init__(self, actor_id: ActorID, cls_name: str):
        self._actor_id = actor_id
        self._cls_name = cls_name

    @classmethod
    def _from_state(cls, state) -> "ActorHandle":
        return cls(state.actor_id, state.cls.__name__)

    def __getattr__(self, name: str) -> ActorMethod:
        if name.startswith("_") and not name.startswith("__ray"):
            raise AttributeError(name)
        return ActorMethod(self, name)

    def _submit_method(self, method_name: str, args, kwargs,
                       num_returns: int = 1):
        from ray_tpu._private import worker as _worker
        w = _worker.global_worker()
        runtime = w.runtime
        state = runtime.actors.get(self._actor_id)
        opts = TaskOptions(
            num_returns=num_returns,
            resources=ResourceSet(),
            max_retries=(state.options.max_task_retries if state else 0),
        )
        spec = TaskSpec(
            task_id=TaskID.for_actor_task(runtime.job_id, self._actor_id),
            job_id=runtime.job_id,
            function=None,  # looked up on the instance
            function_name=f"{self._cls_name}.{method_name}",
            args=tuple(args),
            kwargs=dict(kwargs),
            options=opts,
            actor_id=self._actor_id,
            method_name=method_name,
        )
        return_ids = runtime.submit_actor_task(self._actor_id, spec)
        refs = [ObjectRef(rid, owner=runtime) for rid in return_ids]
        if num_returns == 1:
            return refs[0]
        return refs

    def ready(self):
        """Returns a ref that resolves when the actor finished __init__."""
        return self._submit_method("__ray_ready__", (), {})

    def __reduce__(self):
        return (ActorHandle, (self._actor_id, self._cls_name))

    def __repr__(self):
        return f"ActorHandle({self._cls_name}, {self._actor_id.hex()[:8]})"


class ActorClass:
    def __init__(self, cls: type, options: Optional[Dict[str, Any]] = None):
        self._cls = _inject_builtin_methods(cls)
        self._default_options = options or {}
        functools.update_wrapper(self, cls, updated=[])

    def options(self, **updates) -> "ActorClass":
        merged = dict(self._default_options)
        merged.update(updates)
        return ActorClass.__new__(ActorClass).__init_shim__(self._cls, merged)

    def __init_shim__(self, cls, options):
        self._cls = cls
        self._default_options = options
        return self

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"Actor class {self._cls.__name__} cannot be instantiated "
            "directly; use .remote()")

    def remote(self, *args, **kwargs) -> ActorHandle:
        return self._remote(args, kwargs, self._default_options)

    def bind(self, *args, **kwargs):
        from ray_tpu.dag import ClassNode
        return ClassNode(self, args, kwargs)

    def _remote(self, args, kwargs, opts: Dict[str, Any]) -> ActorHandle:
        from ray_tpu._private import worker as _worker
        from ray_tpu._private.runtime import ActorState
        w = _worker.global_worker()
        options = _build_actor_options(opts)
        namespace = options.namespace or w.namespace
        if options.name and options.get_if_exists:
            try:
                state = w.runtime.get_named_actor(options.name, namespace)
                return ActorHandle._from_state(state)
            except ValueError:
                pass
        actor_id = ActorID.of(w.runtime.job_id)
        state = ActorState(actor_id, self._cls, tuple(args), dict(kwargs),
                           options, options.name, namespace)
        w.runtime.create_actor(state)
        return ActorHandle(actor_id, self._cls.__name__)


def _inject_builtin_methods(cls: type) -> type:
    if not hasattr(cls, "__ray_ready__"):
        cls.__ray_ready__ = lambda self: True
    if not hasattr(cls, "__ray_collective_init__"):
        def _collective_init(self, world_size, rank, backend, group_name,
                             devices=None, config=None):
            from ray_tpu.collective import init_collective_group
            init_collective_group(world_size, rank, backend, group_name,
                                  devices, config)
            return rank
        cls.__ray_collective_init__ = _collective_init
    if not hasattr(cls, "__ray_terminate__"):
        def _terminate(self):
            from ray_tpu._private import worker as _worker
            from ray_tpu._private.runtime import task_context
            rt = _worker.global_worker().runtime
            aid = task_context.actor_id
            if aid is not None:
                rt.offload(lambda: rt.kill_actor(aid, no_restart=True))
            return None
        cls.__ray_terminate__ = _terminate
    return cls
