"""Distributed Dataset: blocks in the object store + a lazy, fusing plan.

Parity with ``python/ray/data/dataset.py`` and ``_internal/plan.py:69,283``
(lazy ExecutionPlan with stage fusion), ``compute.py:56,146`` (task vs actor
pool compute), ``_internal/{shuffle,sort,push_based_shuffle}.py``.

Design: a Dataset is a list of block ``ObjectRef``s plus a list of pending
stages. One-to-one stages (map/map_batches/filter/flat_map/...) are FUSED
into a single task per block at execution time; all-to-all stages
(repartition/random_shuffle/sort/groupby) run as two-phase map+reduce task
graphs. TPU-native additions: ``iter_jax_batches`` feeds sharded
``jax.Array`` batches onto a device mesh.
"""

from __future__ import annotations

import functools
import itertools
import math
import random
from typing import (Any, Callable, Dict, Iterator, List, Optional, Tuple,
                    Union)

import numpy as np

import ray_tpu
from ray_tpu.data.block import BlockAccessor, normalize_block

# --------------------------------------------------------------------------- #
# compute strategies
# --------------------------------------------------------------------------- #


class TaskPoolStrategy:
    """One task per block (reference ``compute.py:56``)."""


class ActorPoolStrategy:
    """Fixed/autoscaling actor pool applying the fused stage
    (reference ``compute.py:146``)."""

    def __init__(self, min_size: int = 1, max_size: Optional[int] = None):
        self.min_size = min_size
        self.max_size = max_size or min_size


@ray_tpu.remote
def _exec_fused_task(fns: Tuple[Callable, ...], block):
    for fn in fns:
        block = fn(block)
    return block


@ray_tpu.remote
class _PoolWorker:
    def exec(self, fns, block):
        for fn in fns:
            block = fn(block)
        return block


# --------------------------------------------------------------------------- #
# stages
# --------------------------------------------------------------------------- #


class _OneToOne:
    def __init__(self, name: str, fn: Callable[[Any], Any],
                 compute: Optional[Any] = None):
        self.name = name
        self.fn = fn
        self.compute = compute or TaskPoolStrategy()


class _AllToAll:
    def __init__(self, name: str, fn: Callable[[List], List]):
        self.name = name
        self.fn = fn  # List[ObjectRef] -> List[ObjectRef]


def _execute_one_to_one(refs: List, fused: List[_OneToOne]) -> List:
    fns = tuple(s.fn for s in fused)
    compute = next((s.compute for s in fused
                    if isinstance(s.compute, ActorPoolStrategy)), None)
    if compute is None:
        return [_exec_fused_task.remote(fns, r) for r in refs]
    pool = [_PoolWorker.remote() for _ in range(compute.min_size)]
    out = [pool[i % len(pool)].exec.remote(fns, r)
           for i, r in enumerate(refs)]
    # release pool actors once results land (results are owned refs)
    ray_tpu.wait(out, num_returns=len(out), timeout=None)
    for w in pool:
        ray_tpu.kill(w)
    return out


# --------------------------------------------------------------------------- #
# Dataset
# --------------------------------------------------------------------------- #


class Dataset:
    def __init__(self, block_refs: List, stages: Optional[List] = None):
        self._block_refs = list(block_refs)
        self._stages: List = list(stages or [])
        self._cached: Optional[List] = None

    # -- plan ----------------------------------------------------------------
    def _with_stage(self, stage) -> "Dataset":
        return Dataset(self._block_refs, self._stages + [stage])

    def _execute(self) -> List:
        """Materialize: fuse runs of one-to-one stages, run all-to-alls."""
        if self._cached is not None:
            return self._cached
        refs = self._block_refs
        pending: List[_OneToOne] = []
        for stage in self._stages:
            if isinstance(stage, _OneToOne):
                pending.append(stage)
            else:
                if pending:
                    refs = _execute_one_to_one(refs, pending)
                    pending = []
                refs = stage.fn(refs)
        if pending:
            refs = _execute_one_to_one(refs, pending)
        self._cached = refs
        return refs

    def materialize(self) -> "Dataset":
        return Dataset(self._execute())

    def get_internal_block_refs(self) -> List:
        return self._execute()

    def _blocks(self) -> List:
        return [ray_tpu.get(r) for r in self._execute()]

    # -- one-to-one transforms ----------------------------------------------
    def map(self, fn: Callable[[Any], Any], *, compute=None) -> "Dataset":
        def _map_block(block):
            acc = BlockAccessor.for_block(block)
            if acc.num_rows() == 0:
                return block
            rows = [fn(r) for r in acc.iter_rows()]
            return _rows_to_block(rows)
        return self._with_stage(_OneToOne("map", _map_block, compute))

    def flat_map(self, fn: Callable[[Any], List[Any]], *,
                 compute=None) -> "Dataset":
        def _fm_block(block):
            acc = BlockAccessor.for_block(block)
            rows: List[Any] = []
            for r in acc.iter_rows():
                rows.extend(fn(r))
            return _rows_to_block(rows)
        return self._with_stage(_OneToOne("flat_map", _fm_block, compute))

    def filter(self, fn: Callable[[Any], bool], *, compute=None) -> "Dataset":
        def _filter_block(block):
            import pandas as pd
            if isinstance(block, pd.DataFrame):
                mask = [bool(fn(r)) for r in
                        BlockAccessor.for_block(block).iter_rows()]
                return block[mask].reset_index(drop=True)
            return [r for r in block if fn(r)]
        return self._with_stage(_OneToOne("filter", _filter_block, compute))

    def map_batches(self, fn: Callable[[Any], Any], *,
                    batch_size: Optional[int] = None,
                    batch_format: str = "default",
                    compute=None, **_ignored) -> "Dataset":
        def _mb_block(block):
            acc = BlockAccessor.for_block(block)
            n = acc.num_rows()
            if n == 0:
                return block
            size = batch_size or n
            outs = []
            for start in range(0, n, size):
                piece = acc.slice(start, min(start + size, n))
                batch = BlockAccessor.for_block(piece).to_batch(
                    "pandas" if batch_format == "default" else batch_format)
                out = fn(batch)
                outs.append(normalize_block(out))
            return BlockAccessor.combine(outs)
        return self._with_stage(_OneToOne("map_batches", _mb_block, compute))

    def add_column(self, name: str, fn: Callable[[Any], Any]) -> "Dataset":
        def _add(df):
            df = df.copy()
            df[name] = fn(df)
            return df
        return self.map_batches(_add, batch_format="pandas")

    def drop_columns(self, cols: List[str]) -> "Dataset":
        return self.map_batches(lambda df: df.drop(columns=cols),
                                batch_format="pandas")

    def select_columns(self, cols: List[str]) -> "Dataset":
        return self.map_batches(lambda df: df[cols], batch_format="pandas")

    def rename_columns(self, mapping: Dict[str, str]) -> "Dataset":
        return self.map_batches(lambda df: df.rename(columns=mapping),
                                batch_format="pandas")

    def random_sample(self, fraction: float,
                      seed: Optional[int] = None) -> "Dataset":
        def _sample(block):
            rng = random.Random(seed)
            import pandas as pd
            if isinstance(block, pd.DataFrame):
                return block.sample(frac=fraction,
                                    random_state=seed).reset_index(drop=True)
            return [r for r in block if rng.random() < fraction]
        return self._with_stage(_OneToOne("random_sample", _sample))

    # -- all-to-all transforms ----------------------------------------------
    def repartition(self, num_blocks: int) -> "Dataset":
        def _repart(refs: List) -> List:
            blocks = [ray_tpu.get(r) for r in refs]
            merged = BlockAccessor.combine(blocks)
            acc = BlockAccessor.for_block(merged)
            n = acc.num_rows()
            per = math.ceil(n / num_blocks) if num_blocks else n
            out = []
            for i in range(num_blocks):
                out.append(ray_tpu.put(acc.slice(
                    min(i * per, n), min((i + 1) * per, n))))
            return out
        return self._with_stage(_AllToAll("repartition", _repart))

    def random_shuffle(self, *, seed: Optional[int] = None) -> "Dataset":
        """Two-phase push-based shuffle (reference
        ``_internal/push_based_shuffle.py``): map tasks scatter each block
        into N partitions; reduce tasks combine + locally shuffle."""
        def _shuffle(refs: List) -> List:
            n_out = max(1, len(refs))

            @ray_tpu.remote
            def _scatter(block, idx):
                rng = random.Random(None if seed is None else seed + idx)
                acc = BlockAccessor.for_block(block)
                rows = list(acc.iter_rows())
                assign = [rng.randrange(n_out) for _ in rows]
                parts: List[List[Any]] = [[] for _ in range(n_out)]
                for row, a in zip(rows, assign):
                    parts[a].append(row)
                return [_rows_to_block(p) for p in parts]

            @ray_tpu.remote
            def _reduce(parts, idx):
                merged = BlockAccessor.combine(list(parts))
                acc = BlockAccessor.for_block(merged)
                rows = list(acc.iter_rows())
                rng = random.Random(None if seed is None else seed * 7 + idx)
                rng.shuffle(rows)
                return _rows_to_block(rows)

            scattered = [_scatter.remote(r, i) for i, r in enumerate(refs)]
            mats = ray_tpu.get(scattered)  # each: list of n_out blocks
            return [_reduce.remote([m[j] for m in mats], j)
                    for j in range(n_out)]
        return self._with_stage(_AllToAll("random_shuffle", _shuffle))

    def sort(self, key: Optional[Union[str, Callable]] = None,
             descending: bool = False) -> "Dataset":
        """Sample-based range partition + per-partition sort
        (reference ``_internal/sort.py``)."""
        def _sort(refs: List) -> List:
            if not refs:
                return refs
            n_out = len(refs)
            keyf = _key_fn(key)
            samples: List[Any] = []
            for r in refs:
                acc = BlockAccessor.for_block(ray_tpu.get(r))
                samples.extend(acc.sample_keys(10, key))
            samples.sort()
            bounds = [samples[int(len(samples) * (i + 1) / n_out)]
                      for i in range(n_out - 1)] if samples else []

            @ray_tpu.remote
            def _part(block):
                acc = BlockAccessor.for_block(block)
                parts: List[List[Any]] = [[] for _ in range(n_out)]
                import bisect
                for row in acc.iter_rows():
                    parts[bisect.bisect_left(bounds, keyf(row))].append(row)
                return [_rows_to_block(p) for p in parts]

            @ray_tpu.remote
            def _sort_part(parts):
                merged = BlockAccessor.combine(list(parts))
                rows = sorted(BlockAccessor.for_block(merged).iter_rows(),
                              key=keyf, reverse=descending)
                return _rows_to_block(rows)

            mats = ray_tpu.get([_part.remote(r) for r in refs])
            out = [_sort_part.remote([m[j] for m in mats])
                   for j in range(n_out)]
            return out[::-1] if descending else out
        return self._with_stage(_AllToAll("sort", _sort))

    def groupby(self, key: Union[str, Callable]) -> "GroupedData":
        return GroupedData(self, key)

    def zip(self, other: "Dataset") -> "Dataset":
        def _zip(refs: List) -> List:
            other_refs = other._execute()
            counts = [BlockAccessor.for_block(ray_tpu.get(r)).num_rows()
                      for r in refs]
            other_rows: List[Any] = []
            for r in other_refs:
                other_rows.extend(
                    BlockAccessor.for_block(ray_tpu.get(r)).iter_rows())
            if sum(counts) != len(other_rows):
                raise ValueError(
                    f"zip requires equal row counts: {sum(counts)} vs "
                    f"{len(other_rows)} (reference dataset.py zip semantics)")
            out, pos = [], 0
            for r, c in zip(refs, counts):
                mine = list(BlockAccessor.for_block(ray_tpu.get(r)).iter_rows())
                theirs = other_rows[pos:pos + c]
                pos += c
                rows = [_merge_rows(a, b) for a, b in zip(mine, theirs)]
                out.append(ray_tpu.put(_rows_to_block(rows)))
            return out
        return self._with_stage(_AllToAll("zip", _zip))

    def union(self, *others: "Dataset") -> "Dataset":
        refs = list(self._execute())
        for o in others:
            refs.extend(o._execute())
        return Dataset(refs)

    def limit(self, n: int) -> "Dataset":
        def _limit(refs: List) -> List:
            out, left = [], n
            for r in refs:
                if left <= 0:
                    break
                block = ray_tpu.get(r)
                acc = BlockAccessor.for_block(block)
                take = min(left, acc.num_rows())
                out.append(ray_tpu.put(acc.slice(0, take)))
                left -= take
            return out
        return self._with_stage(_AllToAll("limit", _limit))

    # -- consumption ---------------------------------------------------------
    def count(self) -> int:
        return sum(BlockAccessor.for_block(b).num_rows()
                   for b in self._blocks())

    def num_blocks(self) -> int:
        return len(self._execute())

    def size_bytes(self) -> int:
        return sum(BlockAccessor.for_block(b).size_bytes()
                   for b in self._blocks())

    def schema(self):
        # lazy: fetch blocks only until the first non-empty one
        for r in self._execute():
            b = ray_tpu.get(r)
            acc = BlockAccessor.for_block(b)
            if acc.num_rows() > 0:
                import pandas as pd
                if isinstance(b, pd.DataFrame):
                    return {c: str(t) for c, t in b.dtypes.items()}
                return type(next(iter(acc.iter_rows())))
        return None

    def columns(self) -> Optional[List[str]]:
        s = self.schema()
        return list(s.keys()) if isinstance(s, dict) else None

    def take(self, n: int = 20) -> List[Any]:
        out: List[Any] = []
        for r in self._execute():
            for row in BlockAccessor.for_block(ray_tpu.get(r)).iter_rows():
                out.append(row)
                if len(out) >= n:
                    return out
        return out

    def take_all(self) -> List[Any]:
        out: List[Any] = []
        for b in self._blocks():
            out.extend(BlockAccessor.for_block(b).iter_rows())
        return out

    def show(self, n: int = 20):
        for row in self.take(n):
            print(row)

    def iter_rows(self) -> Iterator[Any]:
        for r in self._execute():
            yield from BlockAccessor.for_block(ray_tpu.get(r)).iter_rows()

    def iter_batches(self, *, batch_size: Optional[int] = 256,
                     batch_format: str = "default",
                     drop_last: bool = False,
                     local_shuffle_buffer_size: Optional[int] = None,
                     local_shuffle_seed: Optional[int] = None,
                     prefetch_batches: int = 0
                     ) -> Iterator[Any]:
        """``prefetch_batches > 0`` prepares that many batches ahead on a
        background thread (reference ``iter_batches(prefetch_batches=)``):
        host-side batch assembly overlaps the consumer's device step — the
        input-pipeline overlap that keeps a TPU step from waiting on
        pandas.  At the default ``0`` the ``data_prefetch_batches`` knob
        decides, so the autopilot's prefetch policy can deepen the
        pipeline cluster-wide from the ledger's ``data_wait`` share
        without touching call sites; pass a negative depth to force the
        synchronous path regardless of the knob."""
        fmt = "pandas" if batch_format == "default" else batch_format
        if prefetch_batches == 0:
            from ray_tpu._private.config import _config
            prefetch_batches = int(_config.get("data_prefetch_batches"))

        def gen():
            rows_iter = self.iter_rows()
            if local_shuffle_buffer_size:
                rows_iter = _shuffling_iterator(
                    rows_iter, local_shuffle_buffer_size,
                    local_shuffle_seed)
            while True:
                chunk = list(itertools.islice(rows_iter, batch_size or 256))
                if not chunk:
                    return
                if drop_last and batch_size and len(chunk) < batch_size:
                    return
                block = _rows_to_block(chunk)
                yield BlockAccessor.for_block(block).to_batch(fmt)

        if prefetch_batches > 0:
            return _prefetching_iterator(gen(), prefetch_batches)
        return gen()

    def iter_torch_batches(self, *, batch_size: Optional[int] = 256,
                           drop_last: bool = False, **kw) -> Iterator[Any]:
        import torch
        for batch in self.iter_batches(batch_size=batch_size,
                                       batch_format="numpy",
                                       drop_last=drop_last, **kw):
            if isinstance(batch, dict):
                yield {k: torch.as_tensor(v) for k, v in batch.items()}
            else:
                yield torch.as_tensor(batch)

    def iter_jax_batches(self, *, batch_size: Optional[int] = 256,
                         drop_last: bool = False, sharding=None,
                         **kw) -> Iterator[Any]:
        """TPU-native batch feed: numpy batches placed on device, optionally
        sharded over a mesh (``jax.device_put`` with a NamedSharding) —
        the analogue of the reference's ``iter_torch_batches`` pinning to
        GPU, but mesh-aware."""
        import jax
        for batch in self.iter_batches(batch_size=batch_size,
                                       batch_format="numpy",
                                       drop_last=drop_last, **kw):
            if isinstance(batch, dict):
                yield {k: jax.device_put(v, sharding)
                       for k, v in batch.items()}
            else:
                yield jax.device_put(batch, sharding)

    # -- aggregates ----------------------------------------------------------
    def _column_values(self, on: Optional[str]) -> np.ndarray:
        vals: List[np.ndarray] = []
        for b in self._blocks():
            acc = BlockAccessor.for_block(b)
            if acc.num_rows() == 0:
                continue
            v = acc.to_numpy(on) if on else acc.to_numpy()
            if isinstance(v, dict):
                if len(v) != 1:
                    raise ValueError(
                        "aggregate on multi-column dataset requires on=")
                v = next(iter(v.values()))
            vals.append(np.asarray(v, dtype=np.float64))
        if not vals:
            return np.array([])
        return np.concatenate(vals)

    def sum(self, on: Optional[str] = None):
        v = self._column_values(on)
        return float(v.sum()) if v.size else None

    def min(self, on: Optional[str] = None):
        v = self._column_values(on)
        return float(v.min()) if v.size else None

    def max(self, on: Optional[str] = None):
        v = self._column_values(on)
        return float(v.max()) if v.size else None

    def mean(self, on: Optional[str] = None):
        v = self._column_values(on)
        return float(v.mean()) if v.size else None

    def std(self, on: Optional[str] = None, ddof: int = 1):
        v = self._column_values(on)
        return float(v.std(ddof=ddof)) if v.size else None

    # -- splits --------------------------------------------------------------
    def split(self, n: int, *, equal: bool = False) -> List["Dataset"]:
        refs = self._execute()
        if equal:
            # row counts differ by at most 1 across shards: a worker group
            # running per-batch collectives over its shards must not have
            # one member running extra rounds (a silent distributed hang)
            total = self.count()
            sizes = [total // n + (1 if i < total % n else 0)
                     for i in range(n)]
            cuts = []
            acc = 0
            for s in sizes[:-1]:
                acc += s
                cuts.append(acc)
            return self.split_at_indices(cuts)
        out: List[List] = [[] for _ in range(n)]
        for i, r in enumerate(refs):
            out[i % n].append(r)
        return [Dataset(refs) for refs in out]

    def streaming_split(self, n: int, *, equal: bool = False
                        ) -> List["DataIterator"]:
        """``n`` iterators that partition this dataset for concurrent
        consumers (reference ``Dataset.streaming_split`` feeding Train
        workers). Blocks are assigned round-robin up front (this engine's
        plans are materialized-block based, not a streaming executor);
        ``equal=True`` rebalances by rows instead."""
        return [DataIterator(shard) for shard in self.split(n, equal=equal)]

    def iterator(self) -> "DataIterator":
        """A single-consumer ``DataIterator`` over the whole dataset
        (reference ``Dataset.iterator``)."""
        return DataIterator(self)

    def split_at_indices(self, indices: List[int]) -> List["Dataset"]:
        """Blocks are assigned to output shards by cumulative row count and
        sliced IN PLACE (remote per-block tasks) where a cut falls inside
        a block — the driver never materializes rows, so splitting scales
        to datasets larger than driver memory."""
        refs = self._execute()

        @ray_tpu.remote
        def _block_rows(block) -> int:
            return BlockAccessor.for_block(block).num_rows()

        @ray_tpu.remote
        def _block_slice(block, a: int, b: int):
            return BlockAccessor.for_block(block).slice(a, b)

        counts = ray_tpu.get([_block_rows.remote(r) for r in refs])
        total = sum(counts)
        bounds = [0] + sorted(int(i) for i in indices) + [total]
        out: List[List] = []
        block_i, offset = 0, 0  # offset: rows of block_i already consumed
        for a, b in zip(bounds[:-1], bounds[1:]):
            want = b - a
            shard_refs: List = []
            while want > 0 and block_i < len(refs):
                avail = counts[block_i] - offset
                if avail <= 0:
                    block_i += 1
                    offset = 0
                    continue
                take = min(want, avail)
                if offset == 0 and take == counts[block_i]:
                    shard_refs.append(refs[block_i])  # whole block, no copy
                else:
                    shard_refs.append(_block_slice.remote(
                        refs[block_i], offset, offset + take))
                offset += take
                want -= take
                if offset >= counts[block_i]:
                    block_i += 1
                    offset = 0
            out.append(Dataset(shard_refs
                               or [ray_tpu.put(_rows_to_block([]))]))
        return out

    def train_test_split(self, test_size: float,
                         *, shuffle: bool = False,
                         seed: Optional[int] = None) -> Tuple["Dataset", "Dataset"]:
        ds = self.random_shuffle(seed=seed) if shuffle else self
        total = ds.count()
        n_test = int(total * test_size)
        train, test = ds.split_at_indices([total - n_test])
        return train, test

    # -- output --------------------------------------------------------------
    def to_pandas(self, limit: Optional[int] = None):
        import pandas as pd
        dfs = [BlockAccessor.for_block(b).to_pandas()
               for b in self._blocks()]
        df = (pd.concat(dfs, ignore_index=True) if dfs
              else pd.DataFrame())
        return df.head(limit) if limit else df

    def to_arrow_refs(self) -> List:
        @ray_tpu.remote
        def _to_arrow(block):
            return BlockAccessor.for_block(block).to_arrow()
        return [_to_arrow.remote(r) for r in self._execute()]

    def write_parquet(self, path: str):
        self._write(path, "parquet")

    def write_csv(self, path: str):
        self._write(path, "csv")

    def write_json(self, path: str):
        self._write(path, "json")

    def write_tfrecords(self, path: str):
        """One TFRecord file per block; rows serialize as
        tf.train.Example (``data/tfrecords.py`` codec)."""
        self._write(path, "tfrecord")

    def write_numpy(self, path: str, column: Optional[str] = None):
        import os
        os.makedirs(path, exist_ok=True)

        @ray_tpu.remote
        def _w(block, i):
            acc = BlockAccessor.for_block(block)
            np.save(os.path.join(path, f"block_{i:06d}.npy"),
                    acc.to_numpy(column))
            return None
        ray_tpu.get([_w.remote(r, i) for i, r in enumerate(self._execute())])

    def _write(self, path: str, fmt: str):
        import os
        os.makedirs(path, exist_ok=True)

        @ray_tpu.remote
        def _w(block, i):
            df = BlockAccessor.for_block(block).to_pandas()
            fp = os.path.join(path, f"block_{i:06d}.{fmt}")
            if fmt == "parquet":
                df.to_parquet(fp)
            elif fmt == "csv":
                df.to_csv(fp, index=False)
            elif fmt == "tfrecord":
                from ray_tpu.data.tfrecords import (encode_example,
                                                    write_tfrecord_file)
                # to_dict("records") preserves per-COLUMN dtypes;
                # iterrows would coerce rows to one dtype and silently
                # turn int64 ids into lossy float32 FloatLists
                write_tfrecord_file(
                    fp, (encode_example(row)
                         for row in df.to_dict(orient="records")))
            else:
                df.to_json(fp, orient="records", lines=True)
            return None
        ray_tpu.get([_w.remote(r, i) for i, r in enumerate(self._execute())])

    # -- pipeline ------------------------------------------------------------
    def window(self, *, blocks_per_window: int = 10):
        from ray_tpu.data.dataset_pipeline import DatasetPipeline
        refs = self._execute()
        windows = [refs[i:i + blocks_per_window]
                   for i in range(0, len(refs), blocks_per_window)]
        return DatasetPipeline([Dataset(w) for w in windows])

    def repeat(self, times: Optional[int] = None):
        from ray_tpu.data.dataset_pipeline import DatasetPipeline
        return DatasetPipeline([self], repeat=times)

    def __repr__(self):
        try:
            n = len(self._cached) if self._cached else len(self._block_refs)
        except Exception:  # raylint: allow(swallow) repr must never raise
            n = "?"
        stages = "+".join(s.name for s in self._stages) or "read"
        return f"Dataset(blocks={n}, plan={stages})"

    def stats(self) -> str:
        return repr(self)


# --------------------------------------------------------------------------- #
# grouped data
# --------------------------------------------------------------------------- #


class GroupedData:
    """Reference ``python/ray/data/grouped_dataset.py``: hash-partition by
    key then per-partition aggregate."""

    def __init__(self, ds: Dataset, key: Union[str, Callable]):
        self._ds = ds
        self._key = key

    def _agg(self, named_aggs: List[Tuple[str, Optional[str], str]]) -> Dataset:
        """named_aggs: list of (agg_fn, on_column, out_name)."""
        key = self._key
        keyf = _key_fn(key)
        ds = self._ds

        def _group(refs: List) -> List:
            n_out = max(1, len(refs))

            @ray_tpu.remote
            def _part(block):
                acc = BlockAccessor.for_block(block)
                parts: List[List[Any]] = [[] for _ in range(n_out)]
                for row in acc.iter_rows():
                    parts[hash(keyf(row)) % n_out].append(row)
                return [_rows_to_block(p) for p in parts]

            @ray_tpu.remote
            def _aggregate(parts):
                import pandas as pd
                merged = BlockAccessor.combine(list(parts))
                df = BlockAccessor.for_block(merged).to_pandas()
                if df.empty:
                    return df
                if callable(key):
                    df = df.copy()
                    df["__key__"] = [key(dict(r)) for _, r in df.iterrows()]
                    gkey = "__key__"
                else:
                    gkey = key
                g = df.groupby(gkey, sort=True)
                out: Dict[str, Any] = {}
                for fn, on, name in named_aggs:
                    if fn == "count":
                        out[name] = g.size()
                    else:
                        col = on or next(
                            c for c in df.columns if c != gkey)
                        out[name] = getattr(g[col], fn)()
                res = pd.DataFrame(out).reset_index()
                return res

            mats = ray_tpu.get([_part.remote(r) for r in refs])
            return [_aggregate.remote([m[j] for m in mats])
                    for j in range(n_out)]

        return ds._with_stage(_AllToAll("groupby", _group))

    def count(self) -> Dataset:
        return self._agg([("count", None, "count()")])

    def sum(self, on: Optional[str] = None) -> Dataset:
        return self._agg([("sum", on, f"sum({on})")])

    def min(self, on: Optional[str] = None) -> Dataset:
        return self._agg([("min", on, f"min({on})")])

    def max(self, on: Optional[str] = None) -> Dataset:
        return self._agg([("max", on, f"max({on})")])

    def mean(self, on: Optional[str] = None) -> Dataset:
        return self._agg([("mean", on, f"mean({on})")])

    def std(self, on: Optional[str] = None) -> Dataset:
        return self._agg([("std", on, f"std({on})")])

    def aggregate(self, *aggs) -> Dataset:
        """aggs: (fn_name, on, out_name) triples."""
        return self._agg(list(aggs))

    def map_groups(self, fn: Callable) -> Dataset:
        key = self._key
        keyf = _key_fn(key)
        ds = self._ds

        def _group(refs: List) -> List:
            n_out = max(1, len(refs))

            @ray_tpu.remote
            def _part(block):
                acc = BlockAccessor.for_block(block)
                parts: List[List[Any]] = [[] for _ in range(n_out)]
                for row in acc.iter_rows():
                    parts[hash(keyf(row)) % n_out].append(row)
                return [_rows_to_block(p) for p in parts]

            @ray_tpu.remote
            def _apply(parts):
                merged = BlockAccessor.combine(list(parts))
                acc = BlockAccessor.for_block(merged)
                groups: Dict[Any, List[Any]] = {}
                for row in acc.iter_rows():
                    groups.setdefault(keyf(row), []).append(row)
                rows: List[Any] = []
                for k in sorted(groups, key=repr):
                    out = fn(_rows_to_block(groups[k]))
                    rows.extend(BlockAccessor.for_block(
                        normalize_block(out)).iter_rows())
                return _rows_to_block(rows)

            mats = ray_tpu.get([_part.remote(r) for r in refs])
            return [_apply.remote([m[j] for m in mats])
                    for j in range(n_out)]

        return ds._with_stage(_AllToAll("map_groups", _group))


# --------------------------------------------------------------------------- #
# helpers
# --------------------------------------------------------------------------- #


def _data_wait_iter(it: Iterator) -> Iterator[Any]:
    """Attribute each batch pull to the goodput ledger's ``data_wait``
    category — the input-pipeline stall a train worker sees when the
    producer (pandas assembly / prefetch thread) falls behind the step."""
    from ray_tpu.observability import goodput
    while True:
        if goodput.ENABLED:
            with goodput.interval("data_wait"):
                try:
                    batch = next(it)
                except StopIteration:
                    return
        else:
            try:
                batch = next(it)
            except StopIteration:
                return
        yield batch


class DataIterator:
    """Consumer-facing iteration handle over one dataset shard
    (reference ``ray.data.DataIterator``, what ``streaming_split``
    hands each Train worker).  Batch pulls are goodput-attributed as
    ``data_wait`` — this is the handle train workers consume from, so
    pipeline stalls land in the job ledger."""

    def __init__(self, ds: "Dataset"):
        self._ds = ds

    def iter_batches(self, **kw) -> Iterator[Any]:
        return _data_wait_iter(self._ds.iter_batches(**kw))

    def iter_torch_batches(self, **kw) -> Iterator[Any]:
        return _data_wait_iter(self._ds.iter_torch_batches(**kw))

    def iter_jax_batches(self, **kw) -> Iterator[Any]:
        return _data_wait_iter(self._ds.iter_jax_batches(**kw))

    def iter_rows(self) -> Iterator[Any]:
        return self._ds.iter_rows()

    def materialize(self) -> "Dataset":
        return self._ds.materialize()

    def count(self) -> int:
        return self._ds.count()

    def __repr__(self):
        return f"DataIterator({self._ds!r})"


def _prefetching_iterator(it: Iterator, n: int) -> Iterator:
    """Run ``it`` on a daemon thread, buffering up to ``n`` items ahead.

    Producer exceptions re-raise at the consumer's next pull. A consumer
    that ABANDONS the iterator early (break / close / GC) releases the
    producer: the generator's finally sets a stop flag and drains one
    slot, so the thread never stays parked on a full queue holding the
    buffered blocks alive."""
    import queue as _queue
    import threading

    q: "_queue.Queue" = _queue.Queue(maxsize=max(1, n))
    stop = threading.Event()
    _END = object()

    def _put(item) -> bool:
        while not stop.is_set():
            try:
                q.put(item, timeout=0.1)
                return True
            except _queue.Full:
                continue
        return False

    def fill():
        try:
            for item in it:
                if not _put((None, item)):
                    return
        except BaseException as e:  # noqa: BLE001 — re-raised at consumer
            _put((e, None))
            return
        _put((None, _END))

    threading.Thread(target=fill, daemon=True,
                     name="data-prefetch").start()
    try:
        while True:
            err, item = q.get()
            if err is not None:
                raise err
            if item is _END:
                return
            yield item
    finally:
        stop.set()
        try:
            q.get_nowait()  # free a blocked producer immediately
        except _queue.Empty:
            pass


def _rows_to_block(rows: List[Any]):
    import pandas as pd
    if rows and isinstance(rows[0], dict):
        return pd.DataFrame(rows)
    return list(rows)


def _key_fn(key) -> Callable[[Any], Any]:
    if key is None:
        return lambda r: r
    if callable(key):
        return key
    return lambda r: r[key]


def _merge_rows(a, b):
    if isinstance(a, dict) and isinstance(b, dict):
        merged = dict(a)
        for k, v in b.items():
            merged[k if k not in merged else f"{k}_1"] = v
        return merged
    return (a, b)


def _shuffling_iterator(it: Iterator, buffer_size: int,
                        seed: Optional[int]) -> Iterator:
    rng = random.Random(seed)
    buf: List[Any] = []
    for item in it:
        buf.append(item)
        if len(buf) >= buffer_size:
            idx = rng.randrange(len(buf))
            buf[idx], buf[-1] = buf[-1], buf[idx]
            yield buf.pop()
    rng.shuffle(buf)
    yield from buf
