"""Random access over a sorted dataset.

Parity with the reference's ``RandomAccessDataset``
(``python/ray/data/random_access_dataset.py``): sort by a key column,
partition the sorted blocks across worker ACTORS, keep the partition
boundaries on the driver, and serve point lookups / multigets by routing
each key to the actor owning its range (binary search on both levels).
The serving-side feature-lookup primitive (e.g. embedding rows) that a
plain ``Dataset`` — optimized for scans — cannot provide.
"""

from __future__ import annotations

import bisect
from typing import Any, Dict, List, Optional

import numpy as np

import ray_tpu
from ray_tpu.data.dataset import Dataset


@ray_tpu.remote
class _RangeWorker:
    """Holds one sorted partition; answers point lookups."""

    def __init__(self, block, key: str):
        import pandas as pd
        if isinstance(block, list) and not block:
            block = pd.DataFrame({key: []})  # typeless empty partition
        if not isinstance(block, pd.DataFrame):
            raise TypeError(
                "RandomAccessDataset requires column (DataFrame) blocks")
        self._df = block.sort_values(key).reset_index(drop=True)
        self._keys = self._df[key].to_numpy()
        self._key = key

    def get(self, key_value):
        i = int(np.searchsorted(self._keys, key_value))
        if i < len(self._keys) and self._keys[i] == key_value:
            return self._df.iloc[i].to_dict()
        return None

    def multiget(self, key_values: List[Any]) -> List[Optional[dict]]:
        return [self.get(k) for k in key_values]

    def stats(self) -> Dict[str, Any]:
        return {"rows": len(self._df),
                "lo": self._keys[0] if len(self._keys) else None,
                "hi": self._keys[-1] if len(self._keys) else None}


class RandomAccessDataset:
    """O(log n) point lookups over ``ds`` keyed by column ``key``.

    ``num_workers`` actors each own one contiguous key range of the
    sorted data; the driver routes by bisect over the range boundaries.
    """

    def __init__(self, ds: Dataset, key: str, *, num_workers: int = 4):
        self._key = key
        sorted_ds = ds.sort(key).repartition(num_workers)
        refs = sorted_ds.get_internal_block_refs()
        self._workers = [_RangeWorker.remote(r, key) for r in refs]
        stats = ray_tpu.get([w.stats.remote() for w in self._workers])
        keep = [(s, w) for s, w in zip(stats, self._workers)
                if s["rows"] > 0]
        self._workers = [w for _, w in keep]
        # routing table: lower bound of each worker's key range
        self._bounds = [s["lo"] for s, _ in keep]
        self._stats = [s for s, _ in keep]

    def _route(self, key_value) -> int:
        i = bisect.bisect_right(self._bounds, key_value) - 1
        return max(0, i)

    def get_async(self, key_value):
        """ObjectRef of the row dict (or None when absent)."""
        if not self._workers:   # empty source dataset
            return ray_tpu.put(None)
        return self._workers[self._route(key_value)].get.remote(key_value)

    def get(self, key_value, timeout: Optional[float] = None):
        return ray_tpu.get(self.get_async(key_value), timeout=timeout)

    def multiget(self, key_values: List[Any],
                 timeout: Optional[float] = None) -> List[Optional[dict]]:
        """Batched lookup: keys are grouped per owning worker (ONE actor
        call per worker), results re-assembled in input order.
        ``timeout`` bounds the WHOLE call, not each worker."""
        if not self._workers:
            return [None] * len(key_values)
        per_worker: Dict[int, List[int]] = {}
        for pos, k in enumerate(key_values):
            per_worker.setdefault(self._route(k), []).append(pos)
        order = list(per_worker)
        vals_by_worker = ray_tpu.get(
            [self._workers[w].multiget.remote(
                [key_values[p] for p in per_worker[w]]) for w in order],
            timeout=timeout)
        out: List[Optional[dict]] = [None] * len(key_values)
        for w, vals in zip(order, vals_by_worker):
            for p, v in zip(per_worker[w], vals):
                out[p] = v
        return out

    def stats(self) -> List[Dict[str, Any]]:
        return list(self._stats)
