"""Dataset creation APIs.

Parity with ``python/ray/data/read_api.py`` (range/from_items/from_pandas/
from_numpy/from_arrow, read_{csv,parquet,json,numpy,text,binary_files}).
Reads are parallelized: one read task per file / per range shard.
"""

from __future__ import annotations

import math
import os
from typing import Any, Dict, List, Optional, Union

import numpy as np

import ray_tpu
from ray_tpu.data.block import normalize_block
from ray_tpu.data.dataset import Dataset


def _put_blocks(blocks: List[Any]) -> Dataset:
    return Dataset([ray_tpu.put(normalize_block(b)) for b in blocks])


def from_items(items: List[Any], *, parallelism: int = 8) -> Dataset:
    import builtins
    n = max(1, min(parallelism, len(items) or 1))
    per = math.ceil(len(items) / n) if items else 0
    blocks = ([items[i * per:(i + 1) * per] for i in builtins.range(n)]
              if items else [[]])
    return _put_blocks([b for b in blocks if b] or [[]])


def range(n: int, *, parallelism: int = 8) -> Dataset:  # noqa: A001
    import builtins
    per = math.ceil(n / parallelism) if n else 0
    blocks = []
    for i in builtins.range(parallelism):
        lo, hi = i * per, min((i + 1) * per, n)
        if lo >= hi:
            break
        blocks.append(list(builtins.range(lo, hi)))
    return _put_blocks(blocks or [[]])


def range_table(n: int, *, parallelism: int = 8) -> Dataset:
    import pandas as pd
    import builtins
    per = math.ceil(n / parallelism) if n else 0
    blocks = []
    for i in builtins.range(parallelism):
        lo, hi = i * per, min((i + 1) * per, n)
        if lo >= hi:
            break
        blocks.append(pd.DataFrame({"value": list(builtins.range(lo, hi))}))
    return _put_blocks(blocks or [[]])


def from_pandas(dfs: Union[Any, List[Any]]) -> Dataset:
    if not isinstance(dfs, list):
        dfs = [dfs]
    return _put_blocks(dfs)


def from_arrow(tables: Union[Any, List[Any]]) -> Dataset:
    if not isinstance(tables, list):
        tables = [tables]
    return _put_blocks(tables)


def from_numpy(arrays: Union[np.ndarray, List[np.ndarray]]) -> Dataset:
    import pandas as pd
    if not isinstance(arrays, list):
        arrays = [arrays]
    return _put_blocks([pd.DataFrame({"value": list(a)}) for a in arrays])


def _expand_paths(paths: Union[str, List[str]], suffixes=None) -> List[str]:
    if isinstance(paths, str):
        paths = [paths]
    out: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            for f in sorted(os.listdir(p)):
                fp = os.path.join(p, f)
                if os.path.isfile(fp) and (
                        suffixes is None or
                        any(f.endswith(s) for s in suffixes)):
                    out.append(fp)
        else:
            out.append(p)
    return out


def _read_files(paths, reader, suffixes) -> Dataset:
    files = _expand_paths(paths, suffixes)

    @ray_tpu.remote
    def _read(fp):
        return normalize_block(reader(fp))

    return Dataset([_read.remote(fp) for fp in files])


def read_parquet(paths: Union[str, List[str]], **kw) -> Dataset:
    import pandas as pd
    return _read_files(paths, lambda fp: pd.read_parquet(fp, **kw),
                       [".parquet"])


def read_csv(paths: Union[str, List[str]], **kw) -> Dataset:
    import pandas as pd
    return _read_files(paths, lambda fp: pd.read_csv(fp, **kw), [".csv"])


def read_json(paths: Union[str, List[str]], **kw) -> Dataset:
    import pandas as pd
    kw.setdefault("orient", "records")
    kw.setdefault("lines", True)
    return _read_files(paths, lambda fp: pd.read_json(fp, **kw),
                       [".json", ".jsonl"])


def read_numpy(paths: Union[str, List[str]], **kw) -> Dataset:
    import pandas as pd
    return _read_files(
        paths, lambda fp: pd.DataFrame({"value": list(np.load(fp, **kw))}),
        [".npy"])


def read_text(paths: Union[str, List[str]], *, encoding="utf-8") -> Dataset:
    def _reader(fp):
        with open(fp, encoding=encoding) as f:
            return [line.rstrip("\n") for line in f]
    return _read_files(paths, _reader, None)


def read_binary_files(paths: Union[str, List[str]]) -> Dataset:
    def _reader(fp):
        with open(fp, "rb") as f:
            return [f.read()]
    return _read_files(paths, _reader, None)


def read_tfrecords(paths: Union[str, List[str]]) -> Dataset:
    """TFRecord files of tf.train.Example protos -> tabular rows
    (reference ``read_api.py read_tfrecords``; dependency-free codec in
    ``data/tfrecords.py``)."""
    import pandas as pd

    from ray_tpu.data.tfrecords import decode_example, read_tfrecord_file

    def _reader(fp):
        rows = [decode_example(rec) for rec in read_tfrecord_file(fp)]
        return pd.DataFrame(rows)

    return _read_files(paths, _reader, [".tfrecord", ".tfrecords"])
