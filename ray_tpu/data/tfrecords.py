"""TFRecord file format + tf.train.Example codec, dependency-free.

Parity with ``python/ray/data/read_api.py read_tfrecords`` /
``Dataset.write_tfrecords`` (the reference rides tensorflow; this
runtime hand-rolls the two stable public formats so the TPU input
pipeline needs no TF install):

- **TFRecord framing**: ``uint64le length | u32 masked_crc32c(length) |
  data | u32 masked_crc32c(data)`` with CRC32C (Castagnoli) and the
  TFRecord mask ``((crc >> 15) | (crc << 17)) + 0xa282ead8``.
- **tf.train.Example**: the three-field protobuf schema
  (bytes_list/float_list/int64_list per feature), encoded/decoded with
  a minimal varint wire codec — the schema is frozen public API, small
  enough that a hand codec is sturdier than a TF dependency.

Corrupt records fail loudly (CRC mismatch raises), matching TF's
reader behavior.
"""

from __future__ import annotations

import struct
from typing import Any, Dict, Iterable, Iterator, List

import numpy as np

# ---------------------------------------------------------------- crc32c

def _build_crc_tables() -> List[List[int]]:
    """Slice-by-8 tables: table[0] is the classic byte table; table[k]
    advances a byte through k additional zero bytes — 8 bytes per loop
    iteration instead of 1 (~6x over per-byte pure Python, keeping the
    codec dependency-free)."""
    poly = 0x82F63B78
    base = []
    for i in range(256):
        c = i
        for _ in range(8):
            c = (c >> 1) ^ poly if c & 1 else c >> 1
        base.append(c)
    tables = [base]
    for k in range(1, 8):
        prev = tables[k - 1]
        tables.append([(prev[i] >> 8) ^ base[prev[i] & 0xFF]
                       for i in range(256)])
    return tables


# Built eagerly at import: concurrent writer tasks share this module, and
# a lazily-appended global would race (interleaved appends => corrupt
# CRCs in every file written afterwards).
_CRC_TABLES: List[List[int]] = _build_crc_tables()


def crc32c(data: bytes) -> int:
    t0, t1, t2, t3, t4, t5, t6, t7 = _CRC_TABLES
    crc = 0xFFFFFFFF
    n8 = len(data) & ~7
    i = 0
    while i < n8:
        crc ^= (data[i] | data[i + 1] << 8 | data[i + 2] << 16
                | data[i + 3] << 24)
        crc = (t7[crc & 0xFF] ^ t6[(crc >> 8) & 0xFF]
               ^ t5[(crc >> 16) & 0xFF] ^ t4[crc >> 24]
               ^ t3[data[i + 4]] ^ t2[data[i + 5]]
               ^ t1[data[i + 6]] ^ t0[data[i + 7]])
        i += 8
    for b in data[n8:]:
        crc = t0[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


def _masked_crc(data: bytes) -> int:
    crc = crc32c(data)
    return (((crc >> 15) | (crc << 17)) + 0xA282EAD8) & 0xFFFFFFFF


# ------------------------------------------------------------ tfrecord IO

def write_tfrecord_file(path: str, records: Iterable[bytes]) -> int:
    n = 0
    with open(path, "wb") as f:
        for rec in records:
            header = struct.pack("<Q", len(rec))
            f.write(header)
            f.write(struct.pack("<I", _masked_crc(header)))
            f.write(rec)
            f.write(struct.pack("<I", _masked_crc(rec)))
            n += 1
    return n


def read_tfrecord_file(path: str) -> Iterator[bytes]:
    with open(path, "rb") as f:
        while True:
            header = f.read(8)
            if not header:
                return
            if len(header) != 8:
                raise ValueError(f"{path}: truncated record header")
            (length,) = struct.unpack("<Q", header)
            hcrc_b = f.read(4)
            if len(hcrc_b) != 4:
                raise ValueError(f"{path}: truncated header CRC")
            if struct.unpack("<I", hcrc_b)[0] != _masked_crc(header):
                raise ValueError(f"{path}: corrupt record header CRC")
            data = f.read(length)
            if len(data) != length:
                raise ValueError(f"{path}: truncated record data")
            dcrc_b = f.read(4)
            if len(dcrc_b) != 4:
                raise ValueError(f"{path}: truncated data CRC")
            if struct.unpack("<I", dcrc_b)[0] != _masked_crc(data):
                raise ValueError(f"{path}: corrupt record data CRC")
            yield data


# ------------------------------------------------- minimal protobuf wire

def _varint(n: int) -> bytes:
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _read_varint(buf: bytes, pos: int):
    result = shift = 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7


def _ld(field: int, payload: bytes) -> bytes:  # length-delimited
    return _varint((field << 3) | 2) + _varint(len(payload)) + payload


# --------------------------------------------------- tf.train.Example

def encode_example(row: Dict[str, Any]) -> bytes:
    """dict -> serialized Example. Value typing: bytes/str -> BytesList,
    float -> FloatList, int/bool -> Int64List; lists of those likewise."""
    feats = bytearray()
    for name, value in row.items():
        if isinstance(value, np.ndarray):
            values: Any = value.tolist()
        elif isinstance(value, (list, tuple)):
            values = list(value)
        else:
            values = [value]
        first = values[0] if values else 0
        if isinstance(first, (bytes, str)):
            payload = b"".join(
                _ld(1, v.encode() if isinstance(v, str) else v)
                for v in values)
            feature = _ld(1, payload)           # BytesList in field 1
        elif isinstance(first, (float, np.floating)):
            floats = [float(v) for v in values]
            packed = struct.pack(f"<{len(floats)}f", *floats)
            feature = _ld(2, _varint(8 | 2) + _varint(len(packed))
                          + packed)             # FloatList packed field 1
        else:
            packed = b"".join(_varint(int(v) & 0xFFFFFFFFFFFFFFFF)
                              for v in values)
            feature = _ld(3, _varint(8 | 2) + _varint(len(packed))
                          + packed)             # Int64List packed field 1
        entry = _ld(1, name.encode()) + _ld(2, feature)
        feats += _ld(1, entry)                  # map entry
    return bytes(_ld(1, bytes(feats)))          # Example.features


def _decode_list(buf: bytes):
    """Decode one of BytesList/FloatList/Int64List given its kind tag."""
    kind, pos = _read_varint(buf, 0)
    field = kind >> 3
    ln, pos = _read_varint(buf, pos)
    payload = buf[pos:pos + ln]
    if field == 1:    # BytesList
        out = []
        p = 0
        while p < len(payload):
            tag, p = _read_varint(payload, p)
            vlen, p = _read_varint(payload, p)
            out.append(payload[p:p + vlen])
            p += vlen
        return out
    if field == 2:    # FloatList
        if not payload:
            return []  # TF serializes an empty value list as len-0
        inner_tag, p = _read_varint(payload, 0)
        if inner_tag & 7 == 2:  # packed
            plen, p = _read_varint(payload, p)
            data = payload[p:p + plen]
            return list(struct.unpack(f"<{len(data) // 4}f", data))
        out = []
        p = 0
        while p < len(payload):
            tag, p = _read_varint(payload, p)
            out.append(struct.unpack("<f", payload[p:p + 4])[0])
            p += 4
        return out
    # Int64List
    if not payload:
        return []  # TF serializes an empty value list as len-0
    inner_tag, p = _read_varint(payload, 0)
    out = []
    if inner_tag & 7 == 2:  # packed
        plen, p = _read_varint(payload, p)
        end = p + plen
        while p < end:
            v, p = _read_varint(payload, p)
            out.append(v - (1 << 64) if v >= (1 << 63) else v)
        return out
    p = 0
    while p < len(payload):
        tag, p = _read_varint(payload, p)
        v, p = _read_varint(payload, p)
        out.append(v - (1 << 64) if v >= (1 << 63) else v)
    return out


def decode_example(data: bytes) -> Dict[str, Any]:
    """serialized Example -> dict (single values unwrapped)."""
    row: Dict[str, Any] = {}
    tag, pos = _read_varint(data, 0)        # Example.features
    flen, pos = _read_varint(data, pos)
    feats = data[pos:pos + flen]
    p = 0
    while p < len(feats):
        tag, p = _read_varint(feats, p)     # map entry
        elen, p = _read_varint(feats, p)
        entry = feats[p:p + elen]
        p += elen
        q = 0
        name = None
        values: Any = None
        while q < len(entry):
            etag, q = _read_varint(entry, q)
            eln, q = _read_varint(entry, q)
            payload = entry[q:q + eln]
            q += eln
            if etag >> 3 == 1:
                name = payload.decode()
            else:
                values = _decode_list(payload)
        if name is not None:
            row[name] = values[0] if values and len(values) == 1 else values
    return row
