"""Blocks: the unit of distributed data.

Parity with ``python/ray/data/block.py`` + ``_internal/arrow_block.py`` /
``pandas_block.py`` / ``simple_block.py``: a block is either a plain Python
list ("simple" blocks) or a ``pandas.DataFrame`` ("tabular" blocks; Arrow
tables are accepted at the boundary and held as pandas internally).
``BlockAccessor.for_block`` dispatches format-specific operations.
"""

from __future__ import annotations

import random
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple

import numpy as np


def _is_tabular(block: Any) -> bool:
    import pandas as pd
    return isinstance(block, pd.DataFrame)


def normalize_block(block: Any):
    """Accept arrow Table / dict-of-arrays / DataFrame / list."""
    import pandas as pd
    try:
        import pyarrow as pa
        if isinstance(block, pa.Table):
            return block.to_pandas()
    except ImportError:
        pass
    if isinstance(block, pd.DataFrame):
        return block
    if isinstance(block, dict):
        # Multi-dim columns (e.g. one-hot, images) become object columns
        # of per-row arrays — pandas requires 1-D column arrays.
        cols = {}
        for k, v in block.items():
            arr = np.asarray(v)
            cols[k] = list(arr) if arr.ndim > 1 else arr
        return pd.DataFrame(cols)
    if isinstance(block, np.ndarray):
        block = list(block)
    else:
        block = list(block)
    # Dict rows become tabular at block creation (the reference stores
    # them as arrow blocks), so "numpy" batches are dicts of column
    # arrays rather than object arrays of dicts.
    if block and isinstance(block[0], dict):
        return pd.DataFrame(block)
    return block


class BlockAccessor:
    def __init__(self, block: Any):
        self._block = block

    @staticmethod
    def for_block(block: Any) -> "BlockAccessor":
        if _is_tabular(block):
            return PandasBlockAccessor(block)
        return SimpleBlockAccessor(block)

    def num_rows(self) -> int:
        raise NotImplementedError

    def iter_rows(self) -> Iterator[Any]:
        raise NotImplementedError

    def slice(self, start: int, end: int):
        raise NotImplementedError

    def to_pandas(self):
        raise NotImplementedError

    def to_arrow(self):
        import pyarrow as pa
        return pa.Table.from_pandas(self.to_pandas())

    def to_numpy(self, column: Optional[str] = None):
        raise NotImplementedError

    def to_batch(self, batch_format: str):
        if batch_format in ("pandas", "default"):
            return self.to_pandas()
        if batch_format in ("pyarrow", "arrow"):
            return self.to_arrow()
        if batch_format == "numpy":
            return self.to_numpy()
        raise ValueError(f"unknown batch_format {batch_format!r}")

    def sample_keys(self, n: int, key: Any) -> List[Any]:
        raise NotImplementedError

    def size_bytes(self) -> int:
        raise NotImplementedError

    @staticmethod
    def combine(blocks: List[Any]):
        # Empty LIST partitions (e.g. a sort/shuffle range that received
        # no rows) are typeless and must not decide — or break — the
        # concat (pd.concat rejects a bare list mixed with frames).
        # Empty DataFrames are different: they CARRY the schema and must
        # be kept so an all-empty tabular combine preserves its columns.
        typed = [b for b in blocks if not (isinstance(b, list) and not b)]
        if not typed:
            return []
        if _is_tabular(typed[0]):
            import pandas as pd
            return pd.concat(typed, ignore_index=True)
        out: List[Any] = []
        for b in typed:
            out.extend(b)
        return out


class SimpleBlockAccessor(BlockAccessor):
    def num_rows(self) -> int:
        return len(self._block)

    def iter_rows(self):
        return iter(self._block)

    def slice(self, start, end):
        return self._block[start:end]

    def to_pandas(self):
        import pandas as pd
        return pd.DataFrame({"value": self._block})

    def to_numpy(self, column=None):
        return np.asarray(self._block)

    def sample_keys(self, n, key):
        rows = self._block
        if not rows:
            return []
        idx = random.sample(range(len(rows)), min(n, len(rows)))
        if key is None:
            return [rows[i] for i in idx]
        if callable(key):
            return [key(rows[i]) for i in idx]
        return [rows[i][key] for i in idx]

    def size_bytes(self) -> int:
        import sys
        return sum(sys.getsizeof(r) for r in self._block[:100]) * max(
            1, len(self._block) // max(1, min(100, len(self._block))))


class PandasBlockAccessor(BlockAccessor):
    def num_rows(self) -> int:
        return len(self._block)

    def iter_rows(self):
        for _, row in self._block.iterrows():
            yield dict(row)

    def slice(self, start, end):
        return self._block.iloc[start:end].reset_index(drop=True)

    def to_pandas(self):
        return self._block

    def to_numpy(self, column=None):
        if column is not None:
            return self._block[column].to_numpy()
        # tabular "numpy" batches are dicts of column arrays (ref block.py)
        return {c: self._block[c].to_numpy() for c in self._block.columns}

    def sample_keys(self, n, key):
        df = self._block
        if df.empty:
            return []
        s = df.sample(n=min(n, len(df)))
        if callable(key):
            return [key(dict(r)) for _, r in s.iterrows()]
        return list(s[key])

    def size_bytes(self) -> int:
        return int(self._block.memory_usage(deep=False).sum())
