"""ray_tpu.data — distributed datasets over the object store.

Capability parity with ``python/ray/data/``: block-based Datasets with lazy
fused execution, task/actor-pool compute, two-phase shuffle/sort/groupby,
file IO, windowed pipelines. TPU-native: ``iter_jax_batches`` feeds sharded
device arrays directly onto a mesh.
"""

from ray_tpu.data.block import BlockAccessor
from ray_tpu.data.random_access import RandomAccessDataset  # noqa: F401
from ray_tpu.data.dataset import (ActorPoolStrategy, DataIterator,
                                  Dataset, GroupedData,
                                  TaskPoolStrategy)
from ray_tpu.data.dataset_pipeline import DatasetPipeline
from ray_tpu.data.read_api import (from_arrow, from_items, from_numpy,
                                   from_pandas, range, range_table,
                                   read_binary_files, read_csv, read_json,
                                   read_numpy, read_parquet, read_text,
                                   read_tfrecords)

__all__ = [
    "Dataset", "DataIterator", "RandomAccessDataset", "DatasetPipeline", "GroupedData", "BlockAccessor",
    "ActorPoolStrategy", "TaskPoolStrategy",
    "from_items", "from_pandas", "from_arrow", "from_numpy",
    "range", "range_table", "read_csv", "read_parquet", "read_json",
    "read_numpy", "read_text", "read_binary_files", "read_tfrecords",
]
