"""DatasetPipeline: windowed / repeated streaming over datasets.

Parity with ``python/ray/data/dataset_pipeline.py`` +
``_internal/pipeline_executor.py``: a pipeline is a sequence of windows
(each a Dataset); per-window transforms are deferred and applied as windows
stream through, so stage N of window W overlaps stage N+1 of window W-1
(execution of the next window's transforms is kicked off eagerly as soon as
the previous window is consumed).
"""

from __future__ import annotations

import itertools
from typing import Any, Callable, Iterator, List, Optional

from ray_tpu.data.dataset import Dataset


_NO_REPEAT = 1  # a pipeline without .repeat() runs exactly one epoch


class DatasetPipeline:
    def __init__(self, windows: List[Dataset], repeat: Optional[int] = _NO_REPEAT,
                 transforms: Optional[List[Callable[[Dataset], Dataset]]] = None):
        self._windows = windows
        # number of epochs; None = repeat forever (reference repeat(None))
        self._repeat = repeat
        self._transforms: List[Callable[[Dataset], Dataset]] = list(
            transforms or [])

    # -- transforms (deferred per window) ------------------------------------
    def _with_transform(self, t: Callable[[Dataset], Dataset]) -> "DatasetPipeline":
        return DatasetPipeline(self._windows, self._repeat,
                               self._transforms + [t])

    def map(self, fn, **kw) -> "DatasetPipeline":
        return self._with_transform(lambda ds: ds.map(fn, **kw))

    def map_batches(self, fn, **kw) -> "DatasetPipeline":
        return self._with_transform(lambda ds: ds.map_batches(fn, **kw))

    def filter(self, fn, **kw) -> "DatasetPipeline":
        return self._with_transform(lambda ds: ds.filter(fn, **kw))

    def flat_map(self, fn, **kw) -> "DatasetPipeline":
        return self._with_transform(lambda ds: ds.flat_map(fn, **kw))

    def random_shuffle_each_window(self, *, seed=None) -> "DatasetPipeline":
        return self._with_transform(lambda ds: ds.random_shuffle(seed=seed))

    def repeat(self, times: Optional[int] = None) -> "DatasetPipeline":
        return DatasetPipeline(self._windows, times, self._transforms)

    def rewindow(self, *, blocks_per_window: int) -> "DatasetPipeline":
        # _iter_transformed already expands epochs: do not re-apply repeat
        refs: List = []
        for w in self._iter_transformed():
            refs.extend(w._execute())
        windows = [Dataset(refs[i:i + blocks_per_window])
                   for i in range(0, len(refs), blocks_per_window)]
        return DatasetPipeline(windows)

    # -- execution -----------------------------------------------------------
    def _epochs(self) -> Iterator[int]:
        if self._repeat is None:  # repeat forever
            yield from itertools.count()
        else:
            yield from range(self._repeat)

    def _iter_transformed(self) -> Iterator[Dataset]:
        """Yield transformed windows, prefetching the next window's
        execution while the current one is consumed."""
        for _ in self._epochs():
            pending: Optional[Dataset] = None
            for w in self._windows:
                ds = w
                for t in self._transforms:
                    ds = t(ds)
                if pending is not None:
                    yield pending
                ds._execute()  # kick off this window's tasks (prefetch)
                pending = ds
            if pending is not None:
                yield pending

    def iter_datasets(self) -> Iterator[Dataset]:
        return self._iter_transformed()

    def iter_rows(self) -> Iterator[Any]:
        for ds in self._iter_transformed():
            yield from ds.iter_rows()

    def iter_batches(self, **kw) -> Iterator[Any]:
        for ds in self._iter_transformed():
            yield from ds.iter_batches(**kw)

    def iter_torch_batches(self, **kw) -> Iterator[Any]:
        for ds in self._iter_transformed():
            yield from ds.iter_torch_batches(**kw)

    def iter_jax_batches(self, **kw) -> Iterator[Any]:
        for ds in self._iter_transformed():
            yield from ds.iter_jax_batches(**kw)

    def iter_epochs(self) -> Iterator["DatasetPipeline"]:
        for _ in self._epochs():
            yield DatasetPipeline(self._windows, _NO_REPEAT, self._transforms)

    def split(self, n: int) -> List["DatasetPipeline"]:
        """Split each window across n consumers (reference: pipeline.split
        for per-worker shards). Epochs are already expanded here, so the
        shard pipelines must not re-apply repeat."""
        out: List[List[Dataset]] = [[] for _ in range(n)]
        for w in self._iter_transformed():
            shards = w.split(n)
            for i, s in enumerate(shards):
                out[i].append(s)
        return [DatasetPipeline(ws) for ws in out]

    def count(self) -> int:
        return sum(ds.count() for ds in self._iter_transformed())

    def take(self, n: int = 20) -> List[Any]:
        return list(itertools.islice(self.iter_rows(), n))

    def schema(self):
        for ds in self._iter_transformed():
            return ds.schema()
        return None

    def stats(self) -> str:
        return (f"DatasetPipeline(windows={len(self._windows)}, "
                f"repeat={self._repeat}, "
                f"transforms={len(self._transforms)})")

    __repr__ = stats
