"""Seeded, deterministic fault injection for ray_tpu (see engine.py for the
spec grammar and determinism contract).

Call-site pattern — every injection point in the runtime is guarded by one
module-level bool so the disabled path costs a single attribute check::

    from ray_tpu import chaos
    ...
    if chaos.ENABLED:
        if chaos.inject("rpc.client.send", peer=self.address) == "drop":
            return   # silently discard the frame

Activation:

- ``RAY_TPU_CHAOS=<seed>:<spec>`` in the environment (picked up at import,
  inherited by spawned daemons/workers so cluster-wide schedules work), or
- programmatically: ``chaos.configure(seed, spec)`` / ``chaos.install(
  schedule)`` / ``chaos.clear()``.

Injection-point catalog (the ``ARCHITECTURE.md`` "Failure model" section is
the authoritative doc):

====================  =====================================================
point                 labels / where
====================  =====================================================
rpc.client.connect    peer — RpcClient dial, before the TCP connect
rpc.client.send       peer, method — before a request/push frame is written
rpc.client.recv       peer — after a reply/push frame is read off the wire
rpc.server.recv       peer — server side, after a request frame is read
rpc.server.send       peer, method — before a reply frame is written
state.call            method — StateClient._call, before the RPC
state.reconnect       peer — StateClient._reconnect, before re-dialing
state.heartbeat       node — daemon heartbeat loop, before each beat
node.preempt          node — host daemon preemption watcher, per poll; a
                      "drop" return is the eviction notice (deterministic
                      stand-in for the metadata-server probe). For fleet
                      churn drills, :func:`preempt_storm_spec` builds the
                      periodic-trigger storm form
                      ``node.preempt@{M}%{M}=drop`` from a preemptions/
                      hour rate and the watcher poll period
object.push           peer, object — distributed pusher, per chunk
object.fetch          peer, object — distributed fetch, per source attempt
transport.stream      peer, consumer (object.fetch|drain.migrate|
                      ckpt.restore), offset — shared striped transport,
                      per chunk submission; "drop"/reset fails one stripe
                      so failover retries it on the surviving streams
object.store.get      object — local ObjectStore.get
task.execute          task, name — worker, before user code runs
checkpoint.write      path, rank — engine writer, before each chunk write
checkpoint.commit     stage (manifest|latest), step — rank-0 committer,
                      before the manifest rename / LATEST update
checkpoint.restore    manifest, rank — before chunks are read back
serve.replica.execute deployment, replica — serve replica, before the user
                      callable runs (both the direct path and the
                      micro-batcher's per-batch execution); "delay" makes
                      one replica serve slow — the latency-aware router
                      routes around it and the SLO autoscaler sees its
                      p95 — and "error" fails its requests
collective.op         group, op, rank — collective API entry
                      (ray_tpu.collective.*), before the op is issued; a
                      rank-filtered "delay" makes that rank arrive late
                      at the rendezvous, which the comms plane's
                      arrival-skew attribution must name
collective.quant      group, op, rank — compression tier
                      (collective/quantization.py), before one rank
                      block-quantizes its payload; "error" makes a
                      quantized op fail loudly (the rendezvous propagates
                      it to every rank) and a rank-filtered "delay"
                      stretches exactly the compression step, which the
                      ``collective.quantize`` perf histogram must show
autopilot.apply       knob — actuator layer (autopilot/actuators.py),
                      after the bounds clamp and before the knob write
                      lands; "error" must leave the previous value
                      intact and journal a ``failed`` decision
drill.reader          (no labels) — autopilot A/B drill synthetic input
                      pipeline; a "drop" return starves the reader for
                      one step (fixed schedule, both arms)
drill.collective      rank — autopilot A/B drill synthetic collective;
                      a rank-filtered "drop" return adds arrival skew
====================  =====================================================
"""

from __future__ import annotations

import logging
import os
from typing import Optional

from ray_tpu.chaos.engine import (ChaosConnectionReset, ChaosError,
                                  FaultRule, FaultSchedule, parse_env,
                                  parse_spec, register_exit_hook)

__all__ = [
    "ENABLED", "ChaosError", "ChaosConnectionReset", "FaultRule",
    "FaultSchedule", "parse_spec", "parse_env", "configure", "install",
    "clear", "inject", "schedule", "set_observer", "trace_lines", "trace_text",
    "register_exit_hook", "preempt_storm_spec",
]

logger = logging.getLogger("ray_tpu")

#: Fast-path guard — False means every injection point is a no-op attribute
#: check. Only mutated via install()/clear().
ENABLED = False

_schedule: Optional[FaultSchedule] = None

#: Optional fault observer installed by ray_tpu.observability.enable():
#: called as fn(point, labels, action) after every fault that fires (the
#: action name, or the exception class name for raising actions). Kept as
#: a registration hook — chaos stays importable with zero non-stdlib deps.
_observer = None


def set_observer(fn) -> None:
    global _observer
    _observer = fn  # raylint: allow(data-race) observer installed once during chaos setup before faults fire


def install(sched: FaultSchedule) -> FaultSchedule:
    """Install ``sched`` as the process-wide schedule and enable injection."""
    global ENABLED, _schedule
    _schedule = sched  # raylint: allow(data-race) schedule installed once during chaos setup; inject() reads a GIL-atomic snapshot
    ENABLED = True
    return sched


def configure(seed: int, spec: str) -> FaultSchedule:
    """Compile ``spec`` with ``seed`` and install it."""
    return install(parse_spec(seed, spec))


def clear():
    """Disable injection and drop the schedule."""
    global ENABLED, _schedule
    ENABLED = False
    _schedule = None  # raylint: allow(data-race) uninstall is test teardown; inject() reads a GIL-atomic snapshot and tolerates None


def schedule() -> Optional[FaultSchedule]:
    return _schedule


def inject(point: str, **labels) -> Optional[str]:
    """Consult the schedule at a named injection point.

    Returns ``"drop"`` (caller discards the event), ``"delay"`` (the sleep
    already happened), or ``None`` (no fault). Raises
    :class:`ChaosConnectionReset` / :class:`ChaosError`, or exits the
    process, per the matched rule's action.
    """
    sched = _schedule
    if sched is None:
        return None
    obs = _observer
    if obs is None:
        return sched.fire(point, labels)
    try:
        action = sched.fire(point, labels)
    except BaseException as e:
        obs(point, labels, type(e).__name__)
        raise
    if action is not None:
        obs(point, labels, action)
    return action


def preempt_storm_spec(preempts_per_hour: float, poll_ms: float,
                       node: Optional[str] = None) -> str:
    """Spec fragment for a deterministic preemption storm.

    Converts a fleet churn rate (``preempts_per_hour``, per node matching
    the filter) and the preemption watcher's poll period into the periodic
    trigger form ``node.preempt[@M%M]=drop``: every M-th poll of the
    watcher returns an eviction notice, so the inter-preemption gap is
    ``M * poll_ms`` — the closest deterministic stand-in for a Poisson
    churn process that still replays bit-identically from the seed.
    Combine with other fragments via ``,`` and activate through
    ``RAY_TPU_CHAOS=<seed>:<spec>`` (daemons inherit the env).
    """
    if preempts_per_hour <= 0.0 or poll_ms <= 0.0:
        raise ValueError("preempt_storm_spec needs positive rate and poll")
    polls_per_hour = 3600_000.0 / poll_ms
    every = max(1, round(polls_per_hour / preempts_per_hour))
    key = f"[node={node}]" if node else ""
    return f"node.preempt{key}@{every}%{every}=drop"


def trace_lines():
    """Trace lines of the installed schedule ([] when none)."""
    sched = _schedule
    return sched.trace_lines() if sched is not None else []


def trace_text() -> str:
    sched = _schedule
    return sched.trace_text() if sched is not None else ""


def _init_from_env():
    value = os.environ.get("RAY_TPU_CHAOS")
    if not value:
        return
    try:
        install(parse_env(value))
    except ValueError:
        # A typo in the spec must not silently run the workload fault-free:
        # fail loudly at import.
        raise
    logger.warning("chaos: fault injection ENABLED from RAY_TPU_CHAOS=%s",
                   value)


_init_from_env()
