"""Deterministic fault-injection engine.

A :class:`FaultSchedule` is a seeded, ordered list of rules compiled from a
spec string (``RAY_TPU_CHAOS=<seed>:<spec>``) or built programmatically.
Runtime choke points call :func:`ray_tpu.chaos.inject` with a *point name*
(e.g. ``rpc.client.send``) and labels (``peer=...``, ``method=...``); the
schedule decides — deterministically, as a pure function of the seed and the
sequence of matching events — whether to inject a fault there.

Spec grammar (rules separated by ``;``)::

    rule    := point[ "[" key "=" value-glob "]" ][ "@" trigger ] "=" action
    point   := fnmatch glob over injection-point names
    trigger := N          fire on the Nth matching event only (default 1)
             | N+         fire on the Nth and every later matching event
             | N%M        fire when (count - N) % M == 0 and count >= N
             | pP         fire each event with probability P (seeded RNG)
    action  := delay(SECONDS) | drop | reset | error | error(MSG) | exit
             | exit(CODE)

Examples::

    RAY_TPU_CHAOS="42:rpc.client.send@3=reset"
    RAY_TPU_CHAOS="7:state.call[method=HEARTBEAT]@2%5=drop;object.push@p0.1=delay(0.05)"

Determinism: each rule owns a ``random.Random`` seeded from
``(schedule seed, rule index)`` and a per-rule match counter; probability
rules consume exactly one RNG draw per *matching* event whether or not they
fire, so the decision stream depends only on the seed and the event
sequence. Every fired fault appends one line to an in-memory trace
(:meth:`FaultSchedule.trace_lines`) — two runs over the same event sequence
with the same seed produce byte-identical traces.

This module is intentionally stdlib-only: ``rpc.py`` (the lowest layer)
imports it, so it must not import any ``ray_tpu`` internals.  Chaos
exceptions subclass stdlib ``ConnectionError`` so call sites can translate
them through their normal error paths.
"""

from __future__ import annotations

import fnmatch
import os
import random
import re
import sys
import threading
import time
from typing import Dict, List, Optional, Tuple

__all__ = [
    "ChaosError", "ChaosConnectionReset", "FaultRule", "FaultSchedule",
    "parse_spec", "parse_env", "register_exit_hook",
]

# Pre-death callbacks for the ``exit`` action, called (point, exit_code)
# right before ``os._exit``. Registration-hook pattern (same reason as the
# observer in ``chaos/__init__``): this module stays stdlib-only, yet the
# flight recorder can seal a crash bundle on the way down — ``exit`` is the
# deterministic stand-in for a SIGKILL'd host, and a hook here is the only
# cleanup that runs (``os._exit`` skips atexit/finally).
_exit_hooks: List = []


def register_exit_hook(fn) -> None:
    """Register ``fn(point, exit_code)`` to run before a chaos ``exit``
    kills the process. Hooks are best-effort: exceptions are swallowed
    (the process is dying either way) and must not block."""
    if fn not in _exit_hooks:
        _exit_hooks.append(fn)  # raylint: allow(data-race) GIL-atomic list append at setup; read once at injected process exit


class ChaosError(RuntimeError):
    """Injected generic failure (``error`` action)."""


class ChaosConnectionReset(ConnectionError):
    """Injected connection reset (``reset`` action).

    Subclasses ``ConnectionError`` so transport layers translate it exactly
    like a real peer reset (``RpcClient`` wraps it into
    ``RpcConnectionError``; the backoff policy classifies it retryable).
    """


_TRIGGER_RE = re.compile(r"^(?:(\d+)(\+)?|(\d+)%(\d+)|p(0?\.\d+|1(?:\.0*)?))$")
_ACTION_RE = re.compile(r"^(delay|drop|reset|error|exit)(?:\((.*)\))?$")


class FaultRule:
    """One compiled rule: point glob + optional label filter + trigger +
    action. Mutable state (match counter, armed flag, RNG) lives here and is
    only touched under the owning schedule's lock."""

    __slots__ = ("point_glob", "label_key", "label_glob", "trig_kind",
                 "trig_n", "trig_m", "trig_p", "action", "arg", "index",
                 "count", "armed", "rng", "spec")

    def __init__(self, point_glob: str, label_key: Optional[str],
                 label_glob: Optional[str], trig_kind: str, trig_n: int,
                 trig_m: int, trig_p: float, action: str, arg, index: int,
                 spec: str):
        self.point_glob = point_glob
        self.label_key = label_key
        self.label_glob = label_glob
        self.trig_kind = trig_kind    # "nth" | "from" | "every" | "prob"
        self.trig_n = trig_n
        self.trig_m = trig_m
        self.trig_p = trig_p
        self.action = action          # "delay"|"drop"|"reset"|"error"|"exit"
        self.arg = arg                # float seconds | str msg | int code
        self.index = index
        self.spec = spec              # original rule text (for traces)
        self.count = 0                # matching events seen so far
        self.armed = True             # one-shot "nth" rules disarm on fire
        self.rng = None               # seeded lazily by the schedule

    def matches(self, point: str, labels: Dict[str, str]) -> bool:
        if not fnmatch.fnmatchcase(point, self.point_glob):
            return False
        if self.label_key is not None:
            val = labels.get(self.label_key)
            if val is None or not fnmatch.fnmatchcase(str(val),
                                                      self.label_glob):
                return False
        return True

    def should_fire(self) -> bool:
        """Call once per matching event (under the schedule lock). Advances
        the counter / RNG stream; returns True when the fault fires."""
        self.count += 1
        k = self.trig_kind
        if k == "prob":
            # Always draw, even when disarmed impossible here (prob rules
            # never disarm): decision stream = f(seed, event ordinal).
            return self.rng.random() < self.trig_p
        if k == "nth":
            if self.armed and self.count == self.trig_n:
                self.armed = False
                return True
            return False
        if k == "from":
            return self.count >= self.trig_n
        # every: N, N+M, N+2M, ...
        return (self.count >= self.trig_n
                and (self.count - self.trig_n) % self.trig_m == 0)


def _parse_rule(text: str, index: int) -> FaultRule:
    src = text.strip()
    if "=" not in src:
        raise ValueError(f"chaos rule {src!r}: missing '=action'")
    lhs, _, action_src = src.partition("=")
    # The first '=' inside [...] belongs to the label filter; re-split if so.
    if "[" in lhs and "]" not in lhs:
        m = re.match(r"^([^\[]+\[[^\]]*\][^=]*)=(.*)$", src)
        if not m:
            raise ValueError(f"chaos rule {src!r}: unbalanced label filter")
        lhs, action_src = m.group(1), m.group(2)
    lhs = lhs.strip()
    action_src = action_src.strip()

    trig_src = "1"
    if "@" in lhs:
        lhs, _, trig_src = lhs.rpartition("@")
        lhs = lhs.strip()
        trig_src = trig_src.strip()

    label_key = label_glob = None
    m = re.match(r"^(.*?)\[([^=\]]+)=([^\]]*)\]$", lhs)
    if m:
        lhs, label_key, label_glob = (m.group(1).strip(), m.group(2).strip(),
                                      m.group(3).strip())
    if not lhs:
        raise ValueError(f"chaos rule {src!r}: empty point glob")

    tm = _TRIGGER_RE.match(trig_src)
    if not tm:
        raise ValueError(f"chaos rule {src!r}: bad trigger {trig_src!r} "
                         "(want N, N+, N%M, or pP)")
    trig_kind, trig_n, trig_m, trig_p = "nth", 1, 1, 0.0
    if tm.group(5) is not None:
        trig_kind, trig_p = "prob", float(tm.group(5))
    elif tm.group(3) is not None:
        trig_kind = "every"
        trig_n, trig_m = int(tm.group(3)), int(tm.group(4))
        if trig_m <= 0:
            raise ValueError(f"chaos rule {src!r}: modulus must be > 0")
    else:
        trig_n = int(tm.group(1))
        trig_kind = "from" if tm.group(2) else "nth"
    if trig_kind in ("nth", "from", "every") and trig_n <= 0:
        raise ValueError(f"chaos rule {src!r}: trigger ordinal must be >= 1")

    am = _ACTION_RE.match(action_src)
    if not am:
        raise ValueError(f"chaos rule {src!r}: bad action {action_src!r} "
                         "(want delay(s)|drop|reset|error[(msg)]|exit[(code)])")
    action, raw_arg = am.group(1), am.group(2)
    arg = None
    if action == "delay":
        if raw_arg is None:
            raise ValueError(f"chaos rule {src!r}: delay needs seconds")
        arg = float(raw_arg)
        if arg < 0:
            raise ValueError(f"chaos rule {src!r}: negative delay")
    elif action == "error":
        arg = raw_arg if raw_arg else "injected fault"
    elif action == "exit":
        arg = int(raw_arg) if raw_arg else 1
    elif raw_arg:
        raise ValueError(f"chaos rule {src!r}: {action} takes no argument")
    return FaultRule(lhs, label_key, label_glob, trig_kind, trig_n, trig_m,
                     trig_p, action, arg, index, src)


def parse_spec(seed: int, spec: str) -> "FaultSchedule":
    """Compile ``spec`` (rules separated by ``;``) into a schedule."""
    rules = []
    for part in spec.split(";"):
        part = part.strip()
        if part:
            rules.append(_parse_rule(part, len(rules)))
    if not rules:
        raise ValueError(f"chaos spec {spec!r}: no rules")
    return FaultSchedule(seed, rules)


def parse_env(value: str) -> "FaultSchedule":
    """Parse the ``RAY_TPU_CHAOS`` env value: ``<seed>:<spec>``."""
    seed_src, sep, spec = value.partition(":")
    if not sep or not seed_src.strip().isdigit():
        raise ValueError(
            f"RAY_TPU_CHAOS={value!r}: want '<seed>:<spec>', e.g. "
            "'42:rpc.client.send@3=reset'")
    return parse_spec(int(seed_src), spec)


class FaultSchedule:
    """Process-wide, seeded fault schedule.

    ``fire(point, labels)`` is the single entry point: it advances every
    matching rule's counter, executes the first rule that fires (rule order
    breaks ties), records a trace line, and returns/raises according to the
    action. Thread-safe; the decision + trace append happen atomically under
    one lock (the ``delay`` sleep happens outside it).
    """

    def __init__(self, seed: int, rules: List[FaultRule]):
        self.seed = seed
        self.rules = rules
        for r in rules:
            # str seeding hashes with sha512 — stable across processes and
            # Python versions (tuple seeding is deprecated since 3.9)
            r.rng = random.Random(f"{seed}:{r.index}")
        self._lock = threading.Lock()
        self._trace: List[str] = []
        self._events = 0
        self._trace_path = os.environ.get("RAY_TPU_CHAOS_TRACE") or None

    # -- bookkeeping --------------------------------------------------------

    def trace_lines(self) -> List[str]:
        with self._lock:
            return list(self._trace)

    def trace_text(self) -> str:
        return "".join(line + "\n" for line in self.trace_lines())

    def _record(self, line: str):
        self._trace.append(line)
        if self._trace_path:
            try:
                with open(self._trace_path, "a") as f:
                    f.write(f"[pid={os.getpid()}] {line}\n")
            except OSError:
                pass

    # -- the hot path -------------------------------------------------------

    def fire(self, point: str, labels: Dict[str, str]) -> Optional[str]:
        """Consult the schedule for one event. Returns the action name that
        fired (``"delay"``/``"drop"``), ``None`` when nothing fired, or
        raises (``reset``/``error``) / exits the process (``exit``)."""
        fired: Optional[FaultRule] = None
        delay_s = 0.0
        with self._lock:
            self._events += 1
            n = self._events
            for r in self.rules:
                if not r.matches(point, labels):
                    continue
                if r.should_fire() and fired is None:
                    fired = r
                    # keep advancing later matching rules' counters so their
                    # decision streams stay aligned with the event sequence
            if fired is None:
                return None
            lbl = ",".join(f"{k}={labels[k]}" for k in sorted(labels))
            self._record(f"{n:06d} {point} [{lbl}] rule#{fired.index}"
                         f"<{fired.spec}> hit={fired.count}"
                         f" -> {fired.action}"
                         f"{'' if fired.arg is None else f'({fired.arg})'}")
            if fired.action == "delay":
                delay_s = fired.arg
        act = fired.action
        if act == "delay":
            if delay_s > 0:
                time.sleep(delay_s)
            return "delay"
        if act == "drop":
            return "drop"
        if act == "reset":
            raise ChaosConnectionReset(
                f"chaos: injected connection reset at {point}"
                + (f" ({labels})" if labels else ""))
        if act == "error":
            raise ChaosError(f"chaos: {fired.arg} at {point}")
        # exit: hard process death, like a SIGKILL'd host. Flush stderr so
        # the trace tail is visible in test logs, then die without cleanup.
        sys.stderr.write(f"chaos: injected process exit({fired.arg}) at "
                         f"{point} pid={os.getpid()}\n")
        sys.stderr.flush()
        for hook in list(_exit_hooks):
            try:
                hook(point, fired.arg)
            except BaseException:  # noqa: BLE001  # raylint: allow(swallow) dying process: sealing is best-effort
                pass
        os._exit(fired.arg)
