"""User-facing error types.

Parity with ``python/ray/exceptions.py`` in the reference: task errors wrap
the remote traceback and re-raise at ``get``; actor/object/node failures have
dedicated types so retry logic can discriminate.
"""

from __future__ import annotations

import traceback


class RayTpuError(Exception):
    """Base class for all framework errors."""


class TaskError(RayTpuError):
    """A remote task raised an exception; re-raised at ``get``.

    Mirrors ``RayTaskError`` (reference ``python/ray/exceptions.py``): carries
    the remote traceback string and the original cause.
    """

    def __init__(self, function_name: str, cause: BaseException,
                 remote_traceback: str = ""):
        self.function_name = function_name
        self.cause = cause
        self.remote_traceback = remote_traceback or "".join(
            traceback.format_exception(type(cause), cause, cause.__traceback__))
        super().__init__(
            f"task {function_name} failed: {type(cause).__name__}: {cause}\n"
            f"{self.remote_traceback}")

    def __reduce__(self):
        # Exception.__reduce__ replays BaseException.args into __init__,
        # which doesn't match this signature — rebuild from our fields so
        # the error survives pickling (e.g. across the thin-client wire).
        return (TaskError, (self.function_name, self.cause,
                            self.remote_traceback))


class ActorError(RayTpuError):
    pass


class ActorDiedError(ActorError):
    """The actor is dead (killed, crashed past max_restarts, or owner exited)."""


class ActorUnavailableError(ActorError):
    """The actor is temporarily unavailable (restarting)."""


class ObjectLostError(RayTpuError):
    """Object was evicted/lost and could not be reconstructed from lineage."""


class ObjectReconstructionFailedError(ObjectLostError):
    pass


class GetTimeoutError(RayTpuError, TimeoutError):
    pass


class TaskCancelledError(RayTpuError):
    def __init__(self, task_id=None):
        self.task_id = task_id
        super().__init__(f"task {task_id} was cancelled")


class WorkerCrashedError(RayTpuError):
    pass


class NodeDiedError(RayTpuError):
    pass


class RuntimeEnvSetupError(RayTpuError):
    pass


class PlacementGroupSchedulingError(RayTpuError):
    pass


class ServeOverloadedError(RayTpuError):
    """Serve shed this request: every replica's queue exceeds its latency
    budget (router-side) or the request aged out of a replica's admission
    queue (replica-side).  The HTTP proxy maps it to 503 + Retry-After;
    programmatic callers should back off and retry.  Subclasses
    ``RayTpuError`` so it re-raises raw at ``get()`` instead of being
    wrapped in ``TaskError`` — the router and proxy discriminate on it.
    """

    def __init__(self, message: str, retry_after_s: float = 1.0):
        self.retry_after_s = retry_after_s
        super().__init__(message)

    def __reduce__(self):
        return (type(self), (self.args[0] if self.args else "",
                             self.retry_after_s))


class BatchExecutionError(RayTpuError):
    """A serve batch function failed for a whole batch.  Distinguishes
    "I was collateral damage in someone else's batch" from "my request
    was bad": carries the batch size and the originating request ids so
    callers can tell which.  When singleton retry is enabled
    (``serve_batch_retry_singletons``), members are re-run alone and
    receive their *own* errors instead of this batch-level tag.
    """

    def __init__(self, function_name: str, batch_size: int,
                 request_ids, cause: BaseException):
        self.function_name = function_name
        self.batch_size = batch_size
        self.request_ids = tuple(request_ids)
        self.cause = cause
        super().__init__(
            f"batched function {function_name} failed for a batch of "
            f"{batch_size} (request ids {list(self.request_ids)}): "
            f"{type(cause).__name__}: {cause}")

    def __reduce__(self):
        return (type(self), (self.function_name, self.batch_size,
                             self.request_ids, self.cause))
