"""Trainable APIs: class-based and function-based.

Parity with ``python/ray/tune/trainable/trainable.py`` (class API:
``setup``/``step``/``save_checkpoint``/``load_checkpoint``) and
``function_trainable.py`` (function API with a reporter thread pumping
``session.report`` results to the driver one ``train()`` call at a time).
"""

from __future__ import annotations
import logging

import os
import queue
import threading
import time
import uuid
from typing import Any, Callable, Dict, Optional

from ray_tpu.tune import session as tune_session

logger = logging.getLogger("ray_tpu")

RESULT_DONE = "done"
TRAINING_ITERATION = "training_iteration"


class Trainable:
    """Class API. Subclass and override ``setup/step/save_checkpoint/
    load_checkpoint`` (reference ``trainable.py``)."""

    def __init__(self, config: Optional[Dict[str, Any]] = None,
                 logdir: Optional[str] = None):
        self.config = config or {}
        self._logdir = logdir or os.path.join(
            "/tmp/ray_tpu_results", f"trainable_{uuid.uuid4().hex[:8]}")
        os.makedirs(self._logdir, exist_ok=True)
        self._iteration = 0
        self._time_total = 0.0
        self.setup(self.config)

    # -- overridable ------------------------------------------------------
    def setup(self, config: Dict[str, Any]):
        pass

    def step(self) -> Dict[str, Any]:
        raise NotImplementedError

    def save_checkpoint(self, checkpoint_dir: str) -> Any:
        """Return a dict (or write files under checkpoint_dir and return it)."""
        return {}

    def load_checkpoint(self, checkpoint: Any):
        pass

    def cleanup(self):
        pass

    def reset_config(self, new_config: Dict[str, Any]) -> bool:
        """Return True if the trainable supports in-place config reset
        (used by PBT to avoid actor teardown)."""
        return False

    # -- driver-facing ----------------------------------------------------
    @property
    def iteration(self) -> int:
        return self._iteration

    @property
    def logdir(self) -> str:
        return self._logdir

    def train(self) -> Dict[str, Any]:
        start = time.time()
        result = self.step() or {}
        self._iteration += 1
        self._time_total += time.time() - start
        result.setdefault(RESULT_DONE, False)
        result[TRAINING_ITERATION] = self._iteration
        result["time_total_s"] = self._time_total
        result["time_this_iter_s"] = time.time() - start
        result["timestamp"] = time.time()
        return result

    def save(self) -> Dict[str, Any]:
        ckpt_dir = os.path.join(self._logdir,
                                f"checkpoint_{self._iteration:06d}")
        os.makedirs(ckpt_dir, exist_ok=True)
        data = self.save_checkpoint(ckpt_dir)
        return {"data": data, "iteration": self._iteration, "dir": ckpt_dir}

    def restore(self, payload: Dict[str, Any]):
        self._iteration = payload.get("iteration", 0)
        self.load_checkpoint(payload.get("data"))

    def stop(self):
        self.cleanup()

    def reset(self, new_config: Dict[str, Any]) -> bool:
        ok = self.reset_config(new_config)
        if ok:
            self.config = new_config
            self._iteration = 0
            self._time_total = 0.0
        return ok


class FunctionTrainable(Trainable):
    """Wraps ``fn(config)`` in a background thread; each ``train()`` call
    returns the next ``tune.report`` result (reference
    ``function_trainable.py``: reporter thread + result queue)."""

    _fn: Callable = None  # set by wrap_function subclass

    def setup(self, config: Dict[str, Any]):
        self._results: "queue.Queue" = queue.Queue()  # raylint: allow(data-race) assigned in setup() before the runner thread starts; queue.Queue is internally synchronized
        self._continue: "queue.Queue" = queue.Queue()
        self._finished = False
        self._last_metrics: Dict[str, Any] = {}
        self._last_checkpoint: Optional[Dict[str, Any]] = None
        self._restore_checkpoint: Optional[Dict[str, Any]] = None
        self._thread: Optional[threading.Thread] = None

    def _runner(self):
        tune_session._init_session(self)
        try:
            self._fn(self.config)
        except BaseException as e:  # noqa: BLE001 - propagated to driver
            self._results.put(e)  # raylint: allow(data-race) queue.Queue is internally synchronized
        finally:
            tune_session._shutdown_session()
            self._results.put(None)  # raylint: allow(data-race) queue.Queue is internally synchronized (sentinel: function returned)

    def _report(self, metrics: Dict[str, Any],
                checkpoint: Optional[Dict[str, Any]] = None):
        if checkpoint is not None:
            self._last_checkpoint = {"data": checkpoint,
                                     "iteration": self._iteration + 1}
        self._results.put(dict(metrics))  # raylint: allow(data-race) queue.Queue is internally synchronized
        self._continue.get()  # block until driver consumed (backpressure)

    def _get_checkpoint(self) -> Optional[Dict[str, Any]]:
        if self._restore_checkpoint is not None:
            return self._restore_checkpoint.get("data")
        return None

    def step(self) -> Dict[str, Any]:
        if self._thread is None:
            self._thread = threading.Thread(target=self._runner, daemon=True)
            self._thread.start()
        item = self._results.get()
        if isinstance(item, BaseException):
            self._finished = True
            self._results.get()  # drain the completion sentinel
            raise item
        if item is None:
            self._finished = True
            # final result: the last reported metrics, marked done
            # (reference function_trainable.py final-result semantics)
            final = dict(self._last_metrics)
            final[RESULT_DONE] = True
            return final
        self._last_metrics = dict(item)
        self._continue.put(True)
        item.setdefault(RESULT_DONE, False)
        return item

    def save_checkpoint(self, checkpoint_dir: str):
        return (self._last_checkpoint or {}).get("data")

    def load_checkpoint(self, checkpoint: Any):
        self._restore_checkpoint = {"data": checkpoint}

    def cleanup(self):
        if self._thread is not None and self._thread.is_alive():
            # let the fn thread run to completion on next report
            try:
                self._continue.put_nowait(True)
            except Exception as e:
                logger.debug("continue signal failed: %s", e)


def wrap_function(fn: Callable) -> type:
    """Create a FunctionTrainable subclass bound to ``fn``."""
    return type(f"func_{getattr(fn, '__name__', 'trainable')}",
                (FunctionTrainable,), {"_fn": staticmethod(fn)})
