"""Experiment-directory syncing.

Parity with ``python/ray/tune/syncer.py``: a ``SyncConfig`` names an
``upload_dir`` URI; a ``Syncer`` mirrors the experiment directory there
periodically and at experiment end, so results/checkpoints survive the
driver host. The reference ships cloud syncers behind pyarrow's fs; this
environment has no egress, so the built-in syncer handles ``file://`` /
plain paths (NFS-style durable storage) and custom ``Syncer`` subclasses
plug in anything else.
"""

from __future__ import annotations

import os
import shutil
import time
from dataclasses import dataclass
from typing import Optional


class Syncer:
    """Mirror a local directory to remote storage (one-way, newest wins)."""

    def sync_up(self, local_dir: str, remote_dir: str) -> bool:
        raise NotImplementedError

    def sync_down(self, remote_dir: str, local_dir: str) -> bool:
        raise NotImplementedError


class _LocalMirrorSyncer(Syncer):
    """rsync-style incremental copy for file:// / plain-path targets:
    only files whose (size, mtime) changed are rewritten, so periodic
    syncs of a mostly-static experiment dir are cheap. With
    ``prune_stale`` (the default) the mirror also DELETES entries absent
    from the source — rolled-back or renamed trial checkpoints must not
    accumulate in the durable copy forever."""

    def __init__(self, prune_stale: bool = True):
        self.prune_stale = prune_stale

    @staticmethod
    def _strip(uri: str) -> str:
        return uri[len("file://"):] if uri.startswith("file://") else uri

    def _mirror(self, src: str, dst: str) -> bool:
        if not os.path.isdir(src):
            return False
        os.makedirs(dst, exist_ok=True)
        seen_dirs, seen_files = {"."}, set()
        for root, _dirs, files in os.walk(src):
            rel = os.path.relpath(root, src)
            seen_dirs.add(rel)
            troot = os.path.join(dst, rel) if rel != "." else dst
            os.makedirs(troot, exist_ok=True)
            for name in files:
                seen_files.add(os.path.normpath(os.path.join(rel, name)))
                s = os.path.join(root, name)
                d = os.path.join(troot, name)
                try:
                    st = os.stat(s)
                    if os.path.exists(d):
                        dt = os.stat(d)
                        if (dt.st_size == st.st_size
                                and dt.st_mtime >= st.st_mtime):
                            continue
                    shutil.copy2(s, d)
                except OSError:
                    return False
        if self.prune_stale:
            self._prune(dst, seen_dirs, seen_files)
        return True

    @staticmethod
    def _prune(dst: str, seen_dirs, seen_files) -> None:
        for root, dirs, files in os.walk(dst, topdown=True):
            rel = os.path.relpath(root, dst)
            stale_dirs = [d for d in dirs
                          if os.path.normpath(os.path.join(rel, d))
                          not in seen_dirs]
            for d in stale_dirs:
                shutil.rmtree(os.path.join(root, d), ignore_errors=True)
                dirs.remove(d)  # pruned subtree: don't descend
            for name in files:
                if os.path.normpath(os.path.join(rel, name)) in seen_files:
                    continue
                try:
                    os.unlink(os.path.join(root, name))
                except OSError:
                    pass  # raylint: allow(swallow) best-effort cleanup; next sync retries

    def sync_up(self, local_dir: str, remote_dir: str) -> bool:
        return self._mirror(local_dir, self._strip(remote_dir))

    def sync_down(self, remote_dir: str, local_dir: str) -> bool:
        return self._mirror(self._strip(remote_dir), local_dir)


@dataclass
class SyncConfig:
    """Reference ``tune/syncer.py:SyncConfig``."""

    upload_dir: Optional[str] = None
    syncer: Optional[Syncer] = None
    sync_period: float = 300.0
    # delete mirror entries absent from the source (stale checkpoints of
    # rolled-back/renamed trials); off = pure-additive mirroring
    prune_stale: bool = True

    def get_syncer(self) -> Optional[Syncer]:
        if not self.upload_dir:
            return None
        if self.syncer is not None:
            return self.syncer
        if (self.upload_dir.startswith("file://")
                or "://" not in self.upload_dir):
            return _LocalMirrorSyncer(prune_stale=self.prune_stale)
        raise ValueError(
            f"no syncer for {self.upload_dir!r}: schemes other than "
            "file:// need an explicit SyncConfig(syncer=...) (no cloud "
            "egress in this runtime)")


class _SyncerState:
    """Runner-side driver of one experiment's syncing."""

    def __init__(self, sync_config: Optional[SyncConfig],
                 experiment_dir: str, experiment_name: str):
        self.cfg = sync_config
        self.syncer = sync_config.get_syncer() if sync_config else None
        self.local = experiment_dir
        self.remote = (os.path.join(sync_config.upload_dir, experiment_name)
                       if self.syncer else "")
        self._last = 0.0
        self._warned = False

    def maybe_sync(self, force: bool = False) -> bool:
        if self.syncer is None:
            return False
        now = time.monotonic()
        if not force and now - self._last < self.cfg.sync_period:
            return False
        self._last = now
        ok = self.syncer.sync_up(self.local, self.remote)
        if not ok:
            # Every failure is loud (a driver crash between now and the
            # end of the run means the durable mirror is stale), but
            # repeats of the SAME broken target only log once.
            import logging
            if not self._warned:
                self._warned = True
                logging.getLogger("ray_tpu").warning(
                    "experiment sync to %s FAILED — the durable mirror "
                    "is missing or partial (further failures for this "
                    "run are silenced)", self.remote)
        else:
            self._warned = False
        return ok
