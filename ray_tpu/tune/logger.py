"""Result loggers / callbacks.

Parity with ``python/ray/tune/logger/`` (CSV/JSON/TBX logger callbacks) and
the callback interface in ``tune/callback.py``.
"""

from __future__ import annotations

import csv
import json
import os
from typing import Any, Dict, Optional


class Callback:
    def on_trial_start(self, trial):
        pass

    def on_trial_result(self, trial, result: Dict[str, Any]):
        pass

    def on_trial_complete(self, trial):
        pass


def _flat(d: Dict[str, Any], prefix="") -> Dict[str, Any]:
    out = {}
    for k, v in d.items():
        key = f"{prefix}{k}"
        if isinstance(v, dict):
            out.update(_flat(v, key + "/"))
        else:
            out[key] = v
    return out


class JsonLoggerCallback(Callback):
    """Writes result.json (one JSON line per result) per trial."""

    def on_trial_result(self, trial, result):
        if not trial.logdir:
            return
        with open(os.path.join(trial.logdir, "result.json"), "a") as f:
            f.write(json.dumps(result, default=repr) + "\n")


class CSVLoggerCallback(Callback):
    """Writes progress.csv per trial."""

    def __init__(self):
        self._writers: Dict[str, Any] = {}
        self._files: Dict[str, Any] = {}

    def on_trial_result(self, trial, result):
        if not trial.logdir:
            return
        flat = _flat(result)
        if trial.trial_id not in self._writers:
            f = open(os.path.join(trial.logdir, "progress.csv"), "w",
                     newline="")
            w = csv.DictWriter(f, fieldnames=sorted(flat.keys()),
                               extrasaction="ignore")
            w.writeheader()
            self._files[trial.trial_id] = f
            self._writers[trial.trial_id] = w
        self._writers[trial.trial_id].writerow(
            {k: flat.get(k) for k in self._writers[trial.trial_id].fieldnames})
        self._files[trial.trial_id].flush()

    def on_trial_complete(self, trial):
        f = self._files.pop(trial.trial_id, None)
        self._writers.pop(trial.trial_id, None)
        if f:
            f.close()


class TBXLoggerCallback(Callback):
    """TensorBoard via tensorboardX (reference ``tune/logger/tensorboardx.py``)."""

    def __init__(self):
        self._writers: Dict[str, Any] = {}

    def on_trial_result(self, trial, result):
        if not trial.logdir:
            return
        try:
            from tensorboardX import SummaryWriter
        except ImportError:
            return
        if trial.trial_id not in self._writers:
            self._writers[trial.trial_id] = SummaryWriter(trial.logdir)
        w = self._writers[trial.trial_id]
        step = result.get("training_iteration", 0)
        for k, v in _flat(result).items():
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                w.add_scalar(k, v, step)

    def on_trial_complete(self, trial):
        w = self._writers.pop(trial.trial_id, None)
        if w:
            w.close()
