"""ray_tpu.tune — hyperparameter optimization engine.

TPU-native re-design of the capabilities of ``python/ray/tune/``: trials are
``ray_tpu`` actors (one Trainable each) driven by an event-loop TrialRunner
with pluggable schedulers (ASHA/HyperBand/PBT/median-stopping) and searchers
(grid/random + wrappers). Trials that train on TPU share the host's device
mesh; checkpoints interoperate with ``ray_tpu.air.Checkpoint``.
"""

from ray_tpu.tune.analysis import ExperimentAnalysis, ResultGrid
from ray_tpu.tune.sample import (choice, grid_search, lograndint, loguniform,
                                 qloguniform, quniform, randint, randn,
                                 sample_from, uniform)
from ray_tpu.tune.schedulers import (AsyncHyperBandScheduler, FIFOScheduler,
                                     HyperBandScheduler, MedianStoppingRule,
                                     PopulationBasedTraining, TrialScheduler)
from ray_tpu.tune.search import (BasicVariantGenerator, BayesOptSearch,
                                 ConcurrencyLimiter, HyperOptSearch,
                                 OptunaSearch, Repeater, Searcher)
from ray_tpu.tune.bohb import BOHBSearcher, HyperBandForBOHB
from ray_tpu.tune.pb2 import PB2
from ray_tpu.tune.syncer import SyncConfig, Syncer
from ray_tpu.tune.tpe import TPESearcher
from ray_tpu.tune.session import get_checkpoint, get_trial_id, report
from ray_tpu.tune.trainable import FunctionTrainable, Trainable, wrap_function
from ray_tpu.tune.trial import Trial
from ray_tpu.tune.tuner import TuneConfig, Tuner, run

__all__ = [
    "run", "Tuner", "TuneConfig", "Trainable", "FunctionTrainable",
    "wrap_function", "Trial", "report", "get_checkpoint", "get_trial_id",
    "uniform", "quniform", "loguniform", "qloguniform", "randn", "randint",
    "lograndint", "choice", "sample_from", "grid_search",
    "FIFOScheduler", "AsyncHyperBandScheduler", "HyperBandScheduler",
    "MedianStoppingRule", "PopulationBasedTraining", "TrialScheduler",
    "BasicVariantGenerator", "ConcurrencyLimiter", "Repeater", "Searcher",
    "TPESearcher", "OptunaSearch", "HyperOptSearch", "BayesOptSearch",
    "BOHBSearcher", "HyperBandForBOHB", "PB2", "SyncConfig", "Syncer",
    "ExperimentAnalysis", "ResultGrid",
]
