"""Search algorithms: variant generation over a param space.

Parity with ``python/ray/tune/search/basic_variant.py``
(``BasicVariantGenerator``) and ``variant_generator.py`` (grid resolution),
plus the ``ConcurrencyLimiter`` and ``Repeater`` wrappers from
``tune/search/``. External searcher adapters (Optuna/HyperOpt/...) are
import-gated: the libraries are not in this image.
"""

from __future__ import annotations

import itertools
import random
from typing import Any, Dict, Iterator, List, Optional, Tuple

from ray_tpu.tune.sample import Domain, _is_grid


def _walk(space: Dict[str, Any], path=()) -> Iterator[Tuple[Tuple, Any]]:
    for k, v in space.items():
        p = path + (k,)
        if isinstance(v, dict) and not _is_grid(v):
            yield from _walk(v, p)
        else:
            yield p, v


def _set_path(d: Dict[str, Any], path: Tuple, value: Any):
    for k in path[:-1]:
        d = d.setdefault(k, {})
    d[path[-1]] = value


def _deepcopy_plain(space):
    if isinstance(space, dict):
        return {k: _deepcopy_plain(v) for k, v in space.items()}
    return space


def generate_variants(space: Dict[str, Any], num_samples: int,
                      seed: Optional[int] = None) -> Iterator[Dict[str, Any]]:
    """Cross-product every grid_search axis, then draw ``num_samples``
    samples of the remaining Domains for each grid point (matching
    reference semantics: total = num_samples x prod(grid sizes))."""
    rng = random.Random(seed)
    grid_axes: List[Tuple[Tuple, List[Any]]] = []
    sampled: List[Tuple[Tuple, Domain]] = []
    constants: List[Tuple[Tuple, Any]] = []
    for path, v in _walk(space):
        if _is_grid(v):
            grid_axes.append((path, v["grid_search"]))
        elif isinstance(v, Domain):
            sampled.append((path, v))
        else:
            constants.append((path, v))

    grid_values = [vals for _, vals in grid_axes]
    for _ in range(num_samples):
        for combo in itertools.product(*grid_values) if grid_axes else [()]:
            cfg: Dict[str, Any] = {}
            for path, v in constants:
                _set_path(cfg, path, _deepcopy_plain(v))
            for (path, _), val in zip(grid_axes, combo):
                _set_path(cfg, path, val)
            for path, dom in sampled:
                _set_path(cfg, path, dom.sample(rng))
            yield cfg


class Searcher:
    """Base searcher interface (reference ``tune/search/searcher.py``).

    ``mode=None`` means "not configured": the TrialRunner fills it from
    ``run()``'s mode. Searchers must treat ``None`` as "max"."""

    def __init__(self, metric: Optional[str] = None,
                 mode: Optional[str] = None):
        self.metric, self.mode = metric, mode

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        raise NotImplementedError

    def on_trial_result(self, trial_id: str, result: Dict[str, Any]):
        pass

    def on_trial_complete(self, trial_id: str,
                          result: Optional[Dict[str, Any]] = None,
                          error: bool = False):
        pass


class BasicVariantGenerator(Searcher):
    """Grid + random search (reference ``basic_variant.py:BasicVariantGenerator``)."""

    def __init__(self, space: Optional[Dict[str, Any]] = None,
                 num_samples: int = 1, seed: Optional[int] = None,
                 max_concurrent: int = 0):
        super().__init__()
        self._space = space or {}
        self._num_samples = num_samples
        self._seed = seed
        self.max_concurrent = max_concurrent
        self._iter: Optional[Iterator[Dict[str, Any]]] = None

    def set_space(self, space: Optional[Dict[str, Any]],
                  num_samples: Optional[int] = None):
        """None leaves the corresponding constructor value in place."""
        if space:
            self._space = space
        if num_samples is not None:
            self._num_samples = num_samples

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        if self._iter is None:
            self._iter = generate_variants(self._space, self._num_samples,
                                           self._seed)
        try:
            return next(self._iter)
        except StopIteration:
            return None

    def total_variants(self) -> int:
        n = self._num_samples
        for _, v in _walk(self._space):
            if _is_grid(v):
                n *= len(v["grid_search"])
        return n


class ConcurrencyLimiter(Searcher):
    """Cap in-flight suggestions (reference ``tune/search/concurrency_limiter.py``)."""

    def __init__(self, searcher: Searcher, max_concurrent: int):
        super().__init__(searcher.metric, searcher.mode)
        self.searcher = searcher
        self.max_concurrent = max_concurrent
        self._live: set = set()

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        if len(self._live) >= self.max_concurrent:
            return None
        cfg = self.searcher.suggest(trial_id)
        if cfg is not None:
            self._live.add(trial_id)
        return cfg

    def on_trial_result(self, trial_id, result):
        self.searcher.on_trial_result(trial_id, result)

    def on_trial_complete(self, trial_id, result=None, error=False):
        self._live.discard(trial_id)
        self.searcher.on_trial_complete(trial_id, result, error)


class Repeater(Searcher):
    """Repeat each suggestion ``repeat`` times and average the metric
    (reference ``tune/search/repeater.py``)."""

    def __init__(self, searcher: Searcher, repeat: int):
        super().__init__(searcher.metric, searcher.mode)
        self.searcher = searcher
        self.repeat = repeat
        self._pending: List[Dict[str, Any]] = []
        self._group_of: Dict[str, int] = {}
        self._group_results: Dict[int, List[float]] = {}
        self._next_group = 0

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        if not self._pending:
            cfg = self.searcher.suggest(trial_id)
            if cfg is None:
                return None
            self._next_group += 1
            self._pending = [dict(cfg) for _ in range(self.repeat)]
            self._group_results[self._next_group] = []
        self._group_of[trial_id] = self._next_group
        return self._pending.pop()

    def on_trial_complete(self, trial_id, result=None, error=False):
        gid = self._group_of.get(trial_id)
        if gid is None or result is None:
            return
        metric = self.searcher.metric or self.metric
        if metric and metric in result:
            self._group_results[gid].append(result[metric])
        if len(self._group_results[gid]) == self.repeat:
            avg = sum(self._group_results[gid]) / self.repeat
            self.searcher.on_trial_complete(
                trial_id, {metric: avg} if metric else None, error)


def _external_searcher(lib_name: str, cls_name: str):
    """Import-gated adapter factory (reference: ``tune/search/optuna``,
    ``hyperopt``, ``bayesopt`` adapters). The external libraries are not
    in this image; the native ``TPESearcher`` covers the Bayesian-search
    role without them."""

    class _Adapter(Searcher):
        def __init__(self, *a, **kw):
            # Honest in BOTH branches: the adapter is a stub regardless
            # of whether the library is installed — never send the user
            # off to pip-install something that won't help.
            hint = ("ray_tpu ships a dependency-free Bayesian searcher "
                    "with the same role: ray_tpu.tune.TPESearcher")
            try:
                __import__(lib_name)
            except ImportError as e:
                raise ImportError(
                    f"{cls_name} is an adapter stub in this build and the "
                    f"'{lib_name}' package is not installed anyway. "
                    f"{hint}") from e
            raise NotImplementedError(
                f"{cls_name} is an adapter stub in this build (the "
                f"'{lib_name}' package is present but not wired). {hint}")

    _Adapter.__name__ = _Adapter.__qualname__ = cls_name
    return _Adapter


OptunaSearch = _external_searcher("optuna", "OptunaSearch")
HyperOptSearch = _external_searcher("hyperopt", "HyperOptSearch")
BayesOptSearch = _external_searcher("bayes_opt", "BayesOptSearch")
