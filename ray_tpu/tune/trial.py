"""Trial state (reference ``python/ray/tune/experiment/trial.py``)."""

from __future__ import annotations

import time
import uuid
from typing import Any, Dict, List, Optional

PENDING = "PENDING"
RUNNING = "RUNNING"
PAUSED = "PAUSED"
TERMINATED = "TERMINATED"
ERROR = "ERROR"


class Trial:
    def __init__(self, config: Dict[str, Any], trial_id: Optional[str] = None,
                 experiment_tag: str = ""):
        self.trial_id = trial_id or uuid.uuid4().hex[:8]
        self.config = config
        self.experiment_tag = experiment_tag
        self.status = PENDING
        self.results: List[Dict[str, Any]] = []
        self.last_result: Dict[str, Any] = {}
        self.checkpoint: Optional[Dict[str, Any]] = None
        self.error: Optional[str] = None
        self.num_failures = 0
        self.start_time: Optional[float] = None
        self.logdir: Optional[str] = None
        # runner-internal
        self._actor = None
        self._future = None

    def metric_history(self, metric: str) -> List[float]:
        return [r[metric] for r in self.results if metric in r]

    def is_finished(self) -> bool:
        return self.status in (TERMINATED, ERROR)

    def __repr__(self):
        return f"Trial({self.trial_id}, {self.status})"

    def summary(self) -> Dict[str, Any]:
        return {
            "trial_id": self.trial_id,
            "status": self.status,
            "config": _plain(self.config),
            "last_result": _plain(self.last_result),
            "error": self.error,
            "num_failures": self.num_failures,
        }


def _plain(v: Any):
    if isinstance(v, dict):
        return {k: _plain(x) for k, x in v.items()}
    if isinstance(v, (list, tuple)):
        return [_plain(x) for x in v]
    if isinstance(v, (int, float, str, bool, type(None))):
        return v
    return repr(v)
