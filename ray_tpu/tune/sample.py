"""Search-space primitives.

Parity with the reference's ``python/ray/tune/search/sample.py`` (Domain
classes) and ``tune.grid_search``: a config dict may contain ``Domain``
values (sampled per trial) and ``grid_search`` markers (cross-producted
across trials).
"""

from __future__ import annotations

import random
from typing import Any, Callable, Dict, List, Sequence


class Domain:
    """A sampleable value in a param space."""

    def sample(self, rng: random.Random) -> Any:
        raise NotImplementedError


class Float(Domain):
    def __init__(self, lower: float, upper: float, log: bool = False):
        if log and lower <= 0:
            raise ValueError("loguniform requires lower > 0")
        self.lower, self.upper, self.log = lower, upper, log

    def sample(self, rng: random.Random) -> float:
        if self.log:
            import math
            return math.exp(rng.uniform(math.log(self.lower),
                                        math.log(self.upper)))
        return rng.uniform(self.lower, self.upper)

    def quantized(self, q: float) -> "Quantized":
        return Quantized(self, q)


class Integer(Domain):
    def __init__(self, lower: int, upper: int, log: bool = False):
        self.lower, self.upper, self.log = lower, upper, log

    def sample(self, rng: random.Random) -> int:
        if self.log:
            import math
            return int(round(math.exp(rng.uniform(math.log(self.lower),
                                                  math.log(self.upper)))))
        return rng.randint(self.lower, self.upper - 1)


class Categorical(Domain):
    def __init__(self, categories: Sequence[Any]):
        self.categories = list(categories)

    def sample(self, rng: random.Random) -> Any:
        return rng.choice(self.categories)


class Normal(Domain):
    def __init__(self, mean: float = 0.0, sd: float = 1.0):
        self.mean, self.sd = mean, sd

    def sample(self, rng: random.Random) -> float:
        return rng.gauss(self.mean, self.sd)


class Function(Domain):
    def __init__(self, fn: Callable[[], Any]):
        self.fn = fn

    def sample(self, rng: random.Random) -> Any:
        try:
            return self.fn(None)  # reference passes a `spec` argument
        except TypeError:
            return self.fn()


class Quantized(Domain):
    def __init__(self, inner: Domain, q: float):
        self.inner, self.q = inner, q

    def sample(self, rng: random.Random) -> float:
        v = self.inner.sample(rng)
        return round(v / self.q) * self.q


def uniform(lower: float, upper: float) -> Float:
    return Float(lower, upper)


def quniform(lower: float, upper: float, q: float) -> Quantized:
    return Quantized(Float(lower, upper), q)


def loguniform(lower: float, upper: float) -> Float:
    return Float(lower, upper, log=True)


def qloguniform(lower: float, upper: float, q: float) -> Quantized:
    return Quantized(Float(lower, upper, log=True), q)


def randn(mean: float = 0.0, sd: float = 1.0) -> Normal:
    return Normal(mean, sd)


def randint(lower: int, upper: int) -> Integer:
    return Integer(lower, upper)


def lograndint(lower: int, upper: int) -> Integer:
    return Integer(lower, upper, log=True)


def choice(categories: Sequence[Any]) -> Categorical:
    return Categorical(categories)


def sample_from(fn: Callable[[], Any]) -> Function:
    return Function(fn)


def grid_search(values: List[Any]) -> Dict[str, List[Any]]:
    """Marker dict, cross-producted by the variant generator
    (reference: ``tune/search/variant_generator.py``)."""
    return {"grid_search": list(values)}


def _is_grid(v: Any) -> bool:
    return isinstance(v, dict) and set(v.keys()) == {"grid_search"}
